"""Critical-path extraction over an assembled per-query span trace.

Input: the `trace_span` records of one query (profiler/tracing.py),
already assembled across driver threads, pool workers and executor
processes. Output: where the END-TO-END wall clock went, decomposed
into a small fixed vocabulary of edges:

  queue          admission/queue wait in the query service
  plan           logical->physical planning + AQE stage decisions
  compile        sync XLA compiles on the dispatch path
  shuffle_fetch  remote block fetches (incl. injected delays)
  collective     fused SPMD collective launches
  spill          spill write/read (device<->host<->disk)
  pool_wait      waits for exchange-map/broadcast pool admission
  retry          backoff sleeps, fetch retries, degradation recovery
  compute        everything else inside the query window

The decomposition is a TIMELINE SWEEP, not a graph longest-path: the
engine blocks-on-results at every stage barrier, so at any instant the
query's latency is attributable to the DEEPEST span covering that
instant (ties: non-compute beats compute, later-opened beats earlier).
The sweep projects every span onto the root window and integrates per
category, so shares always sum to the root wall time — robust to
executor clock skew at the edges (spans are clamped to the window) and
to overlapping concurrent workers (depth picks the most specific
blame). The dominant edge is simply the largest non-compute share if
any edge exceeds `DOMINANT_FLOOR` of the window, else "compute" — the
name EXPLAIN ANALYZE prints as `criticalPath=`.
"""
from __future__ import annotations

from typing import Dict, List, Optional

__all__ = ["CATEGORIES", "category_of", "summarize", "span_depths",
           "render_waterfall", "dominant_of_pct", "DOMINANT_FLOOR"]

#: edge vocabulary, in render order
CATEGORIES = ("queue", "plan", "compile", "shuffle_fetch", "collective",
              "spill", "pool_wait", "retry", "peer_fetch", "compute")

#: span kind -> edge category (kinds not listed count as compute)
_KIND_CATEGORY = {
    "queue": "queue",
    "plan": "plan",
    "aqe": "plan",
    "compile": "compile",
    "fetch": "shuffle_fetch",
    "shuffle_fetch": "shuffle_fetch",
    "collective": "collective",
    "spill": "spill",
    "spill_write": "spill",
    "spill_read": "spill",
    "pool_wait": "pool_wait",
    "retry": "retry",
    "backoff": "retry",
    "degrade": "retry",
    # fleet peer-cache fetches (fleet/peer_cache.py): a slow peer shows
    # up as its own edge rather than hiding inside compute, so "was the
    # fleet worth it" is answerable per query
    "peer_fetch": "peer_fetch",
}

#: a non-compute edge must cover at least this fraction of the query
#: window to be named dominant (below it, noise would flip the label
#: between runs)
DOMINANT_FLOOR = 0.05


def category_of(kind: Optional[str]) -> str:
    return _KIND_CATEGORY.get(kind or "", "compute")


def dominant_of_pct(share_pct: Dict[str, float]) -> str:
    """The dominant-edge rule applied to a percentage-share dict — used
    by consumers (EXPLAIN ANALYZE) that only kept the numeric shares."""
    dominant, best = "compute", 0.0
    for c, pct in share_pct.items():
        if c == "compute":
            continue
        if pct > best:
            best, dominant = pct, c
    return dominant if best >= DOMINANT_FLOOR * 100.0 else "compute"


def span_depths(spans: List[dict]) -> Dict[str, int]:
    """span_id -> ancestor count within this trace (roots are 0).
    Parent links that point outside the trace (a pruned/unsampled
    ancestor) count as roots."""
    by_id = {s.get("span_id"): s for s in spans}
    depths: Dict[str, int] = {}

    def depth(sid, hops=0):
        if sid in depths:
            return depths[sid]
        if hops > len(by_id) + 1:       # cycle guard: corrupt links
            return 0
        s = by_id.get(sid)
        parent = s.get("parent_id") if s else None
        d = 0 if parent not in by_id else depth(parent, hops + 1) + 1
        depths[sid] = d
        return d

    for s in spans:
        depth(s.get("span_id"))
    return depths


def _window(spans: List[dict]):
    """(start_ns, end_ns) of the query window: the hull of every span.
    The hull — not just the root 'query' span — because the queue span
    is back-dated to BEFORE the root opened (admission happens before
    the query thread runs) and background compiles can outlive the
    root; both must still earn their share."""
    start = min(s.get("start_ns", 0) for s in spans)
    end = max(s.get("end_ns", 0) for s in spans)
    return start, max(end, start)


def summarize(spans: List[dict],
              wall_s: Optional[float] = None) -> Optional[dict]:
    """Latency-share decomposition of one assembled trace.

    Returns {total_ms, shares: {category: ms}, share_pct, dominant,
    dominant_pct, span_count} or None for an empty trace. `wall_s`,
    when given (profile_query knows the true action wall), scales the
    window so the summary matches the query_end record even if some
    edge spans were clipped."""
    spans = [s for s in spans if s.get("end_ns", 0)
             >= s.get("start_ns", 0)]
    if not spans:
        return None
    w0, w1 = _window(spans)
    if w1 <= w0:
        return None
    depths = span_depths(spans)

    # elementary-interval sweep over every span boundary in the window
    cuts = set()
    clipped = []
    for s in spans:
        if s.get("kind") == "query":
            continue
        a = max(s["start_ns"], w0)
        b = min(s["end_ns"], w1)
        if b <= a:
            continue
        clipped.append((a, b, depths.get(s.get("span_id"), 0),
                        category_of(s.get("kind"))))
        cuts.add(a)
        cuts.add(b)
    cuts.add(w0)
    cuts.add(w1)
    edges = sorted(cuts)

    shares = {c: 0.0 for c in CATEGORIES}
    for i in range(len(edges) - 1):
        a, b = edges[i], edges[i + 1]
        if b <= a:
            continue
        mid_cover = [(d, 0 if cat == "compute" else 1, cat)
                     for (sa, sb, d, cat) in clipped
                     if sa <= a and sb >= b]
        if mid_cover:
            cat = max(mid_cover)[2]
        else:
            cat = "compute"
        shares[cat] += (b - a) / 1e6

    total_ms = (w1 - w0) / 1e6
    if wall_s is not None and wall_s > 0:
        # rescale to the action's true wall so shares line up with
        # query_end even when tracing missed the first/last slivers
        scale = (wall_s * 1e3) / total_ms if total_ms > 0 else 1.0
        if scale > 1.0:
            shares["compute"] += wall_s * 1e3 - total_ms
            total_ms = wall_s * 1e3

    share_pct = {c: round(100.0 * v / total_ms, 2) if total_ms else 0.0
                 for c, v in shares.items()}
    dominant = "compute"
    best = 0.0
    for c in CATEGORIES:
        if c == "compute":
            continue
        if shares[c] > best:
            best, dominant = shares[c], c
    if best < DOMINANT_FLOOR * total_ms:
        dominant = "compute"
    return {"total_ms": round(total_ms, 3),
            "shares": {c: round(v, 3) for c, v in shares.items()},
            "share_pct": share_pct,
            "dominant": dominant,
            "dominant_pct": share_pct[dominant],
            "span_count": len(spans)}


# ---------------------------------------------------------------------
# waterfall rendering (tools/profile_report.py --trace)
# ---------------------------------------------------------------------
def render_waterfall(spans: List[dict], width: int = 48,
                     max_rows: int = 60) -> str:
    """Text waterfall: spans start-ordered, indented by trace depth,
    with a proportional bar over the query window."""
    spans = sorted(spans, key=lambda s: (s.get("start_ns", 0),
                                         s.get("end_ns", 0)))
    if not spans:
        return "(no spans)"
    w0, w1 = _window(spans)
    total = max(w1 - w0, 1)
    depths = span_depths(spans)
    lines = []
    shown = spans[:max_rows]
    for s in shown:
        a = max(s.get("start_ns", w0), w0)
        b = min(s.get("end_ns", w0), w1)
        off = int(width * (a - w0) / total)
        bar = max(1, int(width * max(b - a, 0) / total))
        bar = min(bar, width - off)
        gutter = " " * off + "#" * bar
        gutter = gutter.ljust(width)
        d = depths.get(s.get("span_id"), 0)
        name = "  " * d + str(s.get("name"))
        ms = s.get("dur_ms", (b - a) / 1e6)
        proc = s.get("proc", "")
        lines.append(f"|{gutter}| {ms:9.2f}ms  {name} "
                     f"[{s.get('kind')}@{proc}]")
    if len(spans) > max_rows:
        lines.append(f"... {len(spans) - max_rows} more spans")
    return "\n".join(lines)
