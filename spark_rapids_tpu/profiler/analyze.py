"""EXPLAIN ANALYZE renderer: the plan tree annotated with runtime
metrics per node, top time sinks flagged.

(reference: the SQL-UI per-node metric display wired by GpuExec /
GpuMetrics.scala — here rendered as text, since the standalone engine
has no UI process.) Works from the JSON plan tree + lore-keyed metric
dicts of profiler.event_log, so the same renderer serves the local
DataFrame path, the distributed runner's driver-side aggregation, and
the profiling-tool CLI reading an event log after the fact.
"""
from __future__ import annotations

from typing import Dict, Optional

from .event_log import op_time_seconds

__all__ = ["render_analyze", "fmt_bytes"]

_SHUFFLE_BYTE_KEYS = ("shuffleBytesWritten", "shuffleBytesRead",
                      "rawBytes")


def fmt_bytes(n) -> str:
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return (f"{n:.0f}{unit}" if unit == "B"
                    else f"{n:.1f}{unit}")
        n /= 1024
    return f"{n:.1f}GiB"


def render_analyze(tree: dict, metrics_by_lore: Dict[Optional[int], dict],
                   top_n: int = 3, title: Optional[str] = None) -> str:
    """Render the plan tree with per-node rows/batches/op-time/shuffle/
    spill annotations; the `top_n` largest time sinks are flagged with
    their share of total attributed operator time."""
    times = []

    def collect(node):
        m = metrics_by_lore.get(node.get("lore_id")) or {}
        times.append((node.get("lore_id"), op_time_seconds(m)))
        for c in node.get("children", ()):
            collect(c)

    collect(tree)
    total = sum(t for _, t in times)
    sinks = sorted((e for e in times if e[1] > 0), key=lambda e: -e[1])
    rank = {lid: i + 1 for i, (lid, _) in enumerate(sinks[:top_n])}

    lines = [] if title is None else [title]

    def walk(node, indent):
        lid = node.get("lore_id")
        m = metrics_by_lore.get(lid) or {}
        t = op_time_seconds(m)
        line = f"{'  ' * indent}[loreId={lid}] {node.get('describe')}"
        ann = []
        if "numOutputRows" in m:
            ann.append(f"rows={int(m['numOutputRows'])}")
        if "numOutputBatches" in m:
            ann.append(f"batches={int(m['numOutputBatches'])}")
        if t > 0:
            ann.append(f"time={t * 1e3:.1f}ms")
        shuffle = sum(m.get(k, 0) for k in _SHUFFLE_BYTE_KEYS)
        if shuffle:
            ann.append(f"shuffle={fmt_bytes(shuffle)}")
        if m.get("spillBytes"):
            ann.append(f"spill={fmt_bytes(m['spillBytes'])}")
        if m.get("deviceDecodedChunks"):
            ann.append(f"devDecoded={int(m['deviceDecodedChunks'])}")
        if m.get("decompressBusySecs"):
            ann.append(
                f"decompress={m['decompressBusySecs'] * 1e3:.1f}ms")
        if m.get("prefetchWaitSecs") is not None:
            ann.append(
                f"prefetchWait={m['prefetchWaitSecs'] * 1e3:.1f}ms")
        # per-column device-decode fallback reasons: why this scan (or
        # part of it) still decodes on the host — the printf-free answer
        fb = {k.split(".", 1)[1]: int(v) for k, v in m.items()
              if k.startswith("deviceDecodeFallback.")}
        if fb:
            ann.append("fallback={" + ", ".join(
                f"{k}:{v}" for k, v in sorted(fb.items())) + "}")
        # FusedStage member counters: post-stage live rows per fused
        # child (the per-member selectivity view; members are not plan
        # children, so their rows render on the fused node)
        fr = {k.split(".", 1)[1]: int(v) for k, v in m.items()
              if k.startswith("fusedRows.")}
        if fr:
            ann.append("memberRows={" + ", ".join(
                f"{k}:{v}" for k, v in sorted(fr.items())) + "}")
        if m.get("xlaCompiles") is not None:
            ann.append(f"xlaCompiles={int(m['xlaCompiles'])}")
        if m.get("xlaDispatches") is not None:
            ann.append(f"xlaDispatches={int(m['xlaDispatches'])}")
        if m.get("programCacheHits") is not None:
            ann.append(f"programCacheHits={int(m['programCacheHits'])}")
        if m.get("programCacheMisses") is not None:
            ann.append(
                f"programCacheMisses={int(m['programCacheMisses'])}")
        # compile-tail view: wall ms spent compiling during this action
        # and how many compiles ran off the dispatch path (stage-ahead
        # prewarm / warm-pack preload)
        if m.get("compileMs"):
            ann.append(f"compileMs={float(m['compileMs']):.1f}")
        if m.get("backgroundCompiles"):
            ann.append(
                f"backgroundCompiles={int(m['backgroundCompiles'])}")
        # exchange pipeline (docs/observability.md): parallel-map pool
        # waits, async broadcast overlap, and plan-level reuse hits
        if m.get("mapPoolWaitMs") is not None:
            ann.append(f"mapPoolWaitMs={float(m['mapPoolWaitMs']):.1f}")
        if m.get("broadcastBuildOverlapMs") is not None:
            ann.append("broadcastBuildOverlapMs="
                       f"{float(m['broadcastBuildOverlapMs']):.1f}")
        if m.get("broadcastTimeoutFallbacks"):
            ann.append("broadcastTimeoutFallbacks="
                       f"{int(m['broadcastTimeoutFallbacks'])}")
        if m.get("exchangeReuseHits"):
            ann.append(
                f"exchangeReuseHits={int(m['exchangeReuseHits'])}")
        # AQE replan decisions (docs/aqe.md): coalesce/skew on the
        # shuffle readers, demotion on the rewritten join, plus the
        # exact per-reduce-partition byte distribution on exchanges
        if m.get("aqePartitionsBefore") is not None:
            ann.append(f"AQEShuffleRead[coalesced "
                       f"{int(m['aqePartitionsBefore'])}"
                       f"→{int(m['aqePartitionsAfter'])}]")
        if m.get("aqeSkewSplits"):
            ann.append(f"aqeSkewSplits={int(m['aqeSkewSplits'])}")
        if m.get("aqeDemotedBuildBytes") is not None:
            ann.append("aqeDemotedToBroadcast="
                       f"{fmt_bytes(m['aqeDemotedBuildBytes'])}")
        # mesh/SPMD stage metrics: rounds dispatched by the round-based
        # exchange, fused one-program stages, collective traffic, and
        # fault-driven degradations back to the round path
        if m.get("meshRounds"):
            ann.append(f"meshRounds={int(m['meshRounds'])}")
        if m.get("spmdStages"):
            ann.append(f"spmdStages={int(m['spmdStages'])}")
        if m.get("collectiveBytes"):
            ann.append(
                f"collectiveBytes={fmt_bytes(m['collectiveBytes'])}")
        if m.get("spmdDegraded"):
            ann.append(f"spmdDegraded={int(m['spmdDegraded'])}")
        if m.get("spmdActiveShards") is not None:
            ann.append(
                f"spmdActiveShards={int(m['spmdActiveShards'])}")
        if m.get("shufflePartitionBytesMax") is not None:
            ann.append(
                "shufflePartitionBytes="
                f"{fmt_bytes(m.get('shufflePartitionBytesMin', 0))}"
                f"/{fmt_bytes(m.get('shufflePartitionBytesMedian', 0))}"
                f"/{fmt_bytes(m['shufflePartitionBytesMax'])}")
        # query-service waits (root node): time queued behind other
        # queries + time blocked on the TpuSemaphore for the chip
        if m.get("queueWaitMs") is not None:
            ann.append(f"queueWaitMs={float(m['queueWaitMs']):.1f}")
        if m.get("semaphoreWaitMs") is not None:
            ann.append(
                f"semaphoreWaitMs={float(m['semaphoreWaitMs']):.1f}")
        if m.get("semaphoreAcquires") is not None:
            ann.append(
                f"semaphoreAcquires={int(m['semaphoreAcquires'])}")
        # critical-path attribution (root node): where the END-TO-END
        # wall clock went, reduced from the query's trace
        # (profiler/critical_path.py) — dominant edge plus every share
        # above the noise floor
        cps = {k.split(".", 1)[1]: float(v) for k, v in m.items()
               if k.startswith("criticalPathShare.")}
        if cps:
            from .critical_path import dominant_of_pct
            dom = dominant_of_pct(cps)
            tops = ", ".join(
                f"{c}:{cps[c]:.0f}%" for c in sorted(
                    cps, key=cps.get, reverse=True)
                if cps[c] >= 1.0)
            ann.append(f"criticalPath={dom} [{tops}]")
        # resource ledger (root node, when SRTPU_LEDGER/conf enabled):
        # staging-lease traffic this action + the global balance sample
        if m.get("ledgerBalanced") is not None:
            parts = []
            if m.get("ledgerLeaseAcquires"):
                parts.append(f"leases={int(m['ledgerLeaseAcquires'])}")
            if m.get("ledgerPeakLeases"):
                parts.append(f"peak={int(m['ledgerPeakLeases'])}")
            parts.append("balanced=" + ("yes" if m["ledgerBalanced"]
                                        else "NO"))
            ann.append("ledger[" + " ".join(parts) + "]")
        if ann:
            line += "  " + " ".join(ann)
        if lid in rank:
            pct = (100.0 * t / total) if total > 0 else 0.0
            line += (f"  <-- time sink #{rank[lid]} "
                     f"({pct:.0f}% of op time)")
        lines.append(line)
        for c in node.get("children", ()):
            walk(c, indent + 1)

    walk(tree, 0)
    if total > 0:
        lines.append(f"total attributed op time: {total * 1e3:.1f}ms")
    return "\n".join(lines)
