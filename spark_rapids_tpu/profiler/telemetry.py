"""Live service telemetry: process-global counters, gauges and
log-bucket histograms, scrapeable while queries run.

The event log is per-query and post-hoc; a fleet router (ROADMAP
item 3) needs a LIVE surface: what are this process's p95 latency,
queue depth, cache hit rate, pool saturation and memory watermarks
RIGHT NOW. This registry is that surface — the service gateway's
`metrics` verb (service/server.py) returns `snapshot()` as JSON and
`render_prometheus()` as a text exposition.

Histograms are log-bucketed (geometric buckets, ~19% relative width:
base 2^0.25) so p50/p95/p99 come out of ~100 integers per instrument
without storing samples — O(1) memory and a dict-increment per
observation, cheap enough to stay always-on. Quantiles are the
geometric midpoint of the covering bucket, i.e. exact to within one
bucket width (tests/test_telemetry.py pins the error bound against
exact quantiles).

Gauges come in two flavors: set-value (`gauge(name).set(v)`) and
callback (`register_gauge_fn(name, fn)`) — callbacks are sampled at
snapshot time, which keeps watermark/pool-depth reporting out of every
hot path entirely.

Instruments auto-create on first touch and live for the process; the
registry never raises into engine code (a telemetry failure must not
fail a query).
"""
from __future__ import annotations

import math
import threading
from typing import Callable, Dict, Optional

from ..runtime import lockdep, racedep

__all__ = ["counter", "gauge", "histogram", "register_gauge_fn",
           "snapshot", "render_prometheus", "reset", "Histogram"]

_LOCK = lockdep.lock("telemetry._LOCK")
_COUNTERS: Dict[str, "Counter"] = {}
_GAUGES: Dict[str, "Gauge"] = {}
_GAUGE_FNS: Dict[str, Callable[[], object]] = {}
_HISTOGRAMS: Dict[str, "Histogram"] = {}

#: bucket boundaries grow by 2^(1/4) per bucket — ~19% relative error,
#: ~110 buckets span 1e-3 .. 1e9
_LOG_BASE = 2.0 ** 0.25
_LN_BASE = math.log(_LOG_BASE)


class Counter:
    __slots__ = ("name", "_lock", "_n")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._n = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._n += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._n


class Gauge:
    __slots__ = ("name", "_lock", "_v")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._v = 0.0

    def set(self, v) -> None:
        with self._lock:
            self._v = v

    @property
    def value(self):
        with self._lock:
            return self._v


class Histogram:
    """Log-bucketed distribution: p50/p95/p99 without samples."""

    __slots__ = ("name", "_lock", "_buckets", "_count", "_sum", "_min",
                 "_max")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._buckets: Dict[int, int] = {}
        self._count = 0
        self._sum = 0.0
        self._min = None
        self._max = None

    @staticmethod
    def _bucket_of(v: float) -> int:
        if v <= 0.0:
            return -(10 ** 6)          # dedicated zero/negative bucket
        return int(math.floor(math.log(v) / _LN_BASE))

    @staticmethod
    def _bucket_mid(b: int) -> float:
        if b <= -(10 ** 6):
            return 0.0
        # geometric midpoint of [base^b, base^(b+1))
        return _LOG_BASE ** (b + 0.5)

    def observe(self, v) -> None:
        try:
            v = float(v)
        except (TypeError, ValueError):
            return
        b = self._bucket_of(v)
        with self._lock:
            self._buckets[b] = self._buckets.get(b, 0) + 1
            self._count += 1
            self._sum += v
            if self._min is None or v < self._min:
                self._min = v
            if self._max is None or v > self._max:
                self._max = v

    def quantile(self, q: float) -> Optional[float]:
        with self._lock:
            if not self._count:
                return None
            target = q * self._count
            seen = 0
            for b in sorted(self._buckets):
                seen += self._buckets[b]
                if seen >= target:
                    mid = self._bucket_mid(b)
                    # clamp to the observed range: the edge buckets'
                    # midpoints can overshoot the true extremes
                    return min(max(mid, self._min), self._max)
            return self._max

    def summary(self) -> dict:
        with self._lock:
            count, total = self._count, self._sum
            lo, hi = self._min, self._max
        out = {"count": count, "sum": round(total, 6)}
        if count:
            out.update({
                "min": round(lo, 6), "max": round(hi, 6),
                "mean": round(total / count, 6),
                "p50": round(self.quantile(0.50), 6),
                "p95": round(self.quantile(0.95), 6),
                "p99": round(self.quantile(0.99), 6)})
        return out


# ---------------------------------------------------------------------
# registry access
# ---------------------------------------------------------------------
def counter(name: str) -> Counter:
    with _LOCK:
        racedep.note_access("telemetry.registry", name, write=True)
        c = _COUNTERS.get(name)
        if c is None:
            c = _COUNTERS[name] = Counter(name)
        return c


def gauge(name: str) -> Gauge:
    with _LOCK:
        racedep.note_access("telemetry.registry", name, write=True)
        g = _GAUGES.get(name)
        if g is None:
            g = _GAUGES[name] = Gauge(name)
        return g


def histogram(name: str) -> Histogram:
    with _LOCK:
        racedep.note_access("telemetry.registry", name, write=True)
        h = _HISTOGRAMS.get(name)
        if h is None:
            h = _HISTOGRAMS[name] = Histogram(name)
        return h


def register_gauge_fn(name: str, fn: Callable[[], object]) -> None:
    """Pull-gauge: `fn()` is sampled at snapshot/scrape time (memory
    watermarks, pool depths, cache sizes — zero hot-path cost).
    Re-registering replaces (sessions/pools recreate across tests)."""
    with _LOCK:
        _GAUGE_FNS[name] = fn


def reset() -> None:
    """Drop every instrument (tests only)."""
    with _LOCK:
        _COUNTERS.clear()
        _GAUGES.clear()
        _GAUGE_FNS.clear()
        _HISTOGRAMS.clear()


# ---------------------------------------------------------------------
# built-in pull gauges: sampled lazily so the registry reflects live
# process state without any instrumentation on the hot paths
# ---------------------------------------------------------------------
def _builtin_gauges() -> Dict[str, object]:
    out: Dict[str, object] = {}
    try:
        from ..memory import diagnostics
        wm = diagnostics.watermarks_snapshot()
        out["memory_device_peak_bytes"] = wm.get("devicePeakBytes", 0)
        out["memory_host_peak_bytes"] = wm.get("hostPeakBytes", 0)
        for k, v in (wm.get("spill") or {}).items():
            out[f"spill_{k}"] = v
    except Exception:
        pass
    try:
        from ..runtime import program_cache
        for k, v in program_cache.stats().items():
            out[k] = v
    except Exception:
        pass
    try:
        from ..runtime import result_cache
        for k, v in result_cache.stats().items():
            out[k] = v
    except Exception:
        pass
    try:
        from ..runtime.compile_pool import current_pool
        p = current_pool()
        if p is not None:
            # lock-free approximate reads: a scrape must not contend
            # with the pool's own condition variable
            out["compile_pool_queue_depth"] = len(p._queue)
            out["compile_pool_active"] = p._active
            for k, v in p.stats.items():
                out[f"compile_pool_{k}"] = v
    except Exception:
        pass
    try:
        from . import tracing
        out["trace_spans_dropped"] = tracing.dropped_spans()
    except Exception:
        pass
    return out


def snapshot() -> dict:
    """The whole registry as one JSON-able dict (the `metrics` verb)."""
    with _LOCK:
        racedep.note_access("telemetry.registry")
        counters = {n: c.value for n, c in _COUNTERS.items()}
        gauges = {n: g.value for n, g in _GAUGES.items()}
        fns = dict(_GAUGE_FNS)
        hists = dict(_HISTOGRAMS)
    for n, fn in fns.items():
        try:
            v = fn()
        except Exception:
            continue
        if isinstance(v, dict):
            for k, sub in v.items():
                gauges[f"{n}_{k}"] = sub
        else:
            gauges[n] = v
    gauges.update(_builtin_gauges())
    return {"counters": dict(sorted(counters.items())),
            "gauges": dict(sorted(gauges.items())),
            "histograms": {n: hists[n].summary()
                           for n in sorted(hists)}}


def _prom_name(name: str) -> str:
    return "srtpu_" + "".join(
        ch if ch.isalnum() or ch == "_" else "_" for ch in name)


def render_prometheus() -> str:
    """Prometheus text exposition (counters, gauges, and histograms as
    summary-typed quantile series)."""
    snap = snapshot()
    lines = []
    for n, v in snap["counters"].items():
        pn = _prom_name(n)
        lines.append(f"# TYPE {pn} counter")
        lines.append(f"{pn} {v}")
    for n, v in snap["gauges"].items():
        if isinstance(v, bool):
            v = int(v)
        if not isinstance(v, (int, float)):
            continue
        pn = _prom_name(n)
        lines.append(f"# TYPE {pn} gauge")
        lines.append(f"{pn} {v}")
    for n, s in snap["histograms"].items():
        pn = _prom_name(n)
        lines.append(f"# TYPE {pn} summary")
        for q in ("p50", "p95", "p99"):
            if q in s:
                lines.append(
                    f'{pn}{{quantile="0.{q[1:]}"}} {s[q]}')
        lines.append(f"{pn}_sum {s['sum']}")
        lines.append(f"{pn}_count {s['count']}")
    return "\n".join(lines) + "\n"
