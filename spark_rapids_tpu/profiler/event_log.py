"""Structured per-query event log (the Spark event-log analog).

One JSONL file per query under `spark.rapids.tpu.sql.eventLog.dir`, with
typed events the profiling tool post-processes:

  query_queued  {pool, estimate_device_bytes, estimate_host_bytes}
                (query service, service/query_manager.py)
  query_admitted{pool, queue_wait_ms}            (query service)
  query_start   {query_id, action, ts}
  plan          {plan: nested {lore_id, name, describe, children}}
  plan_audit    {ok, nodes, findings: [{kind, reason, node, path,
                 lore_id}]}   (static auditor, analysis/audit.py)
  aqe_replan    {action, decisions: [{rule: shuffle_read|
                 demote_broadcast_join, ...lore ids old→new, partition
                 counts, split/byte thresholds}]}  (AQE stage driver,
                 plan/aqe.py; emitted between stage completion and
                 consumer launch when any replan decision was taken)
  stage_submit  {stage, n_tasks, attempt}        (distributed runner)
  stage_complete{stage, wall_s, shuffle_bytes}   (distributed runner)
  fetch_retry   {stage, pid, shuffle_id}         (distributed runner)
  op_metrics    {ops: [{lore_id, name, describe, metrics}], stage?}
  watermarks    {devicePeakBytes, hostPeakBytes, spill?, hostPressure?}
  xla_compile   {compiles, compile_secs, cache_hits, cache_misses,
                 dispatches}
  result_cache  {hits, misses, fragment_hits, fragment_misses, stores,
                 evictions, invalidations, entries, bytes, fast_path?,
                 rows?}   (cross-query result cache,
                 runtime/result_cache.py; emitted when
                 sql.cache.enabled — fast_path=True records a
                 whole-query hit answered without admission)
  query_cancelled{reason, lockdep?: {threads, findings, edges},
                 ledger?: {kinds, holders, findings}}
                (cooperative cancel / deadline kill; deadline kills
                 attach the runtime/lockdep.py all-threads dump and the
                 runtime/ledger.py outstanding-holders dump)
  concurrency_report{enabled, resources, orderEdges, maxOrderGraph,
                 acquires, findings}  (lockdep witness, when enabled)
  resource_ledger{enabled, kinds: {kind: {acquires, releases,
                 outstanding, peakOutstanding}}, balanceOk,
                 balancedQueries, imbalancedQueries, findings}
                (resource-lifetime ledger, runtime/ledger.py, when
                 enabled — per-kind acquire/release counters and the
                 per-query balance verdicts)
  race_report   {enabled, tracked, shared, accesses, findings,
                 perturbed}  (data-race witness, runtime/racedep.py,
                 when enabled — Eraser lockset tracking over the
                 instrumented shared structures)
  trace_span    {trace_id, span_id, parent_id, name, kind, start_ns,
                 end_ns, dur_ms, proc, attrs?}  (distributed tracing,
                 profiler/tracing.py — the query's assembled spans,
                 driver + pools + executors, one trace per query)
  trace_summary {total_ms, shares, share_pct, dominant, dominant_pct,
                 span_count}  (critical-path decomposition,
                 profiler/critical_path.py)
  query_end     {status: ok|error|cancelled|timeout, wall_s, error?}

Locally `session.py` wraps every action (`profile_query`); the
distributed runner (cluster/query.py) writes one log driver-side from
the executor `MetricSet` snapshots that ride back with task results.
Metric values honor `spark.rapids.tpu.sql.metrics.level`; op time is the
sum of the operator's `*Time` timers (see docs/observability.md for the
async-dispatch skew caveat and the `sql.metrics.sync` gate).
"""
from __future__ import annotations

import itertools
import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional

from ..utils.metrics import DEBUG

__all__ = ["EventLogWriter", "open_query_log", "read_event_log",
           "next_query_id", "plan_tree", "op_metrics_records",
           "aggregate_ops", "op_time_seconds", "top_operators",
           "profile_query", "log_fast_path"]

_QUERY_SEQ = itertools.count()


def next_query_id(prefix: str = "query") -> str:
    """Process-unique query id (also the event-log file stem)."""
    return f"{prefix}-{os.getpid()}-{next(_QUERY_SEQ)}"


def _json_default(o):
    try:
        return float(o)
    except Exception:
        return str(o)


class EventLogWriter:
    """Append-only JSONL writer; one file per query, flushed per event
    so a crashed query still leaves a readable prefix."""

    def __init__(self, path: str, query_id: str):
        self.path = path
        self.query_id = query_id
        self._lock = threading.Lock()
        self._f = open(path, "a", encoding="utf-8")

    def emit(self, event: str, **fields):
        rec = {"event": event, "ts": round(time.time(), 6),
               "query_id": self.query_id}
        rec.update(fields)
        line = json.dumps(rec, default=_json_default)
        with self._lock:
            if self._f is None:
                return
            try:
                self._f.write(line + "\n")
                self._f.flush()
            except OSError:
                # a full/yanked log volume must not fail the query; a
                # torn line is fine — the reader skips it
                f, self._f = self._f, None
                try:
                    f.close()
                except OSError:
                    pass

    def close(self):
        with self._lock:
            if self._f is not None:
                try:
                    self._f.close()
                except OSError:
                    pass
                self._f = None


def open_query_log(conf, query_id: str) -> Optional[EventLogWriter]:
    """EventLogWriter for this query, or None when logging is off."""
    from ..config import EVENT_LOG_DIR, EVENT_LOG_ENABLED
    if not conf.get(EVENT_LOG_ENABLED):
        return None
    d = conf.get(EVENT_LOG_DIR)
    try:
        os.makedirs(d, exist_ok=True)
        return EventLogWriter(os.path.join(d, f"{query_id}.jsonl"),
                              query_id)
    except OSError:
        return None


def read_event_log(path: str) -> List[dict]:
    """Parse a JSONL event log; tolerates a torn trailing line."""
    out = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return out


# ---------------------------------------------------------------------
# plan / metric snapshots (shared by session, cluster runner, tools)
# ---------------------------------------------------------------------
def plan_tree(root) -> dict:
    """Physical plan as a JSON-able tree keyed by lore_id (stable across
    processes for the same plan — the cross-executor aggregation key)."""
    return {"lore_id": getattr(root, "lore_id", None),
            "name": root.node_name(),
            "describe": root.describe(),
            "children": [plan_tree(c) for c in root.children]}


def op_metrics_records(root, metrics_by_opid: Dict[str, object],
                       max_level: int = DEBUG) -> List[dict]:
    """Flatten the physical tree into per-operator metric records.
    `metrics_by_opid` maps `node._op_id` to a MetricSet OR an already
    snapshotted dict (DataFrame.last_metrics shape)."""
    recs = []

    def walk(node):
        ms = metrics_by_opid.get(node._op_id)
        if hasattr(ms, "snapshot"):
            ms = ms.snapshot(max_level)
        recs.append({"lore_id": getattr(node, "lore_id", None),
                     "name": node.node_name(),
                     "describe": node.describe(),
                     "metrics": dict(ms or {})})
        for c in node.children:
            walk(c)

    walk(root)
    return recs


def aggregate_ops(records: List[dict]) -> Dict[str, dict]:
    """Merge operator records across tasks/executors/queries, keyed by
    `lore_id:name` (stable for the same fragment plan in every worker
    process — id()-based _op_ids are NOT). Numeric metrics sum."""
    out: Dict[str, dict] = {}
    for r in records:
        key = f"{r.get('lore_id')}:{r.get('name')}"
        cur = out.setdefault(key, {"lore_id": r.get("lore_id"),
                                   "name": r.get("name"),
                                   "describe": r.get("describe"),
                                   "metrics": {}})
        for k, v in (r.get("metrics") or {}).items():
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                cur["metrics"][k] = v
            else:
                cur["metrics"][k] = cur["metrics"].get(k, 0) + v
    return out


def op_time_seconds(metrics: dict) -> float:
    """An operator's attributed time: the sum of its `*Time` timers
    (opTime, scanTime, buildTime, partitionTime, writeTime, ...)."""
    t = 0.0
    for k, v in (metrics or {}).items():
        if k.endswith("Time") and isinstance(v, (int, float)) \
                and not isinstance(v, bool):
            t += float(v)
    return t


def top_operators(records: List[dict], n: int = 5) -> List[dict]:
    """Top-n operators by attributed time (the bench --profile and
    EXPLAIN ANALYZE sink list)."""
    rows = []
    for r in records:
        m = r.get("metrics") or {}
        t = op_time_seconds(m)
        if t <= 0 and not m:
            continue
        rows.append({"op": r.get("describe"),
                     "loreId": r.get("lore_id"),
                     "time_ms": round(t * 1e3, 3),
                     "rows": m.get("numOutputRows")})
    rows.sort(key=lambda r: r["time_ms"], reverse=True)
    return rows[:n]


# ---------------------------------------------------------------------
# the per-action wrapper session.py runs every query inside
# ---------------------------------------------------------------------
@contextmanager
def profile_query(session, root, ctx, action: str, handle=None):
    """Emit the full event sequence for one local query action. No-op
    (beyond a cheap conf check) when event logging is disabled. With a
    query-service `handle`, the log file is named by the handle's
    query_id and carries queue/admission/cancellation events."""
    w = open_query_log(ctx.conf, handle.query_id if handle is not None
                       else next_query_id())
    if w is None:
        yield None
        return
    from ..memory import diagnostics
    from . import xla_stats
    if session is not None:
        session.last_event_log = w.path
    xla0 = xla_stats.snapshot()
    from ..runtime import result_cache
    rc_on = result_cache.enabled(ctx.conf)
    rc0 = result_cache.stats() if rc_on else None
    fleet0 = _fleet_stats()
    diagnostics.reset_watermarks()
    t0 = time.perf_counter()
    if handle is not None:
        # reconstructed from handle timestamps: by the time the action
        # body runs, the query has already been queued and admitted
        w.emit("query_queued", pool=handle.pool,
               estimate_device_bytes=int(handle.estimate[0]),
               estimate_host_bytes=int(handle.estimate[1]))
        w.emit("query_admitted", pool=handle.pool,
               queue_wait_ms=round(handle.queue_wait_ms, 3))
    w.emit("query_start", action=action)
    w.emit("plan", plan=plan_tree(root))
    audit = getattr(root, "audit_report", None)
    if audit is not None:
        # static-audit verdicts keyed by lore id (analysis/audit.py):
        # which nodes fall back, cannot run, or risk recompiles
        w.emit("plan_audit", ok=audit.ok, nodes=audit.node_count,
               findings=audit.to_events())
    status, err = "ok", None
    try:
        yield w
    except BaseException as e:
        from ..service.query_manager import QueryCancelled, QueryTimedOut
        if isinstance(e, QueryTimedOut):
            status = "timeout"
        elif isinstance(e, QueryCancelled):
            status = "cancelled"
        else:
            status = "error"
        err = repr(e)
        if status != "error":
            # deadline kills carry the lockdep all-threads dump (see
            # runtime/lockdep.attach_dump) — surface it so a timeout in
            # the log is attributable to held resources, not a mystery
            cancel_fields = {"reason": status}
            dump = getattr(e, "lockdep_dump", None)
            if dump is not None:
                cancel_fields["lockdep"] = dump
            ldump = getattr(e, "ledger_dump", None)
            if ldump is not None:
                cancel_fields["ledger"] = ldump
            w.emit("query_cancelled", **cancel_fields)
        raise
    finally:
        try:
            w.emit("op_metrics", ops=op_metrics_records(
                root, ctx.metrics, ctx.metrics_level))
            from ..runtime import ledger, lockdep, racedep
            lw = lockdep.witness()
            if lw is not None:
                w.emit("concurrency_report", **lw.report())
            lg = ledger.ledger()
            if lg is not None:
                w.emit("resource_ledger", **lg.report())
            rw = racedep.witness()
            if rw is not None:
                w.emit("race_report", **rw.report())
            w.emit("watermarks", **diagnostics.watermarks_snapshot())
            x1 = xla_stats.snapshot()
            w.emit("xla_compile",
                   **{k: round(x1[k] - xla0.get(k, 0), 6)
                      for k in x1})
            # per-compile events (program key hash, wall ms, sync vs
            # background) accumulated since the last drain; global, so
            # concurrent queries' compiles land in whichever query's
            # log drains first — attribution is best-effort, the
            # counters above are the invariant
            from ..runtime import program_cache
            for ev in program_cache.drain_compile_events():
                w.emit("compile", **ev)
            if rc_on:
                rc1 = result_cache.stats()
                w.emit("result_cache",
                       hits=rc1["result_cache_hits"]
                       - rc0["result_cache_hits"],
                       misses=rc1["result_cache_misses"]
                       - rc0["result_cache_misses"],
                       fragment_hits=rc1["result_cache_fragment_hits"]
                       - rc0["result_cache_fragment_hits"],
                       fragment_misses=rc1[
                           "result_cache_fragment_misses"]
                       - rc0["result_cache_fragment_misses"],
                       stores=rc1["result_cache_stores"]
                       + rc1["result_cache_fragment_stores"]
                       - rc0["result_cache_stores"]
                       - rc0["result_cache_fragment_stores"],
                       evictions=rc1["result_cache_evictions"]
                       - rc0["result_cache_evictions"],
                       invalidations=rc1["result_cache_invalidations"]
                       - rc0["result_cache_invalidations"],
                       entries=rc1["result_cache_entries"],
                       bytes=rc1["result_cache_bytes"])
            fleet1 = _fleet_stats()
            if fleet1 is not None:
                w.emit("fleet", **_fleet_delta(fleet0, fleet1))
            wall = time.perf_counter() - t0
            # distributed-tracing assembly: end the root span, drain
            # every span the query recorded (driver threads, pool
            # workers, executor-side spans absorbed from the
            # task-metric side channel) and reduce them to the
            # critical-path summary. Failure paths included — a trace
            # of a failed query is exactly when attribution matters.
            try:
                from . import tracing
                spans = tracing.finish(ctx, wall)
                for s in spans:
                    w.emit("trace_span", **s)
                summ = getattr(ctx, "trace_summary", None)
                if spans and summ is not None:
                    w.emit("trace_summary", **summ)
            except Exception:
                pass
            end = {"status": status, "wall_s": round(wall, 6)}
            if err is not None:
                end["error"] = err
            w.emit("query_end", **end)
        finally:
            w.close()


def _fleet_stats():
    """Counter snapshot of this thread's active fleet member, or None
    outside a fleet — the `fleet` event only appears in logs of fleet
    processes."""
    try:
        from ..fleet import context as fleet_context
    except Exception:
        return None
    m = fleet_context.active_member()
    if m is None:
        return None
    return {k: v for k, v in m.snapshot().items()
            if isinstance(v, (int, float))}


def _fleet_delta(before, after) -> dict:
    """Per-query deltas for the monotone counters, absolute values for
    the gauges (export size, live-peer count)."""
    before = before or {}
    out = {}
    for k, v in after.items():
        if k.startswith(("fleet_export_", "fleet_peers_")):
            out[k.replace("fleet_", "", 1)] = v
        else:
            out[k.replace("fleet_", "", 1)] = v - before.get(k, 0)
    return out


def log_fast_path(session, conf, handle, action: str, rows: int,
                  wall_s: float):
    """Compact event log for a result-cache FAST-PATH hit: the query
    never planned or executed, so the full profile_query sequence does
    not apply — but a served query must still leave an auditable
    record (query_start / result_cache / query_end)."""
    w = open_query_log(conf, handle.query_id if handle is not None
                       else next_query_id())
    if w is None:
        return
    try:
        if session is not None:
            session.last_event_log = w.path
        w.emit("query_start", action=action, fast_path=True)
        w.emit("result_cache", hits=1, misses=0, fast_path=True,
               rows=int(rows))
        w.emit("query_end", status="ok", wall_s=round(wall_s, 6))
    finally:
        w.close()
