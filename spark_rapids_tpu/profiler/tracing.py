"""Per-query distributed tracing: spans with context propagation.

The event log (profiler/event_log.py) records WHAT happened; spans
record WHERE the wall clock went once a query fans out across the
service gateway, the AQE stage driver, the compile pool, the
exchange/broadcast map pools and remote executors. One trace per query
(trace_id == query_id), assembled into `trace_span` records in the
query's event log and reduced to latency shares by
profiler/critical_path.py.

Design constraints, in order:

1. CHEAP WHEN OFF. `span()` resolves the active TraceContext with one
   attribute read; an unsampled/disabled trace yields a shared no-op
   span and touches nothing else. The <3% q6 A/B overhead gate in
   tests/test_tracing.py holds the tracing-ON path to the same bar.
2. ONE TRACE PER QUERY ACROSS PROCESSES. The context is three fields
   (trace_id, span_id, sampled) and rides:
     - `ExecContext.trace` on the query thread,
     - a thread-local for worker threads (`use()` — exchange map pools,
       broadcast builds, the compile pool),
     - the serialized conf dict in cluster RPC task frames
       (`inject_into_conf` / `adopt_from_conf`), so executor-side spans
       parent correctly under the driver's stage span and come home
       with task metrics (cluster/task_metrics.py side channel).
3. CLOCKS. start/end are `time.time_ns()` — CLOCK_REALTIME, comparable
   across the driver and executor processes of one host (the cluster
   runner is single-host by construction). Durations inside one
   process additionally carry the monotonic-derived `dur_ms` so a
   clock step cannot corrupt a span's own length.

Span records are plain dicts (JSON-able, picklable for the task-metric
side channel):

  {trace_id, span_id, parent_id, name, kind, start_ns, end_ns,
   dur_ms, proc, attrs?}

Every engine span MUST be closed via `with span(...)` or a
try/finally around `open_span`/`Span.end` — the tpulint `span-leak`
rule (analysis/lint_rules.py) audits the tree for leaks.
"""
from __future__ import annotations

import itertools
import os
import threading
import time
import zlib
from collections import OrderedDict
from contextlib import contextmanager
from typing import Dict, List, Optional

__all__ = ["TraceContext", "Span", "start_trace", "current", "use",
           "span", "open_span", "record_span", "drain_trace",
           "record_queue_span", "record_wait_span", "finish",
           "to_wire", "from_wire",
           "inject_into_conf", "adopt_from_conf", "absorb_spans",
           "TRACE_CONF_KEY"]

#: conf-dict key the distributed runner injects the wire context under:
#: executor task functions rebuild TpuSession(conf) from this very dict,
#: so the context crosses the RPC boundary with zero frame changes
TRACE_CONF_KEY = "spark.rapids.tpu.sql.trace.context"

_SEQ = itertools.count(1)
_TLS = threading.local()

_LOCK = threading.Lock()
_TRACES: Dict[str, List[dict]] = {}     # trace_id -> finished span dicts
#: cap per trace: a runaway span producer must not grow memory without
#: bound; overflow increments the dropped counter instead (the
#: telemetry registry surfaces it)
_MAX_SPANS_PER_TRACE = 4096
_DROPPED = [0]
#: traces already finished on the DRIVER: a straggler span (a
#: background compile outliving its query) must not re-create the
#: trace's buffer — that entry would never be drained again. Bounded
#: ring of recent trace ids; membership drops the span (counted).
_CLOSED: "OrderedDict[str, bool]" = OrderedDict()
_MAX_CLOSED = 512


def _new_span_id() -> str:
    # pid-prefixed so driver and executor processes never collide
    return f"{os.getpid():x}.{next(_SEQ):x}"


class TraceContext:
    """The three propagated fields; immutable by convention."""

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: str, span_id: Optional[str],
                 sampled: bool = True):
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = sampled

    def __repr__(self):
        return (f"TraceContext({self.trace_id!r}, {self.span_id!r}, "
                f"sampled={self.sampled})")


class Span:
    """One open span. End it exactly once (with-statement or finally);
    ending records the finished dict into the per-trace buffer."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "kind",
                 "start_ns", "attrs", "_t0", "_done", "_restore")

    def __init__(self, trace_id, span_id, parent_id, name, kind, attrs):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.kind = kind
        self.attrs = attrs
        self.start_ns = time.time_ns()
        self._t0 = time.perf_counter()
        self._done = False
        self._restore = None

    def set(self, key: str, value) -> None:
        """Attach one attribute (retry counts, byte sizes, fault tags)."""
        if self.attrs is None:
            self.attrs = {}
        self.attrs[key] = value

    def end(self) -> None:
        if self._done:
            return
        self._done = True
        dur = time.perf_counter() - self._t0
        rec = {"trace_id": self.trace_id, "span_id": self.span_id,
               "parent_id": self.parent_id, "name": self.name,
               "kind": self.kind, "start_ns": self.start_ns,
               "end_ns": self.start_ns + int(dur * 1e9),
               "dur_ms": round(dur * 1e3, 4),
               "proc": os.getpid()}
        if self.attrs:
            rec["attrs"] = self.attrs
        record_span(rec)


class _NoopSpan:
    __slots__ = ()

    def set(self, key, value):
        pass

    def end(self):
        pass


_NOOP = _NoopSpan()


# ---------------------------------------------------------------------
# context resolution
# ---------------------------------------------------------------------
def start_trace(query_id: str, conf) -> Optional[TraceContext]:
    """Root TraceContext for a query, or None when tracing is off or
    this query is sampled out. Sampling is DETERMINISTIC on the query
    id (crc32 bucket vs sql.trace.sampleRate) so a retried query and
    its executor fragments agree on the sampling decision without any
    extra coordination."""
    from ..config import TRACE_ENABLED, TRACE_SAMPLE_RATE
    if not conf.get(TRACE_ENABLED):
        return None
    rate = float(conf.get(TRACE_SAMPLE_RATE))
    if rate <= 0.0:
        return None
    if rate < 1.0:
        bucket = zlib.crc32(query_id.encode("utf-8")) % 10000
        if bucket >= rate * 10000:
            return None
    return TraceContext(query_id, None, True)


def current() -> Optional[TraceContext]:
    """The thread's active TraceContext (None off-trace)."""
    return getattr(_TLS, "ctx", None)


@contextmanager
def use(tc: Optional[TraceContext]):
    """Install `tc` as this thread's context for the duration — the
    bridge onto worker threads (pool map tasks, broadcast builds) that
    have no ExecContext of their own."""
    prev = getattr(_TLS, "ctx", None)
    _TLS.ctx = tc
    try:
        yield tc
    finally:
        _TLS.ctx = prev


def _resolve(ctx) -> Optional[TraceContext]:
    """TraceContext from an explicit TraceContext / ExecContext-like
    carrier, falling back to the thread-local."""
    if ctx is not None:
        if isinstance(ctx, TraceContext):
            return ctx if ctx.sampled else None
        tc = getattr(ctx, "trace", None)
        if tc is not None:
            return tc if tc.sampled else None
    return getattr(_TLS, "ctx", None)


# ---------------------------------------------------------------------
# span lifecycle
# ---------------------------------------------------------------------
def open_span(name: str, kind: str, ctx=None, **attrs):
    """Open a span without the with-statement (callers that must end it
    in an async callback). MUST be paired with `.end()` in a finally —
    the span-leak lint rule flags anything else. Returns a no-op span
    off-trace."""
    tc = _resolve(ctx)
    if tc is None:
        return _NOOP
    return Span(tc.trace_id, _new_span_id(), tc.span_id, name, kind,
                attrs or None)


@contextmanager
def span(name: str, kind: str, ctx=None, **attrs):
    """Open/close one span around a block. While the block runs, the
    thread-local context points at this span, so nested `span()` calls
    (and worker threads seeded via `use(current())`) parent under it."""
    tc = _resolve(ctx)
    if tc is None:
        yield _NOOP
        return
    sp = Span(tc.trace_id, _new_span_id(), tc.span_id, name, kind,
              attrs or None)
    prev = getattr(_TLS, "ctx", None)
    _TLS.ctx = TraceContext(tc.trace_id, sp.span_id, True)
    try:
        yield sp
    finally:
        _TLS.ctx = prev
        sp.end()


def record_span(rec: dict) -> None:
    """Append one finished span to its trace buffer (bounded)."""
    with _LOCK:
        if rec["trace_id"] in _CLOSED:
            _DROPPED[0] += 1          # straggler after the query ended
            return
        buf = _TRACES.setdefault(rec["trace_id"], [])
        if len(buf) >= _MAX_SPANS_PER_TRACE:
            _DROPPED[0] += 1
            return
        buf.append(rec)


def record_wait_span(name: str, kind: str, wait_ms, ctx=None,
                     **attrs) -> None:
    """Synthesize a back-dated span for a wait that already happened —
    admission queues, pool-permit waits, retry backoffs measured after
    the fact. One TLS read and out when off-trace."""
    tc = _resolve(ctx)
    if tc is None or not wait_ms or wait_ms <= 0:
        return
    now = time.time_ns()
    rec = {"trace_id": tc.trace_id, "span_id": _new_span_id(),
           "parent_id": tc.span_id, "name": name, "kind": kind,
           "start_ns": now - int(wait_ms * 1e6), "end_ns": now,
           "dur_ms": round(float(wait_ms), 4), "proc": os.getpid()}
    if attrs:
        rec["attrs"] = attrs
    record_span(rec)


def record_queue_span(tc: Optional[TraceContext], wait_ms,
                      pool: Optional[str] = None) -> None:
    """The admission/queue-wait span: by the time the admitted query
    thread runs, the wait already happened, so it is back-dated from
    the handle's measured queue_wait_ms."""
    if tc is None or not tc.sampled:
        return
    kw = {"pool": pool} if pool else {}
    record_wait_span("admission.queue", "queue", wait_ms, ctx=tc, **kw)


def absorb_spans(recs) -> None:
    """Driver-side entry for executor span records that rode home on
    the task-metric side channel: re-buffer them under their trace so
    drain_trace() assembles ONE per-query trace."""
    for rec in recs or ():
        if isinstance(rec, dict) and rec.get("trace_id"):
            record_span(rec)


def drain_trace(trace_id: str, close: bool = True) -> List[dict]:
    """Remove and return the trace's finished spans, start-ordered.

    `close=True` (the driver, at query end) additionally marks the
    trace finished so stragglers are dropped instead of re-creating an
    undrainable buffer. Executors drain with `close=False` — the same
    trace_id keeps accumulating across that query's later tasks."""
    with _LOCK:
        spans = _TRACES.pop(trace_id, [])
        if close:
            _CLOSED[trace_id] = True
            _CLOSED.move_to_end(trace_id)
            while len(_CLOSED) > _MAX_CLOSED:
                _CLOSED.popitem(last=False)
    spans.sort(key=lambda s: s.get("start_ns", 0))
    return spans


def finish(ctx, wall_s=None) -> List[dict]:
    """Close out a query's trace from its ExecContext: end the root
    span, drain the assembled spans, store the critical-path summary on
    `ctx.trace_summary` and feed the per-category share histograms of
    the live telemetry registry. Idempotent; returns the drained spans
    (empty on a later call, off-trace, or for a nested action that has
    no root span of its own)."""
    tc = getattr(ctx, "trace", None)
    rsp = getattr(ctx, "_root_span", None)
    if tc is None or rsp is None:
        return []
    rsp.end()
    spans = drain_trace(tc.trace_id)
    if not spans:
        return []
    from . import critical_path
    summ = critical_path.summarize(spans, wall_s)
    ctx.trace_summary = summ
    if summ is not None:
        try:
            from . import telemetry
            for c, pct in summ["share_pct"].items():
                telemetry.histogram(
                    f"critical_path_share_pct_{c}").observe(pct)
        except Exception:
            pass
    return spans


def dropped_spans() -> int:
    with _LOCK:
        return _DROPPED[0]


# ---------------------------------------------------------------------
# propagation across the RPC boundary
# ---------------------------------------------------------------------
def to_wire(tc: Optional[TraceContext]) -> Optional[str]:
    if tc is None or not tc.sampled:
        return None
    return f"{tc.trace_id}|{tc.span_id or ''}"


def from_wire(s: Optional[str]) -> Optional[TraceContext]:
    if not s or "|" not in s:
        return None
    trace_id, _, span_id = s.partition("|")
    return TraceContext(trace_id, span_id or None, True)


def inject_into_conf(settings: dict, tc: Optional[TraceContext]) -> dict:
    """Copy of a conf-settings dict with the wire context injected —
    the dict the distributed runner already ships in every task frame.
    Identity when off-trace (no copy, no key)."""
    wire = to_wire(tc)
    if wire is None:
        return settings
    out = dict(settings)
    out[TRACE_CONF_KEY] = wire
    return out


def adopt_from_conf(conf) -> Optional[TraceContext]:
    """Executor-side: rebuild the TraceContext a task frame carried
    (None when the driver ran untraced). Accepts a TpuConf or a plain
    settings dict."""
    d = conf if isinstance(conf, dict) \
        else getattr(conf, "_settings", None)
    if not isinstance(d, dict):
        return None
    return from_wire(d.get(TRACE_CONF_KEY))
