"""Query profiler: the CONSUMER half of the operator-metric story.

The engine has always produced per-operator `MetricSet`s (utils/metrics.py,
the GpuMetric analog) — this package aggregates, persists, and renders
them, mirroring the reference's two consumer surfaces:

  - the structured per-query event log (`event_log.py`), the Spark
    event-log analog a standalone Profiling Tool can post-process;
  - the `EXPLAIN ANALYZE` plan renderer (`analyze.py`), the SQL-UI
    per-node metric display analog (GpuExec metric wiring);
  - XLA compile-cache counters (`xla_stats.py`), the reference's
    spark.rapids.sql.debug compile-time accounting analog.

`tools/profile_report.py` is the standalone Profiling Tool analog built
on `read_event_log` + `aggregate_ops`.
"""
from .analyze import render_analyze
from .event_log import (EventLogWriter, aggregate_ops, next_query_id,
                        op_metrics_records, op_time_seconds,
                        open_query_log, plan_tree, profile_query,
                        read_event_log, top_operators)

__all__ = ["EventLogWriter", "aggregate_ops", "next_query_id",
           "op_metrics_records", "op_time_seconds", "open_query_log",
           "plan_tree", "profile_query", "read_event_log",
           "render_analyze", "top_operators"]
