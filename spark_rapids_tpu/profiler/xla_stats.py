"""XLA compile accounting via jax.monitoring listeners.

Counts backend compiles + compile seconds
(`/jax/core/compile/backend_compile_duration`) and persistent
compilation-cache hits/misses (`/jax/compilation_cache/cache_*`), so the
query event log can attribute cold-start time to compilation — the
"untracked compile overhead" PAPERS.md ("Rethinking Analytical
Processing in the GPU Era") calls out as a dominant hidden cost.

Note: jax's in-memory jit tracing cache emits no events; `cache_hits`
counts PERSISTENT cache retrievals only, so on a warm process most
queries show zero compiles and zero cache traffic — that is the success
case, not a gap. Listeners register once per process and are
version-tolerant (no-ops when jax.monitoring is absent).
"""
from __future__ import annotations

import threading
from typing import Dict

__all__ = ["install", "snapshot", "count_dispatch"]

_lock = threading.Lock()
_stats = {"compiles": 0, "compile_secs": 0.0,
          "cache_hits": 0, "cache_misses": 0, "dispatches": 0}
_installed = False


def count_dispatch(n: int = 1) -> None:
    """Record `n` executable dispatches. jax.monitoring has no dispatch
    event, so per-batch jit call sites in the exec layer call this
    explicitly; snapshot() diffs then expose per-query xlaDispatches."""
    with _lock:
        _stats["dispatches"] += n


def install():
    global _installed
    with _lock:
        if _installed:
            return
        _installed = True
    try:
        from jax import monitoring
    except Exception:
        return

    def _on_duration(event, secs, **kw):
        if event.endswith("backend_compile_duration"):
            with _lock:
                _stats["compiles"] += 1
                _stats["compile_secs"] += float(secs)

    def _on_event(event, **kw):
        if event.endswith("cache_hits"):
            with _lock:
                _stats["cache_hits"] += 1
        elif event.endswith("cache_misses"):
            with _lock:
                _stats["cache_misses"] += 1

    try:
        monitoring.register_event_duration_secs_listener(_on_duration)
        monitoring.register_event_listener(_on_event)
    except Exception:
        pass


def snapshot() -> Dict[str, float]:
    """Current cumulative counters (install()s the listeners on first
    use; callers diff two snapshots to scope a query). Includes the
    process-global program cache's hit/miss/eviction counters so the
    xla_compile event record and EXPLAIN ANALYZE carry them alongside
    the compile counts they explain."""
    install()
    with _lock:
        out = dict(_stats)
    try:
        from ..runtime.program_cache import stats as _pc_stats
        pc = _pc_stats()
        pc.pop("program_cache_entries", None)  # gauge, not a counter
        out.update(pc)
    except Exception:
        pass
    return out
