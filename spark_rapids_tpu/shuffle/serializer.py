"""Columnar shuffle wire format — the kudo analog.

(reference: jni kudo.KudoSerializer + GpuColumnarBatchSerializer.scala.)
A flat, length-prefixed binary layout per sub-batch: little-endian header,
then per column validity/data(/offsets) raw buffers. No compression by
default (nvcomp analog is a conf'd host codec). Written/read with numpy
memoryviews — zero object overhead, mmap-friendly.

Layout:
  u32 magic 'KTPU' | u32 n_cols | u64 n_rows
  per column: u8 has_offsets | u64 validity_bytes | u64 data_bytes |
              u64 offsets_bytes | buffers...
"""
from __future__ import annotations

import io
import struct
from typing import BinaryIO, Dict, List, Optional, Tuple

import numpy as np

from ..utils.native import pack_validity, unpack_validity

__all__ = ["write_subbatch", "read_subbatch", "HostSubBatch"]

_MAGIC = 0x4B545056  # v2: validity bit order is LSB-first


class HostSubBatch:
    """Host-side compacted rows of one shuffle partition: per column a
    dict with 'validity', 'data', and optionally 'offsets' (rebased to 0)."""

    def __init__(self, cols: List[Dict[str, np.ndarray]], n_rows: int):
        self.cols = cols
        self.n_rows = n_rows

    @property
    def nbytes(self) -> int:
        return sum(b.nbytes for c in self.cols for b in c.values())


def write_subbatch(out: BinaryIO, sb: HostSubBatch, codec=None) -> int:
    body = io.BytesIO()
    body.write(struct.pack("<IIQ", _MAGIC, len(sb.cols), sb.n_rows))
    for c in sb.cols:
        off = c.get("offsets")
        validity = pack_validity(c["validity"])
        data = np.ascontiguousarray(c["data"])
        body.write(struct.pack("<BQQQ", 1 if off is not None else 0,
                               validity.nbytes, data.nbytes,
                               off.nbytes if off is not None else 0))
        body.write(validity.tobytes())
        body.write(data.tobytes())
        if off is not None:
            body.write(np.ascontiguousarray(off).tobytes())
    raw = body.getvalue()
    if codec is not None:
        raw = codec.compress(raw)
    out.write(struct.pack("<Q", len(raw)))
    out.write(raw)
    return 8 + len(raw)


def read_subbatch(inp: BinaryIO, dtypes, codec=None,
                  items_per_row=None) -> Optional[HostSubBatch]:
    """dtypes: list of numpy dtypes for the data buffers. items_per_row:
    per-column fixed-width items per row (2 for decimal128 limb pairs);
    columns with >1 reshape to [n_rows, items]."""
    hdr = inp.read(8)
    if len(hdr) < 8:
        return None
    (blen,) = struct.unpack("<Q", hdr)
    raw = inp.read(blen)
    if len(raw) < blen:
        raise IOError(f"truncated shuffle block: {len(raw)}/{blen} bytes")
    if codec is not None:
        raw = codec.decompress(raw)
    buf = memoryview(raw)
    if len(buf) < 16:
        raise IOError("corrupt shuffle block: short header")
    magic, n_cols, n_rows = struct.unpack_from("<IIQ", buf, 0)
    if magic != _MAGIC:
        raise IOError(f"corrupt shuffle block: bad magic {magic:#x}")
    if n_cols != len(dtypes):
        raise IOError(f"corrupt shuffle block: {n_cols} columns, "
                      f"expected {len(dtypes)}")
    pos = 16
    cols = []
    for ci in range(n_cols):
        if pos + 25 > len(buf):
            raise IOError("corrupt shuffle block: truncated column header")
        has_off, vb, db, ob = struct.unpack_from("<BQQQ", buf, pos)
        pos += 25
        if pos + vb + db + (ob if has_off else 0) > len(buf):
            raise IOError("corrupt shuffle block: buffer lengths exceed "
                          "block size")
        if vb * 8 < n_rows:
            raise IOError("corrupt shuffle block: validity buffer shorter "
                          f"than {n_rows} rows")
        item = dtypes[ci].itemsize
        if not has_off and (db % item or db // item < n_rows):
            raise IOError(f"corrupt shuffle block: data buffer {db}B for "
                          f"{n_rows} rows of {dtypes[ci]}")
        if has_off and ob < 4 * (n_rows + 1):
            raise IOError(f"corrupt shuffle block: offsets buffer {ob}B "
                          f"for {n_rows} rows")
        vbits = np.frombuffer(buf, np.uint8, vb, pos)
        pos += vb
        validity = unpack_validity(vbits, n_rows)
        data = np.frombuffer(buf, dtypes[ci], db // dtypes[ci].itemsize, pos)
        ipr = items_per_row[ci] if items_per_row else 1
        if ipr > 1 and not has_off:
            if data.shape[0] != n_rows * ipr:
                raise IOError("corrupt shuffle block: limb count mismatch")
            data = data.reshape(n_rows, ipr)
        pos += db
        col = {"validity": validity, "data": data}
        if has_off:
            col["offsets"] = np.frombuffer(buf, np.int32, ob // 4, pos)
            pos += ob
        cols.append(col)
    return HostSubBatch(cols, n_rows)
