"""Columnar shuffle wire format — the kudo analog.

(reference: jni kudo.KudoSerializer + GpuColumnarBatchSerializer.scala.)
A flat, length-prefixed binary layout per sub-batch: little-endian header,
then per column validity/data(/offsets) raw buffers. No compression by
default (nvcomp analog is a conf'd host codec). Written/read with numpy
memoryviews — zero object overhead, mmap-friendly.

Layout:
  u32 magic 'KTPU' | u32 n_cols | u64 n_rows
  per column: u8 has_offsets | u8 n_children | u64 validity_bytes |
              u64 data_bytes | u64 offsets_bytes | buffers...
  then per child: u64 child_n_rows | recursive column block
(nested columns — list offsets + element child, struct field children —
serialize as recursive column blocks, the kudo nested-column analog.)
"""
from __future__ import annotations

import io
import struct
from typing import BinaryIO, Dict, List, Optional, Tuple

import numpy as np

from ..utils.native import pack_validity, unpack_validity

__all__ = ["write_subbatch", "read_subbatch", "HostSubBatch", "wire_spec",
           "cv_shuffle_bufs", "slice_host_col"]


def cv_shuffle_bufs(cv) -> Dict:
    """Device buffer tree of a (possibly nested) CV for the map-side bulk
    D2H fetch."""
    d = {"validity": cv.validity}
    if cv.offsets is not None:
        d["offsets"] = cv.offsets
    if cv.children:
        d["children"] = [cv_shuffle_bufs(c) for c in cv.children]
    else:
        d["data"] = cv.data
    return d


def slice_host_col(cb: Dict, lo: int, hi: int) -> Dict:
    """Slice fetched host buffers to rows [lo, hi), rebasing offsets to 0
    and recursively slicing list element ranges / struct children.
    Assumes dense offsets (map-side columns come out of a compacting
    gather, which rebuilds them dense)."""
    out = {"validity": np.asarray(cb["validity"])[lo:hi]}
    if "offsets" in cb:
        off = np.asarray(cb["offsets"])
        o = off[lo:hi + 1].astype(np.int32)
        base = int(o[0]) if len(o) else 0
        out["offsets"] = o - base
        end = int(o[-1]) if len(o) else 0
        if "children" in cb:
            kid = slice_host_col(cb["children"][0], base, end)
            kid["_n"] = np.int64(end - base)
            out["children"] = [kid]
        else:
            out["data"] = np.asarray(cb["data"])[base:end]
    elif "children" in cb:
        kids = []
        for c in cb["children"]:
            kid = slice_host_col(c, lo, hi)
            kid["_n"] = np.int64(hi - lo)
            kids.append(kid)
        out["children"] = kids
    else:
        out["data"] = np.asarray(cb["data"])[lo:hi]
    return out

_MAGIC = 0x4B545056  # v2: validity bit order is LSB-first


class HostSubBatch:
    """Host-side compacted rows of one shuffle partition: per column a
    dict with 'validity', 'data', and optionally 'offsets' (rebased to 0)."""

    def __init__(self, cols: List[Dict[str, np.ndarray]], n_rows: int):
        self.cols = cols
        self.n_rows = n_rows

    @property
    def nbytes(self) -> int:
        return sum(b.nbytes for c in self.cols for b in c.values())


def _write_col(body: io.BytesIO, c: Dict[str, np.ndarray]):
    off = c.get("offsets")
    kids = c.get("children", [])
    validity = pack_validity(c["validity"])
    data = (np.ascontiguousarray(c["data"]) if "data" in c
            else np.zeros(0, np.uint8))
    body.write(struct.pack("<BBQQQ", 1 if off is not None else 0,
                           len(kids), validity.nbytes, data.nbytes,
                           off.nbytes if off is not None else 0))
    body.write(validity.tobytes())
    body.write(data.tobytes())
    if off is not None:
        body.write(np.ascontiguousarray(off).tobytes())
    for k in kids:
        body.write(struct.pack("<Q", int(k["_n"])))
        _write_col(body, k)


def write_subbatch(out: BinaryIO, sb: HostSubBatch, codec=None) -> int:
    body = io.BytesIO()
    body.write(struct.pack("<IIQ", _MAGIC, len(sb.cols), sb.n_rows))
    for c in sb.cols:
        _write_col(body, c)
    raw = body.getvalue()
    if codec is not None:
        raw = codec.compress(raw)
    out.write(struct.pack("<Q", len(raw)))
    out.write(raw)
    return 8 + len(raw)


def wire_spec(dtype) -> Dict:
    """Per-column wire layout derived from the SQL type:
    {"np": numpy dtype, "items": fixed items/row, "var": has offsets,
     "nested": bool, "children": [spec...]}."""
    from ..columnar import dtypes as dt
    if isinstance(dtype, (dt.ArrayType, dt.MapType)):
        from ..columnar.column import Column
        return {"np": np.dtype(np.uint8), "items": 1, "var": True,
                "nested": True,
                "children": [wire_spec(Column.element_dtype(dtype))]}
    if isinstance(dtype, dt.StructType):
        return {"np": np.dtype(np.uint8), "items": 1, "var": False,
                "nested": True,
                "children": [wire_spec(f.dtype) for f in dtype.fields]}
    items = 2 if (isinstance(dtype, dt.DecimalType)
                  and dtype.is_decimal128) else 1
    return {"np": dtype.np_dtype or np.dtype(np.int8), "items": items,
            "var": dtype.is_variable_width, "nested": False,
            "children": []}


def _read_col(buf, pos: int, n_rows: int, spec: Dict):
    if pos + 26 > len(buf):
        raise IOError("corrupt shuffle block: truncated column header")
    has_off, n_kids, vb, db, ob = struct.unpack_from("<BBQQQ", buf, pos)
    pos += 26
    if n_kids != len(spec["children"]):
        raise IOError(f"corrupt shuffle block: {n_kids} children, "
                      f"expected {len(spec['children'])}")
    if pos + vb + db + (ob if has_off else 0) > len(buf):
        raise IOError("corrupt shuffle block: buffer lengths exceed "
                      "block size")
    if vb * 8 < n_rows:
        raise IOError("corrupt shuffle block: validity buffer shorter "
                      f"than {n_rows} rows")
    item = spec["np"].itemsize
    if not has_off and not spec["nested"] and \
            (db % item or db // item < n_rows * spec["items"]):
        raise IOError(f"corrupt shuffle block: data buffer {db}B for "
                      f"{n_rows} rows of {spec['np']}")
    if has_off and ob < 4 * (n_rows + 1):
        raise IOError(f"corrupt shuffle block: offsets buffer {ob}B "
                      f"for {n_rows} rows")
    vbits = np.frombuffer(buf, np.uint8, vb, pos)
    pos += vb
    validity = unpack_validity(vbits, n_rows)
    col = {"validity": validity}
    if not spec["nested"]:
        data = np.frombuffer(buf, spec["np"], db // item, pos)
        if spec["items"] > 1 and not has_off:
            if data.shape[0] != n_rows * spec["items"]:
                raise IOError("corrupt shuffle block: limb count mismatch")
            data = data.reshape(n_rows, spec["items"])
        col["data"] = data
    pos += db
    if has_off:
        col["offsets"] = np.frombuffer(buf, np.int32, ob // 4, pos)
        pos += ob
    kids = []
    for ks in spec["children"]:
        if pos + 8 > len(buf):
            raise IOError("corrupt shuffle block: truncated child header")
        (child_n,) = struct.unpack_from("<Q", buf, pos)
        pos += 8
        kc, pos = _read_col(buf, pos, child_n, ks)
        kc["_n"] = np.int64(child_n)
        kids.append(kc)
    if kids:
        col["children"] = kids
    return col, pos


def read_subbatch(inp: BinaryIO, specs, codec=None) -> \
        Optional[HostSubBatch]:
    """specs: per-column wire_spec trees."""
    hdr = inp.read(8)
    if len(hdr) < 8:
        return None
    (blen,) = struct.unpack("<Q", hdr)
    raw = inp.read(blen)
    if len(raw) < blen:
        raise IOError(f"truncated shuffle block: {len(raw)}/{blen} bytes")
    if codec is not None:
        raw = codec.decompress(raw)
    buf = memoryview(raw)
    if len(buf) < 16:
        raise IOError("corrupt shuffle block: short header")
    magic, n_cols, n_rows = struct.unpack_from("<IIQ", buf, 0)
    if magic != _MAGIC:
        raise IOError(f"corrupt shuffle block: bad magic {magic:#x}")
    if n_cols != len(specs):
        raise IOError(f"corrupt shuffle block: {n_cols} columns, "
                      f"expected {len(specs)}")
    pos = 16
    cols = []
    for ci in range(n_cols):
        col, pos = _read_col(buf, pos, n_rows, specs[ci])
        cols.append(col)
    return HostSubBatch(cols, n_rows)
