"""Multithreaded host-file shuffle — the portable baseline transport.

(reference: RapidsShuffleThreadedWriter/Reader + MULTITHREADED mode,
RapidsShuffleInternalManagerBase.scala:120; SURVEY.md §2.7.) Map tasks
bucket rows by target partition ON DEVICE (one sort + one bulk D2H per
batch), slice per-partition sub-batches host-side, and a thread pool
appends them to per-map shuffle files with a trailing segment index.
Reduce tasks read their segment from every map file (thread pool),
concatenate on host, and do ONE H2D.
"""
from __future__ import annotations

import concurrent.futures as cf
import io
import os
import struct
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..columnar import dtypes as dt
from ..columnar.column import Column, bucket_capacity
from ..columnar.table import Schema, Table
from ..exec.batch import DeviceBatch
from ..runtime import racedep
from ..utils.transfer import fetch
from .serializer import HostSubBatch, read_subbatch, write_subbatch

__all__ = ["LocalShuffle", "get_codec"]


def get_codec(name: str):
    if name in (None, "none", ""):
        return None
    if name == "lz4":
        try:
            import lz4.frame as lz4f  # optional
            return lz4f
        except ImportError:
            import zlib
            return zlib  # gated fallback: zlib is always available
    if name == "zstd":
        try:
            import zstandard  # optional

            class _Z:
                compress = staticmethod(
                    lambda b: zstandard.ZstdCompressor().compress(b))
                decompress = staticmethod(
                    lambda b: zstandard.ZstdDecompressor().decompress(b))
            return _Z
        except ImportError:
            import zlib
            return zlib
    raise ValueError(f"unknown codec {name}")


def _np_dtype_for(f_dtype: dt.DataType) -> np.dtype:
    return np.dtype(f_dtype.np_dtype or np.int8)


class LocalShuffle:
    """One shuffle exchange: N map inputs -> M reduce partitions."""

    def __init__(self, shuffle_id: str, num_reduce: int, schema: Schema,
                 shuffle_dir: str = "/tmp/srtpu-shuffle",
                 writer_threads: int = 4, reader_threads: int = 4,
                 codec: Optional[str] = None):
        self.id = shuffle_id
        self.n = num_reduce
        self.schema = schema
        self.dir = os.path.join(shuffle_dir, f"shuffle-{shuffle_id}")
        os.makedirs(self.dir, exist_ok=True)
        import atexit
        atexit.register(self.cleanup)  # ShuffleCleanupManager analog
        self.writer_threads = writer_threads
        self.reader_threads = reader_threads
        self.codec = get_codec(codec)
        from ..runtime import lockdep
        self._lock = lockdep.lock("LocalShuffle._lock")
        # keyed by map partition id and iterated in sorted order: with a
        # parallel map side, COMPLETION order is nondeterministic but
        # reduce-side concatenation must stay byte-identical to serial
        self._map_files: Dict[int, str] = {}
        self._arena = None  # lazy HostArena for reduce-side assembly
        self.metrics = {"bytesWritten": 0, "blocksWritten": 0}
        # exact per-reduce-partition serialized bytes + rows, summed at
        # WRITE time (the MapOutputStatistics analog): the skew/coalesce
        # detectors read these without re-opening any map file
        self._rp_bytes = [0] * self.n
        self._rp_rows = [0] * self.n

    # ---------------- map side ----------------------------------------
    def write_map_partition(self, mpid: int, pieces_per_reduce):
        """pieces_per_reduce: list over reduce pid of lists of
        HostSubBatch. Serialization runs on the writer thread pool; the
        file itself is written sequentially with a trailing index."""
        path = os.path.join(self.dir, f"map-{mpid}.bin")

        def ser(sb: HostSubBatch) -> bytes:
            buf = io.BytesIO()
            write_subbatch(buf, sb, self.codec)
            return buf.getvalue()

        flat = [(rp, sb) for rp in range(self.n)
                for sb in pieces_per_reduce[rp]]
        if self.writer_threads > 1 and len(flat) > 1:
            with cf.ThreadPoolExecutor(
                    self.writer_threads,
                    thread_name_prefix="tpu-shufwrite") as pool:
                # tpulint: allow[wait-under-lock] serializer pool is private, CPU/file-bound, and takes no locks or permits — join under the exchange build lock cannot cycle
                blocks = list(pool.map(lambda t: ser(t[1]), flat))
        else:
            blocks = [ser(sb) for _, sb in flat]
        index = []  # (offset, length) per reduce partition
        nbytes = nblocks = 0
        with open(path, "wb") as f:
            bi = 0
            for rp in range(self.n):
                start = f.tell()
                for sb in pieces_per_reduce[rp]:
                    f.write(blocks[bi])
                    nbytes += len(blocks[bi])
                    nblocks += 1
                    bi += 1
                index.append((start, f.tell() - start))
            idx_off = f.tell()
            for off, ln in index:
                f.write(struct.pack("<QQ", off, ln))
            f.write(struct.pack("<QI", idx_off, self.n))
        with self._lock:  # concurrent map workers share the metrics dict
            racedep.note_access("LocalShuffle._map_files", mpid,
                                write=True)
            self.metrics["bytesWritten"] += nbytes
            self.metrics["blocksWritten"] += nblocks
            for rp in range(self.n):
                self._rp_bytes[rp] += index[rp][1]
                self._rp_rows[rp] += sum(sb.n_rows
                                         for sb in pieces_per_reduce[rp])
            self._map_files[mpid] = path

    # ---------------- reduce side --------------------------------------
    def _segment_extent(self, f, rpid: int):
        f.seek(-12, os.SEEK_END)
        idx_off, _n = struct.unpack("<QI", f.read(12))
        f.seek(idx_off + 16 * rpid)
        return struct.unpack("<QQ", f.read(16))

    def _block_ranges(self, path: str, rpid: int):
        """(offset, length) of each serialized block in this partition's
        segment — length prefixes only, payloads are skipped (cheap)."""
        blocks = []
        with open(path, "rb") as f:
            off, ln = self._segment_extent(f, rpid)
            pos, end = off, off + ln
            while pos < end:
                f.seek(pos)
                (blen,) = struct.unpack("<Q", f.read(8))
                blocks.append((pos, 8 + blen))
                pos += 8 + blen
        return blocks

    def read_reduce_partition(self, rpid: int, chunk: int = 0,
                              nchunks: int = 1) -> List[HostSubBatch]:
        """Sub-batches of one reduce partition; with nchunks > 1 only the
        blocks of serialized-byte slice `chunk` are read AND decoded
        (adaptive skew split must not re-materialize the whole partition
        per slice)."""
        from .serializer import wire_spec
        specs = [wire_spec(f.dtype) for f in self.schema.fields]

        with self._lock:
            racedep.note_access("LocalShuffle._map_files")
            files = [self._map_files[k] for k in sorted(self._map_files)]

        selected = None
        if nchunks > 1:
            per_file = [self._block_ranges(p, rpid) for p in files]
            total = sum(ln for blocks in per_file for _, ln in blocks)
            bounds = [total * c // nchunks for c in range(nchunks + 1)]
            selected = []
            acc = 0
            for blocks in per_file:
                sel = []
                for pos, ln in blocks:
                    if bounds[chunk] <= acc < bounds[chunk + 1]:
                        sel.append((pos, ln))
                    acc += ln
                selected.append(sel)

        def read_one(args) -> List[HostSubBatch]:
            fi, path = args
            out = []
            with open(path, "rb") as f:
                if selected is None:
                    off, ln = self._segment_extent(f, rpid)
                    f.seek(off)
                    seg = io.BytesIO(f.read(ln))
                else:
                    chunks = []
                    for pos, ln in selected[fi]:
                        f.seek(pos)
                        chunks.append(f.read(ln))
                    seg = io.BytesIO(b"".join(chunks))
            while True:
                sb = read_subbatch(seg, specs, self.codec)
                if sb is None:
                    break
                out.append(sb)
            return out

        if self.reader_threads > 1 and len(files) > 1:
            with cf.ThreadPoolExecutor(
                    self.reader_threads,
                    thread_name_prefix="tpu-shufread") as pool:
                results = list(pool.map(read_one, enumerate(files)))
        else:
            results = [read_one((i, p)) for i, p in enumerate(files)]
        return [sb for r in results for sb in r]

    def partition_stats(self) -> List[int]:
        """EXACT serialized bytes per reduce partition, accumulated at
        write time (the MapOutputStatistics analog feeding adaptive
        re-planning) — no map-file re-reads on the replan path."""
        with self._lock:
            return list(self._rp_bytes)

    def partition_row_stats(self) -> List[int]:
        """Rows per reduce partition, accumulated at write time."""
        with self._lock:
            return list(self._rp_rows)

    def reduce_batch_slice(self, rpid: int, chunk: int,
                           nchunks: int) -> Optional[DeviceBatch]:
        """One byte-balanced block slice of a reduce partition (adaptive
        skew split: a skewed partition becomes nchunks tasks; only this
        slice's blocks are read + decoded)."""
        return self._device_batch(
            self.read_reduce_partition(rpid, chunk, nchunks))

    def reduce_batch(self, rpid: int) -> Optional[DeviceBatch]:
        """Concat this partition's sub-batches on host, one H2D."""
        return self._device_batch(self.read_reduce_partition(rpid))

    def _device_batch(self, subs) -> Optional[DeviceBatch]:
        import jax
        total = sum(sb.n_rows for sb in subs)
        if total == 0:
            return None
        cap = bucket_capacity(total)
        bufs = [self._assemble([sb.cols[ci] for sb in subs],
                               [sb.n_rows for sb in subs], f.dtype, cap)
                for ci, f in enumerate(self.schema.fields)]
        dev = jax.device_put(bufs)
        if self._arena is not None:
            self._arena.reset()  # safe: device_put copied the buffers
        cols = [Column.build(f.dtype, total, d)
                for f, d in zip(self.schema.fields, dev)]
        return DeviceBatch(Table(self.schema.names, cols), total)

    def _assemble(self, cols, ns, dtype, cap):
        """Concatenate one column's sub-batch host buffers into padded
        device-ready buffers; recurses through list/struct children."""
        validity = np.zeros(cap, np.bool_)
        pos = 0
        for c, n in zip(cols, ns):
            validity[pos:pos + n] = c["validity"][:n]
            pos += n
        if isinstance(dtype, (dt.ArrayType, dt.MapType)):
            kid_ns = [int(c["children"][0]["_n"]) for c in cols]
            child_total = sum(kid_ns)
            offs = [np.zeros(1, np.int32)]
            shift = 0
            p = 0
            for c, n, kn in zip(cols, ns, kid_ns):
                o = c["offsets"][:n + 1].astype(np.int32)
                offs.append(o[1:] + shift)
                shift += kn
                p += n
            off = np.concatenate(offs)
            off = np.concatenate(
                [off, np.full(cap + 1 - len(off),
                              off[-1] if len(off) else 0, np.int32)])
            child_cap = bucket_capacity(max(child_total, 1))
            kid = self._assemble([c["children"][0] for c in cols], kid_ns,
                                 Column.element_dtype(dtype), child_cap)
            kid["_n"] = np.int64(child_total)
            return {"validity": validity, "offsets": off,
                    "children": [kid]}
        if isinstance(dtype, dt.StructType):
            kids = []
            for fi, f in enumerate(dtype.fields):
                kid = self._assemble([c["children"][fi] for c in cols],
                                     ns, f.dtype, cap)
                kid["_n"] = np.int64(sum(ns))
                kids.append(kid)
            return {"validity": validity, "children": kids}
        if dtype.is_variable_width:
            datas, offs = [], [np.zeros(1, np.int32)]
            shift = 0
            for c, n in zip(cols, ns):
                datas.append(c["data"])
                o = c["offsets"][:n + 1]
                offs.append(o[1:].astype(np.int32) + shift)
                shift += len(c["data"])
            data = (np.concatenate(datas) if datas
                    else np.zeros(0, np.uint8))
            dcap = bucket_capacity(max(len(data), 1))
            data = np.concatenate(
                [data, np.zeros(dcap - len(data), np.uint8)])
            off = np.concatenate(offs)
            off = np.concatenate(
                [off, np.full(cap + 1 - len(off), off[-1], np.int32)])
            return {"data": data, "validity": validity, "offsets": off}
        np_dt = _np_dtype_for(dtype)
        if isinstance(dtype, dt.DecimalType) and dtype.is_decimal128:
            data = np.zeros((cap, 2), np_dt)
        else:
            data = self._arena_zeros(cap, np_dt)
        pos = 0
        for c, n in zip(cols, ns):
            data[pos:pos + n] = c["data"][:n]
            validity[pos:pos + n] = c["validity"][:n]
            pos += n
        return {"data": data, "validity": validity}

    def _arena_zeros(self, count: int, np_dt) -> np.ndarray:
        """Assembly buffer from the native host arena (RMM-host-pool
        analog); heap fallback when absent or full."""
        import jax
        from ..utils.native import HostArena, native_lib
        # On the CPU backend device_put may ALIAS host memory, so arena
        # reset would corrupt live batches; accelerators always copy H2D.
        if jax.default_backend() == "cpu":
            return np.zeros(count, np.dtype(np_dt))
        if self._arena is None and native_lib() is not None:
            try:
                # the shuffle-assembly arena draws from the GLOBAL host
                # budget (HostAlloc analog); denied -> heap fallback
                from ..memory.host import HostBudgetExceeded, host_manager
                hm = host_manager()
                try:
                    hm.reserve(256 << 20)
                except HostBudgetExceeded:
                    raise MemoryError("host budget")
                try:
                    self._arena = HostArena(256 << 20)
                    self._arena_reserved = True
                except MemoryError:
                    hm.release(256 << 20)
                    raise
            except MemoryError:
                self._arena = None
        if self._arena is not None:
            arr = self._arena.alloc_array(count, np_dt)
            if arr is not None:
                arr[:] = 0
                return arr
        return np.zeros(count, np.dtype(np_dt))

    def cleanup(self):
        import shutil
        if getattr(self, "_arena_reserved", False):
            # return the arena's host-budget reservation (one per
            # shuffle exchange; leaking it would starve the budget)
            from ..memory.host import host_manager
            host_manager().release(256 << 20)
            self._arena_reserved = False
        if self._arena is not None:
            try:
                self._arena.close()
            except Exception:
                pass
            self._arena = None
        shutil.rmtree(self.dir, ignore_errors=True)
