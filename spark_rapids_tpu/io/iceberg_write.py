"""Iceberg write path: append/overwrite commits with Avro manifests.

Reference: the plugin's iceberg module write support (GpuIcebergWrite /
SparkWrite shimming). Commit flow follows the SHAPE of the Iceberg v1
layout: data parquet files under data/, a manifest Avro listing the
added files, a manifest-list Avro naming every live manifest, a new
vN.metadata.json appending the snapshot, and version-hint.text
pointing at it. Appends reuse the previous snapshot's manifests and
add one more; overwrite starts a fresh manifest list.

COMPATIBILITY: the manifest records carry a SUBSET of the v1 required
fields (no added_snapshot_id, partition data, or sequence numbers) and
embed absolute local file paths, so these commits are self-readable
(by this engine's Iceberg reader) but are NOT guaranteed to load in
standard Iceberg readers. See docs/compatibility.md."""
from __future__ import annotations

import json
import os
import time
import uuid
from typing import Dict, List

from .avro import AvroReader, AvroWriter

__all__ = ["write_iceberg"]


def _iceberg_type(d) -> object:
    from ..columnar import dtypes as dt
    if isinstance(d, dt.BooleanType):
        return "boolean"
    if isinstance(d, (dt.ByteType, dt.ShortType, dt.IntegerType)):
        return "int"
    if isinstance(d, dt.LongType):
        return "long"
    if isinstance(d, dt.FloatType):
        return "float"
    if isinstance(d, dt.DoubleType):
        return "double"
    if isinstance(d, dt.DateType):
        return "date"
    if isinstance(d, dt.TimestampType):
        return "timestamptz"
    if isinstance(d, dt.StringType):
        return "string"
    if isinstance(d, dt.BinaryType):
        return "binary"
    if isinstance(d, dt.DecimalType):
        return f"decimal({d.precision}, {d.scale})"
    raise ValueError(f"iceberg write: unsupported type {d}")


def _schema_json(schema) -> Dict:
    return {"type": "struct",
            "schema-id": 0,
            "fields": [{"id": i + 1, "name": f.name,
                        "required": False,
                        "type": _iceberg_type(f.dtype)}
                       for i, f in enumerate(schema.fields)]}


# faithful subset of the v1 manifest-entry Avro schema: the fields the
# read path (and this writer's own round-trip) consumes
_DATA_FILE = {
    "type": "record", "name": "data_file", "fields": [
        {"name": "content", "type": "int", "default": 0},
        {"name": "file_path", "type": "string"},
        {"name": "file_format", "type": "string"},
        {"name": "record_count", "type": "long"},
        {"name": "file_size_in_bytes", "type": "long"},
    ]}
_MANIFEST_ENTRY = {
    "type": "record", "name": "manifest_entry", "fields": [
        {"name": "status", "type": "int"},
        {"name": "snapshot_id", "type": ["null", "long"],
         "default": None},
        {"name": "data_file", "type": _DATA_FILE},
    ]}
_MANIFEST_FILE = {
    "type": "record", "name": "manifest_file", "fields": [
        {"name": "manifest_path", "type": "string"},
        {"name": "manifest_length", "type": "long"},
        {"name": "partition_spec_id", "type": "int", "default": 0},
        {"name": "content", "type": "int", "default": 0},
        {"name": "added_files_count", "type": ["null", "int"],
         "default": None},
    ]}


def write_iceberg(df, path: str, mode: str = "append") -> int:
    """Commit df as an Iceberg snapshot; returns rows written."""
    import pyarrow.parquet as pq

    mdir = os.path.join(path, "metadata")
    ddir = os.path.join(path, "data")
    os.makedirs(mdir, exist_ok=True)
    os.makedirs(ddir, exist_ok=True)

    # current state (if any)
    from .iceberg import IcebergTable
    exists = bool(
        [n for n in os.listdir(mdir) if n.endswith(".metadata.json")])
    if exists and mode == "errorifexists":
        raise FileExistsError(path)
    if exists and mode == "ignore":
        return 0
    prev = IcebergTable(path) if exists else None
    version = 0
    if exists:
        import re as _re
        vnums = [int(m.group(1)) for n in os.listdir(mdir)
                 if (m := _re.search(r"v(\d+)\.metadata", n))]
        # tables written by standard Iceberg writers name metadata
        # 00001-<uuid>.metadata.json: continue from the file count
        version = max(vnums) if vnums else len(
            [n for n in os.listdir(mdir)
             if n.endswith(".metadata.json")])

    snap_id = int(uuid.uuid4().int % (1 << 62))
    commit = uuid.uuid4().hex[:8]
    now_ms = int(time.time() * 1000)

    # 1) data files
    total_rows = 0
    entries: List[Dict] = []
    seq = 0
    for at in df._iter_partition_tables():
        if at.num_rows == 0:
            continue
        fname = os.path.join(
            ddir, f"part-{seq:05d}-{commit}.parquet")
        pq.write_table(at, fname)
        entries.append({
            "status": 1,                       # ADDED
            "snapshot_id": snap_id,
            "data_file": {
                "content": 0,
                "file_path": fname,
                "file_format": "PARQUET",
                "record_count": at.num_rows,
                "file_size_in_bytes": os.path.getsize(fname),
            }})
        total_rows += at.num_rows
        seq += 1

    # 2) manifest avro
    man_path = os.path.join(mdir, f"manifest-{commit}.avro")
    with AvroWriter(man_path, _MANIFEST_ENTRY) as w:
        w.write_block(entries)

    # 3) manifest list: previous manifests (append) + the new one
    manifests: List[Dict] = []
    if prev is not None and mode == "append":
        snap = prev.snapshot()
        if snap is not None:
            mlist = prev._resolve(snap["manifest-list"])
            for m in AvroReader(mlist).records():
                manifests.append({
                    "manifest_path": prev._resolve(m["manifest_path"]),
                    "manifest_length": m.get("manifest_length", 0) or 0,
                    "partition_spec_id":
                        m.get("partition_spec_id", 0) or 0,
                    "content": m.get("content", 0) or 0,
                    "added_files_count": m.get("added_files_count"),
                })
    manifests.append({
        "manifest_path": man_path,
        "manifest_length": os.path.getsize(man_path),
        "partition_spec_id": 0,
        "content": 0,
        "added_files_count": len(entries),
    })
    mlist_path = os.path.join(
        mdir, f"snap-{snap_id}-manifest-list.avro")
    with AvroWriter(mlist_path, _MANIFEST_FILE) as w:
        w.write_block(manifests)

    # 4) metadata json vN+1
    snapshot = {
        "snapshot-id": snap_id,
        "timestamp-ms": now_ms,
        "manifest-list": mlist_path,
        "summary": {"operation":
                    "append" if mode == "append" else "overwrite"},
    }
    if prev is not None:
        # history stays reachable after overwrite too (time travel);
        # only the new manifest LIST decides what is live
        meta = dict(prev.meta)
        meta["snapshots"] = list(meta.get("snapshots", [])) + [snapshot]
        if mode != "append":
            # an overwrite may change the schema: the table metadata
            # must describe what the live files actually contain
            meta["schema"] = _schema_json(df.schema)
            meta.pop("schemas", None)
            meta.pop("current-schema-id", None)
            meta["last-column-id"] = len(df.schema.fields)
    else:
        meta = {
            "format-version": 1,
            "table-uuid": str(uuid.uuid4()),
            "location": path,
            "last-updated-ms": now_ms,
            "last-column-id": len(df.schema.fields),
            "schema": _schema_json(df.schema),
            "partition-spec": [],
            "properties": {},
            "snapshots": [snapshot],
        }
    meta["current-snapshot-id"] = snap_id
    meta["last-updated-ms"] = now_ms
    version += 1
    mpath = os.path.join(mdir, f"v{version}.metadata.json")
    with open(mpath, "w") as f:
        json.dump(meta, f)
    with open(os.path.join(mdir, "version-hint.text"), "w") as f:
        f.write(str(version))
    try:
        from ..runtime import result_cache
        result_cache.invalidate_prefix(path)
    except Exception:
        pass
    return total_rows
