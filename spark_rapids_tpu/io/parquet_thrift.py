"""Minimal Thrift compact-protocol reader for Parquet page headers.

The device Parquet decode path (reference: GpuParquetScan.scala:3364 —
the reference decodes column chunks ON the accelerator via
Table.readParquet) needs page boundaries + encodings from the raw
column-chunk bytes. Page headers are Thrift compact structs; this
parses JUST the fields the decoder needs (~O(pages) host work, no
value bytes touched).

Format notes (parquet.thrift):
  PageHeader: 1:type 2:uncompressed_page_size 3:compressed_page_size
              4:crc 5:data_page_header 7:dictionary_page_header
              8:data_page_header_v2
  DataPageHeader: 1:num_values 2:encoding 3:definition_level_encoding
                  4:repetition_level_encoding 5:statistics
  DictionaryPageHeader: 1:num_values 2:encoding 3:is_sorted
  DataPageHeaderV2: 1:num_values 2:num_nulls 3:num_rows 4:encoding
                    5:definition_levels_byte_length
                    6:repetition_levels_byte_length 7:is_compressed
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

# Parquet encodings (format/Encoding.thrift)
PLAIN = 0
PLAIN_DICTIONARY = 2
RLE = 3
BIT_PACKED = 4
RLE_DICTIONARY = 8

# Page types
DATA_PAGE = 0
INDEX_PAGE = 1
DICTIONARY_PAGE = 2
DATA_PAGE_V2 = 3


class ThriftError(ValueError):
    pass


def _zigzag(u: int) -> int:
    return (u >> 1) ^ -(u & 1)


class _CompactReader:
    """Enough of the Thrift compact protocol to walk Parquet headers."""

    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def varint(self) -> int:
        out = 0
        shift = 0
        while True:
            if self.pos >= len(self.buf):
                raise ThriftError("varint past end")
            b = self.buf[self.pos]
            self.pos += 1
            out |= (b & 0x7F) << shift
            if not b & 0x80:
                return out
            shift += 7
            if shift > 63:
                raise ThriftError("varint too long")

    def _skip(self, ftype: int):
        if ftype in (1, 2):            # BOOL true/false (value in type)
            return
        if ftype == 3:                 # BYTE
            self.pos += 1
        elif ftype in (4, 5, 6):       # I16/I32/I64 zigzag varint
            self.varint()
        elif ftype == 7:               # DOUBLE
            self.pos += 8
        elif ftype == 8:               # BINARY/STRING
            n = self.varint()
            self.pos += n
        elif ftype == 9:               # LIST
            sz = self.buf[self.pos]
            self.pos += 1
            n = sz >> 4
            et = sz & 0x0F
            if n == 15:
                n = self.varint()
            for _ in range(n):
                self._skip(et)
        elif ftype == 12:              # STRUCT
            self.skip_struct()
        else:
            raise ThriftError(f"unsupported thrift type {ftype}")

    def skip_struct(self):
        for _fid, ftype in self.fields():
            self._skip(ftype)

    def fields(self):
        """Yield (field_id, field_type) until STOP; caller must consume
        the value (read or _skip) before advancing."""
        fid = 0
        while True:
            if self.pos >= len(self.buf):
                raise ThriftError("struct past end")
            b = self.buf[self.pos]
            self.pos += 1
            if b == 0:
                return
            delta = b >> 4
            ftype = b & 0x0F
            if delta == 0:
                fid = _zigzag(self.varint())
            else:
                fid += delta
            yield fid, ftype

    def i32(self) -> int:
        return _zigzag(self.varint())


@dataclass
class PageInfo:
    page_type: int
    uncompressed_size: int = 0
    compressed_size: int = 0
    num_values: int = 0
    encoding: int = PLAIN
    def_level_encoding: int = RLE
    # v2 only
    num_nulls: int = 0
    def_levels_byte_length: int = -1   # -1: v1 (length-prefixed in data)
    rep_levels_byte_length: int = 0    # v2; must be 0 for flat columns
    data_compressed: bool = True       # v2 is_compressed flag
    data_offset: int = 0               # payload start within chunk bytes
    is_v2: bool = False


def parse_page_headers(chunk: bytes, total_values: int) -> List[PageInfo]:
    """Walk every page header in a raw column-chunk byte span."""
    out: List[PageInfo] = []
    pos = 0
    seen = 0
    while seen < total_values and pos < len(chunk):
        r = _CompactReader(chunk, pos)
        info = PageInfo(page_type=-1)
        for fid, ftype in r.fields():
            if fid == 1 and ftype in (4, 5, 6):
                info.page_type = r.i32()
            elif fid == 2 and ftype in (4, 5, 6):
                info.uncompressed_size = r.i32()
            elif fid == 3 and ftype in (4, 5, 6):
                info.compressed_size = r.i32()
            elif fid == 5 and ftype == 12 and info.page_type == DATA_PAGE:
                for f2, t2 in r.fields():
                    if f2 == 1 and t2 in (4, 5, 6):
                        info.num_values = r.i32()
                    elif f2 == 2 and t2 in (4, 5, 6):
                        info.encoding = r.i32()
                    elif f2 == 3 and t2 in (4, 5, 6):
                        info.def_level_encoding = r.i32()
                    else:
                        r._skip(t2)
            elif fid == 7 and ftype == 12 \
                    and info.page_type == DICTIONARY_PAGE:
                for f2, t2 in r.fields():
                    if f2 == 1 and t2 in (4, 5, 6):
                        info.num_values = r.i32()
                    elif f2 == 2 and t2 in (4, 5, 6):
                        info.encoding = r.i32()
                    else:
                        r._skip(t2)
            elif fid == 8 and ftype == 12 \
                    and info.page_type == DATA_PAGE_V2:
                info.is_v2 = True
                for f2, t2 in r.fields():
                    if f2 == 1 and t2 in (4, 5, 6):
                        info.num_values = r.i32()
                    elif f2 == 2 and t2 in (4, 5, 6):
                        info.num_nulls = r.i32()
                    elif f2 == 4 and t2 in (4, 5, 6):
                        info.encoding = r.i32()
                    elif f2 == 5 and t2 in (4, 5, 6):
                        info.def_levels_byte_length = r.i32()
                    elif f2 == 6 and t2 in (4, 5, 6):
                        info.rep_levels_byte_length = r.i32()
                    elif f2 == 7 and t2 in (1, 2):
                        # BOOL carries its value in the field type
                        info.data_compressed = (t2 == 1)
                    else:
                        r._skip(t2)
            else:
                r._skip(ftype)
        info.data_offset = r.pos
        out.append(info)
        if info.page_type in (DATA_PAGE, DATA_PAGE_V2):
            seen += info.num_values
        pos = r.pos + info.compressed_size
    return out


@dataclass
class RleRun:
    """One run of the RLE/bit-packed hybrid encoding."""
    out_start: int          # first output value index
    count: int              # number of output values
    is_packed: bool
    value: int = 0          # RLE literal value
    byte_offset: int = 0    # payload offset of packed bits (is_packed)


def parse_hybrid_runs(buf: bytes, start: int, end: int, n_values: int,
                      bit_width: int) -> List[RleRun]:
    """Host walk of an RLE/bit-packed hybrid section: O(runs), value
    bytes untouched (the device expands them)."""
    runs: List[RleRun] = []
    r = _CompactReader(buf, min(start, len(buf)), )
    produced = 0
    byte_w = (bit_width + 7) // 8
    end = min(end, len(buf))
    while produced < n_values and r.pos < end:
        try:
            header = r.varint()
        except ThriftError:
            break
        if header & 1:                   # bit-packed: header>>1 groups of 8
            n = (header >> 1) * 8
            n = min(n, n_values - produced)
            runs.append(RleRun(produced, n, True,
                               byte_offset=r.pos))
            r.pos += (header >> 1) * bit_width
            produced += n
        else:                            # RLE run: count, value
            n = header >> 1
            if r.pos + byte_w > len(buf):
                break
            v = 0
            for i in range(byte_w):
                v |= buf[r.pos + i] << (8 * i)
            r.pos += byte_w
            runs.append(RleRun(produced, min(n, n_values - produced),
                               False, value=v))
            produced += n
    return runs
