"""Local file cache for scan inputs (the reference's filecache:
spark.rapids.filecache.enabled, GpuFileCache — caching remote-store
reads on local disk so repeated scans skip the slow fetch).

Keyed by (absolute path, mtime, size): a changed source file misses and
re-caches. Copies are atomic (tmp + rename), eviction is LRU by access
time down to `filecache.maxBytes`. Off by default — on a single host
with local inputs the copy is pure overhead; enable it when inputs
live on network mounts."""
from __future__ import annotations

import hashlib
import os
import shutil
import threading

__all__ = ["FileCache", "file_cache", "cached_local_path"]


class FileCache:
    def __init__(self, cache_dir: str, max_bytes: int):
        self.dir = cache_dir
        self.max_bytes = max_bytes
        self._lock = threading.Lock()
        self.metrics = {"hits": 0, "misses": 0, "evictions": 0}
        os.makedirs(cache_dir, exist_ok=True)

    def _key(self, path: str) -> str:
        st = os.stat(path)
        h = hashlib.sha1(
            f"{os.path.abspath(path)}|{st.st_mtime_ns}|{st.st_size}"
            .encode()).hexdigest()
        ext = os.path.splitext(path)[1]
        return f"{h}{ext}"

    def local_path(self, path: str) -> str:
        """Cached local copy of `path` (fetching on miss). The fetch
        runs OUTSIDE the lock (a multi-GB network copy must not stall
        hit-path threads); concurrent misses on one file each copy to a
        pid/thread-unique tmp and the atomic rename races benignly —
        same content, one inode wins."""
        dst = os.path.join(self.dir, self._key(path))
        with self._lock:
            if os.path.exists(dst):
                os.utime(dst)               # LRU touch
                self.metrics["hits"] += 1
                return dst
            self.metrics["misses"] += 1
        tmp = f"{dst}.{os.getpid()}.{threading.get_ident()}.tmp"
        try:
            shutil.copyfile(path, tmp)
            os.replace(tmp, dst)            # atomic publish
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        with self._lock:
            self._evict_locked()
        return dst

    def _evict_locked(self):
        entries = []
        total = 0
        for name in os.listdir(self.dir):
            if name.endswith(".tmp"):
                continue
            p = os.path.join(self.dir, name)
            try:
                st = os.stat(p)
            except OSError:
                continue
            entries.append((st.st_atime, st.st_size, p))
            total += st.st_size
        entries.sort()                      # oldest access first
        for _, size, p in entries:
            if total <= self.max_bytes:
                break
            try:
                os.unlink(p)
                total -= size
                self.metrics["evictions"] += 1
            except OSError:
                pass


_CACHE = None
_CACHE_LOCK = threading.Lock()


def file_cache(conf) -> FileCache:
    from ..config import FILECACHE_DIR, FILECACHE_MAX_BYTES
    global _CACHE
    with _CACHE_LOCK:
        d = conf.get(FILECACHE_DIR)
        if _CACHE is None or _CACHE.dir != d:
            _CACHE = FileCache(d, conf.get(FILECACHE_MAX_BYTES))
        return _CACHE


def cached_local_path(path: str, conf) -> str:
    """The scan-side hook: identity when the cache is off."""
    from ..config import FILECACHE_ENABLED
    if not conf.get(FILECACHE_ENABLED):
        return path
    try:
        return file_cache(conf).local_path(path)
    except OSError:
        return path                          # cache failure -> direct
