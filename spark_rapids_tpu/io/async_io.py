"""Asynchronous write path with host-memory traffic control.

TPU-native analog of the reference's io/async package
(`AsyncOutputStream.scala`, `TrafficController.scala`,
`AsyncWriterThrottlingSuite`): file encode + disk I/O run on a small
writer pool OFF the compute thread, while a global TrafficController
bounds the host bytes held by scheduled-but-unfinished writes so a slow
disk cannot pile the whole query's output into host memory.

Differences from the reference, by design: the unit of work is a whole
output FILE part (an Arrow table already on host), not a stream chunk —
the engine's writers emit part files atomically, so per-chunk ordered
streams collapse to one task per file. Throttling, deferred error
propagation, and the always-admit-one rule match the reference's
TrafficController semantics (`TrafficController.scala` throttle loop).
"""
from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, List, Optional

__all__ = ["TrafficController", "AsyncWriteQueue", "async_stats"]


class TrafficController:
    """Bounds total in-flight (scheduled, unfinished) write bytes.

    `acquire(nbytes)` blocks while admitting the task would exceed the
    budget — EXCEPT when nothing is in flight, where one task is always
    admitted so a single file larger than the budget still writes
    (reference: TrafficController's ThrottlingAppender always admits
    the first buffer)."""

    def __init__(self, max_in_flight_bytes: int, host_mgr=None):
        self.max_bytes = int(max_in_flight_bytes)
        self.host_mgr = host_mgr
        self._bytes = 0
        self._tasks = 0
        self._wait_s = 0.0
        self._cv = threading.Condition()

    def acquire(self, nbytes: int):
        import time
        t0 = time.monotonic()
        with self._cv:
            while (self._tasks > 0
                   and self._bytes + nbytes > self.max_bytes):
                self._cv.wait(timeout=0.5)
            self._bytes += nbytes
            self._tasks += 1
            self._wait_s += time.monotonic() - t0
        if self.host_mgr is not None:
            # in-flight write buffers draw from the GLOBAL host budget
            # (HostAlloc analog): pressure demotes the spill store's
            # host tier to disk; bounded wait, then soft-admit (a
            # deferred write error must never deadlock the pipeline)
            from ..memory.host import HostBudgetExceeded
            deadline = time.monotonic() + 30
            while True:
                try:
                    self.host_mgr.reserve(nbytes)
                    return
                except HostBudgetExceeded:
                    if time.monotonic() > deadline:
                        # soft-admit: charge anyway so every release
                        # pairs; later reservations see the pressure
                        self.host_mgr.force_reserve(nbytes)
                        return
                    time.sleep(0.1)

    def release(self, nbytes: int):
        if self.host_mgr is not None:
            self.host_mgr.release(nbytes)
        with self._cv:
            self._bytes -= nbytes
            self._tasks -= 1
            self._cv.notify_all()

    @property
    def in_flight_bytes(self) -> int:
        with self._cv:
            return self._bytes

    @property
    def throttle_wait_seconds(self) -> float:
        with self._cv:
            return self._wait_s


class AsyncWriteQueue:
    """Schedules file-part writes on a writer pool under a
    TrafficController budget. Submission never reorders *naming* (the
    caller assigns part numbers before submit); completion order is
    irrelevant because parts are independent files. The first failure
    is re-raised on the next submit or on drain() — the reference's
    deferred-exception contract (`AsyncOutputStream.scala` lastError)."""

    def __init__(self, controller: TrafficController, num_threads: int):
        self.controller = controller
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, num_threads),
            thread_name_prefix="tpu-async-write")
        self._futures: List = []
        self._error: Optional[BaseException] = None
        self._lock = threading.Lock()

    def _raise_if_failed(self):
        with self._lock:
            if self._error is not None:
                err = self._error
                raise RuntimeError(
                    f"async write failed: {err}") from err

    def submit(self, nbytes: int, fn: Callable, *args):
        """Blocks under the byte budget, then schedules fn(*args)."""
        self._raise_if_failed()
        self.controller.acquire(nbytes)

        def run():
            try:
                return fn(*args)
            except BaseException as e:      # noqa: BLE001 - deferred
                with self._lock:
                    if self._error is None:
                        self._error = e
                raise
            finally:
                self.controller.release(nbytes)

        self._futures.append(self._pool.submit(run))

    def drain(self) -> list:
        """Waits for every scheduled write; returns their results in
        submission order. Raises the first failure."""
        out = []
        try:
            for f in self._futures:
                try:
                    out.append(f.result())
                except Exception:       # task errors are recorded by the
                    pass                # wrapper and re-raised below;
                                        # KeyboardInterrupt etc propagate
        finally:
            self._futures = []
        self._raise_if_failed()
        return out

    def close(self):
        try:
            self.drain()
        finally:
            self._pool.shutdown(wait=True)


# -- per-conf controller (one budget per session conf, like the
# reference's one TrafficController per executor plugin). Stored ON the
# conf object: id()-keyed registries leak and can alias a recycled id
# to a stale controller with the wrong budget ---------------------------
_controllers_lock = threading.Lock()


def controller_for(conf) -> TrafficController:
    from ..config import ASYNC_WRITE_MAX_IN_FLIGHT
    with _controllers_lock:
        c = getattr(conf, "_srtpu_async_controller", None)
        if c is None:
            from ..memory.host import host_manager
            c = TrafficController(conf.get(ASYNC_WRITE_MAX_IN_FLIGHT),
                                  host_mgr=host_manager(conf))
            try:
                conf._srtpu_async_controller = c
            except AttributeError:
                pass        # conf forbids attributes: fresh per call
        return c


def async_stats(conf) -> dict:
    """Observability hook: current in-flight bytes + cumulative
    throttle wait for the conf's controller."""
    c = controller_for(conf)
    return {"inFlightBytes": c.in_flight_bytes,
            "throttleWaitSeconds": c.throttle_wait_seconds}
