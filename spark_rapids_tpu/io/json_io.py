"""JSON-lines scan (reference: GpuJsonScan.scala over cudf read_json)."""
from __future__ import annotations


def read_json_to_arrow(path: str, schema=None):
    import pyarrow.json as pj
    popts = None
    if schema is not None:
        import pyarrow as pa
        arrow_schema = schema.to_arrow() if hasattr(schema, "to_arrow") \
            else schema
        popts = pj.ParseOptions(explicit_schema=arrow_schema)
    return pj.read_json(path, parse_options=popts)
