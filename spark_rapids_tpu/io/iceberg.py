"""Apache Iceberg table format: metadata reader + snapshot-scoped scans.

Reference: the plugin's iceberg module (iceberg/, ~10k LoC:
GpuIcebergParquetScan, SparkBatchQueryScan shimming) — table metadata
JSON, Avro manifest lists + manifests (io/avro.py, no external deps),
snapshot time travel, and v2 position deletes.

Read path: metadata/v<N>.metadata.json (via version-hint.text or latest)
-> snapshot -> manifest-list.avro -> manifest.avro entries -> live data
files. Without delete files the scan stays lazy (ParquetScan over the
file list); position deletes force a host-side row filter per file
(documented fallback, the reference does this on-GPU via a gather).
"""
from __future__ import annotations

import glob
import json
import os
import re
from typing import Dict, List, Optional, Tuple

from .avro import AvroReader

__all__ = ["IcebergTable", "read_iceberg"]


def _field_type(t) -> "object":
    from ..columnar import dtypes as dt
    if isinstance(t, dict):
        k = t.get("type")
        if k == "struct":
            return dt.StructType(tuple(
                dt.StructField(f["name"], _field_type(f["type"]),
                               not f.get("required", False))
                for f in t["fields"]))
        if k == "list":
            return dt.ArrayType(_field_type(t["element"]))
        if k == "map":
            return dt.MapType(_field_type(t["key"]),
                              _field_type(t["value"]))
        raise ValueError(f"unknown iceberg type {t!r}")
    m = {"boolean": dt.BOOL, "int": dt.INT32, "long": dt.INT64,
         "float": dt.FLOAT32, "double": dt.FLOAT64, "date": dt.DATE,
         "timestamp": dt.TIMESTAMP, "timestamptz": dt.TIMESTAMP,
         "string": dt.STRING, "binary": dt.BINARY, "uuid": dt.STRING}
    if t in m:
        return m[t]
    dm = re.match(r"decimal\((\d+),\s*(\d+)\)", t)
    if dm:
        return dt.DecimalType(int(dm.group(1)), int(dm.group(2)))
    raise ValueError(f"unknown iceberg type {t!r}")


class IcebergTable:
    def __init__(self, path: str):
        self.path = path
        self.meta = self._load_metadata()

    # -- metadata ------------------------------------------------------
    def _load_metadata(self) -> Dict:
        mdir = os.path.join(self.path, "metadata")
        hint = os.path.join(mdir, "version-hint.text")
        if os.path.exists(hint):
            v = open(hint).read().strip()
            p = os.path.join(mdir, f"v{v}.metadata.json")
        else:
            cands = sorted(
                glob.glob(os.path.join(mdir, "v*.metadata.json")),
                key=lambda s: int(
                    re.search(r"v(\d+)\.metadata", s).group(1)))
            if not cands:
                cands = sorted(glob.glob(
                    os.path.join(mdir, "*.metadata.json")))
            if not cands:
                raise FileNotFoundError(
                    f"no iceberg metadata under {mdir}")
            p = cands[-1]
        with open(p) as f:
            return json.load(f)

    def schema(self):
        from ..columnar.table import Field, Schema
        ms = self.meta.get("schemas")
        if ms:
            cur = self.meta.get("current-schema-id", 0)
            sch = next(s for s in ms if s.get("schema-id") == cur)
        else:
            sch = self.meta["schema"]
        return Schema([Field(f["name"], _field_type(f["type"]),
                             not f.get("required", False))
                       for f in sch["fields"]])

    def snapshots(self) -> List[Dict]:
        return self.meta.get("snapshots", [])

    def snapshot(self, snapshot_id=None,
                 as_of_timestamp=None) -> Optional[Dict]:
        snaps = self.snapshots()
        if not snaps:
            return None
        if snapshot_id is not None:
            for s in snaps:
                if s["snapshot-id"] == snapshot_id:
                    return s
            raise KeyError(f"snapshot {snapshot_id} not found")
        if as_of_timestamp is not None:
            ok = [s for s in snaps
                  if s["timestamp-ms"] <= as_of_timestamp]
            if not ok:
                raise KeyError(
                    f"no snapshot at or before {as_of_timestamp}")
            return max(ok, key=lambda s: s["timestamp-ms"])
        cur = self.meta.get("current-snapshot-id")
        for s in snaps:
            if s["snapshot-id"] == cur:
                return s
        return snaps[-1]

    def _resolve(self, p: str) -> str:
        """Manifest paths may carry the original table location prefix."""
        if os.path.exists(p):
            return p
        loc = self.meta.get("location", "")
        if loc and p.startswith(loc):
            return os.path.join(self.path, p[len(loc):].lstrip("/"))
        # fall back: strip scheme and rebase on the local table dir
        tail = re.sub(r"^[a-z0-9+.-]+://[^/]*", "", p)
        for marker in ("/data/", "/metadata/"):
            i = tail.find(marker)
            if i >= 0:
                return os.path.join(self.path, tail[i + 1:])
        return p

    # -- files ---------------------------------------------------------
    def live_files(self, snapshot_id=None, as_of_timestamp=None
                   ) -> Tuple[List[str], List[str]]:
        """(data parquet paths, position-delete parquet paths) reachable
        from the chosen snapshot. Manifest entry status 2 = DELETED rows
        drop out; manifest content 1 = delete manifests."""
        snap = self.snapshot(snapshot_id, as_of_timestamp)
        if snap is None:
            return [], []
        mlist = self._resolve(snap["manifest-list"])
        data_files: List[str] = []
        delete_files: List[str] = []
        for man in AvroReader(mlist).records():
            mpath = self._resolve(man["manifest_path"])
            content = man.get("content", 0) or 0
            for entry in AvroReader(mpath).records():
                if entry.get("status") == 2:     # DELETED entry
                    continue
                df = entry["data_file"]
                fpath = self._resolve(df["file_path"])
                fmt = str(df.get("file_format", "PARQUET")).upper()
                if fmt != "PARQUET":
                    raise ValueError(
                        f"iceberg {fmt} data files not supported")
                fcontent = df.get("content", 0) or 0
                if fcontent == 2:
                    raise ValueError(
                        "iceberg equality deletes not supported")
                if content == 1 or fcontent == 1:
                    delete_files.append(fpath)
                else:
                    data_files.append(fpath)
        return data_files, delete_files


def read_iceberg(session, path: str, snapshot_id=None,
                 as_of_timestamp=None):
    from ..plan import logical as L
    from ..session import DataFrame
    tbl = IcebergTable(path)
    schema = tbl.schema()
    data, deletes = tbl.live_files(snapshot_id, as_of_timestamp)
    if not data:
        import pyarrow as pa
        return DataFrame(session,
                         L.InMemoryScan(schema.to_arrow().empty_table()))
    if not deletes:
        return DataFrame(session, L.ParquetScan(data, schema))
    # v2 position deletes: (file_path, pos) rows; host-filter each data
    # file (the reference gathers surviving rows on-GPU)
    import pyarrow as pa
    import pyarrow.parquet as pq
    dropped: Dict[str, set] = {}
    for dpath in deletes:
        dt_ = pq.read_table(dpath, columns=["file_path", "pos"])
        for fp, pos in zip(dt_.column(0).to_pylist(),
                           dt_.column(1).to_pylist()):
            dropped.setdefault(os.path.basename(fp), set()).add(pos)
    tables = []
    for fpath in data:
        t = pq.read_table(fpath)
        gone = dropped.get(os.path.basename(fpath))
        if gone:
            keep = [i for i in range(t.num_rows) if i not in gone]
            t = t.take(pa.array(keep, type=pa.int64()))
        tables.append(t)
    return DataFrame(session, L.InMemoryScan(pa.concat_tables(tables)))
