"""Avro Object Container File reader/writer (pure Python, no deps).

Two consumers: the `read.avro` scan format (reference: GpuAvroScan in the
avro module) and Iceberg manifest/manifest-list files (io/iceberg.py).
Implements the container spec (magic 'Obj\\x01', header metadata map,
sync-marker-delimited deflate/null blocks) and the binary encoding
(zigzag varints, length-prefixed bytes/strings, records, arrays, maps,
unions, fixed, enums) — Avro spec §object container files.
"""
from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Any, Dict, Iterator, List, Optional, Tuple

__all__ = ["AvroReader", "AvroWriter", "read_avro_to_arrow",
           "iter_avro_blocks", "write_avro"]

_MAGIC = b"Obj\x01"


# ----------------------------------------------------------------------
# binary decoding
# ----------------------------------------------------------------------
class _Decoder:
    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def read_long(self) -> int:
        shift = 0
        acc = 0
        while True:
            b = self.buf[self.pos]
            self.pos += 1
            acc |= (b & 0x7F) << shift
            if not (b & 0x80):
                break
            shift += 7
        return (acc >> 1) ^ -(acc & 1)          # zigzag

    def read_bytes(self) -> bytes:
        n = self.read_long()
        out = self.buf[self.pos:self.pos + n]
        self.pos += n
        return out

    def read_value(self, schema):
        if isinstance(schema, list):               # union
            idx = self.read_long()
            return self.read_value(schema[idx])
        t = schema["type"] if isinstance(schema, dict) else schema
        if isinstance(t, (dict, list)):            # wrapped nested type
            return self.read_value(t)
        if t == "null":
            return None
        if t == "boolean":
            b = self.buf[self.pos]
            self.pos += 1
            return bool(b)
        if t in ("int", "long"):
            return self.read_long()
        if t == "float":
            (v,) = struct.unpack_from("<f", self.buf, self.pos)
            self.pos += 4
            return v
        if t == "double":
            (v,) = struct.unpack_from("<d", self.buf, self.pos)
            self.pos += 8
            return v
        if t == "bytes":
            return self.read_bytes()
        if t == "string":
            return self.read_bytes().decode("utf-8")
        if t == "record":
            return {f["name"]: self.read_value(f["type"])
                    for f in schema["fields"]}
        if t == "array":
            out = []
            while True:
                n = self.read_long()
                if n == 0:
                    break
                if n < 0:                       # block with byte size
                    n = -n
                    self.read_long()
                for _ in range(n):
                    out.append(self.read_value(schema["items"]))
            return out
        if t == "map":
            out = {}
            while True:
                n = self.read_long()
                if n == 0:
                    break
                if n < 0:
                    n = -n
                    self.read_long()
                for _ in range(n):
                    k = self.read_bytes().decode("utf-8")
                    out[k] = self.read_value(schema["values"])
            return out
        if t == "fixed":
            n = schema["size"]
            out = self.buf[self.pos:self.pos + n]
            self.pos += n
            return out
        if t == "enum":
            return schema["symbols"][self.read_long()]
        raise ValueError(f"unsupported avro type: {t!r}")


class _Encoder:
    def __init__(self):
        self.out = bytearray()

    def write_long(self, v: int):
        v = (v << 1) ^ (v >> 63)               # zigzag (python ints)
        if v < 0:
            v &= (1 << 64) - 1
        while True:
            b = v & 0x7F
            v >>= 7
            if v:
                self.out.append(b | 0x80)
            else:
                self.out.append(b)
                break

    def write_bytes(self, b: bytes):
        self.write_long(len(b))
        self.out += b

    def write_value(self, schema, v):
        if isinstance(schema, list):           # union: null else first match
            for i, s in enumerate(schema):
                st = s["type"] if isinstance(s, dict) else s
                if (v is None) == (st == "null"):
                    self.write_long(i)
                    return self.write_value(s, v)
            raise ValueError("no union branch matched")
        t = schema["type"] if isinstance(schema, dict) else schema
        if isinstance(t, (dict, list)):        # wrapped nested type
            return self.write_value(t, v)
        if t == "null":
            return
        if t == "boolean":
            self.out.append(1 if v else 0)
            return
        if t in ("int", "long"):
            self.write_long(int(v))
            return
        if t == "float":
            self.out += struct.pack("<f", v)
            return
        if t == "double":
            self.out += struct.pack("<d", v)
            return
        if t == "bytes":
            self.write_bytes(bytes(v))
            return
        if t == "string":
            self.write_bytes(str(v).encode("utf-8"))
            return
        if t == "record":
            for f in schema["fields"]:
                self.write_value(f["type"], v.get(f["name"]))
            return
        if t == "array":
            if v:
                self.write_long(len(v))
                for item in v:
                    self.write_value(schema["items"], item)
            self.write_long(0)
            return
        if t == "map":
            if v:
                self.write_long(len(v))
                for k, val in v.items():
                    self.write_bytes(str(k).encode("utf-8"))
                    self.write_value(schema["values"], val)
            self.write_long(0)
            return
        if t == "fixed":
            assert len(v) == schema["size"]
            self.out += v
            return
        if t == "enum":
            self.write_long(schema["symbols"].index(v))
            return
        raise ValueError(f"unsupported avro type: {t!r}")


# ----------------------------------------------------------------------
# container files
# ----------------------------------------------------------------------
class AvroReader:
    def __init__(self, path: str):
        with open(path, "rb") as f:
            self.raw = f.read()
        if self.raw[:4] != _MAGIC:
            raise IOError(f"not an avro container file: {path}")
        d = _Decoder(self.raw)
        d.pos = 4
        self.meta: Dict[str, bytes] = {}
        while True:
            n = d.read_long()
            if n == 0:
                break
            if n < 0:
                n = -n
                d.read_long()
            for _ in range(n):
                k = d.read_bytes().decode("utf-8")
                self.meta[k] = d.read_bytes()
        self.schema = json.loads(self.meta["avro.schema"])
        self.codec = self.meta.get("avro.codec", b"null").decode()
        self.sync = self.raw[d.pos:d.pos + 16]
        self._body = d.pos + 16

    def blocks(self) -> Iterator[List[Any]]:
        pos = self._body
        while pos < len(self.raw):
            d = _Decoder(self.raw)
            d.pos = pos
            count = d.read_long()
            nbytes = d.read_long()
            payload = self.raw[d.pos:d.pos + nbytes]
            pos = d.pos + nbytes + 16          # skip sync marker
            if self.codec == "deflate":
                payload = zlib.decompress(payload, -15)
            elif self.codec != "null":
                raise IOError(f"unsupported avro codec {self.codec!r}")
            bd = _Decoder(payload)
            yield [bd.read_value(self.schema) for _ in range(count)]

    def records(self) -> Iterator[Any]:
        for block in self.blocks():
            yield from block


class AvroWriter:
    def __init__(self, path: str, schema: Dict, codec: str = "deflate"):
        self.path = path
        self.schema = schema
        self.codec = codec
        self.sync = os.urandom(16)
        self._f = open(path, "wb")
        self._f.write(_MAGIC)
        e = _Encoder()
        meta = {"avro.schema": json.dumps(schema).encode(),
                "avro.codec": codec.encode()}
        e.write_long(len(meta))
        for k, v in meta.items():
            e.write_bytes(k.encode())
            e.write_bytes(v)
        e.write_long(0)
        self._f.write(bytes(e.out))
        self._f.write(self.sync)

    def write_block(self, records: List[Any]):
        if not records:
            return
        e = _Encoder()
        for r in records:
            e.write_value(self.schema, r)
        payload = bytes(e.out)
        if self.codec == "deflate":
            co = zlib.compressobj(wbits=-15)
            payload = co.compress(payload) + co.flush()
        h = _Encoder()
        h.write_long(len(records))
        h.write_long(len(payload))
        self._f.write(bytes(h.out))
        self._f.write(payload)
        self._f.write(self.sync)

    def close(self):
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


def write_avro(path: str, schema: Dict, records: List[Any],
               codec: str = "deflate", block_records: int = 4096):
    with AvroWriter(path, schema, codec) as w:
        for i in range(0, len(records), block_records):
            w.write_block(records[i:i + block_records])


# ----------------------------------------------------------------------
# arrow bridge
# ----------------------------------------------------------------------
def _arrow_type(schema):
    import pyarrow as pa
    t = schema["type"] if isinstance(schema, dict) else schema
    if isinstance(schema, list):                # union: null + one type
        others = [s for s in schema
                  if (s["type"] if isinstance(s, dict) else s) != "null"]
        return _arrow_type(others[0])
    if isinstance(t, (dict, list)):
        return _arrow_type(t)
    m = {"null": pa.null(), "boolean": pa.bool_(), "int": pa.int32(),
         "long": pa.int64(), "float": pa.float32(),
         "double": pa.float64(), "bytes": pa.binary(),
         "string": pa.string()}
    if t in m:
        return m[t]
    if t == "record":
        return pa.struct([(f["name"], _arrow_type(f["type"]))
                          for f in schema["fields"]])
    if t == "array":
        return pa.list_(_arrow_type(schema["items"]))
    if t == "map":
        return pa.map_(pa.string(), _arrow_type(schema["values"]))
    if t == "fixed":
        return pa.binary(schema["size"])
    if t == "enum":
        return pa.string()
    raise ValueError(f"unsupported avro type for arrow: {t!r}")


def avro_arrow_schema(schema):
    import pyarrow as pa
    assert schema["type"] == "record", "top-level avro type must be record"
    return pa.schema([(f["name"], _arrow_type(f["type"]))
                      for f in schema["fields"]])


def iter_avro_blocks(path: str, columns=None):
    """Arrow tables, one per container block (the lazy scan unit)."""
    import pyarrow as pa
    r = AvroReader(path)
    aschema = avro_arrow_schema(r.schema)
    if columns is not None:
        aschema = pa.schema([f for f in aschema
                             if f.name in set(columns)])
    for block in r.blocks():
        if columns is not None:
            block = [{k: rec.get(k) for k in aschema.names}
                     for rec in block]
        yield pa.Table.from_pylist(block, schema=aschema)


def read_avro_to_arrow(path: str, columns=None):
    import pyarrow as pa
    tables = list(iter_avro_blocks(path, columns))
    if not tables:
        r = AvroReader(path)
        return avro_arrow_schema(r.schema).empty_table()
    return pa.concat_tables(tables)
