"""Delta deletion vectors: 64-bit roaring bitmap codec + DV files.

Reference: the plugin's Delta deletion-vector read support (delta-33x
GpuDeltaParquetFileFormat applying DVs as row filters). Format follows
the Delta spec: a DV file holds a 1-byte version then, at each DV's
offset, [4-byte BE length][bitmap payload][4-byte BE CRC32]. The
payload is a little-endian magic (1681511377) followed by a
RoaringBitmapArray: i64 bucket count, then per bucket a u32 high key
and a standard 32-bit roaring bitmap in the portable serialization
(no-run cookie 12347 written here; array, bitmap AND run containers
readable)."""
from __future__ import annotations

import os
import struct
import zlib
from typing import Iterable, List

__all__ = ["serialize_dv", "deserialize_dv", "write_dv_file",
           "read_dv_file", "load_dv_positions", "apply_dv_to_table"]

_MAGIC = 1681511377
_NO_RUN_COOKIE = 12347
_RUN_COOKIE = 12346


def _ser_rb32(values: List[int]) -> bytes:
    """Sorted u32 values -> portable 32-bit roaring bytes."""
    containers = {}
    for v in values:
        containers.setdefault(v >> 16, []).append(v & 0xFFFF)
    keys = sorted(containers)
    out = bytearray()
    out += struct.pack("<II", _NO_RUN_COOKIE, len(keys))
    for k in keys:
        out += struct.pack("<HH", k, len(containers[k]) - 1)
    # offsets section (present for the no-run cookie)
    off = 8 + 4 * len(keys) + 4 * len(keys)
    offs = []
    for k in keys:
        offs.append(off)
        card = len(containers[k])
        off += (2 * card if card <= 4096 else 8192)
    for o in offs:
        out += struct.pack("<I", o)
    for k in keys:
        vals = sorted(containers[k])
        if len(vals) <= 4096:
            out += struct.pack(f"<{len(vals)}H", *vals)
        else:
            bits = bytearray(8192)
            for v in vals:
                bits[v >> 3] |= 1 << (v & 7)
            out += bits
    return bytes(out)


def _de_rb32(buf: bytes, base: int, out: List[int]):
    cookie = struct.unpack_from("<I", buf, base)[0]
    pos = base
    if (cookie & 0xFFFF) == _RUN_COOKIE:
        n = (cookie >> 16) + 1
        pos += 4
        runbits = buf[pos:pos + (n + 7) // 8]
        pos += (n + 7) // 8
        has_run = [bool(runbits[i >> 3] & (1 << (i & 7)))
                   for i in range(n)]
        has_offsets = False
    elif cookie == _NO_RUN_COOKIE:
        n = struct.unpack_from("<I", buf, base + 4)[0]
        pos += 8
        has_run = [False] * n
        has_offsets = True
    else:
        raise ValueError(f"bad roaring cookie {cookie}")
    heads = []
    for i in range(n):
        k, cm1 = struct.unpack_from("<HH", buf, pos)
        pos += 4
        heads.append((k, cm1 + 1))
    if has_offsets or n >= 4:
        pos += 4 * n    # offsets section (run cookie: present at n>=4)
    for i, (k, card) in enumerate(heads):
        hi = k << 16
        if has_run[i]:
            nruns = struct.unpack_from("<H", buf, pos)[0]
            pos += 2
            for _ in range(nruns):
                start, length = struct.unpack_from("<HH", buf, pos)
                pos += 4
                out.extend(hi | v for v in range(start,
                                                 start + length + 1))
        elif card <= 4096:
            vals = struct.unpack_from(f"<{card}H", buf, pos)
            pos += 2 * card
            out.extend(hi | v for v in vals)
        else:
            bits = buf[pos:pos + 8192]
            pos += 8192
            for byte_i, b in enumerate(bits):
                while b:
                    low = b & (-b)
                    out.append(hi | (byte_i << 3 | low.bit_length() - 1))
                    b ^= low
    return pos


def serialize_dv(positions: Iterable[int]) -> bytes:
    """Sorted 64-bit row positions -> magic + RoaringBitmapArray."""
    buckets = {}
    for p in sorted(set(positions)):
        buckets.setdefault(p >> 32, []).append(p & 0xFFFFFFFF)
    out = bytearray(struct.pack("<I", _MAGIC))
    out += struct.pack("<q", len(buckets))
    for hk in sorted(buckets):
        out += struct.pack("<I", hk)
        out += _ser_rb32(buckets[hk])
    return bytes(out)


def deserialize_dv(buf: bytes) -> List[int]:
    magic = struct.unpack_from("<I", buf, 0)[0]
    if magic != _MAGIC:
        raise ValueError(f"bad DV magic {magic}")
    nb = struct.unpack_from("<q", buf, 4)[0]
    pos = 12
    out: List[int] = []
    for _ in range(nb):
        hk = struct.unpack_from("<I", buf, pos)[0]
        pos += 4
        sub: List[int] = []
        pos = _de_rb32(buf, pos, sub)
        out.extend((hk << 32) | v for v in sub)
    return out


def write_dv_file(path: str, positions: Iterable[int]) -> dict:
    """One-DV file: version byte + [len BE][payload][crc BE]. Returns
    the descriptor fields (offset, sizeInBytes, cardinality)."""
    plist = sorted(set(positions))         # materialize ONCE (iterables)
    payload = serialize_dv(plist)
    with open(path, "wb") as f:
        f.write(b"\x01")
        f.write(struct.pack(">i", len(payload)))
        f.write(payload)
        f.write(struct.pack(">I", zlib.crc32(payload)))
    return {"offset": 1, "sizeInBytes": len(payload),
            "cardinality": len(plist)}


def read_dv_file(path: str, offset: int = 1,
                 size: int = None) -> List[int]:
    with open(path, "rb") as f:
        raw = f.read()
    n = struct.unpack_from(">i", raw, offset)[0]
    if size is not None and n != size:
        raise IOError(
            f"DV length mismatch in {path}: stored {n}, "
            f"descriptor sizeInBytes {size}")
    payload = raw[offset + 4:offset + 4 + n]
    crc = struct.unpack_from(">I", raw, offset + 4 + n)[0]
    if crc != zlib.crc32(payload):
        raise IOError(f"DV checksum mismatch in {path}")
    return deserialize_dv(payload)


def load_dv_positions(table_root: str, descriptor: dict) -> List[int]:
    """Dead row positions from an add action's deletionVector
    descriptor. storageType 'p' carries an absolute path per the Delta
    protocol; tolerate legacy table-relative names too (tables written
    by earlier versions of this engine)."""
    p = descriptor["pathOrInlineDv"]
    if not os.path.isabs(p):
        p = os.path.join(table_root, p)
    return read_dv_file(
        p, descriptor.get("offset", 1), descriptor.get("sizeInBytes"))


def apply_dv_to_table(t, dead) -> "object":
    """Drop dead row positions from an arrow table — vectorized mask,
    no per-row Python loop."""
    import numpy as np
    import pyarrow as pa
    if not dead:
        return t
    mask = np.ones(t.num_rows, bool)
    idx = np.fromiter((d for d in dead if d < t.num_rows), np.int64)
    mask[idx] = False
    return t.filter(pa.array(mask))
