"""File-format writer framework: parquet/ORC/CSV/JSON/hive-text outputs
with Spark-compatible layout (part files, _SUCCESS marker) and dynamic
partitioning.

Reference: GpuFileFormatWriter + GpuDynamicPartitionDataSingleWriter
(ColumnarOutputWriter.scala, GpuFileFormatDataWriter.scala) — the
reference splits each batch by the partition-key tuple and routes slices
to per-directory writers; here the split happens on the host arrow table
after the device compute (encode/compress is host work in this runtime),
one output file per (physical partition, partition-dir).
"""
from __future__ import annotations

import os
import shutil
import uuid
from typing import Dict, List, Optional, Sequence

__all__ = ["DataFrameWriter", "WriteStats"]


class WriteStats:
    """numFiles/numOutputRows/numOutputBytes (the reference's
    BasicColumnarWriteJobStatsTracker metrics)."""

    def __init__(self):
        self.num_files = 0
        self.num_rows = 0
        self.num_bytes = 0
        self.partitions: List[str] = []

    def __repr__(self):
        return (f"WriteStats(files={self.num_files}, rows={self.num_rows},"
                f" bytes={self.num_bytes},"
                f" partitions={len(self.partitions)})")


def _partition_dir(names: Sequence[str], values) -> str:
    import urllib.parse
    parts = []
    for n, v in zip(names, values):
        sv = "__HIVE_DEFAULT_PARTITION__" if v is None else \
            urllib.parse.quote(str(v), safe="")
        parts.append(f"{n}={sv}")
    return "/".join(parts)


class DataFrameWriter:
    """`df.write` builder (pyspark DataFrameWriter surface)."""

    def __init__(self, df):
        self._df = df
        self._mode = "errorifexists"
        self._partition_by: List[str] = []
        self._options: Dict[str, str] = {}

    def mode(self, m: str) -> "DataFrameWriter":
        assert m in ("overwrite", "append", "errorifexists", "ignore")
        self._mode = m
        return self

    def partitionBy(self, *cols: str) -> "DataFrameWriter":
        self._partition_by = list(cols)
        return self

    def option(self, k: str, v) -> "DataFrameWriter":
        self._options[k] = v
        return self

    # ---- formats -----------------------------------------------------
    def parquet(self, path: str, compression: str = "snappy"):
        import pyarrow.parquet as pq

        def wfn(at, fname):
            pq.write_table(at, fname, compression=compression)

        return self._write(path, wfn, "parquet")

    def orc(self, path: str, compression: str = "zstd"):
        import pyarrow.orc as orc

        def wfn(at, fname):
            orc.write_table(at, fname, compression=compression)

        return self._write(path, wfn, "orc")

    def csv(self, path: str, header: bool = True, delimiter: str = ","):
        import pyarrow.csv as pc

        def wfn(at, fname):
            pc.write_csv(at, fname, write_options=pc.WriteOptions(
                include_header=header, delimiter=delimiter))

        return self._write(path, wfn, "csv")

    def json(self, path: str):
        import json as _json

        def wfn(at, fname):
            with open(fname, "w") as f:
                for row in at.to_pylist():
                    f.write(_json.dumps(row, default=str) + "\n")

        return self._write(path, wfn, "json")

    def hive_text(self, path: str, field_delim: str = "\x01",
                  null_marker: str = "\\N"):
        """Hive LazySimpleSerDe text layout (reference: hive text write
        via GpuHiveTextFileFormat)."""

        def wfn(at, fname):
            cols = [at.column(i).to_pylist()
                    for i in range(at.num_columns)]
            with open(fname, "w") as f:
                for row in zip(*cols) if cols else []:
                    f.write(field_delim.join(
                        null_marker if v is None else str(v)
                        for v in row) + "\n")

        return self._write(path, wfn, "txt")

    def iceberg(self, path: str):
        from .iceberg_write import write_iceberg
        if self._partition_by:
            raise NotImplementedError(
                "partitionBy is not supported for iceberg writes yet")
        return write_iceberg(self._df, path, mode=self._mode)

    def delta(self, path: str):
        from .delta import write_delta
        exists = os.path.exists(os.path.join(path, "_delta_log"))
        if exists and self._mode == "errorifexists":
            raise FileExistsError(path)
        if exists and self._mode == "ignore":
            return 0
        mode = "append" if self._mode == "append" else "overwrite"
        return write_delta(self._df, path, mode=mode)

    # ---- core --------------------------------------------------------
    def _write(self, path: str, write_fn, ext: str) -> WriteStats:
        import pyarrow as pa
        if os.path.exists(path) and os.listdir(path):
            if self._mode == "errorifexists":
                raise FileExistsError(path)
            if self._mode == "ignore":
                return WriteStats()
            if self._mode == "overwrite":
                shutil.rmtree(path, ignore_errors=True)
        os.makedirs(path, exist_ok=True)

        stats = WriteStats()
        job = uuid.uuid4().hex[:8]    # append-safe: unique per write job
        pcols = self._partition_by
        out_names = [n for n in self._df.schema.names if n not in pcols]
        if pcols:
            missing = [c for c in pcols if c not in self._df.schema.names]
            if missing:
                raise KeyError(f"partition columns not in schema: "
                               f"{missing}")

        # async path: encode + disk I/O on the writer pool, throttled by
        # the session's TrafficController; the compute loop keeps
        # producing batches (reference: io/async AsyncOutputStream)
        from ..config import ASYNC_WRITE_ENABLED, ASYNC_WRITE_THREADS
        conf = self._df._session.conf
        queue = None
        if conf.get(ASYNC_WRITE_ENABLED):
            from .async_io import AsyncWriteQueue, controller_for
            queue = AsyncWriteQueue(controller_for(conf),
                                    conf.get(ASYNC_WRITE_THREADS))

        def emit(tbl, fname):
            def task(t=tbl, f=fname):
                write_fn(t, f)
                return t.num_rows, os.path.getsize(f)
            if queue is None:
                nrows, nbytes = task()
                stats.num_rows += nrows
                stats.num_bytes += nbytes
                stats.num_files += 1
            else:
                # num_files counted from drain() results: a part whose
                # async write later fails must not be counted
                queue.submit(tbl.nbytes, task)

        try:
            seq = 0
            for at in self._df._iter_partition_tables():
                if at.num_rows == 0:
                    continue
                if not pcols:
                    emit(at, os.path.join(path,
                                          f"part-{seq:05d}-{job}.{ext}"))
                    seq += 1
                    continue
                # dynamic partitioning: split the batch by the
                # partition-key tuple, one directory per distinct tuple
                # (GpuDynamicPartitionDataSingleWriter)
                keys = [at.column(c).to_pylist() for c in pcols]
                groups: Dict[tuple, List[int]] = {}
                for i, tup in enumerate(zip(*keys)):
                    groups.setdefault(tup, []).append(i)
                body = at.select(out_names)
                for tup, idxs in groups.items():
                    sub = body.take(pa.array(idxs, type=pa.int64()))
                    pdir = _partition_dir(pcols, tup)
                    full = os.path.join(path, pdir)
                    os.makedirs(full, exist_ok=True)
                    if pdir not in stats.partitions:
                        stats.partitions.append(pdir)
                    emit(sub, os.path.join(
                        full, f"part-{seq:05d}-{job}.{ext}"))
                    seq += 1
            if queue is not None:
                for nrows, nbytes in queue.drain():
                    stats.num_rows += nrows
                    stats.num_bytes += nbytes
                    stats.num_files += 1
        except BaseException:
            # close() re-raises deferred write errors via drain(); an
            # exception already unwinding here must not be replaced by it
            if queue is not None:
                try:
                    queue.close()
                except Exception:
                    pass
            raise
        else:
            if queue is not None:
                queue.close()
        if stats.num_files == 0:
            # empty result still records the schema
            empty = self._df.schema.to_arrow().empty_table() \
                if not pcols else \
                pa.schema([(n, self._df.schema.to_arrow().field(n).type)
                           for n in out_names]).empty_table()
            fname = os.path.join(path, f"part-00000-{job}.{ext}")
            write_fn(empty, fname)
            stats.num_files = 1
        open(os.path.join(path, "_SUCCESS"), "w").close()
        try:
            from ..runtime import result_cache
            result_cache.invalidate_prefix(path)
        except Exception:
            pass
        return stats
