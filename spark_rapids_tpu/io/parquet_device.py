"""Device Parquet decode orchestration (slice 2).

Reference: GpuParquetScan.scala:3364 (Table.readParquet decodes column
chunks on the accelerator) and the COALESCING reader (:2523) that
stitches chunks into ONE buffer for ONE device decode. TPU shape of the
same idea:

  host:   read RAW column-chunk bytes into pinned staging buffers,
          parse page headers + RLE run tables (O(pages + runs), no
          value bytes touched), and — for snappy chunks — decompress
          pages IN PARALLEL on the multithreaded prefetch pool, off
          the compute thread
  device: ONE uint8 upload per chunk; PLAIN lane assembly, hybrid
          run expansion (def levels, dictionary indices), dictionary
          gather, BYTE_ARRAY offset extraction via pointer doubling,
          def-level->validity + packed-value scatter — all jitted with
          shapes static per (pages, runs, capacity) bucket.

Slice-2 eligibility (everything else falls back to the pyarrow host
path, per column, with a reason counter): UNCOMPRESSED or SNAPPY
chunks; flat INT32/INT64/FLOAT/DOUBLE/BYTE_ARRAY physical types; PLAIN
or RLE_DICTIONARY/PLAIN_DICTIONARY data pages; v1 (RLE def levels) and
v2 (uncompressed-levels layout) data pages. `sql.parquet.deviceSnappy`
additionally moves qualifying pages' snappy decompression itself onto
the device (ops/parquet_decode.snappy_expand).
"""
from __future__ import annotations

from dataclasses import replace
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import parquet_thrift as pt

__all__ = ["chunk_device_plan", "decode_chunk_device",
           "eligible_chunks", "fallback_reasons", "DeviceChunk"]

_PHYS_WIDTH = {"INT32": 4, "INT64": 8, "FLOAT": 4, "DOUBLE": 8}
_PHYS_NP = {"INT32": "int32", "INT64": "int64",
            "FLOAT": "float32", "DOUBLE": "float64"}
_OK_PHYS = set(_PHYS_WIDTH) | {"BYTE_ARRAY"}
_OK_CODECS = {"UNCOMPRESSED", "SNAPPY"}

_OK_ENCODINGS = {"PLAIN", "RLE", "PLAIN_DICTIONARY", "RLE_DICTIONARY",
                 "BIT_PACKED"}

# dictionary pages past this entry count skip the host extent walk
_MAX_DICT_VALUES = 1 << 20
# string output buffers past this bound fall back (pathological blowup)
_MAX_STRING_BYTES = 1 << 30


class DeviceChunk:
    """Host-parsed metadata for one device-decodable column chunk."""

    def __init__(self, name: str, physical: str, nullable: bool,
                 raw, pages: List[pt.PageInfo], num_values: int,
                 staging=None, dev_pages=None):
        self.name = name
        self.physical = physical
        self.nullable = nullable
        self.raw = raw                # bytes | memoryview (live prefix)
        self.pages = pages
        self.num_values = num_values
        # staging-pool leases backing `raw`; released via close()
        self.staging = staging or []
        # device-snappy work: (slot_off, comp np.uint8, el_dst, el_lit,
        # el_src, n_el, out_len) per page decompressed ON device
        self.dev_pages = dev_pages or []
        self.uploaded = None          # device uint8 chunk (set by decode)

    def close(self, sync: bool = False):
        """Return staging buffers to the pool. With sync=True, joins the
        upload first — mandatory on real accelerators where the H2D
        copy may still be reading the host buffer (the prefetch worker
        pays this wait, not the compute thread)."""
        if sync and self.uploaded is not None:
            try:
                import jax
                # tpulint: allow[block-sync] prefetch-thread join: pool
                jax.block_until_ready(self.uploaded)  # reuse must not
                # race the in-flight H2D copy (never the compute thread)
            except Exception:
                pass
        for b in self.staging:
            b.release()
        self.staging = []


def _classify(col, name: str) -> Optional[Tuple[str, str]]:
    """(category, detail) why this chunk cannot device-decode, or None
    when it is eligible. Categories are the fallback-counter keys:
    codec / type / encoding / nested."""
    if "." in name:
        return ("nested", "nested column (repetition levels)")
    if col.compression not in _OK_CODECS:
        return ("codec", f"codec {col.compression}")
    if col.physical_type not in _OK_PHYS:
        return ("type", f"physical type {col.physical_type}")
    bad = set(col.encodings) - _OK_ENCODINGS
    if bad:
        return ("encoding", f"encoding {'/'.join(sorted(bad))}")
    return None


def eligible_chunks(pf, rg: int, columns: List[str]) -> Dict[str, int]:
    """Map column name -> column index for chunks the device path can
    decode in row group `rg`."""
    md = pf.metadata
    out = {}
    names = {}
    for ci in range(md.num_columns):
        col = md.row_group(rg).column(ci)
        names[".".join(col.path_in_schema.split("."))] = ci
    for name in columns:
        ci = names.get(name)
        if ci is None:
            continue
        col = md.row_group(rg).column(ci)
        if _classify(col, name) is None:
            out[name] = ci
    return out


def fallback_reasons(pf, rg: int,
                     columns: List[str]) -> Dict[str, Tuple[str, str]]:
    """Per-column (category, detail) for the columns of `columns` that
    CANNOT device-decode in row group `rg` (the why-did-this-scan-fall-
    back answer, fed to metrics + the plan auditor)."""
    md = pf.metadata
    names = {}
    for ci in range(md.num_columns):
        col = md.row_group(rg).column(ci)
        names[".".join(col.path_in_schema.split("."))] = ci
    out = {}
    for name in columns:
        ci = names.get(name)
        if ci is None:
            continue
        got = _classify(md.row_group(rg).column(ci), name)
        if got is not None:
            out[name] = got
    return out


# ----------------------------------------------------------------------
# snappy: host tag parse (device kernel input) + pool decompression
# ----------------------------------------------------------------------
def _parse_snappy_elements(buf, start: int, end: int):
    """Walk one snappy-compressed span's tag stream into an element
    table for ops/parquet_decode.snappy_expand: O(elements) host work,
    literal bytes untouched. Returns (out_len, dst[], is_lit[], src[])
    where src is a buffer offset for literals and a back-offset for
    copies. Raises ThriftError on a malformed stream."""
    p = start
    # preamble: varint uncompressed length
    out_len = 0
    shift = 0
    while True:
        if p >= end:
            raise pt.ThriftError("snappy preamble past end")
        b = buf[p]
        p += 1
        out_len |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
        if shift > 35:
            raise pt.ThriftError("snappy preamble varint too long")
    dst_l: List[int] = []
    lit_l: List[int] = []
    src_l: List[int] = []
    dst = 0
    while dst < out_len:
        if p >= end:
            raise pt.ThriftError("snappy tag past end")
        tag = buf[p]
        t = tag & 3
        if t == 0:                          # literal
            ln = (tag >> 2) + 1
            p += 1
            if ln > 60:
                nb = ln - 60
                if p + nb > end:
                    raise pt.ThriftError("snappy literal len past end")
                ln = 0
                for j in range(nb):
                    ln |= buf[p + j] << (8 * j)
                ln += 1
                p += nb
            if p + ln > end:
                raise pt.ThriftError("snappy literal bytes past end")
            dst_l.append(dst)
            lit_l.append(1)
            src_l.append(p - start)    # relative to the compressed span
            p += ln
        else:                               # copy
            if t == 1:
                if p + 2 > end:
                    raise pt.ThriftError("snappy copy1 past end")
                ln = ((tag >> 2) & 7) + 4
                off = ((tag >> 5) << 8) | buf[p + 1]
                p += 2
            elif t == 2:
                if p + 3 > end:
                    raise pt.ThriftError("snappy copy2 past end")
                ln = (tag >> 2) + 1
                off = buf[p + 1] | (buf[p + 2] << 8)
                p += 3
            else:
                if p + 5 > end:
                    raise pt.ThriftError("snappy copy4 past end")
                ln = (tag >> 2) + 1
                off = (buf[p + 1] | (buf[p + 2] << 8)
                       | (buf[p + 3] << 16) | (buf[p + 4] << 24))
                p += 5
            if off <= 0 or off > dst:
                raise pt.ThriftError("snappy copy offset out of range")
            dst_l.append(dst)
            lit_l.append(0)
            src_l.append(off)
            ln = min(ln, out_len - dst)
        dst += ln
    return out_len, dst_l, lit_l, src_l


def _snappy_codec():
    import pyarrow as pa
    return pa.Codec("snappy")


def _decompress_page(codec, src, out, out_off: int, expect: int):
    """Decompress one page payload into `out[out_off:out_off+expect]`."""
    buf = codec.decompress(bytes(src), expect)
    got = np.frombuffer(buf, np.uint8, len(buf))
    if len(got) != expect:
        raise pt.ThriftError(
            f"snappy page decompressed to {len(got)}, expected {expect}")
    out[out_off:out_off + expect] = got


def chunk_device_plan(pf, path: str, rg: int, ci: int,
                      name: str, nullable: bool, pool=None,
                      decomp_pool=None, device_snappy: bool = False,
                      metrics=None) -> Optional[DeviceChunk]:
    """Read raw bytes + parse page metadata for one column chunk.
    Snappy chunks come back REASSEMBLED: page payloads decompressed
    (in parallel on `decomp_pool`, or host-inline) into one contiguous
    staging buffer whose PageInfo offsets mirror the uncompressed
    layout — the downstream device decode is codec-blind. With
    `device_snappy`, qualifying pages instead carry a host-parsed
    element table and decompress on device."""
    import time as _time

    col = pf.metadata.row_group(rg).column(ci)
    start = col.data_page_offset
    if col.has_dictionary_page and col.dictionary_page_offset is not None:
        start = min(start, col.dictionary_page_offset)
    size = col.total_compressed_size
    staging = []
    if pool is not None:
        lease = pool.acquire(size)
        staging.append(lease)
        with open(path, "rb") as f:
            f.seek(start)
            if f.readinto(lease.view()) != size:
                for b in staging:
                    b.release()
                return None
        raw = memoryview(lease.array)[:size]
    else:
        with open(path, "rb") as f:
            f.seek(start)
            raw = f.read(size)
    try:
        pages = pt.parse_page_headers(raw, col.num_values)
    except pt.ThriftError:
        for b in staging:
            b.release()
        return None
    for p in pages:
        ok = True
        if p.page_type == pt.DATA_PAGE:
            if p.encoding not in (pt.PLAIN, pt.PLAIN_DICTIONARY,
                                  pt.RLE_DICTIONARY):
                ok = False
            if nullable and p.def_level_encoding != pt.RLE:
                ok = False
        elif p.page_type == pt.DATA_PAGE_V2:
            if p.encoding not in (pt.PLAIN, pt.PLAIN_DICTIONARY,
                                  pt.RLE_DICTIONARY):
                ok = False
            if p.rep_levels_byte_length > 0:
                ok = False                 # flat columns only
        if not ok:
            for b in staging:
                b.release()
            return None

    dev_pages = []
    if col.compression == "SNAPPY":
        t0 = _time.perf_counter()
        total_out = sum(max(p.uncompressed_size, 0) for p in pages)
        if pool is not None:
            out_lease = pool.acquire(total_out)
            staging.append(out_lease)
            out = out_lease.array
        else:
            out = np.zeros(max(total_out, 1), np.uint8)
        new_pages = []
        tasks = []                    # (src span, out_off, expect)
        dst = 0
        for p in pages:
            usize = max(p.uncompressed_size, 0)
            np_page = replace(p, data_offset=dst, compressed_size=usize)
            new_pages.append(np_page)
            off, end = p.data_offset, p.data_offset + p.compressed_size
            if p.page_type == pt.DATA_PAGE_V2:
                # v2 keeps levels UNCOMPRESSED ahead of the data section
                lvl = max(p.rep_levels_byte_length, 0) \
                    + max(p.def_levels_byte_length, 0)
                lvl = min(lvl, min(p.compressed_size, usize))
                out[dst:dst + lvl] = np.frombuffer(
                    raw[off:off + lvl], np.uint8)
                if p.data_compressed:
                    tasks.append((raw[off + lvl:end], dst + lvl,
                                  usize - lvl))
                else:
                    out[dst + lvl:dst + usize] = np.frombuffer(
                        raw[off + lvl:end], np.uint8)
            elif (device_snappy and p.page_type == pt.DATA_PAGE
                  and p.encoding == pt.PLAIN and not nullable):
                try:
                    out_len, dl, ll, sl = _parse_snappy_elements(
                        raw, off, end)
                except pt.ThriftError:
                    tasks.append((raw[off:end], dst, usize))
                else:
                    if out_len != usize:
                        tasks.append((raw[off:end], dst, usize))
                    else:
                        comp = np.frombuffer(raw[off:end], np.uint8)
                        # tpulint: allow[host-sync] python lists, no
                        el = [np.asarray(x, np.int32)  # device data
                              for x in (dl, ll, sl)]
                        dev_pages.append(
                            (dst, comp, el[0], el[1], el[2], len(dl),
                             out_len))
            else:
                tasks.append((raw[off:end], dst, usize))
            dst += usize
        codec = _snappy_codec()
        try:
            if decomp_pool is not None and len(tasks) > 1:
                # per-page, parallel across pages: pyarrow's snappy
                # releases the GIL, so the prefetch pool really fans out
                list(decomp_pool.map(
                    lambda t: _decompress_page(codec, t[0], out, t[1],
                                               t[2]), tasks))
            else:
                for src, ooff, expect in tasks:
                    _decompress_page(codec, src, out, ooff, expect)
        except Exception:
            for b in staging:
                b.release()
            return None
        if metrics is not None:
            metrics.add("decompressBusySecs",
                        _time.perf_counter() - t0)
            metrics.add("decompressedBytes", total_out)
        raw = memoryview(out)[:total_out]
        pages = new_pages
    return DeviceChunk(name, col.physical_type, nullable, raw, pages,
                       col.num_values, staging=staging,
                       dev_pages=dev_pages)


def _parse_sections(c: DeviceChunk):
    """Split every data page into (def-level runs, value section).
    Returns (def_runs, plain_pages, dict_pages, dict_page) where
    def_runs: list[pt.RleRun] with ABSOLUTE out_start,
    plain_pages: [(payload_off, first_row)],
    dict_pages:  [(bit_width, runs, first_row, num_values)],
    dict_page:   PageInfo | None. Handles v1 (length-prefixed RLE def
    levels) and v2 (separate uncompressed level sections) layouts."""
    def_runs: List[pt.RleRun] = []
    plain_pages: List[Tuple[int, int]] = []
    dict_idx_pages: List[Tuple[int, List[pt.RleRun], int, int]] = []
    dict_page = None
    row = 0
    for p in c.pages:
        if p.page_type == pt.DICTIONARY_PAGE:
            dict_page = p
            continue
        if p.page_type not in (pt.DATA_PAGE, pt.DATA_PAGE_V2):
            continue
        off = p.data_offset
        end = p.data_offset + p.compressed_size
        if p.page_type == pt.DATA_PAGE_V2:
            lvl = max(p.rep_levels_byte_length, 0) \
                + max(p.def_levels_byte_length, 0)
            if c.nullable:
                if p.def_levels_byte_length > 0:
                    runs = pt.parse_hybrid_runs(
                        c.raw, off + max(p.rep_levels_byte_length, 0),
                        off + lvl, p.num_values, 1)
                    for r in runs:
                        def_runs.append(pt.RleRun(
                            row + r.out_start, r.count, r.is_packed,
                            r.value, r.byte_offset))
                else:
                    # no level section: every value present
                    def_runs.append(pt.RleRun(row, p.num_values, False,
                                              value=1))
            off += lvl
        elif c.nullable:
            # v1: [int32 LE length][RLE/bit-packed hybrid, bit width 1]
            ln = int.from_bytes(bytes(c.raw[off:off + 4]), "little")
            runs = pt.parse_hybrid_runs(c.raw, off + 4, off + 4 + ln,
                                        p.num_values, 1)
            for r in runs:
                def_runs.append(pt.RleRun(
                    row + r.out_start, r.count, r.is_packed, r.value,
                    r.byte_offset))
            off += 4 + ln
        if p.encoding == pt.PLAIN:
            plain_pages.append((off, row))
        else:                                  # dictionary indices
            bw = c.raw[off] if off < len(c.raw) else 255
            if bw > 32:
                # spec max is 32; a corrupt/hostile byte here must route
                # to the host fallback, not overflow the run tables
                raise pt.ThriftError(f"dict index bit width {bw}")
            runs = pt.parse_hybrid_runs(c.raw, off + 1, end,
                                        p.num_values, bw)
            # index runs address the PACKED (non-null) value stream;
            # out_start is patched on device via per-page valid counts
            dict_idx_pages.append((bw, runs, row, p.num_values))
        row += p.num_values
    return def_runs, plain_pages, dict_idx_pages, dict_page


def _chunk_device_bytes(c: DeviceChunk, metrics=None):
    """Upload the (reassembled) chunk bytes; patch in device-snappy
    pages. The upload keeps the staging buffer's pow2 capacity so
    shapes repeat across chunks."""
    import time as _time

    import jax.numpy as jnp

    from ..ops import parquet_decode as pd

    if c.staging:
        src = c.staging[-1].array       # full pow2 buffer: stable shape
    elif isinstance(c.raw, (bytes, bytearray, memoryview)):
        src = np.frombuffer(c.raw, np.uint8)
    else:
        src = c.raw
    t0 = _time.perf_counter()
    chunk_dev = jnp.asarray(src)
    if metrics is not None:
        # dispatch-time on async backends (docs/observability.md)
        metrics.add("uploadSecs", _time.perf_counter() - t0)
        metrics.add("uploadedBytes", int(src.nbytes))
    for (slot, comp, dl, ll, sl, n_el, out_len) in c.dev_pages:
        E = pd.bucket_len(max(n_el, 1))
        dst = np.full(E, out_len, np.int32)
        lit = np.zeros(E, np.int32)
        srcs = np.zeros(E, np.int32)
        dst[:n_el], lit[:n_el], srcs[:n_el] = dl, ll, sl
        cap_out = pd.bucket_len(max(out_len, 1), floor=128)
        kbits = max(1, (cap_out - 1).bit_length())
        page = pd.snappy_expand(
            jnp.asarray(comp), jnp.asarray(dst), jnp.asarray(lit),
            jnp.asarray(srcs), n_el, out_len, kbits, cap_out)
        chunk_dev = chunk_dev.at[slot:slot + out_len].set(
            page[:out_len])
    c.uploaded = chunk_dev
    return chunk_dev


def _dict_indices(c: DeviceChunk, valid, dict_idx_pages, cap: int):
    """Expand the per-page RLE/bit-packed index runs into ONE packed
    index stream (int32[pcap]): run out_starts are page-relative to the
    packed stream, rebased by per-page valid counts on device."""
    import jax.numpy as jnp

    from ..ops import parquet_decode as pd

    n = c.num_values
    bws = {bw for bw, _, _, _ in dict_idx_pages}
    if len(bws) != 1:
        return None                   # one static bit width per chunk
    bw = bws.pop()
    allruns: List[pt.RleRun] = []
    run_page_row = []
    for _bw, runs, row, _nv in dict_idx_pages:
        for r in runs:
            allruns.append(r)
            run_page_row.append(row)
    if not allruns:
        return None
    vcnt = jnp.cumsum(valid.astype(jnp.int32))
    R = pd.bucket_len(len(allruns))
    rs = np.zeros(R, np.int32)
    rc = np.zeros(R, np.int32)
    rp = np.zeros(R, np.int32)
    rv = np.zeros(R, np.int32)
    rb = np.zeros(R, np.int32)
    prow = np.zeros(R, np.int32)
    for i, r in enumerate(allruns):
        rs[i], rc[i], rp[i] = r.out_start, r.count, int(r.is_packed)
        rv[i], rb[i] = r.value, r.byte_offset
        prow[i] = run_page_row[i]
    prow_dev = jnp.asarray(prow)
    page_val_base = jnp.where(
        prow_dev > 0,
        vcnt[jnp.clip(prow_dev - 1, 0, cap - 1)], 0)
    rs_abs = jnp.asarray(rs) + page_val_base
    # pad rows past the live runs to the sentinel (total packed)
    total_packed = vcnt[jnp.clip(jnp.asarray(n - 1), 0, cap - 1)]
    live = jnp.arange(R) < len(allruns)
    rs_abs = jnp.where(live, rs_abs, total_packed).astype(jnp.int32)
    chunk_dev = c.uploaded
    idx = pd.expand_hybrid(
        chunk_dev, rs_abs, jnp.asarray(rc), jnp.asarray(rp),
        jnp.asarray(rv), jnp.asarray(rb), len(allruns), n, bw,
        pd.bucket_len(max(n, 1), floor=128))
    return idx


def _walk_byte_array_extents(buf, off: int, end: int, n: int):
    """Host walk of a PLAIN BYTE_ARRAY section's [len][bytes] chain
    (dictionary pages only — n is small). Returns (starts, lens)
    int32[n] or raises ThriftError."""
    starts = np.zeros(n, np.int32)
    lens = np.zeros(n, np.int32)
    p = off
    for i in range(n):
        if p + 4 > end:
            raise pt.ThriftError("byte-array extent walk past end")
        ln = int.from_bytes(bytes(buf[p:p + 4]), "little")
        if ln < 0 or p + 4 + ln > end:
            raise pt.ThriftError("byte-array length out of range")
        starts[i] = p + 4
        lens[i] = ln
        p += 4 + ln
    return starts, lens


def _decode_strings(c: DeviceChunk, valid, cap: int, plain_pages,
                    dict_idx_pages, dict_page):
    """BYTE_ARRAY decode: per-row extents (length extraction) ->
    exclusive prefix-sum offsets -> byte gather into the chunked
    string layout. Returns (data uint8[dcap], validity, offsets) or
    None (fallback)."""
    import jax.numpy as jnp

    from ..ops import parquet_decode as pd

    n = c.num_values
    if plain_pages:
        payload_total = sum(
            p.compressed_size for p in c.pages
            if p.page_type in (pt.DATA_PAGE, pt.DATA_PAGE_V2))
        if payload_total > _MAX_STRING_BYTES:
            return None
        dcap = pd.bucket_len(max(payload_total, 1), floor=128)
        P = pd.bucket_len(len(plain_pages))
        po = np.zeros(P, np.int32)
        pr = np.full(P, n, np.int32)
        maxv = 1
        for i, (off, row) in enumerate(plain_pages):
            po[i], pr[i] = off, row
        for p in c.pages:
            if p.page_type in (pt.DATA_PAGE, pt.DATA_PAGE_V2):
                maxv = max(maxv, p.num_values)
        chunk_dev = c.uploaded
        if c.nullable:
            vcnt = jnp.cumsum(valid.astype(jnp.int32))
            pr_dev = jnp.asarray(pr)
            prev_row = jnp.clip(pr_dev - 1, 0, cap - 1)
            first_val = jnp.where(pr_dev > 0, vcnt[prev_row], 0) \
                .astype(jnp.int32)
            total_packed = vcnt[jnp.clip(jnp.asarray(n - 1), 0,
                                         cap - 1)]
        else:
            first_val = jnp.asarray(pr)
            total_packed = jnp.asarray(n, jnp.int32)
        kbits = max(1, (max(maxv - 1, 1)).bit_length())
        pcap = pd.bucket_len(max(n, 1), floor=128)
        starts, lens = pd.byte_array_index(
            chunk_dev, jnp.asarray(po), first_val, len(plain_pages),
            total_packed, kbits, pcap)
        row_start, row_len = pd.rows_from_packed(
            starts, lens, valid, n, cap)
    elif dict_idx_pages:
        if dict_page is None:
            return None
        ndict = dict_page.num_values
        if ndict > _MAX_DICT_VALUES:
            return None
        try:
            dstarts, dlens = _walk_byte_array_extents(
                c.raw, dict_page.data_offset,
                dict_page.data_offset + dict_page.compressed_size,
                ndict)
        except pt.ThriftError:
            return None
        max_len = int(dlens.max()) if ndict else 0
        bound = max(n, 1) * max(max_len, 1)
        if bound > _MAX_STRING_BYTES:
            return None
        dcap = pd.bucket_len(max(bound, 1), floor=128)
        idx = _dict_indices(c, valid, dict_idx_pages, cap)
        if idx is None:
            return None
        D = pd.bucket_len(max(ndict, 1))
        ds = np.zeros(D, np.int32)
        dl = np.zeros(D, np.int32)
        ds[:ndict], dl[:ndict] = dstarts, dlens
        row_start, row_len = pd.dict_rows(
            idx, jnp.asarray(ds), jnp.asarray(dl), valid, n, cap)
    else:
        return None
    data, offsets = pd.assemble_strings(
        c.uploaded, row_start, row_len, n, cap, dcap)
    new_valid = valid & (jnp.arange(cap) < n)
    return data, new_valid, offsets


def decode_chunk_device(c: DeviceChunk, cap: int, metrics=None):
    """Decode one chunk at capacity `cap`. Fixed-width chunks return
    (device values, device validity); BYTE_ARRAY chunks return
    (data bytes, validity, offsets). Returns None when a page shape
    defeats the slice (caller falls back to host decode)."""
    import jax.numpy as jnp

    from ..ops import parquet_decode as pd

    try:
        def_runs, plain_pages, dict_idx_pages, dict_page = \
            _parse_sections(c)
    except pt.ThriftError:
        return None                   # malformed page section: fallback
    if plain_pages and dict_idx_pages:
        return None                   # mixed-encoding chunk: fallback
    chunk_dev = _chunk_device_bytes(c, metrics)
    n = c.num_values

    # -- def levels -> validity + per-page non-null counts -------------
    if c.nullable and def_runs:
        R = pd.bucket_len(len(def_runs))
        rs = np.full(R, n, np.int32)
        rc = np.zeros(R, np.int32)
        rp = np.zeros(R, np.int32)
        rv = np.zeros(R, np.int32)
        rb = np.zeros(R, np.int32)
        for i, r in enumerate(def_runs):
            rs[i], rc[i], rp[i] = r.out_start, r.count, int(r.is_packed)
            rv[i], rb[i] = r.value, r.byte_offset
        def_levels = pd.expand_hybrid(
            chunk_dev, jnp.asarray(rs), jnp.asarray(rc),
            jnp.asarray(rp), jnp.asarray(rv), jnp.asarray(rb),
            len(def_runs), n, 1, cap)
        valid = def_levels == 1
    else:
        i = jnp.arange(cap, dtype=jnp.int32)
        valid = i < n
        def_levels = valid.astype(jnp.int32)

    if c.physical == "BYTE_ARRAY":
        return _decode_strings(c, valid, cap, plain_pages,
                               dict_idx_pages, dict_page)

    width = _PHYS_WIDTH[c.physical]
    np_name = _PHYS_NP[c.physical]

    # -- packed value stream -------------------------------------------
    if plain_pages:
        P = pd.bucket_len(len(plain_pages))
        po = np.zeros(P, np.int32)
        pr = np.full(P, n, np.int32)      # first ROW of page (sentinel n)
        for i, (off, row) in enumerate(plain_pages):
            po[i], pr[i] = off, row
        if c.nullable:
            # PLAIN stores non-null values only: first VALUE index of
            # each page = count of valid rows before the page (device)
            vcnt = jnp.cumsum(valid.astype(jnp.int32))
            pr_dev = jnp.asarray(pr)
            prev_row = jnp.clip(pr_dev - 1, 0, cap - 1)
            first_val = jnp.where(pr_dev > 0, vcnt[prev_row], 0) \
                .astype(jnp.int32)
        else:
            first_val = jnp.asarray(pr)
        packed = pd.decode_plain_fixed(
            chunk_dev, jnp.asarray(po), first_val,
            len(plain_pages), n, width, cap)
    elif dict_idx_pages:
        if dict_page is None:
            return None
        ndict = dict_page.num_values
        dcap = pd.bucket_len(max(ndict, 1), floor=128)
        d_po = np.zeros(8, np.int32)
        d_pr = np.full(8, ndict, np.int32)
        d_po[0], d_pr[0] = dict_page.data_offset, 0
        dict_words = pd.decode_plain_fixed(
            chunk_dev, jnp.asarray(d_po), jnp.asarray(d_pr), 1,
            ndict, width, dcap)
        idx = _dict_indices(c, valid, dict_idx_pages, cap)
        if idx is None:
            return None
        packed = dict_words[jnp.clip(idx, 0, dcap - 1)]
    else:
        return None

    if c.nullable:
        words, valid = pd.apply_def_levels(def_levels, packed, 1, n, cap)
    else:
        words = packed[:cap] if packed.shape[0] >= cap else jnp.pad(
            packed, (0, cap - packed.shape[0]))
        words = jnp.where(valid, words, 0)
    vals = pd.words_to_device(words, np_name)
    return vals, valid
