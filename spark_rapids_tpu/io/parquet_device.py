"""Device Parquet decode orchestration (first slice).

Reference: GpuParquetScan.scala:3364 (Table.readParquet decodes column
chunks on the accelerator) and the COALESCING reader (:2523) that
stitches chunks into ONE buffer for ONE device decode. TPU shape of the
same idea:

  host:   read RAW column-chunk bytes, parse page headers + RLE run
          tables (O(pages + runs), no value bytes touched)
  device: ONE uint8 upload per chunk; PLAIN lane assembly, hybrid
          run expansion (def levels, dictionary indices), dictionary
          gather, def-level->validity + packed-value scatter — all
          jitted with shapes static per (pages, runs, capacity) bucket.

Eligibility (everything else falls back to the pyarrow host path,
per column): UNCOMPRESSED chunks, flat INT32/INT64/FLOAT/DOUBLE
physical types, PLAIN or RLE_DICTIONARY/PLAIN_DICTIONARY data pages,
v1 data pages with RLE def levels.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from . import parquet_thrift as pt

__all__ = ["chunk_device_plan", "decode_chunk_device",
           "eligible_chunks", "DeviceChunk"]

_PHYS_WIDTH = {"INT32": 4, "INT64": 8, "FLOAT": 4, "DOUBLE": 8}
_PHYS_NP = {"INT32": "int32", "INT64": "int64",
            "FLOAT": "float32", "DOUBLE": "float64"}

_OK_ENCODINGS = {"PLAIN", "RLE", "PLAIN_DICTIONARY", "RLE_DICTIONARY",
                 "BIT_PACKED"}


class DeviceChunk:
    """Host-parsed metadata for one device-decodable column chunk."""

    def __init__(self, name: str, physical: str, nullable: bool,
                 raw: bytes, pages: List[pt.PageInfo], num_values: int):
        self.name = name
        self.physical = physical
        self.nullable = nullable
        self.raw = raw
        self.pages = pages
        self.num_values = num_values


def eligible_chunks(pf, rg: int, columns: List[str]) -> Dict[str, int]:
    """Map column name -> column index for chunks the device path can
    decode in row group `rg`."""
    md = pf.metadata
    out = {}
    names = {}
    for ci in range(md.num_columns):
        col = md.row_group(rg).column(ci)
        names[".".join(col.path_in_schema.split("."))] = ci
    for name in columns:
        ci = names.get(name)
        if ci is None:
            continue
        col = md.row_group(rg).column(ci)
        if col.compression != "UNCOMPRESSED":
            continue
        if col.physical_type not in _PHYS_WIDTH:
            continue
        if not set(col.encodings) <= _OK_ENCODINGS:
            continue
        # flat columns only (no repetition levels)
        if "." in name:
            continue
        out[name] = ci
    return out


def chunk_device_plan(pf, path: str, rg: int, ci: int,
                      name: str, nullable: bool) -> Optional[DeviceChunk]:
    """Read raw bytes + parse page metadata for one column chunk."""
    col = pf.metadata.row_group(rg).column(ci)
    start = col.data_page_offset
    if col.has_dictionary_page and col.dictionary_page_offset is not None:
        start = min(start, col.dictionary_page_offset)
    size = col.total_compressed_size
    with open(path, "rb") as f:
        f.seek(start)
        raw = f.read(size)
    try:
        pages = pt.parse_page_headers(raw, col.num_values)
    except pt.ThriftError:
        return None
    for p in pages:
        if p.page_type == pt.DATA_PAGE_V2:
            return None                       # v1 slice only
        if p.page_type == pt.DATA_PAGE:
            if p.encoding not in (pt.PLAIN, pt.PLAIN_DICTIONARY,
                                  pt.RLE_DICTIONARY):
                return None
            if nullable and p.def_level_encoding != pt.RLE:
                return None
    return DeviceChunk(name, col.physical_type, nullable, raw, pages,
                       col.num_values)


def _parse_sections(c: DeviceChunk):
    """Split every data page into (def-level runs, value section).
    Returns (def_runs, plain_pages, dict_pages, dict_page) where
    def_runs: list[pt.RleRun] with ABSOLUTE out_start,
    plain_pages: [(payload_off, first_row)],
    dict_pages:  [(bit_width, runs_abs)] for index sections,
    dict_page:   PageInfo | None."""
    def_runs: List[pt.RleRun] = []
    plain_pages: List[Tuple[int, int]] = []
    dict_idx_pages: List[Tuple[int, List[pt.RleRun]]] = []
    dict_page = None
    row = 0
    for p in c.pages:
        if p.page_type == pt.DICTIONARY_PAGE:
            dict_page = p
            continue
        if p.page_type != pt.DATA_PAGE:
            continue
        off = p.data_offset
        end = p.data_offset + p.compressed_size
        if c.nullable:
            # v1: [int32 LE length][RLE/bit-packed hybrid, bit width 1]
            ln = int.from_bytes(c.raw[off:off + 4], "little")
            runs = pt.parse_hybrid_runs(c.raw, off + 4, off + 4 + ln,
                                        p.num_values, 1)
            for r in runs:
                def_runs.append(pt.RleRun(
                    row + r.out_start, r.count, r.is_packed, r.value,
                    r.byte_offset))
            off += 4 + ln
        if p.encoding == pt.PLAIN:
            plain_pages.append((off, row))
        else:                                  # dictionary indices
            bw = c.raw[off] if off < len(c.raw) else 255
            if bw > 32:
                # spec max is 32; a corrupt/hostile byte here must route
                # to the host fallback, not overflow the run tables
                raise pt.ThriftError(f"dict index bit width {bw}")
            runs = pt.parse_hybrid_runs(c.raw, off + 1, end,
                                        p.num_values, bw)
            # index runs address the PACKED (non-null) value stream;
            # out_start is patched on device via per-page valid counts
            dict_idx_pages.append((bw, runs, row, p.num_values))
        row += p.num_values
    return def_runs, plain_pages, dict_idx_pages, dict_page


def decode_chunk_device(c: DeviceChunk, cap: int):
    """Decode one chunk to (device values, device validity) at
    capacity `cap`. Returns None when a page shape defeats the slice
    (caller falls back to host decode)."""
    import jax.numpy as jnp

    from ..ops import parquet_decode as pd

    try:
        def_runs, plain_pages, dict_idx_pages, dict_page = \
            _parse_sections(c)
    except pt.ThriftError:
        return None                   # malformed page section: fallback
    if plain_pages and dict_idx_pages:
        return None                   # mixed-encoding chunk: fallback
    width = _PHYS_WIDTH[c.physical]
    np_name = _PHYS_NP[c.physical]
    chunk_dev = jnp.asarray(np.frombuffer(c.raw, np.uint8))
    n = c.num_values

    # -- def levels -> validity + per-page non-null counts -------------
    if c.nullable and def_runs:
        R = pd.bucket_len(len(def_runs))
        rs = np.full(R, n, np.int32)
        rc = np.zeros(R, np.int32)
        rp = np.zeros(R, np.int32)
        rv = np.zeros(R, np.int32)
        rb = np.zeros(R, np.int32)
        for i, r in enumerate(def_runs):
            rs[i], rc[i], rp[i] = r.out_start, r.count, int(r.is_packed)
            rv[i], rb[i] = r.value, r.byte_offset
        def_levels = pd.expand_hybrid(
            chunk_dev, jnp.asarray(rs), jnp.asarray(rc),
            jnp.asarray(rp), jnp.asarray(rv), jnp.asarray(rb),
            len(def_runs), n, 1, cap)
        valid = def_levels == 1
    else:
        i = jnp.arange(cap, dtype=jnp.int32)
        valid = i < n
        def_levels = valid.astype(jnp.int32)

    # -- packed value stream -------------------------------------------
    if plain_pages:
        P = pd.bucket_len(len(plain_pages))
        po = np.zeros(P, np.int32)
        pr = np.full(P, n, np.int32)      # first ROW of page (sentinel n)
        for i, (off, row) in enumerate(plain_pages):
            po[i], pr[i] = off, row
        if c.nullable:
            # PLAIN stores non-null values only: first VALUE index of
            # each page = count of valid rows before the page (device)
            vcnt = jnp.cumsum(valid.astype(jnp.int32))
            pr_dev = jnp.asarray(pr)
            prev_row = jnp.clip(pr_dev - 1, 0, cap - 1)
            first_val = jnp.where(pr_dev > 0, vcnt[prev_row], 0) \
                .astype(jnp.int32)
        else:
            first_val = jnp.asarray(pr)
        packed = pd.decode_plain_fixed(
            chunk_dev, jnp.asarray(po), first_val,
            len(plain_pages), n, width, cap)
    elif dict_idx_pages:
        if dict_page is None:
            return None
        ndict = dict_page.num_values
        dcap = pd.bucket_len(max(ndict, 1), floor=128)
        d_po = np.zeros(8, np.int32)
        d_pr = np.full(8, ndict, np.int32)
        d_po[0], d_pr[0] = dict_page.data_offset, 0
        dict_words = pd.decode_plain_fixed(
            chunk_dev, jnp.asarray(d_po), jnp.asarray(d_pr), 1,
            ndict, width, dcap)
        bws = {bw for bw, _, _, _ in dict_idx_pages}
        if len(bws) != 1:
            return None               # one static bit width per chunk
        bw = bws.pop()
        allruns: List[pt.RleRun] = []
        vcnt = jnp.cumsum(valid.astype(jnp.int32))
        # index run out_starts address the packed stream; per page the
        # packed offset = valid-count before the page's first row
        run_page_row = []
        for _bw, runs, row, _nv in dict_idx_pages:
            for r in runs:
                allruns.append(r)
                run_page_row.append(row)
        R = pd.bucket_len(len(allruns))
        rs = np.zeros(R, np.int32)
        rc = np.zeros(R, np.int32)
        rp = np.zeros(R, np.int32)
        rv = np.zeros(R, np.int32)
        rb = np.zeros(R, np.int32)
        prow = np.zeros(R, np.int32)
        for i, r in enumerate(allruns):
            rs[i], rc[i], rp[i] = r.out_start, r.count, int(r.is_packed)
            rv[i], rb[i] = r.value, r.byte_offset
            prow[i] = run_page_row[i]
        prow_dev = jnp.asarray(prow)
        page_val_base = jnp.where(
            prow_dev > 0,
            vcnt[jnp.clip(prow_dev - 1, 0, cap - 1)], 0)
        rs_abs = jnp.asarray(rs) + page_val_base
        # pad rows past the live runs to the sentinel (total packed)
        total_packed = vcnt[jnp.clip(jnp.asarray(n - 1), 0, cap - 1)]
        live = jnp.arange(R) < len(allruns)
        rs_abs = jnp.where(live, rs_abs, total_packed).astype(jnp.int32)
        idx = pd.expand_hybrid(
            chunk_dev, rs_abs, jnp.asarray(rc), jnp.asarray(rp),
            jnp.asarray(rv), jnp.asarray(rb), len(allruns), n, bw,
            pd.bucket_len(max(n, 1), floor=128))
        packed = dict_words[jnp.clip(idx, 0, dcap - 1)]
    else:
        return None

    if c.nullable:
        words, valid = pd.apply_def_levels(def_levels, packed, 1, n, cap)
    else:
        words = packed[:cap] if packed.shape[0] >= cap else jnp.pad(
            packed, (0, cap - packed.shape[0]))
        words = jnp.where(valid, words, 0)
    vals = pd.words_to_device(words, np_name)
    return vals, valid
