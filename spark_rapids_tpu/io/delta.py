"""Delta Lake table support (round-1: transaction log + versioned reads).

The reference carries 60k LoC of Delta support (reference: delta-lake/
GpuDeltaLog, GpuOptimisticTransaction, MERGE/DELETE/UPDATE commands); this
module lands the storage core those build on: the `_delta_log` JSON-action
commit protocol (protocol/metaData/add/remove), snapshot reconstruction at
any version (time travel), and transactional append/overwrite writes.
MERGE INTO / DELETE / UPDATE commands build on this in a later round.
"""
from __future__ import annotations

import json
import os
import time
import uuid
from typing import Dict, List, Optional

__all__ = ["DeltaTable", "write_delta", "read_delta"]


class DeltaTable:
    def __init__(self, path: str):
        self.path = path
        self.log_dir = os.path.join(path, "_delta_log")

    # ---- log protocol -------------------------------------------------
    def _commit_file(self, version: int) -> str:
        return os.path.join(self.log_dir, f"{version:020d}.json")

    def latest_version(self) -> int:
        if not os.path.isdir(self.log_dir):
            return -1
        versions = [int(f.split(".")[0]) for f in os.listdir(self.log_dir)
                    if f.endswith(".json")]
        return max(versions, default=-1)

    def _actions(self, version: int) -> List[dict]:
        out = []
        for v in range(version + 1):
            with open(self._commit_file(v)) as f:
                for line in f:
                    if line.strip():
                        out.append(json.loads(line))
        return out

    def snapshot_files(self, version: Optional[int] = None) -> List[str]:
        """Live data files at a version (add minus remove)."""
        latest = self.latest_version()
        if latest < 0:
            raise FileNotFoundError(f"not a delta table: {self.path}")
        v = latest if version is None else version
        if v > latest:
            raise ValueError(f"version {v} > latest {latest}")
        live: Dict[str, bool] = {}
        for a in self._actions(v):
            if "add" in a:
                live[a["add"]["path"]] = True
            elif "remove" in a:
                live.pop(a["remove"]["path"], None)
        return [os.path.join(self.path, p) for p in live]

    def try_commit(self, actions: List[dict], version: int) -> bool:
        """Optimistic commit of a SPECIFIC version: atomically create the
        version file (O_EXCL, the delta-log concurrency primitive).
        Returns False if another writer won the version — the caller must
        recompute its actions against the new snapshot and retry."""
        os.makedirs(self.log_dir, exist_ok=True)
        path = self._commit_file(version)
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return False
        with os.fdopen(fd, "w") as f:
            for a in actions:
                f.write(json.dumps(a) + "\n")
        return True

    def history(self) -> List[dict]:
        out = []
        for v in range(self.latest_version() + 1):
            with open(self._commit_file(v)) as f:
                for line in f:
                    a = json.loads(line)
                    if "commitInfo" in a:
                        out.append({"version": v, **a["commitInfo"]})
        return out


def write_delta(df, path: str, mode: str = "append"):
    """Transactional write: data files first, then one commit. On a lost
    commit race the actions are RECOMPUTED against the new snapshot (the
    overwrite remove-list and the protocol/metaData bootstrap both depend
    on it)."""
    import pyarrow.parquet as pq
    table = DeltaTable(path)
    os.makedirs(path, exist_ok=True)
    at = df.to_arrow()
    fname = f"part-{uuid.uuid4().hex[:12]}.parquet"
    pq.write_table(at, os.path.join(path, fname))
    while True:
        latest = table.latest_version()
        first = latest < 0
        actions = []
        if first:
            actions.append({"protocol": {"minReaderVersion": 1,
                                         "minWriterVersion": 2}})
            actions.append({"metaData": {
                "id": uuid.uuid4().hex,
                "format": {"provider": "parquet"},
                "schemaString": df.schema.to_arrow().to_string(),
                "partitionColumns": [],
            }})
        op = "WRITE" if mode == "append" or first else "OVERWRITE"
        if mode == "overwrite" and not first:
            for f in table.snapshot_files():
                actions.append({"remove": {
                    "path": os.path.basename(f),
                    "deletionTimestamp": int(time.time() * 1000)}})
        actions.append({"add": {
            "path": fname,
            "size": os.path.getsize(os.path.join(path, fname)),
            "modificationTime": int(time.time() * 1000),
            "dataChange": True}})
        actions.append({"commitInfo": {
            "operation": op, "timestamp": int(time.time() * 1000)}})
        if table.try_commit(actions, latest + 1):
            return latest + 1


def read_delta(session, path: str, version: Optional[int] = None):
    """Read a delta table snapshot (optionally time travel)."""
    from ..plan.logical import ParquetScan
    from ..session import DataFrame
    files = DeltaTable(path).snapshot_files(version)
    if not files:
        raise ValueError(f"delta table {path} has no live files")
    return DataFrame(session, ParquetScan(files))
