"""Delta Lake table support: transaction log, versioned reads, DML.

The reference carries 60k LoC of Delta support (reference: delta-lake/
GpuDeltaLog, GpuOptimisticTransaction, GpuMergeIntoCommand,
GpuDeleteCommand, GpuUpdateCommand); this module implements the storage
core (the `_delta_log` JSON-action commit protocol, snapshot
reconstruction/time travel, transactional append/overwrite), copy-on-write
DML (DELETE / UPDATE / MERGE INTO — per-file rewrites through the TPU
engine, untouched files skipped), and periodic checkpoints
(`NNN.checkpoint.parquet` + `_last_checkpoint`, engine-internal layout).
"""
from __future__ import annotations

import json
import os
import time
import uuid
from typing import Dict, List, Optional

__all__ = ["DeltaTable", "write_delta", "read_delta", "delete_delta",
           "update_delta", "merge_delta", "optimize_delta",
           "maybe_auto_compact", "CHECKPOINT_INTERVAL"]

CHECKPOINT_INTERVAL = 10


class DeltaTable:
    def __init__(self, path: str):
        self.path = path
        self.log_dir = os.path.join(path, "_delta_log")

    # ---- log protocol -------------------------------------------------
    def _commit_file(self, version: int) -> str:
        return os.path.join(self.log_dir, f"{version:020d}.json")

    def latest_version(self) -> int:
        if not os.path.isdir(self.log_dir):
            return -1
        versions = [int(f.split(".")[0]) for f in os.listdir(self.log_dir)
                    if f.endswith(".json")]
        return max(versions, default=-1)

    # ---- checkpoints ---------------------------------------------------
    def _checkpoint_file(self, version: int) -> str:
        return os.path.join(self.log_dir,
                            f"{version:020d}.checkpoint.parquet")

    def _last_checkpoint_version(self) -> int:
        lc = os.path.join(self.log_dir, "_last_checkpoint")
        if not os.path.exists(lc):
            return -1
        try:
            with open(lc) as f:
                return int(json.load(f)["version"])
        except (ValueError, KeyError, OSError):
            return -1

    def write_checkpoint(self, version: int):
        """Consolidate the snapshot at `version` into one parquet
        (engine-internal layout: one JSON action per row; the reference's
        binary checkpoint schema interop is follow-on work)."""
        import pyarrow as pa
        import pyarrow.parquet as pq
        actions = self._replay_actions(version)
        # keep protocol/metaData + LIVE adds only
        live: Dict[str, dict] = {}
        keep: List[dict] = []
        for a in actions:
            if "add" in a:
                live[a["add"]["path"]] = a
            elif "remove" in a:
                live.pop(a["remove"]["path"], None)
            elif "protocol" in a or "metaData" in a:
                keep.append(a)
        rows = keep + list(live.values())
        pq.write_table(
            pa.table({"action": pa.array([json.dumps(a) for a in rows])}),
            self._checkpoint_file(version))
        with open(os.path.join(self.log_dir, "_last_checkpoint"),
                  "w") as f:
            json.dump({"version": version, "size": len(rows)}, f)

    def _replay_actions(self, version: int) -> List[dict]:
        """All actions up to `version`, starting from the newest usable
        checkpoint."""
        import pyarrow.parquet as pq
        out: List[dict] = []
        start = 0
        cp = self._last_checkpoint_version()
        if 0 <= cp <= version and os.path.exists(self._checkpoint_file(cp)):
            at = pq.read_table(self._checkpoint_file(cp))
            out.extend(json.loads(s) for s in at.column(0).to_pylist())
            start = cp + 1
        for v in range(start, version + 1):
            with open(self._commit_file(v)) as f:
                for line in f:
                    if line.strip():
                        out.append(json.loads(line))
        return out

    def _actions(self, version: int) -> List[dict]:
        return self._replay_actions(version)

    def snapshot_adds(self, version: Optional[int] = None) -> List[dict]:
        """Live add actions at a version (add minus remove; a re-add of
        the same path — e.g. attaching a deletion vector — replaces the
        earlier entry)."""
        latest = self.latest_version()
        if latest < 0:
            raise FileNotFoundError(f"not a delta table: {self.path}")
        v = latest if version is None else version
        if v > latest:
            raise ValueError(f"version {v} > latest {latest}")
        live: Dict[str, dict] = {}
        for a in self._actions(v):
            if "add" in a:
                live[a["add"]["path"]] = a["add"]
            elif "remove" in a:
                live.pop(a["remove"]["path"], None)
        return list(live.values())

    def snapshot_files(self, version: Optional[int] = None) -> List[str]:
        """Live data file paths at a version."""
        return [os.path.join(self.path, a["path"])
                for a in self.snapshot_adds(version)]

    def try_commit(self, actions: List[dict], version: int) -> bool:
        """Optimistic commit of a SPECIFIC version: atomically create the
        version file (O_EXCL, the delta-log concurrency primitive).
        Returns False if another writer won the version — the caller must
        recompute its actions against the new snapshot and retry."""
        os.makedirs(self.log_dir, exist_ok=True)
        path = self._commit_file(version)
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return False
        with os.fdopen(fd, "w") as f:
            for a in actions:
                f.write(json.dumps(a) + "\n")
        return True

    def maybe_checkpoint(self, version: int):
        if version > 0 and version % CHECKPOINT_INTERVAL == 0:
            self.write_checkpoint(version)

    def history(self) -> List[dict]:
        out = []
        for v in range(self.latest_version() + 1):
            with open(self._commit_file(v)) as f:
                for line in f:
                    a = json.loads(line)
                    if "commitInfo" in a:
                        out.append({"version": v, **a["commitInfo"]})
        return out


def _invalidate_cached(path: str):
    """Advisory: drop result-cache entries reading any file under path."""
    try:
        from ..runtime import result_cache
        result_cache.invalidate_prefix(path)
    except Exception:
        pass


def write_delta(df, path: str, mode: str = "append"):
    """Transactional write: data files first, then one commit. On a lost
    commit race the actions are RECOMPUTED against the new snapshot (the
    overwrite remove-list and the protocol/metaData bootstrap both depend
    on it)."""
    import pyarrow.parquet as pq
    table = DeltaTable(path)
    os.makedirs(path, exist_ok=True)
    at = df.to_arrow()
    fname = f"part-{uuid.uuid4().hex[:12]}.parquet"
    pq.write_table(at, os.path.join(path, fname))
    while True:
        latest = table.latest_version()
        first = latest < 0
        actions = []
        if first:
            actions.append({"protocol": {"minReaderVersion": 1,
                                         "minWriterVersion": 2}})
            actions.append({"metaData": {
                "id": uuid.uuid4().hex,
                "format": {"provider": "parquet"},
                "schemaString": df.schema.to_arrow().to_string(),
                "partitionColumns": [],
            }})
        op = "WRITE" if mode == "append" or first else "OVERWRITE"
        if mode == "overwrite" and not first:
            for f in table.snapshot_files():
                actions.append({"remove": {
                    "path": os.path.basename(f),
                    "deletionTimestamp": int(time.time() * 1000)}})
        actions.append({"add": {
            "path": fname,
            "size": os.path.getsize(os.path.join(path, fname)),
            "modificationTime": int(time.time() * 1000),
            "dataChange": True}})
        actions.append({"commitInfo": {
            "operation": op, "timestamp": int(time.time() * 1000)}})
        if table.try_commit(actions, latest + 1):
            table.maybe_checkpoint(latest + 1)
            _invalidate_cached(path)
            if mode == "append":
                maybe_auto_compact(df._session, path, df._session.conf)
            return latest + 1


def read_delta(session, path: str, version: Optional[int] = None):
    """Read a delta table snapshot (optionally time travel). Files
    carrying deletion vectors host-filter their dead positions (the
    reference applies DVs as row filters in
    GpuDeltaParquetFileFormat)."""
    from ..plan.logical import InMemoryScan, ParquetScan, Union
    from ..session import DataFrame
    table = DeltaTable(path)
    adds = table.snapshot_adds(version)
    if not adds:
        raise ValueError(f"delta table {path} has no live files")
    # pin the table version in the scan: it rides the structural plan
    # fingerprint, so a commit (append/OPTIMIZE/DML) changes every
    # dependent result-cache key even when file mtimes are unhelpful
    dv_ver = table.latest_version() if version is None else version
    plain = [os.path.join(path, a["path"]) for a in adds
             if not a.get("deletionVector")]
    with_dv = [a for a in adds if a.get("deletionVector")]
    if not with_dv:
        return DataFrame(session, ParquetScan(plain, delta_version=dv_ver))
    import pyarrow as pa
    import pyarrow.parquet as pq
    from .dv import read_dv_file
    tables = []
    for a in with_dv:
        dv = a["deletionVector"]
        dv_path = os.path.join(path, dv["pathOrInlineDv"])
        dead = set(read_dv_file(dv_path, dv.get("offset", 1)))
        t = pq.read_table(os.path.join(path, a["path"]))
        keep = [i for i in range(t.num_rows) if i not in dead]
        tables.append(t.take(pa.array(keep, type=pa.int64())))
    dv_tbl = pa.concat_tables(tables)
    if not plain:
        return DataFrame(session, InMemoryScan(dv_tbl))
    return DataFrame(session, Union([
        ParquetScan(plain, delta_version=dv_ver), InMemoryScan(dv_tbl)]))


# ----------------------------------------------------------------------
# Copy-on-write DML (reference: delta-33x GpuDeleteCommand,
# GpuUpdateCommand, GpuMergeIntoCommand — per-file rewrite through the
# engine; files with no matching rows are left untouched)
# ----------------------------------------------------------------------
def _write_rows(session, at, path: str) -> Optional[dict]:
    """Write an arrow table as one new data file; None when empty."""
    import pyarrow.parquet as pq
    if at.num_rows == 0:
        return None
    fname = f"part-{uuid.uuid4().hex[:12]}.parquet"
    pq.write_table(at, os.path.join(path, fname))
    return {"add": {"path": fname,
                    "size": os.path.getsize(os.path.join(path, fname)),
                    "modificationTime": int(time.time() * 1000),
                    "dataChange": True}}


def _file_df(session, table: "DeltaTable", add: dict):
    """DataFrame over ONE live file with its deletion vector (if any)
    applied — DML rewrites must not resurrect DV-dead rows."""
    fpath = os.path.join(table.path, add["path"])
    dv = add.get("deletionVector")
    if not dv:
        return session.read.parquet(fpath)
    import pyarrow as pa
    import pyarrow.parquet as pq
    from .dv import read_dv_file
    dead = set(read_dv_file(
        os.path.join(table.path, dv["pathOrInlineDv"]),
        dv.get("offset", 1)))
    t = pq.read_table(fpath)
    keep = [i for i in range(t.num_rows) if i not in dead]
    return session.create_dataframe(t.take(
        pa.array(keep, type=pa.int64())))


def _remove_action(f: str) -> dict:
    return {"remove": {"path": os.path.basename(f),
                       "deletionTimestamp": int(time.time() * 1000)}}


def _commit_dml(table: DeltaTable, build_actions, op: str) -> int:
    """Optimistic-commit loop: recompute file actions against the latest
    snapshot on every race loss (GpuOptimisticTransaction analog)."""
    while True:
        latest = table.latest_version()
        if latest < 0:
            raise FileNotFoundError(f"not a delta table: {table.path}")
        actions = build_actions()
        actions.append({"commitInfo": {
            "operation": op, "timestamp": int(time.time() * 1000)}})
        if table.try_commit(actions, latest + 1):
            table.maybe_checkpoint(latest + 1)
            _invalidate_cached(table.path)
            return latest + 1


def delete_delta(session, path: str, condition) -> int:
    """DELETE FROM <path> WHERE condition. Returns the new version.
    With delta.deletionVectors.enabled, matching files get a roaring-
    bitmap DV marking dead rows instead of a rewrite (the descriptor's
    pathOrInlineDv is table-relative with storageType 'p')."""
    table = DeltaTable(path)

    from ..config import DELTA_DV_ENABLED
    from ..expr.expressions import IsNull, Not, Or
    use_dv = session.conf.get(DELTA_DV_ENABLED)

    def build():
        actions: List[dict] = []
        keep_cond = Or(Not(condition), IsNull(condition))  # NULL -> keep
        for a in table.snapshot_adds():
            f = os.path.join(path, a["path"])
            if use_dv:
                # ONE read + ONE predicate evaluation per file: the hit
                # positions drive both the skip decision and the DV
                actions.extend(_dv_delete_actions(session, table, a, f,
                                                  condition))
                continue
            df = _file_df(session, table, a)
            if df.filter(condition).count() == 0:
                continue        # untouched file, no rewrite
            kept = df.filter(keep_cond)
            actions.append(_remove_action(f))
            add = _write_rows(session, kept.to_arrow(), path)
            if add:
                actions.append(add)
        return actions

    return _commit_dml(table, build, "DELETE")


def _dv_delete_actions(session, table, add, fpath, condition):
    """Re-add `add` with a deletion vector covering old + new dead
    rows; no new hits -> no actions; a fully-dead file becomes a plain
    remove."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    from .dv import load_dv_positions, write_dv_file
    t = pq.read_table(fpath)
    old_dead = set()
    dv0 = add.get("deletionVector")
    if dv0:
        old_dead = set(load_dv_positions(table.path, dv0))
    t2 = t.append_column("__pos", pa.array(range(t.num_rows),
                                           pa.int64()))
    hits = session.create_dataframe(t2).filter(condition) \
        .to_arrow().column("__pos").to_pylist()
    if not set(hits) - old_dead:
        return []                          # nothing newly dead
    dead = old_dead | set(hits)
    if len(dead) >= t.num_rows:
        return [_remove_action(fpath)]
    dv_name = f"deletion_vector_{uuid.uuid4().hex[:12]}.bin"
    dv_abs = os.path.abspath(os.path.join(table.path, dv_name))
    desc = write_dv_file(dv_abs, dead)
    new_add = dict(add)
    # storageType 'p' means an ABSOLUTE path per the Delta protocol
    # (the reference resolves descriptor.absolutePath); table-relative
    # names here would break spec-conformant external readers
    new_add["deletionVector"] = {
        "storageType": "p", "pathOrInlineDv": dv_abs,
        "offset": desc["offset"], "sizeInBytes": desc["sizeInBytes"],
        "cardinality": desc["cardinality"]}
    new_add["dataChange"] = True
    return [_remove_action(fpath), {"add": new_add}]


def update_delta(session, path: str, condition,
                 assignments: Dict[str, object]) -> int:
    """UPDATE <path> SET col=expr WHERE condition. Expressions reference
    the table's columns; returns the new version."""
    from ..expr.expressions import (Cast, Expression, If, Literal,
                                    col as col_)
    table = DeltaTable(path)

    def build():
        actions: List[dict] = []
        for a in table.snapshot_adds():
            f = os.path.join(path, a["path"])
            df = _file_df(session, table, a)
            if df.filter(condition).count() == 0:
                continue
            exprs = []
            for fld in df.schema.fields:
                if fld.name in assignments:
                    v = assignments[fld.name]
                    ve = v if isinstance(v, Expression) else Literal(v)
                    # Spark casts the assignment to the COLUMN's type;
                    # an int literal must not narrow int64 -> int32
                    ve = Cast(ve, fld.dtype)
                    exprs.append(If(condition, ve,
                                    col_(fld.name)).alias(fld.name))
                else:
                    exprs.append(col_(fld.name))
            actions.append(_remove_action(f))
            add = _write_rows(session, df.select(*exprs).to_arrow(), path)
            if add:
                actions.append(add)
        return actions

    return _commit_dml(table, build, "UPDATE")


def merge_delta(session, path: str, source_df, on: List[str],
                when_matched: Optional[str] = "update",
                matched_assignments: Optional[Dict[str, object]] = None,
                when_not_matched: Optional[str] = "insert") -> int:
    """MERGE INTO <path> USING source ON target.k == source.k.

    when_matched: "update" (set matched_assignments, or replace the whole
    row with the source's columns when None), "delete", or None (leave
    matched rows); when_not_matched: "insert" or None. Copy-on-write:
    only files containing matches rewrite; inserts append one new file.
    (reference: delta-33x GpuMergeIntoCommand low-shuffle merge.)"""
    from ..expr.expressions import Expression, If, Literal, col as col_
    table = DeltaTable(path)
    src = source_df.to_arrow()      # materialize once; sources are small

    def build():
        import pyarrow as pa
        actions: List[dict] = []
        src_df = session.create_dataframe(src)
        if when_matched is not None:
            # Delta MERGE semantics: a target row matched by MULTIPLE
            # source rows is an error, not a cardinality change
            from ..functions import count as f_count
            dup = src_df.group_by(*on).agg(f_count("*").alias("__c"))
            dup_keys = dup.filter(col_("__c") > 1)
            if dup_keys.count() > 0:
                tgt = read_delta(session, path)
                hits = tgt.join(dup_keys, on=on, how="left_semi")
                if hits.count() > 0:
                    raise ValueError(
                        "MERGE: multiple source rows matched the same "
                        "target row")
        # rename non-key source columns so post-join references are
        # unambiguous ("update all" must read the SOURCE's value)
        src_ren = src_df.select(*(
            [col_(k) for k in on]
            + [col_(c).alias(f"__src_{c}") for c in src_df.columns
               if c not in on]))
        for a_ in table.snapshot_adds():
            f = os.path.join(path, a_["path"])
            tdf = _file_df(session, table, a_)
            if tdf.join(src_df, on=on, how="left_semi").count() == 0:
                continue
            if when_matched == "delete":
                out_at = tdf.join(src_df, on=on,
                                  how="left_anti").to_arrow()
            elif when_matched == "update":
                anti = tdf.join(src_df, on=on, how="left_anti")
                hit = tdf.join(src_ren, on=on, how="inner")
                exprs = []
                for fld in tdf.schema.fields:
                    if matched_assignments and \
                            fld.name in matched_assignments:
                        from ..expr.expressions import Cast as _Cast
                        v = matched_assignments[fld.name]
                        ve = (v if isinstance(v, Expression)
                              else Literal(v))
                        exprs.append(_Cast(ve, fld.dtype)
                                     .alias(fld.name))
                    elif matched_assignments is None \
                            and fld.name not in on \
                            and f"__src_{fld.name}" in hit.columns:
                        exprs.append(
                            col_(f"__src_{fld.name}").alias(fld.name))
                    else:
                        exprs.append(col_(fld.name))
                out_at = pa.concat_tables([
                    anti.to_arrow().select(list(tdf.columns)),
                    hit.select(*exprs).to_arrow()])
            else:
                continue
            actions.append(_remove_action(f))
            add = _write_rows(session, out_at, path)
            if add:
                actions.append(add)
        if when_not_matched == "insert":
            target = read_delta(session, path)
            tcols = [fld.name for fld in target.schema.fields]
            missing = [c for c in tcols if c not in src_df.columns]
            if missing:
                raise ValueError(f"merge insert: source lacks {missing}")
            inserts = src_df.join(target.select(*[col_(k) for k in on]),
                                  on=on, how="left_anti")
            add = _write_rows(session,
                              inserts.to_arrow().select(tcols), path)
            if add:
                actions.append(add)
        return actions

    return _commit_dml(table, build, "MERGE")


# ---- OPTIMIZE / auto-compaction / z-order -----------------------------
def _zorder_indices(at, zorder_by: List[str]):
    """Row order by interleaved-bit (Morton) z-value over the given
    numeric columns: each column min-max normalizes to 16 bits, bits
    interleave MSB-first (reference: sql-plugin zorder/ZOrderRules.scala
    + JNI ZOrder interleave_bits)."""
    import numpy as np
    # bits per column capped so the interleaved key fits uint64 (>4
    # z-order columns would otherwise shift the leading columns' high
    # bits out and scramble the curve)
    bits = min(16, 64 // max(1, len(zorder_by)))
    top = float((1 << bits) - 1)
    cols = []
    for name in zorder_by:
        v = at.column(name).to_numpy(zero_copy_only=False).astype(
            np.float64)
        v = np.where(np.isnan(v), 0.0, v)
        lo, hi = float(v.min()), float(v.max())
        span = (hi - lo) or 1.0
        cols.append(((v - lo) / span * top).astype(np.uint64))
    z = np.zeros(at.num_rows, np.uint64)
    for bit in range(bits - 1, -1, -1):
        for c in cols:
            z = (z << np.uint64(1)) | ((c >> np.uint64(bit))
                                       & np.uint64(1))
    return np.argsort(z, kind="stable")


def optimize_delta(session, path: str, zorder_by: Optional[List[str]]
                   = None, target_file_bytes: int = 128 << 20,
                   min_files: int = 2) -> dict:
    """OPTIMIZE: bin-pack small live files into ~target-sized files
    (deletion vectors applied — survivors carry forward, DV files
    retire), optionally z-order clustering rows by interleaved bits.
    One commit, operation OPTIMIZE, dataChange=False (the rewrite
    changes layout, not content — downstream streaming readers skip
    it). Returns {filesRemoved, filesAdded, version} (reference:
    delta-lake GpuOptimizeWriteExchangeExec + zorder/ZOrderRules).

    Auto-compaction (write_delta with
    spark.rapids.tpu.delta.autoCompact.minFiles) calls this after
    appends once the small-file count crosses the threshold."""
    import pyarrow as pa

    table = DeltaTable(path)
    latest = table.latest_version()
    if latest < 0:
        raise FileNotFoundError(f"not a delta table: {path}")

    def plan_groups():
        """Snapshot + grouping — recomputed INSIDE every commit
        attempt: a race-loss retry must not replay remove/rewrite
        actions against a stale snapshot (a concurrent DELETE's
        rewrite would be resurrected)."""
        adds = table.snapshot_adds()
        # z-order rewrites everything; plain compaction only groups of
        # small files (or DV-carrying files, which fold their DVs in)
        if zorder_by:
            return [adds] if adds else []
        small = [a for a in adds
                 if a.get("size", 0) < target_file_bytes // 2
                 or a.get("deletionVector")]
        return [small] if len(small) >= min_files else []

    if not plan_groups():
        return {"filesRemoved": 0, "filesAdded": 0,
                "version": latest}

    def build_actions():
        actions: List[dict] = []
        removed = 0
        added = 0
        for group in plan_groups():
            tabs = []
            for add in group:
                t = _file_df(session, table, add).to_arrow()
                if t.num_rows:
                    tabs.append(t)
                actions.append(_remove_action(add["path"]))
                removed += 1
            if not tabs:
                continue
            at = pa.concat_tables(tabs)
            if zorder_by:
                import pyarrow as _pa
                idx = _zorder_indices(at, zorder_by)
                at = at.take(_pa.array(idx, type=_pa.int64()))
            # slice into ~target-byte output files
            bpr = max(1, at.nbytes // max(at.num_rows, 1))
            rows_per_file = max(1, target_file_bytes // bpr)
            off = 0
            while off < at.num_rows:
                part = at.slice(off, rows_per_file)
                a = _write_rows(session, part, path)
                if a:
                    a["add"]["dataChange"] = False
                    actions.append(a)
                    added += 1
                off += rows_per_file
        build_actions.stats = (removed, added)
        return actions

    v = _commit_dml(table, build_actions, "OPTIMIZE")
    removed, added = build_actions.stats
    return {"filesRemoved": removed, "filesAdded": added, "version": v}


def maybe_auto_compact(session, path: str, conf) -> Optional[dict]:
    """Post-append auto-compaction: when the table has >= minFiles live
    files smaller than half the target, compact them (reference:
    delta auto-compaction / GpuOptimizeWriteExchangeExec)."""
    from ..config import (DELTA_AUTOCOMPACT_MIN_FILES,
                          DELTA_AUTOCOMPACT_TARGET_BYTES)
    min_files = conf.get(DELTA_AUTOCOMPACT_MIN_FILES)
    if min_files <= 0:
        return None
    target = conf.get(DELTA_AUTOCOMPACT_TARGET_BYTES)
    table = DeltaTable(path)
    adds = table.snapshot_adds()
    small = [a for a in adds if a.get("size", 0) < target // 2]
    if len(small) < min_files:
        return None
    return optimize_delta(session, path, target_file_bytes=target,
                          min_files=min_files)
