"""CSV scan (reference: GpuCSVScan.scala:57 over cudf read_csv; here Arrow
C++ host decode feeding device batches — the same host-decode H2D split the
round-1 parquet reader uses)."""
from __future__ import annotations

from typing import Optional


def read_csv_to_arrow(path: str, header: bool = True, schema=None,
                      delimiter: str = ","):
    import pyarrow.csv as pc
    ropts = pc.ReadOptions(autogenerate_column_names=not header)
    popts = pc.ParseOptions(delimiter=delimiter)
    copts = None
    if schema is not None:
        import pyarrow as pa
        arrow_schema = schema.to_arrow() if hasattr(schema, "to_arrow") \
            else schema
        copts = pc.ConvertOptions(column_types={
            f.name: f.type for f in arrow_schema})
    return pc.read_csv(path, read_options=ropts, parse_options=popts,
                       convert_options=copts)


def write_csv(df, path: str, header: bool = True):
    import pyarrow.csv as pc
    at = df.to_arrow()
    pc.write_csv(at, path,
                 write_options=pc.WriteOptions(include_header=header))
