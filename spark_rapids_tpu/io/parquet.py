"""Parquet write (reference: GpuParquetFileFormat.scala:48 +
ColumnarOutputWriter.scala — chunked device->host->file writes with
Spark-compatible output layout: part files + _SUCCESS marker)."""
from __future__ import annotations

import os
from typing import Optional


def write_parquet(df, path: str, mode: str = "overwrite",
                  compression: str = "snappy",
                  row_group_rows: int = 1 << 20):
    import pyarrow as pa
    import pyarrow.parquet as pq

    if os.path.exists(path):
        if mode == "errorifexists":
            raise FileExistsError(path)
        if mode == "overwrite":
            import shutil
            shutil.rmtree(path, ignore_errors=True)
    os.makedirs(path, exist_ok=True)

    root, ctx = df._execute()
    from ..exec.nodes import collect_to_arrow
    # stream partition-by-partition: one part file per physical partition
    import pyarrow as pa
    from ..columnar.column import Column
    from ..utils.transfer import fetch
    import numpy as np
    nparts = root.num_partitions(ctx)
    wrote = 0
    for pid in range(nparts):
        tables = []
        for batch in root.execute_partition(ctx, pid):
            host = fetch([c.device_buffers()
                          for c in batch.table.columns] + [batch.row_mask])
            mask = np.asarray(host[-1])[:batch.num_rows]
            arrs = [Column.arrow_from_host(c.dtype, c.length, b)
                    for c, b in zip(batch.table.columns, host[:-1])]
            at = pa.Table.from_arrays(arrs,
                                      names=list(batch.table.names))
            if not mask.all():
                at = at.filter(pa.array(mask))
            tables.append(at)
        if not tables:
            continue
        at = pa.concat_tables(tables)
        fname = os.path.join(path, f"part-{pid:05d}.parquet")
        pq.write_table(at, fname, compression=compression,
                       row_group_size=row_group_rows)
        wrote += 1
    if wrote == 0:  # empty result still writes schema
        pq.write_table(df.schema.to_arrow().empty_table(),
                       os.path.join(path, "part-00000.parquet"),
                       compression=compression)
    open(os.path.join(path, "_SUCCESS"), "w").close()
    try:
        from ..runtime import result_cache
        result_cache.invalidate_prefix(path)
    except Exception:
        pass
