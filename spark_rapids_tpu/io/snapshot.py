"""Scan snapshot pinning: capture (path, mtime_ns, size) per data file
at scan BIND time and verify it at execute time, so an overwrite
mid-session can never serve stale bytes — the scan either refreshes
(replan picks up the new files) or raises before mixing old and new
data. Delta scans additionally pin the table version. The same
snapshot tuples key the cross-query result cache
(runtime/result_cache.py): a table write changes the snapshot, which
changes every dependent cache key, which is the invalidation.
"""
from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

__all__ = ["scan_snapshot", "snapshot_current", "refresh_plan_snapshots",
           "SnapshotMismatch"]

# one snapshot element per file; (path, None, None) marks a file that
# could not be statted (deleted mid-session) — never equal to a live stat
SnapshotT = Tuple[Tuple[str, Optional[int], Optional[int]], ...]


class SnapshotMismatch(RuntimeError):
    """A scan's pinned file set changed UNDER a running execution (the
    plan-time refresh in DataFrame._execute handles changes between
    actions; this fires only when files mutate mid-query)."""


def scan_snapshot(paths: Sequence[str]) -> SnapshotT:
    """Stat every file once; deterministic order (the caller's)."""
    out = []
    for p in paths:
        try:
            st = os.stat(p)
            out.append((p, st.st_mtime_ns, st.st_size))
        except OSError:
            out.append((p, None, None))
    return tuple(out)


def refresh_plan_snapshots(plan) -> list:
    """Re-stat every file-pinning scan in a logical tree, updating the
    scans' snapshots in place. Returns the list of paths whose files
    changed (empty = everything current). Runs before every action
    (DataFrame._execute): a changed snapshot drops the cached physical
    plan so the replan rebinds against the new files, and the changed
    paths invalidate dependent result-cache entries."""
    changed = []
    stack = [plan]
    seen = set()
    while stack:
        n = stack.pop()
        if id(n) in seen:
            continue
        seen.add(id(n))
        snap = getattr(n, "snapshot", None)
        if snap is not None and getattr(n, "paths", None) is not None:
            cur = scan_snapshot(n.paths)
            if cur != snap:
                n.snapshot = cur
                changed.extend(n.paths)
        stack.extend(getattr(n, "children", ()) or ())
    return changed


def snapshot_current(snapshot: SnapshotT) -> bool:
    """True when every pinned file still has its bind-time mtime+size."""
    for p, mtime_ns, size in snapshot:
        try:
            st = os.stat(p)
        except OSError:
            return False
        if st.st_mtime_ns != mtime_ns or st.st_size != size:
            return False
    return True
