"""Executor worker process.

(reference: RapidsExecutorPlugin, Plugin.scala:610 — init, heartbeat
endpoint, task hooks.) Each executor is a separate OS process that
connects back to the driver, registers, then serves tasks over one
socket while a daemon thread heartbeats on a second. Tasks are pickled
callables returning picklable results (host-side work only — the TPU
client lives in the driver; JAX stays unimported here unless a task
pulls it in, and then it is forced onto the CPU platform).
"""
from __future__ import annotations

import os
import socket
import sys
import threading
import time
import traceback

from .rpc import RpcClosed, recv_msg, send_msg

__all__ = ["executor_main"]

HEARTBEAT_PERIOD_S = 0.5


def _heartbeat_loop(host: str, port: int, exec_id: int, stop):
    try:
        hb = socket.create_connection((host, port))
        send_msg(hb, "hb_register", {"executor": exec_id,
                                     "pid": os.getpid()})
        while not stop.is_set():
            send_msg(hb, "heartbeat", {"executor": exec_id,
                                       "ts": time.time()})
            stop.wait(HEARTBEAT_PERIOD_S)
    except OSError:
        pass  # driver gone; the task loop will exit too


def executor_main(host: str, port: int, exec_id: int) -> None:
    # any accidental JAX usage inside a task must not grab the TPU
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # The env var alone is NOT enough: site packages can override
    # JAX_PLATFORMS and hang backend init on a broken accelerator
    # tunnel. Pin the platform via jax.config before any task runs a
    # query fragment. SRTPU_EXECUTOR_PLATFORM=tpu opts an executor into
    # the real chip on TPU hosts.
    platform = os.environ.get("SRTPU_EXECUTOR_PLATFORM", "cpu")
    try:
        import jax
        jax.config.update("jax_platforms", platform)
    except ImportError:
        pass
    stop = threading.Event()
    t = threading.Thread(target=_heartbeat_loop,
                         args=(host, port, exec_id, stop), daemon=True,
                         name="tpu-exec-hb")
    t.start()
    sock = socket.create_connection((host, port))
    send_msg(sock, "register", {"executor": exec_id, "pid": os.getpid()})
    try:
        while True:
            kind, payload = recv_msg(sock)
            if kind == "shutdown":
                break
            if kind != "task":
                send_msg(sock, "error", {"message": f"bad kind {kind}"})
                continue
            task_id = payload["task_id"]
            try:
                from ..runtime import faults
                if faults.ACTIVE:
                    # executor.task: raise fails the task (reported,
                    # driver-side retry policy applies), kill exits the
                    # PROCESS — the heartbeat/socket loss path marks
                    # this executor lost and requeues its tasks
                    faults.hit("executor.task")
                fn = payload["fn"]
                args = tuple(payload.get("args", ()))
                # tasks submitted with tables=... get them appended as
                # the final positional argument — ALWAYS when the flag
                # is set, so an empty bucket list doesn't change arity
                if payload.get("has_tables"):
                    args = args + (payload.get("_arrow", []),)
                result = fn(*args)
                # metric snapshots the task recorded (fragment op
                # metrics) ride the result frame back to the driver —
                # without this, executor MetricSets die with the process
                from .task_metrics import drain_task_metrics
                tm = drain_task_metrics()
                extra = {"task_metrics": tm} if tm else {}
                from .rpc import ArrowResult
                if isinstance(result, ArrowResult):
                    send_msg(sock, "result",
                             {"task_id": task_id, "value": result.meta,
                              "arrow_result": True, **extra},
                             tables=result.tables)
                else:
                    send_msg(sock, "result", {"task_id": task_id,
                                              "value": result, **extra})
            except BaseException as e:  # report, don't die
                # drain partial metric records so they can't leak into
                # the NEXT task's result frame
                from .task_metrics import drain_task_metrics
                drain_task_metrics()
                payload = {"task_id": task_id, "message": repr(e),
                           "traceback": traceback.format_exc()}
                from ..runtime.faults import InjectedFault
                from .blocks import FetchFailed
                if isinstance(e, FetchFailed):
                    # structured fields survive the wire so the driver
                    # re-raises a typed FetchFailed (lineage targeting
                    # without exception-text parsing)
                    payload["error_fields"] = {
                        "type": "FetchFailed",
                        "addr": list(e.addr) if e.addr else None,
                        "shuffle_id": e.shuffle_id}
                elif isinstance(e, InjectedFault):
                    # ditto for injections: the driver rebuilds the
                    # type so transient-error classification survives
                    # the process boundary
                    payload["error_fields"] = {
                        "type": "InjectedFault", "point": e.point}
                send_msg(sock, "error", payload)
    except RpcClosed:
        pass
    finally:
        stop.set()


if __name__ == "__main__":
    executor_main(sys.argv[1], int(sys.argv[2]), int(sys.argv[3]))
