"""Driver/executor cluster runtime.

(reference: Plugin.scala — RapidsDriverPlugin :463 / RapidsExecutorPlugin
:610, driver<->executor RPC :469-504, shuffle heartbeats
RapidsShuffleHeartbeatManager.scala:33.) TPU-first shape: one tunneled
TPU client lives in the DRIVER process (libtpu is single-client), so
executors supply host-side parallelism — parquet/text decode, shuffle
file IO — and ship Arrow IPC bytes back; device work stays with the
driver's chip. Liveness is heartbeat-based with task re-execution on
executor loss (the lineage/retry model of §5.3).
"""
from .driver import ClusterManager, ExecutorLostError  # noqa: F401
