"""Distributed query execution: THE unified cluster + mesh topology.

This is the engine's SF3K-scale story (VERDICT r3 missing: "two
distributed stories, unconnected"), mirroring how the reference runs on
a multi-host GPU cluster (UCX/netty shuffle between hosts,
NVLink/shared-HBM within a host — RapidsShuffleInternalManagerBase.scala:56):

  Level 1 (DCN / between hosts): executor PROCESSES each run whole plan
  fragments (scan -> filters/joins -> partial aggregation -> hash
  partition) over their input split, then ship the resulting shuffle
  blocks as **Arrow-IPC frames over the cluster RPC** (cluster/rpc.py)
  — columnar bytes never go through pickle.

  Level 2 (ICI / within a host): a fragment executing inside one
  executor uses that executor's `jax.sharding.Mesh` — the streaming
  collective exchange (exec/mesh_exchange.py) — when its session sets
  `spark.rapids.tpu.mesh.devices`. Nothing about the fragment changes:
  the planner routes its internal exchanges over the mesh.

The two-stage model (map fragments -> Arrow shuffle -> reduce fragments
-> optional driver-side final) matches Spark's stage DAG at exchange
boundaries. Map and reduce fragments are ordinary DataFrame programs
built by picklable module-level functions — the same closure-shipping
model the reference inherits from Spark.

Fault tolerance: fragments are idempotent (deterministic over their
split), so the ClusterManager's lost-executor requeue (§5.3 lineage
re-execution) covers them; results land exactly once per stage because
the driver keys buckets by reduce-partition id.
"""
from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional, Sequence

from .driver import ClusterManager
from .rpc import ArrowResult

__all__ = ["DistributedRunner", "map_fragment_task", "reduce_fragment_task"]


@contextmanager
def _task_trace(conf, name: str, **attrs):
    """Executor-side task scope: adopt the trace context the driver
    injected into this task frame's conf dict, run the task body under
    a `task` span, and ship every span the task recorded home on the
    task-metric side channel. Drained with close=False — later tasks of
    the same query in this executor keep accumulating under the same
    trace. No-op when the driver ran untraced."""
    from ..profiler import tracing
    tc = tracing.adopt_from_conf(conf)
    if tc is None:
        yield
        return
    sp = tracing.open_span(name, "task", tc, **attrs)
    try:
        with tracing.use(tracing.TraceContext(tc.trace_id, sp.span_id,
                                              True)):
            yield
    finally:
        sp.end()
        try:
            from .task_metrics import record_task_metrics
            spans = tracing.drain_trace(tc.trace_id, close=False)
            if spans:
                record_task_metrics({"spans": spans})
        except Exception:
            pass


def _record_fragment_profile(root, ctx, stage: str, **extra):
    """Snapshot this fragment's physical plan + per-operator metrics
    into the task-metric side channel (task_metrics.py). Keys are
    lore ids — stable for the same fragment plan in every executor
    process, unlike the id()-based _op_ids — so the driver can sum
    across executors. Profiling must never fail a query."""
    try:
        from ..memory import diagnostics
        from ..profiler.event_log import op_metrics_records, plan_tree
        from .task_metrics import record_task_metrics
        record_task_metrics({
            "stage": stage,
            "plan": plan_tree(root),
            "ops": op_metrics_records(root, ctx.metrics,
                                      ctx.metrics_level),
            "watermarks": diagnostics.watermarks_snapshot(),
            **extra})
    except Exception:
        pass


def map_fragment_task(map_fn, split, conf, n_reduce: int,
                      part_keys: Sequence[str], shuffle_id: str = None,
                      map_id: int = 0):
    """Executor-side map stage: build + run the fragment over this
    split, hash-partition its output into n_reduce buckets. With a
    shuffle_id (P2P mode, the default runner path), buckets park in
    this executor's local block store and only METADATA returns —
    the reference's map-output-tracker shape
    (RapidsShuffleInternalManagerBase.scala:56). Without one (legacy),
    buckets ride back to the driver as Arrow tables."""
    import pyarrow as pa

    import spark_rapids_tpu as st
    from ..exec.nodes import _batch_to_arrow

    with _task_trace(conf, "task.map", map_id=map_id):
        s = st.TpuSession(conf)
        df = map_fn(s, split)
        df = df.repartition(n_reduce, *part_keys)
        root, ctx = df._execute()
        pids: List[int] = []
        tables = []
        for pid in range(root.num_partitions(ctx)):
            parts = [_batch_to_arrow(b)
                     for b in root.execute_partition(ctx, pid)]
            parts = [p for p in parts if p.num_rows]
            if parts:
                pids.append(pid)
                tables.append(pa.concat_tables(parts))
        _record_fragment_profile(root, ctx, "map", map_id=map_id)
        if shuffle_id is None:
            return ArrowResult({"pids": pids}, tables)
        from . import blocks
        from ..config import CLUSTER_BLOCK_ADVERTISE_HOST
        addr = blocks.ensure_server(
            s.conf.get(CLUSTER_BLOCK_ADVERTISE_HOST))
        st_ = blocks.store()
        sizes = {}
        for pid, t in zip(pids, tables):
            sizes[pid] = st_.put(shuffle_id, map_id, pid, t)
        return {"pids": pids, "sizes": sizes, "addr": addr,
                "map_id": map_id}


def _run_reduce_fragment(reduce_fn, conf, tables, pid):
    """Shared reduce-fragment body: concat the bucket's blocks, run the
    fragment via the execution internals (not DataFrame.to_arrow, which
    would open a session-level event log IN the executor — the driver
    owns the query's log), snapshot its metrics for the driver."""
    import pyarrow as pa

    import spark_rapids_tpu as st
    from ..exec.nodes import collect_to_arrow

    s = st.TpuSession(conf)
    at = pa.concat_tables(tables)
    df = reduce_fn(s, s.create_dataframe(at))
    root, ctx = df._execute()
    try:
        out = collect_to_arrow(root, ctx)
    finally:
        ctx.close()
    _record_fragment_profile(root, ctx, "reduce", reduce_pid=pid)
    return out


def reduce_fragment_task(reduce_fn, conf, tables):
    """Executor-side reduce stage: concatenate this bucket's shuffle
    blocks into a DataFrame, run the reduce fragment, return its result
    as one Arrow table."""
    with _task_trace(conf, "task.reduce"):
        return ArrowResult({}, [_run_reduce_fragment(reduce_fn, conf,
                                                     tables, None)])


def reduce_fetch_task(reduce_fn, conf, shuffle_id: str, pid: int,
                      sources):
    """Executor-side reduce stage (P2P): fetch this partition's blocks
    DIRECTLY from the mapper executors' block servers (transient fetch
    failures retry with bounded backoff per sql.shuffle.fetch.*), then
    run the reduce fragment. `sources` = [(addr, [map_id, ...]), ...]."""
    from ..config import FETCH_RETRY_MAX, FETCH_RETRY_WAIT_MS, TpuConf
    from . import blocks

    tc = TpuConf(conf)
    max_retries = int(tc.get(FETCH_RETRY_MAX))
    wait_ms = float(tc.get(FETCH_RETRY_WAIT_MS))
    with _task_trace(conf, "task.reduce", reduce_pid=pid):
        tables = []
        fetched_bytes = 0
        fstats: dict = {}
        for addr, map_ids in sources:
            got = blocks.fetch_blocks(addr, shuffle_id, map_ids, pid,
                                      max_retries=max_retries,
                                      wait_ms=wait_ms, stats=fstats)
            fetched_bytes += sum(t.nbytes for t in got)
            tables.extend(got)
        out = _run_reduce_fragment(reduce_fn, conf, tables, pid)
        try:
            from .task_metrics import record_task_metrics
            record_task_metrics({"stage": "reduce", "reduce_pid": pid,
                                 "fetch_bytes": fetched_bytes,
                                 **fstats})
        except Exception:
            pass
        return ArrowResult({}, [out])


class DistributedRunner:
    """Run two-stage distributed queries over a ClusterManager.

    `map_fn(session, split) -> DataFrame` and
    `reduce_fn(session, DataFrame) -> DataFrame` must be picklable
    (module-level functions / functools.partial).
    """

    def __init__(self, cm: ClusterManager, conf: Optional[dict] = None):
        self.cm = cm
        self.conf = dict(conf or {})
        # driver-side aggregation of the executor MetricSet snapshots
        # that ride back with task results; shape:
        # {"query_id", "stages": {stage: {"plan", "ops", "tasks",
        #  "wall_s", "watermarks"}}} — rendered by explain_analyze()
        self.last_profile: Dict[str, object] = {}
        self.last_event_log: Optional[str] = None

    # -- driver-side metric aggregation --------------------------------
    def _absorb(self, fut, stages: Dict[str, dict]):
        """Fold one task's shipped metric records into the per-stage
        accumulators (plan kept from the first task; op records
        concatenated for a later lore-keyed merge)."""
        for rec in getattr(fut, "task_metrics", None) or []:
            spans = rec.pop("spans", None)
            if spans:
                # executor-side trace spans come home on the same side
                # channel; re-buffer them under the query's trace so
                # the close-out drain assembles ONE per-query trace
                from ..profiler import tracing
                tracing.absorb_spans(spans)
                if not rec:
                    continue
            acc = stages.setdefault(rec.get("stage") or "map", {
                "plan": None, "ops": [], "tasks": 0, "wall_s": 0.0,
                "watermarks": {}, "fetch_bytes": 0})
            if rec.get("plan") is not None:
                acc["tasks"] += 1
                if acc["plan"] is None:
                    acc["plan"] = rec["plan"]
            acc["ops"].extend(rec.get("ops") or [])
            acc["fetch_bytes"] += rec.get("fetch_bytes") or 0
            # transport-level fetch retry accounting (blocks.py backoff
            # loop): total backoff ms -> the stage's fetchRetryMs
            # metric; per-attempt records -> driver fetch_retry events
            if rec.get("fetch_retry_ms"):
                acc["fetchRetryMs"] = round(
                    acc.get("fetchRetryMs", 0.0)
                    + float(rec["fetch_retry_ms"]), 3)
            if rec.get("fetch_attempts"):
                acc.setdefault("fetch_attempts", []).extend(
                    rec["fetch_attempts"])
            for k, v in (rec.get("watermarks") or {}).items():
                if isinstance(v, (int, float)):
                    acc["watermarks"][k] = max(
                        acc["watermarks"].get(k, 0), v)

    def run(self, splits: Sequence, map_fn: Callable,
            part_keys: Sequence[str], reduce_fn: Callable,
            n_reduce: Optional[int] = None,
            final_fn: Optional[Callable] = None,
            token=None):
        """Execute map fragments over `splits`, peer-to-peer shuffle on
        `part_keys` into `n_reduce` buckets, run reduce fragments, and
        (optionally) a driver-side final fragment over the concatenated
        reduce outputs. Returns a pyarrow Table.

        P2P topology (RapidsShuffleInternalManagerBase.scala:56 /
        RapidsShuffleTransport.scala:44 analog): map outputs stay on
        the mapper executors (cluster/blocks.py); the driver moves only
        block METADATA {pid -> (addr, sizes)}; reducers fetch blocks
        directly from mappers. A reduce whose fetch fails (dead mapper
        / evicted shuffle) triggers lineage re-execution of the
        affected map splits, then one reduce retry.

        `token` (a service CancelToken) makes the run cancellable at
        fragment boundaries: the driver polls it before dispatching
        each stage and between fragment results; on trip it drains the
        query's pending tasks (cancel_tag) and drops in-flight results,
        so executors finish their current fragment but no new work
        starts and nothing resolves back to the caller."""
        import uuid

        import pyarrow as pa

        import spark_rapids_tpu as st

        from ..config import SHUFFLE_MAX_REGENERATIONS, TpuConf
        from ..profiler import event_log as EL
        from ..profiler import tracing
        from ..runtime.faults import note_recovery
        from .blocks import FetchFailed, drop_shuffle
        from .driver import ExecutorLostError

        n_reduce = n_reduce or max(len(self.cm.alive_executors), 1)
        shuffle_id = uuid.uuid4().hex[:12]

        # driver-side query event log (the Spark event-log analog for
        # the distributed topology): stage submit/complete, aggregated
        # executor op metrics, fetch retries
        qid = EL.next_query_id("dist")
        w = EL.open_query_log(TpuConf(self.conf), qid)
        self.last_event_log = w.path if w is not None else None
        stages: Dict[str, dict] = {}
        self.last_profile = {"query_id": qid, "stages": stages}
        t_query = time.perf_counter()

        # one trace for the whole distributed query: driver stage spans
        # parent the executor task spans (context rides the conf dict in
        # every task frame; spans come home with task metrics)
        tc = tracing.start_trace(qid, TpuConf(self.conf))
        # tpulint: allow[span-leak] query root span: ended by tracing.finish() in run()'s trace close-out finally
        rsp = (tracing.open_span("query", "query", tc,
                                 action="distributed_run")
               if tc is not None else None)
        qtc = (tracing.TraceContext(qid, rsp.span_id, True)
               if tc is not None else None)

        def emit(event, **kw):
            if w is not None:
                w.emit(event, **kw)

        def check():
            # cooperative cancel checkpoint at fragment boundaries
            if token is not None:
                token.check()

        def submit_map(i, cnf):
            return self.cm.submit(
                map_fragment_task, map_fn, splits[i], cnf,
                n_reduce, list(part_keys), shuffle_id, i, tag=qid)

        def run_maps(idxs, attempt=0):
            from ..runtime.faults import is_transient_error
            from .driver import MAX_TASK_RETRIES
            check()
            emit("stage_submit", stage="map", n_tasks=len(idxs),
                 attempt=attempt)
            t0 = time.perf_counter()
            with tracing.span("stage.map", "stage", qtc,
                              attempt=attempt, n_tasks=len(idxs)):
                cnf = (tracing.inject_into_conf(self.conf,
                                                tracing.current())
                       if qtc is not None else self.conf)
                pending = [(i, submit_map(i, cnf)) for i in idxs]
                out, tries = {}, {}
                while pending:
                    i, f = pending.pop(0)
                    check()
                    try:
                        out[i] = f.result()
                    except Exception as e:
                        # idempotent map fragments: a TRANSIENT in-task
                        # failure (injected fault, lost executor
                        # mid-run) is resubmitted — possibly landing on
                        # another executor — up to the task-retry budget
                        tries[i] = tries.get(i, 0) + 1
                        if not is_transient_error(e) \
                                or tries[i] > MAX_TASK_RETRIES:
                            raise
                        emit("task_retry", stage="map", split=i,
                             attempt=tries[i], error=repr(e))
                        pending.append((i, submit_map(i, cnf)))
                        continue
                    self._absorb(f, stages)
            wall = time.perf_counter() - t0
            stages.setdefault("map", {}).setdefault("wall_s", 0.0)
            stages["map"]["wall_s"] = stages["map"].get("wall_s",
                                                        0.0) + wall
            emit("stage_complete", stage="map", n_tasks=len(idxs),
                 attempt=attempt, wall_s=round(wall, 6),
                 shuffle_bytes=sum(sum(m2["sizes"].values())
                                   for m2 in out.values()))
            return out

        status, err = "ok", None
        emit("query_start", action="distributed_run",
             n_splits=len(splits), n_reduce=n_reduce,
             shuffle_id=shuffle_id)
        try:
            metas = run_maps(range(len(splits)))
            done: Dict[int, object] = {}     # pid -> reduce output table

            # lineage-based regeneration budget: each round re-executes
            # ONLY the lost map partitions on surviving executors, then
            # retries the missing reduces (sql.shuffle.maxRegenerations)
            max_regen = int(TpuConf(self.conf).get(
                SHUFFLE_MAX_REGENERATIONS))
            try:
                for attempt in range(max_regen + 1):
                    check()
                    # per-pid fetch plan: mapper addr -> map ids that
                    # produced blocks for that pid
                    all_pids = sorted({p for m2 in metas.values()
                                       for p in m2["pids"]})
                    t0 = time.perf_counter()
                    with tracing.span("stage.reduce", "stage", qtc,
                                      attempt=attempt):
                        rcnf = (tracing.inject_into_conf(
                            self.conf, tracing.current())
                            if qtc is not None else self.conf)
                        rfuts = []
                        for pid in all_pids:
                          if pid in done:      # keep completed partitions
                              continue
                          by_addr: Dict[tuple, List[int]] = {}
                          for i, m2 in metas.items():
                              if pid in m2["pids"]:
                                  by_addr.setdefault(
                                      tuple(m2["addr"]),
                                      []).append(m2["map_id"])
                          sources = [(list(a), ids)
                                     for a, ids in sorted(by_addr.items())]
                          rfuts.append((pid, self.cm.submit(
                              reduce_fetch_task, reduce_fn, rcnf,
                              shuffle_id, pid, sources, tag=qid)))
                        emit("stage_submit", stage="reduce",
                             n_tasks=len(rfuts), attempt=attempt)
                        refetch = set()
                        retry_only = False
                        for pid, f in rfuts:
                          check()
                          try:
                              done[pid] = f.result().tables[0]
                              self._absorb(f, stages)
                          except (FetchFailed, ExecutorLostError) as e:
                              emit("fetch_retry", stage="reduce", pid=pid,
                                   shuffle_id=shuffle_id,
                                   addr=list(e.addr)
                                   if getattr(e, "addr", None) else None,
                                   attempt=attempt, error=repr(e))
                              if attempt >= max_regen:
                                  raise
                              # lineage: re-execute the map splits of the
                              # FAILED mapper, identified by the typed
                              # exception's structured addr (idempotent
                              # fragments); an addr-less failure — or an
                              # executor lost outright — re-executes
                              # everything still unreduced
                              dead = set()
                              addr = getattr(e, "addr", None)
                              if addr is not None:
                                  dead = {i for i, m2 in metas.items()
                                          if tuple(m2["addr"]) == addr}
                              refetch |= dead or set(metas)
                          except Exception as e:
                              # TRANSIENT in-task reduce failure (injected
                              # fault): the shuffle blocks are still
                              # parked, so retry JUST this partition next
                              # round — no map regeneration needed
                              from ..runtime.faults import \
                                  is_transient_error
                              if not is_transient_error(e) \
                                      or attempt >= max_regen:
                                  raise
                              emit("task_retry", stage="reduce", pid=pid,
                                   attempt=attempt, error=repr(e))
                              retry_only = True
                    # executor-side transport retries that SUCCEEDED
                    # ride back in task metrics: surface each attempt
                    # as its own driver-log event
                    racc = stages.get("reduce") or {}
                    for rec in racc.pop("fetch_attempts", []):
                        emit("fetch_retry", stage="reduce",
                             shuffle_id=shuffle_id, **rec)
                    wall = time.perf_counter() - t0
                    if "reduce" in stages:
                        stages["reduce"]["wall_s"] = \
                            stages["reduce"].get("wall_s", 0.0) + wall
                    emit("stage_complete", stage="reduce",
                         attempt=attempt, wall_s=round(wall, 6))
                    if not refetch and not retry_only:
                        break
                    if refetch:
                        lost = sorted(refetch)
                        note_recovery("regenerations", len(lost))
                        emit("shuffle_regeneration",
                             shuffle_id=shuffle_id, map_ids=lost,
                             attempt=attempt + 1,
                             survivors=len(self.cm.alive_executors))
                        metas.update(run_maps(lost,
                                              attempt=attempt + 1))
            finally:
                # the shuffle's blocks are pinned on the mappers (the
                # MAX_SHUFFLES LRU never evicts in-flight shuffles); drop
                # them explicitly now the query is done (best-effort —
                # a dead mapper's files died with its temp dir)
                for addr in {tuple(m2["addr"]) for m2 in metas.values()}:
                    drop_shuffle(addr, shuffle_id)
            if not done:
                return None
            result = pa.concat_tables([done[p] for p in sorted(done)])
            if final_fn is not None:
                s = st.TpuSession(self.conf)
                result = final_fn(s,
                                  s.create_dataframe(result)).to_arrow()
            return result
        except BaseException as e:
            status, err = "error", repr(e)
            # drain this query's pending fragments and drop in-flight
            # results so a cancelled/failed run leaves the cluster idle
            try:
                self.cm.cancel_tag(qid)
            except Exception:
                pass
            raise
        finally:
            # merge each stage's op records lore-keyed (stable across
            # executors) and close out the event log
            for name, acc in stages.items():
                acc["ops"] = EL.aggregate_ops(acc.get("ops") or [])
                emit("op_metrics", stage=name,
                     ops=list(acc["ops"].values()))
                if acc.get("watermarks"):
                    emit("watermarks", stage=name, **acc["watermarks"])
            # close out the trace: end the root span, drain the
            # assembled driver+executor spans into trace_span records
            # and reduce them to critical-path shares
            if rsp is not None:
                try:
                    import types
                    shim = types.SimpleNamespace(trace=tc,
                                                 _root_span=rsp)
                    for s2 in tracing.finish(
                            shim, time.perf_counter() - t_query):
                        emit("trace_span", **s2)
                    summ = getattr(shim, "trace_summary", None)
                    if summ is not None:
                        self.last_profile["trace_summary"] = summ
                        emit("trace_summary", **summ)
                except Exception:
                    pass
            end = {"status": status,
                   "wall_s": round(time.perf_counter() - t_query, 6)}
            if err is not None:
                end["error"] = err
            emit("query_end", **end)
            if w is not None:
                w.close()

    def explain_analyze(self) -> str:
        """Render the last run()'s stages as annotated plan trees (the
        EXPLAIN ANALYZE surface for the distributed topology): each
        stage's fragment plan with per-operator rows/batches/op-time
        summed across every executor that ran it."""
        from ..profiler.analyze import render_analyze
        prof = self.last_profile or {}
        parts = []
        summ = prof.get("trace_summary")
        if summ:
            tops = ", ".join(
                f"{c}:{p:.0f}%"
                for c, p in sorted(summ["share_pct"].items(),
                                   key=lambda kv: -kv[1]) if p >= 1.0)
            parts.append(f"criticalPath={summ['dominant']} [{tops}]")
        for name in ("map", "reduce"):
            acc = (prof.get("stages") or {}).get(name)
            if not acc or not acc.get("plan"):
                continue
            ops = acc.get("ops") or {}
            if isinstance(ops, list):    # pre-aggregation shape
                from ..profiler.event_log import aggregate_ops
                ops = aggregate_ops(ops)
            by_lore = {v["lore_id"]: v["metrics"] for v in ops.values()}
            wall = acc.get("wall_s", 0.0)
            parts.append(f"== {name} stage: {acc.get('tasks', 0)} tasks,"
                         f" wall {wall * 1e3:.0f}ms ==")
            parts.append(render_analyze(acc["plan"], by_lore))
        if not parts:
            return ("no profile collected (run() a query first; "
                    "executor metric snapshots ride task results)")
        text = "\n".join(parts)
        print(text)
        return text
