"""Distributed query execution: THE unified cluster + mesh topology.

This is the engine's SF3K-scale story (VERDICT r3 missing: "two
distributed stories, unconnected"), mirroring how the reference runs on
a multi-host GPU cluster (UCX/netty shuffle between hosts,
NVLink/shared-HBM within a host — RapidsShuffleInternalManagerBase.scala:56):

  Level 1 (DCN / between hosts): executor PROCESSES each run whole plan
  fragments (scan -> filters/joins -> partial aggregation -> hash
  partition) over their input split, then ship the resulting shuffle
  blocks as **Arrow-IPC frames over the cluster RPC** (cluster/rpc.py)
  — columnar bytes never go through pickle.

  Level 2 (ICI / within a host): a fragment executing inside one
  executor uses that executor's `jax.sharding.Mesh` — the streaming
  collective exchange (exec/mesh_exchange.py) — when its session sets
  `spark.rapids.tpu.mesh.devices`. Nothing about the fragment changes:
  the planner routes its internal exchanges over the mesh.

The two-stage model (map fragments -> Arrow shuffle -> reduce fragments
-> optional driver-side final) matches Spark's stage DAG at exchange
boundaries. Map and reduce fragments are ordinary DataFrame programs
built by picklable module-level functions — the same closure-shipping
model the reference inherits from Spark.

Fault tolerance: fragments are idempotent (deterministic over their
split), so the ClusterManager's lost-executor requeue (§5.3 lineage
re-execution) covers them; results land exactly once per stage because
the driver keys buckets by reduce-partition id.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

from .driver import ClusterManager
from .rpc import ArrowResult

__all__ = ["DistributedRunner", "map_fragment_task", "reduce_fragment_task"]


def map_fragment_task(map_fn, split, conf, n_reduce: int,
                      part_keys: Sequence[str], shuffle_id: str = None,
                      map_id: int = 0):
    """Executor-side map stage: build + run the fragment over this
    split, hash-partition its output into n_reduce buckets. With a
    shuffle_id (P2P mode, the default runner path), buckets park in
    this executor's local block store and only METADATA returns —
    the reference's map-output-tracker shape
    (RapidsShuffleInternalManagerBase.scala:56). Without one (legacy),
    buckets ride back to the driver as Arrow tables."""
    import pyarrow as pa

    import spark_rapids_tpu as st
    from ..exec.nodes import _batch_to_arrow

    s = st.TpuSession(conf)
    df = map_fn(s, split)
    df = df.repartition(n_reduce, *part_keys)
    root, ctx = df._execute()
    pids: List[int] = []
    tables = []
    for pid in range(root.num_partitions(ctx)):
        parts = [_batch_to_arrow(b)
                 for b in root.execute_partition(ctx, pid)]
        parts = [p for p in parts if p.num_rows]
        if parts:
            pids.append(pid)
            tables.append(pa.concat_tables(parts))
    if shuffle_id is None:
        return ArrowResult({"pids": pids}, tables)
    from . import blocks
    addr = blocks.ensure_server()
    st_ = blocks.store()
    sizes = {}
    for pid, t in zip(pids, tables):
        sizes[pid] = st_.put(shuffle_id, map_id, pid, t)
    return {"pids": pids, "sizes": sizes, "addr": addr,
            "map_id": map_id}


def reduce_fragment_task(reduce_fn, conf, tables):
    """Executor-side reduce stage: concatenate this bucket's shuffle
    blocks into a DataFrame, run the reduce fragment, return its result
    as one Arrow table."""
    import pyarrow as pa

    import spark_rapids_tpu as st

    s = st.TpuSession(conf)
    at = pa.concat_tables(tables)
    out = reduce_fn(s, s.create_dataframe(at)).to_arrow()
    return ArrowResult({}, [out])


def reduce_fetch_task(reduce_fn, conf, shuffle_id: str, pid: int,
                      sources):
    """Executor-side reduce stage (P2P): fetch this partition's blocks
    DIRECTLY from the mapper executors' block servers, then run the
    reduce fragment. `sources` = [(addr, [map_id, ...]), ...]."""
    import pyarrow as pa

    import spark_rapids_tpu as st
    from . import blocks

    tables = []
    for addr, map_ids in sources:
        tables.extend(blocks.fetch_blocks(addr, shuffle_id, map_ids,
                                          pid))
    s = st.TpuSession(conf)
    at = pa.concat_tables(tables)
    out = reduce_fn(s, s.create_dataframe(at)).to_arrow()
    return ArrowResult({}, [out])


class DistributedRunner:
    """Run two-stage distributed queries over a ClusterManager.

    `map_fn(session, split) -> DataFrame` and
    `reduce_fn(session, DataFrame) -> DataFrame` must be picklable
    (module-level functions / functools.partial).
    """

    def __init__(self, cm: ClusterManager, conf: Optional[dict] = None):
        self.cm = cm
        self.conf = dict(conf or {})

    def run(self, splits: Sequence, map_fn: Callable,
            part_keys: Sequence[str], reduce_fn: Callable,
            n_reduce: Optional[int] = None,
            final_fn: Optional[Callable] = None):
        """Execute map fragments over `splits`, peer-to-peer shuffle on
        `part_keys` into `n_reduce` buckets, run reduce fragments, and
        (optionally) a driver-side final fragment over the concatenated
        reduce outputs. Returns a pyarrow Table.

        P2P topology (RapidsShuffleInternalManagerBase.scala:56 /
        RapidsShuffleTransport.scala:44 analog): map outputs stay on
        the mapper executors (cluster/blocks.py); the driver moves only
        block METADATA {pid -> (addr, sizes)}; reducers fetch blocks
        directly from mappers. A reduce whose fetch fails (dead mapper
        / evicted shuffle) triggers lineage re-execution of the
        affected map splits, then one reduce retry."""
        import uuid

        import pyarrow as pa

        import spark_rapids_tpu as st

        from .blocks import FetchFailed, drop_shuffle

        n_reduce = n_reduce or max(len(self.cm.alive_executors), 1)
        shuffle_id = uuid.uuid4().hex[:12]

        def run_maps(idxs):
            futs = {i: self.cm.submit(
                map_fragment_task, map_fn, splits[i], self.conf,
                n_reduce, list(part_keys), shuffle_id, i)
                for i in idxs}
            return {i: f.result() for i, f in futs.items()}

        metas = run_maps(range(len(splits)))
        done: Dict[int, object] = {}     # pid -> reduce output table

        try:
            for attempt in range(3):
                # per-pid fetch plan: mapper addr -> map ids that
                # produced blocks for that pid
                all_pids = sorted({p for m2 in metas.values()
                                   for p in m2["pids"]})
                rfuts = []
                for pid in all_pids:
                    if pid in done:      # keep completed partitions
                        continue
                    by_addr: Dict[tuple, List[int]] = {}
                    for i, m2 in metas.items():
                        if pid in m2["pids"]:
                            by_addr.setdefault(tuple(m2["addr"]),
                                               []).append(m2["map_id"])
                    sources = [(list(a), ids)
                               for a, ids in sorted(by_addr.items())]
                    rfuts.append((pid, self.cm.submit(
                        reduce_fetch_task, reduce_fn, self.conf,
                        shuffle_id, pid, sources)))
                refetch = set()
                for pid, f in rfuts:
                    try:
                        done[pid] = f.result().tables[0]
                    except FetchFailed as e:
                        if attempt == 2:
                            raise
                        # lineage: re-execute the map splits of the
                        # FAILED mapper, identified by the typed
                        # exception's structured addr (idempotent
                        # fragments); an addr-less failure re-executes
                        # everything
                        dead = set()
                        if e.addr is not None:
                            dead = {i for i, m2 in metas.items()
                                    if tuple(m2["addr"]) == e.addr}
                        refetch |= dead or set(metas)
                if not refetch:
                    break
                metas.update(run_maps(sorted(refetch)))
        finally:
            # the shuffle's blocks are pinned on the mappers (the
            # MAX_SHUFFLES LRU never evicts in-flight shuffles); drop
            # them explicitly now the query is done (best-effort —
            # a dead mapper's files died with its temp dir)
            for addr in {tuple(m2["addr"]) for m2 in metas.values()}:
                drop_shuffle(addr, shuffle_id)
        if not done:
            return None
        result = pa.concat_tables([done[p] for p in sorted(done)])
        if final_fn is not None:
            s = st.TpuSession(self.conf)
            result = final_fn(s, s.create_dataframe(result)).to_arrow()
        return result
