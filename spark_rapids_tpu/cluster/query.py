"""Distributed query execution: THE unified cluster + mesh topology.

This is the engine's SF3K-scale story (VERDICT r3 missing: "two
distributed stories, unconnected"), mirroring how the reference runs on
a multi-host GPU cluster (UCX/netty shuffle between hosts,
NVLink/shared-HBM within a host — RapidsShuffleInternalManagerBase.scala:56):

  Level 1 (DCN / between hosts): executor PROCESSES each run whole plan
  fragments (scan -> filters/joins -> partial aggregation -> hash
  partition) over their input split, then ship the resulting shuffle
  blocks as **Arrow-IPC frames over the cluster RPC** (cluster/rpc.py)
  — columnar bytes never go through pickle.

  Level 2 (ICI / within a host): a fragment executing inside one
  executor uses that executor's `jax.sharding.Mesh` — the streaming
  collective exchange (exec/mesh_exchange.py) — when its session sets
  `spark.rapids.tpu.mesh.devices`. Nothing about the fragment changes:
  the planner routes its internal exchanges over the mesh.

The two-stage model (map fragments -> Arrow shuffle -> reduce fragments
-> optional driver-side final) matches Spark's stage DAG at exchange
boundaries. Map and reduce fragments are ordinary DataFrame programs
built by picklable module-level functions — the same closure-shipping
model the reference inherits from Spark.

Fault tolerance: fragments are idempotent (deterministic over their
split), so the ClusterManager's lost-executor requeue (§5.3 lineage
re-execution) covers them; results land exactly once per stage because
the driver keys buckets by reduce-partition id.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

from .driver import ClusterManager
from .rpc import ArrowResult

__all__ = ["DistributedRunner", "map_fragment_task", "reduce_fragment_task"]


def map_fragment_task(map_fn, split, conf, n_reduce: int,
                      part_keys: Sequence[str]):
    """Executor-side map stage: build + run the fragment over this
    split, hash-partition its output into n_reduce buckets, return the
    non-empty buckets as Arrow tables (shuffle blocks)."""
    import pyarrow as pa

    import spark_rapids_tpu as st
    from ..exec.nodes import _batch_to_arrow

    s = st.TpuSession(conf)
    df = map_fn(s, split)
    df = df.repartition(n_reduce, *part_keys)
    root, ctx = df._execute()
    pids: List[int] = []
    tables = []
    for pid in range(root.num_partitions(ctx)):
        parts = [_batch_to_arrow(b)
                 for b in root.execute_partition(ctx, pid)]
        parts = [p for p in parts if p.num_rows]
        if parts:
            pids.append(pid)
            tables.append(pa.concat_tables(parts))
    return ArrowResult({"pids": pids}, tables)


def reduce_fragment_task(reduce_fn, conf, tables):
    """Executor-side reduce stage: concatenate this bucket's shuffle
    blocks into a DataFrame, run the reduce fragment, return its result
    as one Arrow table."""
    import pyarrow as pa

    import spark_rapids_tpu as st

    s = st.TpuSession(conf)
    at = pa.concat_tables(tables)
    out = reduce_fn(s, s.create_dataframe(at)).to_arrow()
    return ArrowResult({}, [out])


class DistributedRunner:
    """Run two-stage distributed queries over a ClusterManager.

    `map_fn(session, split) -> DataFrame` and
    `reduce_fn(session, DataFrame) -> DataFrame` must be picklable
    (module-level functions / functools.partial).
    """

    def __init__(self, cm: ClusterManager, conf: Optional[dict] = None):
        self.cm = cm
        self.conf = dict(conf or {})

    def run(self, splits: Sequence, map_fn: Callable,
            part_keys: Sequence[str], reduce_fn: Callable,
            n_reduce: Optional[int] = None,
            final_fn: Optional[Callable] = None):
        """Execute map fragments over `splits`, Arrow-shuffle on
        `part_keys` into `n_reduce` buckets, run reduce fragments, and
        (optionally) a driver-side final fragment over the concatenated
        reduce outputs. Returns a pyarrow Table."""
        import pyarrow as pa

        import spark_rapids_tpu as st

        n_reduce = n_reduce or max(len(self.cm.alive_executors), 1)
        futs = [self.cm.submit(map_fragment_task, map_fn, sp, self.conf,
                               n_reduce, list(part_keys))
                for sp in splits]
        buckets: Dict[int, List] = {}
        for f in futs:
            res = f.result()
            for pid, t in zip(res.meta["pids"], res.tables):
                buckets.setdefault(pid, []).append(t)

        rfuts = [(pid, self.cm.submit(reduce_fragment_task, reduce_fn,
                                      self.conf, tables=tabs))
                 for pid, tabs in sorted(buckets.items())]
        outs = [f.result().tables[0] for _, f in rfuts]
        if not outs:
            return None
        result = pa.concat_tables(outs)
        if final_fn is not None:
            s = st.TpuSession(self.conf)
            result = final_fn(s, s.create_dataframe(result)).to_arrow()
        return result
