"""Driver-side cluster manager: executor lifecycle, task scheduling,
heartbeat liveness, task re-execution on executor loss.

(reference: RapidsDriverPlugin Plugin.scala:463 — executor registration
and RPC receive loop :469-504; RapidsShuffleHeartbeatManager.scala:33,169
— registration + periodic heartbeats with lost-executor handling. The
recovery model is §5.3's lineage re-execution: tasks are idempotent
callables, so a lost executor's in-flight tasks simply requeue.)
"""
from __future__ import annotations

import os
import queue
import socket
import subprocess
import sys
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional

from .rpc import RpcClosed, recv_msg, send_msg

__all__ = ["ClusterManager", "ExecutorLostError"]

HEARTBEAT_TIMEOUT_S = 3.0
MAX_TASK_RETRIES = 3
# how long a cancelled query's tag stays on the dead list: long enough
# for its in-flight fragments to drain (result frames arriving after a
# cancel are dropped by tag), short enough that a long-lived service
# driver does not accrete one entry per cancelled query forever
DEAD_TAG_TTL_S = 60.0


class ExecutorLostError(RuntimeError):
    pass


class _Executor:
    def __init__(self, exec_id: int, proc: subprocess.Popen):
        self.exec_id = exec_id
        self.proc = proc
        self.sock: Optional[socket.socket] = None
        self.last_heartbeat = time.time()
        self.inflight: Dict[int, "_Task"] = {}
        self.lost = False
        # per-executor outbound queue: Arrow-IPC encoding + sendall of
        # large shuffle frames must not serialize on the one dispatcher
        # thread (executors would idle while another's bucket uploads)
        self.outbox: "queue.Queue[Optional[_Task]]" = queue.Queue()
        # guards sock writes: shutdown() must not splice its frame into
        # the middle of a multi-sendall task frame from _send_loop
        self.send_lock = threading.Lock()


class _Task:
    __slots__ = ("task_id", "fn", "args", "tables", "future", "attempts",
                 "tag")

    def __init__(self, task_id, fn, args, tables=None, tag=None):
        self.task_id = task_id
        self.fn = fn
        self.args = args
        self.tables = tables
        self.future: Future = Future()
        self.attempts = 0
        # query_id of the owning query (cancel drains by tag)
        self.tag = tag


class ClusterManager:
    """Spawn N executor processes; schedule host-side tasks over them.

    Usage:
        cm = ClusterManager(2); cm.start()
        results = cm.map(decode_fn, paths)
        cm.shutdown()
    """

    def __init__(self, n_executors: int,
                 heartbeat_timeout: float = HEARTBEAT_TIMEOUT_S):
        self.n = n_executors
        self.heartbeat_timeout = heartbeat_timeout
        self._executors: Dict[int, _Executor] = {}
        self._pending: "queue.Queue[_Task]" = queue.Queue()
        self._idle: "queue.Queue[int]" = queue.Queue()
        self._lock = threading.Lock()
        self._next_task = 0
        # tags (query_ids) whose tasks were cancelled: dispatch skips
        # them, results for them are dropped on arrival; values are the
        # cancel times so the monitor can prune entries past
        # DEAD_TAG_TTL_S (membership tests read it like a set)
        self._dead_tags: Dict[Any, float] = {}
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._listener: Optional[socket.socket] = None

    # -- lifecycle -----------------------------------------------------
    def start(self):
        self._listener = socket.socket()
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind(("127.0.0.1", 0))
        self._listener.listen(self.n * 2 + 2)
        host, port = self._listener.getsockname()
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        # ship the driver's import environment so by-reference pickled
        # task functions resolve in the executor (the Spark closure-ship
        # analog)
        repo_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        paths = [repo_root] + [p for p in sys.path if os.path.isdir(p)]
        env["PYTHONPATH"] = os.pathsep.join(
            dict.fromkeys(paths + env.get("PYTHONPATH", "").split(
                os.pathsep)))
        for i in range(self.n):
            proc = subprocess.Popen(
                [sys.executable, "-m", "spark_rapids_tpu.cluster.executor",
                 host, str(port), str(i)], env=env)
            self._executors[i] = _Executor(i, proc)
        accept = threading.Thread(target=self._accept_loop, daemon=True,
                                  name="tpu-driver-accept")
        accept.start()
        mon = threading.Thread(target=self._monitor_loop, daemon=True,
                               name="tpu-driver-monitor")
        mon.start()
        disp = threading.Thread(target=self._dispatch_loop, daemon=True,
                                name="tpu-driver-dispatch")
        disp.start()
        # _threads is also appended from the accept loop once it is
        # running; every mutation goes through self._lock
        with self._lock:
            self._threads.extend([accept, mon, disp])
        # wait for registrations
        deadline = time.time() + 30
        while time.time() < deadline:
            with self._lock:
                if all(e.sock is not None
                       for e in self._executors.values()):
                    return
            time.sleep(0.02)
        raise RuntimeError("executors failed to register")

    def shutdown(self):
        self._stop.set()
        with self._lock:
            for e in self._executors.values():
                e.outbox.put(None)  # unblock the sender thread
                try:
                    if e.sock:
                        with e.send_lock:
                            send_msg(e.sock, "shutdown", {})
                except OSError:
                    pass
        for e in self._executors.values():
            try:
                e.proc.wait(timeout=3)
            except subprocess.TimeoutExpired:
                e.proc.kill()
        if self._listener:
            self._listener.close()

    # -- public API ----------------------------------------------------
    def submit(self, fn: Callable, *args, tables=None,
               tag=None) -> Future:
        """Schedule fn(*args) on an executor. When `tables` is given (a
        possibly-empty list of pyarrow Tables), they ride the task frame
        as Arrow IPC and arrive appended as the final positional
        argument of fn — arity is stable even for an empty list. `tag`
        groups tasks for cancel_tag() (the query_id in service runs)."""
        t = _Task(self._alloc_id(), fn, args, tables, tag=tag)
        self._pending.put(t)
        return t.future

    def cancel_tag(self, tag) -> int:
        """Cancel every task submitted under `tag`: queued tasks are
        drained and their futures failed; in-flight results arriving
        later are dropped (the executor finishes the fragment but the
        bytes never resolve a future). Returns the number of queued
        tasks drained. Executors are NOT killed — cooperative cancel on
        the driver side only, matching the engine's checkpoint model."""
        if tag is None:
            return 0
        with self._lock:
            self._dead_tags[tag] = time.time()
        drained = 0
        keep: List[_Task] = []
        while True:
            try:
                t = self._pending.get_nowait()
            except queue.Empty:
                break
            if t.tag == tag:
                drained += 1
                try:
                    t.future.set_exception(RuntimeError(
                        f"task {t.task_id} cancelled (tag {tag})"))
                except Exception:
                    pass
            else:
                keep.append(t)
        for t in keep:
            self._pending.put(t)
        return drained

    def map(self, fn: Callable, items) -> List[Any]:
        futures = [self.submit(fn, it) for it in items]
        return [f.result() for f in futures]

    @property
    def alive_executors(self) -> List[int]:
        with self._lock:
            return [i for i, e in self._executors.items()
                    if not e.lost and e.sock is not None]

    # -- internals -----------------------------------------------------
    def _alloc_id(self):
        with self._lock:
            self._next_task += 1
            return self._next_task

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            try:
                kind, payload = recv_msg(sock)
            except (RpcClosed, OSError):
                sock.close()
                continue
            eid = payload.get("executor")
            if kind == "register":
                with self._lock:
                    ex = self._executors.get(eid)
                    if ex is None:
                        sock.close()
                        continue
                    ex.sock = sock
                    ex.last_heartbeat = time.time()
                rt = threading.Thread(target=self._recv_loop,
                                      args=(eid, sock), daemon=True,
                                      name=f"tpu-driver-recv-{eid}")
                rt.start()
                st_ = threading.Thread(target=self._send_loop,
                                       args=(eid, sock), daemon=True,
                                       name=f"tpu-driver-send-{eid}")
                st_.start()
                with self._lock:
                    self._threads.extend([rt, st_])
                self._idle.put(eid)
            elif kind == "hb_register":
                ht = threading.Thread(target=self._hb_loop,
                                      args=(eid, sock), daemon=True,
                                      name=f"tpu-driver-hb-{eid}")
                ht.start()
                with self._lock:
                    self._threads.append(ht)
            else:
                sock.close()

    def _hb_loop(self, eid: int, sock: socket.socket):
        while not self._stop.is_set():
            try:
                kind, _ = recv_msg(sock)
            except (RpcClosed, OSError):
                return
            if kind == "heartbeat":
                with self._lock:
                    ex = self._executors.get(eid)
                    if ex:
                        ex.last_heartbeat = time.time()

    def _dispatch_loop(self):
        while not self._stop.is_set():
            try:
                task = self._pending.get(timeout=0.1)
            except queue.Empty:
                continue
            with self._lock:
                dead = task.tag is not None \
                    and task.tag in self._dead_tags
            if dead:
                try:
                    task.future.set_exception(RuntimeError(
                        f"task {task.task_id} cancelled "
                        f"(tag {task.tag})"))
                # tpulint: allow[retry-swallows-cancel] double-set guard on an already-cancelled future; the task is dropped, not re-run
                except Exception:
                    pass
                continue
            while not self._stop.is_set():
                try:
                    eid = self._idle.get(timeout=0.2)
                except queue.Empty:
                    if not self.alive_executors:
                        task.future.set_exception(ExecutorLostError(
                            "no live executors"))
                        task = None
                    if task is None:
                        break
                    continue
                with self._lock:
                    ex = self._executors.get(eid)
                    ok = ex and not ex.lost and ex.sock
                if not ok:
                    continue
                task.attempts += 1
                with self._lock:
                    ex.inflight[task.task_id] = task
                # hand off to the executor's sender thread: Arrow-IPC
                # encoding + sendall of big frames must not stall
                # dispatch to other idle executors
                ex.outbox.put(task)
                break

    def _send_loop(self, eid: int, sock: socket.socket):
        while not self._stop.is_set():
            with self._lock:
                ex = self._executors.get(eid)
            if ex is None or ex.lost:
                return
            try:
                task = ex.outbox.get(timeout=0.2)
            except queue.Empty:
                continue
            if task is None:
                return
            try:
                with ex.send_lock:
                    send_msg(sock, "task", {
                        "task_id": task.task_id, "fn": task.fn,
                        "args": task.args,
                        "has_tables": task.tables is not None},
                        tables=task.tables or ())
            except OSError:
                # _mark_lost requeues the executor's inflight tasks
                # (including this one) — do NOT also retry here (double
                # dispatch would run it on two executors)
                self._mark_lost(eid)
                return
            except Exception as e:   # non-fatal send failure: the
                with self._lock:     # executor stays alive
                    ex.inflight.pop(task.task_id, None)
                from ..runtime.backoff import backoff_delays
                from ..runtime.faults import (is_transient_error,
                                              note_recovery)
                if is_transient_error(e) \
                        and task.attempts < MAX_TASK_RETRIES:
                    # transient dispatch failure (injected rpc.send
                    # fault): bounded backoff + jitter, then requeue —
                    # the RPC half of the fetch-backoff story. Sleeping
                    # here only stalls THIS executor's sender thread.
                    note_recovery("rpc_retries")
                    time.sleep(backoff_delays(
                        task.attempts, 25.0,
                        seed=task.task_id)[task.attempts - 1])
                    self._pending.put(task)
                else:
                    # unpicklable task (or retries exhausted): fail it
                    task.future.set_exception(e)
                self._idle.put(eid)

    def _recv_loop(self, eid: int, sock: socket.socket):
        while not self._stop.is_set():
            try:
                kind, payload = recv_msg(sock)
            except (RpcClosed, OSError):
                self._mark_lost(eid)
                return
            task_id = payload.get("task_id")
            dropped = False
            with self._lock:
                ex = self._executors.get(eid)
                task = ex.inflight.pop(task_id, None) if ex else None
                if task is not None and task.tag is not None \
                        and task.tag in self._dead_tags:
                    # cancelled mid-flight: drop the result, re-idle
                    # the executor, fail the future for any waiter
                    try:
                        task.future.set_exception(RuntimeError(
                            f"task {task.task_id} cancelled "
                            f"(tag {task.tag})"))
                    except Exception:
                        pass
                    task = None
                    dropped = True
            if task is None:
                if dropped:
                    self._idle.put(eid)
                continue
            try:
                if kind == "result":
                    # executor MetricSet snapshots ride the result frame;
                    # deliver them ON the future (set before resolving so
                    # a waiter never observes the result without them)
                    task.future.task_metrics = payload.get(
                        "task_metrics")
                    if payload.get("arrow_result"):
                        from .rpc import ArrowResult
                        task.future.set_result(ArrowResult(
                            payload["value"], payload.get("_arrow", [])))
                    else:
                        task.future.set_result(payload["value"])
                else:
                    msg = (f"task failed on executor {eid}: "
                           f"{payload.get('message')}\n"
                           f"{payload.get('traceback', '')}")
                    ef = payload.get("error_fields") or {}
                    if ef.get("type") == "FetchFailed":
                        from .blocks import FetchFailed
                        err = FetchFailed(msg, addr=ef.get("addr"),
                                          shuffle_id=ef.get("shuffle_id"))
                    elif ef.get("type") == "InjectedFault":
                        # typed re-raise so the transient classifier
                        # (service retry) sees the injection for what
                        # it is instead of a generic RuntimeError
                        from ..runtime.faults import InjectedFault
                        err = InjectedFault(msg, point=ef.get("point"))
                    else:
                        err = RuntimeError(msg)
                    task.future.set_exception(err)
            except Exception:
                pass   # future already resolved by a retry path
            self._idle.put(eid)

    def _monitor_loop(self):
        while not self._stop.is_set():
            now = time.time()
            with self._lock:
                stale = [i for i, e in self._executors.items()
                         if e.sock is not None and not e.lost
                         and now - e.last_heartbeat
                         > self.heartbeat_timeout]
                # dead-tag hygiene: a cancelled query's tag only
                # matters while its in-flight fragments drain; expired
                # entries would otherwise accumulate one per cancelled
                # query for the life of a service driver
                expired = [t for t, ts in self._dead_tags.items()
                           if now - ts > DEAD_TAG_TTL_S]
                for t in expired:
                    del self._dead_tags[t]
            for eid in stale:
                self._mark_lost(eid)
            time.sleep(0.2)

    def _mark_lost(self, eid: int):
        """Heartbeat timeout / socket death: requeue the executor's
        in-flight tasks (idempotent re-execution) up to MAX_TASK_RETRIES."""
        with self._lock:
            ex = self._executors.get(eid)
            if ex is None or ex.lost:
                return
            ex.lost = True
            inflight = list(ex.inflight.values())
            ex.inflight.clear()
            try:
                if ex.sock:
                    ex.sock.close()
            except OSError:
                pass
        try:
            ex.proc.kill()
        except OSError:
            pass
        for task in inflight:
            if task.attempts >= MAX_TASK_RETRIES:
                task.future.set_exception(ExecutorLostError(
                    f"task {task.task_id} lost executor {eid} after "
                    f"{task.attempts} attempts"))
            else:
                self._pending.put(task)
