"""Length-prefixed binary RPC frames over TCP, with native Arrow-IPC
table payloads.

(reference analog: the plugin RPC channel Plugin.scala:469-504 rides
Spark's netty; shuffle blocks move as raw buffers through the block
manager. Here: a dependency-free socket protocol whose frames carry an
optional run of pyarrow tables serialized as Arrow IPC streams — columnar
data never goes through pickle, so executors can ship query-fragment
results (shuffle blocks) to the driver at memcpy cost.)

Frame layout:
  8-byte big-endian header length
  pickled (kind, payload, [table_byte_len, ...]) header
  for each table length: that many bytes of Arrow IPC stream

Pickle remains the wire format for the control plane (task closures,
small metadata) by design — driver and executors run the same code tree,
exactly like Spark shipping closures to executors. Received tables are
attached to a dict payload under the reserved key ``"_arrow"``.
"""
from __future__ import annotations

import pickle
import socket
import struct
from typing import Any, List, Sequence, Tuple

__all__ = ["send_msg", "recv_msg", "RpcClosed", "ArrowResult",
           "tables_to_ipc", "ipc_to_table"]

_LEN = struct.Struct(">Q")
MAX_FRAME = 1 << 34


class RpcClosed(Exception):
    """Peer went away mid-frame."""


class ArrowResult:
    """A task result whose pyarrow tables ride the RPC as Arrow-IPC
    frames instead of pickle. ``meta`` is any picklable metadata,
    ``tables`` a list of pyarrow Tables."""

    __slots__ = ("meta", "tables")

    def __init__(self, meta: Any, tables: Sequence):
        self.meta = meta
        self.tables = list(tables)


def tables_to_ipc(tables: Sequence) -> List:
    """Serialize tables to Arrow IPC streams as pyarrow Buffers (buffer
    protocol — sent zero-copy via memoryview, no bytes materialization)."""
    import pyarrow as pa
    blobs = []
    for t in tables:
        sink = pa.BufferOutputStream()
        with pa.ipc.new_stream(sink, t.schema) as w:
            w.write_table(t)
        blobs.append(sink.getvalue())
    return blobs


def ipc_to_table(blob: bytes):
    import pyarrow as pa
    with pa.ipc.open_stream(pa.py_buffer(blob)) as r:
        return r.read_all()


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(n - len(buf), 1 << 20))
        if not chunk:
            raise RpcClosed(f"connection closed ({len(buf)}/{n} bytes)")
        buf.extend(chunk)
    return bytes(buf)


def send_msg(sock: socket.socket, kind: str, payload: Any,
             tables: Sequence = ()) -> None:
    from ..runtime import faults
    if faults.ACTIVE and kind == "task":
        # fault point BEFORE any bytes hit the socket (a partial frame
        # would poison the stream, not simulate a failure) and only for
        # task dispatch — control traffic (heartbeats, register,
        # shutdown) failing would test the harness, not the engine
        faults.hit("rpc.send")
    blobs = tables_to_ipc(tables) if tables else []
    header = pickle.dumps(
        (kind, payload, [len(memoryview(b)) for b in blobs]),
        protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_LEN.pack(len(header)) + header)
    for b in blobs:
        sock.sendall(memoryview(b))


def recv_msg(sock: socket.socket) -> Tuple[str, Any]:
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if n > MAX_FRAME:
        raise IOError(f"oversized RPC frame: {n} bytes")
    kind, payload, lens = pickle.loads(_recv_exact(sock, n))
    if lens:
        if sum(lens) > MAX_FRAME:
            raise IOError(f"oversized Arrow payload: {sum(lens)} bytes")
        tables = [ipc_to_table(_recv_exact(sock, ln)) for ln in lens]
        if isinstance(payload, dict):
            payload["_arrow"] = tables
        else:
            payload = {"value": payload, "_arrow": tables}
    return kind, payload
