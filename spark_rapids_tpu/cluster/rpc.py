"""Length-prefixed binary RPC frames over TCP.

(reference analog: the plugin RPC channel Plugin.scala:469-504 rides
Spark's netty; here a dependency-free socket protocol.) Frame layout:
8-byte big-endian payload length, then a pickled (kind, payload) tuple.
Pickle is the task wire format by design — driver and executors run the
same code tree, exactly like Spark shipping closures to executors.
"""
from __future__ import annotations

import pickle
import socket
import struct
from typing import Any, Tuple

__all__ = ["send_msg", "recv_msg", "RpcClosed"]

_LEN = struct.Struct(">Q")
MAX_FRAME = 1 << 34


class RpcClosed(Exception):
    """Peer went away mid-frame."""


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(n - len(buf), 1 << 20))
        if not chunk:
            raise RpcClosed(f"connection closed ({len(buf)}/{n} bytes)")
        buf.extend(chunk)
    return bytes(buf)


def send_msg(sock: socket.socket, kind: str, payload: Any) -> None:
    data = pickle.dumps((kind, payload), protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_LEN.pack(len(data)) + data)


def recv_msg(sock: socket.socket) -> Tuple[str, Any]:
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if n > MAX_FRAME:
        raise IOError(f"oversized RPC frame: {n} bytes")
    return pickle.loads(_recv_exact(sock, n))
