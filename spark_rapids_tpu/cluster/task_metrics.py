"""Executor-side task-metric side channel.

Before this channel existed, executor `MetricSet`s died with the worker
process — the driver saw task VALUES but never task METRICS (ISSUE 2:
"executor metrics die in the worker process"). Fragment tasks
(cluster/query.py) record per-operator snapshots here while they run;
the executor loop (executor.py) drains the buffer after each task and
attaches it to the result frame as `task_metrics`; the driver
(driver.py) delivers it on the task's Future, where the
DistributedRunner aggregates across executors into the query event log.

The buffer is process-global: the executor runs tasks sequentially on
one thread, so records between two drains belong to the task in between
(the lock only guards against in-task helper threads).
"""
from __future__ import annotations

import threading
from typing import List, Optional

__all__ = ["record_task_metrics", "drain_task_metrics"]

_LOCK = threading.Lock()
_BUF: List[dict] = []


def record_task_metrics(record: dict):
    """Append one metrics record (picklable dict) for the running task.
    Fragment records carry {stage, plan, ops, watermarks, ...}."""
    with _LOCK:
        _BUF.append(record)


def drain_task_metrics() -> Optional[List[dict]]:
    """Take everything recorded since the last drain (None when empty,
    so result frames of metric-less tasks don't grow a field)."""
    with _LOCK:
        if not _BUF:
            return None
        out = list(_BUF)
        _BUF.clear()
    return out
