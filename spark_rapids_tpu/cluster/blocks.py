"""Executor-local shuffle block store + peer-to-peer block server.

The reference's shuffle keeps map outputs ON the executors (served by
the block manager / UCX transport — RapidsShuffleInternalManagerBase
.scala:56, shuffle/RapidsShuffleTransport.scala:44); the driver moves
only locations. Same topology here: map fragments park their shuffle
buckets in this process-local store (Arrow-IPC files under a temp dir),
a daemon server thread serves `fetch` requests from peer executors over
the same length-prefixed Arrow-IPC frame protocol as the cluster RPC,
and reducers dial mappers directly. The driver never touches a data
byte — O(metadata) driver memory at any scale.

Store lifetime: keyed by shuffle_id; an LRU cap of `MAX_SHUFFLES`
evicts the oldest shuffle's files (runs are short-lived; a dropped
shuffle's re-fetch fails like a lost executor and re-executes lineage).
"""
from __future__ import annotations

import os
import socket
import tempfile
import threading
from collections import OrderedDict
from typing import Dict, List, Sequence, Tuple

from .rpc import RpcClosed, recv_msg, send_msg

__all__ = ["BlockStore", "ensure_server", "fetch_blocks",
           "drop_shuffle", "FetchFailed"]

MAX_SHUFFLES = 4


class FetchFailed(RuntimeError):
    """A peer block fetch failed (dead executor / evicted shuffle).

    Carries the observed mapper `addr` and `shuffle_id` as STRUCTURED
    fields — the driver's lineage re-execution targets the failed
    mapper from these, never by parsing exception text (the old repr
    substring match silently degraded to full re-execution whenever a
    message format drifted)."""

    def __init__(self, msg: str, addr=None, shuffle_id: str = None,
                 transient: bool = True):
        super().__init__(msg)
        self.addr = tuple(addr) if addr else None
        self.shuffle_id = shuffle_id
        # transient failures (connect/recv errors — the peer may just
        # be slow) are worth transport-level backoff retries; a
        # structural "missing blocks" reply is not: the blocks will not
        # reappear until the driver regenerates the map outputs
        self.transient = transient


class BlockStore:
    def __init__(self):
        self.dir = tempfile.mkdtemp(prefix="srtpu-shuffle-")
        from ..runtime import lockdep
        self._lock = lockdep.lock("BlockStore._lock")
        # shuffle_id -> {(map_id, pid): path}
        self._shuffles: "OrderedDict[str, Dict[Tuple[int, int], str]]" = \
            OrderedDict()
        # in-flight shuffles are pinned: the LRU never evicts them (an
        # eviction mid-reduce forces full lineage re-execution). put()
        # pins implicitly; drop() unpins + deletes.
        self._pinned: set = set()

    def pin(self, shuffle_id: str):
        with self._lock:
            fresh = shuffle_id not in self._pinned
            self._pinned.add(shuffle_id)
        if fresh:
            from ..runtime import ledger
            ledger.note_acquire("shuffle_pin", token=shuffle_id,
                                tag=f"BlockStore.pin[{shuffle_id}]")

    def unpin(self, shuffle_id: str):
        with self._lock:
            was = shuffle_id in self._pinned
            self._pinned.discard(shuffle_id)
        if was:
            from ..runtime import ledger
            ledger.note_release("shuffle_pin", token=shuffle_id)

    def put(self, shuffle_id: str, map_id: int, pid: int, table) -> int:
        import pyarrow as pa
        path = os.path.join(self.dir,
                            f"{shuffle_id}-{map_id}-{pid}.arrow")
        with pa.OSFile(path, "wb") as f:
            with pa.ipc.new_stream(f, table.schema) as w:
                w.write_table(table)
        with self._lock:
            if shuffle_id not in self._shuffles:
                self._shuffles[shuffle_id] = {}
            fresh_pin = shuffle_id not in self._pinned
            self._pinned.add(shuffle_id)     # in-flight until drop()
            # true LRU: every put refreshes recency before evicting;
            # pinned (in-flight) shuffles are skipped — only completed
            # ones whose owner never dropped them age out
            self._shuffles.move_to_end(shuffle_id)
            evictable = [sid for sid in self._shuffles
                         if sid not in self._pinned]
            while len(self._shuffles) > MAX_SHUFFLES and evictable:
                sid = evictable.pop(0)
                old = self._shuffles.pop(sid)
                for p in old.values():
                    try:
                        os.unlink(p)
                    except OSError:
                        pass
            self._shuffles[shuffle_id][(map_id, pid)] = path
        if fresh_pin:
            from ..runtime import ledger
            ledger.note_acquire("shuffle_pin", token=shuffle_id,
                                tag=f"BlockStore.pin[{shuffle_id}]")
        return os.path.getsize(path)

    def get(self, shuffle_id: str, map_id: int, pid: int):
        import pyarrow as pa
        with self._lock:
            if shuffle_id in self._shuffles:
                self._shuffles.move_to_end(shuffle_id)   # LRU touch
            path = self._shuffles.get(shuffle_id, {}).get((map_id, pid))
        if path is None:
            return None
        with pa.OSFile(path, "rb") as f:
            with pa.ipc.open_stream(f) as r:
                return r.read_all()

    def drop(self, shuffle_id: str):
        with self._lock:
            was = shuffle_id in self._pinned
            self._pinned.discard(shuffle_id)
            old = self._shuffles.pop(shuffle_id, None)
        if was:
            from ..runtime import ledger
            ledger.note_release("shuffle_pin", token=shuffle_id)
        for p in (old or {}).values():
            try:
                os.unlink(p)
            except OSError:
                pass


_STORE: BlockStore = None
_SERVER_ADDR: Tuple[str, int] = None
_INIT_LOCK = threading.Lock()


def store() -> BlockStore:
    global _STORE
    with _INIT_LOCK:
        if _STORE is None:
            _STORE = BlockStore()
    return _STORE


def _serve_conn(sock: socket.socket):
    try:
        while True:
            kind, payload = recv_msg(sock)
            if kind == "fetch":
                sid = payload["shuffle_id"]
                tabs, missing = [], []
                for map_id in payload["map_ids"]:
                    t = store().get(sid, map_id, payload["pid"])
                    if t is None:
                        missing.append(map_id)
                    else:
                        tabs.append(t)
                if missing:
                    send_msg(sock, "missing", {"map_ids": missing})
                else:
                    send_msg(sock, "blocks", {"n": len(tabs)},
                             tables=tabs)
            elif kind == "drop":
                store().drop(payload["shuffle_id"])
                send_msg(sock, "ok", {})
            else:
                return
    except (RpcClosed, OSError):
        pass
    finally:
        sock.close()


def ensure_server(advertise_host: str = None) -> Tuple[str, int]:
    """Start (once) the block server in this process; returns the
    ADVERTISED address for shuffle-map metadata. Binds all interfaces
    so multi-host reducers can connect; what gets advertised to them is
    `advertise_host` (conf `cluster.blockServer.advertiseHost`), which
    defaults to 127.0.0.1 — correct for the single-host default
    deployment, and never leaks a wildcard address into metadata."""
    global _SERVER_ADDR
    with _INIT_LOCK:
        if _SERVER_ADDR is None:
            listener = socket.socket()
            listener.setsockopt(socket.SOL_SOCKET,
                                socket.SO_REUSEADDR, 1)
            listener.bind(("0.0.0.0", 0))
            listener.listen(16)
            _SERVER_ADDR = listener.getsockname()

            def accept_loop():
                while True:
                    try:
                        conn, _ = listener.accept()
                    except OSError:
                        return
                    threading.Thread(target=_serve_conn, args=(conn,),
                                     daemon=True,
                                     name="tpu-blockserv-conn").start()

            threading.Thread(target=accept_loop, daemon=True,
                             name="tpu-blockserv").start()
        if not advertise_host:
            from ..config import CLUSTER_BLOCK_ADVERTISE_HOST
            advertise_host = CLUSTER_BLOCK_ADVERTISE_HOST.default
        return (advertise_host, _SERVER_ADDR[1])


def _fetch_once(addr: Tuple[str, int], shuffle_id: str,
                map_ids: Sequence[int], pid: int) -> List:
    from ..runtime import faults
    if faults.ACTIVE:
        faults.hit("block.fetch")
    try:
        sock = socket.create_connection(addr, timeout=10)
    except OSError as e:
        raise FetchFailed(f"connect {addr}: {e!r}", addr=addr,
                          shuffle_id=shuffle_id) from e
    try:
        send_msg(sock, "fetch", {"shuffle_id": shuffle_id,
                                 "map_ids": list(map_ids), "pid": pid})
        kind, payload = recv_msg(sock)
    except (RpcClosed, OSError) as e:
        raise FetchFailed(f"fetch from {addr}: {e!r}", addr=addr,
                          shuffle_id=shuffle_id) from e
    finally:
        sock.close()
    if kind != "blocks":
        raise FetchFailed(
            f"mapper {addr} missing blocks: {payload}", addr=addr,
            shuffle_id=shuffle_id, transient=False)
    return payload.get("_arrow", [])


def fetch_blocks(addr: Tuple[str, int], shuffle_id: str,
                 map_ids: Sequence[int], pid: int,
                 max_retries: int = 2, wait_ms: float = 50.0,
                 stats: dict = None) -> List:
    """Fetch this reduce partition's blocks from one mapper executor,
    retrying TRANSIENT failures (connect/recv errors) with bounded
    exponential backoff + jitter before letting the FetchFailed
    escalate to the driver's lineage regeneration. The jitter PRNG is
    seeded from (shuffle_id, pid) — deterministic per partition, yet
    concurrent reducers hitting the same mapper de-synchronize. When
    `stats` is given, per-attempt records accumulate under
    "fetch_attempts" and total backoff under "fetch_retry_ms" (the
    driver turns these into fetch_retry events + the fetchRetryMs
    metric)."""
    import time as _time

    from ..runtime.backoff import backoff_delays
    from ..runtime.faults import note_recovery
    from ..profiler import tracing
    addr = tuple(addr)
    seed = hash((shuffle_id, pid)) & 0xFFFFFFFF
    delays = backoff_delays(max_retries, wait_ms, seed=seed)
    attempt = 0
    while True:
        try:
            # the span covers the whole attempt — connect, server read,
            # transfer, AND any injected block.fetch delay (fault
            # harness), which is exactly how an injected slow fetch
            # becomes the critical path's shuffle_fetch edge
            with tracing.span("shuffle.fetch_blocks", "fetch",
                              pid=pid, attempt=attempt):
                out = _fetch_once(addr, shuffle_id, map_ids, pid)
            if attempt and stats is not None:
                stats["fetch_recovered"] = \
                    stats.get("fetch_recovered", 0) + 1
            return out
        except FetchFailed as e:
            if not e.transient or attempt >= max_retries:
                raise
            d = delays[attempt]
            attempt += 1
            note_recovery("fetch_retries")
            ent = None
            if stats is not None:
                # per-attempt timing (ts + the measured wait below)
                # rides home with task metrics so the driver can
                # reconstruct the retry WAIT TIMELINE, not just the
                # stage-level fetchRetryMs sum
                ent = {"addr": list(addr), "pid": pid,
                       "attempt": attempt, "ts": round(_time.time(), 6),
                       "delay_ms": round(d * 1e3, 3), "error": repr(e)}
                stats.setdefault("fetch_attempts", []).append(ent)
                stats["fetch_retry_ms"] = \
                    stats.get("fetch_retry_ms", 0.0) + d * 1e3
            t0 = _time.perf_counter()
            _time.sleep(d)
            waited_ms = (_time.perf_counter() - t0) * 1e3
            if ent is not None:
                ent["wait_ms"] = round(waited_ms, 3)
            tracing.record_wait_span("shuffle.fetch_backoff", "backoff",
                                     waited_ms, pid=pid,
                                     attempt=attempt)


def drop_shuffle(addr: Tuple[str, int], shuffle_id: str) -> bool:
    """Ask one mapper's block server to unpin + delete a shuffle's
    blocks (end-of-query cleanup; best-effort — a dead mapper's files
    died with it)."""
    try:
        sock = socket.create_connection(tuple(addr), timeout=5)
    except OSError:
        return False
    try:
        send_msg(sock, "drop", {"shuffle_id": shuffle_id})
        kind, _ = recv_msg(sock)
        return kind == "ok"
    except (RpcClosed, OSError):
        return False
    finally:
        sock.close()
