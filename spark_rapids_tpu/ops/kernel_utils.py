"""Kernel-layer value type: a traced columnar value.

`CV` is the in-trace representation of a column: plain jax arrays bundled in a
pytree so entire expression trees trace into a single XLA program (the TPU
answer to the reference's per-kernel cudf dispatch — XLA fuses what cuDF had
to launch as separate kernels).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

__all__ = ["CV", "all_valid", "and_validity"]


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class CV:
    """Traced column value: data buffer + validity (+ offsets for strings)."""
    data: Any                      # jnp array [capacity] (uint8 for strings)
    validity: Any                  # jnp bool [capacity]
    offsets: Optional[Any] = None  # jnp int32 [capacity+1] for var-width

    def tree_flatten(self):
        if self.offsets is None:
            return (self.data, self.validity), False
        return (self.data, self.validity, self.offsets), True

    @classmethod
    def tree_unflatten(cls, has_offsets, children):
        if has_offsets:
            return cls(children[0], children[1], children[2])
        return cls(children[0], children[1], None)

    @property
    def capacity(self) -> int:
        return self.validity.shape[0]


def all_valid(shape_like) -> Any:
    return jnp.ones(shape_like.shape[0], dtype=jnp.bool_)


def and_validity(*cvs: CV):
    v = cvs[0].validity
    for c in cvs[1:]:
        v = jnp.logical_and(v, c.validity)
    return v
