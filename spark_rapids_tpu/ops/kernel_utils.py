"""Kernel-layer value type: a traced columnar value.

`CV` is the in-trace representation of a column: plain jax arrays bundled in a
pytree so entire expression trees trace into a single XLA program (the TPU
answer to the reference's per-kernel cudf dispatch — XLA fuses what cuDF had
to launch as separate kernels).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

__all__ = ["CV", "all_valid", "and_validity"]


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class CV:
    """Traced column value: data buffer + validity (+ offsets for var-width,
    + child CVs for list/struct layouts)."""
    data: Any                      # jnp array [capacity] (uint8 for strings)
    validity: Any                  # jnp bool [capacity]
    offsets: Optional[Any] = None  # jnp int32 [capacity+1] for var-width
    children: tuple = ()           # child CVs (list element / struct fields)

    def tree_flatten(self):
        leaves = [self.data, self.validity]
        if self.offsets is not None:
            leaves.append(self.offsets)
        leaves.extend(self.children)
        return tuple(leaves), (self.offsets is not None, len(self.children))

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        has_offsets, n_children = aux
        k = 3 if has_offsets else 2
        return cls(leaves[0], leaves[1], leaves[2] if has_offsets else None,
                   tuple(leaves[k:k + n_children]))

    @property
    def capacity(self) -> int:
        return self.validity.shape[0]

    @property
    def child(self) -> "CV":
        return self.children[0]


def all_valid(shape_like) -> Any:
    return jnp.ones(shape_like.shape[0], dtype=jnp.bool_)


def and_validity(*cvs: CV):
    v = cvs[0].validity
    for c in cvs[1:]:
        v = jnp.logical_and(v, c.validity)
    return v
