"""String kernels over Arrow-layout (offsets + bytes) device columns.

Replaces the cudf string kernel surface (reference: stringFunctions.scala
over cudf strings; JNI CastStrings). The deep TPU problem (SURVEY.md §7.3
item 1): cuDF launches warp-per-row kernels with dynamic outputs; XLA wants
static shapes and regular parallelism. The design here works in the BYTE
DOMAIN: a byte->row map (searchsorted over offsets) turns every per-row
variable-length loop into a dense vectorized pass over the data buffer,
and per-row results come back via segment reductions. Output buffers are
sized by exact computed byte totals (cumsum of per-row output lengths) —
capacity equals the input's byte capacity for non-growing ops.

ASCII-only case mapping round-1 (documented in docs/compatibility.md).
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

from .kernel_utils import CV

__all__ = ["byte_row_map", "str_len_bytes", "str_len_chars", "upper",
           "lower", "substring", "concat_strings", "compare", "contains",
           "startswith", "endswith", "rebuild_strings", "trim", "reverse",
           "find_first", "pad", "repeat_str", "literal_column"]


def byte_row_map(offsets, dcap: int):
    """row index for every byte position of the data buffer (garbage for
    positions beyond the last offset)."""
    pos = jnp.arange(dcap, dtype=jnp.int32)
    n = offsets.shape[0] - 1
    row = jnp.searchsorted(offsets[1:], pos, side="right").astype(jnp.int32)
    return jnp.clip(row, 0, n - 1)


def str_len_bytes(cv: CV):
    return cv.offsets[1:] - cv.offsets[:-1]


def str_len_chars(cv: CV):
    """UTF-8 aware char count: bytes minus continuation bytes."""
    n = cv.offsets.shape[0] - 1
    dcap = cv.data.shape[0]
    row = byte_row_map(cv.offsets, dcap)
    pos = jnp.arange(dcap)
    in_range = (pos >= cv.offsets[row]) & (pos < cv.offsets[row + 1])
    is_cont = (cv.data & 0xC0) == 0x80
    cont = jax.ops.segment_sum((in_range & is_cont).astype(jnp.int32),
                               row, n)
    return str_len_bytes(cv) - cont


def _map_case(cv: CV, to_upper: bool) -> CV:
    d = cv.data
    if to_upper:
        is_lower = (d >= 97) & (d <= 122)
        out = jnp.where(is_lower, d - 32, d)
    else:
        is_upper = (d >= 65) & (d <= 90)
        out = jnp.where(is_upper, d + 32, d)
    return CV(out.astype(jnp.uint8), cv.validity, cv.offsets)


def upper(cv: CV) -> CV:
    return _map_case(cv, True)


def lower(cv: CV) -> CV:
    return _map_case(cv, False)


def rebuild_strings(cv: CV, new_starts, new_lens,
                    out_data_capacity: Optional[int] = None,
                    wrap=None) -> CV:
    """Build a new string column where row i is the byte range
    [new_starts[i], new_starts[i]+new_lens[i]) of cv.data. With `wrap`
    (per-row period), source bytes repeat cyclically every wrap[i] bytes
    (the repeat() kernel)."""
    n = new_lens.shape[0]
    new_lens = jnp.maximum(new_lens, 0)
    new_off = jnp.concatenate([jnp.zeros(1, jnp.int32),
                               jnp.cumsum(new_lens).astype(jnp.int32)])
    out_cap = out_data_capacity or cv.data.shape[0]
    pos = jnp.arange(out_cap, dtype=jnp.int32)
    row = jnp.clip(jnp.searchsorted(new_off[1:], pos, side="right"),
                   0, n - 1).astype(jnp.int32)
    rel = pos - new_off[row]
    if wrap is not None:
        rel = rel % jnp.maximum(wrap[row], 1)
    src = new_starts[row] + rel
    src = jnp.clip(src, 0, cv.data.shape[0] - 1)
    data = cv.data[src]
    total = new_off[n]
    data = jnp.where(pos < total, data, 0).astype(jnp.uint8)
    return CV(data, cv.validity, new_off)


def substring(cv: CV, start: int, length: Optional[int]) -> CV:
    """Spark substring: 1-based start; negative counts from the end;
    byte-based round-1 (exact for ASCII; documented deviation)."""
    lens = str_len_bytes(cv)
    if start > 0:
        s = jnp.minimum(start - 1, lens)
    elif start == 0:
        s = jnp.zeros_like(lens)
    else:
        s = jnp.maximum(lens + start, 0)
    if length is None:
        ln = lens - s
    else:
        ln = jnp.minimum(jnp.maximum(length, 0), lens - s)
    return rebuild_strings(cv, cv.offsets[:-1] + s.astype(jnp.int32),
                           ln.astype(jnp.int32))


def concat_strings(cvs: List[CV], out_data_capacity: int) -> CV:
    """Row-wise concatenation of string columns (null if any input null,
    Spark concat semantics)."""
    n = cvs[0].offsets.shape[0] - 1
    lens = [str_len_bytes(c) for c in cvs]
    tot = sum(lens)
    valid = cvs[0].validity
    for c in cvs[1:]:
        valid = valid & c.validity
    tot = jnp.where(valid, tot, 0)
    new_off = jnp.concatenate([jnp.zeros(1, jnp.int32),
                               jnp.cumsum(tot).astype(jnp.int32)])
    pos = jnp.arange(out_data_capacity, dtype=jnp.int32)
    row = jnp.clip(jnp.searchsorted(new_off[1:], pos, side="right"),
                   0, n - 1).astype(jnp.int32)
    rel = pos - new_off[row]
    # which source column does each output byte come from?
    out = jnp.zeros(out_data_capacity, jnp.uint8)
    acc = jnp.zeros(n, jnp.int32)
    for c, ln in zip(cvs, lens):
        ln = ln.astype(jnp.int32)
        in_this = (rel >= acc[row]) & (rel < acc[row] + ln[row])
        src = c.offsets[row] + (rel - acc[row])
        src = jnp.clip(src, 0, c.data.shape[0] - 1)
        out = jnp.where(in_this, c.data[src], out)
        acc = acc + ln
    total = new_off[n]
    out = jnp.where(pos < total, out, 0).astype(jnp.uint8)
    return CV(out, valid, new_off)


def equals_literal(cv: CV, raw: bytes):
    """Row == constant-string: length check + big-endian 4-byte chunk
    compares — O(rows * len/4) gathers. The general `compare` walks the
    column's whole BYTE domain with a segment_min (O(bytes)), which is
    ~30x more work for a short literal against a long column; XLA also
    CSEs the chunk extraction across many literal compares on the same
    column (q19's 12 container compares cost one extraction). Exact for
    any byte content: equal length + equal zero-padded chunks <=> equal
    bytes."""
    from .sortkeys import string_chunk_keys
    n = cv.offsets.shape[0] - 1
    lens = cv.offsets[1:] - cv.offsets[:-1]
    L = len(raw)
    ok = lens == L
    nch = (L + 3) // 4
    if nch:
        ks = string_chunk_keys(cv, nch)
        for i in range(nch):
            word = int.from_bytes(raw[i * 4:(i + 1) * 4].ljust(4, b"\0"),
                                  "big")
            ok = ok & (ks[i] == jnp.uint32(word))
    return ok


def compare(a: CV, b: CV):
    """Per-row byte-lexicographic compare: returns int8 in {-1,0,1}.
    Works over a's byte domain + a length tiebreak."""
    n = a.offsets.shape[0] - 1
    la = str_len_bytes(a)
    lb = str_len_bytes(b)
    dcap = a.data.shape[0]
    row = byte_row_map(a.offsets, dcap)
    pos = jnp.arange(dcap, dtype=jnp.int32)
    rel = pos - a.offsets[row]
    within = (rel >= 0) & (rel < jnp.minimum(la, lb)[row])
    bsrc = jnp.clip(b.offsets[row] + rel, 0, b.data.shape[0] - 1)
    abyte = a.data
    bbyte = b.data[bsrc]
    differs = within & (abyte != bbyte)
    first_diff = jax.ops.segment_min(
        jnp.where(differs, rel, jnp.int32(2**30)), row, n)
    has_diff = first_diff < 2**30
    # byte values at the first differing position
    asrc = jnp.clip(a.offsets[:-1] + first_diff, 0, dcap - 1)
    bsrc2 = jnp.clip(b.offsets[:-1] + first_diff, 0, b.data.shape[0] - 1)
    av = a.data[asrc].astype(jnp.int32)
    bv = b.data[bsrc2].astype(jnp.int32)
    cmp_diff = jnp.sign(av - bv)
    cmp_len = jnp.sign(la - lb)
    return jnp.where(has_diff, cmp_diff, cmp_len).astype(jnp.int8)


def _find_literal(cv: CV, pattern: bytes, wildcard=None):
    """bool per byte position: pattern matches starting here (within the
    row). Bytes equal to `wildcard` (e.g. ord('_')) match anything."""
    dcap = cv.data.shape[0]
    row = byte_row_map(cv.offsets, dcap)
    pos = jnp.arange(dcap, dtype=jnp.int32)
    rel = pos - cv.offsets[row]
    lens = str_len_bytes(cv)
    m = len(pattern)
    ok = (rel >= 0) & (rel + m <= lens[row])
    for j, pb in enumerate(pattern):
        if wildcard is not None and pb == wildcard:
            continue
        idx = jnp.clip(pos + j, 0, dcap - 1)
        ok = ok & (cv.data[idx] == pb)
    return ok, row, rel, lens


def contains(cv: CV, pattern: bytes, wildcard=None,
             skip_prefix: int = 0, skip_suffix: int = 0):
    """True per row when pattern occurs within
    [skip_prefix, len-skip_suffix) — the bounds let LIKE exclude the
    bytes already consumed by its prefix/suffix runs."""
    n = cv.offsets.shape[0] - 1
    if len(pattern) == 0:
        return jnp.ones(n, jnp.bool_)
    ok, row, rel, lens = _find_literal(cv, pattern, wildcard)
    if skip_prefix:
        ok = ok & (rel >= skip_prefix)
    if skip_suffix:
        ok = ok & (rel + len(pattern) <= lens[row] - skip_suffix)
    return jax.ops.segment_max(ok.astype(jnp.int32), row, n) > 0


def startswith(cv: CV, pattern: bytes, wildcard=None):
    n = cv.offsets.shape[0] - 1
    if len(pattern) == 0:
        return jnp.ones(n, jnp.bool_)
    ok, row, rel, lens = _find_literal(cv, pattern, wildcard)
    at0 = ok & (rel == 0)
    return jax.ops.segment_max(at0.astype(jnp.int32), row, n) > 0


def endswith(cv: CV, pattern: bytes, wildcard=None):
    n = cv.offsets.shape[0] - 1
    if len(pattern) == 0:
        return jnp.ones(n, jnp.bool_)
    ok, row, rel, lens = _find_literal(cv, pattern, wildcard)
    at_end = ok & (rel == lens[row] - len(pattern))
    return jax.ops.segment_max(at_end.astype(jnp.int32), row, n) > 0


def trim(cv: CV, left: bool = True, right: bool = True) -> CV:
    """Strip ASCII spaces (Spark trim/ltrim/rtrim trim ' ' by default).
    Unbounded: one byte-domain pass finds each row's first/last non-space
    via segment reductions."""
    lens = str_len_bytes(cv)
    n = lens.shape[0]
    dcap = cv.data.shape[0]
    starts = cv.offsets[:-1]
    row = byte_row_map(cv.offsets, dcap)
    pos = jnp.arange(dcap, dtype=jnp.int32)
    rel = pos - starts[row]
    in_range = (rel >= 0) & (rel < lens[row])
    non_space = in_range & (cv.data != 32)
    first_rel = jax.ops.segment_min(
        jnp.where(non_space, rel, jnp.int32(2**30)), row, n)
    last_rel = jax.ops.segment_max(
        jnp.where(non_space, rel, jnp.int32(-1)), row, n)
    all_space = first_rel >= 2**30
    lead = jnp.where(left, jnp.where(all_space, lens, first_rel), 0)
    end = jnp.where(right, last_rel + 1, lens)
    new_len = jnp.maximum(end - lead, 0)
    new_len = jnp.where(all_space, 0, new_len)
    return rebuild_strings(cv, (starts + lead).astype(jnp.int32),
                           new_len.astype(jnp.int32))


def reverse(cv: CV) -> CV:
    """Byte-reverse each row (exact for ASCII; documented deviation)."""
    n = cv.offsets.shape[0] - 1
    dcap = cv.data.shape[0]
    row = byte_row_map(cv.offsets, dcap)
    pos = jnp.arange(dcap, dtype=jnp.int32)
    rel = pos - cv.offsets[row]
    lens = str_len_bytes(cv)
    src = cv.offsets[row] + (lens[row] - 1 - rel)
    src = jnp.clip(src, 0, dcap - 1)
    in_range = (rel >= 0) & (rel < lens[row])
    data = jnp.where(in_range, cv.data[src], 0).astype(jnp.uint8)
    return CV(data, cv.validity, cv.offsets)


def find_first(cv: CV, pattern: bytes):
    """1-based position of the first occurrence per row; 0 if absent
    (Spark instr/locate semantics)."""
    n = cv.offsets.shape[0] - 1
    if len(pattern) == 0:
        return jnp.ones(n, jnp.int32)
    ok, row, rel, lens = _find_literal(cv, pattern)
    first = jax.ops.segment_min(
        jnp.where(ok, rel, jnp.int32(2**30)), row, n)
    return jnp.where(first < 2**30, first + 1, 0).astype(jnp.int32)




def pad(cv: CV, target_len: int, pad_bytes: bytes, left: bool) -> CV:
    """lpad/rpad to target_len BYTES with a cyclic literal pad; rows
    longer than target are truncated to it. Byte-based (exact for ASCII;
    documented deviation in docs/compatibility.md — Spark counts chars).
    Spark edge semantics honored: negative target -> empty strings; empty
    pad -> truncate only, never extend."""
    import numpy as np
    target_len = max(int(target_len), 0)
    lens = str_len_bytes(cv)
    n = lens.shape[0]
    if len(pad_bytes) == 0:
        # Spark: empty pad never extends; rows only truncate to target
        return rebuild_strings(cv, cv.offsets[:-1],
                               jnp.minimum(lens, target_len)
                               .astype(jnp.int32))
    new_off = jnp.arange(n + 1, dtype=jnp.int32) * target_len
    out_cap = max(int(n * target_len), 1)
    pos = jnp.arange(out_cap, dtype=jnp.int32)
    row = jnp.clip(pos // max(target_len, 1), 0, n - 1)
    rel = pos - row * target_len
    cur = jnp.minimum(lens, target_len)
    padlen = max(len(pad_bytes), 1)
    pad_arr = jnp.asarray(np.frombuffer(
        pad_bytes if pad_bytes else b"\0", np.uint8))
    if left:
        npad = target_len - cur
        from_pad = rel < npad[row]
        src_data = cv.offsets[row] + (rel - npad[row])
        pad_idx = rel % padlen
    else:
        from_pad = rel >= cur[row]
        src_data = cv.offsets[row] + rel
        pad_idx = (rel - cur[row]) % padlen
    src_data = jnp.clip(src_data, 0, cv.data.shape[0] - 1)
    out = jnp.where(from_pad, pad_arr[jnp.clip(pad_idx, 0, padlen - 1)],
                    cv.data[src_data]).astype(jnp.uint8)
    return CV(out, cv.validity, new_off)


def repeat_str(cv: CV, times: int, out_data_capacity: int) -> CV:
    """Repeat each row `times` times (Spark repeat; times<=0 -> empty)."""
    times = max(times, 0)
    lens = str_len_bytes(cv)
    return rebuild_strings(cv, cv.offsets[:-1],
                           (lens * times).astype(jnp.int32),
                           out_data_capacity, wrap=lens)


def literal_column(raw: bytes, present, capacity: int) -> CV:
    """String CV holding `raw` where `present` is True, '' elsewhere
    (always valid) — the concat_ws separator builder."""
    import numpy as np
    n = present.shape[0]
    nb = max(len(raw), 1)
    lens = jnp.where(present, len(raw), 0).astype(jnp.int32)
    new_off = jnp.concatenate([jnp.zeros(1, jnp.int32),
                               jnp.cumsum(lens).astype(jnp.int32)])
    out_cap = max(capacity, 1)
    pos = jnp.arange(out_cap, dtype=jnp.int32)
    row = jnp.clip(jnp.searchsorted(new_off[1:], pos, side="right"),
                   0, n - 1).astype(jnp.int32)
    rel = pos - new_off[row]
    src = jnp.asarray(np.frombuffer(raw.ljust(nb, b"\0"), np.uint8))
    data = src[jnp.clip(rel, 0, nb - 1)]
    total = new_off[n]
    data = jnp.where(pos < total, data, 0).astype(jnp.uint8)
    return CV(data, jnp.ones(n, jnp.bool_), new_off)


def str_equal_rowmap(ecv: CV, vcv: CV, rows, live):
    """bool[ecap]: element string e equals the per-row string
    vcv[rows[e]]. Compares in the element byte domain with a row-mapped
    source index — no replication gather, so no output-capacity sizing is
    needed (used by array_contains / map element_at over strings)."""
    n = ecv.offsets.shape[0] - 1
    le = str_len_bytes(ecv)
    lv = str_len_bytes(vcv)
    lv_e = lv[rows]
    len_ok = le == lv_e
    dcap = ecv.data.shape[0]
    rowb = byte_row_map(ecv.offsets, dcap)       # element index per byte
    pos = jnp.arange(dcap, dtype=jnp.int32)
    rel = pos - ecv.offsets[rowb]
    lim = jnp.minimum(le, lv_e)
    within = (rel >= 0) & (rel < lim[rowb])
    vsrc = jnp.clip(vcv.offsets[rows[rowb]] + rel, 0,
                    vcv.data.shape[0] - 1)
    differs = within & (ecv.data != vcv.data[vsrc])
    any_diff = jax.ops.segment_max(differs.astype(jnp.int32), rowb, n) > 0
    return (len_ok & ~any_diff & ecv.validity & vcv.validity[rows] & live)
