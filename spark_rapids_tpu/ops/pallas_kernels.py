"""Pallas TPU kernels for shuffle-critical ops.

Where XLA's fusion already covers most of the engine, the shuffle map
side's hash-partition pass is worth a hand kernel: murmur3 is a chain of
int32 bit ops (rotates, xors, multiplies) that map 1:1 onto VPU lanes, and
fusing hash + pmod in VMEM avoids materializing the hash column in HBM.
(reference: the JNI Hash kernels feeding GpuHashPartitioningBase.)

TPU constraints honored: 2D (sublane, 128-lane) tiles, 32-bit ops only,
static partition count. Falls back to interpret mode off-TPU so tests run
on the CPU backend.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["pallas_partition_ids_i32"]

_LANES = 128
_SUBLANES = 8


def _x64_disabled():
    """jax.enable_x64(False) is the public spelling from ~0.6; older
    jax ships the equivalent as jax.experimental.disable_x64()."""
    if hasattr(jax, "enable_x64"):
        return jax.enable_x64(False)
    from jax.experimental import disable_x64
    return disable_x64()


def _make_kernel(num_partitions: int):
    def kernel(vals_ref, valid_ref, out_ref):
        x = vals_ref[:, :].astype(jnp.uint32)
        seed = jnp.uint32(42)

        def rotl(v, r):
            return (v << r) | (v >> (32 - r))

        k1 = x * jnp.uint32(0xCC9E2D51)
        k1 = rotl(k1, 15)
        k1 = k1 * jnp.uint32(0x1B873593)
        h1 = seed ^ k1
        h1 = rotl(h1, 13)
        h1 = h1 * jnp.uint32(5) + jnp.uint32(0xE6546B64)
        # fmix(h1, 4)
        h1 = h1 ^ jnp.uint32(4)
        h1 = h1 ^ (h1 >> 16)
        h1 = h1 * jnp.uint32(0x85EBCA6B)
        h1 = h1 ^ (h1 >> 13)
        h1 = h1 * jnp.uint32(0xC2B2AE35)
        h1 = h1 ^ (h1 >> 16)
        h = h1.astype(jnp.int32)
        # null keys hash to the seed (Spark semantics)
        h = jnp.where(valid_ref[:, :], h, jnp.int32(42))
        n = jnp.int32(num_partitions)
        m = h % n
        out_ref[:, :] = jnp.where(m < 0, m + n, m)
    return kernel


def pallas_partition_ids_i32(vals, validity, num_partitions: int,
                             interpret: bool = False):
    """Spark HashPartitioning pmod(murmur3(int32 key), n) as one VMEM-tiled
    Pallas pass. vals: int32[cap] with cap a multiple of 1024.

    Traced under disable_x64: the engine globally enables x64, but Mosaic
    cannot legalize the i64 index types x64 mode introduces; this kernel is
    pure 32-bit."""
    cap = vals.shape[0]
    tile = _SUBLANES * _LANES
    assert cap % tile == 0, "capacity must be a multiple of 1024"
    rows = cap // _LANES
    v2 = vals.reshape(rows, _LANES)
    m2 = validity.reshape(rows, _LANES)
    grid = (rows // _SUBLANES,)
    with _x64_disabled():
        out = pl.pallas_call(
            _make_kernel(num_partitions),
            grid=grid,
            in_specs=[
                pl.BlockSpec((_SUBLANES, _LANES), lambda i: (i, 0)),
                pl.BlockSpec((_SUBLANES, _LANES), lambda i: (i, 0)),
            ],
            out_specs=pl.BlockSpec((_SUBLANES, _LANES), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((rows, _LANES), jnp.int32),
            interpret=interpret,
        )(v2, m2)
    return out.reshape(cap)
