"""Order-key normalization: map any column to TPU-sortable key arrays.

TPU-native replacement for cudf's comparator-based sort/groupby
(reference: SortUtils.scala, cudf OrderByArg). Design constraint: TPU has no
native 64-bit lanes — XLA emulates s64/f64 — and the x64 rewrite cannot
implement f64<->s64 bitcasts. So keys avoid 64-bit bitcasts entirely:

  - bool/ints/decimal/date/timestamp: the value itself (signed order);
    descending = bitwise NOT (exact order reversal, no overflow)
  - float32: IEEE bitcast trick on 32-bit (supported): uint32 radix key;
    NaN canonicalized and ordered greatest (Spark), -0.0 == +0.0
  - float64: TWO keys (isnan, canonical value). NaN rows get canonical 0.0
    so equality/boundary checks are NaN-safe, and the isnan key orders NaN
    greatest per Spark; -0.0 canonicalized to +0.0
  - strings/binary: big-endian 4-byte chunks as uint32 (nchunks static
    per trace); padding 0x00 sorts first = byte-lexicographic order

Ascending argsort over the returned key list (most-significant first)
yields Spark's ordering; `group_boundaries` on the same arrays is exact
(no NaNs survive canonicalization).
"""
from __future__ import annotations

from typing import List, Sequence

import jax.numpy as jnp

from ..columnar import dtypes as dt
from .kernel_utils import CV

__all__ = ["order_keys", "string_chunk_keys", "lexsort", "group_boundaries",
           "nchunks_for_len"]


def nchunks_for_len(maxlen: int) -> int:
    """Chunk count for string keys of max byte length `maxlen`, rounded
    onto the shape-bucket grid (columnar/column.py set_bucket_policy) so
    chunk-count program signatures canonicalize the same way capacities
    do. The default grid keeps the historical next-power-of-two."""
    from ..columnar.column import bucket_chunks
    return bucket_chunks(max(1, -(-maxlen // 4)))


def _f32_key(x, descending):
    x = jnp.where(x == 0, jnp.zeros_like(x), x)          # -0.0 -> +0.0
    x = jnp.where(jnp.isnan(x), jnp.full_like(x, jnp.nan), x)
    b = x.view(jnp.int32).view(jnp.uint32)
    sign = jnp.uint32(0x80000000)
    k = jnp.where((b & sign) != 0, ~b, b | sign)
    return [~k if descending else k]


def _f64_keys(x, descending):
    x = jnp.where(x == 0, jnp.zeros_like(x), x)
    nan = jnp.isnan(x)
    canon = jnp.where(nan, jnp.zeros_like(x), x)
    nankey = nan.astype(jnp.uint8)                        # NaN greatest
    if descending:
        return [~nankey, -canon]
    return [nankey, canon]


def order_keys(cv: CV, dtype: dt.DataType, nchunks: int = 0,
               descending: bool = False) -> List[jnp.ndarray]:
    """Key arrays for one column (excluding the null key), most-significant
    first. Ascending unsigned/signed order of the keys == requested order."""
    if isinstance(dtype, (dt.StringType, dt.BinaryType)):
        ks = string_chunk_keys(cv, nchunks)
        return [~k for k in ks] if descending else ks
    x = cv.data
    if isinstance(dtype, dt.BooleanType):
        k = x.astype(jnp.uint8)
        return [~k if descending else k]
    if isinstance(dtype, dt.FloatType):
        return _f32_key(x, descending)
    if isinstance(dtype, dt.DoubleType):
        return _f64_keys(x, descending)
    if isinstance(dtype, dt.NullType):
        return [jnp.zeros(cv.capacity, jnp.uint8)]
    if isinstance(dtype, dt.DecimalType) and dtype.is_decimal128:
        # two keys: signed hi limb, then lo limb mapped to signed-
        # comparable order (bias flip of the top bit)
        hi = x[:, 1]
        lo = x[:, 0] ^ jnp.int64(-(1 << 63))   # flip the sign bit
        if descending:
            return [~hi, ~lo]
        return [hi, lo]
    # integral / decimal / date / timestamp: natural signed order
    return [~x if descending else x]


def string_chunk_keys(cv: CV, nchunks: int) -> List[jnp.ndarray]:
    """Big-endian uint32 4-byte chunk keys (32-bit native on TPU)."""
    n = cv.offsets.shape[0] - 1
    starts = cv.offsets[:-1]
    lens = cv.offsets[1:] - starts
    keys = []
    data = cv.data
    dcap = data.shape[0]
    for c in range(nchunks):
        base = starts + 4 * c
        key = jnp.zeros(n, jnp.uint32)
        for b in range(4):
            pos = base + b
            inb = (4 * c + b) < lens
            idx = jnp.clip(pos, 0, dcap - 1)
            byte = jnp.where(inb, data[idx], 0).astype(jnp.uint32)
            key = (key << 8) | byte
        keys.append(key)
    return keys


def lexsort(keys: Sequence[jnp.ndarray],
            allow_host: bool = True) -> jnp.ndarray:
    """Stable permutation ordering rows by keys[0], then keys[1], ...

    ONE variadic `lax.sort` over all key arrays (lexicographic, stable)
    with an iota payload operand that becomes the permutation — k times
    less sort work than the chained-argsort (LSD) formulation.

    On the CPU fallback backend, XLA's comparator sort is single-threaded
    scalar code (~10x slower than numpy's radix-ish sorts at 1M rows). A
    host-callback into np.lexsort recovers that — but jax.pure_callback
    proved unsafe under CONCURRENT executions (deadlocks inside
    shard_map; intermittent multi-minute stalls when several programs
    with callbacks run at once, XLA callback-queue starvation), so it is
    OPT-IN via SRTPU_HOST_SORT=1 for single-threaded batch workloads
    only. The default is the always-correct pure XLA sort; the hot
    paths that used to need big sorts (join builds, groupbys) now use
    the sort-free direct/hash paths instead.

    allow_host=False force-disables the callback regardless (shard_map
    callers).
    """
    import os

    import jax
    n = keys[0].shape[0]
    if (allow_host and os.environ.get("SRTPU_HOST_SORT") == "1"
            and jax.default_backend() == "cpu" and n >= 1 << 15):
        import numpy as np

        def _host_lexsort(*ks):
            # np.lexsort: LAST key is primary -> reverse
            return np.lexsort(ks[::-1]).astype(np.int32)

        return jax.pure_callback(
            _host_lexsort,
            jax.ShapeDtypeStruct((n,), jnp.int32),
            *keys, vmap_method="sequential")
    iota = jnp.arange(n, dtype=jnp.int32)
    ops = list(keys) + [iota]
    out = jax.lax.sort(ops, num_keys=len(keys), is_stable=True)
    return out[-1]


def group_boundaries(sorted_keys: Sequence[jnp.ndarray]) -> jnp.ndarray:
    """bool[n]: True where row starts a new group (row 0 is True)."""
    n = sorted_keys[0].shape[0]
    new = jnp.zeros(n, jnp.bool_).at[0].set(True)
    for k in sorted_keys:
        prev = jnp.roll(k, 1)
        new = new | (k != prev).at[0].set(True)
    return new


def string_nchunks(cv: CV, mask) -> int:
    """Static order-key chunk count covering the longest live+valid
    string (shared by aggregate/join/collect key sizing: dead and padding
    rows must not inflate the count)."""
    from ..utils.transfer import fetch_int
    lens = cv.offsets[1:] - cv.offsets[:-1]
    lens = jnp.where(mask & cv.validity, lens, 0)
    mx = fetch_int(jnp.max(lens)) if lens.shape[0] else 0
    return nchunks_for_len(max(mx, 1))
