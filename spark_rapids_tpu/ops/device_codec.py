"""Device-side shuffle-payload compression (nvcomp analog, TPU-native).

The reference compresses shuffle batches on the GPU with nvcomp LZ4
(NvcompLZ4CompressionCodec.scala, TableCompressionCodec.scala). LZ4's
greedy match-finding is a sequential dependency chain — a scalar loop
on a TPU core — so the TPU-native codec here is BYTE-PLANE PACKING:

  view the buffer as 64-bit words, chunk into 128-word (1 KiB) tiles,
  and per tile keep only the byte planes that contain any non-zero
  byte (an 8-bit mask per tile + the surviving planes).

Columnar shuffle payloads are dominated by int64/int32 lanes whose high
bytes are zero (keys, offsets, small measures), where this reaches
2-6x, fully vectorized in BOTH directions (transpose + cumsum +
gather/scatter — no data-dependent control flow). Incompressible bytes
cost only the per-tile mask (128 bytes per 128 KiB). Exactly
invertible for any byte content.

Layout: [u8 mask per tile | concatenated surviving 128-byte planes].
Compressed size = ntiles + 128 * popcount(masks).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["plane_compress", "plane_decompress", "TILE_BYTES"]

TILE_WORDS = 128
TILE_BYTES = TILE_WORDS * 8


def _pad_to_tiles(nbytes: int) -> int:
    return ((nbytes + TILE_BYTES - 1) // TILE_BYTES) * TILE_BYTES


@jax.jit
def plane_compress(buf):
    """uint8[N] (N a multiple of TILE_BYTES) -> (uint8[ntiles + N],
    compressed_nbytes). The output buffer is worst-case sized; the
    caller slices to a bucket of compressed_nbytes before moving it."""
    n = buf.shape[0]
    ntiles = n // TILE_BYTES
    tiles = buf.reshape(ntiles, TILE_WORDS, 8)
    planes = jnp.transpose(tiles, (0, 2, 1))      # (ntiles, 8, 128)
    nonzero = jnp.any(planes != 0, axis=2)        # (ntiles, 8)
    masks = jnp.sum(nonzero.astype(jnp.uint8)
                    << jnp.arange(8, dtype=jnp.uint8), axis=1)
    keep = nonzero.reshape(-1)                    # (ntiles*8,)
    kept_before = jnp.cumsum(keep.astype(jnp.int32)) - keep
    dest = ntiles + kept_before * TILE_WORDS      # byte offset per plane
    flat_planes = planes.reshape(ntiles * 8, TILE_WORDS)
    idx = (dest[:, None]
           + jnp.arange(TILE_WORDS, dtype=jnp.int32)[None, :])
    idx = jnp.where(keep[:, None], idx, ntiles + n)   # OOB drop slot
    out = jnp.zeros(ntiles + n + 1, jnp.uint8) \
        .at[:ntiles].set(masks) \
        .at[idx.reshape(-1)].set(flat_planes.reshape(-1))[:ntiles + n]
    total = ntiles + (jnp.sum(keep.astype(jnp.int32)) * TILE_WORDS)
    return out, total


@functools.partial(jax.jit, static_argnames=("nbytes",))
def plane_decompress(comp, nbytes: int):
    """Inverse of plane_compress: comp (uint8, any capacity >= the
    compressed size) -> uint8[nbytes]."""
    ntiles = nbytes // TILE_BYTES
    cap = comp.shape[0]
    masks = comp[:ntiles]
    keep = ((masks[:, None]
             >> jnp.arange(8, dtype=jnp.uint8)[None, :]) & 1) \
        .astype(jnp.bool_).reshape(-1)            # (ntiles*8,)
    kept_before = jnp.cumsum(keep.astype(jnp.int32)) - keep
    src = ntiles + kept_before * TILE_WORDS
    idx = (src[:, None]
           + jnp.arange(TILE_WORDS, dtype=jnp.int32)[None, :])
    idx = jnp.clip(idx, 0, cap - 1)
    flat = jnp.where(keep[:, None], comp[idx], 0)  # (ntiles*8, 128)
    planes = flat.reshape(ntiles, 8, TILE_WORDS)
    tiles = jnp.transpose(planes, (0, 2, 1))       # (ntiles, 128, 8)
    return tiles.reshape(nbytes)


def compress_array(arr):
    """Any-dtype device array -> (uint8 comp buffer, total_bytes device
    scalar, orig_nbytes). Pads to tile size; caller keeps shape/dtype."""
    nbytes = arr.size * arr.dtype.itemsize
    padded = _pad_to_tiles(max(nbytes, TILE_BYTES))
    if arr.dtype == jnp.bool_:
        u8 = arr.reshape(-1).astype(jnp.uint8)
    else:
        u8 = jax.lax.bitcast_convert_type(
            arr.reshape(-1), jnp.uint8).reshape(-1)
    if u8.shape[0] < padded:
        u8 = jnp.pad(u8, (0, padded - u8.shape[0]))
    comp, total = plane_compress(u8)
    return comp, total, nbytes


def decompress_array(comp, orig_nbytes: int, shape, dtype):
    """Inverse of compress_array on (possibly sliced) comp bytes."""
    padded = _pad_to_tiles(max(orig_nbytes, TILE_BYTES))
    u8 = plane_decompress(comp, padded)[:]
    itemsize = jnp.dtype(dtype).itemsize
    n = orig_nbytes // itemsize
    if jnp.dtype(dtype) == jnp.bool_:
        return u8[:n].astype(jnp.bool_).reshape(shape)
    words = u8[:n * itemsize].reshape(n, itemsize)
    out = jax.lax.bitcast_convert_type(words, dtype)
    return out.reshape(shape)
