"""Device concatenation of column values (cudf `Table.concatenate` analog).

Used by batch coalescing and aggregate merge. String concatenation rebuilds
a gap-free byte layout from per-part row lengths: naively shifting raw
offsets would extend each part's final row into that part's padding bytes
whenever the part is exactly full (offsets[-1] < data capacity cannot be
assumed), corrupting the row with trailing NULs.
"""
from __future__ import annotations

from typing import List, Sequence

import jax.numpy as jnp

from ..columnar import dtypes as dt
from .kernel_utils import CV

__all__ = ["concat_cvs", "concat_masks", "pad_cv", "pad_mask"]


def concat_cvs(parts: Sequence[CV], dtype: dt.DataType) -> CV:
    if len(parts) == 1:
        return parts[0]
    if parts[0].children:
        return _concat_nested(parts, dtype)
    data = jnp.concatenate([p.data for p in parts])
    valid = jnp.concatenate([p.validity for p in parts])
    if parts[0].offsets is None:
        return CV(data, valid)
    from .strings import rebuild_strings
    starts, lens = [], []
    shift = 0
    for p in parts:
        starts.append((p.offsets[:-1] + shift).astype(jnp.int32))
        lens.append((p.offsets[1:] - p.offsets[:-1]).astype(jnp.int32))
        shift += p.data.shape[0]
    return rebuild_strings(CV(data, valid),
                           jnp.concatenate(starts), jnp.concatenate(lens))


def _concat_nested(parts: Sequence[CV], dtype: dt.DataType) -> CV:
    """Concatenate list/struct columns. Lists rebuild a gap-free element
    layout (same reasoning as strings): children are concatenated
    recursively, then the referenced element ranges are re-gathered."""
    from ..columnar.column import Column
    from .gather import take
    valid = jnp.concatenate([p.validity for p in parts])
    if parts[0].offsets is None:  # struct
        kids = tuple(
            concat_cvs([p.children[i] for p in parts], f.dtype)
            for i, f in enumerate(dtype.fields))
        return CV(jnp.zeros(0, jnp.int8), valid, None, kids)
    elem_dt = Column.element_dtype(dtype)
    child_comb = concat_cvs([p.child for p in parts], elem_dt)
    starts, lens = [], []
    shift = 0
    for p in parts:
        ln = (p.offsets[1:] - p.offsets[:-1]).astype(jnp.int32)
        ln = jnp.where(p.validity, ln, 0)
        starts.append((p.offsets[:-1] + shift).astype(jnp.int32))
        lens.append(ln)
        shift += p.child.capacity
    starts = jnp.concatenate(starts)
    lens = jnp.concatenate(lens)
    n_out = valid.shape[0]
    new_off = jnp.concatenate([jnp.zeros(1, jnp.int32),
                               jnp.cumsum(lens).astype(jnp.int32)])
    out_cap = child_comb.capacity
    pos = jnp.arange(out_cap, dtype=jnp.int32)
    row = jnp.searchsorted(new_off[1:], pos, side="right").astype(jnp.int32)
    row = jnp.clip(row, 0, n_out - 1)
    src = starts[row] + (pos - new_off[row])
    elem_ok = pos < new_off[n_out]
    child = take(child_comb, src, elem_ok)
    return CV(jnp.zeros(0, jnp.int8), valid, new_off, (child,))


def concat_masks(masks: Sequence) -> jnp.ndarray:
    return jnp.concatenate(list(masks))


def pad_cv(cv: CV, capacity: int) -> CV:
    cap = cv.validity.shape[0]
    if cap >= capacity:
        return cv
    extra = capacity - cap
    valid = jnp.concatenate([cv.validity, jnp.zeros(extra, jnp.bool_)])
    if cv.children:
        if cv.offsets is None:  # struct: pad each field column
            kids = tuple(pad_cv(ch, capacity) for ch in cv.children)
            return CV(cv.data, valid, None, kids)
        last = cv.offsets[-1]
        off = jnp.concatenate([
            cv.offsets, jnp.broadcast_to(last, (extra,)).astype(jnp.int32)])
        return CV(cv.data, valid, off, cv.children)
    data = (jnp.concatenate(
        [cv.data, jnp.zeros((extra,) + cv.data.shape[1:], cv.data.dtype)])
        if cv.offsets is None else cv.data)
    if cv.offsets is None:
        return CV(data, valid)
    last = cv.offsets[-1]
    off = jnp.concatenate([
        cv.offsets, jnp.broadcast_to(last, (extra,)).astype(jnp.int32)])
    return CV(cv.data, valid, off)


def pad_mask(mask, capacity: int):
    cap = mask.shape[0]
    if cap >= capacity:
        return mask
    return jnp.concatenate([mask, jnp.zeros(capacity - cap, jnp.bool_)])
