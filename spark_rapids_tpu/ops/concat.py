"""Device concatenation of column values (cudf `Table.concatenate` analog).

Used by batch coalescing and aggregate merge. String concatenation rebuilds
a gap-free byte layout from per-part row lengths: naively shifting raw
offsets would extend each part's final row into that part's padding bytes
whenever the part is exactly full (offsets[-1] < data capacity cannot be
assumed), corrupting the row with trailing NULs.
"""
from __future__ import annotations

from typing import List, Sequence

import jax.numpy as jnp

from ..columnar import dtypes as dt
from .kernel_utils import CV

__all__ = ["concat_cvs", "concat_masks", "pad_cv", "pad_mask"]


def concat_cvs(parts: Sequence[CV], dtype: dt.DataType) -> CV:
    if len(parts) == 1:
        return parts[0]
    data = jnp.concatenate([p.data for p in parts])
    valid = jnp.concatenate([p.validity for p in parts])
    if parts[0].offsets is None:
        return CV(data, valid)
    from .strings import rebuild_strings
    starts, lens = [], []
    shift = 0
    for p in parts:
        starts.append((p.offsets[:-1] + shift).astype(jnp.int32))
        lens.append((p.offsets[1:] - p.offsets[:-1]).astype(jnp.int32))
        shift += p.data.shape[0]
    return rebuild_strings(CV(data, valid),
                           jnp.concatenate(starts), jnp.concatenate(lens))


def concat_masks(masks: Sequence) -> jnp.ndarray:
    return jnp.concatenate(list(masks))


def pad_cv(cv: CV, capacity: int) -> CV:
    cap = cv.validity.shape[0]
    if cap >= capacity:
        return cv
    extra = capacity - cap
    data = (jnp.concatenate(
        [cv.data, jnp.zeros((extra,) + cv.data.shape[1:], cv.data.dtype)])
        if cv.offsets is None else cv.data)
    valid = jnp.concatenate([cv.validity, jnp.zeros(extra, jnp.bool_)])
    if cv.offsets is None:
        return CV(data, valid)
    last = cv.offsets[-1]
    off = jnp.concatenate([
        cv.offsets, jnp.broadcast_to(last, (extra,)).astype(jnp.int32)])
    return CV(cv.data, valid, off)


def pad_mask(mask, capacity: int):
    cap = mask.shape[0]
    if cap >= capacity:
        return mask
    return jnp.concatenate([mask, jnp.zeros(capacity - cap, jnp.bool_)])
