"""Date/timestamp kernels (reference: datetimeExpressions.scala + JNI
DateTimeUtils). Pure integer math (Howard Hinnant's civil-from-days), no
host round-trips; timestamps are UTC microseconds (session-timezone
conversion lands with the timezone DB port)."""
from __future__ import annotations

import jax.numpy as jnp

from .kernel_utils import CV

__all__ = ["civil_from_days", "year", "month", "day", "day_of_week",
           "day_of_year", "quarter", "hour", "minute", "second",
           "micros_to_days", "days_in_month", "last_day"]

MICROS_PER_DAY = 86400 * 1_000_000
MICROS_PER_SEC = 1_000_000


def civil_from_days(days):
    """days since 1970-01-01 -> (year, month, day)."""
    z = days.astype(jnp.int64) + 719468
    era = jnp.where(z >= 0, z, z - 146096) // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = jnp.where(mp < 10, mp + 3, mp - 9)
    y = jnp.where(m <= 2, y + 1, y)
    return y.astype(jnp.int32), m.astype(jnp.int32), d.astype(jnp.int32)


def days_from_civil(y, m, d):
    y = y.astype(jnp.int64)
    m = m.astype(jnp.int64)
    d = d.astype(jnp.int64)
    y = jnp.where(m <= 2, y - 1, y)
    era = jnp.where(y >= 0, y, y - 399) // 400
    yoe = y - era * 400
    mp = jnp.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return (era * 146097 + doe - 719468).astype(jnp.int32)


def micros_to_days(micros):
    return (micros // MICROS_PER_DAY).astype(jnp.int32)


def year(days):
    return civil_from_days(days)[0]


def month(days):
    return civil_from_days(days)[1]


def day(days):
    return civil_from_days(days)[2]


def quarter(days):
    m = civil_from_days(days)[1]
    return ((m - 1) // 3 + 1).astype(jnp.int32)


def day_of_week(days):
    """Spark dayofweek: 1 = Sunday ... 7 = Saturday."""
    d = days.astype(jnp.int64)
    dow = (d + 4) % 7  # 1970-01-01 was a Thursday (0=Sun basis: +4)
    dow = jnp.where(dow < 0, dow + 7, dow)
    return (dow + 1).astype(jnp.int32)


def day_of_year(days):
    y, m, d = civil_from_days(days)
    start = days_from_civil(y, jnp.ones_like(m), jnp.ones_like(d))
    return (days.astype(jnp.int32) - start + 1).astype(jnp.int32)


def _is_leap(y):
    return ((y % 4 == 0) & (y % 100 != 0)) | (y % 400 == 0)


def days_in_month(y, m):
    base = jnp.asarray([31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31],
                       jnp.int32)
    d = base[jnp.clip(m - 1, 0, 11)]
    return jnp.where((m == 2) & _is_leap(y), 29, d).astype(jnp.int32)


def last_day(days):
    y, m, d = civil_from_days(days)
    return (days.astype(jnp.int32) - d + days_in_month(y, m))


def _time_of_day(micros):
    tod = micros - micros_to_days(micros).astype(jnp.int64) * MICROS_PER_DAY
    return tod


def hour(micros):
    return (_time_of_day(micros) // (3600 * MICROS_PER_SEC)).astype(
        jnp.int32)


def minute(micros):
    return ((_time_of_day(micros) // (60 * MICROS_PER_SEC)) % 60).astype(
        jnp.int32)


def second(micros):
    return ((_time_of_day(micros) // MICROS_PER_SEC) % 60).astype(jnp.int32)
