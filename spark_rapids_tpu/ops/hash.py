"""Columnar hash kernels — Spark-compatible Murmur3 (seed 42).

Replaces the reference's JNI Hash kernels (reference: HashFunctions.scala,
jni Hash: murmur3/xxhash64). Spark's hash() uses Murmur3_x86_32 with
hashInt/hashLong on the raw bits; implemented here in pure int32 jnp ops
(native TPU lanes), vectorized across rows. Spark-bit-compatible for
bool/int/long/date/timestamp/decimal64 and float32. Strings hash the
first 64 bytes Spark-style plus a tail-word + length fold (engine-internal
beyond 64 bytes); float64 uses a frexp decomposition (engine-internal —
the TPU x64 rewrite cannot bitcast f64). Documented in
docs/compatibility.md.

Null handling follows Spark: a null input leaves the running hash
unchanged (the seed/previous column hash passes through).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..columnar import dtypes as dt
from .kernel_utils import CV

__all__ = ["murmur3_cv", "murmur3_row_hash", "partition_ids",
           "fold64", "avalanche32", "hash_once_rows",
           "xxhash64_cv", "xxhash64_row_hash",
           "hive_hash_cv", "hive_hash_row_hash"]

# numpy (NOT jnp) scalars: module-level eager jnp constants become
# captured device buffers hoisted into executable parameters, and the
# dispatch fast path drops them when an executable's own output is fed
# back as an argument ("supplied N buffers but compiled program expected
# N+2") — np constants bake into the HLO as literals instead
import numpy as _np

_C1 = _np.int32(-862048943)    # 0xcc9e2d51
_C2 = _np.int32(461845907)     # 0x1b873593


def _rotl(x, r):
    ux = x.astype(jnp.uint32)
    return ((ux << r) | (ux >> (32 - r))).astype(jnp.int32)


def _mix_k1(k1):
    k1 = (k1 * _C1).astype(jnp.int32)
    k1 = _rotl(k1, 15)
    return (k1 * _C2).astype(jnp.int32)


def _mix_h1(h1, k1):
    h1 = (h1 ^ k1).astype(jnp.int32)
    h1 = _rotl(h1, 13)
    return (h1 * jnp.int32(5) + jnp.int32(-430675100)).astype(jnp.int32)


def _fmix(h1, length):
    h1 = (h1 ^ jnp.int32(length)).astype(jnp.int32)
    u = h1.astype(jnp.uint32)
    u = u ^ (u >> 16)
    u = (u * jnp.uint32(-2048144789 & 0xFFFFFFFF))
    u = u ^ (u >> 13)
    u = (u * jnp.uint32(-1028477387 & 0xFFFFFFFF))
    u = u ^ (u >> 16)
    return u.astype(jnp.int32)


def _hash_int32(x_i32, seed_i32):
    h1 = _mix_h1(seed_i32, _mix_k1(x_i32))
    return _fmix(h1, 4)


def _hash_int64(x_i64, seed_i32):
    lo = (x_i64 & 0xFFFFFFFF).astype(jnp.uint32).astype(jnp.int32)
    hi = ((x_i64 >> 32) & 0xFFFFFFFF).astype(jnp.uint32).astype(jnp.int32)
    h1 = _mix_h1(seed_i32, _mix_k1(lo))
    h1 = _mix_h1(h1, _mix_k1(hi))
    return _fmix(h1, 8)


def murmur3_cv(cv: CV, dtype: dt.DataType, seed):
    """Per-row murmur3 of one column, folding into `seed` (int32 array).
    Rows with null input return the seed unchanged (Spark semantics)."""
    x = cv.data
    if isinstance(dtype, dt.BooleanType):
        h = _hash_int32(jnp.where(x, 1, 0).astype(jnp.int32), seed)
    elif isinstance(dtype, (dt.ByteType, dt.ShortType, dt.IntegerType,
                            dt.DateType)):
        h = _hash_int32(x.astype(jnp.int32), seed)
    elif isinstance(dtype, (dt.LongType, dt.TimestampType)):
        h = _hash_int64(x.astype(jnp.int64), seed)
    elif isinstance(dtype, dt.DecimalType):
        if dtype.is_decimal128:
            # engine-internal: fold the two limbs (Spark hashes the
            # BigDecimal byte array for p>18 — documented deviation)
            h = _hash_int64(x[:, 0] ^ x[:, 1], seed)
        else:
            h = _hash_int64(x.astype(jnp.int64), seed)
    elif isinstance(dtype, dt.FloatType):
        # Spark: -0.0 -> 0.0, then hash the int bits
        xx = jnp.where(x == 0, jnp.zeros_like(x), x)
        h = _hash_int32(xx.view(jnp.int32), seed)
    elif isinstance(dtype, dt.DoubleType):
        xx = jnp.where(x == 0, jnp.zeros_like(x), x)
        # avoid f64 bitcast (unsupported under TPU x64 rewrite): decompose
        # via f32 cast of mantissa halves is lossy, so hash the pair
        # (int64 of scaled frexp) — engine-internal consistency only.
        m, e = jnp.frexp(jnp.abs(xx))
        mant = (m * (2.0 ** 53)).astype(jnp.int64)
        mant = jnp.where(xx < 0, -mant, mant)
        h = _hash_int64(mant ^ (e.astype(jnp.int64) << 1), seed)
    elif isinstance(dtype, (dt.StringType, dt.BinaryType)):
        h = _hash_string(cv, seed)
    else:
        raise NotImplementedError(f"hash({dtype})")
    return jnp.where(cv.validity, h, seed)


def _hash_string(cv: CV, seed):
    """Spark Murmur3_x86_32.hashUnsafeBytes: mix each full 4-byte
    little-endian word, then each remaining tail byte individually as a
    sign-extended int (its own mixK1/mixH1 round). Exact for strings up to
    64 bytes; beyond that a last-word fold keeps common-prefix keys apart
    (engine-internal, documented in docs/compatibility.md)."""
    n = cv.offsets.shape[0] - 1
    starts = cv.offsets[:-1]
    lens = (cv.offsets[1:] - starts).astype(jnp.int32)
    data = cv.data
    dcap = data.shape[0]
    # Practical bound: 64 bytes (engine-internal hashing for exchange).
    MAXB = 64
    h1 = seed
    nwords = MAXB // 4
    nfull = lens // 4
    for w in range(nwords):
        base = starts + 4 * w
        word = jnp.zeros(n, jnp.int32)
        for b in range(4):
            idx = jnp.clip(base + b, 0, dcap - 1)
            word = word | (data[idx].astype(jnp.int32) << (8 * b))
        h1 = jnp.where(w < nfull, _mix_h1(h1, _mix_k1(word)), h1)
    # tail (lens % 4 bytes): one round per byte, sign-extended
    overlong = lens > MAXB
    aligned = nfull * 4
    for t in range(3):
        pos = aligned + t
        idx = jnp.clip(starts + pos, 0, dcap - 1)
        byte = data[idx].astype(jnp.int32)
        byte = jnp.where(byte >= 128, byte - 256, byte)
        active = (pos < lens) & (~overlong)
        h1 = jnp.where(active, _mix_h1(h1, _mix_k1(byte)), h1)
    # beyond the 64-byte prefix, fold in the LAST word so common-prefix
    # keys (URLs, paths) do not collapse into one partition
    tail_base = jnp.maximum(starts, starts + lens - 4)
    tail = jnp.zeros(n, jnp.int32)
    for b in range(4):
        idx = jnp.clip(tail_base + b, 0, dcap - 1)
        inb = b < lens
        byte = jnp.where(inb, data[idx], 0).astype(jnp.int32)
        tail = tail | (byte << (8 * b))
    h1 = jnp.where(overlong, _mix_h1(h1, _mix_k1(tail)), h1)
    return _fmix(h1, lens)


def murmur3_row_hash(cvs, dtypes, seed: int = 42):
    """Row hash across columns, Spark style: fold column hashes left to
    right starting from the seed."""
    n = cvs[0].validity.shape[0]
    h = jnp.full(n, seed, jnp.int32)
    for cv, dtp in zip(cvs, dtypes):
        h = murmur3_cv(cv, dtp, h)
    return h


def partition_ids(cvs, dtypes, num_partitions: int, seed: int = 42):
    """Spark's HashPartitioning: pmod(murmur3, n)."""
    h = murmur3_row_hash(cvs, dtypes, seed)
    m = h % jnp.int32(num_partitions)
    return jnp.where(m < 0, m + num_partitions, m).astype(jnp.int32)


# ----------------------------------------------------------------------
# Hash-once 64-bit keying (xxhash64-style) for the aggregation fast path
# ----------------------------------------------------------------------
# The grouped-aggregation hash pass needs a bucket hash AND exact
# equality keys for every grouping column. For string keys the equality
# keys are the padded 4-byte chunk words (ops/sortkeys.py) — already an
# O(bytes) read of the column. Hashing the SAME words with xxhash64-style
# mixing gives the bucket hash for free: one byte pass total, instead of
# murmur3's second independent walk over the string bytes (the reference
# leans on cudf's hash-based string keying the same way; xxhash64 is the
# jni Hash kernel family's second algorithm). Engine-internal only —
# exchanges keep Spark-compatible murmur3.

_P64_1 = 0x9E3779B185EBCA87
_P64_2 = 0xC2B2AE3D27D4EB4F
_P64_3 = 0x165667B19E3779F9


def fold64(h, a):
    """One xxhash64-style accumulation round folding integer array `a`
    into the uint64 accumulator `h` (element-wise, vectorized)."""
    a64 = (a.astype(jnp.uint64) * jnp.uint64(_P64_2))
    a64 = (a64 << 31) | (a64 >> 33)
    a64 = a64 * jnp.uint64(_P64_1)
    h = h ^ a64
    h = ((h << 27) | (h >> 37)) * jnp.uint64(_P64_1) \
        + jnp.uint64(_P64_3)
    return h


def avalanche32(h):
    """Finalize a uint64 accumulator into a well-mixed int32 (bucket
    index source)."""
    h = h ^ (h >> 33)
    h = h * jnp.uint64(_P64_2)
    h = h ^ (h >> 29)
    h = h * jnp.uint64(_P64_3)
    h = h ^ (h >> 32)
    return (h & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32) \
        .astype(jnp.int32)


def hash_once_rows(eq_arrays, seed: int = 0x9E3779B1):
    """Row bucket hash derived from the already-built equality key
    arrays (null flags + order-key chunk words, possibly uint64-packed):
    every column's every key array folds into one 64-bit accumulator,
    avalanched to int32. Equal rows hash equal by construction (the
    arrays ARE the equality definition); no second pass over string
    bytes. `eq_arrays` is a list (per column) of lists of arrays."""
    n = eq_arrays[0][0].shape[0] if eq_arrays and eq_arrays[0] else 0
    h = jnp.full(n, seed, jnp.uint64)
    for arrs in eq_arrays:
        for a in arrs:
            h = fold64(h, a)
    return avalanche32(h)


# ----------------------------------------------------------------------
# Spark-facing xxhash64 / hive-hash row hashes (reference: the jni Hash
# kernel family's other two algorithms next to murmur3 — XXHash64.scala /
# HiveHash in HashFunctions). Same fold-left null semantics as murmur3:
# a null input passes the running hash through unchanged (xxhash64);
# hive-hash contributes 0 for nulls (Hive's ObjectInspectorUtils).
# ----------------------------------------------------------------------

_P64_4 = 0x85EBCA77C2B2AE63
_P64_5 = 0x27D4EB2F165667C5


def _rotl64(x, r):
    return (x << r) | (x >> (64 - r))


def _xxh_fmix(h):
    h = h ^ (h >> 33)
    h = h * jnp.uint64(_P64_2)
    h = h ^ (h >> 29)
    h = h * jnp.uint64(_P64_3)
    return h ^ (h >> 32)


def _xxh_int(x_i32, seed_u64, length=4):
    """Spark XXH64.hashInt: 4-byte input fast path."""
    h = seed_u64 + jnp.uint64(_P64_5 + length)
    w = (x_i32.astype(jnp.int64) & 0xFFFFFFFF).astype(jnp.uint64)
    h = h ^ (w * jnp.uint64(_P64_1))
    h = _rotl64(h, 23) * jnp.uint64(_P64_2) + jnp.uint64(_P64_3)
    return _xxh_fmix(h)


def _xxh_long(x_i64, seed_u64, length=8):
    """Spark XXH64.hashLong: 8-byte input fast path."""
    h = seed_u64 + jnp.uint64(_P64_5 + length)
    k1 = _rotl64(x_i64.astype(jnp.uint64) * jnp.uint64(_P64_2), 31) \
        * jnp.uint64(_P64_1)
    h = h ^ k1
    h = _rotl64(h, 27) * jnp.uint64(_P64_1) + jnp.uint64(_P64_4)
    return _xxh_fmix(h)


def _xxh_string(cv: CV, seed_u64):
    """XXH64 over the byte payload: Spark's hashUnsafeBytes small-input
    path (8-byte rounds, a 4-byte round, tail bytes), exact for strings
    under 32 bytes and byte-faithful to that schedule up to 64; beyond
    the 64-byte prefix a last-word fold keeps common-prefix keys apart
    (engine-internal, same bound as the murmur3 string path)."""
    n = cv.offsets.shape[0] - 1
    starts = cv.offsets[:-1]
    lens = (cv.offsets[1:] - starts).astype(jnp.int32)
    data = cv.data
    dcap = data.shape[0]
    MAXB = 64
    h = seed_u64 + jnp.uint64(_P64_5) + lens.astype(jnp.uint64)
    overlong = lens > MAXB
    eff = jnp.where(overlong, MAXB, lens)
    nfull8 = eff // 8
    for w in range(MAXB // 8):
        base = starts + 8 * w
        word = jnp.zeros(n, jnp.uint64)
        for b in range(8):
            idx = jnp.clip(base + b, 0, dcap - 1)
            word = word | (data[idx].astype(jnp.uint64) << (8 * b))
        k1 = _rotl64(word * jnp.uint64(_P64_2), 31) * jnp.uint64(_P64_1)
        step = _rotl64(h ^ k1, 27) * jnp.uint64(_P64_1) \
            + jnp.uint64(_P64_4)
        h = jnp.where(w < nfull8, step, h)
    aligned = nfull8 * 8
    # one 4-byte round when >= 4 bytes remain
    word4 = jnp.zeros(n, jnp.uint64)
    for b in range(4):
        idx = jnp.clip(starts + aligned + b, 0, dcap - 1)
        word4 = word4 | (data[idx].astype(jnp.uint64) << (8 * b))
    has4 = aligned + 4 <= eff
    step = _rotl64(h ^ (word4 * jnp.uint64(_P64_1)), 23) \
        * jnp.uint64(_P64_2) + jnp.uint64(_P64_3)
    h = jnp.where(has4, step, h)
    aligned = jnp.where(has4, aligned + 4, aligned)
    # tail bytes, one round each
    for t in range(3):
        pos = aligned + t
        idx = jnp.clip(starts + pos, 0, dcap - 1)
        byte = data[idx].astype(jnp.uint64)
        step = _rotl64(h ^ (byte * jnp.uint64(_P64_5)), 11) \
            * jnp.uint64(_P64_1)
        h = jnp.where(pos < eff, step, h)
    # beyond the prefix: fold the LAST word (engine-internal)
    tail_base = jnp.maximum(starts, starts + lens - 8)
    tail = jnp.zeros(n, jnp.uint64)
    for b in range(8):
        idx = jnp.clip(tail_base + b, 0, dcap - 1)
        tail = tail | (data[idx].astype(jnp.uint64) << (8 * b))
    h = jnp.where(overlong, fold64(h, tail), h)
    return _xxh_fmix(h)


def xxhash64_cv(cv: CV, dtype: dt.DataType, seed_u64):
    """Per-row xxhash64 of one column folding into `seed_u64` (uint64
    array); null rows pass the seed through (Spark semantics)."""
    x = cv.data
    if isinstance(dtype, dt.BooleanType):
        h = _xxh_int(jnp.where(x, 1, 0).astype(jnp.int32), seed_u64)
    elif isinstance(dtype, (dt.ByteType, dt.ShortType, dt.IntegerType,
                            dt.DateType)):
        h = _xxh_int(x.astype(jnp.int32), seed_u64)
    elif isinstance(dtype, (dt.LongType, dt.TimestampType)):
        h = _xxh_long(x.astype(jnp.int64), seed_u64)
    elif isinstance(dtype, dt.DecimalType):
        if dtype.is_decimal128:
            h = _xxh_long(x[:, 0] ^ x[:, 1], seed_u64)
        else:
            h = _xxh_long(x.astype(jnp.int64), seed_u64)
    elif isinstance(dtype, dt.FloatType):
        xx = jnp.where(x == 0, jnp.zeros_like(x), x)
        h = _xxh_int(xx.view(jnp.int32), seed_u64)
    elif isinstance(dtype, dt.DoubleType):
        # same frexp decomposition as murmur3 (no f64 bitcast on TPU):
        # engine-internally consistent, documented deviation
        xx = jnp.where(x == 0, jnp.zeros_like(x), x)
        m, e = jnp.frexp(jnp.abs(xx))
        mant = (m * (2.0 ** 53)).astype(jnp.int64)
        mant = jnp.where(xx < 0, -mant, mant)
        h = _xxh_long(mant ^ (e.astype(jnp.int64) << 1), seed_u64)
    elif isinstance(dtype, (dt.StringType, dt.BinaryType)):
        h = _xxh_string(cv, seed_u64)
    else:
        raise NotImplementedError(f"xxhash64({dtype})")
    return jnp.where(cv.validity, h, seed_u64)


def xxhash64_row_hash(cvs, dtypes, seed: int = 42):
    """Row xxhash64 across columns, Spark style: fold column hashes
    left to right from the int64 seed; int64 result."""
    n = cvs[0].validity.shape[0]
    h = jnp.full(n, jnp.uint64(seed))
    for cv, dtp in zip(cvs, dtypes):
        h = xxhash64_cv(cv, dtp, h)
    return h.astype(jnp.int64)


def hive_hash_cv(cv: CV, dtype: dt.DataType):
    """Hive hashCode of one column (int32); null rows contribute 0
    (ObjectInspectorUtils.hashCode semantics)."""
    x = cv.data
    if isinstance(dtype, dt.BooleanType):
        h = jnp.where(x, 1, 0).astype(jnp.int32)
    elif isinstance(dtype, (dt.ByteType, dt.ShortType, dt.IntegerType,
                            dt.DateType)):
        h = x.astype(jnp.int32)
    elif isinstance(dtype, (dt.LongType, dt.TimestampType)):
        v = x.astype(jnp.int64)
        h = (v ^ (v.astype(jnp.uint64) >> 32).astype(jnp.int64)) \
            .astype(jnp.int32)
    elif isinstance(dtype, dt.DecimalType):
        v = (x[:, 0] ^ x[:, 1]) if dtype.is_decimal128 \
            else x.astype(jnp.int64)
        h = (v ^ (v.astype(jnp.uint64) >> 32).astype(jnp.int64)) \
            .astype(jnp.int32)
    elif isinstance(dtype, dt.FloatType):
        xx = jnp.where(x == 0, jnp.zeros_like(x), x)
        h = xx.view(jnp.int32)
    elif isinstance(dtype, dt.DoubleType):
        xx = jnp.where(x == 0, jnp.zeros_like(x), x)
        m, e = jnp.frexp(jnp.abs(xx))
        mant = (m * (2.0 ** 53)).astype(jnp.int64)
        mant = jnp.where(xx < 0, -mant, mant)
        v = mant ^ (e.astype(jnp.int64) << 1)
        h = (v ^ (v.astype(jnp.uint64) >> 32).astype(jnp.int64)) \
            .astype(jnp.int32)
    elif isinstance(dtype, (dt.StringType, dt.BinaryType)):
        # Java String.hashCode polynomial over the UTF-8 bytes, bounded
        # at the same 64-byte prefix as the other string hashes
        n = cv.offsets.shape[0] - 1
        starts = cv.offsets[:-1]
        lens = (cv.offsets[1:] - starts).astype(jnp.int32)
        data, dcap = cv.data, cv.data.shape[0]
        h = jnp.zeros(n, jnp.int32)
        for pos in range(64):
            idx = jnp.clip(starts + pos, 0, dcap - 1)
            byte = data[idx].astype(jnp.int32)
            byte = jnp.where(byte >= 128, byte - 256, byte)
            h = jnp.where(pos < lens,
                          (h * jnp.int32(31) + byte).astype(jnp.int32),
                          h)
    else:
        raise NotImplementedError(f"hive_hash({dtype})")
    return jnp.where(cv.validity, h, jnp.int32(0))


def hive_hash_row_hash(cvs, dtypes):
    """Hive row hash: result = result * 31 + columnHash, folded left to
    right from 0 (int32 wraparound)."""
    n = cvs[0].validity.shape[0]
    h = jnp.zeros(n, jnp.int32)
    for cv, dtp in zip(cvs, dtypes):
        h = (h * jnp.int32(31) + hive_hash_cv(cv, dtp)) \
            .astype(jnp.int32)
    return h


# bloom-filter hash scheme shared by BloomFilterAggregate (build),
# BloomFilterMightContain (foldable probe), and RuntimeBloomFilterExec
# (runtime join filter): TWO murmur3 passes combined as h1 + i*h2 over
# a power-of-two bit count. ONE definition — a drifted copy would
# build and probe mismatched positions (silent false negatives).
BLOOM_SEED1 = 0
BLOOM_SEED2 = -1749833076


def bloom_positions(cv, dtype, k: int, num_bits: int):
    """Per-row bloom bit positions: k int32 arrays; invalid rows get
    -1 in every position."""
    import jax.numpy as jnp
    h1 = murmur3_cv(cv, dtype, jnp.int32(BLOOM_SEED1)) \
        .astype(jnp.uint32)
    h2 = murmur3_cv(cv, dtype, jnp.int32(BLOOM_SEED2)) \
        .astype(jnp.uint32)
    m = jnp.uint32(num_bits)
    out = []
    for i in range(k):
        p = ((h1 + jnp.uint32(i) * h2) % m).astype(jnp.int32)
        out.append(jnp.where(cv.validity, p, -1))
    return out
