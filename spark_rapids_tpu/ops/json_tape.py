"""Device-side get_json_object over the string byte tape.

The TPU answer to the reference's hand-written CUDA JSON kernel (JNI
``JSONUtils.getJsonObject``, GpuGetJsonObject.scala): instead of a
per-row character state machine, the WHOLE column's byte tape is
classified in parallel with global-cumsum-rebased segmented scans —

  * escape parity (run length of preceding backslashes, via a clamped
    cummax of non-backslash positions),
  * in-string parity (cumsum of unescaped quotes per row),
  * structural depth (cumsum of +/-1 braces outside strings),
  * next-non-whitespace (reverse cummin of non-ws positions),

and each static path step (field / array index — SCALAR paths) narrows a
per-row [start, end) span with one masked segment-min per probe. The
result span is sliced out with the shared string-rebuild gather and
simple escapes are folded on the (much smaller) result column.

Deviations (documented in docs/compatibility.md): \\uXXXX escapes pass
through verbatim; malformed JSON yields null (Spark's error behavior on
malformed rows is also null, but the boundary cases differ); wildcard
paths stay on the host bridge.
"""
from __future__ import annotations

from typing import List, Tuple

import jax
import jax.numpy as jnp

from .kernel_utils import CV
from .strings import byte_row_map, rebuild_strings

__all__ = ["device_path_supported", "get_json_object_tape"]

_BIG = jnp.int32(2**30)


def device_path_supported(steps: List[Tuple[str, object]]) -> bool:
    """Scalar paths only: field and non-negative index steps."""
    return all(kind == "field" or (kind == "index" and arg >= 0)
               for kind, arg in steps)


def _seg_cumsum_excl(x, row, offsets):
    """Per-row EXCLUSIVE prefix sum over the byte tape: global cumsum
    rebased at each row start (no associative-scan primitive needed)."""
    c = jnp.cumsum(x)
    excl = c - x                       # exclusive global
    base = jnp.concatenate([jnp.zeros(1, c.dtype), c])[offsets[:-1]]
    return excl - base[row]


def _classify(data, offsets, row):
    """Per-byte flags for the whole tape."""
    pos = jnp.arange(data.shape[0], dtype=jnp.int32)
    d = data.astype(jnp.int32)
    row_start = offsets[:-1][row]
    row_end = offsets[1:][row]
    in_row = (pos >= row_start) & (pos < row_end)

    # escape parity: j = last non-backslash position STRICTLY before pos
    # (clamped to row_start-1); run of backslashes = pos-1-j; a byte is
    # escaped iff that run is odd
    non_bs = jnp.where((d != 92) | ~in_row, pos, -_BIG)
    nb_cm = jax.lax.cummax(non_bs)
    prev_nb = jnp.concatenate([jnp.full(1, -1, jnp.int32), nb_cm[:-1]])
    j = jnp.maximum(prev_nb, row_start - 1)
    escaped = ((pos - 1 - j) % 2) == 1

    quote = (d == 34) & ~escaped & in_row
    qpar = _seg_cumsum_excl(quote.astype(jnp.int32), row, offsets)
    in_str = (qpar % 2) == 1          # content + CLOSING quote bytes

    structural = ~in_str & in_row
    opens = structural & ((d == 123) | (d == 91))     # { [
    closes = structural & ((d == 125) | (d == 93))    # } ]
    delta = opens.astype(jnp.int32) - closes.astype(jnp.int32)
    # EXCLUSIVE depth: '{' at depth D -> its content bytes AND its
    # matching '}' byte all see D+1 (the closer's own -1 is excluded
    # from its exclusive prefix)
    depth = _seg_cumsum_excl(delta, row, offsets)

    is_ws = in_row & ((d == 32) | (d == 9) | (d == 10) | (d == 13))
    # next non-ws position >= pos (within the tape; row bound is checked
    # at use sites): reverse cummin of non-ws positions
    nws = jnp.where(~is_ws, pos, _BIG)
    nnw = jnp.flip(jax.lax.cummin(jnp.flip(nws)))
    return pos, d, in_row, escaped, in_str, depth, nnw, row_start, row_end


def _first_where(cond, pos, row, n):
    """Per-row first position satisfying cond (else _BIG)."""
    masked = jnp.where(cond, pos, _BIG)
    return jax.ops.segment_min(masked, row, n)


def get_json_object_tape(cv: CV, steps, out_data_capacity: int) -> CV:
    """Evaluate a scalar JSON path over a string column on device."""
    data, offsets, validity = cv.data, cv.offsets, cv.validity
    n = offsets.shape[0] - 1
    (pos, d, in_row, escaped, in_str, depth, nnw,
     row_start, row_end) = _classify(data, offsets, row := byte_row_map(
         offsets, data.shape[0]))

    def clampget(arr, idx):
        return arr[jnp.clip(idx, 0, arr.shape[0] - 1)]

    # current value span per row: v = first non-ws byte
    v = clampget(nnw, offsets[:-1])
    e = offsets[1:]
    ok = (v < e) & validity

    structural_quote = (d == 34) & ~in_str & in_row

    for kind, arg in steps:
        dv = clampget(depth, v)
        if kind == "field":
            key = arg.encode("utf-8")
            k = len(key)
            # value must be an object
            ok = ok & (clampget(d, v) == 123)
            # candidate key quotes at depth dv+1 inside [v, e)
            cand = (structural_quote
                    & (depth == dv[row] + 1)
                    & (pos > v[row]) & (pos < e[row]) & ok[row])
            # key content match (static unroll over key bytes), no
            # escapes inside, closing quote right after
            match = cand
            for i, b in enumerate(key):
                match = match & (clampget(d, pos + 1 + i) == b) \
                    & ~clampget(escaped, pos + 1 + i) \
                    & (clampget(d, pos + 1 + i) != 92)
            close_q = pos + 1 + k
            match = match & (clampget(d, close_q) == 34) \
                & clampget(in_str, close_q)
            # then ':' as next non-ws
            colon = clampget(nnw, close_q + 1)
            match = match & (clampget(d, colon) == 58) \
                & (colon < e[row])
            kp = _first_where(match, pos, row, n)
            ok = ok & (kp < _BIG)
            kp_safe = jnp.clip(kp, 0, data.shape[0] - 1)
            colon_r = clampget(nnw, kp_safe + 2 + k)
            new_v = clampget(nnw, colon_r + 1)
            v = jnp.where(ok, new_v, v)
        else:  # index
            idx_want = int(arg)
            ok = ok & (clampget(d, v) == 91)
            inside = (pos > v[row]) & (pos < e[row]) & ok[row] & in_row
            comma = inside & ~in_str & (d == 44) & (depth == dv[row] + 1)
            if idx_want == 0:
                new_v = clampget(nnw, jnp.clip(v + 1, 0,
                                               data.shape[0] - 1))
                # empty array -> not found
                ok = ok & (clampget(d, new_v) != 93)
            else:
                ccount = _seg_cumsum_excl(comma.astype(jnp.int32), row,
                                          offsets)
                nth = comma & (ccount == idx_want - 1)
                cp = _first_where(nth, pos, row, n)
                ok = ok & (cp < _BIG)
                new_v = clampget(nnw, jnp.clip(cp, 0,
                                               data.shape[0] - 1) + 1)
                ok = ok & (clampget(d, new_v) != 93)
            v = jnp.where(ok, new_v, v)
        # narrow e to the end of the selected value
        dv2 = clampget(depth, v)
        first_b = clampget(d, v)
        is_container = (first_b == 123) | (first_b == 91)
        closer = jnp.where(first_b == 123, 125, 93)
        cont_end = _first_where(
            (pos > v[row]) & in_row & ~in_str
            & (d == closer[row]) & (depth == dv2[row] + 1),
            pos, row, n)
        is_string = first_b == 34
        str_end = _first_where(
            (pos > v[row]) & in_row & (d == 34) & ~escaped & in_str,
            pos, row, n)
        scal_end = _first_where(
            (pos > v[row]) & in_row & ~in_str
            & ((d == 44) | (d == 125) | (d == 93))
            & (depth == dv2[row]),
            pos, row, n)
        new_e = jnp.where(is_container, cont_end + 1,
                          jnp.where(is_string, str_end + 1, scal_end))
        new_e = jnp.minimum(new_e, e)
        ok = ok & (new_e > v)
        e = jnp.where(ok, new_e, e)

    # ---- extract [v, e) ------------------------------------------------
    first_b = clampget(d, v)
    is_string = first_b == 34
    # strings: strip surrounding quotes
    out_s = jnp.where(is_string, v + 1, v)
    out_e = jnp.where(is_string, e - 1, e)
    # scalars: trim trailing whitespace ('{"a": 1 }' -> '1', not '1 ')
    # via the last non-ws position at or before out_e-1
    is_ws_b = in_row & ((d == 32) | (d == 9) | (d == 10) | (d == 13))
    pnw = jax.lax.cummax(jnp.where(~is_ws_b & in_row, pos, -_BIG))
    trimmed = clampget(pnw, out_e - 1) + 1
    out_e = jnp.where(is_string, out_e,
                      jnp.clip(trimmed, out_s, out_e))
    lens = jnp.maximum(out_e - out_s, 0)
    # JSON null -> SQL NULL (match 'null' exactly)
    is_null_lit = ((lens == 4)
                   & (clampget(d, out_s) == 110)
                   & (clampget(d, out_s + 1) == 117)
                   & (clampget(d, out_s + 2) == 108)
                   & (clampget(d, out_s + 3) == 108)
                   & ~is_string)
    ok = ok & ~is_null_lit
    lens = jnp.where(ok, lens, 0)
    raw = rebuild_strings(CV(data, validity, offsets), out_s, lens,
                          out_data_capacity=out_data_capacity)
    # ONLY string results unescape — container results are the raw JSON
    # substring and must stay verbatim (their inner escapes are still
    # quoted JSON)
    unescaped = _unescape_simple(CV(raw.data, ok, raw.offsets),
                                 apply_row=is_string)
    return unescaped


def _unescape_simple(cv: CV, apply_row=None) -> CV:
    """Fold simple escapes (\\" \\\\ \\/ \\n \\t \\r \\b \\f) in place;
    \\uXXXX passes through verbatim (documented). Rows where apply_row
    is False pass through untouched."""
    data, offsets = cv.data, cv.offsets
    B = data.shape[0]
    row = byte_row_map(offsets, B)
    pos = jnp.arange(B, dtype=jnp.int32)
    d = data.astype(jnp.int32)
    row_start = offsets[:-1][row]
    row_end = offsets[1:][row]
    in_row = (pos >= row_start) & (pos < row_end)
    non_bs = jnp.where((d != 92) | ~in_row, pos, -_BIG)
    nb_cm = jax.lax.cummax(non_bs)
    prev_nb = jnp.concatenate([jnp.full(1, -1, jnp.int32), nb_cm[:-1]])
    j = jnp.maximum(prev_nb, row_start - 1)
    escaped = ((pos - 1 - j) % 2) == 1
    if apply_row is not None:
        # non-apply rows keep every byte verbatim: escape detection and
        # byte mapping are disabled there, but in_row/keep stay intact
        app = apply_row[row]
        escaped = escaped & app
    else:
        app = jnp.ones(B, jnp.bool_)
    nxt = jnp.concatenate([d[1:], jnp.zeros(1, jnp.int32)])
    simple = (nxt == 34) | (nxt == 92) | (nxt == 47) | (nxt == 110) \
        | (nxt == 116) | (nxt == 114) | (nxt == 98) | (nxt == 102)
    esc_start = in_row & app & (d == 92) & ~escaped & simple
    drop = esc_start
    # map the escaped byte to its value
    mapped = jnp.where(escaped & (d == 110), 10, d)          # \n
    mapped = jnp.where(escaped & (d == 116), 9, mapped)      # \t
    mapped = jnp.where(escaped & (d == 114), 13, mapped)     # \r
    mapped = jnp.where(escaped & (d == 98), 8, mapped)       # \b
    mapped = jnp.where(escaped & (d == 102), 12, mapped)     # \f
    keep = in_row & ~drop
    # compact kept bytes across the tape (per-row contiguity follows
    # because rows are contiguous and lengths shrink)
    new_pos = jnp.cumsum(keep.astype(jnp.int32)) - keep.astype(jnp.int32)
    out = jnp.zeros(B, data.dtype)
    out = out.at[jnp.where(keep, new_pos, B)].set(
        mapped.astype(data.dtype), mode="drop")
    # per-row new lengths -> offsets
    kept_per_row = jax.ops.segment_sum(keep.astype(jnp.int32), row,
                                       offsets.shape[0] - 1)
    new_off = jnp.concatenate([
        jnp.zeros(1, jnp.int32),
        jnp.cumsum(kept_per_row).astype(jnp.int32)])
    return CV(out, cv.validity, new_off)
