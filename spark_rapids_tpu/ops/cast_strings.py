"""String <-> numeric casts with Spark semantics.

Replaces the reference's JNI CastStrings kernels (reference: GpuCast.scala
:286 + com.nvidia.spark.rapids.jni.CastStrings). Same byte-domain strategy
as ops/strings.py: static-bound digit loops, per-row validity for
malformed input (non-ANSI: invalid -> null).

Known round-1 deviations (docs/compatibility.md): int parse rejects
>19-digit magnitudes instead of exact-boundary checks; float parse may
differ from strtod in the last ulp; float->string is not yet implemented.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..columnar import dtypes as dt
from .kernel_utils import CV
from .strings import str_len_bytes

__all__ = ["string_to_int", "string_to_float", "string_to_bool",
           "string_to_decimal", "int_to_string", "bool_to_string",
           "decimal_to_string", "date_to_string", "timestamp_to_string",
           "string_to_date", "string_to_timestamp"]

_MAX_DIGITS = 19


def _trim_bounds(cv: CV):
    """(start, end) byte offsets per row after trimming ASCII whitespace."""
    lens = str_len_bytes(cv)
    n = lens.shape[0]
    starts = cv.offsets[:-1]
    dcap = cv.data.shape[0]
    lead = jnp.zeros(n, jnp.int32)
    trail = jnp.zeros(n, jnp.int32)
    # static scan over a bounded prefix/suffix (64 bytes) is enough for
    # numeric casts; longer strings with numeric content are invalid anyway
    for k in range(64):
        idx = jnp.clip(starts + k, 0, dcap - 1)
        is_ws = (cv.data[idx] == 32) | ((cv.data[idx] >= 9)
                                        & (cv.data[idx] <= 13))
        lead = jnp.where((lead == k) & (k < lens) & is_ws, k + 1, lead)
        idx2 = jnp.clip(starts + lens - 1 - k, 0, dcap - 1)
        is_ws2 = (cv.data[idx2] == 32) | ((cv.data[idx2] >= 9)
                                          & (cv.data[idx2] <= 13))
        trail = jnp.where((trail == k) & (k < lens) & is_ws2, k + 1, trail)
    tstart = starts + lead
    tlen = jnp.maximum(lens - lead - trail, 0)
    return tstart, tlen


def _parse_digits(cv: CV, tstart, tlen):
    """Parse [sign] digits [. digits] -> (int_value int64, int_digits,
    frac_first_digit, has_frac, valid)."""
    dcap = cv.data.shape[0]
    n = tlen.shape[0]

    def byte_at(k):
        idx = jnp.clip(tstart + k, 0, dcap - 1)
        return jnp.where(k < tlen, cv.data[idx].astype(jnp.int32), -1)

    b0 = byte_at(0)
    neg = b0 == 45  # '-'
    plus = b0 == 43
    skip = (neg | plus).astype(jnp.int32)

    value = jnp.zeros(n, jnp.int64)
    ndig = jnp.zeros(n, jnp.int32)
    state_int = jnp.ones(n, jnp.bool_)     # before the dot
    seen_dot = jnp.zeros(n, jnp.bool_)
    frac_first = jnp.full(n, -1, jnp.int32)
    invalid = jnp.zeros(n, jnp.bool_)
    done = jnp.zeros(n, jnp.bool_)

    for k in range(_MAX_DIGITS + 22):
        p = skip + k
        b = byte_at(p)
        active = (p < tlen) & ~done
        is_digit = (b >= 48) & (b <= 57)
        is_dot = b == 46
        value = jnp.where(active & is_digit & state_int,
                          value * 10 + (b - 48).astype(jnp.int64), value)
        ndig = jnp.where(active & is_digit & state_int, ndig + 1, ndig)
        frac_first = jnp.where(active & is_digit & seen_dot
                               & (frac_first < 0), b - 48, frac_first)
        newly_dot = active & is_dot & ~seen_dot
        state_int = jnp.where(newly_dot, False, state_int)
        seen_dot = seen_dot | newly_dot
        invalid = invalid | (active & ~is_digit & ~newly_dot)
        done = done | (active & ~is_digit & ~newly_dot)
    invalid = invalid | (tlen > skip + _MAX_DIGITS + 21)
    has_digits = ndig > 0
    invalid = invalid | ~has_digits | (ndig > _MAX_DIGITS)
    invalid = invalid | (tlen == 0)
    # 19-digit magnitudes can wrap int64: a wrapped accumulator is negative.
    # The single legal wrap is INT64_MIN ("-9223372036854775808").
    int64_min = jnp.int64(-2**63)
    invalid = invalid | ((value < 0) & ~(neg & (value == int64_min)))
    value = jnp.where(neg, -value, value)
    return value, ndig, frac_first, seen_dot, ~invalid


def string_to_int(cv: CV, to_t: dt.DataType) -> CV:
    tstart, tlen = _trim_bounds(cv)
    value, ndig, frac_first, _, ok = _parse_digits(cv, tstart, tlen)
    from .cast import _INT_RANGE
    lo, hi = _INT_RANGE[type(to_t)] if type(to_t) in _INT_RANGE else (
        -2**63, 2**63 - 1)
    in_range = (value >= lo) & (value <= hi)
    return CV(value.astype(to_t.np_dtype), cv.validity & ok & in_range)


def string_to_float(cv: CV) -> CV:
    """Basic decimal float parse: [sign] digits [. digits] [eE [sign]
    digits]; also Infinity/-Infinity/NaN literals."""
    tstart, tlen = _trim_bounds(cv)
    dcap = cv.data.shape[0]
    n = tlen.shape[0]

    def byte_at(k):
        idx = jnp.clip(tstart + k, 0, dcap - 1)
        return jnp.where(k < tlen, cv.data[idx].astype(jnp.int32), -1)

    b0 = byte_at(0)
    neg = b0 == 45
    skip = ((b0 == 45) | (b0 == 43)).astype(jnp.int32)

    mant = jnp.zeros(n, jnp.float64)
    frac_scale = jnp.zeros(n, jnp.int32)
    exp_val = jnp.zeros(n, jnp.int32)
    exp_neg = jnp.zeros(n, jnp.bool_)
    seen_dot = jnp.zeros(n, jnp.bool_)
    in_exp = jnp.zeros(n, jnp.bool_)
    ndig = jnp.zeros(n, jnp.int32)
    invalid = jnp.zeros(n, jnp.bool_)
    prev_was_e = jnp.zeros(n, jnp.bool_)
    exp_ndig = jnp.zeros(n, jnp.int32)

    for k in range(40):
        p = skip + k
        b = byte_at(p)
        active = p < tlen
        is_digit = (b >= 48) & (b <= 57)
        d = (b - 48).astype(jnp.float64)
        mant = jnp.where(active & is_digit & ~in_exp, mant * 10 + d, mant)
        frac_scale = jnp.where(active & is_digit & seen_dot & ~in_exp,
                               frac_scale + 1, frac_scale)
        ndig = jnp.where(active & is_digit & ~in_exp, ndig + 1, ndig)
        exp_val = jnp.where(active & is_digit & in_exp,
                            exp_val * 10 + (b - 48), exp_val)
        newly_dot = active & (b == 46) & ~seen_dot & ~in_exp
        seen_dot = seen_dot | newly_dot
        newly_exp = active & ((b == 101) | (b == 69)) & ~in_exp & (ndig > 0)
        p1 = p + 1
        b1 = jnp.where(p1 < tlen,
                       cv.data[jnp.clip(tstart + p1, 0, dcap - 1)]
                       .astype(jnp.int32), -1)
        exp_neg = jnp.where(newly_exp & (b1 == 45), True, exp_neg)
        was_in_exp = in_exp
        in_exp = in_exp | newly_exp
        # a sign inside the exponent is legal ONLY immediately after e/E
        sign_ok = prev_was_e & ((b == 45) | (b == 43))
        valid_char = is_digit | newly_dot | newly_exp | sign_ok
        invalid = invalid | (active & ~valid_char)
        prev_was_e = newly_exp
        exp_ndig = jnp.where(active & is_digit & in_exp & ~newly_exp,
                             exp_ndig + 1, exp_ndig)
    # anything beyond the scan window is unvalidated -> reject
    invalid = invalid | (tlen > skip + 40)
    # 'e' with no exponent digits is malformed
    invalid = invalid | (in_exp & (exp_ndig == 0))
    exp = jnp.where(exp_neg, -exp_val, exp_val) - frac_scale
    out = mant * jnp.power(10.0, exp.astype(jnp.float64))
    out = jnp.where(neg, -out, out)
    ok = ~invalid & (ndig > 0) & (tlen > 0)

    # literals: Infinity / -Infinity / NaN (Spark accepts case-insensitive)
    def is_literal(lit: bytes, offset):
        m = jnp.ones(n, jnp.bool_)
        for j, ch in enumerate(lit):
            b = byte_at(offset + j)
            low = jnp.where((b >= 65) & (b <= 90), b + 32, b)
            m = m & (low == (ch | 0x20 if 65 <= ch <= 122 else ch))
        return m & (tlen == offset + len(lit))

    inf = is_literal(b"infinity", skip) | is_literal(b"inf", skip)
    nan = is_literal(b"nan", 0)
    out = jnp.where(inf, jnp.where(neg, -jnp.inf, jnp.inf), out)
    out = jnp.where(nan, jnp.nan, out)
    ok = ok | inf | nan
    return CV(out, cv.validity & ok)


def _dec_mul_pow10_dyn(v2, k, kmax: int):
    """128-bit multiply by a per-row DYNAMIC power of ten 0 <= k <= kmax
    via binary decomposition (at most 6 dec_muls). Returns (v2, ovf)."""
    from .decimal128 import dec_from_i64, dec_mul, from_limbs, to_limbs
    ovf = jnp.zeros(k.shape[0], jnp.bool_)
    bit = 0
    while (1 << bit) <= kmax:
        e = 1 << bit
        if e <= 18:
            const = dec_from_i64(jnp.full(k.shape[0], 10 ** e, jnp.int64))
        else:
            # 10^32 exceeds int64: build from limbs of the magnitude
            limbs = [(10 ** e >> (32 * i)) & 0xFFFFFFFF for i in range(4)]
            const = from_limbs([jnp.full(k.shape[0], l, jnp.int64)
                                for l in limbs])
        prod, o = dec_mul(v2, const, 38)
        on = (k & e) != 0
        v2 = jnp.where(on[:, None], prod, v2)
        ovf = ovf | (on & o)
        bit += 1
    return v2, ovf


def string_to_decimal(cv: CV, to_t: dt.DecimalType) -> CV:
    """EXACT string -> decimal(p, s): [sign] digits [. digits]
    [eE [sign] digits]. Mantissa digits accumulate into 18-digit int64
    chunks combined with 128-bit limb arithmetic, and the target scale
    is applied positionally during the scan (the digit one place past
    scale s drives HALF_UP) — up to 38 significant digits with no
    float64 detour (reference: JNI CastStrings decimal parse,
    GpuCast.scala:286)."""
    from .decimal128 import (dec_add, dec_from_i64, dec_neg, dec_to_i64,
                             fits_precision, to_limbs)
    p_, s_ = to_t.precision, to_t.scale
    tstart, tlen = _trim_bounds(cv)
    dcap = cv.data.shape[0]
    n = tlen.shape[0]

    def byte_at(k):
        idx = jnp.clip(tstart + k, 0, dcap - 1)
        return jnp.where(k < tlen, cv.data[idx].astype(jnp.int32), -1)

    b0 = byte_at(0)
    neg = b0 == 45
    skip = ((b0 == 45) | (b0 == 43)).astype(jnp.int32)

    # pass 1: syntax + counts (mantissa digits, int-part digits, leading
    # int-part zeros, exponent). lax.fori_loop keeps the compiled graph
    # ~64x smaller than unrolling (XLA CPU chokes on big gather chains).
    # 64 bytes covers sign + 38 significant digits + zero padding + dot
    # + exponent; longer trimmed inputs -> null (docs/compatibility.md)
    SCAN = 64
    st0 = dict(nd=jnp.zeros(n, jnp.int32), nint=jnp.zeros(n, jnp.int32),
               lead=jnp.zeros(n, jnp.int32),
               lead_run=jnp.ones(n, jnp.bool_),
               seen_dot=jnp.zeros(n, jnp.bool_),
               in_exp=jnp.zeros(n, jnp.bool_),
               exp_val=jnp.zeros(n, jnp.int32),
               exp_neg=jnp.zeros(n, jnp.bool_),
               exp_ndig=jnp.zeros(n, jnp.int32),
               prev_was_e=jnp.zeros(n, jnp.bool_),
               invalid=jnp.zeros(n, jnp.bool_))

    def p1(k, s):
        pos = skip + k
        b = byte_at(pos)
        active = pos < tlen
        is_digit = (b >= 48) & (b <= 57)
        m_dig = active & is_digit & ~s["in_exp"]
        nd = jnp.where(m_dig, s["nd"] + 1, s["nd"])
        nint = jnp.where(m_dig & ~s["seen_dot"], s["nint"] + 1, s["nint"])
        is_lead0 = m_dig & ~s["seen_dot"] & s["lead_run"] & (b == 48)
        lead = jnp.where(is_lead0, s["lead"] + 1, s["lead"])
        lead_run = s["lead_run"] & (~m_dig | is_lead0)
        newly_dot = active & (b == 46) & ~s["seen_dot"] & ~s["in_exp"]
        seen_dot = s["seen_dot"] | newly_dot
        newly_exp = (active & ((b == 101) | (b == 69)) & ~s["in_exp"]
                     & (nd > 0))
        nxt = byte_at(pos + 1)
        exp_neg = jnp.where(newly_exp & (nxt == 45), True, s["exp_neg"])
        in_exp = s["in_exp"] | newly_exp
        e_dig = active & is_digit & in_exp & ~newly_exp
        exp_val = jnp.where(e_dig,
                            jnp.minimum(s["exp_val"] * 10 + (b - 48),
                                        9999), s["exp_val"])
        exp_ndig = jnp.where(e_dig, s["exp_ndig"] + 1, s["exp_ndig"])
        sign_ok = s["prev_was_e"] & ((b == 45) | (b == 43))
        invalid = s["invalid"] | (active & ~(is_digit | newly_dot
                                             | newly_exp | sign_ok))
        return dict(nd=nd, nint=nint, lead=lead, lead_run=lead_run,
                    seen_dot=seen_dot, in_exp=in_exp, exp_val=exp_val,
                    exp_neg=exp_neg, exp_ndig=exp_ndig,
                    prev_was_e=newly_exp, invalid=invalid)

    s1r = jax.lax.fori_loop(0, SCAN, p1, st0)
    nd, nint, lead = s1r["nd"], s1r["nint"], s1r["lead"]
    invalid = s1r["invalid"] | (tlen > skip + SCAN)
    invalid = invalid | (nd == 0) | (tlen == 0)
    invalid = invalid | (s1r["in_exp"] & (s1r["exp_ndig"] == 0))
    exp = jnp.where(s1r["exp_neg"], -s1r["exp_val"], s1r["exp_val"])

    # significant accept window in mantissa-digit index space:
    # [lead, end) contributes, digit at `end` drives HALF_UP
    point = nint + exp
    end = point + s_
    nsig = jnp.clip(jnp.minimum(end, nd) - lead, 0, 40)
    invalid = invalid | ((end - lead) > 38)
    pad = jnp.clip(end - jnp.maximum(nd, lead), 0, 38)

    # pass 2: route digits into 18+18+2 chunks by significant index
    st2 = dict(h0=jnp.zeros(n, jnp.int64), h1=jnp.zeros(n, jnp.int64),
               h2=jnp.zeros(n, jnp.int64),
               roundup=jnp.zeros(n, jnp.bool_),
               mi=jnp.zeros(n, jnp.int32),
               in_e2=jnp.zeros(n, jnp.bool_))

    def p2(k, s):
        pos = skip + k
        b = byte_at(pos)
        active = pos < tlen
        in_e2 = s["in_e2"] | (active & ((b == 101) | (b == 69)))
        is_digit = active & (b >= 48) & (b <= 57)
        m_dig = is_digit & ~in_e2       # exponent digits excluded
        d = (b - 48).astype(jnp.int64)
        mi = s["mi"]
        c = mi - lead
        acc = m_dig & (mi >= lead) & (mi < end)
        h0 = jnp.where(acc & (c < 18), s["h0"] * 10 + d, s["h0"])
        h1 = jnp.where(acc & (c >= 18) & (c < 36), s["h1"] * 10 + d,
                       s["h1"])
        h2 = jnp.where(acc & (c >= 36), s["h2"] * 10 + d, s["h2"])
        roundup = s["roundup"] | (m_dig & (mi == end) & (d >= 5))
        return dict(h0=h0, h1=h1, h2=h2, roundup=roundup,
                    mi=jnp.where(m_dig, mi + 1, mi), in_e2=in_e2)

    s2r = jax.lax.fori_loop(0, SCAN, p2, st2)
    h0, h1, h2, roundup = s2r["h0"], s2r["h1"], s2r["h2"], s2r["roundup"]
    n1 = jnp.clip(nsig - 18, 0, 18)
    n2 = jnp.clip(nsig - 36, 0, 2)

    v = dec_from_i64(h0)
    v, o1 = _dec_mul_pow10_dyn(v, n1, 18)
    v, oa = dec_add(v, dec_from_i64(h1))
    v, o2 = _dec_mul_pow10_dyn(v, n2, 2)
    v, ob = dec_add(v, dec_from_i64(h2))
    v, o3 = _dec_mul_pow10_dyn(v, pad, 38)
    v, oc = dec_add(v, dec_from_i64(roundup.astype(jnp.int64)))
    ovf = o1 | oa | o2 | ob | o3 | oc
    ok = (~invalid & ~ovf & fits_precision(to_limbs(v), p_)
          & cv.validity)
    v = jnp.where(neg[:, None], dec_neg(v), v)
    if to_t.is_decimal128:
        return CV(jnp.where(ok[:, None], v, 0), ok)
    v64, fits = dec_to_i64(v)
    ok = ok & fits
    return CV(jnp.where(ok, v64, 0), ok)


def string_to_bool(cv: CV) -> CV:
    tstart, tlen = _trim_bounds(cv)
    dcap = cv.data.shape[0]
    n = tlen.shape[0]

    def lower_at(k):
        idx = jnp.clip(tstart + k, 0, dcap - 1)
        b = jnp.where(k < tlen, cv.data[idx].astype(jnp.int32), -1)
        return jnp.where((b >= 65) & (b <= 90), b + 32, b)

    def match(lit: bytes):
        m = tlen == len(lit)
        for j, ch in enumerate(lit):
            m = m & (lower_at(j) == ch)
        return m

    true_m = (match(b"true") | match(b"t") | match(b"yes") | match(b"y")
              | match(b"1"))
    false_m = (match(b"false") | match(b"f") | match(b"no") | match(b"n")
               | match(b"0"))
    return CV(true_m, cv.validity & (true_m | false_m))


# ----------------------------------------------------------------------
# number -> string
# ----------------------------------------------------------------------
def _digits_matrix(absval, max_digits: int):
    """[n, max_digits] right-aligned ASCII digits + per-row digit count."""
    n = absval.shape[0]
    cols = []
    v = absval
    for _ in range(max_digits):
        cols.append((v % 10).astype(jnp.uint8) + 48)
        v = v // 10
    mat = jnp.stack(cols[::-1], axis=1)  # most significant first
    ndig = jnp.maximum(
        max_digits - jnp.sum(
            jnp.cumsum(jnp.where(mat != 48, 1, 0), axis=1) == 0, axis=1),
        1)
    return mat, ndig.astype(jnp.int32)


def _emit_from_staging(staging, row_lens, out_capacity: int,
                       validity) -> CV:
    """Build a string CV from a [n, W] staging matrix where each row's
    bytes occupy the LAST row_lens columns."""
    n, w = staging.shape
    lens = jnp.where(validity, row_lens, 0)
    new_off = jnp.concatenate([jnp.zeros(1, jnp.int32),
                               jnp.cumsum(lens).astype(jnp.int32)])
    pos = jnp.arange(out_capacity, dtype=jnp.int32)
    row = jnp.clip(jnp.searchsorted(new_off[1:], pos, side="right"),
                   0, n - 1).astype(jnp.int32)
    rel = pos - new_off[row]
    colidx = w - lens[row] + rel
    colidx = jnp.clip(colidx, 0, w - 1)
    data = staging[row, colidx]
    total = new_off[n]
    data = jnp.where(pos < total, data, 0).astype(jnp.uint8)
    return CV(data, validity, new_off)


def int_to_string(cv: CV, out_capacity: Optional[int] = None) -> CV:
    x = cv.data.astype(jnp.int64)
    neg = x < 0
    absval = jnp.where(neg, -x, x)  # note: INT64_MIN overflows; see doc
    mat, ndig = _digits_matrix(absval, 19)
    n = x.shape[0]
    lens = ndig + neg.astype(jnp.int32)
    # [n, 20]: the last `lens` columns hold [sign] digits
    out = jnp.zeros((n, 20), jnp.uint8)
    rows = jnp.arange(n)
    for c in range(20):  # c = position from the right
        digit = mat[rows, jnp.clip(18 - c, 0, 18)]
        val = jnp.where(c < ndig, digit,
                        jnp.where((c == ndig) & neg, jnp.uint8(45),
                                  jnp.uint8(0)))
        out = out.at[:, 19 - c].set(val)
    # worst case 20 bytes/row (19 digits + sign)
    cap = out_capacity or max(int(cv.validity.shape[0]) * 20, 128)
    return _emit_from_staging(out, lens, cap, cv.validity)


def bool_to_string(cv: CV, out_capacity: Optional[int] = None) -> CV:
    n = cv.validity.shape[0]
    # staging: "false" (5) or " true" -> use width 5, true right-aligned
    t = jnp.asarray(list(b"true"), jnp.uint8)
    f = jnp.asarray(list(b"false"), jnp.uint8)
    staging = jnp.where(cv.data.astype(jnp.bool_)[:, None],
                        jnp.concatenate([jnp.zeros(1, jnp.uint8), t])[None, :],
                        f[None, :])
    lens = jnp.where(cv.data.astype(jnp.bool_), 4, 5).astype(jnp.int32)
    cap = out_capacity or max(n * 5, 128)
    return _emit_from_staging(staging, lens, cap, cv.validity)


def decimal_to_string(cv: CV, scale: int,
                      out_capacity: Optional[int] = None) -> CV:
    x = cv.data.astype(jnp.int64)
    neg = x < 0
    absval = jnp.where(neg, -x, x)
    mat, ndig = _digits_matrix(absval, 19)  # [n,19] right-aligned digits
    n = x.shape[0]
    if scale == 0:
        w = 20
        lens = ndig + neg.astype(jnp.int32)
        out = jnp.zeros((n, w), jnp.uint8)
        rows = jnp.arange(n)
        for c in range(w):
            digit = mat[rows, jnp.clip(18 - c, 0, 18)]
            out = out.at[:, w - 1 - c].set(
                jnp.where(c < ndig, digit,
                          jnp.where((c == ndig) & neg, jnp.uint8(45),
                                    jnp.uint8(0))))
        return _emit_from_staging(out, lens,
                                  out_capacity or max(n * 20, 128),
                                  cv.validity)
    # scaled: int part (>=1 digit), '.', scale fraction digits
    int_digits = jnp.maximum(ndig - scale, 1)
    w = 22
    out = jnp.zeros((n, w), jnp.uint8)
    lens = int_digits + 1 + scale + neg.astype(jnp.int32)
    for c in range(w):
        # position c from the right: fraction digits [0, scale), then '.',
        # then int digits, then sign
        is_frac = c < scale
        is_dot = c == scale
        digit_i = jnp.where(is_frac, c, c - 1)  # index from right in mat
        mval = mat[jnp.arange(n), jnp.clip(18 - digit_i, 0, 18)]
        int_pos = c - scale - 1
        val = jnp.where(is_frac, mval,
                        jnp.where(is_dot, jnp.uint8(46),
                                  jnp.where(int_pos < int_digits, mval,
                                            jnp.where((int_pos == int_digits)
                                                      & neg, jnp.uint8(45),
                                                      jnp.uint8(0)))))
        out = out.at[:, w - 1 - c].set(val)
    return _emit_from_staging(out, lens, out_capacity or max(n * 22, 128),
                              cv.validity)


def date_to_string(cv: CV, out_capacity: Optional[int] = None) -> CV:
    """days-since-epoch -> 'YYYY-MM-DD' (civil-from-days, Howard Hinnant's
    algorithm in integer jnp ops)."""
    from .datetime import civil_from_days
    y, m, d = civil_from_days(cv.data)
    n = cv.data.shape[0]
    staging = jnp.zeros((n, 10), jnp.uint8)
    vals = [(y // 1000) % 10, (y // 100) % 10, (y // 10) % 10, y % 10,
            None, (m // 10) % 10, m % 10, None, (d // 10) % 10, d % 10]
    for i, v in enumerate(vals):
        if v is None:
            staging = staging.at[:, i].set(45)  # '-'
        else:
            staging = staging.at[:, i].set((v + 48).astype(jnp.uint8))
    lens = jnp.full(n, 10, jnp.int32)
    return _emit_from_staging(staging, lens,
                              out_capacity or max(n * 10, 128), cv.validity)


def timestamp_to_string(cv: CV, out_capacity: Optional[int] = None) -> CV:
    """micros-since-epoch -> 'YYYY-MM-DD HH:MM:SS[.f{1..6}]' (Spark's
    default timestamp rendering: fractional seconds shown without
    trailing zeros, omitted when zero)."""
    from .datetime import civil_from_days
    from .cast import MICROS_PER_DAY, MICROS_PER_SEC
    x = cv.data.astype(jnp.int64)
    days = x // MICROS_PER_DAY                    # floors negatives
    tod = x - days * MICROS_PER_DAY               # always >= 0
    y, mo, d = civil_from_days(days.astype(jnp.int32))
    secs = tod // MICROS_PER_SEC
    fr = (tod - secs * MICROS_PER_SEC).astype(jnp.int32)
    hh = (secs // 3600).astype(jnp.int32)
    mi = ((secs // 60) % 60).astype(jnp.int32)
    ss = (secs % 60).astype(jnp.int32)
    n = x.shape[0]
    # fraction digits, least-significant first, and the trailing-zero run
    fd = [(fr // (10 ** i)) % 10 for i in range(6)]
    tz = jnp.full(n, 0, jnp.int32)
    run = jnp.ones(n, jnp.bool_)
    for i in range(6):
        z = run & (fd[i] == 0)
        tz = jnp.where(z, tz + 1, tz)
        run = z
    fl = jnp.where(fr == 0, 0, 6 - tz + 1)        # incl. '.', 0 if none
    lens = 19 + fl
    # years outside 1..9999 don't fit the fixed 4-digit layout (Spark
    # renders '+10000-...'): null instead of silent mod-10000 garbage
    validity = cv.validity & (y >= 1) & (y <= 9999)
    W = 26
    # positions from the RIGHT: fraction digits, '.', then the fixed
    # 19-byte 'YYYY-MM-DD HH:MM:SS' layout — built fully vectorized over
    # an [n, W] position grid (no scatter loop: cheap to compile)
    fixed = [ss % 10, ss // 10, None, mi % 10, mi // 10, None,
             hh % 10, hh // 10, None, d % 10, d // 10, None,
             mo % 10, mo // 10, None, y % 10, (y // 10) % 10,
             (y // 100) % 10, (y // 1000) % 10]
    seps = {2: 58, 5: 58, 8: 32, 11: 45, 14: 45}  # ':' ':' ' ' '-' '-'
    frac_mat = jnp.stack(fd, axis=1)              # [n, 6] lsd-first
    fixed_vals = jnp.stack(
        [jnp.full(n, seps[i], jnp.int32) if i in seps
         else fixed[i].astype(jnp.int32) + 48
         for i in range(19)], axis=1)             # [n, 19]
    c = jnp.arange(W)[None, :]                    # position from right
    flc = fl[:, None]
    in_frac = c < (flc - 1)
    is_dot = c == (flc - 1)
    fi = jnp.clip(tz[:, None] + c, 0, 5)
    fval = jnp.take_along_axis(frac_mat, fi, axis=1) + 48
    cp = jnp.clip(c - flc, 0, 18)
    fxv = jnp.take_along_axis(fixed_vals, cp, axis=1)
    val = jnp.where(in_frac, fval,
                    jnp.where(is_dot, 46,
                              jnp.where(c - flc < 19, fxv, 0)))
    out = val[:, ::-1].astype(jnp.uint8)          # to left-to-right
    cap = out_capacity or max(n * W, 128)
    return _emit_from_staging(out, lens, cap, validity)


def _digits_at(cv: CV, tstart, tlen, pos: int, width: int):
    """Parse `width` digits at byte offset `pos` of each trimmed row.
    Returns (value, ok)."""
    dcap = cv.data.shape[0]
    n = tlen.shape[0]
    val = jnp.zeros(n, jnp.int32)
    ok = jnp.ones(n, jnp.bool_)
    for k in range(width):
        idx = jnp.clip(tstart + pos + k, 0, dcap - 1)
        b = jnp.where(pos + k < tlen, cv.data[idx].astype(jnp.int32), -1)
        is_d = (b >= 48) & (b <= 57)
        ok = ok & is_d
        val = val * 10 + jnp.where(is_d, b - 48, 0)
    return val, ok


def _char_at(cv: CV, tstart, tlen, pos: int):
    dcap = cv.data.shape[0]
    idx = jnp.clip(tstart + pos, 0, dcap - 1)
    return jnp.where(pos < tlen, cv.data[idx].astype(jnp.int32), -1)


def string_to_date(cv: CV) -> CV:
    """Parse 'YYYY-MM-DD' (Spark default date format; other layouts ->
    null round-1, docs/compatibility.md)."""
    from .datetime import days_from_civil, days_in_month
    tstart, tlen = _trim_bounds(cv)
    y, oky = _digits_at(cv, tstart, tlen, 0, 4)
    m, okm = _digits_at(cv, tstart, tlen, 5, 2)
    d, okd = _digits_at(cv, tstart, tlen, 8, 2)
    dashes = (_char_at(cv, tstart, tlen, 4) == 45) &         (_char_at(cv, tstart, tlen, 7) == 45)
    ok = (oky & okm & okd & dashes & (tlen == 10)
          & (m >= 1) & (m <= 12) & (d >= 1))
    ok = ok & (d <= days_in_month(y, m))
    days = days_from_civil(y, m, d)
    return CV(jnp.where(ok, days, 0).astype(jnp.int32), cv.validity & ok)


def string_to_timestamp(cv: CV) -> CV:
    """Parse 'YYYY-MM-DD[ HH:MM:SS]' as UTC micros (bare dates ->
    midnight; fractional seconds / timezones -> null round-1)."""
    from .datetime import days_from_civil, days_in_month
    tstart, tlen = _trim_bounds(cv)
    y, oky = _digits_at(cv, tstart, tlen, 0, 4)
    m, okm = _digits_at(cv, tstart, tlen, 5, 2)
    d, okd = _digits_at(cv, tstart, tlen, 8, 2)
    dashes = (_char_at(cv, tstart, tlen, 4) == 45) &         (_char_at(cv, tstart, tlen, 7) == 45)
    date_ok = (oky & okm & okd & dashes & (m >= 1) & (m <= 12)
               & (d >= 1) & (d <= days_in_month(y, m)))
    hh, okh = _digits_at(cv, tstart, tlen, 11, 2)
    mi, okmi = _digits_at(cv, tstart, tlen, 14, 2)
    ss, oks = _digits_at(cv, tstart, tlen, 17, 2)
    seps = ((_char_at(cv, tstart, tlen, 10) == 32)
            | (_char_at(cv, tstart, tlen, 10) == 84))  # ' ' or 'T'
    colons = (_char_at(cv, tstart, tlen, 13) == 58) &         (_char_at(cv, tstart, tlen, 16) == 58)
    time_ok = (okh & okmi & oks & seps & colons & (hh < 24) & (mi < 60)
               & (ss < 60) & (tlen == 19))
    bare_date = tlen == 10
    ok = date_ok & (bare_date | time_ok)
    from .datetime import MICROS_PER_DAY, MICROS_PER_SEC
    days = days_from_civil(y, m, d).astype(jnp.int64)
    tod = jnp.where(bare_date, 0,
                    (hh.astype(jnp.int64) * 3600 + mi * 60 + ss)
                    * MICROS_PER_SEC)
    micros = days * MICROS_PER_DAY + tod
    return CV(jnp.where(ok, micros, 0), cv.validity & ok)
