"""Regex -> TPU-executable NFA transpiler (the RegexParser analog).

The reference transpiles Java regexes to cuDF's regex kernel dialect
(reference: RegexParser.scala:47, CudfRegexTranspiler:696, 2,137 LoC).
There is no regex kernel on TPU, so this module compiles a Java-regex
SUBSET straight to data: a Thompson NFA with <= 32 states represented as
uint32 bitmasks plus a 256-entry byte->equivalence-class table, executed
as a vectorized bit-parallel simulation (ops/regex_exec.py) — O(bytes x
states) fused VPU work, no per-row control flow.

Supported subset (byte-domain, ASCII patterns):
  literals, escaped metachars, `.` (any byte except \\n), char classes
  [a-z0-9_], [^...], \\d \\w \\s \\D \\W \\S (in and out of classes),
  quantifiers * + ? {m} {m,n} {m,} (greedy), alternation |, groups
  ( ) (?: ), anchors ^ $.
Rejected (raises RegexUnsupported -> planner tags/falls back): lazy
quantifiers, backreferences, lookaround, \\b, unicode classes, patterns
needing > 32 NFA states.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Set, Tuple

import numpy as np

__all__ = ["RegexUnsupported", "RegexSyntaxError", "parse", "compile_nfa",
           "CompiledRegex"]

MAX_STATES = 32


class RegexUnsupported(Exception):
    """Valid Java pattern outside the transpilable subset — eligible for
    the host CPU fallback."""


class RegexSyntaxError(ValueError):
    """Pattern Java itself would reject (PatternSyntaxException analog):
    a hard user error, NOT eligible for fallback — Python `re` may parse
    some of these as literals and silently change answers."""


# ---------------------------------------------------------------------
# AST
# ---------------------------------------------------------------------
@dataclasses.dataclass
class Lit:
    byte: int


@dataclasses.dataclass
class Klass:
    bytes_in: frozenset          # set of matching byte values


@dataclasses.dataclass
class Concat:
    parts: list


@dataclasses.dataclass
class Alt:
    options: list


@dataclasses.dataclass
class Repeat:
    child: object
    lo: int
    hi: Optional[int]            # None = unbounded


@dataclasses.dataclass
class Group:
    child: object
    index: int                   # 0 = non-capturing


ANY_NO_NL = frozenset(range(256)) - {10}
_D = frozenset(range(48, 58))
_W = _D | frozenset(range(65, 91)) | frozenset(range(97, 123)) | {95}
_S = frozenset([32, 9, 10, 11, 12, 13])


class _Parser:
    def __init__(self, pat: str):
        try:
            self.b = pat.encode("ascii")
        except UnicodeEncodeError:
            raise RegexUnsupported("non-ASCII pattern")
        self.i = 0
        self.ngroups = 0
        self.anchored_start = False
        self.anchored_end = False

    def peek(self):
        return self.b[self.i] if self.i < len(self.b) else None

    def take(self):
        c = self.b[self.i]
        self.i += 1
        return c

    # -- grammar: alt := concat ('|' concat)* ---------------------------
    def parse(self):
        if self.peek() == ord("^"):
            self.take()
            self.anchored_start = True
        node = self._alt(top=True)
        if isinstance(node, Alt) and (self.anchored_start
                                      or self.anchored_end):
            # Java scopes '^'/'$' to their branch; this compiler anchors
            # the whole pattern — reject instead of mis-matching
            raise RegexUnsupported(
                "anchors with top-level alternation")
        return node

    def _alt(self, top=False):
        opts = [self._concat(top)]
        while self.peek() == ord("|"):
            self.take()
            opts.append(self._concat(top))
        return opts[0] if len(opts) == 1 else Alt(opts)

    def _concat(self, top=False):
        parts = []
        while True:
            c = self.peek()
            if c is None or c in (ord("|"), ord(")")):
                break
            if c == ord("$"):
                # only valid at the very end of the pattern (subset)
                if self.i == len(self.b) - 1 and top:
                    self.take()
                    self.anchored_end = True
                    break
                raise RegexUnsupported("'$' not at pattern end")
            parts.append(self._quantified())
        return Concat(parts)

    def _quantified(self):
        atom = self._atom()
        c = self.peek()
        if c == ord("*"):
            self.take()
            self._no_lazy()
            return Repeat(atom, 0, None)
        if c == ord("+"):
            self.take()
            self._no_lazy()
            return Repeat(atom, 1, None)
        if c == ord("?"):
            self.take()
            self._no_lazy()
            return Repeat(atom, 0, 1)
        if c == ord("{"):
            j = self.b.find(b"}", self.i)
            if j < 0:
                raise RegexSyntaxError("unterminated {..}")
            body = self.b[self.i + 1:j].decode()
            self.i = j + 1
            self._no_lazy()
            import re as _re
            if not _re.fullmatch(r"\d+(,\d*)?", body):
                raise RegexSyntaxError(f"bad repeat {{{body}}}")
            if "," in body:
                lo_s, hi_s = body.split(",", 1)
                lo = int(lo_s)
                hi = int(hi_s) if hi_s else None
            else:
                lo = hi = int(body)
            if hi is not None and hi < lo:
                raise RegexSyntaxError(f"bad repeat bound {{{body}}}")
            if lo > 64 or (hi is not None and hi > 64):
                raise RegexUnsupported("repeat bound > 64")
            return Repeat(atom, lo, hi)
        return atom

    def _no_lazy(self):
        if self.peek() == ord("?"):
            raise RegexUnsupported("lazy quantifiers")
        if self.peek() == ord("+"):
            raise RegexUnsupported("possessive quantifiers")

    def _atom(self):
        c = self.take()
        if c == ord("("):
            if self.b[self.i:self.i + 2] == b"?:":
                self.i += 2
                idx = 0
            elif self.peek() == ord("?"):
                raise RegexUnsupported("(?...) construct")
            else:
                self.ngroups += 1
                idx = self.ngroups
            inner = self._alt()
            if self.peek() != ord(")"):
                raise RegexSyntaxError("unbalanced group")
            self.take()
            return Group(inner, idx)
        if c == ord("["):
            return self._klass()
        if c == ord("."):
            return Klass(ANY_NO_NL)
        if c == ord("\\"):
            return self._escape(in_class=False)
        if c in (ord("*"), ord("+"), ord("?"), ord(")"), ord("]"),
                 ord("{"), ord("}")):
            raise RegexSyntaxError(f"dangling metachar {chr(c)!r}")
        if c == ord("^"):
            raise RegexUnsupported("'^' not at pattern start")
        return Lit(c)

    def _escape(self, in_class: bool):
        if self.peek() is None:
            raise RegexSyntaxError("trailing backslash")
        c = self.take()
        simple = {ord("n"): 10, ord("t"): 9, ord("r"): 13, ord("f"): 12,
                  ord("a"): 7, ord("e"): 27, ord("0"): 0}
        if c in simple:
            return Lit(simple[c])
        if c == ord("d"):
            return Klass(_D)
        if c == ord("D"):
            return Klass(frozenset(range(256)) - _D)
        if c == ord("w"):
            return Klass(_W)
        if c == ord("W"):
            return Klass(frozenset(range(256)) - _W)
        if c == ord("s"):
            return Klass(_S)
        if c == ord("S"):
            return Klass(frozenset(range(256)) - _S)
        if c == ord("x"):
            h = self.b[self.i:self.i + 2]
            try:
                val = int(h, 16)
            except ValueError:
                raise RegexSyntaxError("bad \\x escape")
            if len(h) != 2:
                raise RegexSyntaxError("bad \\x escape")
            self.i += 2
            return Lit(val)
        if chr(c) in ".*+?()[]{}|^$\\/-'\"!#%&,:;<=>@_`~ ":
            return Lit(c)
        if chr(c) in "bBAzZG123456789pPucQEkhHvVRXN":
            # valid Java constructs (boundaries, backrefs, \p classes,
            # \uXXXX, ...) outside the subset -> host fallback
            raise RegexUnsupported(f"escape \\{chr(c)} construct")
        raise RegexSyntaxError(f"escape \\{chr(c)!r}")

    def _klass(self):
        neg = False
        if self.peek() == ord("^"):
            self.take()
            neg = True
        members: Set[int] = set()
        first = True
        while True:
            c = self.peek()
            if c is None:
                raise RegexSyntaxError("unterminated class")
            if c == ord("]") and not first:
                self.take()
                break
            first = False
            self.take()
            if c == ord("\\"):
                atom = self._escape(in_class=True)
                if isinstance(atom, Klass):
                    members |= atom.bytes_in
                    continue
                c = atom.byte
            if self.peek() == ord("-") and self.i + 1 < len(self.b) \
                    and self.b[self.i + 1] != ord("]"):
                self.take()
                hi = self.take()
                if hi == ord("\\"):
                    hi_atom = self._escape(in_class=True)
                    if not isinstance(hi_atom, Lit):
                        raise RegexSyntaxError("class range to a class")
                    hi = hi_atom.byte
                if hi < c:
                    raise RegexSyntaxError("reversed class range")
                members |= set(range(c, hi + 1))
            else:
                members.add(c)
        if neg:
            # Java negated classes DO match \n (unlike `.`)
            members = set(range(256)) - members
        return Klass(frozenset(members))


def parse(pattern: str):
    p = _Parser(pattern)
    ast = p.parse()
    if p.i != len(p.b):
        raise RegexUnsupported(f"trailing characters at {p.i}")
    return ast, p.anchored_start, p.anchored_end, p.ngroups


# ---------------------------------------------------------------------
# Thompson construction over byte classes
# ---------------------------------------------------------------------
@dataclasses.dataclass
class CompiledRegex:
    n_states: int
    start_mask: int              # ε-closure of the start state
    accept_mask: int
    class_table: np.ndarray      # uint8[256] byte -> class id
    n_classes: int
    trans: np.ndarray            # uint32[n_states, n_classes] next-mask
    anchored_start: bool
    anchored_end: bool
    min_len: int
    max_len: Optional[int]       # None = unbounded match length


class _NFA:
    def __init__(self):
        self.edges: List[Tuple[int, frozenset, int]] = []  # (src, cls, dst)
        self.eps: List[Tuple[int, int]] = []
        self.n = 0

    def new_state(self):
        s = self.n
        self.n += 1
        if self.n > MAX_STATES:
            raise RegexUnsupported(f"pattern needs > {MAX_STATES} states")
        return s


def _build(nfa: _NFA, node, src: int, dst: int):
    """Wire `node` to match between states src -> dst."""
    if isinstance(node, Lit):
        nfa.edges.append((src, frozenset([node.byte]), dst))
    elif isinstance(node, Klass):
        if not node.bytes_in:
            raise RegexSyntaxError("empty character class")
        nfa.edges.append((src, node.bytes_in, dst))
    elif isinstance(node, Group):
        _build(nfa, node.child, src, dst)
    elif isinstance(node, Concat):
        cur = src
        for i, part in enumerate(node.parts):
            nxt = dst if i == len(node.parts) - 1 else nfa.new_state()
            _build(nfa, part, cur, nxt)
            cur = nxt
        if not node.parts:
            nfa.eps.append((src, dst))
    elif isinstance(node, Alt):
        for opt in node.options:
            _build(nfa, opt, src, dst)
    elif isinstance(node, Repeat):
        lo, hi = node.lo, node.hi
        cur = src
        for _ in range(lo):
            nxt = nfa.new_state()
            _build(nfa, node.child, cur, nxt)
            cur = nxt
        if hi is None:
            # loop state: child may repeat on cur
            loop_mid = nfa.new_state()
            _build(nfa, node.child, cur, loop_mid)
            nfa.eps.append((loop_mid, cur))
            nfa.eps.append((cur, dst))
        else:
            nfa.eps.append((cur, dst))
            for _ in range(hi - lo):
                nxt = nfa.new_state()
                _build(nfa, node.child, cur, nxt)
                nfa.eps.append((nxt, dst))
                cur = nxt
    else:  # pragma: no cover
        raise RegexUnsupported(f"unknown node {node!r}")


def _len_bounds(node) -> Tuple[int, Optional[int]]:
    if isinstance(node, (Lit, Klass)):
        return 1, 1
    if isinstance(node, Group):
        return _len_bounds(node.child)
    if isinstance(node, Concat):
        lo = hi = 0
        for p in node.parts:
            l2, h2 = _len_bounds(p)
            lo += l2
            hi = None if hi is None or h2 is None else hi + h2
        return lo, hi
    if isinstance(node, Alt):
        los, his = zip(*(_len_bounds(o) for o in node.options))
        hi = None if any(h is None for h in his) else max(his)
        return min(los), hi
    if isinstance(node, Repeat):
        l2, h2 = _len_bounds(node.child)
        lo = l2 * node.lo
        if node.hi is None or h2 is None:
            return lo, None
        return lo, h2 * node.hi
    raise RegexUnsupported(f"unknown node {node!r}")


def compile_nfa(pattern: str) -> CompiledRegex:
    ast, astart, aend, _ = parse(pattern)
    if aend:
        # Java/Python `$` also matches just before a final line
        # terminator: append an optional (\r?\n)
        ast = Concat([ast, Repeat(
            Concat([Repeat(Lit(13), 0, 1), Lit(10)]), 0, 1)])
    nfa = _NFA()
    start = nfa.new_state()
    accept = nfa.new_state()
    _build(nfa, ast, start, accept)
    n = nfa.n

    # ε-closures: fixpoint over eps edges reaches the transitive closure
    closure = [1 << s for s in range(n)]
    changed = True
    while changed:
        changed = False
        for (a, b) in nfa.eps:
            new = closure[a] | closure[b]
            if new != closure[a]:
                closure[a] = new
                changed = True

    # byte equivalence classes over the edge alphabet
    sets = [frozenset(e[1]) for e in nfa.edges]
    class_of_byte = np.zeros(256, np.uint8)
    signatures = {}
    for byte in range(256):
        key = tuple(byte in s for s in sets)
        if key not in signatures:
            signatures[key] = len(signatures)
        class_of_byte[byte] = signatures[key]
    n_classes = len(signatures)
    if n_classes > 64:
        raise RegexUnsupported("too many byte classes")

    trans = np.zeros((n, n_classes), np.uint32)
    class_members = [[] for _ in range(n_classes)]
    for byte in range(256):
        class_members[class_of_byte[byte]].append(byte)
    for (src, cls, dstn) in nfa.edges:
        target = closure[dstn]
        for c_id, members in enumerate(class_members):
            if members[0] in cls:
                trans[src, c_id] |= np.uint32(target & 0xFFFFFFFF)

    mn, mx = _len_bounds(ast)
    return CompiledRegex(
        n_states=n,
        start_mask=closure[start],
        accept_mask=1 << accept,
        class_table=class_of_byte,
        n_classes=n_classes,
        trans=trans,
        anchored_start=astart,
        anchored_end=aend,
        min_len=mn,
        max_len=mx,
    )
