"""Vectorized NFA execution over string columns.

Bit-parallel Thompson simulation: per lane a uint32 state bitmask; each
scan step gathers one byte per lane, looks up its equivalence class, and
advances every active state through the dense transition table — all
fused VPU work, no per-row control flow (the TPU answer to cuDF's regex
kernel; reference: jni RegexProgram usage in stringFunctions.scala).

Two drivers:
- `nfa_match` (rlike): one lane per ROW, scan over character positions.
- `match_spans` (extract/replace): one lane per BYTE POSITION — computes
  for every position whether a match starts there and its greedy-longest
  length; `_leftmost_nonoverlap` then picks the matches a left-to-right
  scan would, by pointer-jumping over the skip chain.

Documented deviations (docs/compatibility.md Regex): byte-domain (ASCII
exact; multi-byte UTF-8 matched bytewise), greedy-longest instead of
backtracking order for alternations of different lengths, zero-length
matches at end-of-string are not replaced.
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .kernel_utils import CV
from .regex_nfa import CompiledRegex

__all__ = ["nfa_match", "match_spans", "replace_all", "extract_first",
           "MAX_SCAN"]

# scan-length safety bound: matches past this byte offset in longer rows
# are missed (documented in docs/compatibility.md Regex)
MAX_SCAN = 256


def _advance(state, cls_id, trans_dev, n_states):
    """One NFA step for all lanes: state uint32[n], cls_id int32[n]."""
    nxt = jnp.zeros_like(state)
    for s in range(n_states):
        active = ((state >> np.uint32(s)) & np.uint32(1)).astype(jnp.bool_)
        nxt = nxt | jnp.where(active, trans_dev[s][cls_id], jnp.uint32(0))
    return nxt


def nfa_match(rx: CompiledRegex, cv: CV, max_len: int):
    """bool[n]: does each row match (Spark rlike = unanchored search)."""
    n = cv.offsets.shape[0] - 1
    starts = cv.offsets[:-1]
    lens = (cv.offsets[1:] - starts).astype(jnp.int32)
    data = cv.data
    dcap = data.shape[0]
    ctab = jnp.asarray(rx.class_table.astype(np.int32))
    trans_dev = [jnp.asarray(rx.trans[s]) for s in range(rx.n_states)]
    start_mask = jnp.uint32(rx.start_mask)
    accept = jnp.uint32(rx.accept_mask)

    state0 = jnp.full(n, rx.start_mask, jnp.uint32)
    zero_ok = bool(rx.start_mask & rx.accept_mask)
    if zero_ok:
        # the empty match: always for unanchored-end; at len==0 otherwise
        matched0 = (jnp.ones(n, jnp.bool_) if not rx.anchored_end
                    else (lens == 0))
    else:
        matched0 = jnp.zeros(n, jnp.bool_)
    final0 = jnp.where(lens == 0, state0, jnp.zeros(n, jnp.uint32))

    def body(carry, t):
        state, matched, final = carry
        idx = jnp.clip(starts + t, 0, dcap - 1)
        inb = t < lens
        cls = ctab[data[idx].astype(jnp.int32)]
        nxt = _advance(state, cls, trans_dev, rx.n_states)
        if not rx.anchored_start:
            nxt = nxt | start_mask    # search: a match may start anywhere
        nxt = jnp.where(inb, nxt, state)
        if rx.anchored_end:
            final = jnp.where(t + 1 == lens, nxt, final)
        else:
            matched = matched | (inb & ((nxt & accept) != 0))
        return (nxt, matched, final), None

    (_, matched, final), _ = jax.lax.scan(
        body, (state0, matched0, final0),
        jnp.arange(int(max_len), dtype=jnp.int32))
    if rx.anchored_end:
        matched = matched0 | ((final & accept) != 0)
    return matched & cv.validity


def match_spans(rx: CompiledRegex, cv: CV, max_match: int):
    """(ok bool[B], length int32[B]): for every byte position, whether a
    match starts there (anchored at that position) and its greedy-longest
    length, bounded by max_match bytes. Matches never cross row ends."""
    from .strings import byte_row_map
    data = cv.data
    B = data.shape[0]
    row = byte_row_map(cv.offsets, B)
    row_start = cv.offsets[:-1][row]
    row_end = cv.offsets[1:][row]
    ctab = jnp.asarray(rx.class_table.astype(np.int32))
    trans_dev = [jnp.asarray(rx.trans[s]) for s in range(rx.n_states)]
    accept = jnp.uint32(rx.accept_mask)
    pos = jnp.arange(B, dtype=jnp.int32)

    state0 = jnp.full(B, rx.start_mask, jnp.uint32)
    zero_ok = bool(rx.start_mask & rx.accept_mask)
    best0 = jnp.full(B, 0 if (zero_ok and not rx.anchored_end) else -1,
                     jnp.int32)

    def body(carry, j):
        state, best = carry
        idx = jnp.clip(pos + j, 0, B - 1)
        inb = (pos + j) < row_end
        cls = ctab[data[idx].astype(jnp.int32)]
        nxt = _advance(state, cls, trans_dev, rx.n_states)
        nxt = jnp.where(inb, nxt, jnp.uint32(0))
        hit = (nxt & accept) != 0
        if rx.anchored_end:
            hit = hit & ((pos + j + 1) == row_end)
        best = jnp.where(hit, j + 1, best)
        return (nxt, best), None

    (_, best), _ = jax.lax.scan(
        body, (state0, best0),
        jnp.arange(int(max_match), dtype=jnp.int32))
    ok = best >= 0
    if rx.anchored_start:
        ok = ok & (pos == row_start)
    ok = ok & (pos < cv.offsets[-1])
    return ok, jnp.maximum(best, 0)


def _leftmost_nonoverlap(cv: CV, ok, length):
    """Positions a left-to-right scan would select: walk each row from its
    start, skipping max(len,1) at a match else 1. Pointer-jumping over the
    skip chain marks the visited positions in O(log B) doubling steps."""
    B = ok.shape[0]
    pos = jnp.arange(B, dtype=jnp.int32)
    step = jnp.where(ok, jnp.maximum(length, 1), 1)
    jump = jnp.minimum(pos + step, B)
    from .strings import byte_row_map
    row = byte_row_map(cv.offsets, B)
    row_start = cv.offsets[:-1][row]
    visited = (pos == row_start) & (pos < cv.offsets[-1])
    n_steps = max(1, int(np.ceil(np.log2(max(B, 2)))) + 1)

    def body(carry, _):
        visited, jump = carry
        targets = jnp.where(visited, jump, B)
        newly = jnp.zeros(B + 1, jnp.bool_).at[targets].set(True)[:B]
        visited = visited | newly
        jext = jnp.concatenate([jump, jnp.full(1, B, jnp.int32)])
        jump = jext[jump]
        return (visited, jump), None

    (visited, _), _ = jax.lax.scan(body, (visited, jump),
                                   jnp.arange(n_steps))
    return visited & ok


def replace_all(rx: CompiledRegex, cv: CV, repl: bytes, max_match: int,
                out_capacity: int) -> CV:
    """Replace every selected (leftmost, non-overlapping) match with the
    literal `repl`. Output layout: at a match start the replacement bytes
    are emitted; bytes covered by a match are dropped; everything else
    copies through."""
    ok, length = match_spans(rx, cv, max_match)
    sel = _leftmost_nonoverlap(cv, ok, length)
    B = cv.data.shape[0]
    pos = jnp.arange(B, dtype=jnp.int32)
    in_row = pos < cv.offsets[-1]
    sel = sel & in_row

    covered = jnp.zeros(B + 1, jnp.int32)
    mstart = jnp.where(sel, pos, B)
    mend = jnp.where(sel, jnp.minimum(pos + jnp.maximum(length, 0), B), B)
    covered = covered.at[mstart].add(1).at[mend].add(-1)
    covered = jnp.cumsum(covered[:B]) > 0
    keep = in_row & ~covered

    rl = len(repl)
    contrib = jnp.where(sel, rl, 0) + keep.astype(jnp.int32)
    from .strings import byte_row_map
    n = cv.offsets.shape[0] - 1
    row = byte_row_map(cv.offsets, B)
    row_safe = jnp.clip(row, 0, n - 1)
    out_len = jax.ops.segment_sum(jnp.where(in_row, contrib, 0),
                                  row_safe, n)
    new_off = jnp.concatenate([jnp.zeros(1, jnp.int32),
                               jnp.cumsum(out_len).astype(jnp.int32)])
    excl = jnp.cumsum(contrib) - contrib
    row_base = jax.ops.segment_min(
        jnp.where(in_row, excl, jnp.iinfo(jnp.int32).max), row_safe, n)
    row_base = jnp.where(out_len > 0, row_base, 0)
    dst_base = new_off[:-1][row_safe] + (excl - row_base[row_safe])

    out = jnp.zeros(out_capacity, jnp.uint8)
    dst_keep = dst_base + jnp.where(sel, rl, 0)
    ok_keep = keep & (dst_keep < out_capacity)
    out = out.at[jnp.minimum(dst_keep, out_capacity - 1)].max(
        jnp.where(ok_keep, cv.data, 0).astype(jnp.uint8))
    for k in range(rl):
        dsel = dst_base + k
        ok_r = sel & (dsel < out_capacity)
        out = out.at[jnp.minimum(dsel, out_capacity - 1)].max(
            jnp.where(ok_r, jnp.uint8(repl[k]), jnp.uint8(0)))
    return CV(out, cv.validity, new_off)


def extract_first(rx: CompiledRegex, cv: CV, max_match: int):
    """(start int32[n], length int32[n], found bool[n]) of the leftmost
    (then greedy-longest) whole match per row."""
    from .strings import byte_row_map
    ok, length = match_spans(rx, cv, max_match)
    B = cv.data.shape[0]
    pos = jnp.arange(B, dtype=jnp.int32)
    row = byte_row_map(cv.offsets, B)
    n = cv.offsets.shape[0] - 1
    row_safe = jnp.clip(row, 0, n - 1)
    in_row = pos < cv.offsets[-1]
    cand = jnp.where(ok & in_row, pos, B)
    first = jax.ops.segment_min(cand, row_safe, n)
    found = first < B
    safe = jnp.clip(first, 0, B - 1)
    ln = jnp.where(found, length[safe], 0)
    start = jnp.where(found, safe, cv.offsets[:-1])
    zero_ok = bool(rx.start_mask & rx.accept_mask)
    if zero_ok and not rx.anchored_end:
        # a zero-length match always exists (e.g. `x*`): empty rows match
        found = jnp.ones(n, jnp.bool_)
    return start, ln, found & cv.validity
