"""Decimal128 exact arithmetic on 32-bit limbs (JNI DecimalUtils analog).

The reference does 128-bit decimal math in CUDA via spark-rapids-jni
DecimalUtils; TPU lanes are 32-bit, so values travel as FOUR 32-bit limbs
held in int64 arrays (each limb in [0, 2^32); the COLUMN stores them as a
[cap, 2] int64 buffer: limb pairs packed little-endian, two's complement).
All kernels below are elementwise/vectorized — multi-precision schoolbook
arithmetic with column accumulators, bit-for-bit exact:

  add/sub    : 4-limb ripple carry, signed overflow detect
  mul        : 8-column 32x32 products -> 256-bit, overflow past 127 bits
  div        : sign-magnitude; numerator scaled to 256 bits, shift-subtract
               long division (lax.scan), HALF_UP rounding like Spark
  rescale    : multiply/divide by 10^k with rounding
  sum limbs  : per-segment limb sums + final carry recombination

Overflow semantics: Spark non-ANSI — result null (overflow flags returned
to callers)."""
from __future__ import annotations

from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["to_limbs", "from_limbs", "dec_add", "dec_sub", "dec_mul",
           "dec_div", "dec_rescale", "dec_neg", "dec_cmp", "dec_from_i64",
           "dec_to_i64", "POW10_128", "fits_precision"]

# python int, NOT a jnp array: a module-level device array used inside a
# jitted function gets lifted to a hidden executable input, which breaks
# executable reuse across calls ("supplied 8 buffers, expected 9")
_MASK32 = 0xFFFFFFFF

# 10^k as 4x32 limb constants, k = 0..38
POW10_128: List[Tuple[int, int, int, int]] = []
for _k in range(39):
    _v = 10 ** _k
    POW10_128.append(tuple((_v >> (32 * i)) & 0xFFFFFFFF for i in range(4)))

# max |unscaled| for precision p: 10^p - 1
def _bound_limbs(p: int):
    v = 10 ** p - 1
    return tuple((v >> (32 * i)) & 0xFFFFFFFF for i in range(4))


# ---------------------------------------------------------------------
# [cap,2] int64 <-> 4-limb lists (int64 lanes holding [0, 2^32))
# ---------------------------------------------------------------------
def to_limbs(data2):
    """[cap,2] packed -> [l0,l1,l2,l3] (two's-complement raw limbs)."""
    lo, hi = data2[:, 0], data2[:, 1]
    ulo = lo.astype(jnp.uint64)
    uhi = hi.astype(jnp.uint64)
    return [
        (ulo & jnp.uint64(0xFFFFFFFF)).astype(jnp.int64),
        (ulo >> jnp.uint64(32)).astype(jnp.int64),
        (uhi & jnp.uint64(0xFFFFFFFF)).astype(jnp.int64),
        (uhi >> jnp.uint64(32)).astype(jnp.int64),
    ]


def from_limbs(limbs):
    """[l0..l3] -> [cap,2] packed int64 (limbs already in [0,2^32))."""
    l0, l1, l2, l3 = limbs
    ulo = l0.astype(jnp.uint64) | (l1.astype(jnp.uint64) << jnp.uint64(32))
    uhi = l2.astype(jnp.uint64) | (l3.astype(jnp.uint64) << jnp.uint64(32))
    return jnp.stack([ulo.astype(jnp.int64), uhi.astype(jnp.int64)],
                     axis=-1)


def _is_neg(limbs):
    return limbs[3] >= jnp.int64(1 << 31)


def _neg_raw(limbs):
    """Two's-complement negate of a 4-limb value."""
    out = []
    carry = jnp.ones_like(limbs[0])
    for l in limbs:
        v = (l ^ _MASK32) + carry
        out.append(v & _MASK32)
        carry = v >> 32
    return out


def _abs(limbs):
    neg = _is_neg(limbs)
    n = _neg_raw(limbs)
    return [jnp.where(neg, a, b) for a, b in zip(n, limbs)], neg


def _add_raw(a, b, k=None):
    """Limbwise add with ripple carry; returns (limbs, carry_out)."""
    k = k or max(len(a), len(b))
    out = []
    carry = jnp.zeros_like(a[0])
    for i in range(k):
        ai = a[i] if i < len(a) else 0
        bi = b[i] if i < len(b) else 0
        v = ai + bi + carry
        out.append(v & _MASK32)
        carry = v >> 32
    return out, carry


def _sub_raw(a, b, k=None):
    """a - b limbwise with borrow; returns (limbs, borrow_out in {0,1})."""
    k = k or max(len(a), len(b))
    out = []
    borrow = jnp.zeros_like(a[0])
    for i in range(k):
        ai = a[i] if i < len(a) else 0
        bi = b[i] if i < len(b) else 0
        v = ai - bi - borrow
        out.append(v & _MASK32)
        borrow = (v >> 32) & 1
    return out, borrow


def _cmp_raw(a, b):
    """unsigned compare of equal-length limb lists: -1/0/1 per lane."""
    res = jnp.zeros_like(a[0])
    for ai, bi in zip(reversed(a), reversed(b)):
        res = jnp.where(res != 0, res,
                        jnp.sign(ai - bi))
    return res


def _const_limbs(tpl, like):
    return [jnp.full_like(like, int(x)) for x in tpl]


def fits_precision(limbs, precision: int):
    """|value| <= 10^precision - 1 (on raw two's-complement limbs)."""
    mag, _ = _abs(limbs)
    bound = _const_limbs(_bound_limbs(precision), limbs[0])
    return _cmp_raw(mag, bound) <= 0


# ---------------------------------------------------------------------
def dec_add(a2, b2):
    """(result [cap,2], overflow bool): 128-bit signed add."""
    a, b = to_limbs(a2), to_limbs(b2)
    s, _ = _add_raw(a, b, 4)
    # signed overflow: same-sign operands, different-sign result
    sa, sb, sr = _is_neg(a), _is_neg(b), _is_neg(s)
    ovf = (sa == sb) & (sr != sa)
    return from_limbs(s), ovf


def dec_neg(a2):
    return from_limbs(_neg_raw(to_limbs(a2)))


def dec_sub(a2, b2):
    a, b = to_limbs(a2), to_limbs(b2)
    nb = _neg_raw(b)
    s, _ = _add_raw(a, nb, 4)
    sa, sb, sr = _is_neg(a), ~_is_neg(b), _is_neg(s)
    # a + (-b): overflow when sign(a) == sign(-b) != sign(result); the
    # -b edge (b == MIN128) negates to itself — treat sign(-b) as ~sign(b)
    ovf = (sa == sb) & (sr != sa)
    return from_limbs(s), ovf


def _mul_raw_columns(a, b, out_limbs=8):
    """Magnitude multiply via 16x16-bit sub-limbs to keep every product
    inside int64."""
    # split each 32-bit limb into two 16-bit half-limbs: 8 halves each
    ah = []
    bh = []
    for l in a:
        ah.append(l & 0xFFFF)
        ah.append(l >> 16)
    for l in b:
        bh.append(l & 0xFFFF)
        bh.append(l >> 16)
    H = out_limbs * 2
    cols = [jnp.zeros_like(a[0]) for _ in range(H + 1)]
    for i in range(8):
        for j in range(8):
            k = i + j
            if k >= H:
                continue
            cols[k] = cols[k] + ah[i] * bh[j]   # < 2^32 each, <=64 terms
    # carry-propagate 16-bit columns
    out16 = []
    carry = jnp.zeros_like(a[0])
    for k in range(H):
        v = cols[k] + carry
        out16.append(v & 0xFFFF)
        carry = v >> 16
    # fold halves back to 32-bit limbs
    out = [(out16[2 * i] | (out16[2 * i + 1] << 16))
           for i in range(out_limbs)]
    return out, carry


def dec_mul(a2, b2, precision: int):
    """(result [cap,2], overflow): exact signed multiply; overflow when
    |product| needs more than `precision` digits (or > 127 bits)."""
    a, b = to_limbs(a2), to_limbs(b2)
    ma, na = _abs(a)
    mb, nb = _abs(b)
    prod, carry = _mul_raw_columns(ma, mb, 8)
    hi_any = (sum(prod[4:]) + carry) > 0
    fits = fits_precision_mag(prod[:4], precision)
    ovf = hi_any | ~fits
    neg = na ^ nb
    res = [jnp.where(neg, x, y) for x, y in zip(_neg_raw(prod[:4]),
                                                prod[:4])]
    return from_limbs(res), ovf


def fits_precision_mag(mag_limbs, precision: int):
    bound = _const_limbs(_bound_limbs(precision), mag_limbs[0])
    return _cmp_raw(mag_limbs, bound) <= 0


def _shift_left_one(limbs, bit_in):
    """(limbs << 1) | bit_in over k 32-bit limbs."""
    out = []
    carry = bit_in
    for l in limbs:
        v = (l << 1) | carry
        out.append(v & _MASK32)
        carry = (v >> 32) & 1
    return out, carry


def _long_div(num, den, nbits: int):
    """Unsigned long division: num (k-limb) / den (4-limb), both
    magnitudes. Returns (quotient k-limb, remainder 4-limb). Shift-
    subtract over nbits via lax.scan (static)."""
    k = len(num)

    def body(carry, bit):
        quo, rem = carry
        # bit runs nbits-1 .. 0
        b = jnp.zeros_like(num[0])
        for limb_i in range(k):
            sel = (bit // 32) == limb_i
            b = jnp.where(sel, (num[limb_i] >> (bit % 32)) & 1, b)
        rem, _ = _shift_left_one(rem, b)
        rem5 = rem  # 5 limbs to be safe against shift carry
        ge = _cmp_raw(rem5[:5], den + [jnp.zeros_like(den[0])]) >= 0
        sub, _ = _sub_raw(rem5[:5], den + [jnp.zeros_like(den[0])], 5)
        rem = [jnp.where(ge, s, r) for s, r in zip(sub, rem5)]
        # set quotient bit
        quo2 = []
        for limb_i in range(k):
            sel = (bit // 32) == limb_i
            quo2.append(jnp.where(sel & ge,
                                  quo[limb_i] | (jnp.int64(1)
                                                 << (bit % 32)),
                                  quo[limb_i]))
        return (quo2, rem), None

    quo0 = [jnp.zeros_like(num[0]) for _ in range(k)]
    rem0 = [jnp.zeros_like(num[0]) for _ in range(5)]
    (quo, rem), _ = jax.lax.scan(
        body, (quo0, rem0),
        jnp.arange(nbits - 1, -1, -1, dtype=jnp.int32))
    return quo, rem[:4]


def dec_div(a2, b2, scale_shift: int, precision: int,
            num_digits: int = 38):
    """Spark decimal divide: (a * 10^scale_shift) / b with HALF_UP
    rounding. Numerator computed in 256 bits; the long-division scan is
    bounded by the numerator's static digit count (num_digits = operand
    precision; ~3.33 bits/digit) instead of a flat 256 steps. Returns
    (result, overflow, divzero)."""
    a, b = to_limbs(a2), to_limbs(b2)
    ma, na = _abs(a)
    mb, nb = _abs(b)
    divzero = sum(mb) == 0
    safe_mb = [jnp.where(divzero, jnp.ones_like(x) * (i == 0), x)
               for i, x in enumerate(mb)]
    pow_l = _const_limbs(POW10_128[scale_shift], a[0])
    num, _ = _mul_raw_columns(ma, pow_l, 8)      # 256-bit numerator
    nbits = min(256, int((num_digits + scale_shift) * 3.33) + 2)
    quo, rem = _long_div(num, safe_mb, nbits)
    # HALF_UP: round away from zero when 2*rem >= |b|
    rem2, c = _shift_left_one(rem, jnp.zeros_like(rem[0]))
    ge = (_cmp_raw(rem2, safe_mb) >= 0) | (c > 0)
    one = [jnp.ones_like(quo[0])] + [jnp.zeros_like(quo[0])] * 7
    quo_up, _ = _add_raw(quo, one, 8)
    quo = [jnp.where(ge, u, q) for u, q in zip(quo_up, quo)]
    hi_any = sum(quo[4:]) > 0
    fits = fits_precision_mag(quo[:4], precision)
    ovf = hi_any | ~fits
    neg = na ^ nb
    res = [jnp.where(neg, x, y)
           for x, y in zip(_neg_raw(quo[:4]), quo[:4])]
    return from_limbs(res), ovf, divzero


def dec_rescale(a2, from_scale: int, to_scale: int, precision: int,
                half_up: bool = True):
    """Rescale by 10^(to-from): up = exact multiply (overflow checked),
    down = divide with HALF_UP (or truncation toward zero when half_up is
    False — the decimal->integral cast). Returns (result, overflow)."""
    if to_scale == from_scale:
        a = to_limbs(a2)
        return a2, ~fits_precision(a, precision)
    a = to_limbs(a2)
    ma, neg = _abs(a)
    if to_scale > from_scale:
        pow_l = _const_limbs(POW10_128[to_scale - from_scale], a[0])
        prod, carry = _mul_raw_columns(ma, pow_l, 8)
        hi_any = (sum(prod[4:]) + carry) > 0
        fits = fits_precision_mag(prod[:4], precision)
        mag = prod[:4]
        ovf = hi_any | ~fits
    else:
        k = from_scale - to_scale
        pow_l = _const_limbs(POW10_128[k], a[0])
        quo, rem = _long_div(ma + [jnp.zeros_like(ma[0])] * 4, pow_l, 128)
        if half_up:
            rem2, c = _shift_left_one(rem, jnp.zeros_like(rem[0]))
            ge = (_cmp_raw(rem2, pow_l) >= 0) | (c > 0)
            one = [jnp.ones_like(quo[0])] + [jnp.zeros_like(quo[0])] * 7
            quo_up, _ = _add_raw(quo, one, 8)
            quo = [jnp.where(ge, u, q) for u, q in zip(quo_up, quo)]
        mag = quo[:4]
        ovf = ~fits_precision_mag(mag, precision)
    res = [jnp.where(neg, x, y) for x, y in zip(_neg_raw(mag), mag)]
    return from_limbs(res), ovf


def dec_cmp(a2, b2):
    """Signed three-way compare (-1/0/1) of two [cap,2] decimals with the
    same scale. Same-sign two's-complement values order like their raw
    unsigned limbs, so no subtraction (and no wrap) is needed."""
    a, b = to_limbs(a2), to_limbs(b2)
    na, nb = _is_neg(a), _is_neg(b)
    ucmp = _cmp_raw(a, b)
    return jnp.where(na != nb, jnp.where(na, -1, 1),
                     ucmp).astype(jnp.int32)


def dec_mul_scaled(a2, b2, down_shift: int, precision: int):
    """Exact multiply at full scale (s1+s2) then HALF_UP rescale down by
    10^down_shift, all on the 256-bit product — matches Spark's clamped
    result scale without intermediate overflow."""
    a, b = to_limbs(a2), to_limbs(b2)
    ma, na = _abs(a)
    mb, nb = _abs(b)
    prod, carry = _mul_raw_columns(ma, mb, 8)
    if down_shift > 0:
        pow_l = _const_limbs(POW10_128[down_shift], a[0])
        quo, rem = _long_div(prod, pow_l, 256)
        rem2, c = _shift_left_one(rem, jnp.zeros_like(rem[0]))
        ge = (_cmp_raw(rem2, pow_l) >= 0) | (c > 0)
        one = [jnp.ones_like(quo[0])] + [jnp.zeros_like(quo[0])] * 7
        quo_up, _ = _add_raw(quo, one, 8)
        prod = [jnp.where(ge, u, q) for u, q in zip(quo_up, quo)]
        carry = jnp.zeros_like(carry)
    hi_any = (sum(prod[4:]) + carry) > 0
    fits = fits_precision_mag(prod[:4], precision)
    ovf = hi_any | ~fits
    neg = na ^ nb
    res = [jnp.where(neg, x, y)
           for x, y in zip(_neg_raw(prod[:4]), prod[:4])]
    return from_limbs(res), ovf


def dec_cmp_scaled(a2, sa: int, b2, sb: int):
    """Three-way compare of decimals with different scales: the smaller
    scale side scales up into 256 bits (no overflow possible), compared
    as sign + 8-limb magnitude."""
    a, b = to_limbs(a2), to_limbs(b2)
    ma, na = _abs(a)
    mb, nb = _abs(b)
    ka, kb = max(sb - sa, 0), max(sa - sb, 0)
    pa = _const_limbs(POW10_128[ka], a[0])
    pb = _const_limbs(POW10_128[kb], a[0])
    wa, ca = _mul_raw_columns(ma, pa, 8)
    wb, cb = _mul_raw_columns(mb, pb, 8)
    mag = _cmp_raw(wa + [ca], wb + [cb])
    za = (sum(wa) + ca) == 0
    zb = (sum(wb) + cb) == 0
    both_zero = za & zb
    res = jnp.where(
        na & ~nb, -1, jnp.where(
            nb & ~na, 1, jnp.where(na & nb, -mag, mag)))
    return jnp.where(both_zero, 0, res).astype(jnp.int32)


def split_i64_limbs(x):
    """int64 -> [lo32 (unsigned), hi32 (signed)] for exact summation."""
    return [x & _MASK32, x >> 32]


def split_d128_limbs(a2):
    """[cap,2] -> [l0,l1,l2 (unsigned 32), l3 (signed 32)] for exact
    summation (value = l0 + l1*2^32 + l2*2^64 + l3*2^96)."""
    l = to_limbs(a2)
    lo, hi = a2[:, 0], a2[:, 1]
    return [l[0], l[1], l[2], hi >> 32]


def combine_limb_sums(sums, precision: int):
    """Reconstruct the exact total from per-limb int64 sums (sums[k]
    multiplies 2^(32k); the last is signed). Returns ([cap,2] packed,
    overflow_beyond_precision). Exact while each |sums[k]| < 2^62."""
    K = 6
    cols = [jnp.zeros_like(sums[0]) for _ in range(K)]
    for k, s in enumerate(sums):
        cols[k] = cols[k] + (s & _MASK32)
        if k + 1 < K:
            cols[k + 1] = cols[k + 1] + (s >> 32)   # arithmetic shift
    # normalize signed columns to 32-bit limbs (two's complement)
    limbs = []
    carry = jnp.zeros_like(cols[0])
    for k in range(K):
        v = cols[k] + carry
        limbs.append(v & _MASK32)
        carry = v >> 32
    # sign from the (virtual) limb beyond: carry is the sign extension
    neg = carry < 0
    # magnitude check: value fits 128 bits AND 10^precision - 1
    # negate if negative (6-limb two's complement with the carry word)
    full = limbs + [carry & _MASK32]
    comp = []
    c2 = jnp.ones_like(cols[0])
    for l in full:
        v = (l ^ _MASK32) + c2
        comp.append(v & _MASK32)
        c2 = v >> 32
    mag = [jnp.where(neg, a, b) for a, b in zip(comp, full)]
    hi_any = sum(mag[4:]) > 0
    fits = fits_precision_mag(mag[:4], precision) & ~hi_any
    res_mag = mag[:4]
    res = [jnp.where(neg, x, y)
           for x, y in zip(_neg_raw(res_mag), res_mag)]
    return from_limbs(res), ~fits


def dec_from_i64(x):
    """int64 unscaled -> [cap,2] (sign-extended)."""
    hi = jnp.where(x < 0, jnp.int64(-1), jnp.int64(0))
    return jnp.stack([x, hi], axis=-1)


def dec_to_i64(a2):
    """[cap,2] -> int64 (truncating; valid when the value fits 64 bits).
    Returns (value, fits_bool)."""
    lo, hi = a2[:, 0], a2[:, 1]
    fits = (hi == 0) & (lo >= 0) | (hi == -1) & (lo < 0)
    return lo, fits
