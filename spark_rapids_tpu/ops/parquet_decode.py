"""Device-side Parquet decode kernels.

The reference decodes column chunks on the accelerator
(GpuParquetScan.scala:3364 Table.readParquet; chunked readers :2523,
:3134). TPU equivalent: the host reads RAW column-chunk bytes and
parses only page-header/run metadata (io/parquet_thrift.py, O(pages)),
uploads the bytes ONCE, and everything that touches values runs here as
jitted programs — PLAIN fixed-width assembly from byte lanes,
RLE/bit-packed hybrid expansion (def levels + dictionary indices) via
the scatter+cummax run-ownership map, dictionary gather, and
def-level -> validity + packed-value scatter.

All shapes are static per (page-count, run-count, capacity) bucket; the
byte buffer is the only data-dependent input.
"""
from __future__ import annotations

import functools
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .gather import row_of_unit

__all__ = ["decode_plain_fixed", "expand_hybrid", "apply_def_levels",
           "bucket_len", "byte_array_index", "rows_from_packed",
           "dict_rows", "assemble_strings", "snappy_expand"]


def bucket_len(n: int, floor: int = 8) -> int:
    """Pow2 bucket for metadata-table lengths (page/run tables) so jit
    shapes repeat across chunks."""
    c = floor
    while c < n:
        c <<= 1
    return c


@functools.partial(jax.jit, static_argnames=("width", "cap"))
def decode_plain_fixed(chunk, page_payload_off, page_first_val,
                       n_pages, total, width: int, cap: int):
    """Assemble little-endian fixed-width values from PLAIN page
    payloads. chunk: uint8[*]; page_payload_off/page_first_val:
    int32[P] (bucketed, padded with sentinels past n_pages).

    Returns uint64[cap] raw value words (caller bitcasts/narrows)."""
    i = jnp.arange(cap, dtype=jnp.int32)
    pg = row_of_unit(page_first_val, page_payload_off.shape[0], cap)
    pg = jnp.minimum(pg, n_pages - 1)
    base = page_payload_off[pg] + (i - page_first_val[pg]) * width
    nb = chunk.shape[0]
    word = jnp.zeros(cap, jnp.uint64)
    for b in range(width):
        byte = chunk[jnp.clip(base + b, 0, nb - 1)].astype(jnp.uint64)
        word = word | (byte << jnp.uint64(8 * b))
    return jnp.where(i < total, word, 0)


@functools.partial(jax.jit, static_argnames=("bit_width", "cap"))
def expand_hybrid(chunk, run_start, run_count, run_packed, run_value,
                  run_byteoff, n_runs, total, bit_width: int, cap: int):
    """Expand an RLE/bit-packed hybrid section to one value per output
    index. Run tables are int32[R] (bucketed; padding rows must carry
    out_start == total). Returns int32[cap]."""
    i = jnp.arange(cap, dtype=jnp.int32)
    rid = row_of_unit(run_start, run_start.shape[0], cap)
    rid = jnp.minimum(rid, jnp.maximum(n_runs - 1, 0))
    within = i - run_start[rid]
    # bit-packed lanes: value j of the run occupies bits
    # [j*bw, (j+1)*bw) of the payload starting at run_byteoff
    bitpos = run_byteoff[rid].astype(jnp.int64) * 8 + \
        within.astype(jnp.int64) * bit_width
    byte0 = (bitpos >> 3).astype(jnp.int32)
    shift = (bitpos & 7).astype(jnp.uint64)
    nb = chunk.shape[0]
    word = jnp.zeros(cap, jnp.uint64)
    nbytes_needed = (bit_width + 7 + 7) // 8  # bw bits + up to 7 shift
    for b in range(min(nbytes_needed, 8)):
        byte = chunk[jnp.clip(byte0 + b, 0, nb - 1)].astype(jnp.uint64)
        word = word | (byte << jnp.uint64(8 * b))
    mask = (jnp.uint64((1 << bit_width) - 1) if bit_width < 64
            # tpulint: allow[strong-literal] uint64 mask must be strong:
            else jnp.uint64(0xFFFFFFFFFFFFFFFF))
    packed = ((word >> shift) & mask).astype(jnp.int32)
    rle = run_value[rid]
    out = jnp.where(run_packed[rid].astype(jnp.bool_), packed, rle)
    return jnp.where(i < total, out, 0)


@functools.partial(jax.jit, static_argnames=("cap",))
def apply_def_levels(def_levels, packed_words, max_def, total,
                     cap: int):
    """def level == max_def -> valid; packed (non-null-only) values
    scatter to their row positions. Returns (uint64[cap] words,
    bool[cap] validity)."""
    i = jnp.arange(cap, dtype=jnp.int32)
    valid = (def_levels == max_def) & (i < total)
    vidx = jnp.cumsum(valid.astype(jnp.int32)) - 1
    words = packed_words[jnp.clip(vidx, 0, packed_words.shape[0] - 1)]
    return jnp.where(valid, words, 0), valid


def _le32_at(chunk, pos):
    """Little-endian uint32 read at arbitrary byte positions (gather of
    four lanes; out-of-range positions clip and yield garbage the
    caller masks)."""
    nb = chunk.shape[0]
    w = jnp.zeros(pos.shape, jnp.int32)
    for b in range(4):
        byte = chunk[jnp.clip(pos + b, 0, nb - 1)].astype(jnp.int32)
        w = w | (byte << (8 * b))
    return w


@functools.partial(jax.jit, static_argnames=("kbits", "cap"))
def byte_array_index(chunk, page_payload_off, page_first_val,
                     n_pages, total, kbits: int, cap: int):
    """Locate every PACKED value of a PLAIN BYTE_ARRAY section: returns
    (byte_start int32[cap], byte_len int32[cap]) into `chunk`.

    The [uint32 len][bytes] stream is a linked list (each length tells
    where the next one starts), so value positions are found by pointer
    doubling: a jump table next[b] = b + 4 + le32(b) over every byte
    position, squared kbits times; value i applies the 2^k jump for
    each set bit of its within-page ordinal. O(kbits) gathers instead
    of a sequential host walk of the value stream. `kbits` must cover
    the max per-page value count; page_first_val rows past n_pages must
    carry the sentinel `total`."""
    i = jnp.arange(cap, dtype=jnp.int32)
    pg = row_of_unit(page_first_val, page_payload_off.shape[0], cap)
    pg = jnp.minimum(pg, jnp.maximum(n_pages - 1, 0))
    k = jnp.maximum(i - page_first_val[pg], 0)
    pos = page_payload_off[pg]
    nb = chunk.shape[0]
    b = jnp.arange(nb, dtype=jnp.int32)
    nxt = jnp.clip(b + 4 + _le32_at(chunk, b), 0, nb - 1) \
        .astype(jnp.int32)
    for bit in range(kbits):
        take = ((k >> bit) & 1).astype(jnp.bool_)
        pos = jnp.where(take, nxt[jnp.clip(pos, 0, nb - 1)], pos)
        if bit != kbits - 1:
            nxt = nxt[nxt]
    live = i < total
    lens = jnp.clip(_le32_at(chunk, pos), 0, nb)
    return (jnp.where(live, pos + 4, 0).astype(jnp.int32),
            jnp.where(live, lens, 0).astype(jnp.int32))


@functools.partial(jax.jit, static_argnames=("cap",))
def rows_from_packed(starts, lens, valid, total, cap: int):
    """Map packed-stream (start, len) pairs to the ROW domain: nulls get
    length 0, non-null row r takes packed value rank(r)."""
    i = jnp.arange(cap, dtype=jnp.int32)
    v = valid & (i < total)
    vidx = jnp.clip(jnp.cumsum(v.astype(jnp.int32)) - 1, 0,
                    starts.shape[0] - 1)
    return starts[vidx], jnp.where(v, lens[vidx], 0)


@functools.partial(jax.jit, static_argnames=("cap",))
def dict_rows(idx, dstart, dlen, valid, total, cap: int):
    """Per-row (start, len) for dictionary-encoded strings: packed
    index stream -> row domain via validity rank, then dictionary
    entry extents."""
    i = jnp.arange(cap, dtype=jnp.int32)
    v = valid & (i < total)
    vidx = jnp.clip(jnp.cumsum(v.astype(jnp.int32)) - 1, 0,
                    idx.shape[0] - 1)
    rid = jnp.clip(idx[vidx], 0, dstart.shape[0] - 1)
    return dstart[rid], jnp.where(v, dlen[rid], 0)


@functools.partial(jax.jit, static_argnames=("cap", "dcap"))
def assemble_strings(chunk, row_start, row_len, total, cap: int,
                     dcap: int):
    """Gather per-row byte ranges of `chunk` into the engine's chunked
    string layout: (data uint8[dcap], offsets int32[cap+1]). Offsets
    come from an exclusive prefix sum of the (null-masked) lengths;
    bytes move via the scatter+cummax byte->row ownership map."""
    i = jnp.arange(cap, dtype=jnp.int32)
    row_len = jnp.where(i < total, jnp.maximum(row_len, 0), 0)
    off = jnp.concatenate([jnp.zeros(1, jnp.int32),
                           jnp.cumsum(row_len).astype(jnp.int32)])
    rob = row_of_unit(off, cap, dcap)
    pos = jnp.arange(dcap, dtype=jnp.int32)
    src = row_start[rob] + (pos - off[rob])
    nb = chunk.shape[0]
    data = chunk[jnp.clip(src, 0, nb - 1)]
    data = jnp.where(pos < off[cap], data, 0).astype(jnp.uint8)
    return data, off


@functools.partial(jax.jit, static_argnames=("kbits", "cap"))
def snappy_expand(comp, el_dst, el_lit, el_src, n_el, out_len,
                  kbits: int, cap: int):
    """Device snappy decompression of ONE page from its host-parsed
    element table (the nvcomp-snappy analog; conf
    `sql.parquet.deviceSnappy`).

    Each output byte first maps to its owning element (scatter+cummax).
    Literal bytes resolve directly to a compressed-buffer position
    (encoded as -(pos+1)); copy bytes point at an EARLIER output byte
    (i - back_offset — overlapping copies included, since the target is
    always strictly earlier). kbits pointer-doubling rounds
    (src = src[src]) then resolve every byte to a literal source, and
    one gather materializes the page. el_dst rows past n_el must carry
    the sentinel out_len."""
    i = jnp.arange(cap, dtype=jnp.int32)
    eid = row_of_unit(el_dst, el_dst.shape[0], cap)
    eid = jnp.minimum(eid, jnp.maximum(n_el - 1, 0))
    within = i - el_dst[eid]
    lit = el_lit[eid].astype(jnp.bool_)
    src = jnp.where(lit, -(el_src[eid] + within) - 1, i - el_src[eid])
    for _ in range(kbits):
        t = jnp.clip(src, 0, cap - 1)
        src = jnp.where(src >= 0, src[t], src)
    nb = comp.shape[0]
    out = comp[jnp.clip(-src - 1, 0, nb - 1)]
    return jnp.where(i < out_len, out, 0).astype(jnp.uint8)


def words_to_np_values(words: np.ndarray, physical: str):
    """Bitcast raw LE words to numpy values (host-side helper for
    parity tests; the engine bitcasts on device via column dtypes)."""
    if physical == "INT32":
        return words.astype(np.uint32).view(np.int32)
    if physical == "INT64":
        return words.view(np.int64)
    if physical == "FLOAT":
        return words.astype(np.uint32).view(np.float32)
    if physical == "DOUBLE":
        return words.view(np.float64)
    raise ValueError(physical)


# -- device bitcasts for the engine's column layout ---------------------
@functools.partial(jax.jit, static_argnames=("np_name",))
def words_to_device(words, np_name: str):
    if np_name == "int32":
        return jax.lax.bitcast_convert_type(
            words.astype(jnp.uint32), jnp.int32)
    if np_name == "int64":
        return jax.lax.bitcast_convert_type(words, jnp.int64)
    if np_name == "float32":
        return jax.lax.bitcast_convert_type(
            words.astype(jnp.uint32), jnp.float32)
    if np_name == "float64":
        return jax.lax.bitcast_convert_type(words, jnp.float64)
    if np_name == "bool":
        return words.astype(jnp.bool_)
    raise ValueError(np_name)
