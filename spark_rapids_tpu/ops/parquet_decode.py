"""Device-side Parquet decode kernels.

The reference decodes column chunks on the accelerator
(GpuParquetScan.scala:3364 Table.readParquet; chunked readers :2523,
:3134). TPU equivalent: the host reads RAW column-chunk bytes and
parses only page-header/run metadata (io/parquet_thrift.py, O(pages)),
uploads the bytes ONCE, and everything that touches values runs here as
jitted programs — PLAIN fixed-width assembly from byte lanes,
RLE/bit-packed hybrid expansion (def levels + dictionary indices) via
the scatter+cummax run-ownership map, dictionary gather, and
def-level -> validity + packed-value scatter.

All shapes are static per (page-count, run-count, capacity) bucket; the
byte buffer is the only data-dependent input.
"""
from __future__ import annotations

import functools
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .gather import row_of_unit

__all__ = ["decode_plain_fixed", "expand_hybrid", "apply_def_levels",
           "bucket_len"]


def bucket_len(n: int, floor: int = 8) -> int:
    """Pow2 bucket for metadata-table lengths (page/run tables) so jit
    shapes repeat across chunks."""
    c = floor
    while c < n:
        c <<= 1
    return c


@functools.partial(jax.jit, static_argnames=("width", "cap"))
def decode_plain_fixed(chunk, page_payload_off, page_first_val,
                       n_pages, total, width: int, cap: int):
    """Assemble little-endian fixed-width values from PLAIN page
    payloads. chunk: uint8[*]; page_payload_off/page_first_val:
    int32[P] (bucketed, padded with sentinels past n_pages).

    Returns uint64[cap] raw value words (caller bitcasts/narrows)."""
    i = jnp.arange(cap, dtype=jnp.int32)
    pg = row_of_unit(page_first_val, page_payload_off.shape[0], cap)
    pg = jnp.minimum(pg, n_pages - 1)
    base = page_payload_off[pg] + (i - page_first_val[pg]) * width
    nb = chunk.shape[0]
    word = jnp.zeros(cap, jnp.uint64)
    for b in range(width):
        byte = chunk[jnp.clip(base + b, 0, nb - 1)].astype(jnp.uint64)
        word = word | (byte << jnp.uint64(8 * b))
    return jnp.where(i < total, word, 0)


@functools.partial(jax.jit, static_argnames=("bit_width", "cap"))
def expand_hybrid(chunk, run_start, run_count, run_packed, run_value,
                  run_byteoff, n_runs, total, bit_width: int, cap: int):
    """Expand an RLE/bit-packed hybrid section to one value per output
    index. Run tables are int32[R] (bucketed; padding rows must carry
    out_start == total). Returns int32[cap]."""
    i = jnp.arange(cap, dtype=jnp.int32)
    rid = row_of_unit(run_start, run_start.shape[0], cap)
    rid = jnp.minimum(rid, jnp.maximum(n_runs - 1, 0))
    within = i - run_start[rid]
    # bit-packed lanes: value j of the run occupies bits
    # [j*bw, (j+1)*bw) of the payload starting at run_byteoff
    bitpos = run_byteoff[rid].astype(jnp.int64) * 8 + \
        within.astype(jnp.int64) * bit_width
    byte0 = (bitpos >> 3).astype(jnp.int32)
    shift = (bitpos & 7).astype(jnp.uint64)
    nb = chunk.shape[0]
    word = jnp.zeros(cap, jnp.uint64)
    nbytes_needed = (bit_width + 7 + 7) // 8  # bw bits + up to 7 shift
    for b in range(min(nbytes_needed, 8)):
        byte = chunk[jnp.clip(byte0 + b, 0, nb - 1)].astype(jnp.uint64)
        word = word | (byte << jnp.uint64(8 * b))
    mask = (jnp.uint64((1 << bit_width) - 1) if bit_width < 64
            # tpulint: allow[strong-literal] uint64 mask must be strong:
            else jnp.uint64(0xFFFFFFFFFFFFFFFF))
    packed = ((word >> shift) & mask).astype(jnp.int32)
    rle = run_value[rid]
    out = jnp.where(run_packed[rid].astype(jnp.bool_), packed, rle)
    return jnp.where(i < total, out, 0)


@functools.partial(jax.jit, static_argnames=("cap",))
def apply_def_levels(def_levels, packed_words, max_def, total,
                     cap: int):
    """def level == max_def -> valid; packed (non-null-only) values
    scatter to their row positions. Returns (uint64[cap] words,
    bool[cap] validity)."""
    i = jnp.arange(cap, dtype=jnp.int32)
    valid = (def_levels == max_def) & (i < total)
    vidx = jnp.cumsum(valid.astype(jnp.int32)) - 1
    words = packed_words[jnp.clip(vidx, 0, packed_words.shape[0] - 1)]
    return jnp.where(valid, words, 0), valid


def words_to_np_values(words: np.ndarray, physical: str):
    """Bitcast raw LE words to numpy values (host-side helper for
    parity tests; the engine bitcasts on device via column dtypes)."""
    if physical == "INT32":
        return words.astype(np.uint32).view(np.int32)
    if physical == "INT64":
        return words.view(np.int64)
    if physical == "FLOAT":
        return words.astype(np.uint32).view(np.float32)
    if physical == "DOUBLE":
        return words.view(np.float64)
    raise ValueError(physical)


# -- device bitcasts for the engine's column layout ---------------------
@functools.partial(jax.jit, static_argnames=("np_name",))
def words_to_device(words, np_name: str):
    if np_name == "int32":
        return jax.lax.bitcast_convert_type(
            words.astype(jnp.uint32), jnp.int32)
    if np_name == "int64":
        return jax.lax.bitcast_convert_type(words, jnp.int64)
    if np_name == "float32":
        return jax.lax.bitcast_convert_type(
            words.astype(jnp.uint32), jnp.float32)
    if np_name == "float64":
        return jax.lax.bitcast_convert_type(words, jnp.float64)
    if np_name == "bool":
        return words.astype(jnp.bool_)
    raise ValueError(np_name)
