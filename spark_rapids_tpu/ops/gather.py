"""Gather / compaction kernels.

The TPU answers to cudf's gather & apply_boolean_mask
(reference: JoinGatherer.scala, GpuFilterExec). Static-shape discipline:
outputs keep the input capacity; a row count / live mask travels alongside.

String gathers rebuild the offsets via cumsum and move bytes with a
searchsorted-based byte-index map — O(bytes) fully vectorized, no
per-row loops.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

from .kernel_utils import CV

__all__ = ["take", "compact", "compaction_perm", "take_strings"]


def compaction_perm(mask) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Stable permutation moving live rows to the front.

    Returns (perm, count). perm[i] = source row for dense output slot i.
    """
    # stable argsort on (!mask) keeps relative order of live rows
    perm = jnp.argsort(jnp.logical_not(mask), stable=True)
    count = jnp.sum(mask.astype(jnp.int32))
    return perm, count


def take_fixed(cv: CV, idx, in_bounds=None) -> CV:
    """Gather rows of a fixed-width column. idx values outside the valid
    domain must be pre-clipped; rows where in_bounds is False become null."""
    safe = jnp.clip(idx, 0, cv.data.shape[0] - 1)
    data = cv.data[safe]
    valid = cv.validity[safe]
    if in_bounds is not None:
        valid = valid & in_bounds
    return CV(data, valid)


def take_strings(cv: CV, idx, in_bounds=None,
                 out_data_capacity: Optional[int] = None) -> CV:
    """Gather rows of a string column, rebuilding offsets + data."""
    n_out = idx.shape[0]
    safe = jnp.clip(idx, 0, cv.offsets.shape[0] - 2)
    starts = cv.offsets[safe]
    ends = cv.offsets[safe + 1]
    lens = ends - starts
    valid = cv.validity[safe]
    if in_bounds is not None:
        valid = valid & in_bounds
        lens = jnp.where(in_bounds, lens, 0)
    new_off = jnp.concatenate([jnp.zeros(1, jnp.int32),
                               jnp.cumsum(lens).astype(jnp.int32)])
    out_cap = out_data_capacity or cv.data.shape[0]
    pos = jnp.arange(out_cap, dtype=jnp.int32)
    row = jnp.searchsorted(new_off[1:], pos, side="right").astype(jnp.int32)
    row = jnp.clip(row, 0, n_out - 1)
    src = starts[row] + (pos - new_off[row])
    src = jnp.clip(src, 0, cv.data.shape[0] - 1)
    data = cv.data[src]
    # bytes beyond total length are garbage; mask to zero for determinism
    total = new_off[n_out]
    data = jnp.where(pos < total, data, 0).astype(jnp.uint8)
    return CV(data, valid, new_off)


def take(cv: CV, idx, in_bounds=None) -> CV:
    if cv.offsets is not None:
        return take_strings(cv, idx, in_bounds)
    return take_fixed(cv, idx, in_bounds)


def compact(cvs: List[CV], mask) -> Tuple[List[CV], jnp.ndarray]:
    """Move live rows to the front of every column; returns (cvs, count)."""
    perm, count = compaction_perm(mask)
    in_bounds = jnp.arange(perm.shape[0]) < count
    out = [take(cv, perm, in_bounds) for cv in cvs]
    return out, count
