"""Gather / compaction kernels.

The TPU answers to cudf's gather & apply_boolean_mask
(reference: JoinGatherer.scala, GpuFilterExec). Static-shape discipline:
outputs keep the input capacity; a row count / live mask travels alongside.

String gathers rebuild the offsets via cumsum and move bytes with a
searchsorted-based byte-index map — O(bytes) fully vectorized, no
per-row loops.
"""
from __future__ import annotations

import functools
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

from .kernel_utils import CV

__all__ = ["take", "compact", "compaction_perm", "take_strings"]


@functools.partial(jax.jit, static_argnames=("caps_all",))
def _gather_table_jit(cvs, idx, inb, caps_all):
    """Whole-table gather as ONE compiled program. Eager per-op dispatch
    here cost ~0.6ms/primitive on the hot join path (hundreds of ops per
    probe); a single jit turns that into one dispatch + lets XLA fuse."""
    its = [iter(c) if c else None for c in caps_all]
    return [take(cv, idx, inb, it) for cv, it in zip(cvs, its)]


@jax.jit
def _compact_table_jit(cvs, mask):
    perm, count = compaction_perm(mask)
    in_bounds = jnp.arange(perm.shape[0]) < count
    return [take(cv, perm, in_bounds) for cv in cvs], count


def compaction_perm(mask) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Stable permutation moving live rows to the front.

    Returns (perm, count). perm[i] = source row for dense output slot i.
    Cumsum + scatter, NOT argsort: XLA's sort is O(n log n) single-threaded
    scalar code on CPU (~0.5s at 1M rows) while this is three linear passes.
    """
    n = mask.shape[0]
    m = mask.astype(jnp.int32)
    count = jnp.sum(m)
    live_pos = jnp.cumsum(m) - m              # dense slot for live rows
    dead_pos = count + jnp.cumsum(1 - m) - (1 - m)
    pos = jnp.where(mask, live_pos, dead_pos)  # dest slot of source row i
    perm = jnp.zeros(n, jnp.int32).at[pos].set(
        jnp.arange(n, dtype=jnp.int32))
    return perm, count


def row_of_unit(new_off, n_out: int, out_cap: int):
    """For var-width layouts: map each output unit position (byte /
    element) to its owning row. scatter(row start) + cummax — two linear
    passes instead of searchsorted's O(units * log rows) scalar loop
    (~25x faster at 4M units on XLA:CPU, and gather/scan vectorize on
    TPU where searchsorted does not)."""
    starts = new_off[:n_out].astype(jnp.int32)
    safe = jnp.minimum(starts, out_cap)
    rob = jnp.zeros(out_cap + 1, jnp.int32).at[safe].max(
        jnp.arange(n_out, dtype=jnp.int32))
    rob = jax.lax.cummax(rob)[:out_cap]
    return rob


def take_fixed(cv: CV, idx, in_bounds=None) -> CV:
    """Gather rows of a fixed-width column. idx values outside the valid
    domain must be pre-clipped; rows where in_bounds is False become null."""
    safe = jnp.clip(idx, 0, cv.data.shape[0] - 1)
    data = cv.data[safe]
    valid = cv.validity[safe]
    if in_bounds is not None:
        valid = valid & in_bounds
    return CV(data, valid)


def take_strings(cv: CV, idx, in_bounds=None,
                 out_data_capacity: Optional[int] = None) -> CV:
    """Gather rows of a string column, rebuilding offsets + data."""
    n_out = idx.shape[0]
    safe = jnp.clip(idx, 0, cv.offsets.shape[0] - 2)
    starts = cv.offsets[safe]
    ends = cv.offsets[safe + 1]
    lens = ends - starts
    valid = cv.validity[safe]
    if in_bounds is not None:
        valid = valid & in_bounds
        lens = jnp.where(in_bounds, lens, 0)
    new_off = jnp.concatenate([jnp.zeros(1, jnp.int32),
                               jnp.cumsum(lens).astype(jnp.int32)])
    out_cap = out_data_capacity or cv.data.shape[0]
    pos = jnp.arange(out_cap, dtype=jnp.int32)
    row = row_of_unit(new_off, n_out, out_cap)
    src = starts[row] + (pos - new_off[row])
    src = jnp.clip(src, 0, cv.data.shape[0] - 1)
    data = cv.data[src]
    # bytes beyond total length are garbage; mask to zero for determinism
    total = new_off[n_out]
    data = jnp.where(pos < total, data, 0).astype(jnp.uint8)
    return CV(data, valid, new_off)


def repeat_measures(cv: CV, eff) -> List:
    """Device scalars of var-width output units needed when row i of `cv`
    is replicated eff[i] times (strings: bytes; arrays: elements), in the
    same DFS order `take(..., caps=...)` consumes them. Nested levels
    compose through offset spans: bytes for a list<string> row =
    child_offsets[row_end_elem] - child_offsets[row_start_elem]."""
    out: List = []
    _rm(cv, eff, out)
    return out


def _rm(cv: CV, eff, out: List):
    if cv.children and cv.offsets is None:      # struct
        for ch in cv.children:
            _rm(ch, eff, out)
        return
    if cv.offsets is None:
        return
    lens = (cv.offsets[1:] - cv.offsets[:-1]).astype(jnp.int64)
    lens = jnp.where(cv.validity, lens, 0)
    out.append(jnp.sum(eff.astype(jnp.int64) * lens))
    if cv.children:
        _rm_span(cv.child, cv.offsets[:-1], cv.offsets[1:],
                 cv.validity, eff, out)


def _rm_span(cv: CV, starts, ends, valid, eff, out: List):
    if cv.children and cv.offsets is None:      # struct element
        for ch in cv.children:
            _rm_span(ch, starts, ends, valid, eff, out)
        return
    if cv.offsets is None:
        return
    hi = cv.offsets.shape[0] - 1
    s2 = cv.offsets[jnp.clip(starts, 0, hi)]
    e2 = cv.offsets[jnp.clip(ends, 0, hi)]
    units = jnp.where(valid, (e2 - s2).astype(jnp.int64), 0)
    out.append(jnp.sum(eff.astype(jnp.int64) * units))
    if cv.children:
        _rm_span(cv.child, s2, e2, valid, eff, out)


def take_measures(cv: CV, idx, in_bounds=None) -> List:
    """Device scalars of var-width output units needed to gather rows
    `idx` of `cv` (gathers may repeat rows, so source capacities are NOT
    upper bounds). Same DFS order as `take(..., caps=...)`."""
    out: List = []
    _tm(cv, idx, in_bounds, out)
    return out


def _tm(cv: CV, idx, inb, out: List):
    if cv.children and cv.offsets is None:      # struct
        for ch in cv.children:
            _tm(ch, idx, inb, out)
        return
    if cv.offsets is None:
        return
    safe = jnp.clip(idx, 0, cv.offsets.shape[0] - 2)
    starts = cv.offsets[safe]
    ends = cv.offsets[safe + 1]
    valid = cv.validity[safe]
    if inb is not None:
        valid = valid & inb
    units = jnp.where(valid, (ends - starts).astype(jnp.int64), 0)
    out.append(jnp.sum(units))
    if cv.children:
        ones = jnp.ones(idx.shape[0], jnp.int64)
        _rm_span(cv.child, starts, ends, valid, ones, out)


def take_array(cv: CV, idx, in_bounds=None,
               out_elem_capacity: Optional[int] = None, caps=None) -> CV:
    """Gather rows of a list column: rebuild offsets from gathered row
    lengths, then gather the referenced element ranges from the child
    (recursively, so list<string>/list<list<...>> work)."""
    n_out = idx.shape[0]
    off = cv.offsets
    safe = jnp.clip(idx, 0, off.shape[0] - 2)
    starts = off[safe]
    lens = off[safe + 1] - off[safe]
    valid = cv.validity[safe]
    # null slots may carry placeholder ranges — never read them
    lens = jnp.where(valid, lens, 0)
    if in_bounds is not None:
        valid = valid & in_bounds
        lens = jnp.where(in_bounds, lens, 0)
    new_off = jnp.concatenate([jnp.zeros(1, jnp.int32),
                               jnp.cumsum(lens).astype(jnp.int32)])
    out_cap = out_elem_capacity or cv.child.capacity
    pos = jnp.arange(out_cap, dtype=jnp.int32)
    row = row_of_unit(new_off, n_out, out_cap)
    src = starts[row] + (pos - new_off[row])
    elem_ok = pos < new_off[n_out]
    child = take(cv.child, src, elem_ok, caps)
    return CV(jnp.zeros(0, jnp.int8), valid, new_off, (child,))


def take_struct(cv: CV, idx, in_bounds=None, caps=None) -> CV:
    safe = jnp.clip(idx, 0, cv.validity.shape[0] - 1)
    valid = cv.validity[safe]
    if in_bounds is not None:
        valid = valid & in_bounds
    kids = tuple(take(ch, idx, in_bounds, caps) for ch in cv.children)
    return CV(jnp.zeros(0, jnp.int8), valid, None, kids)


def take(cv: CV, idx, in_bounds=None, caps=None) -> CV:
    """Gather rows. `caps` is an optional iterator of output var-width
    capacities (from `repeat_measures`, bucketed) consumed in DFS order;
    without it, source capacities are reused (correct only when no row is
    replicated)."""
    if cv.children:
        if cv.offsets is not None:
            return take_array(cv, idx, in_bounds,
                              next(caps) if caps else None, caps)
        return take_struct(cv, idx, in_bounds, caps)
    if cv.offsets is not None:
        return take_strings(cv, idx, in_bounds,
                            next(caps) if caps else None)
    return take_fixed(cv, idx, in_bounds)


def compact(cvs: List[CV], mask) -> Tuple[List[CV], jnp.ndarray]:
    """Move live rows to the front of every column; returns (cvs, count)."""
    if any(cv.offsets is not None or cv.children for cv in cvs):
        # var-width columns trace per-column (source capacities reused —
        # compaction never replicates rows)
        perm, count = compaction_perm(mask)
        in_bounds = jnp.arange(perm.shape[0]) < count
        out = [take(cv, perm, in_bounds) for cv in cvs]
        return out, count
    return _compact_table_jit(cvs, mask)


@jax.jit
def _measures_jit(var_cvs, idx, inb):
    return {i: take_measures(cv, idx, inb) for i, cv in var_cvs.items()}


def gather_cols(cvs: List[CV], idx, inb) -> List[CV]:
    """Gather a table's columns by idx. Var-width columns (strings AND
    nested lists, recursively) get output capacities sized from the actual
    gathered unit totals — gathers may replicate rows, so source
    capacities are not upper bounds. The gather itself runs as one jitted
    program per (schema, caps) shape."""
    from ..columnar.column import bucket_capacity
    from ..utils.transfer import fetch
    var_cols = [i for i, cv in enumerate(cvs)
                if cv.offsets is not None or cv.children]
    caps = {}
    if var_cols:
        measures = _measures_jit({i: cvs[i] for i in var_cols}, idx, inb)
        got = fetch(measures)
        caps = {i: tuple(bucket_capacity(max(int(v), 1)) for v in ms)
                for i, ms in got.items()}
    caps_all = tuple(caps.get(i, ()) for i in range(len(cvs)))
    return _gather_table_jit(cvs, idx, inb, caps_all)
