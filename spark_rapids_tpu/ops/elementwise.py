"""Elementwise kernels with Spark SQL semantics.

Replaces the cudf elementwise kernel surface used by the reference's
expression layer (reference: org/apache/spark/sql/rapids/arithmetic.scala,
predicates.scala, mathExpressions.scala). Semantics implemented here:

  - null propagation: result is null if any input is null (except Kleene
    and/or, null predicates, null-safe equality)
  - divide / remainder by zero -> null (non-ANSI Spark)
  - integral overflow wraps (Java semantics; jnp ints wrap likewise)
  - float NaN: Spark orders NaN greater than any value and NaN == NaN is
    true in comparisons/grouping (reference docs/compatibility.md)

All functions take/return `CV` and are pure jax — safe under jit, fused by
XLA.
"""
from __future__ import annotations

import jax.numpy as jnp

from .kernel_utils import CV, and_validity

__all__ = [
    "add", "sub", "mul", "divide", "int_divide", "remainder", "pmod",
    "negate", "abs_", "eq", "ne", "lt", "le", "gt", "ge", "eq_null_safe",
    "logical_and", "logical_or", "logical_not", "is_null", "is_not_null",
    "is_nan", "nan_safe_eq",
]


def _is_float(x) -> bool:
    return jnp.issubdtype(x.dtype, jnp.floating)


# ----------------------------------------------------------------------
# Arithmetic
# ----------------------------------------------------------------------
def add(a: CV, b: CV) -> CV:
    return CV(a.data + b.data, and_validity(a, b))


def sub(a: CV, b: CV) -> CV:
    return CV(a.data - b.data, and_validity(a, b))


def mul(a: CV, b: CV) -> CV:
    return CV(a.data * b.data, and_validity(a, b))


def divide(a: CV, b: CV) -> CV:
    """Spark `/`: output is fractional (or decimal); divisor 0 -> null."""
    zero = b.data == 0
    safe = jnp.where(zero, jnp.ones_like(b.data), b.data)
    out = a.data / safe if _is_float(a.data) else a.data // safe
    return CV(out, and_validity(a, b) & ~zero)


def int_divide(a: CV, b: CV) -> CV:
    """Spark `div`: integral division, divisor 0 -> null, Java truncation."""
    zero = b.data == 0
    safe = jnp.where(zero, jnp.ones_like(b.data), b.data)
    # Java integer division truncates toward zero; jnp floor-divides.
    q = a.data // safe
    r = a.data - q * safe
    q = jnp.where((r != 0) & ((a.data < 0) != (b.data < 0)), q + 1, q)
    return CV(q, and_validity(a, b) & ~zero)


def remainder(a: CV, b: CV) -> CV:
    """Spark `%`: sign follows dividend (Java), divisor 0 -> null."""
    zero = b.data == 0
    safe = jnp.where(zero, jnp.ones_like(b.data), b.data)
    r = jnp.where(zero, jnp.zeros_like(a.data),
                  a.data - jnp.trunc(a.data / safe).astype(a.data.dtype) * safe
                  if _is_float(a.data) else
                  a.data - _java_div(a.data, safe) * safe)
    return CV(r, and_validity(a, b) & ~zero)


def _java_div(a, b):
    q = a // b
    r = a - q * b
    return jnp.where((r != 0) & ((a < 0) != (b < 0)), q + 1, q)


def pmod(a: CV, b: CV) -> CV:
    """Spark pmod: positive modulus, divisor 0 -> null."""
    zero = b.data == 0
    safe = jnp.where(zero, jnp.ones_like(b.data), b.data)
    m = jnp.mod(a.data, safe)
    m = jnp.where(m < 0, m + jnp.abs(safe), m)
    return CV(m, and_validity(a, b) & ~zero)


def negate(a: CV) -> CV:
    return CV(-a.data, a.validity)


def abs_(a: CV) -> CV:
    return CV(jnp.abs(a.data), a.validity)


# ----------------------------------------------------------------------
# Comparison (Spark NaN semantics: NaN == NaN, NaN greater than all)
# ----------------------------------------------------------------------
def nan_safe_eq(x, y):
    if _is_float(x):
        return (x == y) | (jnp.isnan(x) & jnp.isnan(y))
    return x == y


def _nan_lt(x, y):
    if _is_float(x):
        # NaN is greatest: x < y iff (x<y) or (x not NaN and y NaN)
        return (x < y) | (~jnp.isnan(x) & jnp.isnan(y))
    return x < y


def eq(a: CV, b: CV) -> CV:
    return CV(nan_safe_eq(a.data, b.data), and_validity(a, b))


def ne(a: CV, b: CV) -> CV:
    return CV(~nan_safe_eq(a.data, b.data), and_validity(a, b))


def lt(a: CV, b: CV) -> CV:
    return CV(_nan_lt(a.data, b.data), and_validity(a, b))


def le(a: CV, b: CV) -> CV:
    return CV(_nan_lt(a.data, b.data) | nan_safe_eq(a.data, b.data),
              and_validity(a, b))


def gt(a: CV, b: CV) -> CV:
    return CV(_nan_lt(b.data, a.data), and_validity(a, b))


def ge(a: CV, b: CV) -> CV:
    return CV(_nan_lt(b.data, a.data) | nan_safe_eq(a.data, b.data),
              and_validity(a, b))


def eq_null_safe(a: CV, b: CV) -> CV:
    """<=> : null <=> null is true; never returns null."""
    both_null = ~a.validity & ~b.validity
    both_valid = a.validity & b.validity
    out = both_null | (both_valid & nan_safe_eq(a.data, b.data))
    return CV(out, jnp.ones_like(out))


# ----------------------------------------------------------------------
# Boolean (Kleene three-valued logic)
# ----------------------------------------------------------------------
def logical_and(a: CV, b: CV) -> CV:
    av = a.validity & a.data.astype(jnp.bool_)
    bv = b.validity & b.data.astype(jnp.bool_)
    af = a.validity & ~a.data.astype(jnp.bool_)
    bf = b.validity & ~b.data.astype(jnp.bool_)
    out = av & bv
    valid = (af | bf) | (a.validity & b.validity)
    return CV(out, valid)


def logical_or(a: CV, b: CV) -> CV:
    av = a.validity & a.data.astype(jnp.bool_)
    bv = b.validity & b.data.astype(jnp.bool_)
    out = av | bv
    valid = (av | bv) | (a.validity & b.validity)
    return CV(out, valid)


def logical_not(a: CV) -> CV:
    return CV(~a.data.astype(jnp.bool_), a.validity)


# ----------------------------------------------------------------------
# Null predicates
# ----------------------------------------------------------------------
def is_null(a: CV) -> CV:
    out = ~a.validity
    return CV(out, jnp.ones_like(out))


def is_not_null(a: CV) -> CV:
    return CV(a.validity, jnp.ones_like(a.validity))


def is_nan(a: CV) -> CV:
    if _is_float(a.data):
        return CV(jnp.isnan(a.data), a.validity)
    return CV(jnp.zeros_like(a.validity), a.validity)
