"""CAST kernels with Spark (non-ANSI) semantics.

TPU-side analog of the reference's GpuCast
(reference: sql-plugin/.../GpuCast.scala:286 and JNI CastStrings). Round-1
covers numeric/bool/temporal/decimal casts; string casts land with the
string kernel pack.

Spark-specific behaviors implemented:
  - floating -> integral saturates at the target range; NaN -> 0
    (Scala `Double.toInt` semantics)
  - integral -> narrower integral wraps (Java narrowing)
  - decimal rescale rounds HALF_UP; overflow -> null (non-ANSI)
  - timestamp -> date floors toward negative infinity
"""
from __future__ import annotations

import jax.numpy as jnp

from ..columnar import dtypes as dt
from .kernel_utils import CV

__all__ = ["cast_cv"]

_INT_RANGE = {
    dt.ByteType: (-128, 127),
    dt.ShortType: (-32768, 32767),
    dt.IntegerType: (-2**31, 2**31 - 1),
    dt.LongType: (-2**63, 2**63 - 1),
}

MICROS_PER_DAY = 86400 * 1_000_000
MICROS_PER_SEC = 1_000_000


def _floor_div(a, b):
    return a // b


def cast_cv(cv: CV, from_t: dt.DataType, to_t: dt.DataType) -> CV:
    if from_t == to_t:
        return cv
    if isinstance(from_t, dt.NullType):
        np_dt = to_t.np_dtype
        return CV(jnp.zeros(cv.capacity, np_dt),
                  jnp.zeros(cv.capacity, jnp.bool_))

    x, valid = cv.data, cv.validity

    # ---- boolean source ------------------------------------------------
    if isinstance(from_t, dt.BooleanType):
        if isinstance(to_t, dt.DecimalType):
            return CV(x.astype(jnp.int64) * (10 ** to_t.scale), valid)
        return CV(x.astype(to_t.np_dtype), valid)

    # ---- to boolean ----------------------------------------------------
    if isinstance(to_t, dt.BooleanType):
        if isinstance(from_t, dt.DecimalType):
            return CV(x != 0, valid)
        return CV(x != 0, valid)

    # ---- temporal ------------------------------------------------------
    if isinstance(from_t, dt.TimestampType):
        if isinstance(to_t, dt.DateType):
            return CV(_floor_div(x, MICROS_PER_DAY).astype(jnp.int32), valid)
        secs = _floor_div(x, MICROS_PER_SEC)
        if isinstance(to_t, dt.LongType):
            return CV(secs, valid)
        if to_t.is_integral:
            # narrowing wraps like Java (Spark non-ANSI long -> int/...)
            return CV(secs.astype(to_t.np_dtype), valid)
        if to_t.is_floating:
            return CV((x.astype(jnp.float64) / MICROS_PER_SEC)
                      .astype(to_t.np_dtype), valid)
        if isinstance(to_t, dt.DecimalType):
            # seconds with 6 fractional digits, rescaled to the target
            if to_t.is_decimal128:
                from .decimal128 import dec_from_i64, dec_rescale
                out, ovf = dec_rescale(dec_from_i64(x), 6, to_t.scale,
                                       to_t.precision)
                return CV(out, valid & ~ovf)
            return _rescale_decimal(x, valid, 6, to_t)
        raise NotImplementedError(f"cast timestamp -> {to_t}")
    if isinstance(from_t, dt.DateType):
        if isinstance(to_t, dt.TimestampType):
            return CV(x.astype(jnp.int64) * MICROS_PER_DAY, valid)
        if isinstance(to_t, dt.IntegerType):
            return CV(x.astype(jnp.int32), valid)
        raise NotImplementedError(f"cast date -> {to_t}")
    if isinstance(to_t, dt.TimestampType):
        if from_t.is_integral:
            return CV(x.astype(jnp.int64) * MICROS_PER_SEC, valid)
        if from_t.is_floating:
            # seconds (fraction -> micros); NaN/Inf -> null (Spark)
            xf = x.astype(jnp.float64) * MICROS_PER_SEC
            ok = jnp.isfinite(x.astype(jnp.float64))
            return CV(jnp.where(ok, xf, 0.0).astype(jnp.int64),
                      valid & ok)

    # ---- decimal source ------------------------------------------------
    if isinstance(from_t, dt.DecimalType):
        s = from_t.scale
        if from_t.is_decimal128 or (isinstance(to_t, dt.DecimalType)
                                    and to_t.is_decimal128):
            return _cast_decimal128(cv, from_t, to_t)
        if isinstance(to_t, dt.DecimalType):
            return _rescale_decimal(x, valid, s, to_t)
        if to_t.is_floating:
            return CV((x.astype(jnp.float64) / (10.0 ** s)).astype(
                to_t.np_dtype), valid)
        if to_t.is_integral:
            p = 10 ** s
            q = x // p
            r = x - q * p
            q = jnp.where((r != 0) & (x < 0), q + 1, q)  # trunc toward zero
            lo, hi = _INT_RANGE[type(to_t)]
            ok = (q >= lo) & (q <= hi)
            return CV(q.astype(to_t.np_dtype), valid & ok)
        if isinstance(to_t, dt.TimestampType):
            # decimal seconds -> micros; sub-micro digits TRUNCATE
            # toward zero (Spark decimalToTimestamp = longValue)
            ds = 6 - s
            if ds >= 0:
                return CV(x.astype(jnp.int64) * (10 ** ds), valid)
            p = 10 ** (-ds)
            q = x // p
            r = x - q * p
            q = jnp.where((r != 0) & (x < 0), q + 1, q)
            return CV(q.astype(jnp.int64), valid)
        raise NotImplementedError(f"cast decimal -> {to_t}")

    # ---- to decimal ----------------------------------------------------
    if isinstance(to_t, dt.DecimalType):
        if to_t.is_decimal128:
            if from_t.is_integral:
                from .decimal128 import dec_from_i64, dec_rescale
                w = dec_from_i64(x.astype(jnp.int64))
                out, ovf = dec_rescale(w, 0, to_t.scale, to_t.precision)
                return CV(out, valid & ~ovf)
            if from_t.is_floating:
                return _float_to_decimal128(x, valid, to_t)
            raise NotImplementedError(f"cast {from_t} -> {to_t}")
        limit = 10 ** to_t.precision
        if from_t.is_integral:
            scaled = x.astype(jnp.int64) * (10 ** to_t.scale)
            ok = jnp.abs(x.astype(jnp.int64)) < 10 ** (to_t.precision
                                                       - to_t.scale)
            return CV(scaled, valid & ok)
        if from_t.is_floating:
            xf = x.astype(jnp.float64) * (10.0 ** to_t.scale)
            scaled = jnp.where(xf >= 0, jnp.floor(xf + 0.5),
                               jnp.ceil(xf - 0.5))
            ok = jnp.abs(scaled) < limit
            ok = ok & ~jnp.isnan(x)
            return CV(scaled.astype(jnp.int64), valid & ok)
        raise NotImplementedError(f"cast {from_t} -> decimal")

    # ---- numeric -> numeric --------------------------------------------
    if from_t.is_floating and to_t.is_integral:
        lo, hi = _INT_RANGE[type(to_t)]
        xf = jnp.nan_to_num(x, nan=0.0)
        clamped = jnp.clip(xf, float(lo), float(hi))
        return CV(clamped.astype(to_t.np_dtype), valid)
    if from_t.is_numeric and to_t.is_numeric:
        return CV(x.astype(to_t.np_dtype), valid)

    raise NotImplementedError(f"cast {from_t} -> {to_t}")


def _float_to_decimal128(x, valid, to_t: dt.DecimalType) -> CV:
    """float -> decimal(p>18): scale, round half-up, and decompose the
    (<= 53 significant bits) double into 32-bit limbs exactly."""
    from .decimal128 import from_limbs
    xf = x.astype(jnp.float64) * (10.0 ** to_t.scale)
    scaled = jnp.where(xf >= 0, jnp.floor(xf + 0.5), jnp.ceil(xf - 0.5))
    ok = (jnp.abs(scaled) < 10.0 ** to_t.precision) & ~jnp.isnan(x)
    mag = jnp.abs(jnp.where(ok, scaled, 0.0))
    limbs = []
    rem = mag
    for _ in range(4):
        l = jnp.mod(rem, 2.0 ** 32)
        limbs.append(l.astype(jnp.int64))
        rem = jnp.floor(rem / (2.0 ** 32))
    pos = from_limbs(limbs)
    from .decimal128 import dec_neg
    neg = dec_neg(pos)
    out = jnp.where((scaled < 0)[:, None], neg, pos)
    return CV(out, valid & ok)


def _cast_decimal128(cv: CV, from_t: dt.DecimalType,
                     to_t: dt.DataType) -> CV:
    """Casts where either side is a [cap,2]-limb decimal128."""
    from .decimal128 import (dec_from_i64, dec_rescale, dec_to_i64,
                             to_limbs)
    x, valid = cv.data, cv.validity
    wide = x if from_t.is_decimal128 else dec_from_i64(x)
    if isinstance(to_t, dt.DecimalType):
        out, ovf = dec_rescale(wide, from_t.scale, to_t.scale,
                               to_t.precision)
        if to_t.is_decimal128:
            return CV(out, valid & ~ovf)
        v64, fits = dec_to_i64(out)
        return CV(v64, valid & ~ovf & fits)
    if to_t.is_floating:
        lo, hi = wide[:, 0], wide[:, 1]
        ulo = jnp.where(lo < 0, lo.astype(jnp.float64) + 2.0**64,
                        lo.astype(jnp.float64))
        f = (hi.astype(jnp.float64) * (2.0**64) + ulo) / (10.0
                                                          ** from_t.scale)
        return CV(f.astype(to_t.np_dtype), valid)
    if to_t.is_integral:
        # truncation toward zero like the d64 path (Spark cast)
        out, ovf = dec_rescale(wide, from_t.scale, 0, 38, half_up=False)
        v64, fits = dec_to_i64(out)
        lo_b, hi_b = _INT_RANGE[type(to_t)]
        ok = (v64 >= lo_b) & (v64 <= hi_b) & fits & ~ovf
        return CV(v64.astype(to_t.np_dtype), valid & ok)
    if isinstance(to_t, dt.TimestampType):
        # sub-micro digits truncate toward zero (Spark longValue)
        out, ovf = dec_rescale(wide, from_t.scale, 6, 38, half_up=False)
        v64, fits = dec_to_i64(out)
        return CV(v64, valid & ~ovf & fits)
    raise NotImplementedError(f"cast {from_t} -> {to_t}")


def _rescale_decimal(x, valid, from_scale: int, to_t: dt.DecimalType) -> CV:
    ds = to_t.scale - from_scale
    if ds >= 0:
        out = x * (10 ** ds)
    else:
        p = 10 ** (-ds)
        half = p // 2
        adj = jnp.where(x >= 0, x + half, x - half)
        q = adj // p
        r = adj - q * p
        out = jnp.where((r != 0) & (adj < 0), q + 1, q)
    ok = jnp.abs(out) < 10 ** to_t.precision
    return CV(out, valid & ok)
