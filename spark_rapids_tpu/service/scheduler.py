"""Fair-share scheduler: weighted pools + memory-aware admission.

Analog of Spark's fair scheduler (FIFO within a pool, weighted shares
across pools) crossed with the admission side of the reference's
GpuSemaphore story: the semaphore bounds TASKS on the chip, this bounds
QUERIES in the engine, gated on a device+host memory estimate derived
from the plan's scan/build sizes (plan/planner.py cardinality
estimator) so concurrent queries cannot jointly blow the
DeviceManager/HostMemoryManager budgets — an oversized admission mix
queues with metrics instead of OOMing mid-flight.

Cross-pool arbitration is deficit round robin: every recharge round
credits each contending pool by its weight, and each admission debits
one credit from the granted pool, so under saturation grant counts
converge to the weight ratio without starving light pools.
"""
from __future__ import annotations

from collections import deque
from typing import Optional, Tuple

__all__ = ["Pool", "FairScheduler", "estimate_plan_memory"]


class Pool:
    __slots__ = ("name", "weight", "queue", "credit")

    def __init__(self, name: str, weight: int = 1):
        self.name = name
        self.weight = max(1, int(weight))
        self.queue = deque()
        self.credit = 0.0

    def __repr__(self):
        return f"Pool({self.name}, w={self.weight}, q={len(self.queue)})"


def _parse_pools(spec: str):
    pools = {}
    for part in str(spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        name, _, w = part.partition(":")
        try:
            weight = int(w) if w else 1
        except ValueError:
            weight = 1
        pools[name.strip()] = Pool(name.strip(), weight)
    if "default" not in pools:
        pools["default"] = Pool("default", 1)
    return pools


class FairScheduler:
    """NOT thread-safe on its own: the QueryManager serializes every
    call under its lock (offer/next_ready/remove/release are lock-free
    hot-path pieces of the manager's pump)."""

    def __init__(self, conf=None):
        from ..config import (SERVICE_SCHEDULER_MODE,
                              SERVICE_SCHEDULER_POOLS, TpuConf)
        self.conf = conf or TpuConf()
        self.mode = str(self.conf.get(SERVICE_SCHEDULER_MODE)).lower()
        self.pools = _parse_pools(self.conf.get(SERVICE_SCHEDULER_POOLS))
        # admitted-estimate accounting (bytes committed to running
        # queries; compared against _limits(), NOT real reservations —
        # the managers keep owning actuals + spill)
        self._admitted_dev = 0
        self._admitted_host = 0
        self._admitted_count = 0

    # -- queue maintenance ---------------------------------------------
    def pool_of(self, h) -> Pool:
        p = self.pools.get(h.pool)
        if p is None:
            # unknown pool names materialize with weight 1 rather than
            # failing the query (matches Spark's fair-scheduler behavior)
            # tpulint: allow[unlocked-shared-write] guarded by caller: QueryManager holds _cond across every scheduler call
            p = self.pools[h.pool] = Pool(h.pool, 1)
        return p

    def offer(self, h):
        self.pool_of(h).queue.append(h)

    def remove(self, h) -> bool:
        try:
            self.pool_of(h).queue.remove(h)
            return True
        except ValueError:
            return False

    def queued_count(self) -> int:
        return sum(len(p.queue) for p in self.pools.values())

    def priority_of(self, h) -> int:
        """TpuSemaphore acquire priority for this query's tasks: the
        heap pops the SMALLEST priority first, so heavier pools map to
        more-negative priorities and win device admission ties."""
        return -self.pool_of(h).weight

    # -- admission ------------------------------------------------------
    def _limits(self) -> Tuple[int, int]:
        from ..config import (SERVICE_ADMISSION_DEVICE_FRACTION,
                              SERVICE_ADMISSION_DEVICE_LIMIT,
                              SERVICE_ADMISSION_HOST_FRACTION)
        explicit = int(self.conf.get(SERVICE_ADMISSION_DEVICE_LIMIT) or 0)
        if explicit > 0:
            dev_limit = explicit
        else:
            from ..memory.device import device_manager
            dev_limit = int(device_manager(self.conf).budget * float(
                self.conf.get(SERVICE_ADMISSION_DEVICE_FRACTION)))
        from ..memory.host import host_manager
        host_budget = host_manager(self.conf).budget
        host_limit = (int(host_budget * float(
            self.conf.get(SERVICE_ADMISSION_HOST_FRACTION)))
            if host_budget and host_budget > 0 else 0)  # 0 = unlimited
        return dev_limit, host_limit

    def _fits(self, h) -> bool:
        from ..config import SERVICE_ADMISSION_ENABLED
        if not self.conf.get(SERVICE_ADMISSION_ENABLED):
            return True
        if self._admitted_count == 0:
            # never starve: a query whose solo estimate exceeds the
            # budget is admitted when it would run alone
            return True
        dev, host = h.estimate
        dev_limit, host_limit = self._limits()
        if (dev_limit > 0 and self._admitted_dev + int(dev) > dev_limit) \
                or (host_limit > 0
                    and self._admitted_host + int(host) > host_limit):
            # admission deferred on the memory budget: the query stays
            # queued; the counter tells a scraper the service is
            # memory-bound rather than slot-bound
            try:
                from ..profiler import telemetry
                telemetry.counter("admission_rejections").inc()
            except Exception:
                pass
            return False
        return True

    def release(self, h):
        """A granted query finished: return its estimate to the pot.
        Guarded by the caller: QueryManager holds _cond across every
        offer/grant/release (`release` sits on the resolver's
        polymorphic-name blocklist, so the static pass cannot see the
        caller's lock)."""
        dev, host = h.estimate
        # tpulint: allow[unlocked-shared-write] guarded by caller's QueryManager._cond
        self._admitted_dev = max(0, self._admitted_dev - int(dev))
        # tpulint: allow[unlocked-shared-write] guarded by caller's QueryManager._cond
        self._admitted_host = max(0, self._admitted_host - int(host))
        # tpulint: allow[unlocked-shared-write] guarded by caller's QueryManager._cond
        self._admitted_count = max(0, self._admitted_count - 1)

    def _grant(self, pool: Pool, h):
        pool.queue.popleft()
        pool.credit -= 1.0
        dev, host = h.estimate
        self._admitted_dev += int(dev)
        self._admitted_host += int(host)
        self._admitted_count += 1
        return h

    def _live_head(self, pool: Pool):
        """FIFO head of the pool, dropping dead (cancelled/expired)
        entries — their waiter threads finalize them."""
        while pool.queue:
            h = pool.queue[0]
            if h.token.cancelled():
                pool.queue.popleft()
                continue
            return h
        return None

    def next_ready(self):
        """Pick the next admissible query, or None. FIFO mode: global
        submission order. Fair mode: deficit round robin over pools."""
        contending = [p for p in self.pools.values()
                      if self._live_head(p) is not None]
        if not contending:
            return None
        if self.mode == "fifo":
            pool = min(contending, key=lambda p: p.queue[0]._seq)
            h = pool.queue[0]
            return self._grant(pool, h) if self._fits(h) else None
        # deficit round robin: recharge when no contending pool has
        # credit, then grant from the most-credited pool whose head fits
        if all(p.credit < 1.0 for p in contending):
            for p in contending:
                p.credit += p.weight
        for p in sorted(contending,
                        key=lambda p: (-p.credit, p.queue[0]._seq)):
            if p.credit < 1.0:
                continue
            h = p.queue[0]
            if self._fits(h):
                return self._grant(p, h)
        return None


# -- plan-derived memory estimate ---------------------------------------
def estimate_plan_memory(plan, conf=None) -> Tuple[int, int]:
    """(device_bytes, host_bytes) admission estimate for a LOGICAL plan:
    every scan leaf contributes its estimated materialized size and
    every join's build side (right child) counts again for the resident
    hash build — the same audited row/width numbers the planner's
    broadcast decision uses (plan/planner.py _estimate_bytes). Host
    estimate is half the device total (shuffle assembly + D2H staging
    ride host buffers but stream). Unknowable plans estimate 0 and are
    bounded only by the running-query cap."""
    if plan is None:
        return (0, 0)
    from ..plan.planner import _estimate_bytes
    dev = 0
    stack = [plan]
    seen = set()
    while stack:
        node = stack.pop()
        if id(node) in seen:
            continue
        seen.add(id(node))
        children = list(getattr(node, "children", []) or [])
        if not children:
            try:
                b = _estimate_bytes(node)
            except Exception:
                b = None
            if b:
                dev += int(b)
        else:
            if type(node).__name__ == "Join" and len(children) == 2:
                try:
                    b = _estimate_bytes(children[1])
                except Exception:
                    b = None
                if b:
                    dev += int(b)
            stack.extend(children)
    return (dev, dev // 2)
