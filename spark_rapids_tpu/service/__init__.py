"""Concurrent query service: admission control, fair scheduling,
cancellation & deadlines — the serving layer multiplexing independent
queries over one engine process (Thrift-Server / fair-scheduler analog;
see docs/service.md)."""
from .query_manager import (CancelToken, QueryCancelled, QueryHandle,
                            QueryManager, QueryTimedOut, QueryState,
                            current_query_id)
from .scheduler import FairScheduler, estimate_plan_memory
from .server import QueryServer

__all__ = ["CancelToken", "QueryCancelled", "QueryTimedOut", "QueryHandle",
           "QueryManager", "QueryState", "FairScheduler", "QueryServer",
           "estimate_plan_memory", "current_query_id"]
