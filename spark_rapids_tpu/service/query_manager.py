"""Per-query lifecycle: handles, cooperative cancellation, deadlines.

The multi-tenant serving layer the reference gets from Spark itself
(SparkContext job groups + the Thrift server's session/operation
lifecycle): every action becomes a `QueryHandle` walking
QUEUED -> ADMITTED -> RUNNING -> {FINISHED, FAILED, CANCELLED,
TIMED_OUT}, admission is arbitrated by the fair-share scheduler
(service/scheduler.py), and interruption is COOPERATIVE — a
`CancelToken` rides the query's `ExecContext` and every batch loop,
fragment dispatch, and semaphore wait polls it (`ctx.check_cancel()`,
enforced by the `ctx-cancel` lint rule), so a cancel lands at the next
batch boundary instead of killing threads mid-kernel.

Wall-clock deadlines (`sql.service.queryTimeoutSecs`) are just a
pre-armed cancel: the token carries an absolute monotonic deadline and
`check()` trips it exactly like an explicit `cancel()`, including while
the query is still queued.
"""
from __future__ import annotations

import threading
import time
from typing import Optional

__all__ = ["QueryState", "QueryCancelled", "QueryTimedOut", "CancelToken",
           "QueryHandle", "QueryManager", "current_query_id"]


class QueryState:
    QUEUED = "QUEUED"
    ADMITTED = "ADMITTED"
    RUNNING = "RUNNING"
    FINISHED = "FINISHED"
    FAILED = "FAILED"
    CANCELLED = "CANCELLED"
    TIMED_OUT = "TIMED_OUT"
    TERMINAL = frozenset({FINISHED, FAILED, CANCELLED, TIMED_OUT})


class QueryCancelled(RuntimeError):
    """Raised at a cooperative checkpoint after CancelToken.cancel()."""

    def __init__(self, query_id: str = "?", reason: str = "cancelled"):
        super().__init__(f"query {query_id} {reason}")
        self.query_id = query_id
        self.reason = reason


class QueryTimedOut(QueryCancelled):
    """The query's wall-clock deadline passed (queue time included)."""

    def __init__(self, query_id: str = "?", timeout_secs: float = 0.0):
        super().__init__(query_id,
                         f"exceeded deadline ({timeout_secs:g}s)")
        self.timeout_secs = timeout_secs


class CancelToken:
    """Cheap cooperative interruption flag + optional deadline.

    `check()` is called per batch in hot loops, so the fast path is one
    attribute read; the deadline compare only runs while a deadline is
    armed."""

    __slots__ = ("query_id", "deadline", "timeout_secs", "_cancelled",
                 "_reason")

    def __init__(self, query_id: str = "?",
                 timeout_secs: Optional[float] = None):
        self.query_id = query_id
        self.timeout_secs = timeout_secs or 0.0
        self.deadline = (time.monotonic() + timeout_secs
                         if timeout_secs else None)
        self._cancelled = False
        self._reason = "cancelled"

    def cancel(self, reason: str = "cancelled"):
        self._reason = reason
        # tpulint: allow[unlocked-shared-write] monotonic flag set before read by design: check() runs per batch and must stay one attr read
        self._cancelled = True

    def cancelled(self) -> bool:
        if self._cancelled:
            return True
        if self.deadline is not None and time.monotonic() > self.deadline:
            return True
        return False

    def check(self):
        """Raise QueryCancelled/QueryTimedOut when tripped; else no-op."""
        if self._cancelled:
            raise QueryCancelled(self.query_id, self._reason)
        if self.deadline is not None and time.monotonic() > self.deadline:
            e = QueryTimedOut(self.query_id, self.timeout_secs)
            # a deadline kill is where PR 8's deadlocks used to surface
            # as bare timeouts: attach the all-threads held-resource
            # dump so the exception (and event log) says WHO was stuck,
            # plus the resource ledger's outstanding-holders table (who
            # still holds leases/permits/handles, on which thread)
            from ..runtime import ledger, lockdep
            lockdep.attach_dump(e)
            ledger.attach_dump(e)
            raise e


class QueryHandle:
    """One submitted query: identity, lifecycle state, result rendezvous."""

    def __init__(self, query_id: str, pool: str, token: CancelToken,
                 action: str = "", estimate=(0, 0)):
        self.query_id = query_id
        self.pool = pool
        self.token = token
        self.action = action
        # (device_bytes, host_bytes) admission estimate from the plan
        self.estimate = estimate
        self.state = QueryState.QUEUED
        self.submitted_at = time.monotonic()
        self.admitted_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self.error: Optional[BaseException] = None
        self._result = None
        self._done = threading.Event()
        self._admitted = threading.Event()
        # scheduler bookkeeping: FIFO sequence within the pool
        self._seq = 0
        self._manager: Optional["QueryManager"] = None

    # -- caller surface -------------------------------------------------
    @property
    def queue_wait_ms(self) -> float:
        """Milliseconds spent QUEUED before admission (or until now /
        until death-in-queue)."""
        end = self.admitted_at
        if end is None:
            end = self.finished_at if self.finished_at is not None \
                else time.monotonic()
        return max(0.0, (end - self.submitted_at) * 1e3)

    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    def result(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError(f"query {self.query_id} still "
                               f"{self.state} after {timeout}s")
        if self.error is not None:
            raise self.error
        return self._result

    def cancel(self, reason: str = "cancelled") -> bool:
        mgr = self._manager
        if mgr is not None:
            return mgr.cancel(self, reason)
        self.token.cancel(reason)
        return True

    def status(self) -> dict:
        return {"query_id": self.query_id, "pool": self.pool,
                "state": self.state, "action": self.action,
                "queue_wait_ms": round(self.queue_wait_ms, 3),
                "error": (f"{type(self.error).__name__}: {self.error}"
                          if self.error is not None else None)}

    def __repr__(self):
        return f"QueryHandle({self.query_id}, {self.state})"


# query-id attribution for memory managers: reserve()/release() read
# this to tag reservations without threading a ctx through every call
# site (see memory/diagnostics.py query attribution)
_TLS = threading.local()


def current_query_id() -> Optional[str]:
    return getattr(_TLS, "query_id", None)


class _query_scope:
    """Tags the dynamic extent of a query's execution on this thread."""

    def __init__(self, query_id: str):
        self.query_id = query_id

    def __enter__(self):
        self._prev = getattr(_TLS, "query_id", None)
        _TLS.query_id = self.query_id
        return self

    def __exit__(self, *exc):
        _TLS.query_id = self._prev
        return False


class QueryManager:
    """Admission + lifecycle arbiter for one engine process.

    Synchronous actions (`DataFrame.to_arrow` etc.) run on the CALLER's
    thread: `open_query()` blocks until the scheduler grants admission,
    the caller executes, then `close_query()` releases the grant. Async
    submissions (`submit()`, used by the gateway and the throughput
    bench) get a thread that walks the same path. Either way the
    scheduler fully decides who runs: grants are handed out in `_pump()`
    under one lock whenever a slot or admitted memory frees up."""

    def __init__(self, conf=None):
        from ..config import (SERVICE_MAX_CONCURRENT, TpuConf)
        self.conf = conf or TpuConf()
        from .scheduler import FairScheduler
        from ..runtime import lockdep
        self._lock = lockdep.lock("QueryManager._lock")
        self._cond = threading.Condition(self._lock)
        self.scheduler = FairScheduler(self.conf)
        self.max_concurrent = max(1, int(
            self.conf.get(SERVICE_MAX_CONCURRENT)))
        self._running = 0
        self._seq = 0
        self._queries = {}  # query_id -> handle (bounded: pruned on close)
        self.stats = {"submitted": 0, "admitted": 0, "finished": 0,
                      "failed": 0, "cancelled": 0, "timed_out": 0,
                      "queued_peak": 0, "cache_fast_path": 0}
        # live-telemetry pull gauges: sampled at scrape time, so the
        # admission path itself carries zero instrumentation cost
        try:
            from ..profiler import telemetry
            telemetry.register_gauge_fn(
                "service",
                lambda: {"running": self._running,
                         "queued": self.scheduler.queued_count()})
        except Exception:
            pass

    # -- submission -----------------------------------------------------
    def _new_handle(self, plan=None, conf=None, action: str = "",
                    pool: Optional[str] = None,
                    timeout: Optional[float] = None,
                    estimate=None) -> QueryHandle:
        from ..config import SERVICE_POOL, SERVICE_QUERY_TIMEOUT_SECS
        from ..profiler.event_log import next_query_id
        conf = conf or self.conf
        if timeout is None:
            timeout = float(conf.get(SERVICE_QUERY_TIMEOUT_SECS)) or None
        if pool is None:
            pool = str(conf.get(SERVICE_POOL))
        qid = next_query_id()
        if estimate is None:
            from .scheduler import estimate_plan_memory
            estimate = estimate_plan_memory(plan, conf)
        h = QueryHandle(qid, pool, CancelToken(qid, timeout),
                        action=action, estimate=estimate)
        h._manager = self
        return h

    def open_query(self, plan=None, conf=None, action: str = "",
                   pool: Optional[str] = None,
                   timeout: Optional[float] = None,
                   estimate=None) -> QueryHandle:
        """Enqueue and BLOCK until admitted. Returns the handle in
        RUNNING state; the caller must pair with close_query(). Raises
        QueryCancelled/QueryTimedOut when the query dies in the queue."""
        h = self._new_handle(plan, conf, action, pool, timeout, estimate)
        self._enqueue(h)
        self._await_admission(h)
        return h

    def submit(self, fn, plan=None, conf=None, action: str = "",
               pool: Optional[str] = None,
               timeout: Optional[float] = None,
               estimate=None) -> QueryHandle:
        """Async submission: `fn(handle)` runs on a service thread once
        admitted; the result/exception lands on the returned handle."""
        h = self._new_handle(plan, conf, action, pool, timeout, estimate)
        self._enqueue(h)

        def _worker():
            try:
                self._await_admission(h)
            except QueryCancelled:
                return  # closed out by the queue sweep already
            try:
                out = fn(h)
            except BaseException as e:  # noqa: BLE001 — recorded on handle
                self.close_query(h, error=e)
            else:
                self.close_query(h, result=out)

        t = threading.Thread(target=_worker, daemon=True,
                             name=f"tpu-svc-query-{h.query_id}")
        t.start()
        return h

    def _enqueue(self, h: QueryHandle):
        with self._cond:
            self._seq += 1
            h._seq = self._seq
            self._queries[h.query_id] = h
            self.scheduler.offer(h)
            self.stats["submitted"] += 1
            self.stats["queued_peak"] = max(self.stats["queued_peak"],
                                            self.scheduler.queued_count())
            self._pump_locked()

    def _await_admission(self, h: QueryHandle):
        """Block until the scheduler grants this handle (marking it
        RUNNING) or its token trips in the queue."""
        while True:
            if h._admitted.wait(timeout=0.05):
                with self._cond:
                    h.state = QueryState.RUNNING
                return
            if h.token.cancelled():
                with self._cond:
                    if h._admitted.is_set():
                        h.state = QueryState.RUNNING
                        return
                    self.scheduler.remove(h)
                try:
                    h.token.check()
                    raise QueryCancelled(h.query_id)  # pragma: no cover
                except QueryCancelled as e:
                    self._finalize(h, error=e)
                    raise

    def fast_path(self, plan=None, conf=None, action: str = "",
                  pool: Optional[str] = None, result=None) -> QueryHandle:
        """Answer a query from the result cache WITHOUT consuming an
        admission slot: no enqueue, no scheduler offer, no wait — the
        whole point of the cache fast path is that a hit must not sit
        behind admitted queries. Still metered: the handle counts in
        submitted/finished plus the cache_fast_path counter, and the
        caller still event-logs it (result_cache record)."""
        h = self._new_handle(plan, conf, action, pool, None,
                             estimate=(0, 0))
        with self._cond:
            self._seq += 1
            h._seq = self._seq
            self.stats["submitted"] += 1
            self.stats["cache_fast_path"] += 1
        h.admitted_at = h.submitted_at        # zero queue wait
        self._finalize(h, result=result)      # admitted=False: no slot
        return h

    # -- completion -----------------------------------------------------
    def close_query(self, h: QueryHandle, result=None, error=None):
        """Release the admission grant and publish the outcome."""
        self._finalize(h, result=result, error=error, admitted=True)

    def _finalize(self, h: QueryHandle, result=None, error=None,
                  admitted: bool = False):
        with self._cond:
            if h.state in QueryState.TERMINAL:
                return
            h.finished_at = time.monotonic()
            if error is None:
                h.state = QueryState.FINISHED
                self.stats["finished"] += 1
            elif isinstance(error, QueryTimedOut):
                h.state = QueryState.TIMED_OUT
                self.stats["timed_out"] += 1
            elif isinstance(error, QueryCancelled):
                h.state = QueryState.CANCELLED
                self.stats["cancelled"] += 1
            else:
                h.state = QueryState.FAILED
                self.stats["failed"] += 1
            h.error = error
            h._result = result
            if admitted:
                self._running -= 1
                self.scheduler.release(h)
            self._queries.pop(h.query_id, None)
            self._pump_locked()
            self._cond.notify_all()
        # live telemetry: latency by terminal state + queue wait (the
        # event log is per-query and post-hoc; the registry is what the
        # gateway's `metrics` verb scrapes while the service runs)
        try:
            from ..config import TELEMETRY_ENABLED
            if self.conf.get(TELEMETRY_ENABLED):
                from ..profiler import telemetry
                st_ = h.state.lower()
                telemetry.counter(f"queries_{st_}").inc()
                telemetry.histogram("queue_wait_ms").observe(
                    h.queue_wait_ms)
                if h.finished_at is not None:
                    telemetry.histogram(
                        f"query_latency_ms_{st_}").observe(
                        (h.finished_at - h.submitted_at) * 1e3)
        except Exception:
            pass
        # drop the query's memory-attribution record (bounded bookkeeping)
        try:
            from ..memory.diagnostics import reset_query_attribution
            reset_query_attribution(h.query_id)
        except Exception:
            pass
        h._done.set()
        # resource-ledger balance witness: EVERY terminal state —
        # FINISHED, CANCELLED, TIMED_OUT alike — must leave the query's
        # owner-scoped resources (leases, permits, ride slots) balanced.
        # A clean finish with a leak raises to the caller; on an error
        # path the finding is recorded but must not mask the original
        # error.
        from ..runtime import ledger
        try:
            ledger.note_query_end(h.query_id, h.state)
        except ledger.ResourceLeakError:
            if error is None:
                raise

    # -- cancellation ---------------------------------------------------
    def cancel(self, handle_or_id, reason: str = "cancelled") -> bool:
        """Cancel by handle or query_id. Queued queries die immediately;
        running queries get their token tripped and die at the next
        cooperative checkpoint."""
        h = handle_or_id
        if isinstance(handle_or_id, str):
            with self._lock:
                h = self._queries.get(handle_or_id)
            if h is None:
                return False
        if h.state in QueryState.TERMINAL:
            return False
        h.token.cancel(reason)
        with self._cond:
            queued = h.state == QueryState.QUEUED and \
                not h._admitted.is_set()
            if queued:
                self.scheduler.remove(h)
        if queued:
            self._finalize(h, error=QueryCancelled(h.query_id, reason))
        return True

    def get(self, query_id: str) -> Optional[QueryHandle]:
        with self._lock:
            return self._queries.get(query_id)

    # -- scheduling pump ------------------------------------------------
    def _pump_locked(self):
        """Grant admission while slots and admitted-memory budget allow
        (called under self._lock whenever the picture changes)."""
        while self._running < self.max_concurrent:
            # sweep queued queries whose deadline already passed: their
            # waiter thread will observe the tripped token and finalize
            h = self.scheduler.next_ready()
            if h is None:
                break
            self._running += 1
            h.admitted_at = time.monotonic()
            h.state = QueryState.ADMITTED
            self.stats["admitted"] += 1
            h._admitted.set()

    def snapshot(self) -> dict:
        with self._lock:
            out = dict(self.stats)
            out["running"] = self._running
            out["queued"] = self.scheduler.queued_count()
            return out
