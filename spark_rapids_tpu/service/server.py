"""JSON-lines socket gateway: the Thrift-Server analog.

Multiplexes concurrent client sessions onto ONE engine process: each
connection sends newline-delimited JSON requests and reads one JSON
response line per request. Queries run asynchronously through the
session's QueryManager (submit returns a `query_id` immediately);
clients poll status, page through the columnar result, or cancel.

Wire protocol (see docs/service.md):

    {"op": "submit", "sql": "...", "pool": "etl", "timeout_secs": 30}
        -> {"ok": true, "query_id": "query-123-0"}
    {"op": "status", "query_id": "..."}
        -> {"ok": true, "state": "RUNNING", "queue_wait_ms": 1.2, ...}
    {"op": "fetch", "query_id": "...", "page": 0, "page_rows": 4096}
        -> {"ok": true, "columns": {...}, "num_rows": N, "last": false}
    {"op": "cancel", "query_id": "..."}  -> {"ok": true, "cancelled": true}
    {"op": "ping"}                       -> {"ok": true}
    {"op": "metrics"}                    -> {"ok": true, "metrics": {...}}
    {"op": "metrics", "format": "prometheus"} -> {"ok": true, "text": "..."}

The `metrics` verb scrapes the live telemetry registry
(profiler/telemetry.py): process-wide counters, pull gauges and
log-bucket latency histograms (p50/p95/p99), readable WHILE queries
run — the surface a fleet router polls. `format: "prometheus"` returns
the standard text exposition instead of JSON.

Fleet verbs (live only when this process joined a fleet —
spark_rapids_tpu/fleet/, docs/fleet.md):

    {"op": "route", "sql": "...", "tenant": "t1"}
        -> {"ok": true, "peer_id": "...", "host": ..., "port": ...,
            "sticky": true, "lease": "..."}
    {"op": "route_done", "lease": "..."}  -> {"ok": true}
    {"op": "fleet"}  -> {"ok": true, "peer_id": ..., "peers": [...],
                         "stats": {...}}

`route` answers WHERE to submit (the fingerprint-sticky rendezvous
choice, admission-checked); the client then submits to that peer's
gateway. Any member's gateway answers `route` identically — the
rendezvous hash needs no shared state.

Result pages are COLUMNAR ({name: [values...]}) — the arrow batches a
Thrift client would receive, JSON-encoded for transport neutrality.
"""
from __future__ import annotations

import json
import socket
import threading
from typing import Optional

__all__ = ["QueryServer"]


def _json_value(v):
    """JSON-safe scalar: arrow fetches yield decimals/dates/datetimes."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    return str(v)


class QueryServer:
    def __init__(self, session, host: str = "127.0.0.1", port: int = 0):
        self.session = session
        self.host = host
        self.port = port
        self._sock: Optional[socket.socket] = None
        self._threads = []
        self._stop = threading.Event()
        # query_id -> (handle, result holder); results stay fetchable
        # after the handle leaves the manager's live table
        self._results = {}
        self._lock = threading.Lock()
        self._router = None       # built on first route (fleet only)

    # -- lifecycle ------------------------------------------------------
    def start(self):
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self.host, self.port))
        s.listen(16)
        self.host, self.port = s.getsockname()
        self._sock = s
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="tpu-svc-gateway-accept")
        t.start()
        self._threads.append(t)
        return self.host, self.port

    @property
    def address(self):
        return self.host, self.port

    def close(self):
        self._stop.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        with self._lock:
            for h, _ in self._results.values():
                if not h.done():
                    h.cancel("gateway shutdown")
            self._results.clear()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- connection handling --------------------------------------------
    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True, name="tpu-svc-conn")
            t.start()

    def _member(self):
        """This gateway's fleet member (None outside a fleet)."""
        return getattr(self.session, "_fleet_member", None)

    def _serve_conn(self, conn: socket.socket):
        # bind this connection's work to the session's fleet member:
        # in-process multi-member tests run several gateways in one
        # interpreter, and a submit through gateway B must consult and
        # publish as member B
        member = self._member()
        if member is not None:
            from ..fleet import context as fleet_context
            with fleet_context.scoped(member):
                self._conn_loop(conn)
        else:
            self._conn_loop(conn)

    def _conn_loop(self, conn: socket.socket):
        with conn:
            rfile = conn.makefile("r", encoding="utf-8")
            wfile = conn.makefile("w", encoding="utf-8")
            for line in rfile:
                line = line.strip()
                if not line:
                    continue
                try:
                    req = json.loads(line)
                    resp = self._handle(req)
                except Exception as e:  # noqa: BLE001 — wire boundary
                    resp = {"ok": False,
                            "error": f"{type(e).__name__}: {e}"}
                try:
                    wfile.write(json.dumps(resp) + "\n")
                    wfile.flush()
                except OSError:
                    return

    # -- request dispatch -----------------------------------------------
    def _handle(self, req: dict) -> dict:
        op = req.get("op")
        if op == "ping":
            return {"ok": True, "stats":
                    self.session.query_manager().snapshot()}
        if op == "submit":
            return self._submit(req)
        if op == "status":
            return self._status(req)
        if op == "fetch":
            return self._fetch(req)
        if op == "cancel":
            return self._cancel(req)
        if op == "metrics":
            return self._metrics(req)
        if op == "route":
            return self._route(req)
        if op == "route_done":
            return self._route_done(req)
        if op == "fleet":
            return self._fleet_info(req)
        return {"ok": False, "error": f"unknown op: {op!r}"}

    # -- fleet verbs ----------------------------------------------------
    def _get_router(self):
        member = self._member()
        if member is None:
            return None
        with self._lock:
            if self._router is None:
                from ..fleet.router import Router
                self._router = Router(member)
            return self._router

    def _route(self, req: dict) -> dict:
        router = self._get_router()
        if router is None:
            return {"ok": False, "error": "not a fleet member"}
        from ..fleet.router import RouteRejected
        from ..runtime.program_cache import expr_fp
        plan_fp = expr_fp(self.session.sql(req["sql"])._plan)
        try:
            out = router.route(plan_fp,
                               tenant=str(req.get("tenant", "default")))
        except RouteRejected as e:
            return {"ok": False, "rejected": True, "error": e.reason,
                    "tenant": e.tenant}
        out["ok"] = True
        return out

    def _route_done(self, req: dict) -> dict:
        router = self._get_router()
        if router is None:
            return {"ok": False, "error": "not a fleet member"}
        return {"ok": True,
                "released": router.done(str(req.get("lease", "")))}

    def _fleet_info(self, req: dict) -> dict:
        member = self._member()
        if member is None:
            return {"ok": False, "error": "not a fleet member"}
        out = {"ok": True, "peer_id": member.peer_id,
               "peers": [p.to_dict() for p in
                         member.peers(include_self=True)],
               "stats": member.snapshot()}
        if self._router is not None:
            out["router"] = self._router.stats()
        return out

    def _metrics(self, req: dict) -> dict:
        from ..config import TELEMETRY_ENABLED
        if not self.session.conf.get(TELEMETRY_ENABLED):
            return {"ok": False, "error": "telemetry disabled "
                    "(spark.rapids.tpu.sql.telemetry.enabled=false)"}
        from ..profiler import telemetry
        if req.get("format") == "prometheus":
            return {"ok": True, "text": telemetry.render_prometheus()}
        return {"ok": True, "metrics": telemetry.snapshot()}

    def _submit(self, req: dict) -> dict:
        df = self.session.sql(req["sql"])
        handle = df.submit(pool=req.get("pool"),
                           timeout=req.get("timeout_secs"))
        with self._lock:
            self._results[handle.query_id] = (handle, None)
        return {"ok": True, "query_id": handle.query_id}

    def _entry(self, req: dict):
        qid = req.get("query_id", "")
        with self._lock:
            return qid, self._results.get(qid)

    def _status(self, req: dict) -> dict:
        qid, ent = self._entry(req)
        if ent is None:
            return {"ok": False, "error": f"unknown query_id: {qid!r}"}
        out = {"ok": True}
        out.update(ent[0].status())
        return out

    def _fetch(self, req: dict) -> dict:
        qid, ent = self._entry(req)
        if ent is None:
            return {"ok": False, "error": f"unknown query_id: {qid!r}"}
        handle = ent[0]
        if not handle.done():
            return {"ok": False, "pending": True,
                    "state": handle.state}
        try:
            table = handle.result()
        except BaseException as e:  # noqa: BLE001 — reported to client
            return {"ok": False, "state": handle.state,
                    "error": f"{type(e).__name__}: {e}"}
        page = max(0, int(req.get("page", 0)))
        page_rows = max(1, int(req.get("page_rows", 4096)))
        sliced = table.slice(page * page_rows, page_rows)
        cols = {name: [_json_value(v) for v in
                       sliced.column(i).to_pylist()]
                for i, name in enumerate(table.column_names)}
        return {"ok": True, "columns": cols,
                "num_rows": sliced.num_rows,
                "total_rows": table.num_rows,
                "last": (page + 1) * page_rows >= table.num_rows}

    def _cancel(self, req: dict) -> dict:
        qid, ent = self._entry(req)
        if ent is None:
            return {"ok": False, "error": f"unknown query_id: {qid!r}"}
        return {"ok": True, "cancelled": ent[0].cancel()}
