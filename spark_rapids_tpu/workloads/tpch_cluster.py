"""TPC-H Q3 as a distributed two-stage query (cluster/query.py).

The multi-operator distributed benchmark shape the VERDICT asks for:
each executor's MAP fragment runs scan -> filter -> join -> join ->
partial grouped aggregation over its lineitem split (customer/orders are
read in full on every executor — the broadcast-side model, exactly like
Spark shipping broadcast tables to every node); the shuffle moves
partial (group, revenue) rows as Arrow-IPC frames; REDUCE fragments
re-aggregate (sum of partial sums is exact for decimal sums) and emit a
per-bucket top-10; the driver's FINAL fragment merges bucket top-10s.

All functions are module-level so the cluster RPC can pickle them by
reference.
"""
from __future__ import annotations

import decimal

from .. import functions as F
from ..expr.expressions import col, lit

_CUT = 9204  # day("1995-03-15")


def _sorted_top10(df):
    from ..plan.logical import Sort, SortOrder
    from ..session import DataFrame
    return DataFrame(df._session, Sort(df._plan, [
        SortOrder(col("revenue"), ascending=False),
        SortOrder(col("o_orderdate"), ascending=True)])).limit(10)


def q3_map(s, split):
    """split: {"lineitem": path(s) of this executor's slice,
    "customer": full path(s), "orders": full path(s)}."""
    d = decimal.Decimal
    li = s.read.parquet(*_as_list(split["lineitem"]))
    cust = s.read.parquet(*_as_list(split["customer"]))
    orders = s.read.parquet(*_as_list(split["orders"]))
    rev = col("l_extendedprice") * (lit(d("1")) - col("l_discount"))
    return (cust.filter(col("c_mktsegment") == lit("BUILDING"))
            .join(orders.with_column("c_custkey", col("o_custkey")),
                  on=["c_custkey"], how="inner")
            .filter(col("o_orderdate") < _CUT)
            .with_column("l_orderkey", col("o_orderkey"))
            .join(li, on=["l_orderkey"], how="inner")
            .filter(col("l_shipdate") > _CUT)
            .group_by("l_orderkey", "o_orderdate", "o_shippriority")
            .agg(F.sum(rev).alias("revenue")))


def q3_reduce(s, df):
    """Per-bucket final aggregation + local top-10."""
    return _sorted_top10(
        df.group_by("l_orderkey", "o_orderdate", "o_shippriority")
        .agg(F.sum(col("revenue")).alias("revenue")))


def q3_final(s, df):
    """Driver-side merge of the buckets' top-10s."""
    return _sorted_top10(df)


def q6_map(s, split):
    """TPC-H Q6 map fragment: filter + partial revenue sum over this
    executor's lineitem split. The single constant group key makes the
    shuffle a 1-bucket partial-aggregate merge — the smallest
    distributed shape, which is why the chaos smoke uses it alongside
    Q3."""
    d = decimal.Decimal
    li = s.read.parquet(*_as_list(split["lineitem"]))
    return (li.filter((col("l_shipdate") >= 8766)
                      & (col("l_shipdate") < 9131)
                      & (col("l_discount") >= lit(d("0.05")))
                      & (col("l_discount") <= lit(d("0.07")))
                      & (col("l_quantity") < lit(d("24"))))
            .with_column("g", lit(0))
            .group_by("g")
            .agg(F.sum(col("l_extendedprice") * col("l_discount"))
                 .alias("revenue")))


def q6_reduce(s, df):
    """Merge the mappers' partial sums (sum of partial decimal sums is
    exact) and drop the synthetic group key."""
    return (df.group_by("g")
            .agg(F.sum(col("revenue")).alias("revenue"))
            .select(col("revenue")))


def _as_list(x):
    return list(x) if isinstance(x, (list, tuple)) else [x]
