"""TPC-H queries 2-22 over the engine's DataFrame API.

Every function takes a dict of DataFrames keyed by table name (the output
of ``TpuSession.create_dataframe`` over :func:`tpch.gen_all`) and returns
a DataFrame. Shapes follow the official TPC-H v3 query set; correlated
subqueries are decomposed into aggregate+join form (the standard
decorrelation — the reference runs these through Spark's own
decorrelation, e.g. RewriteCorrelatedScalarSubquery, so the physical
shape the engine sees is the same joins/aggregates produced here).

Date columns are int32 days-since-epoch in this workload; date literals
come from :func:`tpch.day`. Divisions cast to FLOAT64 first — the engine
keeps decimals exact through +,-,* and requires an explicit cast for
ratio-style outputs (matching docs/compatibility.md).

Reference parity targets: each query's docstring cites the reference's
integration test that runs the same query shape
(integration_tests/src/main/python/tpch_test.py in /root/reference).
"""
from __future__ import annotations

import decimal

from .. import functions as F
from ..columnar import dtypes as dt
from ..expr.expressions import col, lit
from .tpch import day

D = decimal.Decimal


def _sort(df, *orders):
    """Multi-key sort with per-key direction: orders are (expr, asc)."""
    from ..plan.logical import Sort, SortOrder
    from ..session import DataFrame
    sos = [SortOrder(e if not isinstance(e, str) else col(e), ascending=a)
           for e, a in orders]
    return DataFrame(df._session, Sort(df._plan, sos))


def _rename(df, **mapping):
    """Project all columns, renaming old→new per mapping (new=old)."""
    names = list(df.columns)
    inv = {old: new for new, old in mapping.items()}
    return df.select(*[col(c).alias(inv.get(c, c)) for c in names])


def _rev():
    return col("l_extendedprice") * (lit(D("1")) - col("l_discount"))


def q2(t, size: int = 15, type_suffix: str = "BRASS",
       region: str = "EUROPE"):
    """Minimum cost supplier (tpch_test.py::test_tpch_q2)."""
    eu_supp = (t["supplier"]
               .join(_rename(t["nation"], s_nationkey="n_nationkey"),
                     on=["s_nationkey"])
               .join(_rename(t["region"], n_regionkey="r_regionkey"),
                     on=["n_regionkey"])
               .filter(col("r_name") == lit(region)))
    ps_eu = (t["partsupp"]
             .join(_rename(eu_supp, ps_suppkey="s_suppkey"),
                   on=["ps_suppkey"]))
    min_cost = (ps_eu.group_by("ps_partkey")
                .agg(F.min(col("ps_supplycost")).alias("min_cost")))
    parts = t["part"].filter((col("p_size") == lit(size))
                             & F.endswith(col("p_type"), type_suffix))
    out = (parts
           .join(_rename(ps_eu, p_partkey="ps_partkey"), on=["p_partkey"])
           .join(min_cost.select(col("ps_partkey").alias("p_partkey"),
                                 col("min_cost")),
                 on=["p_partkey"])
           .filter(col("ps_supplycost") == col("min_cost"))
           .select("s_acctbal", "s_name", "n_name", "p_partkey",
                   "p_mfgr", "s_address", "s_phone", "s_comment"))
    return _sort(out, ("s_acctbal", False), ("n_name", True),
                 ("s_name", True), ("p_partkey", True)).limit(100)


def q4(t, d0: str = "1993-07-01", d1: str = "1993-10-01"):
    """Order priority checking: EXISTS decorrelated to a left-semi join
    (tpch_test.py::test_tpch_q4)."""
    late = t["lineitem"].filter(col("l_commitdate") < col("l_receiptdate"))
    out = (t["orders"]
           .filter((col("o_orderdate") >= day(d0))
                   & (col("o_orderdate") < day(d1)))
           .with_column("l_orderkey", col("o_orderkey"))
           .join(late, on=["l_orderkey"], how="left_semi")
           .group_by("o_orderpriority")
           .agg(F.count("*").alias("order_count")))
    return _sort(out, ("o_orderpriority", True))


def q5(t, region: str = "ASIA", d0: str = "1994-01-01",
       d1: str = "1995-01-01"):
    """Local supplier volume (tpch_test.py::test_tpch_q5)."""
    out = (t["customer"]
           .join(_rename(t["orders"], c_custkey="o_custkey"),
                 on=["c_custkey"])
           .filter((col("o_orderdate") >= day(d0))
                   & (col("o_orderdate") < day(d1)))
           .with_column("l_orderkey", col("o_orderkey"))
           .join(t["lineitem"], on=["l_orderkey"])
           # supplier must be in the customer's nation (spec join)
           .join(_rename(t["supplier"], l_suppkey="s_suppkey",
                         c_nationkey="s_nationkey"),
                 on=["l_suppkey", "c_nationkey"])
           .join(_rename(t["nation"], c_nationkey="n_nationkey"),
                 on=["c_nationkey"])
           .join(_rename(t["region"], n_regionkey="r_regionkey"),
                 on=["n_regionkey"])
           .filter(col("r_name") == lit(region))
           .group_by("n_name")
           .agg(F.sum(_rev()).alias("revenue")))
    return _sort(out, ("revenue", False))


def q7(t, n1: str = "FRANCE", n2: str = "GERMANY"):
    """Volume shipping between two nations
    (tpch_test.py::test_tpch_q7)."""
    y95, y96 = day("1995-01-01"), day("1996-12-31")
    supp_n = _rename(t["nation"], l_suppkey_nk="n_nationkey",
                     supp_nation="n_name").select(
        col("l_suppkey_nk"), col("supp_nation"))
    cust_n = _rename(t["nation"], c_nationkey="n_nationkey",
                     cust_nation="n_name").select(
        col("c_nationkey"), col("cust_nation"))
    df = (t["lineitem"]
          .filter((col("l_shipdate") >= y95) & (col("l_shipdate") <= y96))
          .join(_rename(t["supplier"], l_suppkey="s_suppkey",
                        l_suppkey_nk="s_nationkey")
                .select(col("l_suppkey"), col("l_suppkey_nk")),
                on=["l_suppkey"])
          .join(_rename(t["orders"], l_orderkey="o_orderkey")
                .select(col("l_orderkey"), col("o_custkey")),
                on=["l_orderkey"])
          .join(_rename(t["customer"], o_custkey="c_custkey")
                .select(col("o_custkey"), col("c_nationkey")),
                on=["o_custkey"])
          .join(supp_n, on=["l_suppkey_nk"])
          .join(cust_n, on=["c_nationkey"])
          .filter(((col("supp_nation") == lit(n1))
                   & (col("cust_nation") == lit(n2)))
                  | ((col("supp_nation") == lit(n2))
                     & (col("cust_nation") == lit(n1))))
          .with_column("l_year",
                       F.when(col("l_shipdate") <= day("1995-12-31"),
                              1995).otherwise(1996))
          .group_by("supp_nation", "cust_nation", "l_year")
          .agg(F.sum(_rev()).alias("revenue")))
    return _sort(df, ("supp_nation", True), ("cust_nation", True),
                 ("l_year", True))


def _order_year():
    """year(o_orderdate) over int32 days: 7-branch CASE, exact for the
    TPC-H date domain 1992..1998."""
    e = F.when(col("o_orderdate") <= day("1992-12-31"), 1992)
    for y in range(1993, 1998):
        e = e.when(col("o_orderdate") <= day(f"{y}-12-31"), y)
    return e.otherwise(1998)


def q8(t, nation: str = "BRAZIL", region: str = "AMERICA",
       ptype: str = "ECONOMY ANODIZED STEEL"):
    """National market share (tpch_test.py::test_tpch_q8)."""
    df = (t["part"].filter(col("p_type") == lit(ptype))
          .select(col("p_partkey").alias("l_partkey"))
          .join(t["lineitem"], on=["l_partkey"])
          .join(_rename(t["supplier"], l_suppkey="s_suppkey")
                .select(col("l_suppkey"), col("s_nationkey")),
                on=["l_suppkey"])
          .join(_rename(t["orders"], l_orderkey="o_orderkey")
                .select(col("l_orderkey"), col("o_custkey"),
                        col("o_orderdate")),
                on=["l_orderkey"])
          .filter((col("o_orderdate") >= day("1995-01-01"))
                  & (col("o_orderdate") <= day("1996-12-31")))
          .join(_rename(t["customer"], o_custkey="c_custkey")
                .select(col("o_custkey"), col("c_nationkey")),
                on=["o_custkey"])
          .join(_rename(t["nation"], c_nationkey="n_nationkey")
                .select(col("c_nationkey"), col("n_regionkey")),
                on=["c_nationkey"])
          .join(_rename(t["region"], n_regionkey="r_regionkey"),
                on=["n_regionkey"])
          .filter(col("r_name") == lit(region))
          .join(_rename(t["nation"], s_nationkey="n_nationkey",
                        supp_nation="n_name")
                .select(col("s_nationkey"), col("supp_nation")),
                on=["s_nationkey"])
          .with_column("o_year",
                       F.when(col("o_orderdate") <= day("1995-12-31"),
                              1995).otherwise(1996))
          .with_column("volume", _rev().cast(dt.FLOAT64))
          .with_column("nat_volume",
                       F.when(col("supp_nation") == lit(nation),
                              _rev().cast(dt.FLOAT64)).otherwise(0.0))
          .group_by("o_year")
          .agg(F.sum(col("nat_volume")).alias("nat"),
               F.sum(col("volume")).alias("total"))
          .select(col("o_year"),
                  (col("nat") / col("total")).alias("mkt_share")))
    return _sort(df, ("o_year", True))


def q9(t, word: str = "green"):
    """Product type profit measure (tpch_test.py::test_tpch_q9)."""
    amount = (_rev().cast(dt.FLOAT64)
              - (col("ps_supplycost") * col("l_quantity"))
              .cast(dt.FLOAT64))
    df = (t["part"].filter(F.contains(col("p_name"), word))
          .select(col("p_partkey").alias("l_partkey"))
          .join(t["lineitem"], on=["l_partkey"])
          .join(_rename(t["supplier"], l_suppkey="s_suppkey")
                .select(col("l_suppkey"), col("s_nationkey")),
                on=["l_suppkey"])
          .join(_rename(t["partsupp"], l_partkey="ps_partkey",
                        l_suppkey="ps_suppkey")
                .select(col("l_partkey"), col("l_suppkey"),
                        col("ps_supplycost")),
                on=["l_partkey", "l_suppkey"])
          .join(_rename(t["orders"], l_orderkey="o_orderkey")
                .select(col("l_orderkey"), col("o_orderdate")),
                on=["l_orderkey"])
          .join(_rename(t["nation"], s_nationkey="n_nationkey")
                .select(col("s_nationkey"), col("n_name")),
                on=["s_nationkey"])
          .with_column("o_year", _order_year())
          .with_column("amount", amount)
          .group_by("n_name", "o_year")
          .agg(F.sum(col("amount")).alias("sum_profit")))
    return _sort(df, ("n_name", True), ("o_year", False))


def q10(t, d0: str = "1993-10-01", d1: str = "1994-01-01"):
    """Returned item reporting (tpch_test.py::test_tpch_q10)."""
    df = (t["customer"]
          .join(_rename(t["orders"], c_custkey="o_custkey"),
                on=["c_custkey"])
          .filter((col("o_orderdate") >= day(d0))
                  & (col("o_orderdate") < day(d1)))
          .with_column("l_orderkey", col("o_orderkey"))
          .join(t["lineitem"], on=["l_orderkey"])
          .filter(col("l_returnflag") == lit("R"))
          .join(_rename(t["nation"], c_nationkey="n_nationkey"),
                on=["c_nationkey"])
          .group_by("c_custkey", "c_name", "c_acctbal", "c_phone",
                    "n_name", "c_address")
          .agg(F.sum(_rev()).alias("revenue")))
    return _sort(df, ("revenue", False), ("c_custkey", True)).limit(20)


def q11(t, nation: str = "GERMANY", fraction: float = 0.0001):
    """Important stock identification: scalar subquery decorrelated to a
    cross join against the 1-row total (tpch_test.py::test_tpch_q11)."""
    de_ps = (t["partsupp"]
             .join(_rename(t["supplier"], ps_suppkey="s_suppkey")
                   .select(col("ps_suppkey"), col("s_nationkey")),
                   on=["ps_suppkey"])
             .join(_rename(t["nation"], s_nationkey="n_nationkey"),
                   on=["s_nationkey"])
             .filter(col("n_name") == lit(nation))
             .with_column("value", (col("ps_supplycost")
                                    * col("ps_availqty"))
                          .cast(dt.FLOAT64)))
    per_part = (de_ps.group_by("ps_partkey")
                .agg(F.sum(col("value")).alias("part_value")))
    total = de_ps.agg(F.sum(col("value")).alias("total_value"))
    df = (per_part.join(total, how="cross")
          .filter(col("part_value") > col("total_value") * lit(fraction))
          .select(col("ps_partkey"), col("part_value")))
    return _sort(df, ("part_value", False), ("ps_partkey", True))


def q12(t, m1: str = "MAIL", m2: str = "SHIP", d0: str = "1994-01-01",
        d1: str = "1995-01-01"):
    """Shipping modes and order priority
    (tpch_test.py::test_tpch_q12)."""
    high = F.when(col("o_orderpriority").isin("1-URGENT", "2-HIGH"),
                  1).otherwise(0)
    low = F.when(col("o_orderpriority").isin("1-URGENT", "2-HIGH"),
                 0).otherwise(1)
    df = (t["orders"].with_column("l_orderkey", col("o_orderkey"))
          .join(t["lineitem"], on=["l_orderkey"])
          .filter(col("l_shipmode").isin(m1, m2)
                  & (col("l_commitdate") < col("l_receiptdate"))
                  & (col("l_shipdate") < col("l_commitdate"))
                  & (col("l_receiptdate") >= day(d0))
                  & (col("l_receiptdate") < day(d1)))
          .group_by("l_shipmode")
          .agg(F.sum(high).alias("high_line_count"),
               F.sum(low).alias("low_line_count")))
    return _sort(df, ("l_shipmode", True))


def q13(t, w1: str = "special", w2: str = "requests"):
    """Customer distribution: left join + NOT LIKE
    (tpch_test.py::test_tpch_q13)."""
    kept = t["orders"].filter(
        ~F.like(col("o_comment"), f"%{w1}%{w2}%"))
    per_cust = (t["customer"].select(col("c_custkey"))
                .with_column("o_custkey", col("c_custkey"))
                .join(kept.select(col("o_custkey"), col("o_orderkey")),
                      on=["o_custkey"], how="left")
                .group_by("c_custkey")
                .agg(F.count(col("o_orderkey")).alias("c_count")))
    df = (per_cust.group_by("c_count")
          .agg(F.count("*").alias("custdist")))
    return _sort(df, ("custdist", False), ("c_count", False))


def q14(t, d0: str = "1995-09-01", d1: str = "1995-10-01"):
    """Promotion effect (tpch_test.py::test_tpch_q14)."""
    promo = F.when(F.startswith(col("p_type"), "PROMO"),
                   _rev().cast(dt.FLOAT64)).otherwise(0.0)
    df = (t["lineitem"]
          .filter((col("l_shipdate") >= day(d0))
                  & (col("l_shipdate") < day(d1)))
          .join(_rename(t["part"], l_partkey="p_partkey")
                .select(col("l_partkey"), col("p_type")),
                on=["l_partkey"])
          .with_column("rev", _rev().cast(dt.FLOAT64))
          .with_column("promo_rev", promo)
          .agg(F.sum(col("promo_rev")).alias("p"),
               F.sum(col("rev")).alias("r"))
          .select((lit(100.0) * col("p") / col("r"))
                  .alias("promo_revenue")))
    return df


def q15(t, d0: str = "1996-01-01", d1: str = "1996-04-01"):
    """Top supplier: the revenue view + scalar max decorrelated to a
    cross join (tpch_test.py::test_tpch_q15)."""
    revenue = (t["lineitem"]
               .filter((col("l_shipdate") >= day(d0))
                       & (col("l_shipdate") < day(d1)))
               .with_column("r", _rev().cast(dt.FLOAT64))
               .group_by("l_suppkey")
               .agg(F.sum(col("r")).alias("total_revenue")))
    mx = revenue.agg(F.max(col("total_revenue")).alias("max_revenue"))
    df = (revenue.join(mx, how="cross")
          .filter(col("total_revenue") == col("max_revenue"))
          .join(_rename(t["supplier"], l_suppkey="s_suppkey"),
                on=["l_suppkey"])
          .select(col("l_suppkey").alias("s_suppkey"), col("s_name"),
                  col("s_address"), col("s_phone"),
                  col("total_revenue")))
    return _sort(df, ("s_suppkey", True))


def q16(t, brand: str = "Brand#45", tprefix: str = "MEDIUM POLISHED",
        sizes=(49, 14, 23, 45, 19, 3, 36, 9)):
    """Parts/supplier relationship: NOT IN decorrelated to a left-anti
    join (tpch_test.py::test_tpch_q16)."""
    bad_supp = (t["supplier"]
                .filter(F.like(col("s_comment"),
                               "%Customer%Complaints%"))
                .select(col("s_suppkey").alias("ps_suppkey")))
    df = (t["partsupp"]
          .join(bad_supp, on=["ps_suppkey"], how="left_anti")
          .join(_rename(t["part"], ps_partkey="p_partkey"),
                on=["ps_partkey"])
          .filter((col("p_brand") != lit(brand))
                  & ~F.startswith(col("p_type"), tprefix)
                  & col("p_size").isin(*sizes))
          .group_by("p_brand", "p_type", "p_size")
          .agg(F.countDistinct(col("ps_suppkey")).alias("supplier_cnt")))
    return _sort(df, ("supplier_cnt", False), ("p_brand", True),
                 ("p_type", True), ("p_size", True))


def q17(t, brand: str = "Brand#23", container: str = "MED BOX"):
    """Small-quantity-order revenue: correlated avg decorrelated to a
    grouped-agg join (tpch_test.py::test_tpch_q17)."""
    avg_qty = (t["lineitem"]
               .group_by("l_partkey")
               .agg(F.avg(col("l_quantity").cast(dt.FLOAT64))
                    .alias("avg_qty"))
               .select(col("l_partkey"),
                       (col("avg_qty") * 0.2).alias("qty_threshold")))
    df = (t["part"]
          .filter((col("p_brand") == lit(brand))
                  & (col("p_container") == lit(container)))
          .select(col("p_partkey").alias("l_partkey"))
          .join(t["lineitem"], on=["l_partkey"])
          .join(avg_qty, on=["l_partkey"])
          .filter(col("l_quantity").cast(dt.FLOAT64)
                  < col("qty_threshold"))
          .agg(F.sum(col("l_extendedprice").cast(dt.FLOAT64))
               .alias("total"))
          .select((col("total") / lit(7.0)).alias("avg_yearly")))
    return df


def q18(t, qty: int = 300):
    """Large volume customers: IN decorrelated to a left-semi join
    (tpch_test.py::test_tpch_q18)."""
    big = (t["lineitem"].group_by("l_orderkey")
           .agg(F.sum(col("l_quantity")).alias("sum_qty"))
           .filter(col("sum_qty") > lit(D(qty)))
           .select(col("l_orderkey").alias("o_orderkey")))
    df = (t["orders"]
          .join(big, on=["o_orderkey"], how="left_semi")
          .join(t["customer"].with_column("o_custkey", col("c_custkey")),
                on=["o_custkey"])
          .with_column("l_orderkey", col("o_orderkey"))
          .join(t["lineitem"].select(col("l_orderkey"),
                                     col("l_quantity")),
                on=["l_orderkey"])
          .group_by("c_name", "c_custkey", "o_orderkey", "o_orderdate",
                    "o_totalprice")
          .agg(F.sum(col("l_quantity")).alias("sum_qty")))
    return _sort(df, ("o_totalprice", False),
                 ("o_orderdate", True), ("o_orderkey", True)).limit(100)


def q19(t):
    """Discounted revenue: disjunctive join filters
    (tpch_test.py::test_tpch_q19). Shipmode pair adjusted to this
    datagen's vocabulary (spec text says 'AIR REG'; the mode list has
    'REG AIR')."""
    def branch(brand, containers, qlo, qhi, szhi):
        return ((col("p_brand") == lit(brand))
                & col("p_container").isin(*containers)
                & (col("l_quantity") >= lit(D(qlo)))
                & (col("l_quantity") <= lit(D(qhi)))
                & (col("p_size") >= 1) & (col("p_size") <= szhi))
    df = (t["lineitem"]
          .filter(col("l_shipmode").isin("AIR", "REG AIR")
                  & (col("l_shipinstruct") == lit("DELIVER IN PERSON")))
          .join(_rename(t["part"], l_partkey="p_partkey"),
                on=["l_partkey"])
          .filter(branch("Brand#12", ("SM CASE", "SM BOX", "SM PACK",
                                      "SM PKG"), 1, 11, 5)
                  | branch("Brand#23", ("MED BAG", "MED BOX", "MED PKG",
                                        "MED PACK"), 10, 20, 10)
                  | branch("Brand#34", ("LG CASE", "LG BOX", "LG PACK",
                                        "LG PKG"), 20, 30, 15))
          .agg(F.sum(_rev()).alias("revenue")))
    return df


def q20(t, word: str = "forest", nation: str = "CANADA",
        d0: str = "1994-01-01", d1: str = "1995-01-01"):
    """Potential part promotion: nested INs decorrelated to semi joins +
    a grouped-agg join (tpch_test.py::test_tpch_q20)."""
    forest_parts = (t["part"]
                    .filter(F.startswith(col("p_name"), word))
                    .select(col("p_partkey").alias("ps_partkey")))
    half_qty = (t["lineitem"]
                .filter((col("l_shipdate") >= day(d0))
                        & (col("l_shipdate") < day(d1)))
                .group_by("l_partkey", "l_suppkey")
                .agg(F.sum(col("l_quantity").cast(dt.FLOAT64))
                     .alias("sum_qty"))
                .select(col("l_partkey"), col("l_suppkey"),
                        (col("sum_qty") * 0.5).alias("half_qty")))
    qual_ps = (t["partsupp"]
               .join(forest_parts, on=["ps_partkey"], how="left_semi")
               .join(_rename(half_qty, ps_partkey="l_partkey",
                             ps_suppkey="l_suppkey"),
                     on=["ps_partkey", "ps_suppkey"])
               .filter(col("ps_availqty").cast(dt.FLOAT64)
                       > col("half_qty"))
               .select(col("ps_suppkey").alias("s_suppkey")).distinct())
    df = (t["supplier"]
          .join(qual_ps, on=["s_suppkey"], how="left_semi")
          .join(_rename(t["nation"], s_nationkey="n_nationkey"),
                on=["s_nationkey"])
          .filter(col("n_name") == lit(nation))
          .select(col("s_name"), col("s_address")))
    return _sort(df, ("s_name", True))


def q21(t, nation: str = "SAUDI ARABIA"):
    """Suppliers who kept orders waiting: the EXISTS/NOT-EXISTS pair
    decorrelated to per-order distinct-supplier counts
    (tpch_test.py::test_tpch_q21)."""
    li = t["lineitem"].select(col("l_orderkey"), col("l_suppkey"),
                              col("l_commitdate"), col("l_receiptdate"))
    late = li.filter(col("l_receiptdate") > col("l_commitdate"))
    per_order = (li.group_by("l_orderkey")
                 .agg(F.countDistinct(col("l_suppkey")).alias("n_supp")))
    late_per_order = (late.group_by("l_orderkey")
                      .agg(F.countDistinct(col("l_suppkey"))
                           .alias("n_late")))
    df = (late
          .join(_rename(t["orders"], l_orderkey="o_orderkey")
                .select(col("l_orderkey"), col("o_orderstatus")),
                on=["l_orderkey"])
          .filter(col("o_orderstatus") == lit("F"))
          .join(per_order, on=["l_orderkey"])
          .join(late_per_order, on=["l_orderkey"])
          # exists another supplier on the order; no OTHER late supplier
          .filter((col("n_supp") > 1) & (col("n_late") == 1))
          .join(_rename(t["supplier"], l_suppkey="s_suppkey"),
                on=["l_suppkey"])
          .join(_rename(t["nation"], s_nationkey="n_nationkey"),
                on=["s_nationkey"])
          .filter(col("n_name") == lit(nation))
          .group_by("s_name")
          .agg(F.count("*").alias("numwait")))
    return _sort(df, ("numwait", False), ("s_name", True)).limit(100)


def q22(t, codes=("13", "31", "23", "29", "30", "18", "17")):
    """Global sales opportunity: anti join + scalar-avg cross join
    (tpch_test.py::test_tpch_q22)."""
    cc = F.substring(col("c_phone"), 1, 2)
    cust = (t["customer"]
            .with_column("cntrycode", cc)
            .filter(col("cntrycode").isin(*codes)))
    avg_bal = (cust.filter(col("c_acctbal") > lit(D("0.00")))
               .agg(F.avg(col("c_acctbal").cast(dt.FLOAT64))
                    .alias("avg_bal")))
    df = (cust
          .with_column("o_custkey", col("c_custkey"))
          .join(t["orders"].select(col("o_custkey")).distinct(),
                on=["o_custkey"], how="left_anti")
          .join(avg_bal, how="cross")
          .filter(col("c_acctbal").cast(dt.FLOAT64) > col("avg_bal"))
          .group_by("cntrycode")
          .agg(F.count("*").alias("numcust"),
               F.sum(col("c_acctbal")).alias("totacctbal")))
    return _sort(df, ("cntrycode", True))
