"""TPC-H workload: schema, data generation, and query definitions.

The perf harness analog of the reference's datagen/ScaleTest
(reference: datagen/ScaleTest.md). Decimal columns use precisions that keep
the engine on the decimal64 (int64) path — exact fixed-point arithmetic
without f64 emulation on TPU.
"""
from __future__ import annotations

import numpy as np
import pyarrow as pa

import spark_rapids_tpu.functions as F
from spark_rapids_tpu.expr.expressions import col, lit

LINEITEM_ROWS_PER_SF = 6_001_215


def dec_from_unscaled(vals: np.ndarray, precision: int, scale: int):
    """Build a decimal128 array whose UNSCALED value is `vals` (a cast from
    int64 would rescale instead)."""
    n = len(vals)
    lo = vals.astype(np.int64)
    hi = np.where(lo < 0, np.int64(-1), np.int64(0))
    words = np.empty(2 * n, np.int64)
    words[0::2] = lo
    words[1::2] = hi
    return pa.Array.from_buffers(
        pa.decimal128(38, scale), n,
        [None, pa.py_buffer(words.tobytes())]).cast(
            pa.decimal128(precision, scale))


def day(s: str) -> int:
    """Date literal as int32 days-since-epoch (the engine's date model in
    this workload: TPC-H dates span 1992-01-01..1998-12-31 = 8036..10592)."""
    return int((np.datetime64(s) - np.datetime64("1970-01-01"))
               // np.timedelta64(1, "D"))


# spec vocabularies (TPC-H v3 clause 4.2.2.13 / 4.2.3)
SHIPMODES = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]
SHIPINSTRUCT = ["DELIVER IN PERSON", "COLLECT COD", "NONE",
                "TAKE BACK RETURN"]
ORDERPRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED",
                   "5-LOW"]
SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD", "MACHINERY"]
TYPE_S1 = ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
TYPE_S2 = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
TYPE_S3 = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
CONTAINER_S1 = ["SM", "MED", "LG", "JUMBO"]
CONTAINER_S2 = ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]
COLORS = ["almond", "antique", "aquamarine", "azure", "beige", "bisque",
          "black", "blanched", "blue", "blush", "brown", "burlywood",
          "burnished", "chartreuse", "chiffon", "chocolate", "coral",
          "cornflower", "cornsilk", "cream", "cyan", "dark", "deep",
          "dim", "dodger", "drab", "firebrick", "floral", "forest",
          "frosted", "gainsboro", "ghost", "goldenrod", "green", "grey",
          "honeydew", "hot", "hotpink", "indian", "ivory", "khaki",
          "lace", "lavender", "lawn", "lemon", "light", "lime", "linen",
          "magenta", "maroon", "medium", "metallic", "midnight", "mint",
          "misty", "moccasin", "navajo", "navy", "olive", "orange",
          "orchid", "pale", "papaya", "peach", "peru", "pink", "plum",
          "powder", "puff", "purple", "red", "rose", "rosy", "royal",
          "saddle", "salmon", "sandy", "seashell", "sienna", "sky",
          "slate", "smoke", "snow", "spring", "steel", "tan", "thistle",
          "tomato", "turquoise", "violet", "wheat", "white", "yellow"]
NATIONS = [  # (name, regionkey) — spec nation table clause 4.2.3
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1)]
REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]

PART_ROWS_PER_SF = 200_000
SUPPLIER_ROWS_PER_SF = 10_000


def _pick(rng, words, n):
    return np.array(words, dtype=object)[rng.integers(0, len(words), n)]


def gen_lineitem(sf: float = 0.1, seed: int = 0,
                 full: bool = False) -> pa.Table:
    n = int(LINEITEM_ROWS_PER_SF * sf)
    rng = np.random.default_rng(seed)
    qty = rng.integers(1, 51, n).astype(np.int64) * 100          # dec(12,2)
    price = rng.integers(90_000, 10_500_000, n).astype(np.int64)  # dec(12,2)
    disc = rng.integers(0, 11, n).astype(np.int64)                # dec(4,2)
    tax = rng.integers(0, 9, n).astype(np.int64)
    shipdate = rng.integers(8036, 10591, n).astype(np.int32)      # days
    rf = rng.integers(0, 3, n)
    ls = rng.integers(0, 2, n)
    returnflag = pa.array(np.array(["A", "N", "R"])[rf])
    linestatus = pa.array(np.array(["F", "O"])[ls])
    okey = rng.integers(0, max(n // 4, 1), n).astype(np.int64)
    cols = {
        "l_orderkey": pa.array(okey, pa.int64()),
        "l_quantity": dec_from_unscaled(qty, 12, 2),
        "l_extendedprice": dec_from_unscaled(price, 12, 2),
        "l_discount": dec_from_unscaled(disc, 4, 2),
        "l_tax": dec_from_unscaled(tax, 4, 2),
        "l_returnflag": returnflag,
        "l_linestatus": linestatus,
        "l_shipdate": pa.array(shipdate, pa.int32()),
    }
    if full:
        # independent stream: adding columns must not perturb the draws
        # above (bench numbers stay comparable round-over-round)
        r2 = np.random.default_rng(seed + 104729)
        npart = max(int(PART_ROWS_PER_SF * sf), 1)
        nsupp = max(int(SUPPLIER_ROWS_PER_SF * sf), 1)
        commit = shipdate + r2.integers(-30, 31, n).astype(np.int32)
        receipt = shipdate + r2.integers(1, 31, n).astype(np.int32)
        # (l_partkey, l_suppkey) drawn FROM partsupp's pairs (spec: each
        # part has 4 suppliers; lineitem references one of them), so
        # q9/q20's partsupp joins hit
        pk = r2.integers(0, npart, n)
        si = r2.integers(0, 4, n)
        sk = (pk * 4 + si * max(nsupp // 4, 1)) % nsupp
        cols.update({
            "l_partkey": pa.array(pk.astype(np.int64)),
            "l_suppkey": pa.array(sk.astype(np.int64)),
            "l_linenumber": pa.array(
                r2.integers(1, 8, n).astype(np.int32), pa.int32()),
            "l_commitdate": pa.array(commit, pa.int32()),
            "l_receiptdate": pa.array(receipt, pa.int32()),
            "l_shipinstruct": pa.array(_pick(r2, SHIPINSTRUCT, n),
                                       pa.string()),
            "l_shipmode": pa.array(_pick(r2, SHIPMODES, n), pa.string()),
        })
    return pa.table(cols)


def q6(df):
    """TPC-H Q6: forecasting revenue change (scan+filter+reduction)."""
    import decimal
    d = decimal.Decimal
    return (df.filter((col("l_shipdate") >= 8766) & (col("l_shipdate") < 9131)
                      & (col("l_discount") >= lit(d("0.05")))
                      & (col("l_discount") <= lit(d("0.07")))
                      & (col("l_quantity") < lit(d("24"))))
            .agg(F.sum(col("l_extendedprice") * col("l_discount"))
                 .alias("revenue")))


def q1(df):
    """TPC-H Q1: pricing summary report (grouped agg, 8 aggregates)."""
    import decimal
    d = decimal.Decimal
    disc_price = col("l_extendedprice") * (lit(d("1")) - col("l_discount"))
    charge = disc_price * (lit(d("1")) + col("l_tax"))
    return (df.filter(col("l_shipdate") <= 10471)
            .group_by("l_returnflag", "l_linestatus")
            .agg(F.sum("l_quantity").alias("sum_qty"),
                 F.sum("l_extendedprice").alias("sum_base_price"),
                 F.sum(disc_price).alias("sum_disc_price"),
                 F.sum(charge).alias("sum_charge"),
                 F.avg("l_quantity").alias("avg_qty"),
                 F.avg("l_extendedprice").alias("avg_price"),
                 F.avg("l_discount").alias("avg_disc"),
                 F.count("*").alias("count_order")))


def q6_numpy_baseline(ship, disc_unscaled, qty_unscaled, price_unscaled):
    """Vectorized single-core CPU reference over the raw unscaled arrays
    (the CPU-Spark stand-in for bench.py)."""
    m = ((ship >= 8766) & (ship < 9131)
         & (disc_unscaled >= 5) & (disc_unscaled <= 7)
         & (qty_unscaled < 2400))
    return int(np.sum(price_unscaled[m] * disc_unscaled[m]))


def q1_numpy_baseline(ship, rf, ls, qty, price, disc, tax):
    """Vectorized single-core Q1 reference: grouped sums via bincount over
    the 6 (returnflag, linestatus) combinations. rf/ls are small int codes."""
    m = ship <= 10471
    g = (rf * 2 + ls)[m]
    qty, price, disc, tax = qty[m], price[m], disc[m], tax[m]
    disc_price = price * (100 - disc)          # scale 4
    charge = disc_price * (100 + tax)          # scale 6
    out = {}
    out["sum_qty"] = np.bincount(g, qty, 6)
    out["sum_base_price"] = np.bincount(g, price, 6)
    out["sum_disc_price"] = np.bincount(g, disc_price, 6)
    out["sum_charge"] = np.bincount(g, charge.astype(np.float64), 6)
    out["count"] = np.bincount(g, minlength=6)
    return out


def q3_numpy_baseline(c_key, c_seg, o_okey, o_ckey, o_date, o_prio,
                      l_okey, l_ship, l_price, l_disc):
    """Vectorized single-core Q3 reference: semi-join via np.isin +
    dict-free grouped sum over order keys."""
    cust = c_key[c_seg == 1]                      # BUILDING code == 1
    om = (o_date < 9204) & np.isin(o_ckey, cust)
    okeys = o_okey[om]
    lm = (l_ship > 9204) & np.isin(l_okey, okeys)
    lk = l_okey[lm]
    rev = l_price[lm] * (100 - l_disc[lm])
    order = np.argsort(lk, kind="stable")
    lk_s, rev_s = lk[order], rev[order]
    starts = np.flatnonzero(np.r_[True, lk_s[1:] != lk_s[:-1]])
    sums = np.add.reduceat(rev_s, starts) if lk_s.size else np.array([])
    keys = lk_s[starts] if lk_s.size else np.array([], np.int64)
    top = np.argsort(-sums, kind="stable")[:10]
    return keys[top], sums[top]


ORDERS_ROWS_PER_SF = 1_500_000


def gen_orders(sf: float = 0.1, seed: int = 1,
               full: bool = False) -> pa.Table:
    n = int(ORDERS_ROWS_PER_SF * sf)
    rng = np.random.default_rng(seed)
    okey = np.arange(n, dtype=np.int64)
    ckey = rng.integers(0, max(n // 10, 1), n).astype(np.int64)
    odate = rng.integers(8036, 10591, n).astype(np.int32)
    seg = rng.integers(0, 5, n)
    total = rng.integers(100_000, 50_000_000, n).astype(np.int64)
    cols = {
        "o_orderkey": pa.array(okey),
        "o_custkey": pa.array(ckey),
        "o_orderdate": pa.array(odate, pa.int32()),
        "o_totalprice": dec_from_unscaled(total, 15, 2),
        "o_shippriority": pa.array(rng.integers(0, 2, n).astype(np.int32),
                                   pa.int32()),
    }
    if full:
        r2 = np.random.default_rng(seed + 104729)
        # spec clause 4.2.3: orders reference only custkeys NOT divisible
        # by 3, so a third of customers have no orders (q13/q22 depend on
        # this). Drawn from the r2 stream so the base (bench Q3) dataset
        # keeps its round-over-round draws.
        ncust = max(n // 10, 1)
        j = r2.integers(0, max(2 * ncust // 3, 1), n)
        cols["o_custkey"] = pa.array(
            (3 * (j // 2) + 1 + (j % 2)).astype(np.int64))
        status = np.array(["F", "O", "P"])[r2.integers(0, 3, n)]
        comments = _pick(r2, COLORS, n)
        # ~2% of comments carry the q13 exclusion pattern
        special = r2.random(n) < 0.02
        comments = np.where(
            special, comments + np.array([" special requests"], object),
            comments)
        cols.update({
            "o_orderstatus": pa.array(status, pa.string()),
            "o_orderpriority": pa.array(_pick(r2, ORDERPRIORITIES, n),
                                        pa.string()),
            "o_comment": pa.array(comments.astype(object), pa.string()),
        })
    return pa.table(cols)


def gen_customer(sf: float = 0.1, seed: int = 2,
                 full: bool = False) -> pa.Table:
    n = int(150_000 * sf)
    rng = np.random.default_rng(seed)
    segs = np.array(SEGMENTS)
    cols = {
        "c_custkey": pa.array(np.arange(n, dtype=np.int64)),
        "c_mktsegment": pa.array(segs[rng.integers(0, 5, n)]),
    }
    if full:
        r2 = np.random.default_rng(seed + 104729)
        nk = r2.integers(0, 25, n)
        # spec phone format: country code = 10 + nationkey
        phones = np.array([f"{10 + k}-{r2.integers(100,1000)}-"
                           f"{r2.integers(100,1000)}-{r2.integers(1000,10000)}"
                           for k in nk], dtype=object)
        acct = r2.integers(-99_999, 1_000_000, n).astype(np.int64)
        cols.update({
            "c_name": pa.array(
                np.array([f"Customer#{i:09d}" for i in range(n)], object),
                pa.string()),
            "c_address": pa.array(_pick(r2, COLORS, n), pa.string()),
            "c_nationkey": pa.array(nk.astype(np.int64)),
            "c_phone": pa.array(phones, pa.string()),
            "c_acctbal": dec_from_unscaled(acct, 12, 2),
        })
    return pa.table(cols)


def gen_part(sf: float = 0.1, seed: int = 3) -> pa.Table:
    n = max(int(PART_ROWS_PER_SF * sf), 1)
    rng = np.random.default_rng(seed)
    c1 = _pick(rng, COLORS, n)
    c2 = _pick(rng, COLORS, n)
    name = c1 + np.array([" "], object) + c2
    ptype = (_pick(rng, TYPE_S1, n) + np.array([" "], object)
             + _pick(rng, TYPE_S2, n) + np.array([" "], object)
             + _pick(rng, TYPE_S3, n))
    container = (_pick(rng, CONTAINER_S1, n) + np.array([" "], object)
                 + _pick(rng, CONTAINER_S2, n))
    brand = np.array([f"Brand#{i}{j}" for i, j in zip(
        rng.integers(1, 6, n), rng.integers(1, 6, n))], dtype=object)
    price = (90_000 + (np.arange(n) % 200_001) * 100
             + rng.integers(0, 100, n)).astype(np.int64)
    return pa.table({
        "p_partkey": pa.array(np.arange(n, dtype=np.int64)),
        "p_name": pa.array(name, pa.string()),
        "p_mfgr": pa.array(np.array(
            [f"Manufacturer#{i}" for i in rng.integers(1, 6, n)], object),
            pa.string()),
        "p_brand": pa.array(brand, pa.string()),
        "p_type": pa.array(ptype, pa.string()),
        "p_size": pa.array(rng.integers(1, 51, n).astype(np.int32),
                           pa.int32()),
        "p_container": pa.array(container, pa.string()),
        "p_retailprice": dec_from_unscaled(price, 12, 2),
    })


def gen_supplier(sf: float = 0.1, seed: int = 4) -> pa.Table:
    n = max(int(SUPPLIER_ROWS_PER_SF * sf), 1)
    rng = np.random.default_rng(seed)
    nk = rng.integers(0, 25, n)
    phones = np.array([f"{10 + k}-{rng.integers(100,1000)}-"
                       f"{rng.integers(100,1000)}-{rng.integers(1000,10000)}"
                       for k in nk], dtype=object)
    comments = _pick(rng, COLORS, n)
    # spec: SF*5 suppliers get "Customer Complaints" (q16 exclusion)
    bad = rng.random(n) < 0.01
    comments = np.where(
        bad, comments + np.array([" Customer Complaints"], object),
        comments)
    acct = rng.integers(-99_999, 1_000_000, n).astype(np.int64)
    return pa.table({
        "s_suppkey": pa.array(np.arange(n, dtype=np.int64)),
        "s_name": pa.array(np.array(
            [f"Supplier#{i:09d}" for i in range(n)], object), pa.string()),
        "s_address": pa.array(_pick(rng, COLORS, n), pa.string()),
        "s_nationkey": pa.array(nk.astype(np.int64)),
        "s_phone": pa.array(phones, pa.string()),
        "s_acctbal": dec_from_unscaled(acct, 12, 2),
        "s_comment": pa.array(comments.astype(object), pa.string()),
    })


def gen_partsupp(sf: float = 0.1, seed: int = 5) -> pa.Table:
    npart = max(int(PART_ROWS_PER_SF * sf), 1)
    nsupp = max(int(SUPPLIER_ROWS_PER_SF * sf), 1)
    rng = np.random.default_rng(seed)
    # spec: 4 rows per part, supplier spread deterministically
    pk = np.repeat(np.arange(npart, dtype=np.int64), 4)
    n = len(pk)
    sk = ((pk * 4 + np.tile(np.arange(4), npart)
           * max(nsupp // 4, 1)) % nsupp).astype(np.int64)
    cost = rng.integers(100, 100_100, n).astype(np.int64)
    return pa.table({
        "ps_partkey": pa.array(pk),
        "ps_suppkey": pa.array(sk),
        "ps_availqty": pa.array(rng.integers(1, 10_000, n).astype(np.int32),
                                pa.int32()),
        "ps_supplycost": dec_from_unscaled(cost, 12, 2),
    })


def gen_nation() -> pa.Table:
    return pa.table({
        "n_nationkey": pa.array(np.arange(25, dtype=np.int64)),
        "n_name": pa.array([n for n, _ in NATIONS], pa.string()),
        "n_regionkey": pa.array(
            np.array([r for _, r in NATIONS], np.int64)),
    })


def gen_region() -> pa.Table:
    return pa.table({
        "r_regionkey": pa.array(np.arange(5, dtype=np.int64)),
        "r_name": pa.array(REGIONS, pa.string()),
    })


def gen_all(sf: float = 0.1, seed: int = 7) -> dict:
    """All 8 TPC-H tables as pyarrow Tables, FK-consistent at this sf."""
    return {
        "lineitem": gen_lineitem(sf, seed, full=True),
        "orders": gen_orders(sf, seed, full=True),
        "customer": gen_customer(sf, seed, full=True),
        "part": gen_part(sf),
        "supplier": gen_supplier(sf),
        "partsupp": gen_partsupp(sf),
        "nation": gen_nation(),
        "region": gen_region(),
    }


def q3(customer, orders, lineitem):
    """TPC-H Q3 shape: shipping priority (join+join+grouped agg+topk)."""
    import decimal
    d = decimal.Decimal
    rev = col("l_extendedprice") * (lit(d("1")) - col("l_discount"))
    df = (customer.filter(col("c_mktsegment") == lit("BUILDING"))
          .join(orders.with_column("c_custkey", col("o_custkey")),
                on=["c_custkey"], how="inner")
          .filter(col("o_orderdate") < 9204)
          .with_column("l_orderkey", col("o_orderkey"))
          .join(lineitem, on=["l_orderkey"], how="inner")
          .filter(col("l_shipdate") > 9204)
          .group_by("l_orderkey", "o_orderdate", "o_shippriority")
          .agg(F.sum(rev).alias("revenue")))
    from ..plan.logical import Sort, SortOrder
    from ..session import DataFrame
    sorted_df = DataFrame(df._session, Sort(df._plan, [
        SortOrder(col("revenue"), ascending=False),
        SortOrder(col("o_orderdate"), ascending=True)]))
    return sorted_df.limit(10)


def queries() -> dict:
    """Registry of all 22 TPC-H queries with the uniform signature
    ``fn(tables: dict[str, DataFrame]) -> DataFrame``."""
    from . import tpch_queries as Q

    reg = {
        1: lambda t: q1(t["lineitem"]),
        3: lambda t: q3(t["customer"], t["orders"], t["lineitem"]),
        6: lambda t: q6(t["lineitem"]),
    }
    for n in (2, 4, 5, 7, 8, 9, 10, 11, 12, 13, 14, 15, 16, 17, 18, 19,
              20, 21, 22):
        reg[n] = getattr(Q, f"q{n}")
    return reg
