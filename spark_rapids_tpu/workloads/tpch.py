"""TPC-H workload: schema, data generation, and query definitions.

The perf harness analog of the reference's datagen/ScaleTest
(reference: datagen/ScaleTest.md). Decimal columns use precisions that keep
the engine on the decimal64 (int64) path — exact fixed-point arithmetic
without f64 emulation on TPU.
"""
from __future__ import annotations

import numpy as np
import pyarrow as pa

import spark_rapids_tpu.functions as F
from spark_rapids_tpu.expr.expressions import col, lit

LINEITEM_ROWS_PER_SF = 6_001_215


def dec_from_unscaled(vals: np.ndarray, precision: int, scale: int):
    """Build a decimal128 array whose UNSCALED value is `vals` (a cast from
    int64 would rescale instead)."""
    n = len(vals)
    lo = vals.astype(np.int64)
    hi = np.where(lo < 0, np.int64(-1), np.int64(0))
    words = np.empty(2 * n, np.int64)
    words[0::2] = lo
    words[1::2] = hi
    return pa.Array.from_buffers(
        pa.decimal128(38, scale), n,
        [None, pa.py_buffer(words.tobytes())]).cast(
            pa.decimal128(precision, scale))


def gen_lineitem(sf: float = 0.1, seed: int = 0) -> pa.Table:
    n = int(LINEITEM_ROWS_PER_SF * sf)
    rng = np.random.default_rng(seed)
    qty = rng.integers(1, 51, n).astype(np.int64) * 100          # dec(12,2)
    price = rng.integers(90_000, 10_500_000, n).astype(np.int64)  # dec(12,2)
    disc = rng.integers(0, 11, n).astype(np.int64)                # dec(4,2)
    tax = rng.integers(0, 9, n).astype(np.int64)
    shipdate = rng.integers(8036, 10591, n).astype(np.int32)      # days
    rf = rng.integers(0, 3, n)
    ls = rng.integers(0, 2, n)
    returnflag = pa.array(np.array(["A", "N", "R"])[rf])
    linestatus = pa.array(np.array(["F", "O"])[ls])
    okey = rng.integers(0, max(n // 4, 1), n).astype(np.int64)
    return pa.table({
        "l_orderkey": pa.array(okey, pa.int64()),
        "l_quantity": dec_from_unscaled(qty, 12, 2),
        "l_extendedprice": dec_from_unscaled(price, 12, 2),
        "l_discount": dec_from_unscaled(disc, 4, 2),
        "l_tax": dec_from_unscaled(tax, 4, 2),
        "l_returnflag": returnflag,
        "l_linestatus": linestatus,
        "l_shipdate": pa.array(shipdate, pa.int32()),
    })


def q6(df):
    """TPC-H Q6: forecasting revenue change (scan+filter+reduction)."""
    import decimal
    d = decimal.Decimal
    return (df.filter((col("l_shipdate") >= 8766) & (col("l_shipdate") < 9131)
                      & (col("l_discount") >= lit(d("0.05")))
                      & (col("l_discount") <= lit(d("0.07")))
                      & (col("l_quantity") < lit(d("24"))))
            .agg(F.sum(col("l_extendedprice") * col("l_discount"))
                 .alias("revenue")))


def q1(df):
    """TPC-H Q1: pricing summary report (grouped agg, 8 aggregates)."""
    import decimal
    d = decimal.Decimal
    disc_price = col("l_extendedprice") * (lit(d("1")) - col("l_discount"))
    charge = disc_price * (lit(d("1")) + col("l_tax"))
    return (df.filter(col("l_shipdate") <= 10471)
            .group_by("l_returnflag", "l_linestatus")
            .agg(F.sum("l_quantity").alias("sum_qty"),
                 F.sum("l_extendedprice").alias("sum_base_price"),
                 F.sum(disc_price).alias("sum_disc_price"),
                 F.sum(charge).alias("sum_charge"),
                 F.avg("l_quantity").alias("avg_qty"),
                 F.avg("l_extendedprice").alias("avg_price"),
                 F.avg("l_discount").alias("avg_disc"),
                 F.count("*").alias("count_order")))


def q6_numpy_baseline(ship, disc_unscaled, qty_unscaled, price_unscaled):
    """Vectorized single-core CPU reference over the raw unscaled arrays
    (the CPU-Spark stand-in for bench.py)."""
    m = ((ship >= 8766) & (ship < 9131)
         & (disc_unscaled >= 5) & (disc_unscaled <= 7)
         & (qty_unscaled < 2400))
    return int(np.sum(price_unscaled[m] * disc_unscaled[m]))


def q1_numpy_baseline(ship, rf, ls, qty, price, disc, tax):
    """Vectorized single-core Q1 reference: grouped sums via bincount over
    the 6 (returnflag, linestatus) combinations. rf/ls are small int codes."""
    m = ship <= 10471
    g = (rf * 2 + ls)[m]
    qty, price, disc, tax = qty[m], price[m], disc[m], tax[m]
    disc_price = price * (100 - disc)          # scale 4
    charge = disc_price * (100 + tax)          # scale 6
    out = {}
    out["sum_qty"] = np.bincount(g, qty, 6)
    out["sum_base_price"] = np.bincount(g, price, 6)
    out["sum_disc_price"] = np.bincount(g, disc_price, 6)
    out["sum_charge"] = np.bincount(g, charge.astype(np.float64), 6)
    out["count"] = np.bincount(g, minlength=6)
    return out


def q3_numpy_baseline(c_key, c_seg, o_okey, o_ckey, o_date, o_prio,
                      l_okey, l_ship, l_price, l_disc):
    """Vectorized single-core Q3 reference: semi-join via np.isin +
    dict-free grouped sum over order keys."""
    cust = c_key[c_seg == 1]                      # BUILDING code == 1
    om = (o_date < 9204) & np.isin(o_ckey, cust)
    okeys = o_okey[om]
    lm = (l_ship > 9204) & np.isin(l_okey, okeys)
    lk = l_okey[lm]
    rev = l_price[lm] * (100 - l_disc[lm])
    order = np.argsort(lk, kind="stable")
    lk_s, rev_s = lk[order], rev[order]
    starts = np.flatnonzero(np.r_[True, lk_s[1:] != lk_s[:-1]])
    sums = np.add.reduceat(rev_s, starts) if lk_s.size else np.array([])
    keys = lk_s[starts] if lk_s.size else np.array([], np.int64)
    top = np.argsort(-sums, kind="stable")[:10]
    return keys[top], sums[top]


ORDERS_ROWS_PER_SF = 1_500_000


def gen_orders(sf: float = 0.1, seed: int = 1) -> pa.Table:
    n = int(ORDERS_ROWS_PER_SF * sf)
    rng = np.random.default_rng(seed)
    okey = np.arange(n, dtype=np.int64)
    ckey = rng.integers(0, max(n // 10, 1), n).astype(np.int64)
    odate = rng.integers(8036, 10591, n).astype(np.int32)
    seg = rng.integers(0, 5, n)
    total = rng.integers(100_000, 50_000_000, n).astype(np.int64)
    return pa.table({
        "o_orderkey": pa.array(okey),
        "o_custkey": pa.array(ckey),
        "o_orderdate": pa.array(odate, pa.int32()),
        "o_totalprice": dec_from_unscaled(total, 15, 2),
        "o_shippriority": pa.array(rng.integers(0, 2, n).astype(np.int32),
                                   pa.int32()),
    })


def gen_customer(sf: float = 0.1, seed: int = 2) -> pa.Table:
    n = int(150_000 * sf)
    rng = np.random.default_rng(seed)
    segs = np.array(["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD",
                     "MACHINERY"])
    return pa.table({
        "c_custkey": pa.array(np.arange(n, dtype=np.int64)),
        "c_mktsegment": pa.array(segs[rng.integers(0, 5, n)]),
    })


def q3(customer, orders, lineitem):
    """TPC-H Q3 shape: shipping priority (join+join+grouped agg+topk)."""
    import decimal
    d = decimal.Decimal
    rev = col("l_extendedprice") * (lit(d("1")) - col("l_discount"))
    df = (customer.filter(col("c_mktsegment") == lit("BUILDING"))
          .join(orders.with_column("c_custkey", col("o_custkey")),
                on=["c_custkey"], how="inner")
          .filter(col("o_orderdate") < 9204)
          .with_column("l_orderkey", col("o_orderkey"))
          .join(lineitem, on=["l_orderkey"], how="inner")
          .filter(col("l_shipdate") > 9204)
          .group_by("l_orderkey", "o_orderdate", "o_shippriority")
          .agg(F.sum(rev).alias("revenue")))
    from ..plan.logical import Sort, SortOrder
    from ..session import DataFrame
    sorted_df = DataFrame(df._session, Sort(df._plan, [
        SortOrder(col("revenue"), ascending=False),
        SortOrder(col("o_orderdate"), ascending=True)]))
    return sorted_df.limit(10)
