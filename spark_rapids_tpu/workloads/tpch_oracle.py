"""Pandas oracles for all 22 TPC-H queries.

Independent implementations of the official query set used to verify the
engine's results (tests/test_tpch.py) and as the CPU baseline for the
bench geomean. Written directly from the TPC-H v3 SQL — NOT by
translating tpch_queries.py — so an engine bug and an oracle bug would
have to coincide to go unseen.

Decimal columns arrive as float64 (converted by :func:`to_pandas`);
monetary sums therefore compare within rtol, counts exactly.
"""
from __future__ import annotations

import numpy as np
import pandas as pd

from .tpch import day


def to_pandas(tables: dict) -> dict:
    """pyarrow tables -> pandas frames with decimals as float64."""
    import pyarrow as pa
    out = {}
    for name, at in tables.items():
        df = pd.DataFrame()
        for c in at.column_names:
            colv = at.column(c)
            if pa.types.is_decimal(colv.type):
                df[c] = np.asarray(colv.cast(pa.float64()))
            else:
                df[c] = colv.to_pandas()
        out[name] = df
    return out


def _rev(li):
    return li["l_extendedprice"] * (1 - li["l_discount"])


def q1(t):
    li = t["lineitem"]
    m = li[li["l_shipdate"] <= 10471].copy()
    m["disc_price"] = _rev(m)
    m["charge"] = m["disc_price"] * (1 + m["l_tax"])
    g = m.groupby(["l_returnflag", "l_linestatus"], as_index=False).agg(
        sum_qty=("l_quantity", "sum"),
        sum_base_price=("l_extendedprice", "sum"),
        sum_disc_price=("disc_price", "sum"),
        sum_charge=("charge", "sum"),
        avg_qty=("l_quantity", "mean"),
        avg_price=("l_extendedprice", "mean"),
        avg_disc=("l_discount", "mean"),
        count_order=("l_quantity", "size"))
    return g.sort_values(["l_returnflag", "l_linestatus"])


def q2(t, size=15, type_suffix="BRASS", region="EUROPE"):
    n = t["nation"].merge(t["region"], left_on="n_regionkey",
                          right_on="r_regionkey")
    n = n[n["r_name"] == region]
    s = t["supplier"].merge(n, left_on="s_nationkey",
                            right_on="n_nationkey")
    ps = t["partsupp"].merge(s, left_on="ps_suppkey",
                             right_on="s_suppkey")
    p = t["part"]
    p = p[(p["p_size"] == size) & p["p_type"].str.endswith(type_suffix)]
    j = p.merge(ps, left_on="p_partkey", right_on="ps_partkey")
    mc = (ps.groupby("ps_partkey")["ps_supplycost"].min()
          .rename("min_cost").reset_index())
    j = j.merge(mc, on="ps_partkey")
    j = j[j["ps_supplycost"] == j["min_cost"]]
    j = j[["s_acctbal", "s_name", "n_name", "p_partkey", "p_mfgr",
           "s_address", "s_phone", "s_comment"]]
    return j.sort_values(["s_acctbal", "n_name", "s_name", "p_partkey"],
                         ascending=[False, True, True, True]).head(100)


def q3(t, segment="BUILDING", d="1995-03-15"):
    dd = day(d)
    c = t["customer"]
    c = c[c["c_mktsegment"] == segment]
    o = t["orders"]
    o = o[o["o_orderdate"] < dd].merge(c, left_on="o_custkey",
                                       right_on="c_custkey")
    li = t["lineitem"]
    li = li[li["l_shipdate"] > dd].merge(
        o, left_on="l_orderkey", right_on="o_orderkey").copy()
    li["revenue"] = _rev(li)
    g = li.groupby(["l_orderkey", "o_orderdate", "o_shippriority"],
                   as_index=False)["revenue"].sum()
    return g.sort_values(["revenue", "o_orderdate"],
                         ascending=[False, True]).head(10)


def q4(t, d0="1993-07-01", d1="1993-10-01"):
    o = t["orders"]
    o = o[(o["o_orderdate"] >= day(d0)) & (o["o_orderdate"] < day(d1))]
    li = t["lineitem"]
    late_orders = li[li["l_commitdate"] < li["l_receiptdate"]][
        "l_orderkey"].unique()
    o = o[o["o_orderkey"].isin(late_orders)]
    g = (o.groupby("o_orderpriority").size()
         .rename("order_count").reset_index())
    return g.sort_values("o_orderpriority")


def q5(t, region="ASIA", d0="1994-01-01", d1="1995-01-01"):
    o = t["orders"]
    o = o[(o["o_orderdate"] >= day(d0)) & (o["o_orderdate"] < day(d1))]
    j = (t["customer"].merge(o, left_on="c_custkey", right_on="o_custkey")
         .merge(t["lineitem"], left_on="o_orderkey",
                right_on="l_orderkey")
         .merge(t["supplier"], left_on="l_suppkey", right_on="s_suppkey"))
    j = j[j["c_nationkey"] == j["s_nationkey"]]
    j = (j.merge(t["nation"], left_on="c_nationkey",
                 right_on="n_nationkey")
         .merge(t["region"], left_on="n_regionkey",
                right_on="r_regionkey"))
    j = j[j["r_name"] == region].copy()
    j["revenue"] = _rev(j)
    g = j.groupby("n_name", as_index=False)["revenue"].sum()
    return g.sort_values("revenue", ascending=False)


def q6(t):
    li = t["lineitem"]
    m = li[(li["l_shipdate"] >= 8766) & (li["l_shipdate"] < 9131)
           & (li["l_discount"] >= 0.05 - 1e-9)
           & (li["l_discount"] <= 0.07 + 1e-9)
           & (li["l_quantity"] < 24)]
    return pd.DataFrame(
        {"revenue": [(m["l_extendedprice"] * m["l_discount"]).sum()]})


def q7(t, n1="FRANCE", n2="GERMANY"):
    li = t["lineitem"]
    li = li[(li["l_shipdate"] >= day("1995-01-01"))
            & (li["l_shipdate"] <= day("1996-12-31"))]
    j = (li.merge(t["supplier"], left_on="l_suppkey", right_on="s_suppkey")
         .merge(t["orders"], left_on="l_orderkey", right_on="o_orderkey")
         .merge(t["customer"], left_on="o_custkey", right_on="c_custkey")
         .merge(t["nation"].rename(columns={"n_name": "supp_nation"}),
                left_on="s_nationkey", right_on="n_nationkey")
         .merge(t["nation"].rename(
             columns={"n_name": "cust_nation",
                      "n_nationkey": "n2_nationkey",
                      "n_regionkey": "n2_regionkey"}),
             left_on="c_nationkey", right_on="n2_nationkey"))
    j = j[((j["supp_nation"] == n1) & (j["cust_nation"] == n2))
          | ((j["supp_nation"] == n2) & (j["cust_nation"] == n1))].copy()
    j["l_year"] = np.where(j["l_shipdate"] <= day("1995-12-31"),
                           1995, 1996)
    j["revenue"] = _rev(j)
    g = j.groupby(["supp_nation", "cust_nation", "l_year"],
                  as_index=False)["revenue"].sum()
    return g.sort_values(["supp_nation", "cust_nation", "l_year"])


def _o_year(dates):
    bins = [day(f"{y}-12-31") for y in range(1992, 1998)]
    return np.searchsorted(bins, dates) + 1992


def q8(t, nation="BRAZIL", region="AMERICA",
       ptype="ECONOMY ANODIZED STEEL"):
    p = t["part"]
    p = p[p["p_type"] == ptype]
    o = t["orders"]
    o = o[(o["o_orderdate"] >= day("1995-01-01"))
          & (o["o_orderdate"] <= day("1996-12-31"))]
    j = (p.merge(t["lineitem"], left_on="p_partkey", right_on="l_partkey")
         .merge(t["supplier"], left_on="l_suppkey", right_on="s_suppkey")
         .merge(o, left_on="l_orderkey", right_on="o_orderkey")
         .merge(t["customer"], left_on="o_custkey", right_on="c_custkey")
         .merge(t["nation"], left_on="c_nationkey",
                right_on="n_nationkey")
         .merge(t["region"], left_on="n_regionkey",
                right_on="r_regionkey"))
    j = j[j["r_name"] == region]
    j = j.merge(t["nation"].rename(
        columns={"n_name": "supp_nation", "n_nationkey": "sn_key",
                 "n_regionkey": "sn_rk"}),
        left_on="s_nationkey", right_on="sn_key").copy()
    j["o_year"] = np.where(j["o_orderdate"] <= day("1995-12-31"),
                           1995, 1996)
    j["volume"] = _rev(j)
    j["nat"] = np.where(j["supp_nation"] == nation, j["volume"], 0.0)
    g = j.groupby("o_year", as_index=False).agg(
        nat=("nat", "sum"), total=("volume", "sum"))
    g["mkt_share"] = g["nat"] / g["total"]
    return g[["o_year", "mkt_share"]].sort_values("o_year")


def q9(t, word="green"):
    p = t["part"]
    p = p[p["p_name"].str.contains(word, regex=False)]
    j = (p.merge(t["lineitem"], left_on="p_partkey", right_on="l_partkey")
         .merge(t["supplier"], left_on="l_suppkey", right_on="s_suppkey")
         .merge(t["partsupp"],
                left_on=["l_partkey", "l_suppkey"],
                right_on=["ps_partkey", "ps_suppkey"])
         .merge(t["orders"], left_on="l_orderkey", right_on="o_orderkey")
         .merge(t["nation"], left_on="s_nationkey",
                right_on="n_nationkey")).copy()
    j["o_year"] = _o_year(j["o_orderdate"].to_numpy())
    j["amount"] = _rev(j) - j["ps_supplycost"] * j["l_quantity"]
    g = j.groupby(["n_name", "o_year"], as_index=False)["amount"].sum()
    g = g.rename(columns={"amount": "sum_profit"})
    return g.sort_values(["n_name", "o_year"], ascending=[True, False])


def q10(t, d0="1993-10-01", d1="1994-01-01"):
    o = t["orders"]
    o = o[(o["o_orderdate"] >= day(d0)) & (o["o_orderdate"] < day(d1))]
    li = t["lineitem"]
    li = li[li["l_returnflag"] == "R"]
    j = (t["customer"].merge(o, left_on="c_custkey", right_on="o_custkey")
         .merge(li, left_on="o_orderkey", right_on="l_orderkey")
         .merge(t["nation"], left_on="c_nationkey",
                right_on="n_nationkey")).copy()
    j["revenue"] = _rev(j)
    g = j.groupby(["c_custkey", "c_name", "c_acctbal", "c_phone",
                   "n_name", "c_address"], as_index=False)["revenue"].sum()
    return g.sort_values(["revenue", "c_custkey"],
                         ascending=[False, True]).head(20)


def q11(t, nation="GERMANY", fraction=0.0001):
    j = (t["partsupp"]
         .merge(t["supplier"], left_on="ps_suppkey", right_on="s_suppkey")
         .merge(t["nation"], left_on="s_nationkey",
                right_on="n_nationkey"))
    j = j[j["n_name"] == nation].copy()
    j["value"] = j["ps_supplycost"] * j["ps_availqty"]
    g = (j.groupby("ps_partkey")["value"].sum()
         .rename("part_value").reset_index())
    g = g[g["part_value"] > j["value"].sum() * fraction]
    return g.sort_values(["part_value", "ps_partkey"],
                         ascending=[False, True])


def q12(t, m1="MAIL", m2="SHIP", d0="1994-01-01", d1="1995-01-01"):
    li = t["lineitem"]
    li = li[li["l_shipmode"].isin([m1, m2])
            & (li["l_commitdate"] < li["l_receiptdate"])
            & (li["l_shipdate"] < li["l_commitdate"])
            & (li["l_receiptdate"] >= day(d0))
            & (li["l_receiptdate"] < day(d1))]
    j = li.merge(t["orders"], left_on="l_orderkey",
                 right_on="o_orderkey").copy()
    hi = j["o_orderpriority"].isin(["1-URGENT", "2-HIGH"])
    j["high_line_count"] = hi.astype(np.int64)
    j["low_line_count"] = (~hi).astype(np.int64)
    g = j.groupby("l_shipmode", as_index=False)[
        ["high_line_count", "low_line_count"]].sum()
    return g.sort_values("l_shipmode")


def q13(t, w1="special", w2="requests"):
    o = t["orders"]
    o = o[~o["o_comment"].str.contains(f"{w1}.*{w2}", regex=True)]
    j = t["customer"][["c_custkey"]].merge(
        o[["o_custkey", "o_orderkey"]], left_on="c_custkey",
        right_on="o_custkey", how="left")
    cc = (j.groupby("c_custkey")["o_orderkey"].count()
          .rename("c_count").reset_index())
    g = (cc.groupby("c_count").size().rename("custdist").reset_index())
    return g.sort_values(["custdist", "c_count"], ascending=[False, False])


def q14(t, d0="1995-09-01", d1="1995-10-01"):
    li = t["lineitem"]
    li = li[(li["l_shipdate"] >= day(d0)) & (li["l_shipdate"] < day(d1))]
    j = li.merge(t["part"], left_on="l_partkey",
                 right_on="p_partkey").copy()
    j["rev"] = _rev(j)
    promo = j["p_type"].str.startswith("PROMO")
    num = j.loc[promo, "rev"].sum()
    return pd.DataFrame(
        {"promo_revenue": [100.0 * num / j["rev"].sum()]})


def q15(t, d0="1996-01-01", d1="1996-04-01"):
    li = t["lineitem"]
    li = li[(li["l_shipdate"] >= day(d0))
            & (li["l_shipdate"] < day(d1))].copy()
    li["r"] = _rev(li)
    rev = (li.groupby("l_suppkey")["r"].sum()
           .rename("total_revenue").reset_index())
    mx = rev["total_revenue"].max()
    j = rev[rev["total_revenue"] == mx].merge(
        t["supplier"], left_on="l_suppkey", right_on="s_suppkey")
    j = j[["s_suppkey", "s_name", "s_address", "s_phone",
           "total_revenue"]]
    return j.sort_values("s_suppkey")


def q16(t, brand="Brand#45", tprefix="MEDIUM POLISHED",
        sizes=(49, 14, 23, 45, 19, 3, 36, 9)):
    bad = t["supplier"]
    bad = bad[bad["s_comment"].str.contains("Customer.*Complaints",
                                            regex=True)]["s_suppkey"]
    ps = t["partsupp"]
    ps = ps[~ps["ps_suppkey"].isin(bad)]
    p = t["part"]
    p = p[(p["p_brand"] != brand)
          & ~p["p_type"].str.startswith(tprefix)
          & p["p_size"].isin(sizes)]
    j = ps.merge(p, left_on="ps_partkey", right_on="p_partkey")
    g = (j.groupby(["p_brand", "p_type", "p_size"])["ps_suppkey"]
         .nunique().rename("supplier_cnt").reset_index())
    return g.sort_values(["supplier_cnt", "p_brand", "p_type", "p_size"],
                         ascending=[False, True, True, True])


def q17(t, brand="Brand#23", container="MED BOX"):
    li = t["lineitem"]
    avg_qty = (li.groupby("l_partkey")["l_quantity"].mean() * 0.2)
    p = t["part"]
    p = p[(p["p_brand"] == brand) & (p["p_container"] == container)]
    j = p.merge(li, left_on="p_partkey", right_on="l_partkey")
    thr = j["l_partkey"].map(avg_qty)
    total = j.loc[j["l_quantity"] < thr, "l_extendedprice"].sum()
    return pd.DataFrame({"avg_yearly": [total / 7.0]})


def q18(t, qty=300):
    li = t["lineitem"]
    sums = li.groupby("l_orderkey")["l_quantity"].sum()
    big = sums[sums > qty].index
    o = t["orders"]
    o = o[o["o_orderkey"].isin(big)]
    j = (o.merge(t["customer"], left_on="o_custkey", right_on="c_custkey")
         .merge(li[["l_orderkey", "l_quantity"]],
                left_on="o_orderkey", right_on="l_orderkey"))
    g = j.groupby(["c_name", "c_custkey", "o_orderkey", "o_orderdate",
                   "o_totalprice"], as_index=False)["l_quantity"].sum()
    g = g.rename(columns={"l_quantity": "sum_qty"})
    return g.sort_values(["o_totalprice", "o_orderdate", "o_orderkey"],
                         ascending=[False, True, True]).head(100)


def q19(t):
    li = t["lineitem"]
    li = li[li["l_shipmode"].isin(["AIR", "REG AIR"])
            & (li["l_shipinstruct"] == "DELIVER IN PERSON")]
    j = li.merge(t["part"], left_on="l_partkey", right_on="p_partkey")

    def branch(brand, containers, qlo, qhi, szhi):
        return ((j["p_brand"] == brand)
                & j["p_container"].isin(containers)
                & (j["l_quantity"] >= qlo) & (j["l_quantity"] <= qhi)
                & (j["p_size"] >= 1) & (j["p_size"] <= szhi))

    m = (branch("Brand#12", ["SM CASE", "SM BOX", "SM PACK", "SM PKG"],
                1, 11, 5)
         | branch("Brand#23", ["MED BAG", "MED BOX", "MED PKG",
                               "MED PACK"], 10, 20, 10)
         | branch("Brand#34", ["LG CASE", "LG BOX", "LG PACK", "LG PKG"],
                  20, 30, 15))
    return pd.DataFrame({"revenue": [_rev(j[m]).sum()]})


def q20(t, word="forest", nation="CANADA", d0="1994-01-01",
        d1="1995-01-01"):
    p = t["part"]
    pk = p[p["p_name"].str.startswith(word)]["p_partkey"]
    li = t["lineitem"]
    li = li[(li["l_shipdate"] >= day(d0)) & (li["l_shipdate"] < day(d1))]
    hq = (li.groupby(["l_partkey", "l_suppkey"])["l_quantity"].sum()
          * 0.5).rename("half_qty").reset_index()
    ps = t["partsupp"]
    ps = ps[ps["ps_partkey"].isin(pk)]
    ps = ps.merge(hq, left_on=["ps_partkey", "ps_suppkey"],
                  right_on=["l_partkey", "l_suppkey"])
    ps = ps[ps["ps_availqty"] > ps["half_qty"]]
    s = t["supplier"]
    s = s[s["s_suppkey"].isin(ps["ps_suppkey"].unique())]
    s = s.merge(t["nation"], left_on="s_nationkey",
                right_on="n_nationkey")
    s = s[s["n_name"] == nation]
    return s[["s_name", "s_address"]].sort_values("s_name")


def q21(t, nation="SAUDI ARABIA"):
    li = t["lineitem"]
    late = li[li["l_receiptdate"] > li["l_commitdate"]]
    n_supp = li.groupby("l_orderkey")["l_suppkey"].nunique()
    n_late = late.groupby("l_orderkey")["l_suppkey"].nunique()
    o = t["orders"]
    fo = set(o[o["o_orderstatus"] == "F"]["o_orderkey"])
    j = late[late["l_orderkey"].isin(fo)].copy()
    j["n_supp"] = j["l_orderkey"].map(n_supp)
    j["n_late"] = j["l_orderkey"].map(n_late)
    j = j[(j["n_supp"] > 1) & (j["n_late"] == 1)]
    j = (j.merge(t["supplier"], left_on="l_suppkey", right_on="s_suppkey")
         .merge(t["nation"], left_on="s_nationkey",
                right_on="n_nationkey"))
    j = j[j["n_name"] == nation]
    g = j.groupby("s_name").size().rename("numwait").reset_index()
    return g.sort_values(["numwait", "s_name"],
                         ascending=[False, True]).head(100)


def q22(t, codes=("13", "31", "23", "29", "30", "18", "17")):
    c = t["customer"].copy()
    c["cntrycode"] = c["c_phone"].str[:2]
    c = c[c["cntrycode"].isin(codes)]
    avg_bal = c.loc[c["c_acctbal"] > 0, "c_acctbal"].mean()
    has_orders = set(t["orders"]["o_custkey"])
    c = c[~c["c_custkey"].isin(has_orders)
          & (c["c_acctbal"] > avg_bal)]
    g = c.groupby("cntrycode", as_index=False).agg(
        numcust=("c_acctbal", "size"), totacctbal=("c_acctbal", "sum"))
    return g.sort_values("cntrycode")


ORACLES = {i: fn for i, fn in enumerate(
    [q1, q2, q3, q4, q5, q6, q7, q8, q9, q10, q11, q12, q13, q14, q15,
     q16, q17, q18, q19, q20, q21, q22], start=1)}
