"""Multichip SPMD-stage dryrun: the worker behind ``bench.py
--multichip``.

Runs the q3/q6 distributed shapes over an N-device mesh (virtual CPU
devices in CI — the parent process forces
``--xla_force_host_platform_device_count`` BEFORE jax imports, which is
why this lives in a subprocess) through THREE engine paths and prints
ONE JSON document on the last stdout line:

  host    mesh disabled (``mesh.devices 0``) — the single-chip + host
          shuffle reference every other path must match byte-for-byte
  round   mesh on, ``mesh.spmdStage.enabled false`` — the streaming
          round-based MeshExchangeExec (bounded-memory fallback)
  fused   mesh on, SPMD stages on — exchange + consumer as ONE
          shard_map program per stage (the PR 16 tentpole)

Per query the document carries the fused-stage count, collective bytes
moved, programs compiled cold vs on a warm rerun (the warm count must
be zero — the stage program is keyed on mesh topology + plan
fingerprints, so a rerun recompiles nothing), and parity booleans
against the host path. ``bench.py`` folds the document into
MULTICHIP_r06.json and regression-gates the parity bits.

Results are canonicalized (rows sorted by every column) before
comparison: the three paths partition rows differently, so row ORDER
is path-dependent while row CONTENT must not be.
"""
from __future__ import annotations

import json
import os
import sys


def _canon(tbl):
    """Row-order canonical form: sort by all columns (paths shard rows
    differently; content, not order, is the parity contract)."""
    import pyarrow.compute as pc
    if tbl.num_rows <= 1:
        return tbl
    idx = pc.sort_indices(
        tbl, sort_keys=[(name, "ascending") for name in tbl.column_names])
    return tbl.take(idx)


def _q6_shape(lineitem):
    """TPC-H Q6 distributed shape: the Q6 predicate stack feeding a
    grouped revenue sum (plain Q6 is a global reduction — no exchange
    to fuse — so the dryrun groups by return flag to route the same
    filter+agg shape through the mesh exchange)."""
    import decimal

    import spark_rapids_tpu.functions as F
    from spark_rapids_tpu.expr.expressions import col, lit
    d = decimal.Decimal
    return (lineitem.filter(
                (col("l_shipdate") >= 8766) & (col("l_shipdate") < 9131)
                & (col("l_discount") >= lit(d("0.05")))
                & (col("l_discount") <= lit(d("0.07")))
                & (col("l_quantity") < lit(d("24"))))
            .group_by("l_returnflag")
            .agg(F.sum(col("l_extendedprice") * col("l_discount"))
                 .alias("revenue")))


def _q3_shape(customer, orders, lineitem):
    """TPC-H Q3 distributed shape — filter + join + join + grouped agg
    (the topk tail is dropped: limit-ties would make cross-path byte
    parity order-dependent, which is not what this dryrun measures)."""
    import decimal

    import spark_rapids_tpu.functions as F
    from spark_rapids_tpu.expr.expressions import col, lit
    d = decimal.Decimal
    rev = col("l_extendedprice") * (lit(d("1")) - col("l_discount"))
    return (customer.filter(col("c_mktsegment") == lit("BUILDING"))
            .join(orders.with_column("c_custkey", col("o_custkey")),
                  on=["c_custkey"], how="inner")
            .filter(col("o_orderdate") < 9204)
            .with_column("l_orderkey", col("o_orderkey"))
            .join(lineitem, on=["l_orderkey"], how="inner")
            .filter(col("l_shipdate") > 9204)
            .group_by("l_orderkey", "o_orderdate", "o_shippriority")
            .agg(F.sum(rev).alias("revenue")))


def _metric_sum(df, key) -> int:
    """Sum `key` over the per-operator metrics of `df`'s last action."""
    return int(sum(m.get(key, 0)
                   for m in df.last_metrics().values()))


def _spmd_compiles(events) -> int:
    return sum(1 for ev in events
               if ev.get("program", "").startswith("SpmdStageExec"))


def main() -> int:
    import jax

    import spark_rapids_tpu as st
    from spark_rapids_tpu.runtime import program_cache
    from spark_rapids_tpu.workloads import tpch

    n_dev = min(int(os.environ.get("SPMD_BENCH_DEVICES", "8")),
                len(jax.devices()))
    doc = {"n_devices": n_dev, "queries": {}, "ok": True,
           "skipped": False}
    if n_dev < 2:
        doc.update(ok=True, skipped=True,
                   reason=f"{len(jax.devices())} device(s); mesh needs 2+")
        print(json.dumps(doc))
        return 0

    sf = float(os.environ.get("SPMD_BENCH_SF", "0.02"))
    # small batches force multiple shards/batches per partition so the
    # collective actually moves rows between devices
    batch = int(os.environ.get("SPMD_BENCH_BATCH", "2048"))
    li = tpch.gen_lineitem(sf=sf, seed=7)
    od = tpch.gen_orders(sf=sf, seed=8)
    cu = tpch.gen_customer(sf=sf, seed=9)

    def build(s, qname):
        dfs = {k: s.create_dataframe(v)
               for k, v in (("lineitem", li), ("orders", od),
                            ("customer", cu))}
        if qname == "q6":
            return _q6_shape(dfs["lineitem"])
        return _q3_shape(dfs["customer"], dfs["orders"], dfs["lineitem"])

    def session(extra):
        conf = {"spark.rapids.tpu.sql.batchSizeRows": batch,
                "spark.rapids.tpu.sql.resultCache.enabled": "false"}
        conf.update(extra)
        return st.TpuSession(conf)

    mesh_on = {"spark.rapids.tpu.mesh.devices": n_dev}
    for qname in ("q6", "q3"):
        host = _canon(build(session(
            {"spark.rapids.tpu.mesh.devices": 0}), qname).to_arrow())

        s_round = session(dict(
            mesh_on, **{"spark.rapids.tpu.mesh.spmdStage.enabled":
                        "false"}))
        round_df = build(s_round, qname)
        round_tbl = _canon(round_df.to_arrow())
        round_rounds = _metric_sum(round_df, "meshRounds")
        round_bytes = _metric_sum(round_df, "collectiveBytes")

        s_fused = session(dict(mesh_on))
        program_cache.drain_compile_events()
        fused_df = build(s_fused, qname)
        fused_tbl = _canon(fused_df.to_arrow())
        cold = _spmd_compiles(program_cache.drain_compile_events())
        stages = _metric_sum(fused_df, "spmdStages")
        fused_bytes = _metric_sum(fused_df, "collectiveBytes")
        degraded = _metric_sum(fused_df, "spmdDegraded")
        # warm rerun: fresh query tree, same session — the mesh-keyed
        # program cache must serve every stage program without compiling
        warm_df = build(s_fused, qname)
        warm_tbl = _canon(warm_df.to_arrow())
        warm = _spmd_compiles(program_cache.drain_compile_events())

        q = {
            "rows": host.num_rows,
            "spmd_stages": stages,
            "collective_bytes_fused": fused_bytes,
            "collective_bytes_round": round_bytes,
            "mesh_rounds_round_path": round_rounds,
            "programs_compiled_cold": cold,
            "programs_compiled_warm": warm,
            "spmd_degraded": degraded,
            "parity_fused_vs_host": fused_tbl.equals(host),
            "parity_round_vs_host": round_tbl.equals(host),
            "parity_warm_rerun": warm_tbl.equals(host),
        }
        q["ok"] = bool(q["parity_fused_vs_host"]
                       and q["parity_round_vs_host"]
                       and q["parity_warm_rerun"]
                       and stages > 0 and degraded == 0
                       and cold > 0 and warm == 0)
        doc["queries"][qname] = q
        doc["ok"] = doc["ok"] and q["ok"]
        print(f"spmd_bench: {qname} rows={q['rows']} stages={stages} "
              f"cold={cold} warm={warm} ok={q['ok']}", file=sys.stderr)

    print(json.dumps(doc))
    return 0 if doc["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
