"""Scale-test harness: configurable-size synthetic workloads with
per-query timing JSON (analog of the reference's datagen/ScaleTest.md
scale test: complexity-scaled data generation + a fixed query battery
reporting elapsed times for regression tracking).

Usage:
    python -m spark_rapids_tpu.workloads.scale_test \
        --scale 1.0 --data-dir /tmp/srtpu-scale --out report.json

Scale 1.0 ~= 6M lineitem rows; data generates once per (scale, seed)
and is reused. Each query runs `iterations` times (first = cold,
including compile; min of the rest = hot) and the report carries
rows/s so runs at different scales compare."""
from __future__ import annotations

import json
import os
import time

__all__ = ["run_scale_test", "QUERIES"]


def _ensure_data(session, data_dir: str, scale: float, seed: int):
    from . import tpch
    os.makedirs(data_dir, exist_ok=True)
    marker = os.path.join(data_dir, f"_ready_sf{scale}_s{seed}")
    tables = {}
    gens = {
        "lineitem": lambda: tpch.gen_lineitem(sf=scale, seed=seed,
                                              full=True),
        "orders": lambda: tpch.gen_orders(sf=scale, seed=seed,
                                          full=True),
        "customer": lambda: tpch.gen_customer(sf=scale, seed=seed,
                                              full=True),
    }
    for name, gen in gens.items():
        path = os.path.join(data_dir, name)
        if not os.path.exists(marker):
            df = session.create_dataframe(gen())
            df.write.mode("overwrite").parquet(path)
        tables[name] = path
    open(marker, "w").close()
    return tables


def _q_scan_agg(s, t):
    import spark_rapids_tpu.functions as F
    from spark_rapids_tpu.functions import col
    df = s.read.parquet(t["lineitem"])
    return df.group_by("l_returnflag").agg(
        F.sum(col("l_extendedprice")).alias("rev"),
        F.avg(col("l_discount")).alias("ad"),
        F.count(col("l_quantity")).alias("n")).to_arrow()


def _q_filter_project(s, t):
    from spark_rapids_tpu.functions import col
    df = s.read.parquet(t["lineitem"])
    return df.filter((col("l_discount") >= 0.05)
                     & (col("l_quantity") < 24)).select(
        (col("l_extendedprice") * (1 - col("l_discount")))
        .alias("x")).to_arrow()


def _q_join_agg(s, t):
    import spark_rapids_tpu.functions as F
    from spark_rapids_tpu.functions import col
    li = s.read.parquet(t["lineitem"])
    od = s.read.parquet(t["orders"])
    j = li.join(od, on=(col("l_orderkey") == col("o_orderkey")))
    return j.group_by("o_orderpriority").agg(
        F.sum(col("l_extendedprice")).alias("rev")).to_arrow()


def _q_window(s, t):
    from spark_rapids_tpu.window import Window, win_sum, row_number
    from spark_rapids_tpu.functions import col
    df = s.read.parquet(t["orders"])
    w = Window.partition_by("o_orderpriority").order_by("o_orderdate")
    return df.select(
        col("o_orderkey"),
        row_number().over(w).alias("rn"),
        win_sum(col("o_totalprice").cast("double")).over(w)
        .alias("run"),
    ).to_arrow()


def _q_sort_limit(s, t):
    df = s.read.parquet(t["lineitem"])
    return df.sort("l_extendedprice", ascending=False).limit(100) \
        .to_arrow()


QUERIES = {
    "scan_agg": _q_scan_agg,
    "filter_project": _q_filter_project,
    "join_agg": _q_join_agg,
    "window": _q_window,
    "sort_limit": _q_sort_limit,
}


def run_scale_test(scale: float = 0.1, data_dir: str = "/tmp/srtpu-scale",
                   iterations: int = 3, seed: int = 0,
                   conf: dict = None, queries=None) -> dict:
    import spark_rapids_tpu as st
    s = st.TpuSession(conf or {})
    tables = _ensure_data(s, data_dir, scale, seed)
    li_rows = s.read.parquet(tables["lineitem"]).count()
    report = {"scale": scale, "lineitem_rows": li_rows, "queries": {}}
    for name in (queries or QUERIES):
        fn = QUERIES[name]
        times = []
        out_rows = 0
        for _ in range(max(1, iterations)):
            t0 = time.perf_counter()
            out = fn(s, tables)
            times.append(time.perf_counter() - t0)
            out_rows = out.num_rows
        hot = min(times[1:]) if len(times) > 1 else times[0]
        report["queries"][name] = {
            "cold_s": round(times[0], 4),
            "hot_s": round(hot, 4),
            "output_rows": out_rows,
            "input_rows_per_sec": round(li_rows / hot, 1),
        }
    return report


def main():
    import argparse
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--scale", type=float, default=0.1)
    ap.add_argument("--data-dir", default="/tmp/srtpu-scale")
    ap.add_argument("--iterations", type=int, default=3)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default=None)
    ap.add_argument("--platform", default=None,
                    help="jax platform override (e.g. 'cpu'); a broken "
                         "TPU tunnel hangs backend init otherwise")
    args = ap.parse_args()
    if args.platform:
        import jax
        jax.config.update("jax_platforms", args.platform)
    rep = run_scale_test(args.scale, args.data_dir, args.iterations,
                         args.seed)
    text = json.dumps(rep, indent=2)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
    print(text)


if __name__ == "__main__":
    main()
