"""Background XLA compilation: a bounded pool that moves the compile
tail off the dispatch path.

BENCH_r05 put numbers on the cold tail: q4 compiles 211 programs to do
14 ms of work. The programs are all known *before* they are needed —
the planner fixes every stage's program key at launch, and a service
restart knows yesterday's whole key set (runtime/warm_pack.py) — so
compilation is an amortizable, pipelinable cost, not an inline one
(spark-rapids pre-builds cudf kernels per process; Theseus overlaps
every non-compute cost with the pipeline). This pool is the overlap
mechanism:

- **stage-ahead** tasks: at query launch the physical tree's
  `prewarm_programs()` hooks submit downstream stage programs; they
  compile on `tpu-compile-N` daemon threads while upstream stages
  execute (XLA's C++ compiler releases the GIL).
- **speculative** tasks: warm-pack preload at service startup. These
  are admission-aware — a busy hook (wired to the QueryManager's
  running count) defers them while any query is running, so a running
  query's dispatch never competes with speculative compilation. They
  are also per-topology: the pack fingerprint (warm_pack._fingerprint)
  includes the mesh identity, so an 8-device service process preloads
  sharded collective programs (SpmdStageExec / MeshExchangeExec, keyed
  on mesh_topology_key) recorded on the SAME topology, and a pack from
  a different mesh never spends this pool's budget.

The dispatch path NEVER waits on this pool: `CachedProgram.__call__`
compiles inline on a miss exactly as before — a duplicate compile is
accepted over a stall — and `CachedProgram.prewarm` stores only when
the key is still absent. Background failures (including injected
`xla.compile` faults, which fire in prewarm with `background=True`)
are swallowed here and counted
(`program_cache_background_failures`); the query that needed the
program falls back to the sync path and is never affected.

Cancellation is cooperative: tasks carry the submitting query's id,
`cancel_query()` drops its queued-not-started tasks (the service calls
it when a query dies), and `shutdown()` drains the queue and joins the
workers (tests, interpreter exit).
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Callable, List, Optional

from . import lockdep

__all__ = ["CompilePool", "get_pool", "current_pool", "shutdown_pool",
           "set_busy_hook"]


class _Task:
    __slots__ = ("prog", "args_thunk", "speculative", "query_id",
                 "cancelled", "trace")

    def __init__(self, prog, args_thunk, speculative, query_id,
                 trace=None):
        self.prog = prog
        self.args_thunk = args_thunk    # () -> example args (built lazily
        self.speculative = speculative  # on the worker, not the submitter)
        self.query_id = query_id
        self.cancelled = False
        # submitter's TraceContext: background compiles show up in the
        # submitting query's trace (profiler/tracing.py)
        self.trace = trace


class CompilePool:
    """Bounded background compile pool; one per process (get_pool)."""

    def __init__(self, threads: int = 2, queue_cap: int = 256):
        self._lock = lockdep.lock("CompilePool._lock")
        self._cv = threading.Condition(self._lock)
        self._queue: "deque[_Task]" = deque()
        self._queue_cap = max(8, int(queue_cap))
        self._stop = False
        self._busy_hook: Optional[Callable[[], bool]] = None
        self._idle = threading.Event()
        self._idle.set()
        self._active = 0
        self.stats = {"submitted": 0, "compiled": 0, "already_warm": 0,
                      "failed": 0, "cancelled": 0, "dropped_full": 0,
                      "deferred_busy": 0}
        self._threads: List[threading.Thread] = []
        for i in range(max(1, int(threads))):
            t = threading.Thread(target=self._worker, daemon=True,
                                 name=f"tpu-compile-{i}")
            t.start()
            self._threads.append(t)

    # ------------------------------------------------------------------
    def set_busy_hook(self, hook: Optional[Callable[[], bool]]) -> None:
        """`hook() == True` means queries are running: speculative
        tasks wait; stage-ahead tasks (for those very queries) run."""
        # tpulint: allow[unlocked-shared-write] single reference swap; _busy() snapshots into a local before calling
        self._busy_hook = hook

    def _busy(self) -> bool:
        hook = self._busy_hook
        if hook is None:
            return False
        try:
            return bool(hook())
        except Exception:
            return False

    # ------------------------------------------------------------------
    def submit(self, prog, args_thunk: Callable[[], tuple],
               speculative: bool = False,
               query_id: Optional[str] = None) -> bool:
        """Enqueue one prewarm. Never blocks: a full queue drops the
        task (the sync path compiles it later; counted dropped_full)."""
        from ..profiler import tracing
        task = _Task(prog, args_thunk, speculative, query_id,
                     trace=tracing.current())
        with self._cv:
            if self._stop or len(self._queue) >= self._queue_cap:
                self.stats["dropped_full"] += 1
                return False
            self._queue.append(task)
            self.stats["submitted"] += 1
            self._idle.clear()
            self._cv.notify()
        return True

    def cancel_query(self, query_id: Optional[str]) -> int:
        """Drop queued-not-started tasks submitted by `query_id`
        (cooperative: a task already compiling runs to completion —
        the result is cached for the retry)."""
        if query_id is None:
            return 0
        n = 0
        with self._cv:
            for t in self._queue:
                if t.query_id == query_id and not t.cancelled:
                    t.cancelled = True
                    n += 1
            if n:
                self.stats["cancelled"] += n
        return n

    def drain(self, timeout: float = 30.0) -> bool:
        """Block until the queue is empty and workers are idle (tests,
        bench --compile-tail). Returns False on timeout."""
        return self._idle.wait(timeout)

    def shutdown(self) -> None:
        with self._cv:
            self._stop = True
            n = sum(1 for t in self._queue if not t.cancelled)
            self.stats["cancelled"] += n
            self._queue.clear()
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=5.0)

    # ------------------------------------------------------------------
    def _worker(self) -> None:
        from . import program_cache
        while True:
            with self._cv:
                while not self._queue and not self._stop:
                    if not self._active:
                        self._idle.set()
                    self._cv.wait(timeout=0.5)
                if self._stop:
                    if not self._active:
                        self._idle.set()
                    return
                task = self._queue[0]
                if task.speculative and not task.cancelled \
                        and self._busy():
                    # admission-aware: speculative work yields to
                    # running queries. Rotate it to the tail so
                    # stage-ahead tasks behind it still run, and park
                    # briefly so a long-running query cannot spin us
                    self.stats["deferred_busy"] += 1
                    self._queue.rotate(-1)
                    self._cv.wait(timeout=0.05)
                    continue
                self._queue.popleft()
                if task.cancelled:
                    continue
                self._active += 1
            try:
                args = task.args_thunk()
                if args is None:
                    with self._cv:
                        self.stats["already_warm"] += 1
                else:
                    # the span lands in the SUBMITTING query's trace
                    # (task.trace rode along from submit); no-op when
                    # that query ran untraced
                    from ..profiler import tracing
                    with tracing.span("xla.prewarm", "compile",
                                      task.trace, bg=1) as sp:
                        compiled = task.prog.prewarm(args)
                        sp.set("compiled", bool(compiled))
                    with self._cv:
                        self.stats["compiled" if compiled
                                   else "already_warm"] += 1
            except Exception:
                # swallowed by contract: background compilation must
                # never fail a query (the sync path recompiles);
                # injected xla.compile faults land here
                program_cache.note_background_failure()
                with self._cv:
                    self.stats["failed"] += 1
            finally:
                with self._cv:
                    self._active -= 1
                    if not self._queue and not self._active:
                        self._idle.set()


# ---------------------------------------------------------------------
# process-global pool
# ---------------------------------------------------------------------
_pool: Optional[CompilePool] = None
_pool_lock = threading.Lock()
_pending_busy_hook: Optional[Callable[[], bool]] = None


def get_pool(conf) -> Optional[CompilePool]:
    """The process pool, created on first use from `conf`'s thread
    count; None when sql.exec.compilePool.enabled is off (callers skip
    prewarming entirely)."""
    global _pool
    from ..config import COMPILE_POOL_ENABLED, COMPILE_POOL_THREADS
    if not bool(conf.get(COMPILE_POOL_ENABLED)):
        return None
    if _pool is None:
        with _pool_lock:
            if _pool is None:
                _pool = CompilePool(
                    threads=int(conf.get(COMPILE_POOL_THREADS)))
                if _pending_busy_hook is not None:
                    _pool.set_busy_hook(_pending_busy_hook)
    return _pool


def current_pool() -> Optional[CompilePool]:
    """The live pool, if one was ever created — never creates (failure
    paths use this to cancel a dead query's queued prewarms)."""
    return _pool


def set_busy_hook(hook: Optional[Callable[[], bool]]) -> None:
    """Install the admission-awareness hook (the session wires the
    QueryManager's running count here); applies to the live pool and
    to one created later."""
    global _pending_busy_hook
    _pending_busy_hook = hook
    with _pool_lock:
        if _pool is not None:
            _pool.set_busy_hook(hook)


def shutdown_pool() -> None:
    """Tear down the process pool (tests)."""
    global _pool
    with _pool_lock:
        p, _pool = _pool, None
    if p is not None:
        p.shutdown()
