"""AOT warm packs: persist the program-cache key set, preload it at
service startup.

PR 6's persistent XLA cache (`.jax_cache/host-<fp>`) made *re*-compiles
across processes cheap, but a fresh service still pays the full trace +
cache-deserialize tail inline, on the first user-visible query per
shape. A warm pack moves that tail to startup: a recording session
writes a manifest of (a) the SQL texts it served and (b) every stable
program-cache key it compiled, with a zero-fill recipe for each key's
input signature (`program_cache._args_spec`). Preload re-plans the
recorded SQL — reconstructing the builder closures and repopulating the
program-cache registry — then compiles every recorded signature through
the background pool (`runtime/compile_pool.py`) as SPECULATIVE tasks,
so a query arriving mid-preload is never queued behind warm-up work.

Safety posture mirrors the persistent cache it extends:

- the manifest is bound to `_cache_fingerprint()` (CPU model + features
  + jaxlib) and a format version; a mismatch logs one warning and
  preloads nothing — programs traced for another microarchitecture
  must not be reconstructed here.
- a corrupt/unreadable pack logs a warning, never raises: warm-up is
  advisory.
- keys carrying identity fallbacks (`('id', N)` / `('inst', N)`) are
  excluded at record time — they cannot match across processes (the
  `unstable-program-key` lint rule polices the sources).
- `SRTPU_COMPILE_CACHE=0` hard-disables record and preload alongside
  the persistent cache.
- preload is idempotent: `CachedProgram.prewarm` skips keys that are
  already warm, so restarting a service against the same pack re-does
  no work.
"""
from __future__ import annotations

import logging
import os
import pickle
import threading
from typing import Optional

__all__ = ["VERSION", "enabled", "record_path", "note_query", "save",
           "preload", "recorded_queries", "reset", "build_manifest",
           "preload_manifest"]

log = logging.getLogger(__name__)

VERSION = 1

_lock = threading.Lock()
_queries: list = []          # recorded sql texts, insertion-ordered
_queries_set: set = set()
_QUERIES_CAP = 256


def enabled() -> bool:
    """False when SRTPU_COMPILE_CACHE=0: the warm pack is an extension
    of the persistent compile cache and obeys its kill switch."""
    return os.environ.get("SRTPU_COMPILE_CACHE") != "0"


def record_path(conf) -> Optional[str]:
    from ..config import WARM_PACK_RECORD
    p = str(conf.get(WARM_PACK_RECORD) or "").strip()
    return p if p and enabled() else None


def note_query(sql_text: str, conf) -> None:
    """Record one served SQL text (session.sql calls this when
    sql.service.warmPack.record is set)."""
    if not sql_text or record_path(conf) is None:
        return
    with _lock:
        if sql_text in _queries_set or len(_queries) >= _QUERIES_CAP:
            return
        _queries.append(sql_text)
        _queries_set.add(sql_text)


def recorded_queries() -> list:
    with _lock:
        return list(_queries)


def reset() -> None:
    """Drop recorded state (tests)."""
    with _lock:
        del _queries[:]
        _queries_set.clear()


def _fingerprint() -> str:
    from .. import _cache_fingerprint
    from ..parallel.mesh import mesh_fingerprint
    # packs are per-topology: a manifest recorded against an 8-device
    # mesh carries sharded collective signatures that can never warm a
    # 1-device process (and would waste its compile-pool budget), so
    # the device kind + visible device count gates the load
    return _cache_fingerprint() + "|" + mesh_fingerprint()


def build_manifest(conf=None) -> dict:
    """The manifest dict `save` persists — also the fleet warm-state
    payload a member serves to a joining peer (fleet/member.py), which
    ships it over the wire instead of through a file. Same content
    either way: recorded SQL + every stable observed program spec,
    bound to this host's cache/mesh fingerprint (the RECEIVER gates on
    it, exactly like load_manifest)."""
    from . import program_cache
    programs = [p for p in program_cache.observed_programs()
                if program_cache.key_stable(p["base_key"])]
    return {"version": VERSION, "fingerprint": _fingerprint(),
            "queries": recorded_queries(), "programs": programs}


def save(conf, path: Optional[str] = None) -> Optional[str]:
    """Write the manifest: recorded SQL + every stable observed program
    spec. Returns the path written, or None when recording is disabled
    and no explicit path was given. Atomic (tmp + rename): a reader
    never sees a half-written pack."""
    if not enabled():
        return None
    path = path or record_path(conf)
    if not path:
        return None
    manifest = build_manifest(conf)
    tmp = f"{path}.tmp.{os.getpid()}"
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(tmp, "wb") as f:
        pickle.dump(manifest, f)
    os.replace(tmp, path)
    return path


def load_manifest(path: str) -> Optional[dict]:
    """Read + validate a pack. None (with one warning) on any problem:
    missing file, unpicklable bytes, wrong version, wrong host
    fingerprint — a warm pack must never take the service down."""
    if not enabled():
        return None
    try:
        with open(path, "rb") as f:
            m = pickle.load(f)
    except FileNotFoundError:
        log.warning("warm pack %s not found; starting cold", path)
        return None
    except Exception as e:  # noqa: BLE001 — corrupt pack is advisory
        log.warning("warm pack %s is unreadable (%r); starting cold",
                    path, e)
        return None
    return m if _validate_manifest(m, path) else None


def _validate_manifest(m, source: str) -> bool:
    """Version + host-fingerprint gate, shared by the file path and
    the fleet wire path — a peer's manifest is as foreign as a file
    recorded on another box and gets exactly the same scrutiny."""
    if not isinstance(m, dict) or m.get("version") != VERSION:
        log.warning("warm pack %s has version %r (want %d); ignoring",
                    source, m.get("version") if isinstance(m, dict)
                    else None, VERSION)
        return False
    fp = _fingerprint()
    if m.get("fingerprint") != fp:
        log.warning(
            "warm pack %s was recorded on host fingerprint %s; this "
            "host is %s — programs may embed foreign microarch target "
            "options, ignoring the pack", source,
            m.get("fingerprint"), fp)
        return False
    return True


def preload(session, path: Optional[str] = None) -> dict:
    """Replay the pack's queries (rebuilding — and, by default,
    compiling — every program in their trees), then background-compile
    any recorded signature still cold. Returns a summary dict;
    {"status": "skipped"} when disabled/invalid. Never raises."""
    from ..config import WARM_PACK_PATH
    conf = session.conf
    path = path or str(conf.get(WARM_PACK_PATH) or "").strip()
    if not path or not enabled():
        return {"status": "skipped"}
    m = load_manifest(path)
    if m is None:
        return {"status": "skipped"}
    return preload_manifest(session, m, validated=True)


def preload_manifest(session, m: dict, validated: bool = False) -> dict:
    """Preload from an in-memory manifest (the fleet cold-join pull
    hands the donor's manifest straight here). Validates unless the
    caller already did."""
    if not enabled() or m is None:
        return {"status": "skipped"}
    if not validated and not _validate_manifest(m, "<peer>"):
        return {"status": "skipped"}
    from ..config import WARM_PACK_REPLAY
    conf = session.conf
    from . import compile_pool, program_cache
    # seed the observed-spec table first: even for sites the replay
    # below cannot resolve to a live program (missing tables on this
    # host), launch-time stage-ahead prewarm can still find the
    # recorded signatures when a real query constructs the site
    seeded = program_cache.seed_observed(m.get("programs", ()))
    replay = bool(conf.get(WARM_PACK_REPLAY))
    planned = 0
    roots = []
    for sql in m.get("queries", ()):
        try:
            df = session.sql(sql)
            if replay:
                # full replay: one throwaway execution compiles every
                # program the query dispatches, including the ones
                # built lazily inside execute_partition that a
                # plan-only pass cannot reach. Runs through normal
                # admission, so the busy hook parks speculative pool
                # work during it.
                df.collect()
            else:
                # plan-only: constructs the exec tree — every
                # construction-time cached_program registers its
                # base_key. Roots are retained on the summary so the
                # registry entries stay alive until the prewarms run.
                root, _ = df._execute(conf)
                roots.append(root)
            planned += 1
        except Exception:
            # table moved / data absent on this host: warm what we can
            continue
    pool = compile_pool.get_pool(conf)
    matched = submitted = 0
    for entry in m.get("programs", ()):
        try:
            prog = program_cache.lookup_program(entry["base_key"])
        except TypeError:
            prog = None
        if prog is None:
            continue
        matched += 1
        thunk = program_cache.prewarm_thunk(prog, entry["spec"])
        if pool is None:
            # pool disabled: compile inline at startup (still off the
            # query path — we ARE startup)
            try:
                args = thunk()
                if args is not None:
                    prog.prewarm(args)
                submitted += 1
            except Exception:
                program_cache.note_background_failure()
            continue
        if pool.submit(prog, thunk, speculative=True):
            submitted += 1
    summary = {"status": "ok", "queries": len(m.get("queries", ())),
               "queries_planned": planned, "seeded": seeded,
               "programs": len(m.get("programs", ())),
               "programs_matched": matched, "submitted": submitted,
               "_roots": roots}
    return summary
