"""Runtime data-race witness: Eraser locksets on live shared state.

The static half (analysis/races.py) proves lockset properties about
code shapes; this module watches the accesses the engine ACTUALLY
performs. Modeled on Eraser: each instrumented shared structure keeps
per-(structure, key) state that starts *exclusive* to its first
thread, turns *shared* when a second thread arrives, and from then on
refines a candidate lockset — the intersection of the locks held at
every access. A write to shared state whose candidate lockset has
collapsed to empty is a witnessed race: two threads reached the same
slot with no common lock, and only scheduling luck ordered them.

Instrumented structures (each a `note_access` call at the access
site, one None-check when the witness is off):

- program cache observed-spec table (runtime/program_cache.py)
- live telemetry registry (profiler/telemetry.py)
- result-cache LRU (runtime/result_cache.py)
- local shuffle map-file slots (shuffle/local.py)
- operator MetricSet values (utils/metrics.py)

Lockset tracking rides the lockdep factories: every lock created
through `lockdep.lock()/rlock()` reports acquire/release into this
module's thread-local held-set (`note_lock`/`note_unlock`), so a
lockdep-wrapped lock is visible to BOTH witnesses. Each access records
(thread-context, lockset) — the last few per slot are kept for the
finding message, mirroring what the static report prints.

Schedule perturbation: `perturb(seed)` arms a seeded adversarial mode
— `sys.setswitchinterval` drops to microseconds and instrumented
access points inject `time.sleep(0)` yields chosen by a seeded RNG —
so interleavings that would need days of wall clock to occur by
chance happen in one `bench --chaos` pass, which then asserts
byte-identity and balanced ledgers under them.

Enablement: env ``SRTPU_RACEDEP=1`` BEFORE the engine imports
(conftest.py sets it record-only for the tier-1 suite), or conf
``spark.rapids.tpu.sql.debug.racedep.enabled`` at session
construction (``...racedep.raiseOnRace`` picks raise-vs-record).
Disabled, every hook is one None-check — zero overhead. Enabled
overhead is budgeted <3% of q6 wall (tests/test_racedep.py gates it):
the access fast path is a dict probe plus a set intersection under
one mutex, on structures that are touched per batch, not per row.
"""
from __future__ import annotations

import os
import random
import sys
import threading
import time
from typing import Dict, List, Optional

__all__ = ["DataRaceDetected", "Witness", "witness", "enabled",
           "enable", "disable", "note_access", "note_lock",
           "note_unlock", "perturb", "restore", "maybe_enable_from_conf"]

_ENV = "SRTPU_RACEDEP"

#: per-(structure, key) states tracked before new keys fold into "*"
_VARS_CAP = 4096
#: (thread, lockset, op) samples kept per slot for finding messages
_HISTORY = 4


class DataRaceDetected(RuntimeError):
    """A write reached shared state with a collapsed lockset."""


class _VarState:
    """Eraser state machine for one (structure, key) slot."""

    __slots__ = ("owner", "shared", "modified", "lockset", "reported",
                 "history")

    def __init__(self, owner: str):
        self.owner = owner            # first thread: exclusive phase
        self.shared = False
        self.modified = False
        self.lockset: Optional[set] = None   # candidate; None = virgin
        self.reported = False
        self.history: List[tuple] = []


class Witness:
    """Process-global Eraser table + per-thread held locksets."""

    def __init__(self, raise_on_race: bool = True):
        self.raise_on_race = raise_on_race
        self._mu = threading.Lock()   # guards the var table only; never
        # held while touching an engine lock (same discipline as lockdep)
        self._vars: Dict[tuple, _VarState] = {}
        self._tls = threading.local()
        self.findings: List[dict] = []
        self.accesses = 0
        # perturbation state
        self._rng: Optional[random.Random] = None
        self._yield_prob = 0.0
        self._orig_interval: Optional[float] = None

    # -- lockset tracking ----------------------------------------------
    def _held(self) -> list:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = []
            self._tls.held = held
        return held

    def lock_acquired(self, key: str):
        self._held().append(key)

    def lock_released(self, key: str):
        held = getattr(self._tls, "held", None)
        if not held:
            return
        for i in range(len(held) - 1, -1, -1):
            if held[i] == key:
                del held[i]
                return

    def held_keys(self) -> List[str]:
        return list(getattr(self._tls, "held", None) or ())

    # -- access recording ----------------------------------------------
    def access(self, structure: str, key: str = "", write: bool = False):
        """Record one access to (structure, key) by the current thread
        with its current lockset; raise on lockset collapse."""
        self._maybe_yield()
        tname = threading.current_thread().name
        held = frozenset(self._held())
        finding = None
        with self._mu:
            self.accesses += 1
            vk = (structure, key)
            st = self._vars.get(vk)
            if st is None:
                if len(self._vars) >= _VARS_CAP:
                    vk = (structure, "*")
                    st = self._vars.get(vk)
                if st is None:
                    st = self._vars[vk] = _VarState(tname)
            if len(st.history) >= _HISTORY:
                del st.history[0]
            st.history.append((tname, sorted(held),
                               "w" if write else "r"))
            if tname == st.owner and not st.shared:
                # exclusive phase: init writes before hand-off are fine
                st.modified = st.modified or write
            else:
                if not st.shared:
                    # second thread: sharing starts, lockset candidate
                    # initializes to THIS access's held set
                    st.shared = True
                    st.lockset = set(held)
                else:
                    st.lockset &= held
                st.modified = st.modified or write
                if st.modified and not st.lockset and not st.reported:
                    st.reported = True
                    finding = {
                        "kind": "lockset-collapse",
                        "structure": structure,
                        "key": str(key),
                        "thread": tname,
                        "write": write,
                        "history": list(st.history),
                    }
                    self.findings.append(finding)
        if finding is not None and self.raise_on_race:
            hist = "; ".join(
                f"{t}[{','.join(ls) or '-'}]{op}"
                for t, ls, op in finding["history"])
            raise DataRaceDetected(
                f"lockset collapse on {structure}[{finding['key']}]: "
                f"{'write' if write else 'read'} from thread {tname} "
                f"leaves no common lock across sharing threads "
                f"(recent accesses: {hist})")

    # -- schedule perturbation -----------------------------------------
    def perturb(self, seed: int, yield_prob: float = 0.05,
                switch_interval: float = 1e-5):
        """Arm seeded adversarial scheduling: tiny bytecode switch
        interval plus RNG-chosen yields at instrumented accesses."""
        self._rng = random.Random(seed)
        self._yield_prob = float(yield_prob)
        if self._orig_interval is None:
            self._orig_interval = sys.getswitchinterval()
        sys.setswitchinterval(switch_interval)

    def restore(self):
        self._rng = None
        self._yield_prob = 0.0
        if self._orig_interval is not None:
            sys.setswitchinterval(self._orig_interval)
            self._orig_interval = None

    def _maybe_yield(self):
        rng = self._rng
        if rng is None:
            return
        with self._mu:
            hit = rng.random() < self._yield_prob
        if hit:
            time.sleep(0)

    # -- reporting -----------------------------------------------------
    def report(self) -> dict:
        """Summary counters for the race_report event and bench
        extra.chaos."""
        with self._mu:
            shared = sum(1 for s in self._vars.values() if s.shared)
            return {"enabled": True, "tracked": len(self._vars),
                    "shared": shared, "accesses": self.accesses,
                    "findings": len(self.findings),
                    "perturbed": self._rng is not None}


# ---------------------------------------------------------------------
# process-global enablement
# ---------------------------------------------------------------------
_WITNESS: Optional[Witness] = None


def enabled() -> bool:
    return _WITNESS is not None


def witness() -> Optional[Witness]:
    return _WITNESS


def enable(raise_on_race: bool = True) -> Witness:
    """Idempotent; locks created BEFORE this are not lockset-visible,
    so enable before importing the engine (conftest/env) for full
    coverage."""
    global _WITNESS
    if _WITNESS is None:
        _WITNESS = Witness(raise_on_race=raise_on_race)
    return _WITNESS


def disable():
    global _WITNESS
    _WITNESS = None


def maybe_enable_from_conf(conf):
    """Session-construction hook for sql.debug.racedep.* confs."""
    from ..config import RACEDEP_ENABLED, RACEDEP_RAISE
    if conf.get(RACEDEP_ENABLED):
        enable(raise_on_race=bool(conf.get(RACEDEP_RAISE)))


# ---------------------------------------------------------------------
# note hooks: one None-check when the witness is off
# ---------------------------------------------------------------------
def note_access(structure: str, key: str = "", write: bool = False):
    w = _WITNESS
    if w is not None:
        w.access(structure, key, write)


def note_lock(key: str):
    w = _WITNESS
    if w is not None:
        w.lock_acquired(key)


def note_unlock(key: str):
    w = _WITNESS
    if w is not None:
        w.lock_released(key)


def perturb(seed: int, yield_prob: float = 0.05,
            switch_interval: float = 1e-5):
    w = _WITNESS
    if w is not None:
        w.perturb(seed, yield_prob, switch_interval)


def restore():
    w = _WITNESS
    if w is not None:
        w.restore()


# env-gated enablement at import: sees every lock created after this
# module loads (conftest sets the env before importing the engine)
if os.environ.get(_ENV, "").strip().lower() in ("1", "true", "yes", "on"):
    enable(raise_on_race=os.environ.get(
        _ENV + "_RAISE", "1").strip().lower() in ("1", "true", "yes",
                                                  "on"))
