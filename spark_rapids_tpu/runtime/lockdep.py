"""Runtime lockdep witness: observe real lock orderings, catch cycles.

The static half (analysis/concurrency.py) proves properties about code
shapes; this module watches the orderings the engine ACTUALLY takes.
Modeled on the Linux kernel's lockdep: resources are keyed by CLASS
(``ShuffleExchangeExec._lock``, ``TpuSemaphore.permit``), not instance,
so one observed ordering validates every instance pair. Each thread
keeps a held-stack; acquiring B while holding A inserts the order edge
A -> B into a process-global graph, and an insertion that closes a
cycle is reported (and raised) at FORMATION time — long before the
interleaving that would actually deadlock.

Three deadlock classes from the engine's history are covered:

- lock-order cycles: edge insertion runs a reachability check; a
  B ->* A path plus the new A -> B edge is a cycle. Same-class edges
  (chained exchanges nesting `ShuffleExchangeExec._lock` inside itself
  via child materialization) are benign nesting and skipped, which
  also means a true same-class ABBA between two INSTANCES is not
  witnessed — the static pass covers that shape instead.
- pool self-wait (the PR 8 q2 bug): `check_pool_wait(prefix)` guards a
  Future.result on a bounded pool; called FROM a worker of that same
  pool it reports the wait-cycle instead of letting the bounded pool
  park every worker behind itself.
- attribution on deadline kill: `dump()` snapshots every live thread
  (named per satellite 1) with its held resources and current frame,
  and CancelToken deadline kills attach it to QueryTimedOut and the
  event log, replacing the bare-timeout debugging of PR 8.

Enablement: env ``SRTPU_LOCKDEP=1`` BEFORE the engine imports (locks
are wrapped at creation; conftest.py sets it for the whole tier-1
suite), or conf ``spark.rapids.tpu.sql.debug.lockdep.enabled`` at
session construction. Disabled, `lock()`/`rlock()` return plain
threading primitives and the note hooks are one None-check — zero
overhead. Enabled overhead is budgeted <3% of tier-1 suite wall: the
acquire fast path is a TLS list append plus one set-membership probe;
the graph mutex is only taken for never-seen edges.
"""
from __future__ import annotations

import os
import sys
import threading
from typing import Dict, List, Optional

from . import racedep

__all__ = ["LockOrderViolation", "PoolSelfWait", "Witness", "witness",
           "enabled", "enable", "disable", "lock", "rlock",
           "note_acquired", "note_released", "check_pool_wait",
           "attach_dump", "format_dump"]

_ENV = "SRTPU_LOCKDEP"


class LockOrderViolation(RuntimeError):
    """A lock acquisition closed a cycle in the global order graph."""


class PoolSelfWait(RuntimeError):
    """A bounded pool worker blocked on a future of its own pool."""


class Witness:
    """Process-global acquisition-order graph + per-thread held stacks."""

    def __init__(self, raise_on_finding: bool = True):
        self.raise_on_finding = raise_on_finding
        self._mu = threading.Lock()     # guards graph mutation only;
        # NEVER held while touching an engine lock (the witness must
        # not itself create orderings)
        self._succ: Dict[str, set] = {}
        self._edges: set = set()        # {(a, b)} fast membership probe
        self._tls = threading.local()
        # ident -> (thread name, held list) — live view for dump();
        # entries are the same list objects the TLS mutates
        self._held_by: Dict[int, tuple] = {}
        self.findings: List[dict] = []
        self.acquires = 0
        self.max_edges = 0

    # -- held tracking ------------------------------------------------
    def _held(self) -> list:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = []
            self._tls.held = held
            t = threading.current_thread()
            self._held_by[t.ident] = (t.name, held)
        return held

    def acquired(self, key: str):
        """Record that the current thread now holds `key`."""
        held = self._held()
        self.acquires += 1
        if held and key not in held:
            for h in held:
                if (h, key) not in self._edges:
                    self._add_edge(h, key)
        held.append(key)

    def released(self, key: str):
        held = getattr(self._tls, "held", None)
        if not held:
            return
        for i in range(len(held) - 1, -1, -1):
            if held[i] == key:
                del held[i]
                return

    def held_keys(self) -> List[str]:
        return list(getattr(self._tls, "held", None) or ())

    # -- order graph --------------------------------------------------
    def _add_edge(self, a: str, b: str):
        if a == b:
            return  # benign same-class nesting (chained exchanges)
        cycle = None
        with self._mu:
            if (a, b) in self._edges:
                return
            cycle = self._find_path(b, a)
            self._edges.add((a, b))
            self._succ.setdefault(a, set()).add(b)
            if len(self._edges) > self.max_edges:
                self.max_edges = len(self._edges)
        if cycle is not None:
            finding = {
                "kind": "lock-order-cycle",
                "edge": [a, b],
                "cycle": cycle + [b],
                "thread": threading.current_thread().name,
            }
            self.findings.append(finding)
            if self.raise_on_finding:
                raise LockOrderViolation(
                    f"lock-order cycle formed by {a} -> {b} on thread "
                    f"{finding['thread']}: existing order "
                    f"{' -> '.join(cycle + [b])}")

    def _find_path(self, src: str, dst: str) -> Optional[List[str]]:
        """DFS path src ->* dst in the order graph (caller holds _mu)."""
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in self._succ.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    # -- pool self-wait ------------------------------------------------
    def check_pool_wait(self, pool_prefix: str):
        """Guard a blocking Future.result on the bounded pool whose
        workers are named `pool_prefix*`: waiting from one of its own
        workers is the PR 8 q2 wait-cycle."""
        name = threading.current_thread().name
        if name.startswith(pool_prefix):
            finding = {"kind": "pool-self-wait", "pool": pool_prefix,
                       "thread": name, "held": self.held_keys()}
            self.findings.append(finding)
            if self.raise_on_finding:
                raise PoolSelfWait(
                    f"thread {name} blocking on a future of its own "
                    f"bounded pool '{pool_prefix}' — wait cycle (every "
                    f"worker can park behind itself)")

    # -- reporting -----------------------------------------------------
    def dump(self) -> dict:
        """Attributed all-threads snapshot: name, held resources,
        current frame. This is what a deadline kill attaches in place
        of a bare timeout."""
        frames = sys._current_frames()
        threads = []
        for t in threading.enumerate():
            _, held = self._held_by.get(t.ident, (t.name, ()))
            fr = frames.get(t.ident)
            at = "?"
            if fr is not None:
                at = (f"{os.path.basename(fr.f_code.co_filename)}:"
                      f"{fr.f_lineno} in {fr.f_code.co_name}")
            threads.append({"thread": t.name, "daemon": t.daemon,
                            "held": list(held), "at": at})
        threads.sort(key=lambda r: (not r["held"], r["thread"]))
        return {"threads": threads, "findings": list(self.findings),
                "edges": len(self._edges)}

    def report(self) -> dict:
        """Summary counters for the concurrency_report event and
        bench extra.lockdep."""
        nodes = set()
        for a, b in self._edges:
            nodes.add(a)
            nodes.add(b)
        return {"enabled": True, "resources": len(nodes),
                "orderEdges": len(self._edges),
                "maxOrderGraph": self.max_edges,
                "acquires": self.acquires,
                "findings": len(self.findings)}


# ---------------------------------------------------------------------
# process-global enablement
# ---------------------------------------------------------------------
_WITNESS: Optional[Witness] = None


def enabled() -> bool:
    return _WITNESS is not None


def witness() -> Optional[Witness]:
    return _WITNESS


def enable(raise_on_finding: bool = True) -> Witness:
    """Idempotent; locks created BEFORE this are not instrumented, so
    enable before importing the engine (conftest/env) for full
    coverage."""
    global _WITNESS
    if _WITNESS is None:
        _WITNESS = Witness(raise_on_finding=raise_on_finding)
    return _WITNESS


def disable():
    global _WITNESS
    _WITNESS = None


def maybe_enable_from_conf(conf):
    """Session-construction hook for sql.debug.lockdep.* confs."""
    from ..config import LOCKDEP_ENABLED, LOCKDEP_RAISE
    if conf.get(LOCKDEP_ENABLED):
        enable(raise_on_finding=bool(conf.get(LOCKDEP_RAISE)))


# ---------------------------------------------------------------------
# note hooks (semaphore permits, pool ride slots): one None-check when
# the witness is off
# ---------------------------------------------------------------------
def note_acquired(key: str):
    w = _WITNESS
    if w is not None:
        w.acquired(key)


def note_released(key: str):
    w = _WITNESS
    if w is not None:
        w.released(key)


def check_pool_wait(pool_prefix: str):
    w = _WITNESS
    if w is not None:
        w.check_pool_wait(pool_prefix)


# ---------------------------------------------------------------------
# instrumented lock factories
# ---------------------------------------------------------------------
class _WitnessLock:
    """Wraps a threading lock; usable as a Condition base (the stdlib
    Condition falls back to plain acquire/release when the lock exposes
    no _release_save, which keeps held-tracking correct across
    cond.wait: the wait releases through us, so the resource is NOT
    reported held while parked)."""

    __slots__ = ("_inner", "name")

    def __init__(self, name: str, inner):
        self.name = name
        self._inner = inner

    def acquire(self, blocking=True, timeout=-1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            w = _WITNESS
            if w is not None:
                w.acquired(self.name)
            racedep.note_lock(self.name)
        return ok

    def release(self):
        w = _WITNESS
        if w is not None:
            w.released(self.name)
        racedep.note_unlock(self.name)
        self._inner.release()

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<WitnessLock {self.name} {self._inner!r}>"


def _wrapping() -> bool:
    """Wrap freshly created locks when EITHER witness is live: lockdep
    needs orderings, racedep (runtime/racedep.py) needs per-thread
    locksets — both ride the same acquire/release notes."""
    return _WITNESS is not None or racedep.enabled()


def lock(name: str):
    """A threading.Lock, witness-wrapped when lockdep or racedep is
    enabled."""
    inner = threading.Lock()
    return _WitnessLock(name, inner) if _wrapping() else inner


def rlock(name: str):
    """A threading.RLock, witness-wrapped when lockdep or racedep is
    enabled. Recursive re-entry appends the key again (no self edges),
    so the paired releases unwind correctly."""
    inner = threading.RLock()
    return _WitnessLock(name, inner) if _wrapping() else inner


# ---------------------------------------------------------------------
# dump formatting / exception attachment
# ---------------------------------------------------------------------
def format_dump(dump: dict, limit: int = 12) -> str:
    """Human-readable held-resource table for exception messages."""
    rows = []
    for r in dump.get("threads", ())[:limit]:
        held = ",".join(r["held"]) if r["held"] else "-"
        rows.append(f"  {r['thread']}: held=[{held}] at {r['at']}")
    extra = len(dump.get("threads", ())) - limit
    if extra > 0:
        rows.append(f"  ... {extra} more threads")
    return "\n".join(rows)


def attach_dump(exc: BaseException) -> Optional[dict]:
    """On deadline kill: hang the witness dump off the exception (read
    by the event log) and fold the held-resource table into its
    message. Returns the dump, or None when the witness is off or the
    exception already carries one."""
    w = _WITNESS
    if w is None or getattr(exc, "lockdep_dump", None) is not None:
        return None
    d = w.dump()
    exc.lockdep_dump = d
    try:
        text = format_dump(d)
        if text and exc.args and isinstance(exc.args[0], str):
            exc.args = (exc.args[0] + "\nlockdep threads:\n" + text,
                        ) + exc.args[1:]
    except Exception:
        pass  # attribution must never mask the kill itself
    return d


# env-gated enablement at import: wraps every lock created after this
# module loads (conftest sets the env before importing the engine)
if os.environ.get(_ENV, "").strip().lower() in ("1", "true", "yes", "on"):
    enable(raise_on_finding=os.environ.get(
        _ENV + "_RAISE", "1").strip().lower() in ("1", "true", "yes", "on"))
