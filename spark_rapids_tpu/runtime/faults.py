"""Deterministic, seeded fault injection for the engine's own failure
paths.

The reference ships a CUDA fault-injection tool (spark-rapids-jni) so
the plugin's OOM-retry / shuffle-refetch machinery is *exercised*, not
hoped-for. Same idea here, engine-native: named fault points are
instrumented across cluster/, shuffle/, exec/, memory/ and service/
(`block.fetch`, `rpc.send`, `executor.task`, `device.dispatch`,
`exchange.map`, `spill.write`, `xla.compile`, `mesh.collective`,
`peer.fetch`), and a fault PLAN selects which calls fail and how.
`peer.fetch` fires on every fleet peer-cache request (fetch,
invalidation delivery, warm-state pull — fleet/peer_cache.py; the verb
arrives as op=), so peer failures, slow peers, and delayed/lost
invalidation broadcasts are all injectable; every one must degrade to
local recompute, byte-identically. `mesh.collective` fires in
the SPMD stage launch path (exec/spmd_stage.py): live hits
(background=0) fail the fused collective program and must degrade the
stage to the round-based exchange (counted `spmdDegraded`); bg=1 hits
fire in the prewarm walk, which is best-effort and swallows them.

Plan grammar (conf `spark.rapids.tpu.sql.debug.faults.plan` or env
`SRTPU_FAULTS`), rules separated by `;`:

    point[:selector]*[:action]

    selectors   nth=N       fire on exactly the Nth call of the point
                            (1-based; implies times=1 unless overridden)
                prob=P      fire each call with probability P, drawn
                            from this rule's own seeded PRNG
                seed=S      PRNG seed for prob= (default 0 — the SAME
                            plan always injects the SAME failures)
                times=K     stop after K injections from this rule
                query=SUB   only calls whose query_id contains SUB
                op=NAME     only calls whose operator class == NAME
    actions     raise=NAME  raise a typed error: FetchFailed and
                            ExecutorLost map to the engine's structured
                            exceptions; anything else raises
                            InjectedFault with NAME as the message head
                            (so `raise=RESOURCE_EXHAUSTED` routes
                            through the OOM classifier)
                delay=MS    sleep MS milliseconds (deadline/backoff
                            paths), then continue normally
                kill        os._exit(1) — executor-kill at
                            `executor.task`

    block.fetch:nth=3:raise=FetchFailed
    device.dispatch:prob=0.05:seed=7:raise=RESOURCE_EXHAUSTED
    executor.task:nth=2:kill

Determinism: per-rule `random.Random(seed)` plus per-point call
counters, both under one lock; `injection_trace()` returns the ordered
(point, call, action) list so a test can assert that the same plan +
seed reproduces the identical trace. Executor processes inherit the
driver's environment (cluster/driver.py ships os.environ), so an
`SRTPU_FAULTS` plan is live in every executor too; conf-shipped plans
activate in `TpuSession.__init__` via `install_from_conf`.

Zero overhead disabled: every call site guards with the module-level
bool `if faults.ACTIVE: faults.hit(...)` — one dict-free attribute
read on the hot path, nothing else.
"""
from __future__ import annotations

import os
import threading
import time
from random import Random
from typing import Dict, List, Optional

__all__ = ["ACTIVE", "POINTS", "InjectedFault", "install_plan",
           "clear_plan", "install_from_conf", "hit", "injection_trace",
           "injection_counts", "current_plan", "is_transient_error",
           "note_recovery", "recovery_stats", "reset_recovery_stats"]

#: the zero-overhead guard: call sites read this bool and skip hit()
#: entirely when no plan is installed
ACTIVE = False

#: the instrumented fault-point inventory (docs/robustness.md and the
#: bench --chaos plan generator both derive from this tuple)
POINTS = ("block.fetch", "device.dispatch", "executor.task",
          "spill.write", "xla.compile", "exchange.map", "rpc.send",
          "mesh.collective", "peer.fetch")

_lock = threading.Lock()
_spec: Optional[str] = None
_rules: List["_Rule"] = []
_calls: Dict[str, int] = {}          # point -> total calls observed
_trace: List[dict] = []              # ordered injections (determinism)
_counts: Dict[str, int] = {}         # action kind -> injections


class InjectedFault(RuntimeError):
    """An error raised by the fault-injection harness (classified
    transient by `is_transient_error` — recovery paths must absorb
    it)."""

    def __init__(self, msg: str, point: str = None):
        super().__init__(msg)
        self.point = point


class _Rule:
    __slots__ = ("point", "nth", "prob", "seed", "times", "query", "op",
                 "action", "arg", "bg", "_rng", "_fired")

    def __init__(self, point: str):
        self.point = point
        self.bg: Optional[bool] = None  # None matches either path
        self.nth: Optional[int] = None
        self.prob: Optional[float] = None
        self.seed: int = 0
        self.times: Optional[int] = None
        self.query: Optional[str] = None
        self.op: Optional[str] = None
        self.action: str = "raise"
        self.arg: Optional[str] = None
        self._rng: Optional[Random] = None
        self._fired: int = 0


def _parse_rule(text: str) -> _Rule:
    fields = [f.strip() for f in text.split(":") if f.strip()]
    if not fields:
        raise ValueError(f"empty fault rule in {text!r}")
    r = _Rule(fields[0])
    for f in fields[1:]:
        if f == "kill":
            r.action = "kill"
            continue
        if "=" not in f:
            raise ValueError(f"bad fault rule field {f!r} (rule {text!r})")
        k, v = f.split("=", 1)
        if k == "nth":
            r.nth = int(v)
        elif k == "prob":
            r.prob = float(v)
        elif k == "seed":
            r.seed = int(v)
        elif k == "times":
            r.times = int(v)
        elif k == "query":
            r.query = v
        elif k == "op":
            r.op = v
        elif k == "bg":
            # background-path selector: bg=1 matches only compile-pool
            # prewarms, bg=0 only the sync dispatch path (xla.compile)
            r.bg = bool(int(v))
        elif k == "raise":
            r.action, r.arg = "raise", v
        elif k == "delay":
            r.action, r.arg = "delay", v
        else:
            raise ValueError(f"unknown fault rule field {k!r} "
                             f"(rule {text!r})")
    # an nth= rule is a single shot unless an explicit times= widens it
    if r.nth is not None and r.times is None:
        r.times = 1
    r._rng = Random(r.seed)
    return r


def install_plan(spec: str) -> int:
    """Parse and install a fault plan, resetting counters, PRNGs and
    the injection trace (same plan ⇒ same injections). Returns the
    number of rules installed."""
    global ACTIVE, _spec
    rules = [_parse_rule(part)
             for part in spec.replace(",", ";").split(";")
             if part.strip()]
    with _lock:
        _rules[:] = rules
        _spec = spec
        _calls.clear()
        _trace.clear()
        _counts.clear()
        ACTIVE = bool(rules)
    return len(rules)


def clear_plan() -> None:
    global ACTIVE, _spec
    with _lock:
        _rules.clear()
        _spec = None
        _calls.clear()
        _trace.clear()
        _counts.clear()
        ACTIVE = False


def current_plan() -> Optional[str]:
    with _lock:
        return _spec


def install_from_conf(conf) -> None:
    """Adopt a conf-carried plan (`sql.debug.faults.plan`). Idempotent
    by spec equality so per-fragment TpuSession construction in
    executors does not reset mid-query call counters."""
    try:
        from ..config import FAULTS_PLAN
        spec = conf.get(FAULTS_PLAN)
    except Exception:
        return
    if spec and spec != current_plan():
        install_plan(spec)


def hit(point: str, query_id: str = None, op: str = None,
        background: bool = False) -> None:
    """The fault point entry: count this call, match it against the
    installed rules, and perform the first matching rule's action.
    Call sites guard with `if faults.ACTIVE:` so this never runs while
    injection is disabled. `background=True` marks the compile pool's
    prewarm path (rules select it with bg=1)."""
    with _lock:
        _calls[point] = call = _calls.get(point, 0) + 1
        fired = None
        for r in _rules:
            if r.point != point:
                continue
            if r.times is not None and r._fired >= r.times:
                continue
            if r.query is not None and (query_id is None
                                        or r.query not in query_id):
                continue
            if r.op is not None and r.op != op:
                continue
            if r.bg is not None and r.bg != bool(background):
                continue
            if r.nth is not None:
                if call != r.nth:
                    continue
            elif r.prob is not None:
                if r._rng.random() >= r.prob:
                    continue
            r._fired += 1
            _counts["injected"] = _counts.get("injected", 0) + 1
            _counts[r.action] = _counts.get(r.action, 0) + 1
            _trace.append({"point": point, "call": call,
                           "action": r.action, "arg": r.arg})
            fired = r
            break
    if fired is None:
        return
    if fired.action == "delay":
        time.sleep(float(fired.arg) / 1000.0)
        return
    if fired.action == "kill":
        os._exit(1)
    _raise_named(fired.arg or "InjectedFault", point)


def _raise_named(name: str, point: str) -> None:
    if name == "FetchFailed":
        from ..cluster.blocks import FetchFailed
        raise FetchFailed(f"injected fault at {point}")
    if name == "ExecutorLost":
        from ..cluster.driver import ExecutorLostError
        raise ExecutorLostError(f"injected fault at {point}")
    # the name leads the message HEAD so classifier routing works
    # (raise=RESOURCE_EXHAUSTED is seen as OOM by memory/retry.py)
    raise InjectedFault(f"{name}: injected fault at {point}", point=point)


def injection_trace() -> List[dict]:
    """Ordered record of every injection since install_plan() — the
    determinism witness (same plan + seed ⇒ identical trace)."""
    with _lock:
        return [dict(t) for t in _trace]


def injection_counts() -> Dict[str, int]:
    with _lock:
        return dict(_counts)


# -- transient-error classification (service-level retry) ---------------

def is_transient_error(e: BaseException) -> bool:
    """True when a query failure is worth a transparent re-admission:
    injected faults, shuffle fetch failures, executor loss, connection
    resets. CONSERVATIVE by contract: cancellation, deadline,
    KeyboardInterrupt and user/plan errors are NEVER transient — a
    retry there would override an explicit decision or re-fail
    identically."""
    if isinstance(e, (KeyboardInterrupt, SystemExit, GeneratorExit)):
        return False
    try:
        from ..service.query_manager import QueryCancelled
        if isinstance(e, QueryCancelled):   # QueryTimedOut subclasses it
            return False
    except ImportError:                      # pragma: no cover
        pass
    if isinstance(e, InjectedFault):
        return True
    try:
        from ..cluster.blocks import FetchFailed
        from ..cluster.driver import ExecutorLostError
        if isinstance(e, (FetchFailed, ExecutorLostError)):
            return True
    except ImportError:                      # pragma: no cover
        pass
    return isinstance(e, ConnectionError)


# -- recovery accounting (chaos soak / bench reporting) -----------------

_recovery_lock = threading.Lock()
_recovery: Dict[str, int] = {}


def note_recovery(kind: str, n: int = 1) -> None:
    """Count one recovery-path activation (`regenerations`,
    `query_retries`, `fetch_retries`, `rpc_retries`, `degradations`).
    Cheap and unconditional — recovery paths are rare by definition."""
    with _recovery_lock:
        _recovery[kind] = _recovery.get(kind, 0) + n


def recovery_stats() -> Dict[str, int]:
    with _recovery_lock:
        return dict(_recovery)


def reset_recovery_stats() -> None:
    with _recovery_lock:
        _recovery.clear()


# env activation: executors inherit the driver's environment, so one
# SRTPU_FAULTS= covers every process of a cluster run
_env_spec = os.environ.get("SRTPU_FAULTS")
if _env_spec:
    install_plan(_env_spec)
del _env_spec
