"""Bounded exponential backoff with deterministic jitter.

One helper shared by every retry path (block fetches, driver RPC task
resends, the service-level query retry): attempt k waits
`min(base * 2^k, max) * U[0.5, 1.0)` where U comes from a seeded PRNG —
two reducers retrying the same dead mapper de-synchronize, yet a seeded
run reproduces the exact same waits (the fault-injection determinism
contract extends to the recovery timings)."""
from __future__ import annotations

from random import Random
from typing import List, Optional

__all__ = ["backoff_delays"]


def backoff_delays(attempts: int, base_ms: float,
                   max_ms: float = 10_000.0,
                   seed: Optional[int] = None) -> List[float]:
    """Return `attempts` sleep durations in SECONDS, exponentially
    grown from base_ms and capped at max_ms, each jittered into
    [50%, 100%) of its cap by a PRNG seeded with `seed`."""
    rng = Random(seed)
    out = []
    for k in range(max(attempts, 0)):
        exp = min(float(base_ms) * (2.0 ** k), float(max_ms))
        out.append(exp * (0.5 + rng.random() * 0.5) / 1000.0)
    return out
