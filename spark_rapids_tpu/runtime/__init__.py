"""Process-global runtime services shared by every exec instance.

Today: the XLA program cache (program_cache.py) — compiled-program
reuse across exec instances, DataFrames, and Sessions within one
process, the property the reference engine gets for free from pre-built
cuDF kernels (GpuOverrides.scala:5017 plans in milliseconds because
nothing compiles per query) — and the lockdep witness (lockdep.py),
the runtime half of the concurrency auditor
(docs/static_analysis.md).
"""
from . import lockdep  # noqa: F401
from . import program_cache  # noqa: F401
from . import racedep  # noqa: F401

__all__ = ["lockdep", "program_cache", "racedep"]
