"""Process-global XLA program cache: compile once, run many.

Every per-exec-instance `jax.jit` made the compile-once property
per-DataFrame: a fresh q4 tree re-traced and re-lowered ~every operator
program even though an identical-shaped tree ran seconds earlier in the
same process. The reference engine compiles nothing per query — cuDF
kernels are pre-built — and Eiger/Theseus (PAPERS.md) both key reusable
pre-compiled operator kernels by type signature. This module retrofits
that property: a thread-safe, LRU-bounded, process-global table of
jitted programs keyed by

    (operator class, program tag, site key [expression fingerprints,
     chunk counts, capacities...], donate/static argnums, backend,
     jit-relevant conf fingerprint, input avals signature
     [pytree structure + dtypes + bucketed capacities])

Exec nodes call `cached_program(builder_fn, cls=..., tag=..., key=...)`
instead of `jax.jit(builder_fn)`. The builder must be parameterized on
the key — it may close over plan configuration (bound expressions,
dtypes, bucketed capacities) but never over per-run device state or
large buffers: on a hit the FIRST-seen builder's trace runs, so any
instance state not captured by the key would silently leak into other
instances' results. Capacities are already power-of-two bucketed
(`columnar.column.bucket_capacity`), which is what bounds the avals-
signature cardinality and keeps this table small.

Counters (hits/misses/evictions) surface through
`profiler/xla_stats.snapshot()` into EXPLAIN ANALYZE
(`programCacheHits=`/`programCacheMisses=` at the root), the
`xla_compile` event-log record, and `tools/profile_report.py`. A miss
is (at most) one fresh trace; on a warm process a same-shaped fresh
query tree performs zero new XLA compiles.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Dict, Iterable, Optional, Tuple

__all__ = ["cached_program", "CachedProgram", "stats", "clear",
           "set_active_conf", "expr_fp", "exprs_fp", "conf_fingerprint"]

_lock = threading.RLock()
_cache: "OrderedDict[tuple, Any]" = OrderedDict()
_stats = {"program_cache_hits": 0, "program_cache_misses": 0,
          "program_cache_evictions": 0}
_enabled = True
_max_entries = 512
_active_conf_fp: tuple = ()

# conf entries whose values change the shape or contents of traced
# programs (plan-affecting knobs); everything else — metric levels,
# event-log paths, memory thresholds — only steers host-side control
# flow and must NOT split the cache
_JIT_RELEVANT_CONF_KEYS = (
    "spark.rapids.tpu.sql.exec.stageFusion.enabled",
    "spark.rapids.tpu.sql.exec.stageFusion.maxOps",
)


def conf_fingerprint(conf) -> tuple:
    """Fingerprint of the jit-relevant conf subset (part of every cache
    key, so two sessions with different program-shaping confs never
    share a trace)."""
    out = []
    for key in _JIT_RELEVANT_CONF_KEYS:
        try:
            from ..config import REGISTRY
            entry = REGISTRY.get(key)
            out.append((key, conf.get(entry) if entry is not None
                        else None))
        except Exception:
            out.append((key, None))
    return tuple(out)


def set_active_conf(conf) -> None:
    """Adopt a session conf: enable/size the cache and record the
    jit-relevant conf fingerprint mixed into every key. Called by
    ExecContext at query start; process-global by design (the cache
    itself is process-global), so the fingerprint-in-key is what keeps
    concurrently active sessions with different program-shaping confs
    from sharing traces."""
    global _enabled, _max_entries, _active_conf_fp
    from ..config import (PROGRAM_CACHE_ENABLED,
                          PROGRAM_CACHE_MAX_ENTRIES)
    fp = conf_fingerprint(conf)
    with _lock:
        _enabled = bool(conf.get(PROGRAM_CACHE_ENABLED))
        _max_entries = max(1, int(conf.get(PROGRAM_CACHE_MAX_ENTRIES)))
        _active_conf_fp = fp
        while len(_cache) > _max_entries:
            _release(_cache.popitem(last=False)[1])
            _stats["program_cache_evictions"] += 1


def _release(prog) -> None:
    """Drop a program's compiled executables NOW instead of waiting for
    GC. Each live XLA:CPU executable holds ~10-20 mmap'd segments;
    a process that merely *retains* a few thousand compiled programs
    walks into vm.max_map_count (default 65530), at which point the
    next LLVM JIT mmap fails and the compiler segfaults. Eviction and
    clear() therefore free eagerly — reference cycles through jit
    closures must not delay the unmap."""
    try:
        prog.clear_cache()
    except Exception:
        pass


def stats() -> Dict[str, int]:
    with _lock:
        out = dict(_stats)
        out["program_cache_entries"] = len(_cache)
        return out


def clear() -> None:
    """Drop every entry (releasing compiled executables eagerly) and
    zero the counters (tests, module teardown)."""
    with _lock:
        for prog in _cache.values():
            _release(prog)
        _cache.clear()
        for k in _stats:
            _stats[k] = 0


# ---------------------------------------------------------------------
# fingerprints: structural identity for bound expression trees (and any
# package config object — SortOrder, WindowSpec, AggExpr reductions...)
# ---------------------------------------------------------------------
_SCALARS = (str, bytes, int, float, bool, complex, type(None))

# the join-rename machinery (session.py) gensyms hidden key columns
# from a process-global counter (`__join_r<N>_x`): two identical fresh
# query trees carry different counters in otherwise identical bound
# expressions. Post-binding, column NAMES are cosmetic — emit works on
# ordinals — so the fingerprint normalizes the counter away; ordinals
# and dtypes still distinguish genuinely different columns.
import re as _re

_GENSYM_RE = _re.compile(r"__join_r\d+_")


def expr_fp(obj, _memo: Optional[dict] = None):
    """Structural fingerprint of a bound expression tree (or any plan
    config object): class name + dtype + scalar attributes, preorder —
    the same stability property as the preorder lore ids, so two
    semantically identical trees built by different DataFrames collide
    correctly. Unhashable or callable attribute values fall back to
    `("id", id(v))` — correct (never falsely shared) but unshared."""
    if isinstance(obj, str):
        return _GENSYM_RE.sub("__join_r?_", obj)
    if isinstance(obj, _SCALARS):
        return obj
    if _memo is None:
        _memo = {}
    oid = id(obj)
    if oid in _memo:
        return _memo[oid]
    if isinstance(obj, (list, tuple)):
        return ("seq",) + tuple(expr_fp(x, _memo) for x in obj)
    if isinstance(obj, (set, frozenset)):
        return ("set",) + tuple(sorted(
            (repr(expr_fp(x, _memo)) for x in obj)))
    if isinstance(obj, dict):
        return ("map",) + tuple(sorted(
            ((str(k), expr_fp(v, _memo)) for k, v in obj.items())))
    mod = type(obj).__module__ or ""
    if mod.startswith("spark_rapids_tpu") and hasattr(obj, "__dict__") \
            and not callable(obj):
        _memo[oid] = ("cyc", type(obj).__qualname__)  # cycle guard
        parts: list = [type(obj).__qualname__]
        for k, v in sorted(vars(obj).items()):
            # skip obvious runtime attachments (jitted wrappers,
            # lore/op ids assigned post-construction don't change
            # semantics and would split the key per instance).
            # Private `_*_cache` attrs are derived memos by convention
            # (_ndv_cache, _est_rows_cache, ...): planning another
            # query lazily sets them on shared plan nodes, which would
            # destabilize every later fingerprint of those nodes.
            if k.startswith("_jit") \
                    or (k.startswith("_") and k.endswith("_cache")) \
                    or k in ("_op_id", "lore_id", "_cached"):
                continue
            parts.append((k, expr_fp(v, _memo)))
        fp = tuple(parts)
        _memo[oid] = fp
        return fp
    if callable(obj):
        return ("id", oid)
    try:
        hash(obj)
    except TypeError:
        return ("id", oid)
    # hashable foreign value (numpy scalar, Decimal, date, dtype...):
    # identity-hashed objects stay distinct (unshared but correct)
    return obj


def exprs_fp(exprs: Iterable) -> tuple:
    return tuple(expr_fp(e) for e in exprs)


# ---------------------------------------------------------------------
# avals signature: pytree structure + (shape, dtype) per array leaf
# ---------------------------------------------------------------------
def _leaf_sig(x):
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return ("a", tuple(shape), str(dtype))
    # python scalars trace as weak-typed 0-d values: the aval depends on
    # the python type, never the value
    if isinstance(x, bool):
        return ("pyb",)
    if isinstance(x, int):
        return ("pyi",)
    if isinstance(x, float):
        return ("pyf",)
    return ("o", type(x).__name__)


def avals_signature(args: tuple,
                    static_argnums: Tuple[int, ...] = ()) -> tuple:
    import jax
    static = set(static_argnums)
    parts = []
    for i, a in enumerate(args):
        if i in static:
            parts.append(("s", a if _hashable(a) else ("id", id(a))))
        else:
            leaves, treedef = jax.tree_util.tree_flatten(a)
            parts.append((treedef, tuple(_leaf_sig(x) for x in leaves)))
    return tuple(parts)


def _hashable(v) -> bool:
    try:
        hash(v)
        return True
    except TypeError:
        return False


# ---------------------------------------------------------------------
# the cache proper
# ---------------------------------------------------------------------
class CachedProgram:
    """Callable wrapper over one builder function + site key. Each call
    computes the input avals signature and resolves the jitted program
    in the process-global table; a hit from a DIFFERENT exec instance
    reuses the first-seen builder's trace (that is the point)."""

    __slots__ = ("_fn", "_base_key", "_donate", "_static", "_local")

    def __init__(self, fn, base_key: tuple,
                 donate_argnums: Tuple[int, ...] = (),
                 static_argnums: Tuple[int, ...] = ()):
        self._fn = fn
        self._base_key = base_key
        self._donate = tuple(donate_argnums)
        self._static = tuple(static_argnums)
        self._local = None  # fallback jit when the cache is disabled

    def _jit(self):
        import jax
        kw = {}
        if self._donate:
            kw["donate_argnums"] = self._donate
        if self._static:
            kw["static_argnums"] = self._static
        return jax.jit(self._fn, **kw)

    def __call__(self, *args):
        import jax
        if not _enabled:
            if self._local is None:
                self._local = self._jit()
            return self._local(*args)
        sig = avals_signature(args, self._static)
        key = (self._base_key, self._donate, self._static,
               jax.default_backend(), _active_conf_fp, sig)
        with _lock:
            prog = _cache.get(key)
            if prog is not None:
                _cache.move_to_end(key)
                _stats["program_cache_hits"] += 1
            else:
                from . import faults
                if faults.ACTIVE:
                    # compile-on-miss is the xla.compile fault point: a
                    # raise here fails the query before any dispatch (a
                    # service-level retry re-enters and recompiles)
                    faults.hit("xla.compile", op=self._base_key[0]
                               if self._base_key else None)
                prog = self._jit()
                _cache[key] = prog
                _stats["program_cache_misses"] += 1
                while len(_cache) > _max_entries:
                    _release(_cache.popitem(last=False)[1])
                    _stats["program_cache_evictions"] += 1
        return prog(*args)


def cached_program(fn, *, cls: str, tag: str, key: tuple = (),
                   donate_argnums: Tuple[int, ...] = (),
                   static_argnums: Tuple[int, ...] = ()) -> CachedProgram:
    """Process-global replacement for a per-instance `jax.jit(fn)`.

    `cls`/`tag` name the call site (operator class + which of its
    programs); `key` carries everything instance-specific the traced
    program depends on — expression fingerprints (`expr_fp`), chunk
    counts, capacities, flags. `fn` may close over exactly that keyed
    state and nothing else. A site whose program genuinely depends on
    unkeyable instance state must key on `("id", id(self))` — correct
    but unshared — rather than omit it."""
    return CachedProgram(fn, ("prog", cls, tag, key),
                         donate_argnums=donate_argnums,
                         static_argnums=static_argnums)
