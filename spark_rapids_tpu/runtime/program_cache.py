"""Process-global XLA program cache: compile once, run many.

Every per-exec-instance `jax.jit` made the compile-once property
per-DataFrame: a fresh q4 tree re-traced and re-lowered ~every operator
program even though an identical-shaped tree ran seconds earlier in the
same process. The reference engine compiles nothing per query — cuDF
kernels are pre-built — and Eiger/Theseus (PAPERS.md) both key reusable
pre-compiled operator kernels by type signature. This module retrofits
that property: a thread-safe, LRU-bounded, process-global table of
jitted programs keyed by

    (operator class, program tag, site key [expression fingerprints,
     chunk counts, capacities...], donate/static argnums, backend,
     jit-relevant conf fingerprint, input avals signature
     [pytree structure + dtypes + bucketed capacities])

Exec nodes call `cached_program(builder_fn, cls=..., tag=..., key=...)`
instead of `jax.jit(builder_fn)`. The builder must be parameterized on
the key — it may close over plan configuration (bound expressions,
dtypes, bucketed capacities) but never over per-run device state or
large buffers: on a hit the FIRST-seen builder's trace runs, so any
instance state not captured by the key would silently leak into other
instances' results. Capacities are already power-of-two bucketed
(`columnar.column.bucket_capacity`), which is what bounds the avals-
signature cardinality and keeps this table small.

Counters (hits/misses/evictions) surface through
`profiler/xla_stats.snapshot()` into EXPLAIN ANALYZE
(`programCacheHits=`/`programCacheMisses=` at the root), the
`xla_compile` event-log record, and `tools/profile_report.py`. A miss
is (at most) one fresh trace; on a warm process a same-shaped fresh
query tree performs zero new XLA compiles.
"""
from __future__ import annotations

import threading
import time as _time
import weakref
from collections import OrderedDict
from typing import Any, Dict, Iterable, List, Optional, Tuple

from . import lockdep, racedep

__all__ = ["cached_program", "CachedProgram", "stats", "clear",
           "set_active_conf", "expr_fp", "exprs_fp", "conf_fingerprint",
           "drain_compile_events", "observed_programs",
           "lookup_program", "example_args_from_spec", "key_stable",
           "observed_for", "seed_observed", "prewarm_thunk"]

_lock = lockdep.rlock("program_cache._lock")
_cache: "OrderedDict[tuple, Any]" = OrderedDict()
_stats = {"program_cache_hits": 0, "program_cache_misses": 0,
          "program_cache_evictions": 0,
          "program_cache_background_compiles": 0,
          "program_cache_background_failures": 0,
          "program_cache_compile_ms": 0.0}
_enabled = True
_max_entries = 512
_active_conf_fp: tuple = ()

# base_key -> a live CachedProgram for that site (weak: dies with the
# last exec instance). Warm-pack preload re-plans recorded queries —
# reconstructing the builders and repopulating this registry — then
# prewarms the recorded signatures through whichever instance is live.
_registry: "weakref.WeakValueDictionary" = weakref.WeakValueDictionary()
# full cache key -> prewarmable spec (leaf specs + pickled-able
# treedefs per arg) observed on a sync miss; the warm-pack manifest is
# written from this table. Bounded like the cache itself.
_observed: "OrderedDict[tuple, dict]" = OrderedDict()
# base_key -> [observed keys]: stage-ahead prewarm resolves every
# program in a launching query's tree, so the per-site lookup must not
# scan the whole table under the dispatch lock
_observed_by_base: Dict[tuple, List[tuple]] = {}
_OBSERVED_CAP = 2048
# per-compile events (program key, wall ms, sync|background) drained by
# the profiler wrapper into the query event log; bounded so an unlogged
# session cannot grow it
_events: List[dict] = []
_EVENTS_CAP = 1024

# conf entries whose values change the shape or contents of traced
# programs (plan-affecting knobs); everything else — metric levels,
# event-log paths, memory thresholds — only steers host-side control
# flow and must NOT split the cache
_JIT_RELEVANT_CONF_KEYS = (
    "spark.rapids.tpu.sql.exec.stageFusion.enabled",
    "spark.rapids.tpu.sql.exec.stageFusion.maxOps",
)


def conf_fingerprint(conf) -> tuple:
    """Fingerprint of the jit-relevant conf subset (part of every cache
    key, so two sessions with different program-shaping confs never
    share a trace)."""
    out = []
    for key in _JIT_RELEVANT_CONF_KEYS:
        try:
            from ..config import REGISTRY
            entry = REGISTRY.get(key)
            out.append((key, conf.get(entry) if entry is not None
                        else None))
        except Exception:
            out.append((key, None))
    return tuple(out)


def set_active_conf(conf) -> None:
    """Adopt a session conf: enable/size the cache, record the
    jit-relevant conf fingerprint mixed into every key, and install the
    shape-bucket policy (sql.exec.shapeBuckets.*) that canonicalizes
    every capacity and chunk-count feeding the keys. Called by
    ExecContext at query start; process-global by design (the cache
    itself is process-global), so the fingerprint-in-key is what keeps
    concurrently active sessions with different program-shaping confs
    from sharing traces — and shapes self-describe in the avals
    signature, so two bucket policies never share a trace either."""
    global _enabled, _max_entries, _active_conf_fp
    from ..config import (PROGRAM_CACHE_ENABLED,
                          PROGRAM_CACHE_MAX_ENTRIES,
                          SHAPE_BUCKET_GROWTH, SHAPE_BUCKET_MIN_ROWS)
    from ..columnar.column import set_bucket_policy
    try:
        set_bucket_policy(int(conf.get(SHAPE_BUCKET_MIN_ROWS)),
                          int(conf.get(SHAPE_BUCKET_GROWTH)))
    except Exception:
        pass
    fp = conf_fingerprint(conf)
    with _lock:
        _enabled = bool(conf.get(PROGRAM_CACHE_ENABLED))
        _max_entries = max(1, int(conf.get(PROGRAM_CACHE_MAX_ENTRIES)))
        _active_conf_fp = fp
        while len(_cache) > _max_entries:
            _release(_cache.popitem(last=False)[1])
            _stats["program_cache_evictions"] += 1


def _release(prog) -> None:
    """Drop a program's compiled executables NOW instead of waiting for
    GC. Each live XLA:CPU executable holds ~10-20 mmap'd segments;
    a process that merely *retains* a few thousand compiled programs
    walks into vm.max_map_count (default 65530), at which point the
    next LLVM JIT mmap fails and the compiler segfaults. Eviction and
    clear() therefore free eagerly — reference cycles through jit
    closures must not delay the unmap."""
    try:
        prog.clear_cache()
    except Exception:
        pass


def stats() -> Dict[str, int]:
    with _lock:
        out = dict(_stats)
        out["program_cache_entries"] = len(_cache)
        return out


def clear() -> None:
    """Drop every entry (releasing compiled executables eagerly) and
    zero the counters (tests, module teardown)."""
    with _lock:
        for prog in _cache.values():
            _release(prog)
        _cache.clear()
        _observed.clear()
        _observed_by_base.clear()
        del _events[:]
        for k in _stats:
            _stats[k] = 0


# ---------------------------------------------------------------------
# compile events + warm-pack observation tables
# ---------------------------------------------------------------------
def _note_compile(base_key: tuple, wall_ms: float, mode: str) -> None:
    """Record one compile (sync miss or background prewarm) for the
    event log: site name, stable key hash, wall ms, mode."""
    import hashlib
    cls = base_key[1] if len(base_key) > 2 else "?"
    tag = base_key[2] if len(base_key) > 2 else "?"
    kh = hashlib.sha256(repr(base_key).encode()).hexdigest()[:12]
    ev = {"program": f"{cls}.{tag}", "key_hash": kh,
          "wall_ms": round(float(wall_ms), 3), "mode": mode}
    with _lock:
        _stats["program_cache_compile_ms"] = round(
            _stats["program_cache_compile_ms"] + float(wall_ms), 3)
        if mode == "background":
            _stats["program_cache_background_compiles"] += 1
        _events.append(ev)
        if len(_events) > _EVENTS_CAP:
            del _events[:len(_events) - _EVENTS_CAP]


def note_background_failure() -> None:
    """Counted by the compile pool when a background task dies (fault
    injection included): swallowed there, visible here."""
    with _lock:
        _stats["program_cache_background_failures"] += 1


def drain_compile_events() -> List[dict]:
    """Return-and-clear the compile events since the last drain (the
    profiler wrapper folds them into the query event log). Global, not
    per-query: concurrent queries' compiles interleave, like every
    other process-global counter here."""
    with _lock:
        out = list(_events)
        del _events[:]
    return out


def _leaf_spec(x):
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return ("arr", tuple(int(s) for s in shape), str(dtype))
    if isinstance(x, bool):
        return ("py", "b")
    if isinstance(x, int):
        return ("py", "i")
    if isinstance(x, float):
        return ("py", "f")
    return None


def _args_spec(args: tuple, static_argnums: Tuple[int, ...]):
    """A picklable recipe to rebuild example arguments with the same
    avals signature: per arg, (leaf specs, treedef) — or, for static
    args, the value itself when it is a picklable scalar. None when any
    leaf cannot be described (such a program cannot be prewarmed)."""
    import jax
    static = set(static_argnums)
    spec = []
    for i, a in enumerate(args):
        if i in static:
            if isinstance(a, (str, bytes, int, float, bool, type(None))):
                spec.append(("static", a))
                continue
            return None
        leaves, treedef = jax.tree_util.tree_flatten(a)
        ls = tuple(_leaf_spec(x) for x in leaves)
        if any(s is None for s in ls):
            return None
        spec.append(("tree", ls, treedef))
    return tuple(spec)


def example_args_from_spec(spec) -> tuple:
    """Zero-filled concrete arguments matching a recorded spec: the
    prewarm call traces and compiles exactly the program a real call
    with that signature would."""
    import jax
    import jax.numpy as jnp
    args = []
    for part in spec:
        if part[0] == "static":
            args.append(part[1])
            continue
        _, leaf_specs, treedef = part
        leaves = []
        for s in leaf_specs:
            if s[0] == "arr":
                leaves.append(jnp.zeros(s[1], dtype=s[2]))
            else:
                leaves.append({"b": False, "i": 0, "f": 0.0}[s[1]])
        args.append(jax.tree_util.tree_unflatten(treedef, leaves))
    return tuple(args)


def key_stable(base_key) -> bool:
    """False when the key carries an identity fallback (('id', N) /
    ('inst', N) / ('cyc', ...)): correct in-process but meaningless in
    a warm-pack manifest — the same site can never match after a
    restart (the unstable-program-key lint rule polices the sources)."""
    if isinstance(base_key, tuple):
        if len(base_key) == 2 and base_key[0] in ("id", "inst") \
                and isinstance(base_key[1], int):
            return False
        return all(key_stable(x) for x in base_key)
    return True


def _note_observed(key: tuple, base_key: tuple, donate, static,
                   args: tuple) -> None:
    if not key_stable(base_key):
        return
    spec = _args_spec(args, static)
    if spec is None:
        return
    with _lock:
        racedep.note_access("program_cache._observed", key, write=True)
        _observed_insert(key, {"base_key": base_key,
                               "donate": tuple(donate),
                               "static": tuple(static), "spec": spec})


def _observed_insert(key: tuple, entry: dict) -> None:
    """Insert under _lock, maintaining the by-base_key index and the
    LRU cap."""
    if key not in _observed:
        _observed_by_base.setdefault(entry["base_key"], []).append(key)
    _observed[key] = entry
    _observed.move_to_end(key)
    while len(_observed) > _OBSERVED_CAP:
        old_key, old = _observed.popitem(last=False)
        keys = _observed_by_base.get(old["base_key"])
        if keys is not None:
            try:
                keys.remove(old_key)
            except ValueError:
                pass
            if not keys:
                _observed_by_base.pop(old["base_key"], None)


def observed_programs() -> List[dict]:
    """Snapshot of the observed program table (warm-pack record)."""
    with _lock:
        racedep.note_access("program_cache._observed")
        return [dict(v) for v in _observed.values()]


def lookup_program(base_key) -> Optional["CachedProgram"]:
    """A live CachedProgram registered for `base_key`, if any exec
    instance holding one is still alive (warm-pack preload resolves
    manifest entries through this after re-planning)."""
    return _registry.get(base_key)


def observed_for(base_key) -> List[dict]:
    """Every observed spec entry for one program site (stage-ahead
    prewarm at query launch looks up the signatures a structurally
    identical tree compiled before — earlier in this process, or seeded
    from a warm-pack manifest)."""
    with _lock:
        racedep.note_access("program_cache._observed", base_key)
        return [dict(_observed[k])
                for k in _observed_by_base.get(base_key, ())]


def seed_observed(entries: Iterable) -> int:
    """Merge warm-pack manifest entries into the observed table so
    launch-time stage-ahead prewarm can find recorded signatures even
    for sites the preload re-plan could not resolve to a live program.
    Returns the number of new entries."""
    n = 0
    with _lock:
        racedep.note_access("program_cache._observed", write=True)
        for e in entries:
            try:
                k = ("seed", e["base_key"], tuple(e["donate"]),
                     tuple(e["static"]), e["spec"])
                if k in _observed:
                    continue
                _observed_insert(k, dict(e))
            except (TypeError, KeyError):
                continue
            n += 1
    return n


def spec_signature(spec) -> tuple:
    """The avals signature `example_args_from_spec(spec)` would
    produce, computed without allocating the arrays (cheap warm check
    before a prewarm allocates zero buffers)."""
    parts = []
    for part in spec:
        if part[0] == "static":
            v = part[1]
            parts.append(("s", v if _hashable(v) else ("id", id(v))))
            continue
        _, leaf_specs, treedef = part
        sigs = []
        for s in leaf_specs:
            if s[0] == "arr":
                sigs.append(("a", tuple(s[1]), s[2]))
            else:
                sigs.append({"b": ("pyb",), "i": ("pyi",),
                             "f": ("pyf",)}[s[1]])
        parts.append((treedef, tuple(sigs)))
    return tuple(parts)


def prewarm_needed(prog: "CachedProgram", spec) -> bool:
    """True when the spec's full cache key is cold. Caller-side filter
    for prewarm_tree: in steady state every observed spec is already
    warm, and checking here keeps the launch path from paying a pool
    submit + worker wakeup per program just to find that out."""
    import jax
    key = (prog._base_key, prog._donate, prog._static,
           jax.default_backend(), _active_conf_fp,
           spec_signature(spec))
    with _lock:
        return key not in _cache


def prewarm_thunk(prog: "CachedProgram", spec):
    """The compile pool's lazy-args contract for one recorded spec:
    the returned thunk runs on a worker thread and yields example args,
    or None when the spec's cache key is already warm — skipping the
    zero-buffer allocation on every repeat query."""
    def thunk():
        import jax
        key = (prog._base_key, prog._donate, prog._static,
               jax.default_backend(), _active_conf_fp,
               spec_signature(spec))
        with _lock:
            if key in _cache:
                return None
        return example_args_from_spec(spec)
    return thunk


# ---------------------------------------------------------------------
# fingerprints: structural identity for bound expression trees (and any
# package config object — SortOrder, WindowSpec, AggExpr reductions...)
# ---------------------------------------------------------------------
_SCALARS = (str, bytes, int, float, bool, complex, type(None))

# the join-rename machinery (session.py) gensyms hidden key columns
# from a process-global counter (`__join_r<N>_x`): two identical fresh
# query trees carry different counters in otherwise identical bound
# expressions. Post-binding, column NAMES are cosmetic — emit works on
# ordinals — so the fingerprint normalizes the counter away; ordinals
# and dtypes still distinguish genuinely different columns.
import re as _re

_GENSYM_RE = _re.compile(r"__join_r\d+_")


def expr_fp(obj, _memo: Optional[dict] = None):
    """Structural fingerprint of a bound expression tree (or any plan
    config object): class name + dtype + scalar attributes, preorder —
    the same stability property as the preorder lore ids, so two
    semantically identical trees built by different DataFrames collide
    correctly. Unhashable or callable attribute values fall back to
    `("id", id(v))` — correct (never falsely shared) but unshared."""
    if isinstance(obj, str):
        return _GENSYM_RE.sub("__join_r?_", obj)
    if isinstance(obj, _SCALARS):
        return obj
    if _memo is None:
        _memo = {}
    oid = id(obj)
    if oid in _memo:
        return _memo[oid]
    if isinstance(obj, (list, tuple)):
        return ("seq",) + tuple(expr_fp(x, _memo) for x in obj)
    if isinstance(obj, (set, frozenset)):
        return ("set",) + tuple(sorted(
            (repr(expr_fp(x, _memo)) for x in obj)))
    if isinstance(obj, dict):
        return ("map",) + tuple(sorted(
            ((str(k), expr_fp(v, _memo)) for k, v in obj.items())))
    mod = type(obj).__module__ or ""
    if mod.startswith("spark_rapids_tpu") and hasattr(obj, "__dict__") \
            and not callable(obj):
        _memo[oid] = ("cyc", type(obj).__qualname__)  # cycle guard
        parts: list = [type(obj).__qualname__]
        for k, v in sorted(vars(obj).items()):
            # skip obvious runtime attachments (jitted wrappers,
            # lore/op ids assigned post-construction don't change
            # semantics and would split the key per instance).
            # Private `_*_cache` attrs are derived memos by convention
            # (_ndv_cache, _est_rows_cache, ...): planning another
            # query lazily sets them on shared plan nodes, which would
            # destabilize every later fingerprint of those nodes.
            if k.startswith("_jit") \
                    or (k.startswith("_") and k.endswith("_cache")) \
                    or k in ("_op_id", "lore_id", "_cached"):
                continue
            parts.append((k, expr_fp(v, _memo)))
        fp = tuple(parts)
        _memo[oid] = fp
        return fp
    if callable(obj):
        return ("id", oid)
    try:
        hash(obj)
    except TypeError:
        return ("id", oid)
    # hashable foreign value (numpy scalar, Decimal, date, dtype...):
    # identity-hashed objects stay distinct (unshared but correct)
    return obj


def exprs_fp(exprs: Iterable) -> tuple:
    return tuple(expr_fp(e) for e in exprs)


# ---------------------------------------------------------------------
# avals signature: pytree structure + (shape, dtype) per array leaf
# ---------------------------------------------------------------------
def _leaf_sig(x):
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return ("a", tuple(shape), str(dtype))
    # python scalars trace as weak-typed 0-d values: the aval depends on
    # the python type, never the value
    if isinstance(x, bool):
        return ("pyb",)
    if isinstance(x, int):
        return ("pyi",)
    if isinstance(x, float):
        return ("pyf",)
    return ("o", type(x).__name__)


def avals_signature(args: tuple,
                    static_argnums: Tuple[int, ...] = ()) -> tuple:
    import jax
    static = set(static_argnums)
    parts = []
    for i, a in enumerate(args):
        if i in static:
            parts.append(("s", a if _hashable(a) else ("id", id(a))))
        else:
            leaves, treedef = jax.tree_util.tree_flatten(a)
            parts.append((treedef, tuple(_leaf_sig(x) for x in leaves)))
    return tuple(parts)


def _hashable(v) -> bool:
    try:
        hash(v)
        return True
    except TypeError:
        return False


# ---------------------------------------------------------------------
# the cache proper
# ---------------------------------------------------------------------
class CachedProgram:
    """Callable wrapper over one builder function + site key. Each call
    computes the input avals signature and resolves the jitted program
    in the process-global table; a hit from a DIFFERENT exec instance
    reuses the first-seen builder's trace (that is the point)."""

    __slots__ = ("_fn", "_base_key", "_donate", "_static", "_local",
                 "__weakref__")

    def __init__(self, fn, base_key: tuple,
                 donate_argnums: Tuple[int, ...] = (),
                 static_argnums: Tuple[int, ...] = ()):
        self._fn = fn
        self._base_key = base_key
        self._donate = tuple(donate_argnums)
        self._static = tuple(static_argnums)
        self._local = None  # fallback jit when the cache is disabled
        try:
            _registry[base_key] = self   # last-registered wins; weak
        except TypeError:
            pass                         # unhashable key: unregistered

    @property
    def base_key(self) -> tuple:
        return self._base_key

    def _jit(self):
        import jax
        kw = {}
        if self._donate:
            kw["donate_argnums"] = self._donate
        if self._static:
            kw["static_argnums"] = self._static
        return jax.jit(self._fn, **kw)

    def _key_for(self, args: tuple):
        import jax
        sig = avals_signature(args, self._static)
        return (self._base_key, self._donate, self._static,
                jax.default_backend(), _active_conf_fp, sig)

    def __call__(self, *args):
        if not _enabled:
            if self._local is None:
                self._local = self._jit()
            return self._local(*args)
        key = self._key_for(args)
        miss = False
        with _lock:
            prog = _cache.get(key)
            if prog is not None:
                _cache.move_to_end(key)
                _stats["program_cache_hits"] += 1
            else:
                from . import faults
                if faults.ACTIVE:
                    # compile-on-miss is the xla.compile fault point: a
                    # raise here fails the query before any dispatch (a
                    # service-level retry re-enters and recompiles)
                    faults.hit("xla.compile", op=self._base_key[0]
                               if self._base_key else None)
                prog = self._jit()
                _cache[key] = prog
                _stats["program_cache_misses"] += 1
                miss = True
                while len(_cache) > _max_entries:
                    _release(_cache.popitem(last=False)[1])
                    _stats["program_cache_evictions"] += 1
        if not miss:
            return prog(*args)
        # sync miss: the actual trace+compile happens on this first
        # call (outside the lock). The timed wall includes one
        # dispatch — the event log documents it as such. The spec is
        # recorded BEFORE the call: donated arg buffers are dead after.
        _note_observed(key, self._base_key, self._donate, self._static,
                       args)
        from ..profiler import tracing
        t0 = _time.perf_counter()
        # sync compile ON the dispatch path: exactly the latency the
        # critical path must blame on 'compile' (thread-local context —
        # the query thread runs under tracing.use)
        with tracing.span("xla.compile", "compile",
                          op=self._base_key[0] if self._base_key
                          else None):
            out = prog(*args)
        _note_compile(self._base_key,
                      (_time.perf_counter() - t0) * 1e3, "sync")
        return out

    def prewarm(self, args: tuple) -> bool:
        """Compile this program for `args`' signature ahead of first
        dispatch (compile-pool workers call this with zero-filled
        example args). Returns True when a program was compiled, False
        when the key was already warm or the cache is disabled. Runs
        the compiled program once on the example args — engine builder
        functions are pure batch transforms, so the throwaway execution
        is safe and leaves jax's tracing cache hot. Never called on the
        dispatch path: a concurrent sync miss for the same key compiles
        a duplicate rather than waiting."""
        if not _enabled:
            return False
        key = self._key_for(args)
        with _lock:
            if key in _cache:
                return False
        from . import faults
        if faults.ACTIVE:
            # the background half of the xla.compile fault point: the
            # compile pool swallows + counts the raise, and the query
            # falls back to the sync compile path
            faults.hit("xla.compile", op=self._base_key[0]
                       if self._base_key else None, background=True)
        prog = self._jit()
        t0 = _time.perf_counter()
        prog(*args)
        wall_ms = (_time.perf_counter() - t0) * 1e3
        stored = False
        with _lock:
            if key not in _cache:
                _cache[key] = prog
                stored = True
                while len(_cache) > _max_entries:
                    _release(_cache.popitem(last=False)[1])
                    _stats["program_cache_evictions"] += 1
        if stored:
            _note_observed(key, self._base_key, self._donate,
                           self._static, args)
            _note_compile(self._base_key, wall_ms, "background")
        else:
            _release(prog)
        return stored


def cached_program(fn, *, cls: str, tag: str, key: tuple = (),
                   donate_argnums: Tuple[int, ...] = (),
                   static_argnums: Tuple[int, ...] = ()) -> CachedProgram:
    """Process-global replacement for a per-instance `jax.jit(fn)`.

    `cls`/`tag` name the call site (operator class + which of its
    programs); `key` carries everything instance-specific the traced
    program depends on — expression fingerprints (`expr_fp`), chunk
    counts, capacities, flags. `fn` may close over exactly that keyed
    state and nothing else. A site whose program genuinely depends on
    unkeyable instance state must key on `("id", id(self))` — correct
    but unshared — rather than omit it."""
    return CachedProgram(fn, ("prog", cls, tag, key),
                         donate_argnums=donate_argnums,
                         static_argnums=static_argnums)
