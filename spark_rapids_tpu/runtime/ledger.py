"""Runtime resource ledger: balanced acquire/release witness.

The static half (analysis/lifetime.py) proves lifetime properties about
code shapes; this module watches the acquisitions the engine ACTUALLY
makes. Modeled on runtime/lockdep.py: resources are typed by KIND —

  device_bytes   DeviceManager reservations
  host_bytes     HostMemoryManager reservations
  staging_lease  PinnedStagingPool leases (StagingBuffer)
  spill_handle   SpillStore handles (SpillableBatchHandle)
  shuffle_pin    BlockStore in-flight shuffle pins
  permit         TpuSemaphore permits
  ride           PermitRider ride slots
  cache_charge   result-cache host-byte charges

— and every instrumented acquire/release site notes its kind here.
Three mechanisms turn lifetime bugs from heisenbugs into assertions:

- per-query balance: acquisitions are attributed to the submitting
  query (TLS scope where available; the holder registry pins an
  acquisition's query so a release from a worker thread without the
  TLS tag still credits the right ledger). At EVERY terminal state
  (FINISHED, CANCELLED, TIMED_OUT alike) QueryManager._finalize asks
  the ledger to assert the query's owner-scoped kinds are balanced.
  Only kinds whose lifetime is bounded by the query are asserted
  (staging_lease, permit, ride); parkable kinds (spill handles and
  shuffle pins held in reusable exchange state, cross-query cache
  charges, raw byte reservations) are tracked and reported but not
  raised on — their balance is owned by plan/cache teardown.
- poison mode: released cached staging buffers are filled with 0xAB
  before returning to the free list, so a use-after-release reads
  deterministic garbage instead of whatever the next lease wrote —
  the PR 4 corruption class becomes reproducible.
- attribution on kill: `dump()` snapshots outstanding holders (kind,
  acquisition site tag, named thread, owning query) and is attached to
  deadline kills (CancelToken) and budget-exhaustion OOM text next to
  the lockdep thread dump.

Enablement: env ``SRTPU_LEDGER=1`` (conftest.py sets it for the whole
tier-1 suite) or conf ``spark.rapids.tpu.sql.debug.ledger.enabled`` at
session construction. Disabled, the note hooks are one None-check —
zero overhead. Enabled overhead is budgeted <5% of tier-1 wall: each
note is a dict bump under one short-lived mutex (never held while
touching an engine lock).
"""
from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional

__all__ = ["ResourceLeakError", "Ledger", "ledger", "enabled", "enable",
           "disable", "poison_enabled", "note_acquire", "note_release",
           "note_query_end", "attach_dump", "format_dump",
           "STRICT_KINDS", "POISON_BYTE"]

_ENV = "SRTPU_LEDGER"

#: kinds whose lifetime is bounded by the submitting query: asserted
#: balanced at every terminal state. Parkable kinds (spill handles /
#: shuffle pins in reusable exchange state, cache charges) are not.
STRICT_KINDS = frozenset({"staging_lease", "permit", "ride"})

#: released staging buffers are memset to this in poison mode
POISON_BYTE = 0xAB


class ResourceLeakError(RuntimeError):
    """A query reached a terminal state with owner-scoped resources
    still outstanding (or over-released)."""


def _qid() -> Optional[str]:
    """Current query id from the service TLS scope, lazily bound (the
    service layer imports memory modules which import us)."""
    global _QID_FN
    fn = _QID_FN
    if fn is None:
        try:
            from ..service.query_manager import current_query_id as fn
        except Exception:
            return None
        _QID_FN = fn
    return fn()


_QID_FN = None


class Ledger:
    """Process-global per-kind counters + holder registry + per-query
    balance ledgers."""

    def __init__(self, raise_on_finding: bool = True,
                 poison: bool = False):
        self.raise_on_finding = raise_on_finding
        self.poison = poison
        self._mu = threading.Lock()     # guards ledger state only;
        # NEVER held while touching an engine lock
        # kind -> counter dict
        self._kinds: Dict[str, dict] = {}
        # (kind, token) -> holder record; token is the held object's
        # id() (leases, handles) or a stable key (shuffle id), letting
        # a release on a DIFFERENT thread than the acquire credit the
        # acquiring query
        self._holders: Dict[tuple, dict] = {}
        # qid -> kind -> [count, bytes]
        self._queries: Dict[str, Dict[str, list]] = {}
        self.findings: List[dict] = []
        self.balanced_queries = 0
        self.imbalanced_queries = 0

    def _kind(self, kind: str) -> dict:
        k = self._kinds.get(kind)
        if k is None:
            k = {"acquires": 0, "releases": 0, "outstanding": 0,
                 "outstandingBytes": 0, "peakOutstanding": 0,
                 "untrackedReleases": 0}
            self._kinds[kind] = k
        return k

    # -- note hooks ----------------------------------------------------
    def acquired(self, kind: str, nbytes: int = 0, token=None,
                 tag: Optional[str] = None):
        qid = _qid()
        tname = threading.current_thread().name
        with self._mu:
            k = self._kind(kind)
            k["acquires"] += 1
            k["outstanding"] += 1
            k["outstandingBytes"] += nbytes
            if k["outstanding"] > k["peakOutstanding"]:
                k["peakOutstanding"] = k["outstanding"]
            if token is not None:
                self._holders[(kind, token)] = {
                    "kind": kind, "tag": tag or kind, "thread": tname,
                    "query": qid, "nbytes": int(nbytes)}
            if qid is not None:
                c = self._queries.setdefault(qid, {}).setdefault(
                    kind, [0, 0])
                c[0] += 1
                c[1] += nbytes

    def released(self, kind: str, nbytes: int = 0, token=None):
        qid = _qid()
        with self._mu:
            k = self._kind(kind)
            if token is not None:
                rec = self._holders.pop((kind, token), None)
                if rec is None:
                    # idempotent close / acquired before enablement:
                    # count it but do not drive outstanding negative
                    k["untrackedReleases"] += 1
                    return
                qid = rec["query"]
                nbytes = rec["nbytes"]
            k["releases"] += 1
            k["outstanding"] -= 1
            k["outstandingBytes"] -= nbytes
            if qid is not None:
                c = self._queries.setdefault(qid, {}).setdefault(
                    kind, [0, 0])
                c[0] -= 1
                c[1] -= nbytes

    # -- per-query balance ---------------------------------------------
    def query_balance(self, qid: str) -> Dict[str, int]:
        """Outstanding count per kind attributed to `qid` (unbalanced
        kinds only)."""
        with self._mu:
            q = self._queries.get(qid) or {}
            return {kind: c[0] for kind, c in q.items() if c[0] != 0}

    def query_end(self, qid: str, state=None):
        """Drop the query's ledger; assert owner-scoped kinds balanced.
        Called by QueryManager._finalize for every terminal state."""
        with self._mu:
            q = self._queries.pop(qid, None)
            bad = {}
            if q:
                for kind in STRICT_KINDS:
                    c = q.get(kind)
                    if c is not None and c[0] != 0:
                        bad[kind] = c[0]
            holders = [dict(r) for r in self._holders.values()
                       if r["query"] == qid] if bad else []
        if not bad:
            self.balanced_queries += 1
            return
        self.imbalanced_queries += 1
        finding = {"kind": "query-imbalance", "query": qid,
                   "state": str(state), "counts": bad,
                   "holders": holders}
        self.findings.append(finding)
        if self.raise_on_finding:
            parts = ", ".join(f"{k}={n:+d}" for k, n in sorted(bad.items()))
            who = "; ".join(
                f"{h['tag']} on {h['thread']}" for h in holders[:6])
            raise ResourceLeakError(
                f"query {qid} reached {state} with unbalanced "
                f"resources: {parts}"
                + (f" (outstanding: {who})" if who else ""))

    # -- reporting -----------------------------------------------------
    def outstanding(self, kind: str) -> int:
        with self._mu:
            k = self._kinds.get(kind)
            return k["outstanding"] if k else 0

    def dump(self) -> dict:
        """Attributed outstanding-holders snapshot: what a deadline
        kill or OOM attaches next to the lockdep thread dump."""
        with self._mu:
            kinds = {k: dict(v) for k, v in self._kinds.items()}
            holders = [dict(r) for r in self._holders.values()]
        holders.sort(key=lambda r: (r["kind"], r["thread"], r["tag"]))
        return {"kinds": kinds, "holders": holders,
                "findings": list(self.findings)}

    def report(self) -> dict:
        """Summary counters for the resource_ledger event and bench
        extra.ledger."""
        with self._mu:
            kinds = {
                k: {"acquires": v["acquires"], "releases": v["releases"],
                    "outstanding": v["outstanding"],
                    "peakOutstanding": v["peakOutstanding"]}
                for k, v in sorted(self._kinds.items())}
            strict_out = sum(
                v["outstanding"] for k, v in self._kinds.items()
                if k in STRICT_KINDS)
        return {"enabled": True, "kinds": kinds,
                "balanceOk": not self.findings and strict_out == 0,
                "balancedQueries": self.balanced_queries,
                "imbalancedQueries": self.imbalanced_queries,
                "findings": len(self.findings)}


# ---------------------------------------------------------------------
# process-global enablement
# ---------------------------------------------------------------------
_LEDGER: Optional[Ledger] = None


def enabled() -> bool:
    return _LEDGER is not None


def ledger() -> Optional[Ledger]:
    return _LEDGER


def poison_enabled() -> bool:
    lg = _LEDGER
    return lg is not None and lg.poison


def enable(raise_on_finding: bool = True, poison: bool = False) -> Ledger:
    """Idempotent; acquisitions made BEFORE this are not tracked (their
    later releases land in untrackedReleases), so enable before the
    engine runs queries (conftest/env) for exact balance."""
    global _LEDGER
    if _LEDGER is None:
        _LEDGER = Ledger(raise_on_finding=raise_on_finding,
                         poison=poison)
    elif poison:
        _LEDGER.poison = True
    return _LEDGER


def disable():
    global _LEDGER
    _LEDGER = None


def maybe_enable_from_conf(conf):
    """Session-construction hook for sql.debug.ledger.* confs."""
    from ..config import LEDGER_ENABLED, LEDGER_POISON, LEDGER_RAISE
    if conf.get(LEDGER_ENABLED):
        enable(raise_on_finding=bool(conf.get(LEDGER_RAISE)),
               poison=bool(conf.get(LEDGER_POISON)))
    elif _LEDGER is not None and conf.get(LEDGER_POISON):
        _LEDGER.poison = True


# ---------------------------------------------------------------------
# note hooks: one None-check when the ledger is off
# ---------------------------------------------------------------------
def note_acquire(kind: str, nbytes: int = 0, token=None,
                 tag: Optional[str] = None):
    lg = _LEDGER
    if lg is not None:
        lg.acquired(kind, nbytes, token, tag)


def note_release(kind: str, nbytes: int = 0, token=None):
    lg = _LEDGER
    if lg is not None:
        lg.released(kind, nbytes, token)


def note_query_end(qid: str, state=None):
    lg = _LEDGER
    if lg is not None:
        lg.query_end(qid, state)


# ---------------------------------------------------------------------
# dump formatting / exception attachment
# ---------------------------------------------------------------------
def format_dump(dump: dict, limit: int = 12) -> str:
    """Human-readable outstanding-resources table for exception text."""
    rows = []
    for kind, k in sorted(dump.get("kinds", {}).items()):
        if k.get("outstanding"):
            rows.append(f"  {kind}: outstanding={k['outstanding']} "
                        f"bytes={k['outstandingBytes']} "
                        f"peak={k['peakOutstanding']}")
    shown = 0
    for h in dump.get("holders", ()):
        if shown >= limit:
            rows.append(f"  ... {len(dump['holders']) - limit} "
                        f"more holders")
            break
        rows.append(f"  {h['kind']}: {h['tag']} thread={h['thread']} "
                    f"query={h['query'] or '-'} nbytes={h['nbytes']}")
        shown += 1
    return "\n".join(rows)


def attach_dump(exc: BaseException) -> Optional[dict]:
    """On deadline kill / OOM: hang the ledger dump off the exception
    (read by the event log) and fold the outstanding table into its
    message, next to lockdep's thread table. Returns the dump, or None
    when the ledger is off or the exception already carries one."""
    lg = _LEDGER
    if lg is None or getattr(exc, "ledger_dump", None) is not None:
        return None
    d = lg.dump()
    exc.ledger_dump = d
    try:
        text = format_dump(d)
        if text and exc.args and isinstance(exc.args[0], str):
            exc.args = (exc.args[0] + "\nresource ledger:\n" + text,
                        ) + exc.args[1:]
    except Exception:
        pass  # attribution must never mask the kill itself
    return d


# env-gated enablement at import (conftest sets the env before the
# engine runs its first query)
if os.environ.get(_ENV, "").strip().lower() in ("1", "true", "yes", "on"):
    enable(
        raise_on_finding=os.environ.get(
            _ENV + "_RAISE", "1").strip().lower()
        in ("1", "true", "yes", "on"),
        poison=os.environ.get(
            _ENV + "_POISON", "").strip().lower()
        in ("1", "true", "yes", "on"))
