"""Process-global cross-query result & fragment cache: execute once,
serve many.

The service (PR 7) admits and schedules queries; dashboard traffic is
overwhelmingly REPEATED queries over slowly-changing tables. The
program cache (PR 6) made "compile once, run many" real; this module
makes "execute once, serve many" real, in two tiers under one
byte-budgeted LRU:

- **query tier** — whole-query Arrow results, keyed on the
  name/gensym-blind structural fingerprint of the LOGICAL plan
  (program_cache.expr_fp: join-rename gensyms normalized, underscore
  state skipped) composed with the per-query conf snapshot and the
  backend. A hit is served on the service FAST PATH: no admission
  slot, no planning, no execution — still metered
  (QueryManager.stats["cache_fast_path"]) and still event-logged
  (`result_cache` record).
- **fragment tier** — materialized exchange map outputs (host Arrow,
  one table per reduce partition + the partition-stats vector), keyed
  on the exchange subtree's `plan/reuse.node_fp` fingerprint. The
  planner consults this tier AFTER the exchange-reuse pass: a hit
  substitutes a `CachedFragmentExec` source (ReusedExchangeExec-style
  delegation shape), eliding the whole map phase; a miss tags the
  exchange so a successful run harvests its output for next time.

**Invalidation** is carried by the keys themselves: every scan binds a
snapshot (path, mtime_ns, size / Delta version — plan/logical.py,
io/snapshot.py) that flows into both fingerprints, so a table write
changes every dependent key and the stale entries simply become
unreachable (the LRU ages them out). Writes through the engine
(io/parquet.py, io/delta.py) additionally drop intersecting entries
eagerly via `invalidate_paths`, and `DataFrame.uncache()` drops the
plan's query-tier entries via `invalidate_plan` so "fresh execution"
stays honest.

**Memory discipline**: entry bytes charge the host-memory budget
(memory/host.py) via try_reserve, the cache registers a pressure hook
that evicts LRU entries first when OTHER consumers hit the budget,
and an internal byte cap (sql.cache.maxBytes) bounds the cache even
with no host budget configured. All mutation happens under a
lockdep-witnessed lock (runtime/lockdep.py) so the PR 9 concurrency
auditor covers the cache for the whole tier-1 suite.

Off by default (`spark.rapids.tpu.sql.cache.enabled`): repeat-heavy
serving opts in per session, the Spark/Presto result-cache posture.

**Fleet tier** (PR 20): when a process has joined the serving fabric
(spark_rapids_tpu/fleet/), a local miss in either tier consults the
rendezvous-ordered owning peers before recomputing, local stores are
published (by reference) to the member's export store, and every
invalidation broadcasts to the fleet. The hook is one module-level
dispatcher installed by `set_peer_tier`; all peer IO happens OUTSIDE
`_lock`, and soundness never depends on it — keys embed scan
snapshots, so a peer holding a stale entry holds an unreachable key,
and fetched entries are re-stat'd before acceptance besides.
"""
from __future__ import annotations

import weakref
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from . import lockdep, racedep

__all__ = [
    "enabled", "fragments_enabled", "lookup_query", "put_query",
    "substitute_fragments", "harvest_fragments", "invalidate_paths",
    "invalidate_prefix", "invalidate_plan", "invalidate_plan_fp",
    "stats", "clear", "set_host_manager", "set_peer_tier",
    "CachedFragmentExec",
]

# ---------------------------------------------------------------------
# state — every access under _lock (lockdep-witnessed when enabled)

_lock = lockdep.lock("ResultCache._lock")
_entries: "OrderedDict[tuple, _Entry]" = OrderedDict()  # LRU: MRU last
_by_path: Dict[str, set] = {}        # data-file path -> {keys}
_by_plan: Dict[tuple, set] = {}      # logical plan fp -> {query keys}
_bytes = 0                           # sum of entry nbytes
_stats = {
    "result_cache_hits": 0,
    "result_cache_misses": 0,
    "result_cache_fragment_hits": 0,
    "result_cache_fragment_misses": 0,
    "result_cache_stores": 0,
    "result_cache_fragment_stores": 0,
    "result_cache_evictions": 0,
    "result_cache_invalidations": 0,
    "result_cache_rejected": 0,
    "result_cache_peer_hits": 0,
    "result_cache_peer_fragment_hits": 0,
}
# host managers that already carry our pressure hook (the global
# singleton plus any test-injected private manager)
_hooked: "weakref.WeakSet" = weakref.WeakSet()
# test hook: a PRIVATE HostMemoryManager so budget tests never mutate
# the process singleton's budget (that would poison later tests)
_host_override = None
# the fleet dispatcher (fleet/member.py installs it; None = no fleet).
# Resolved per call, never under _lock: consult/publish/broadcast all
# do socket IO and must not serialize the cache.
_peer_tier = None


def set_peer_tier(tier) -> None:
    """Install (or clear, with None) the fleet peer-tier dispatcher:
    an object with consult(key, paths), publish(key, value, nbytes,
    tier, paths, plan_fp=), broadcast(mode, arg). Every dispatch
    no-ops when no fleet member is active on the calling thread."""
    global _peer_tier
    _peer_tier = tier


class _Entry:
    __slots__ = ("value", "nbytes", "tier", "paths", "plan_fp", "mgr")

    def __init__(self, value, nbytes: int, tier: str,
                 paths: Tuple[str, ...], plan_fp=None, mgr=None):
        self.value = value        # pa.Table | _Fragment
        self.nbytes = nbytes
        self.tier = tier          # "query" | "fragment"
        self.paths = paths
        self.plan_fp = plan_fp    # query tier only
        self.mgr = mgr            # host manager charged, if any


class _Fragment:
    """A cached exchange map output: per-reduce-partition host Arrow
    tables (None = empty partition, matching reduce_batch's None) and
    the serialized-bytes partition-stats vector AQE planning reads."""
    __slots__ = ("tables", "pstats", "nparts")

    def __init__(self, tables: List, pstats: List[int]):
        self.tables = tables
        self.pstats = list(pstats)
        self.nparts = len(tables)


# ---------------------------------------------------------------------
# conf accessors

def enabled(conf) -> bool:
    from ..config import RESULT_CACHE_ENABLED
    return bool(conf.get(RESULT_CACHE_ENABLED))


def fragments_enabled(conf) -> bool:
    from ..config import RESULT_CACHE_FRAGMENTS
    return bool(conf.get(RESULT_CACHE_FRAGMENTS))


def _max_bytes(conf) -> int:
    from ..config import RESULT_CACHE_MAX_BYTES
    return int(conf.get(RESULT_CACHE_MAX_BYTES))


def _max_entry_bytes(conf) -> int:
    from ..config import RESULT_CACHE_MAX_ENTRY_BYTES
    return int(conf.get(RESULT_CACHE_MAX_ENTRY_BYTES))


# ---------------------------------------------------------------------
# keys

def _backend() -> str:
    try:
        import jax
        return jax.default_backend()
    except Exception:
        return "?"


def _conf_fp(conf) -> tuple:
    # the FULL conf snapshot: partition counts, batch sizes, broadcast
    # thresholds etc. all change row order or typing of results, and
    # byte-identity to fresh execution is the acceptance bar —
    # conservative splitting beats a subtly shared wrong answer.
    # sql.fleet.* is the one excluded family: fleet confs (directory
    # path, fanout, timeouts) cannot change result bytes, and they
    # NECESSARILY differ across members — including them would make
    # every cross-peer key a guaranteed miss.
    return tuple(sorted(
        (k, repr(v)) for k, v in conf._settings.items()
        if not k.startswith("spark.rapids.tpu.sql.fleet.")))


def _plan_paths(plan) -> Tuple[str, ...]:
    """Every data-file path a logical (or physical) tree scans."""
    out, stack, seen = [], [plan], set()
    while stack:
        n = stack.pop()
        if id(n) in seen:
            continue
        seen.add(id(n))
        if getattr(n, "snapshot", None) is not None:
            out.extend(getattr(n, "paths", ()) or ())
        stack.extend(getattr(n, "children", ()) or ())
        t = getattr(n, "target", None)   # ReusedExchangeExec delegation
        if t is not None and hasattr(t, "children"):
            stack.append(t)
    return tuple(out)


def _query_key(plan, conf):
    from .program_cache import expr_fp
    pfp = expr_fp(plan)
    return ("q", pfp, _conf_fp(conf), _backend()), pfp, _plan_paths(plan)


# ---------------------------------------------------------------------
# core LRU under _lock

def _unindex_locked(key, e: _Entry):
    global _bytes
    _bytes -= e.nbytes
    for p in e.paths:
        s = _by_path.get(p)
        if s is not None:
            s.discard(key)
            if not s:
                del _by_path[p]
    if e.plan_fp is not None:
        s = _by_plan.get(e.plan_fp)
        if s is not None:
            s.discard(key)
            if not s:
                del _by_plan[e.plan_fp]


def _release_host(dropped: List[_Entry]):
    """Return host-budget reservations AFTER _lock is dropped (keeps
    the ResultCache -> HostMemoryManager lock order one-way)."""
    from . import ledger
    for e in dropped:
        if e.mgr is not None:
            try:
                e.mgr.release(e.nbytes)
            except Exception:
                pass
        ledger.note_release("cache_charge", token=id(e))


def _host_mgr(conf):
    if _host_override is not None:
        return _host_override
    from ..memory.host import host_manager
    return host_manager(conf)


def _pressure_hook(bytes_needed: int) -> int:
    """Host-memory pressure: evict LRU entries first. Registered on
    every manager the cache charges; called by HostMemoryManager.reserve
    outside its own lock."""
    dropped, freed = [], 0
    with _lock:
        while _entries and freed < bytes_needed:
            key, e = _entries.popitem(last=False)
            _unindex_locked(key, e)
            _stats["result_cache_evictions"] += 1
            freed += e.nbytes
            dropped.append(e)
    _release_host(dropped)
    return freed


def _store(key, entry: _Entry, conf, publish: bool = True):
    """Insert under the byte budget: evict LRU past sql.cache.maxBytes,
    charge the host budget, reject when the host refuses even after
    making room. `publish=False` suppresses the fleet export (peer-
    fetched inserts: a member only ever serves what IT computed, so
    entries never ping-pong around the fleet)."""
    global _bytes
    cap = _max_bytes(conf)
    if entry.nbytes > min(cap, _max_entry_bytes(conf)):
        with _lock:
            _stats["result_cache_rejected"] += 1
        return False
    mgr = _host_mgr(conf)
    if mgr is not None:
        if mgr not in _hooked:
            mgr.register_pressure_hook(_pressure_hook)  # idempotent
            _hooked.add(mgr)
        if not mgr.try_reserve(entry.nbytes):
            # make room with our own LRU, then retry once
            _pressure_hook(entry.nbytes)
            if not mgr.try_reserve(entry.nbytes):
                with _lock:
                    _stats["result_cache_rejected"] += 1
                return False
        entry.mgr = mgr
    from . import ledger
    ledger.note_acquire("cache_charge", entry.nbytes, token=id(entry),
                        tag=f"result_cache[{entry.tier}]")
    dropped = []
    with _lock:
        racedep.note_access("result_cache._entries", key, write=True)
        old = _entries.pop(key, None)
        if old is not None:
            _unindex_locked(key, old)
            dropped.append(old)
        while _entries and _bytes + entry.nbytes > cap:
            k2, e2 = _entries.popitem(last=False)
            _unindex_locked(k2, e2)
            _stats["result_cache_evictions"] += 1
            dropped.append(e2)
        _entries[key] = entry
        _bytes += entry.nbytes
        for p in entry.paths:
            _by_path.setdefault(p, set()).add(key)
        if entry.plan_fp is not None:
            _by_plan.setdefault(entry.plan_fp, set()).add(key)
        _stats["result_cache_stores" if entry.tier == "query"
               else "result_cache_fragment_stores"] += 1
    _release_host(dropped)
    if publish and _peer_tier is not None:
        try:
            _peer_tier.publish(key, entry.value, entry.nbytes,
                               entry.tier, entry.paths,
                               plan_fp=entry.plan_fp)
        except Exception:
            pass              # export is advisory, never fails a store
    return True


def _get(key, tier: str) -> Optional[_Entry]:
    hk = ("result_cache_hits" if tier == "query"
          else "result_cache_fragment_hits")
    mk = ("result_cache_misses" if tier == "query"
          else "result_cache_fragment_misses")
    with _lock:
        racedep.note_access("result_cache._entries", key)
        e = _entries.get(key)
        if e is None:
            _stats[mk] += 1
            return None
        _entries.move_to_end(key)
        _stats[hk] += 1
        return e


# ---------------------------------------------------------------------
# query tier

def lookup_query(plan, conf):
    """Consult the whole-query tier for a collect over `plan`. Returns
    (arrow_table | None, token): the token carries the key + paths for
    `put_query` after a miss executes; (None, None) when disabled.
    Refreshes scan snapshots first, so an external overwrite makes the
    old key unreachable (and eagerly drops entries over the changed
    paths)."""
    if not enabled(conf):
        return None, None
    from ..io.snapshot import refresh_plan_snapshots
    changed = refresh_plan_snapshots(plan)
    if changed:
        invalidate_paths(changed)
    key, pfp, paths = _query_key(plan, conf)
    e = _get(key, "query")
    token = (key, pfp, paths)
    if e is not None:
        return e.value, token
    value = _peer_consult_query(key, pfp, paths, conf)
    return value, token


def _peer_consult_query(key, pfp, paths, conf):
    """Fleet consult after a local query-tier miss (outside _lock).
    A peer hit is adopted into the local cache WITHOUT re-export
    (publish=False) and served exactly like a local hit."""
    if _peer_tier is None:
        return None
    try:
        got = _peer_tier.consult(key, paths)
    except Exception:
        return None
    if got is None or got[0] != "query":
        return None
    _, value, _meta = got
    try:
        nbytes = int(value.get_total_buffer_size())
    except Exception:
        return None
    with _lock:
        _stats["result_cache_peer_hits"] += 1
    _store(key, _Entry(value, nbytes, "query", tuple(paths),
                       plan_fp=pfp), conf, publish=False)
    return value


def put_query(token, value, conf) -> bool:
    """Store a collect result (pa.Table) after a miss executed."""
    if token is None or value is None:
        return False
    try:
        nbytes = int(value.get_total_buffer_size())
    except Exception:
        return False
    key, pfp, paths = token
    return _store(key, _Entry(value, nbytes, "query", paths,
                              plan_fp=pfp), conf)


# ---------------------------------------------------------------------
# fragment tier — planner substitution + post-run harvest

class CachedFragmentExec:
    """A fragment-tier hit: serves a previously materialized exchange
    map output as a source node (the cached analog of
    ReusedExchangeExec). Implements the exchange consumer surface —
    num_partitions / stage_stats / read_slice / execute_partition — by
    re-hydrating the stored host Arrow tables into device batches, so
    shuffle readers and AQE planning work unchanged."""

    fusion_opt_out = True
    fuses_child_chain = False
    fusion_require_ordinals = False

    def __init__(self, entry: _Entry, original):
        frag: _Fragment = entry.value
        self.children: list = []
        self._schema = original.schema
        self._op_id = f"CachedFragmentExec@{id(self):x}"
        self.lore_id = getattr(original, "lore_id", None)
        self._frag = frag
        self._hit_lock = lockdep.lock("CachedFragmentExec._hit_lock")
        self._hit_ctxs: set = set()

    @property
    def schema(self):
        return self._schema

    def _count_hit(self, ctx):
        with self._hit_lock:
            if id(ctx) in self._hit_ctxs or len(self._hit_ctxs) >= 64:
                return
            self._hit_ctxs.add(id(ctx))
        ctx.metrics_for(self._op_id).add("resultCacheFragmentHits", 1)

    def num_partitions(self, ctx) -> int:
        return self._frag.nparts

    def stage_stats(self, ctx) -> List[int]:
        self._count_hit(ctx)
        return list(self._frag.pstats)

    def read_slice(self, ctx, rpid: int, chunk: int = 0,
                   nchunks: int = 1):
        self._count_hit(ctx)
        at = self._frag.tables[rpid]
        if at is None:
            return None
        if nchunks > 1:
            per = -(-at.num_rows // nchunks)
            at = at.slice(chunk * per, per)
        if at.num_rows == 0 and len(at.columns) > 0:
            return None
        from ..columnar.table import Table
        from ..exec.batch import DeviceBatch
        m = ctx.metrics_for(self._op_id)
        with m.timer("fetchAndMergeTime"):
            tbl = Table.from_arrow(at)
        m.add("numOutputRows", at.num_rows)
        m.add("numOutputBatches", 1)
        return DeviceBatch(tbl, num_rows=at.num_rows)

    def execute_partition(self, ctx, pid: int):
        b = self.read_slice(ctx, pid)
        if b is not None:
            yield b

    def execute_all(self, ctx):
        for pid in range(self.num_partitions(ctx)):
            for b in self.execute_partition(ctx, pid):
                ctx.check_cancel()
                yield b

    def release(self):
        """The cache owns the Arrow tables; nothing to free here."""

    def fusable_stage(self):
        return None

    def preserves_ordinals(self) -> bool:
        return True

    def stage_fingerprint(self) -> tuple:
        return ("inst", id(self))

    def node_name(self) -> str:
        return "CachedFragmentExec"

    def describe(self) -> str:
        return (f"CachedFragmentExec[{self._frag.nparts} partitions, "
                f"{sum(self._frag.pstats)} bytes]")

    def tree_string(self, indent: int = 0) -> str:
        return "  " * indent + self.describe() + "\n"


def _fragment_key(node, conf_fp, backend):
    from ..plan.reuse import node_fp
    fp = node_fp(node)
    if fp is None:
        return None
    return ("f", fp, conf_fp, backend)


def substitute_fragments(root, conf):
    """Planner pass (after exchange reuse): replace shuffle exchanges
    whose subtree fingerprint has a cached map output with
    CachedFragmentExec, rewiring ReusedExchangeExec targets and
    AqeShufflePlan.exchanges references the same way the reuse pass
    does. Misses tag the exchange (`_frag_key`, underscore = excluded
    from fingerprints) for harvest after a successful run. Returns
    (root, hits)."""
    if not (enabled(conf) and fragments_enabled(conf)):
        return root, 0
    from ..exec.aqe import AqeShufflePlan
    from ..exec.exchange import ShuffleExchangeExec
    from ..plan.reuse import ReusedExchangeExec
    cfp = _conf_fp(conf)
    backend = _backend()
    replaced: Dict[int, CachedFragmentExec] = {}
    hits = 0

    def walk(node):
        nonlocal hits
        for i, c in enumerate(node.children):
            node.children[i] = walk(c)
        p = getattr(node, "plan", None)
        if isinstance(p, AqeShufflePlan):
            p.exchanges = [replaced.get(id(e), e) for e in p.exchanges]
        if isinstance(node, ReusedExchangeExec):
            node.target = replaced.get(id(node.target), node.target)
            return node
        if isinstance(node, ShuffleExchangeExec):
            key = _fragment_key(node, cfp, backend)
            if key is None:
                return node
            e = _get(key, "fragment")
            if e is None:
                e = _peer_consult_fragment(key, node, conf)
            if e is not None:
                r = CachedFragmentExec(e, node)
                replaced[id(node)] = r
                hits += 1
                return r
            node._frag_key = key
        return node

    root = walk(root)
    return root, hits


def _peer_consult_fragment(key, node, conf) -> Optional[_Entry]:
    """Fleet consult after a fragment-tier miss (planner thread,
    outside _lock): a peer's materialized map output substitutes just
    like a local one, adopted locally without re-export."""
    if _peer_tier is None:
        return None
    paths = _plan_paths(node)
    try:
        got = _peer_tier.consult(key, paths)
    except Exception:
        return None
    if got is None or got[0] != "fragment":
        return None
    _, value, _meta = got
    tables, pstats = value
    nbytes = sum(int(t.get_total_buffer_size())
                 for t in tables if t is not None)
    entry = _Entry(_Fragment(tables, pstats), nbytes, "fragment", paths)
    with _lock:
        _stats["result_cache_peer_fragment_hits"] += 1
    _store(key, entry, conf, publish=False)
    return entry


def harvest_fragments(root, ctx) -> int:
    """After a successful action: store the map outputs of exchanges
    the planner tagged on a fragment miss AND that actually
    materialized during this run. Reads each reduce partition back
    through the exchange's own read_slice (one D2H per partition, paid
    once per distinct fragment) into host Arrow. Returns stores."""
    conf = ctx.conf
    if not (enabled(conf) and fragments_enabled(conf)):
        return 0
    from ..exec.nodes import _batch_to_arrow
    stored = 0
    stack, seen = [root], set()
    while stack:
        node = stack.pop()
        if id(node) in seen:
            continue
        seen.add(id(node))
        stack.extend(getattr(node, "children", ()) or ())
        t = getattr(node, "target", None)
        if t is not None and hasattr(t, "children"):
            stack.append(t)
        key = getattr(node, "_frag_key", None)
        if key is None or getattr(node, "_shuffle", None) is None:
            continue
        with _lock:
            if key in _entries:
                continue
        pstats = getattr(node, "_pstats", None)
        if pstats is None:
            continue
        est = sum(pstats)
        if est > _max_entry_bytes(conf):
            continue
        try:
            tables = []
            for rpid in range(node.num_partitions(ctx)):
                b = node.read_slice(ctx, rpid)
                tables.append(None if b is None else _batch_to_arrow(b))
        except Exception:
            continue          # advisory: never fail the query
        nbytes = sum(int(t.get_total_buffer_size())
                     for t in tables if t is not None)
        frag = _Fragment(tables, pstats)
        if _store(key, _Entry(frag, nbytes, "fragment",
                              _plan_paths(node)), conf):
            stored += 1
    return stored


# ---------------------------------------------------------------------
# invalidation

def _broadcast(mode: str, arg) -> None:
    """Gossip one invalidation to the fleet (outside _lock, best-
    effort). No-op without a joined member — the common case costs one
    None check."""
    if _peer_tier is None:
        return
    try:
        _peer_tier.broadcast(mode, arg)
    except Exception:
        pass


def invalidate_paths(paths, propagate: bool = True) -> int:
    """Drop every entry that scans any of `paths` (called by the write
    paths — parquet overwrite, Delta commit — and by the snapshot
    refresh when it observes an external change). Returns drops.
    `propagate=False` marks a fleet-delivered invalidation: apply
    locally only, the origin already told everyone else."""
    paths = list(paths)
    dropped = []
    with _lock:
        keys = set()
        for p in paths:
            keys |= _by_path.get(p, set())
        for key in keys:
            e = _entries.pop(key, None)
            if e is not None:
                _unindex_locked(key, e)
                dropped.append(e)
        if dropped:
            _stats["result_cache_invalidations"] += len(dropped)
    _release_host(dropped)
    if propagate and paths:
        _broadcast("paths", paths)
    return len(dropped)


def invalidate_prefix(prefix: str, propagate: bool = True) -> int:
    """Drop every entry scanning a file under `prefix` (a table
    directory — the Delta/parquet writers know the root, not which
    scans read which data files). The broadcast ships the PREFIX, not
    our resolved paths: each peer indexes different data files."""
    with _lock:
        paths = [p for p in _by_path if p.startswith(prefix)]
    n = invalidate_paths(paths, propagate=False) if paths else 0
    if propagate:
        _broadcast("prefix", prefix)
    return n


def _drop_plan_fp(pfp) -> int:
    dropped = []
    with _lock:
        for key in list(_by_plan.get(pfp, ())):
            e = _entries.pop(key, None)
            if e is not None:
                _unindex_locked(key, e)
                dropped.append(e)
        if dropped:
            _stats["result_cache_invalidations"] += len(dropped)
    _release_host(dropped)
    return len(dropped)


def invalidate_plan(plan, conf=None, propagate: bool = True) -> int:
    """Drop the query-tier entries for `plan` under ANY conf — the
    `DataFrame.uncache()` interplay: uncache promises the next action
    is a fresh execution, so the cache must not answer it — on THIS
    process and (via the broadcast) on every peer."""
    try:
        from .program_cache import expr_fp
        pfp = expr_fp(plan)
    except Exception:
        return 0
    n = _drop_plan_fp(pfp)
    if propagate:
        _broadcast("plan_fp", pfp)
    return n


def invalidate_plan_fp(pfp) -> int:
    """Fleet-delivered uncache: drop by plan fingerprint directly (the
    wire carries the fp, not the plan). Never propagates."""
    return _drop_plan_fp(pfp)


# ---------------------------------------------------------------------
# introspection / lifecycle

def stats() -> dict:
    with _lock:
        out = dict(_stats)
        out["result_cache_entries"] = len(_entries)
        out["result_cache_bytes"] = _bytes
    return out


def clear():
    """Drop everything, release host reservations, zero the counters,
    and reset the test host-manager override (tests/conftest.py calls
    this at module boundaries, program-cache precedent)."""
    global _host_override
    with _lock:
        dropped = list(_entries.values())
        keys = list(_entries.keys())
        for key, e in zip(keys, dropped):
            _unindex_locked(key, e)
        _entries.clear()
        _by_path.clear()
        _by_plan.clear()
        for k in _stats:
            _stats[k] = 0
    _release_host(dropped)
    _host_override = None


def set_host_manager(mgr):
    """Test hook: charge cache bytes against a PRIVATE
    HostMemoryManager instead of the process singleton (whose budget
    must never be mutated by a test). clear() resets it."""
    global _host_override
    _host_override = mgr
