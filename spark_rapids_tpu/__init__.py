"""spark-rapids-tpu: a TPU-native columnar SQL execution framework.

A ground-up TPU redesign of the capabilities of NVIDIA's RAPIDS Accelerator
for Apache Spark (the reference implementation surveyed in SURVEY.md):
Arrow-layout columnar batches resident in TPU HBM as jax Arrays; expression
and operator kernels compiled by XLA (with Pallas for the hot paths);
sort-based segmented groupby/join/sort under a static-shape regime; a
handle-based HBM->host->disk spill framework with split-and-retry
out-of-core execution; and a partition-exchange shuffle with host-file and
ICI-collective transports.
"""
import os as _os

import jax as _jax

# SQL semantics require 64-bit ints/floats (LongType, DoubleType, decimal64,
# timestamps); enable before any array is created.
_jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: query-shaped programs are large and
# tunneled-TPU compiles are minutes; caching across processes turns cold
# starts into seconds. SRTPU_COMPILE_CACHE overrides the location; set it
# to "0" to disable.
#
# The cache dir is fingerprinted by host CPU model + features +
# jaxlib version: AOT results compiled on one machine can embed vector
# instructions (or microarch-specific XLA target options) another host
# lacks (cpu_aot_loader feature-mismatch
# spam, and SIGILL if a mismatched program runs anyway), so each
# distinct feature set gets its own subdirectory. Foreign-fingerprint
# subdirs or a legacy unfingerprinted cache log ONE structured warning
# — never a per-program complaint.


def _cache_fingerprint() -> str:
    import hashlib
    import platform
    feats = ""
    model = ""
    try:
        with open("/proc/cpuinfo", encoding="utf-8") as f:
            for line in f:
                if not feats and line.startswith(("flags", "Features")):
                    feats = " ".join(sorted(line.split(":", 1)[1].split()))
                elif not model and line.startswith(("model name", "CPU part",
                                                    "vendor_id")):
                    model = line.split(":", 1)[1].strip()
                if feats and model:
                    break
    except OSError:
        feats = platform.machine() + " " + platform.processor()
    try:
        import jaxlib
        ver = getattr(jaxlib, "__version__", "?")
    except Exception:
        ver = "?"
    # note: no jax.default_backend() here — that would force backend
    # initialization at import time
    # model identity matters beyond the flags list: XLA:CPU picks
    # per-microarchitecture target options (prefer-no-gather/-scatter)
    # that the flags line does not expose, and loading an AOT result
    # built under different options can SIGILL/crash outright
    return hashlib.sha256(
        f"{model}|{feats}|{ver}".encode()).hexdigest()[:12]


_cache = _os.environ.get("SRTPU_COMPILE_CACHE")
if _cache != "0":
    if not _cache:
        _cache = _os.path.join(
            _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))),
            ".jax_cache")
    try:
        _fp = _cache_fingerprint()
        _sub = _os.path.join(_cache, f"host-{_fp}")
        _legacy = [e for e in (_os.listdir(_cache)
                               if _os.path.isdir(_cache) else [])
                   if not _os.path.isdir(_os.path.join(_cache, e))
                   or (e != _os.path.basename(_sub) and "-" in e)]
        if _legacy:
            import logging
            logging.getLogger(__name__).warning(
                "compile cache %s holds %d entr%s from other machine "
                "fingerprints (or a pre-fingerprint layout); they are "
                "ignored — this host uses %s",
                _cache, len(_legacy), "y" if len(_legacy) == 1 else "ies",
                _sub)
        _os.makedirs(_sub, exist_ok=True)
        _jax.config.update("jax_compilation_cache_dir", _sub)
        _jax.config.update("jax_persistent_cache_min_compile_time_secs",
                           0.5)
    except Exception:
        pass

from .columnar import dtypes
from .columnar.column import Column
from .columnar.table import Table, Schema, Field
from .config import TpuConf
from .session import TpuSession, DataFrame
from . import functions

__version__ = "0.1.0"
__all__ = ["TpuSession", "DataFrame", "Table", "Column", "Schema", "Field",
           "TpuConf", "functions", "dtypes"]
