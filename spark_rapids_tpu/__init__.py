"""spark-rapids-tpu: a TPU-native columnar SQL execution framework.

A ground-up TPU redesign of the capabilities of NVIDIA's RAPIDS Accelerator
for Apache Spark (the reference implementation surveyed in SURVEY.md):
Arrow-layout columnar batches resident in TPU HBM as jax Arrays; expression
and operator kernels compiled by XLA (with Pallas for the hot paths);
sort-based segmented groupby/join/sort under a static-shape regime; a
handle-based HBM->host->disk spill framework with split-and-retry
out-of-core execution; and a partition-exchange shuffle with host-file and
ICI-collective transports.
"""
import os as _os

import jax as _jax

# SQL semantics require 64-bit ints/floats (LongType, DoubleType, decimal64,
# timestamps); enable before any array is created.
_jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: query-shaped programs are large and
# tunneled-TPU compiles are minutes; caching across processes turns cold
# starts into seconds. SRTPU_COMPILE_CACHE overrides the location; set it
# to "0" to disable.
_cache = _os.environ.get("SRTPU_COMPILE_CACHE")
if _cache != "0":
    if not _cache:
        _cache = _os.path.join(
            _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))),
            ".jax_cache")
    try:
        _os.makedirs(_cache, exist_ok=True)
        _jax.config.update("jax_compilation_cache_dir", _cache)
        _jax.config.update("jax_persistent_cache_min_compile_time_secs",
                           0.5)
    except Exception:
        pass

from .columnar import dtypes
from .columnar.column import Column
from .columnar.table import Table, Schema, Field
from .config import TpuConf
from .session import TpuSession, DataFrame
from . import functions

__version__ = "0.1.0"
__all__ = ["TpuSession", "DataFrame", "Table", "Column", "Schema", "Field",
           "TpuConf", "functions", "dtypes"]
