"""String expressions (reference: stringFunctions.scala rules in
GpuOverrides.scala:933-4258 — Length, Upper, Lower, Substring, Concat,
Contains, StartsWith, EndsWith, Like)."""
from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..ops import strings as ops_str
from ..ops.kernel_utils import CV
from .expressions import (Expression, Literal, UnsupportedExpr, _UnaryOp)

__all__ = ["Length", "Upper", "Lower", "Substring", "ConcatStr",
           "Contains", "StartsWith", "EndsWith", "Like", "Trim",
           "Reverse", "Instr", "Pad", "Repeat", "ConcatWs"]


def _require_string(e: Expression, what: str):
    if not isinstance(e.dtype, (dt.StringType, dt.BinaryType)):
        raise UnsupportedExpr(f"{what} on {e.dtype}")


class Length(_UnaryOp):
    def _resolve_type(self):
        _require_string(self.child, "length")
        self.dtype = dt.INT32

    def emit(self, ctx):
        cv = self.child.emit(ctx)
        return CV(ops_str.str_len_chars(cv).astype(jnp.int32), cv.validity)

    def __repr__(self):
        return f"length({self.child})"


class Upper(_UnaryOp):
    def _resolve_type(self):
        _require_string(self.child, "upper")
        self.dtype = dt.STRING

    def emit(self, ctx):
        return ops_str.upper(self.child.emit(ctx))

    def __repr__(self):
        return f"upper({self.child})"


class Lower(_UnaryOp):
    def _resolve_type(self):
        _require_string(self.child, "lower")
        self.dtype = dt.STRING

    def emit(self, ctx):
        return ops_str.lower(self.child.emit(ctx))

    def __repr__(self):
        return f"lower({self.child})"


class Substring(Expression):
    def __init__(self, child: Expression, start: int,
                 length: Optional[int] = None):
        self.child = child
        self.start = start
        self.length = length
        self.children = [child]

    def bind(self, schema):
        b = Substring(self.child.bind(schema), self.start, self.length)
        _require_string(b.child, "substring")
        b.dtype = dt.STRING
        return b

    def emit(self, ctx):
        return ops_str.substring(self.child.emit(ctx), self.start,
                                 self.length)

    def __repr__(self):
        return f"substring({self.child}, {self.start}, {self.length})"


class ConcatStr(Expression):
    def __init__(self, *children: Expression):
        self.children = list(children)

    def bind(self, schema):
        bc = [c.bind(schema) for c in self.children]
        for c in bc:
            _require_string(c, "concat")
        b = ConcatStr(*bc)
        b.dtype = dt.STRING
        return b

    def emit(self, ctx):
        cvs = [c.emit(ctx) for c in self.children]
        out_cap = sum(cv.data.shape[0] for cv in cvs)
        return ops_str.concat_strings(cvs, out_cap)

    def __repr__(self):
        return "concat(" + ", ".join(map(repr, self.children)) + ")"


class _LiteralPatternPredicate(Expression):
    kernel = None

    def __init__(self, child: Expression, pattern: Expression):
        self.child = child
        self.pattern = pattern
        self.children = [child, pattern]

    def bind(self, schema):
        c = self.child.bind(schema)
        p = self.pattern.bind(schema)
        _require_string(c, type(self).__name__.lower())
        if not isinstance(p, Literal) or not isinstance(p.value, (str, bytes)):
            raise UnsupportedExpr(
                f"{type(self).__name__} requires a literal pattern round-1")
        b = type(self)(c, p)
        b.dtype = dt.BOOL
        return b

    def _pattern_bytes(self) -> bytes:
        v = self.pattern.value
        return v.encode() if isinstance(v, str) else v

    def emit(self, ctx):
        cv = self.child.emit(ctx)
        out = type(self).kernel(cv, self._pattern_bytes())
        return CV(out, cv.validity)


class Contains(_LiteralPatternPredicate):
    kernel = staticmethod(ops_str.contains)


class StartsWith(_LiteralPatternPredicate):
    kernel = staticmethod(ops_str.startswith)


class EndsWith(_LiteralPatternPredicate):
    kernel = staticmethod(ops_str.endswith)


_WILD = ord("_")


class Like(Expression):
    """SQL LIKE with a literal pattern: runs of literals/_ separated by %.
    `_` matches exactly one byte. Escapes land with the regex transpiler
    (reference: RegexParser.scala)."""

    def __init__(self, child: Expression, pattern: str):
        self.child = child
        self.pattern = pattern
        self.children = [child]

    def bind(self, schema):
        c = self.child.bind(schema)
        _require_string(c, "like")
        if "\\" in self.pattern:
            raise UnsupportedExpr("LIKE escapes land with the regex "
                                  "transpiler")
        b = Like(c, self.pattern)
        b.dtype = dt.BOOL
        return b

    def emit(self, ctx):
        cv = self.child.emit(ctx)
        pat = self.pattern
        lens0 = ops_str.str_len_bytes(cv)
        if "%" not in pat:
            raw = pat.encode()
            ok = (lens0 == len(raw)) & (
                ops_str.startswith(cv, raw, _WILD) if raw
                else (lens0 == 0))
            return CV(ok, cv.validity)
        parts = [p.encode() for p in pat.split("%")]
        lead = not pat.startswith("%")
        trail = not pat.endswith("%")
        inner = [p for p in parts if p]
        n = cv.offsets.shape[0] - 1
        ok = (lens0 >= sum(len(p) for p in inner))
        if not inner:
            # pattern is only % signs: matches anything (incl. empty)
            return CV(jnp.ones(n, jnp.bool_), cv.validity)
        # with >=1 '%', a single literal run cannot be both the required
        # prefix and suffix, so lead/trail consume distinct runs
        middle = list(inner)
        if lead:
            ok = ok & ops_str.startswith(cv, parts[0], _WILD)
            middle = middle[1:]
        if trail:
            ok = ok & ops_str.endswith(cv, parts[-1], _WILD)
            middle = middle[:-1]
        # middle runs must appear BETWEEN the consumed prefix/suffix;
        # multiple middle runs are containment-checked, which can
        # over-match when they overlap (docs/compatibility.md)
        skip_pre = len(parts[0]) if lead else 0
        skip_suf = len(parts[-1]) if trail else 0
        for p in middle:
            ok = ok & ops_str.contains(cv, p, _WILD, skip_pre, skip_suf)
        return CV(ok, cv.validity)

    def __repr__(self):
        return f"({self.child} LIKE '{self.pattern}')"


class Trim(Expression):
    def __init__(self, child: Expression, left: bool = True,
                 right: bool = True):
        self.child = child
        self.left, self.right = left, right
        self.children = [child]

    def bind(self, schema):
        b = Trim(self.child.bind(schema), self.left, self.right)
        _require_string(b.child, "trim")
        b.dtype = dt.STRING
        return b

    def emit(self, ctx):
        return ops_str.trim(self.child.emit(ctx), self.left, self.right)

    def __repr__(self):
        kind = "trim" if self.left and self.right else (
            "ltrim" if self.left else "rtrim")
        return f"{kind}({self.child})"


class Reverse(_UnaryOp):
    def _resolve_type(self):
        _require_string(self.child, "reverse")
        self.dtype = dt.STRING

    def emit(self, ctx):
        return ops_str.reverse(self.child.emit(ctx))


class Instr(_LiteralPatternPredicate):
    """instr(str, substr): 1-based position, 0 when absent."""

    def bind(self, schema):
        b = super().bind(schema)
        b.dtype = dt.INT32
        return b

    def emit(self, ctx):
        cv = self.child.emit(ctx)
        out = ops_str.find_first(cv, self._pattern_bytes())
        return CV(out, cv.validity)


class Pad(Expression):
    def __init__(self, child: Expression, target_len: int, pad: str,
                 left: bool):
        self.child = child
        self.target_len = int(target_len)
        self.pad = pad
        self.left = left
        self.children = [child]

    def bind(self, schema):
        b = Pad(self.child.bind(schema), self.target_len, self.pad,
                self.left)
        _require_string(b.child, "lpad/rpad")
        b.dtype = dt.STRING
        return b

    def emit(self, ctx):
        return ops_str.pad(self.child.emit(ctx), self.target_len,
                           self.pad.encode(), self.left)

    def __repr__(self):
        return f"{'l' if self.left else 'r'}pad({self.child})"


class Repeat(Expression):
    def __init__(self, child: Expression, times: int):
        self.child = child
        self.times = int(times)
        self.children = [child]

    def bind(self, schema):
        b = Repeat(self.child.bind(schema), self.times)
        _require_string(b.child, "repeat")
        b.dtype = dt.STRING
        return b

    def emit(self, ctx):
        cv = self.child.emit(ctx)
        out_cap = max(cv.data.shape[0] * max(self.times, 1), 1)
        return ops_str.repeat_str(cv, self.times, out_cap)

    def __repr__(self):
        return f"repeat({self.child}, {self.times})"


class ConcatWs(Expression):
    """concat_ws(sep, cols...): skips NULL inputs (Spark semantics,
    unlike concat which nulls out the row)."""

    def __init__(self, sep: str, *children: Expression):
        self.sep = sep
        self.children = list(children)

    def bind(self, schema):
        bc = [c.bind(schema) for c in self.children]
        for c in bc:
            _require_string(c, "concat_ws")
        b = ConcatWs(self.sep, *bc)
        b.dtype = dt.STRING
        return b

    def emit(self, ctx):
        cvs = [c.emit(ctx) for c in self.children]
        cap = ctx.capacity
        if not cvs:
            return CV(jnp.zeros(128, jnp.uint8), jnp.ones(cap, jnp.bool_),
                      jnp.zeros(cap + 1, jnp.int32))
        sep_raw = self.sep.encode()
        # single interleaved pass: [c0, sep1, c1, sep2, c2, ...] where
        # sep_i is present iff any of c0..c_{i-1} is non-null AND c_i is
        parts = []
        prefix_has = None
        for i, cv in enumerate(cvs):
            has = cv.validity
            lens = ops_str.str_len_bytes(cv)
            safe = ops_str.rebuild_strings(
                cv, cv.offsets[:-1],
                jnp.where(has, lens, 0).astype(jnp.int32))
            safe = CV(safe.data, jnp.ones(cap, jnp.bool_), safe.offsets)
            if i > 0 and sep_raw:
                present = prefix_has & has
                parts.append(ops_str.literal_column(
                    sep_raw, present, cap * len(sep_raw)))
            parts.append(safe)
            prefix_has = has if prefix_has is None else (prefix_has | has)
        out_cap = sum(p.data.shape[0] for p in parts)
        out = ops_str.concat_strings(parts, out_cap)
        return CV(out.data, jnp.ones(cap, jnp.bool_), out.offsets)

    def __repr__(self):
        return f"concat_ws('{self.sep}', ...)"
