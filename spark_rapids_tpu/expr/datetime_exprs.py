"""Date/time expressions (reference: datetimeExpressions.scala rules)."""
from __future__ import annotations

import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..ops import datetime as ops_dt
from ..ops.kernel_utils import CV
from .expressions import (Expression, UnsupportedExpr, _BinaryOp, _UnaryOp,
                          _wrap)

__all__ = ["Year", "Month", "DayOfMonth", "DayOfWeek", "DayOfYear",
           "FromUTCTimestamp", "ToUTCTimestamp",
           "Quarter", "Hour", "Minute", "Second", "DateAdd", "DateSub",
           "DateDiff", "LastDay", "ToDate", "ToTimestamp"]


class _DateField(_UnaryOp):
    kernel = None

    def _resolve_type(self):
        ct = self.child.dtype
        if not isinstance(ct, (dt.DateType, dt.TimestampType)):
            raise UnsupportedExpr(f"{type(self).__name__}({ct})")
        self.dtype = dt.INT32

    def emit(self, ctx):
        cv = self.child.emit(ctx)
        days = (ops_dt.micros_to_days(cv.data)
                if isinstance(self.child.dtype, dt.TimestampType)
                else cv.data)
        return CV(type(self).kernel(days), cv.validity)

    def __repr__(self):
        return f"{type(self).__name__.lower()}({self.child})"


class Year(_DateField):
    kernel = staticmethod(ops_dt.year)


class Month(_DateField):
    kernel = staticmethod(ops_dt.month)


class DayOfMonth(_DateField):
    kernel = staticmethod(ops_dt.day)


class DayOfWeek(_DateField):
    kernel = staticmethod(ops_dt.day_of_week)


class DayOfYear(_DateField):
    kernel = staticmethod(ops_dt.day_of_year)


class Quarter(_DateField):
    kernel = staticmethod(ops_dt.quarter)


class _TimeField(_UnaryOp):
    kernel = None

    def _resolve_type(self):
        if not isinstance(self.child.dtype, dt.TimestampType):
            raise UnsupportedExpr(f"{type(self).__name__} needs timestamp")
        self.dtype = dt.INT32

    def emit(self, ctx):
        cv = self.child.emit(ctx)
        return CV(type(self).kernel(cv.data), cv.validity)


class Hour(_TimeField):
    kernel = staticmethod(ops_dt.hour)


class Minute(_TimeField):
    kernel = staticmethod(ops_dt.minute)


class Second(_TimeField):
    kernel = staticmethod(ops_dt.second)


class _DateDelta(_BinaryOp):
    sign = 1

    def _resolve_type(self):
        if not isinstance(self.left.dtype, dt.DateType):
            raise UnsupportedExpr("date_add/sub needs a date")
        if not self.right.dtype.is_integral:
            raise UnsupportedExpr("date_add/sub delta must be integral")
        self.dtype = dt.DATE

    def emit(self, ctx):
        l, r = self.left.emit(ctx), self.right.emit(ctx)
        out = l.data + self.sign * r.data.astype(jnp.int32)
        return CV(out.astype(jnp.int32), l.validity & r.validity)


class DateAdd(_DateDelta):
    sign = 1
    symbol = "date_add"


class DateSub(_DateDelta):
    sign = -1
    symbol = "date_sub"


class DateDiff(_BinaryOp):
    symbol = "datediff"

    def _resolve_type(self):
        if not (isinstance(self.left.dtype, dt.DateType)
                and isinstance(self.right.dtype, dt.DateType)):
            raise UnsupportedExpr("datediff needs dates")
        self.dtype = dt.INT32

    def emit(self, ctx):
        l, r = self.left.emit(ctx), self.right.emit(ctx)
        return CV((l.data - r.data).astype(jnp.int32),
                  l.validity & r.validity)


class LastDay(_UnaryOp):
    def _resolve_type(self):
        if not isinstance(self.child.dtype, dt.DateType):
            raise UnsupportedExpr("last_day needs a date")
        self.dtype = dt.DATE

    def emit(self, ctx):
        cv = self.child.emit(ctx)
        return CV(ops_dt.last_day(cv.data), cv.validity)


class ToDate(_UnaryOp):
    def _resolve_type(self):
        ct = self.child.dtype
        if isinstance(ct, (dt.DateType, dt.TimestampType, dt.StringType)):
            self.dtype = dt.DATE
        else:
            raise UnsupportedExpr(f"to_date({ct})")

    def emit(self, ctx):
        cv = self.child.emit(ctx)
        if isinstance(self.child.dtype, dt.TimestampType):
            return CV(ops_dt.micros_to_days(cv.data), cv.validity)
        if isinstance(self.child.dtype, dt.StringType):
            from ..ops.cast_strings import string_to_date
            return string_to_date(cv)
        return cv


class ToTimestamp(_UnaryOp):
    def _resolve_type(self):
        ct = self.child.dtype
        if isinstance(ct, (dt.TimestampType, dt.DateType, dt.StringType)):
            self.dtype = dt.TIMESTAMP
        else:
            raise UnsupportedExpr(f"to_timestamp({ct})")

    def emit(self, ctx):
        cv = self.child.emit(ctx)
        ct = self.child.dtype
        if isinstance(ct, dt.TimestampType):
            return cv
        if isinstance(ct, dt.DateType):
            return CV(cv.data.astype(jnp.int64) * ops_dt.MICROS_PER_DAY,
                      cv.validity)
        from ..ops.cast_strings import string_to_timestamp
        return string_to_timestamp(cv)


class _TzConvert(Expression):
    """from_utc_timestamp / to_utc_timestamp over the TZif transition
    tables (reference: GpuFromUTCTimestamp/GpuToUTCTimestamp +
    GpuTimeZoneDB device table; here utils/tzdb.py). Per batch: one
    searchsorted over the zone's transition instants + a gather — fully
    vectorized, tables become XLA constants."""

    to_utc = False

    def __init__(self, child: Expression, tz: str):
        self.child = child
        self.tz = tz
        self.children = [child]

    def bind(self, schema):
        b = type(self)(self.child.bind(schema), self.tz)
        if not isinstance(b.child.dtype, dt.TimestampType):
            raise UnsupportedExpr(
                f"{type(self).__name__} on {b.child.dtype}")
        from ..utils.tzdb import load_transitions
        try:
            load_transitions(self.tz)
        except ValueError as e:
            raise UnsupportedExpr(str(e))
        b.dtype = dt.TIMESTAMP
        return b

    def emit(self, ctx):
        from ..utils.tzdb import utc_to_wall_tables, wall_to_utc_tables
        tables = (wall_to_utc_tables if self.to_utc
                  else utc_to_wall_tables)(self.tz)
        trans = jnp.asarray(tables[0])
        offs = jnp.asarray(tables[1])
        cv = self.child.emit(ctx)
        idx = jnp.searchsorted(trans, cv.data, side="right") - 1
        off = offs[jnp.clip(idx, 0, offs.shape[0] - 1)]
        out = cv.data - off if self.to_utc else cv.data + off
        return CV(out, cv.validity)

    def __repr__(self):
        fn = "to_utc_timestamp" if self.to_utc else "from_utc_timestamp"
        return f"{fn}({self.child}, {self.tz!r})"


class FromUTCTimestamp(_TzConvert):
    to_utc = False


class ToUTCTimestamp(_TzConvert):
    to_utc = True
