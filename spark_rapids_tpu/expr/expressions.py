"""Expression trees: resolution, Spark type coercion, and traced evaluation.

The analog of the reference's GpuExpression layer (reference:
sql-plugin/.../RapidsMeta.scala:1112 BaseExprMeta; arithmetic.scala,
predicates.scala). Differences, TPU-first:

  - An expression node's `emit(ctx)` runs *inside* a jax trace and returns a
    `CV`; the whole bound tree therefore compiles into one fused XLA program
    instead of a sequence of cudf kernel launches.
  - Binding maps ColumnRef -> BoundRef(ordinal) against an input Schema, like
    the reference's `GpuBindReferences.bindGpuReferences`.

Unsupported expressions raise `UnsupportedExpr` during binding — the planner
catches this and falls back to CPU for the enclosing operator, mirroring
`willNotWorkOnGpu` tagging (RapidsMeta.scala:87).
"""
from __future__ import annotations

import datetime
import decimal
import math
from typing import List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..columnar import dtypes as dt
from ..columnar.table import Schema
from ..ops import elementwise as ew
from ..ops.kernel_utils import CV

__all__ = [
    "Expression", "UnsupportedExpr", "EmitCtx", "ColumnRef", "BoundRef",
    "Literal", "Alias", "Add", "Subtract", "Multiply", "Divide", "IntDivide",
    "Remainder", "Pmod", "Negate", "Abs", "Eq", "Ne", "Lt", "Le", "Gt", "Ge",
    "EqNullSafe", "And", "Or", "Not", "IsNull", "IsNotNull", "IsNaN", "Cast",
    "Coalesce", "If", "CaseWhen", "In", "MathUnary", "Round", "Greatest",
    "Least", "lit", "col", "BitwiseAnd", "BitwiseOr", "BitwiseXor",
    "BitwiseNot", "ShiftLeft", "ShiftRight", "Pow", "Atan2",
]


class UnsupportedExpr(Exception):
    """Raised at bind time when an expression cannot run on TPU."""


class EmitCtx:
    """Trace-time context: the input CVs and the batch capacity."""

    def __init__(self, cvs: Sequence[CV], capacity: int):
        self.cvs = list(cvs)
        self.capacity = capacity
        # bound lambda-variable values for higher-order array functions
        # (collection_exprs): var id -> element-domain CV
        self.lambda_vals = {}


class Expression:
    children: List["Expression"] = []
    dtype: Optional[dt.DataType] = None   # set after bind

    def bind(self, schema: Schema) -> "Expression":
        raise NotImplementedError

    def emit(self, ctx: EmitCtx) -> CV:
        raise NotImplementedError

    @property
    def name(self) -> str:
        return str(self)

    # Fluent builder API (the DataFrame `Column` surface).
    def alias(self, name):
        return Alias(self, name)

    def cast(self, dtype):
        return Cast(self, dtype)

    def __add__(self, o):
        return Add(self, _wrap(o))

    def __radd__(self, o):
        return Add(_wrap(o), self)

    def __sub__(self, o):
        return Subtract(self, _wrap(o))

    def __rsub__(self, o):
        return Subtract(_wrap(o), self)

    def __mul__(self, o):
        return Multiply(self, _wrap(o))

    def __rmul__(self, o):
        return Multiply(_wrap(o), self)

    def __truediv__(self, o):
        return Divide(self, _wrap(o))

    def __mod__(self, o):
        return Remainder(self, _wrap(o))

    def __neg__(self):
        return Negate(self)

    def __eq__(self, o):  # type: ignore[override]
        return Eq(self, _wrap(o))

    def __ne__(self, o):  # type: ignore[override]
        return Ne(self, _wrap(o))

    def __lt__(self, o):
        return Lt(self, _wrap(o))

    def __le__(self, o):
        return Le(self, _wrap(o))

    def __gt__(self, o):
        return Gt(self, _wrap(o))

    def __ge__(self, o):
        return Ge(self, _wrap(o))

    def __and__(self, o):
        return And(self, _wrap(o))

    def __or__(self, o):
        return Or(self, _wrap(o))

    def __invert__(self):
        return Not(self)

    def __hash__(self):
        return id(self)

    def isNull(self):
        return IsNull(self)

    def isNotNull(self):
        return IsNotNull(self)

    def isin(self, *values):
        return In(self, [_wrap(v) for v in values])

    def between(self, lo, hi):
        return And(Ge(self, _wrap(lo)), Le(self, _wrap(hi)))

    # string surface (module imported lazily to avoid a cycle)
    def contains(self, pattern):
        from .string_exprs import Contains
        return Contains(self, _wrap(pattern))

    def startswith(self, pattern):
        from .string_exprs import StartsWith
        return StartsWith(self, _wrap(pattern))

    def endswith(self, pattern):
        from .string_exprs import EndsWith
        return EndsWith(self, _wrap(pattern))

    def like(self, pattern: str):
        from .string_exprs import Like
        return Like(self, pattern)

    def rlike(self, pattern: str):
        from .regex_exprs import RLike
        return RLike(self, pattern)

    def substr(self, start, length=None):
        from .string_exprs import Substring
        return Substring(self, start, length)

    def getItem(self, key):
        from .collection_exprs import GetArrayItem
        return GetArrayItem(self, _wrap(key))

    def getField(self, name: str):
        from .collection_exprs import GetStructField
        return GetStructField(self, name)

    def __getitem__(self, key):
        if isinstance(key, str):
            return self.getField(key)
        return self.getItem(key)


def _wrap(v) -> Expression:
    return v if isinstance(v, Expression) else Literal(v)


def col(name: str) -> "ColumnRef":
    return ColumnRef(name)


def lit(v) -> "Literal":
    return Literal(v)


# ----------------------------------------------------------------------
class ColumnRef(Expression):
    def __init__(self, name: str):
        self._name = name
        self.children = []

    @property
    def name(self):
        return self._name

    def bind(self, schema: Schema):
        idx = schema.index_of(self._name)
        return BoundRef(idx, schema[idx].dtype, self._name)

    def __repr__(self):
        return self._name


class BoundRef(Expression):
    def __init__(self, ordinal: int, dtype: dt.DataType, name: str = ""):
        self.ordinal = ordinal
        self.dtype = dtype
        self._name = name or f"c{ordinal}"
        self.children = []

    @property
    def name(self):
        return self._name

    def bind(self, schema):
        return self

    def emit(self, ctx: EmitCtx) -> CV:
        return ctx.cvs[self.ordinal]

    def __repr__(self):
        return f"{self._name}#{self.ordinal}"


def _infer_literal_dtype(v) -> dt.DataType:
    if v is None:
        return dt.NULLTYPE
    if isinstance(v, bool):
        return dt.BOOL
    if isinstance(v, int):
        return dt.INT32 if -2**31 <= v < 2**31 else dt.INT64
    if isinstance(v, float):
        return dt.FLOAT64
    if isinstance(v, str):
        return dt.STRING
    if isinstance(v, bytes):
        return dt.BINARY
    if isinstance(v, decimal.Decimal):
        sign, digits, exp = v.as_tuple()
        scale = max(0, -exp)
        precision = max(len(digits), scale)
        return dt.DecimalType(precision, scale)
    if isinstance(v, datetime.datetime):
        return dt.TIMESTAMP
    if isinstance(v, datetime.date):
        return dt.DATE
    raise UnsupportedExpr(f"cannot infer literal type for {v!r}")


class Literal(Expression):
    def __init__(self, value, dtype: Optional[dt.DataType] = None):
        self.value = value
        self.dtype = dtype or _infer_literal_dtype(value)
        self.children = []

    def bind(self, schema):
        return self

    def device_value(self):
        v, d = self.value, self.dtype
        if v is None:
            return 0
        if isinstance(d, dt.DecimalType):
            return int(decimal.Decimal(v).scaleb(d.scale).to_integral_value(
                rounding=decimal.ROUND_HALF_UP))
        if isinstance(d, dt.DateType):
            return (v - datetime.date(1970, 1, 1)).days
        if isinstance(d, dt.TimestampType):
            ts = v if v.tzinfo else v.replace(tzinfo=datetime.timezone.utc)
            return int(ts.timestamp() * 1_000_000)
        if isinstance(d, (dt.StringType, dt.BinaryType)):
            return v
        return v

    def emit(self, ctx: EmitCtx) -> CV:
        cap = ctx.capacity
        if self.value is None:
            from ..columnar.column import alloc_shape
            np_dt = self.dtype.np_dtype or np.int8
            return CV(jnp.zeros(alloc_shape(self.dtype, cap), np_dt),
                      jnp.zeros(cap, jnp.bool_))
        if isinstance(self.dtype, dt.DecimalType) \
                and self.dtype.is_decimal128:
            u = self.device_value() & ((1 << 128) - 1)
            lo = u & ((1 << 64) - 1)
            hi = u >> 64
            lo = lo - (1 << 64) if lo >= (1 << 63) else lo
            hi = hi - (1 << 64) if hi >= (1 << 63) else hi
            row = jnp.asarray([lo, hi], jnp.int64)
            return CV(jnp.broadcast_to(row, (cap, 2)),
                      jnp.ones(cap, jnp.bool_))
        if isinstance(self.dtype, (dt.StringType, dt.BinaryType)):
            raw = (self.value.encode() if isinstance(self.value, str)
                   else self.value)
            nb = len(raw)
            if nb == 0:
                return CV(jnp.zeros(128, jnp.uint8), jnp.ones(cap, jnp.bool_),
                          jnp.zeros(cap + 1, jnp.int32))
            # tile the bytes so offsets stay monotonic (Arrow invariant)
            tiled = np.tile(np.frombuffer(raw, np.uint8), cap)
            off = (jnp.arange(cap + 1, dtype=jnp.int32) * nb)
            return CV(jnp.asarray(tiled), jnp.ones(cap, jnp.bool_), off)
        return CV(jnp.full(cap, self.device_value(), self.dtype.np_dtype),
                  jnp.ones(cap, jnp.bool_))

    def __repr__(self):
        return repr(self.value)


class Alias(Expression):
    def __init__(self, child: Expression, name: str):
        self.child = child
        self._name = name
        self.children = [child]

    @property
    def name(self):
        return self._name

    def bind(self, schema):
        b = Alias(self.child.bind(schema), self._name)
        b.dtype = b.child.dtype
        return b

    def emit(self, ctx):
        return self.child.emit(ctx)

    def __repr__(self):
        return f"{self.child} AS {self._name}"


# ----------------------------------------------------------------------
# Implicit cast insertion (Spark's binary-op type coercion)
# ----------------------------------------------------------------------
def _coerce_pair(l: Expression, r: Expression, for_division=False):
    lt_, rt = l.dtype, r.dtype
    if isinstance(lt_, dt.NullType):
        l = Cast.bound(l, rt)
        lt_ = rt
    if isinstance(rt, dt.NullType):
        r = Cast.bound(r, lt_)
        rt = lt_
    if isinstance(lt_, dt.DecimalType) or isinstance(rt, dt.DecimalType):
        return _coerce_decimal(l, r, for_division)
    if for_division:
        if not lt_.is_floating:
            l = Cast.bound(l, dt.FLOAT64)
        if not rt.is_floating:
            r = Cast.bound(r, dt.FLOAT64)
        lt_, rt = l.dtype, r.dtype
    if lt_ == rt:
        return l, r, lt_
    out = dt.promote(lt_, rt)
    if lt_ != out:
        l = Cast.bound(l, out)
    if rt != out:
        r = Cast.bound(r, out)
    return l, r, out


def _coerce_decimal(l, r, for_division):
    # decimal op decimal/integral: Spark's implicit coercion; results over
    # precision 18 run on the exact decimal128 kernels.
    def as_dec(e):
        if isinstance(e.dtype, dt.DecimalType):
            return e
        if e.dtype.is_integral:
            # Spark: Byte->dec(3,0) Short->dec(5,0) Int->dec(10,0)
            # Long->dec(20,0)
            p = {1: 3, 2: 5, 4: 10, 8: 20}[e.dtype.np_dtype.itemsize]
            return Cast.bound(e, dt.DecimalType(p, 0))
        raise UnsupportedExpr(f"decimal with {e.dtype}")
    if l.dtype.is_floating or r.dtype.is_floating:
        return (Cast.bound(l, dt.FLOAT64), Cast.bound(r, dt.FLOAT64),
                dt.FLOAT64)
    l, r = as_dec(l), as_dec(r)
    return l, r, None  # result dtype decided per-op


class _BinaryOp(Expression):
    symbol = "?"

    def __init__(self, left: Expression, right: Expression):
        self.left, self.right = left, right
        self.children = [left, right]

    def bind(self, schema):
        b = type(self)(self.left.bind(schema), self.right.bind(schema))
        b._resolve_type()
        return b

    def _resolve_type(self):
        raise NotImplementedError

    def __repr__(self):
        return f"({self.left} {self.symbol} {self.right})"


def _dec_scale_shift(cv: CV, shift: int) -> CV:
    if shift == 0:
        return cv
    return CV(cv.data * (10 ** shift), cv.validity)


def _reject_d128(dtype, what: str):
    """Gate for operators not yet wired to the two-limb kernels: a
    decimal128 column through a plain elementwise kernel would silently
    corrupt (1-D math over [cap,2] limb pairs)."""
    if isinstance(dtype, dt.DecimalType) and dtype.is_decimal128:
        raise UnsupportedExpr(
            f"{what} over decimal precision > 18 not yet implemented")


def _adjust_precision_scale(p: int, s: int):
    """Spark DecimalType.adjustPrecisionScale: clamp precision at 38,
    sacrificing scale down to a floor of min(s, 6)."""
    if p <= 38:
        return p, s
    int_digits = p - s
    min_scale = min(s, 6)
    adjusted = max(38 - int_digits, min_scale)
    return 38, adjusted


def _as_dec128(cv: CV, dtype) -> CV:
    """Widen a decimal64 CV to the [cap,2] limb layout (no-op for 128)."""
    if dtype.is_decimal128:
        return cv
    from ..ops.decimal128 import dec_from_i64
    return CV(dec_from_i64(cv.data), cv.validity)


class _Arith(_BinaryOp):
    kernel = None
    dec128_fn = None    # d128.dec_add / dec_sub

    def _resolve_type(self):
        self.left, self.right, out = _coerce_pair(self.left, self.right)
        if out is None:  # decimal
            p1, s1 = self.left.dtype.precision, self.left.dtype.scale
            p2, s2 = self.right.dtype.precision, self.right.dtype.scale
            s = max(s1, s2)
            p = max(p1 - s1, p2 - s2) + s + 1
            p, s = _adjust_precision_scale(p, s)
            self.dtype = dt.DecimalType(p, s)
        else:
            self.dtype = out

    def emit(self, ctx):
        l, r = self.left.emit(ctx), self.right.emit(ctx)
        if isinstance(self.dtype, dt.DecimalType):
            s = self.dtype.scale
            if self.dtype.is_decimal128:
                # exact 128-bit two-limb path (JNI DecimalUtils analog)
                from ..ops import decimal128 as d128
                ld = _as_dec128(l, self.left.dtype)
                rd = _as_dec128(r, self.right.dtype)
                la, o1 = d128.dec_rescale(ld.data, self.left.dtype.scale,
                                          s, 38)
                ra, o2 = d128.dec_rescale(rd.data, self.right.dtype.scale,
                                          s, 38)
                res, o3 = type(self).dec128_fn(la, ra)
                ok = d128.fits_precision(d128.to_limbs(res),
                                         self.dtype.precision)
                valid = (l.validity & r.validity & ~o1 & ~o2 & ~o3 & ok)
                return CV(res, valid)
            l = _dec_scale_shift(l, s - self.left.dtype.scale)
            r = _dec_scale_shift(r, s - self.right.dtype.scale)
        return type(self).kernel(l, r)


class Add(_Arith):
    symbol = "+"
    kernel = staticmethod(ew.add)

    @staticmethod
    def dec128_fn(a, b):
        from ..ops.decimal128 import dec_add
        return dec_add(a, b)


class Subtract(_Arith):
    symbol = "-"
    kernel = staticmethod(ew.sub)

    @staticmethod
    def dec128_fn(a, b):
        from ..ops.decimal128 import dec_sub
        return dec_sub(a, b)


class Multiply(_BinaryOp):
    symbol = "*"

    def _resolve_type(self):
        self.left, self.right, out = _coerce_pair(self.left, self.right)
        if out is None:
            p1, s1 = self.left.dtype.precision, self.left.dtype.scale
            p2, s2 = self.right.dtype.precision, self.right.dtype.scale
            p, s = _adjust_precision_scale(p1 + p2 + 1, s1 + s2)
            self._full_scale = s1 + s2
            self.dtype = dt.DecimalType(p, s)
        else:
            self.dtype = out

    def emit(self, ctx):
        l, r = self.left.emit(ctx), self.right.emit(ctx)
        if isinstance(self.dtype, dt.DecimalType) \
                and self.dtype.is_decimal128:
            from ..ops import decimal128 as d128
            ld = _as_dec128(l, self.left.dtype)
            rd = _as_dec128(r, self.right.dtype)
            res, ovf = d128.dec_mul_scaled(
                ld.data, rd.data, self._full_scale - self.dtype.scale,
                self.dtype.precision)
            return CV(res, l.validity & r.validity & ~ovf)
        return ew.mul(l, r)


class Divide(_BinaryOp):
    symbol = "/"

    def _resolve_type(self):
        self.left, self.right, out = _coerce_pair(self.left, self.right,
                                                  for_division=True)
        if out is None:
            # Spark decimal division result type, exact 128-bit long
            # division with HALF_UP (JNI DecimalUtils.divide128 analog)
            p1, s1 = self.left.dtype.precision, self.left.dtype.scale
            p2, s2 = self.right.dtype.precision, self.right.dtype.scale
            s = max(6, s1 + p2 + 1)
            p = p1 - s1 + s2 + s
            p, s = _adjust_precision_scale(p, s)
            self.dtype = dt.DecimalType(p, s)
        else:
            self.dtype = out

    def emit(self, ctx):
        l, r = self.left.emit(ctx), self.right.emit(ctx)
        if isinstance(self.dtype, dt.DecimalType):
            from ..ops import decimal128 as d128
            s = self.dtype.scale
            shift = s - self.left.dtype.scale + self.right.dtype.scale
            ld = _as_dec128(l, self.left.dtype)
            rd = _as_dec128(r, self.right.dtype)
            res, ovf, divzero = d128.dec_div(
                ld.data, rd.data, shift, self.dtype.precision,
                num_digits=self.left.dtype.precision)
            valid = ew.and_validity(l, r) & ~ovf & ~divzero
            if self.dtype.is_decimal128:
                return CV(res, valid)
            v64, fits = d128.dec_to_i64(res)
            return CV(v64, valid & fits)
        return ew.divide(l, r)


class IntDivide(_BinaryOp):
    symbol = "div"

    def _resolve_type(self):
        self.left, self.right, out = _coerce_pair(self.left, self.right)
        if out is None or not out.is_integral:
            if out is None:
                _reject_d128(self.left.dtype, "div")
                _reject_d128(self.right.dtype, "div")
                self.dtype = dt.INT64
                return
            raise UnsupportedExpr("div on non-integral")
        self.dtype = dt.INT64

    def emit(self, ctx):
        l, r = self.left.emit(ctx), self.right.emit(ctx)
        if isinstance(self.left.dtype, dt.DecimalType):
            s1, s2 = self.left.dtype.scale, self.right.dtype.scale
            s = max(s1, s2)
            l = _dec_scale_shift(l, s - s1)
            r = _dec_scale_shift(r, s - s2)
        out = ew.int_divide(l, r)
        return CV(out.data.astype(jnp.int64), out.validity)


class Remainder(_BinaryOp):
    symbol = "%"

    def _resolve_type(self):
        self.left, self.right, out = _coerce_pair(self.left, self.right)
        if out is None:
            _reject_d128(self.left.dtype, "remainder")
            _reject_d128(self.right.dtype, "remainder")
            s = max(self.left.dtype.scale, self.right.dtype.scale)
            p = min(18, max(self.left.dtype.precision,
                            self.right.dtype.precision))
            self.dtype = dt.DecimalType(p, s)
        else:
            self.dtype = out

    def emit(self, ctx):
        l, r = self.left.emit(ctx), self.right.emit(ctx)
        if isinstance(self.dtype, dt.DecimalType):
            s = self.dtype.scale
            l = _dec_scale_shift(l, s - self.left.dtype.scale)
            r = _dec_scale_shift(r, s - self.right.dtype.scale)
        return ew.remainder(l, r)


class Pmod(Remainder):
    symbol = "pmod"

    def emit(self, ctx):
        l, r = self.left.emit(ctx), self.right.emit(ctx)
        if isinstance(self.dtype, dt.DecimalType):
            s = self.dtype.scale
            l = _dec_scale_shift(l, s - self.left.dtype.scale)
            r = _dec_scale_shift(r, s - self.right.dtype.scale)
        return ew.pmod(l, r)


class _UnaryOp(Expression):
    def __init__(self, child: Expression):
        self.child = child
        self.children = [child]

    def bind(self, schema):
        b = type(self)(self.child.bind(schema))
        b._resolve_type()
        return b

    def _resolve_type(self):
        self.dtype = self.child.dtype


class Negate(_UnaryOp):
    def _resolve_type(self):
        _reject_d128(self.child.dtype, "negate")
        self.dtype = self.child.dtype

    def emit(self, ctx):
        return ew.negate(self.child.emit(ctx))

    def __repr__(self):
        return f"(- {self.child})"


class Abs(_UnaryOp):
    def _resolve_type(self):
        _reject_d128(self.child.dtype, "abs")
        self.dtype = self.child.dtype

    def emit(self, ctx):
        return ew.abs_(self.child.emit(ctx))

    def __repr__(self):
        return f"abs({self.child})"


class _Comparison(_BinaryOp):
    kernel = None
    cmp_op = None   # for string compares: applied to sign(-1/0/1)

    def _resolve_type(self):
        lt_, rt = self.left.dtype, self.right.dtype
        l_str = isinstance(lt_, (dt.StringType, dt.BinaryType))
        r_str = isinstance(rt, (dt.StringType, dt.BinaryType))
        if l_str != r_str:
            raise UnsupportedExpr("string/non-string compare")
        if not l_str and lt_ != rt:
            self.left, self.right, _ = _coerce_pair(self.left, self.right)
        self.dtype = dt.BOOL

    def emit(self, ctx):
        # literal string equality: chunked compare, not the byte-domain
        # walk (ops.strings.equals_literal)
        if (isinstance(self.left.dtype, (dt.StringType, dt.BinaryType))
                and type(self) in (Eq, Ne)):
            lit = col = None
            if isinstance(self.right, Literal):
                lit, col = self.right, self.left
            elif isinstance(self.left, Literal):
                lit, col = self.left, self.right
            if lit is not None and isinstance(lit.value, (str, bytes)):
                from ..ops import strings as ops_str
                cv = col.emit(ctx)
                raw = (lit.value.encode() if isinstance(lit.value, str)
                       else lit.value)
                eq = ops_str.equals_literal(cv, raw)
                if type(self) is Ne:
                    eq = jnp.logical_not(eq)
                return CV(eq, cv.validity)
        l, r = self.left.emit(ctx), self.right.emit(ctx)
        if isinstance(self.left.dtype, (dt.StringType, dt.BinaryType)):
            from ..ops import strings as ops_str
            c = ops_str.compare(l, r)
            return CV(type(self).cmp_op(c), ew.and_validity(l, r))
        if isinstance(self.left.dtype, dt.DecimalType):
            lt_, rt = self.left.dtype, self.right.dtype
            if lt_.is_decimal128 or rt.is_decimal128:
                from ..ops.decimal128 import dec_cmp_scaled
                ld = _as_dec128(l, lt_)
                rd = _as_dec128(r, rt)
                c = dec_cmp_scaled(ld.data, lt_.scale, rd.data, rt.scale)
                return CV(type(self).cmp_op(c), ew.and_validity(l, r))
            s = max(lt_.scale, rt.scale)
            l = _dec_scale_shift(l, s - lt_.scale)
            r = _dec_scale_shift(r, s - rt.scale)
        return type(self).kernel(l, r)


class Eq(_Comparison):
    symbol = "="
    kernel = staticmethod(ew.eq)
    cmp_op = staticmethod(lambda c: c == 0)


class Ne(_Comparison):
    symbol = "!="
    kernel = staticmethod(ew.ne)
    cmp_op = staticmethod(lambda c: c != 0)


class Lt(_Comparison):
    symbol = "<"
    kernel = staticmethod(ew.lt)
    cmp_op = staticmethod(lambda c: c < 0)


class Le(_Comparison):
    symbol = "<="
    kernel = staticmethod(ew.le)
    cmp_op = staticmethod(lambda c: c <= 0)


class Gt(_Comparison):
    symbol = ">"
    kernel = staticmethod(ew.gt)
    cmp_op = staticmethod(lambda c: c > 0)


class Ge(_Comparison):
    symbol = ">="
    kernel = staticmethod(ew.ge)
    cmp_op = staticmethod(lambda c: c >= 0)


class EqNullSafe(_Comparison):
    symbol = "<=>"
    kernel = staticmethod(ew.eq_null_safe)

    def emit(self, ctx):
        l, r = self.left.emit(ctx), self.right.emit(ctx)
        if isinstance(self.left.dtype, (dt.StringType, dt.BinaryType)):
            from ..ops import strings as ops_str
            c = ops_str.compare(l, r)
            both_null = ~l.validity & ~r.validity
            both_valid = l.validity & r.validity
            out = both_null | (both_valid & (c == 0))
            return CV(out, jnp.ones_like(out))
        return super().emit(ctx)


class And(_BinaryOp):
    symbol = "AND"

    def _resolve_type(self):
        self.dtype = dt.BOOL

    def emit(self, ctx):
        return ew.logical_and(self.left.emit(ctx), self.right.emit(ctx))


class Or(_BinaryOp):
    symbol = "OR"

    def _resolve_type(self):
        self.dtype = dt.BOOL

    def emit(self, ctx):
        return ew.logical_or(self.left.emit(ctx), self.right.emit(ctx))


class Not(_UnaryOp):
    def _resolve_type(self):
        self.dtype = dt.BOOL

    def emit(self, ctx):
        return ew.logical_not(self.child.emit(ctx))

    def __repr__(self):
        return f"NOT {self.child}"


class IsNull(_UnaryOp):
    def _resolve_type(self):
        self.dtype = dt.BOOL

    def emit(self, ctx):
        return ew.is_null(self.child.emit(ctx))

    def __repr__(self):
        return f"({self.child} IS NULL)"


class IsNotNull(_UnaryOp):
    def _resolve_type(self):
        self.dtype = dt.BOOL

    def emit(self, ctx):
        return ew.is_not_null(self.child.emit(ctx))

    def __repr__(self):
        return f"({self.child} IS NOT NULL)"


class IsNaN(_UnaryOp):
    def _resolve_type(self):
        self.dtype = dt.BOOL

    def emit(self, ctx):
        return ew.is_nan(self.child.emit(ctx))


class Cast(Expression):
    """Spark CAST. Full string<->numeric semantics live in ops/cast.py;
    numeric/temporal casts are inline here."""

    def __init__(self, child: Expression, to, ansi=False):
        self.child = child
        if isinstance(to, str):
            to = dt.from_name(to)   # pyspark-style .cast("bigint")
        self.to = to
        self.ansi = ansi
        self.children = [child]

    @staticmethod
    def bound(child: Expression, to: dt.DataType) -> "Cast":
        c = Cast(child, to)
        c.dtype = to
        return c

    def bind(self, schema):
        b = Cast(self.child.bind(schema), self.to, self.ansi)
        b.dtype = self.to
        from_t = b.child.dtype
        str_src_ok = (isinstance(from_t, dt.StringType)
                      and (self.to.is_numeric
                           or isinstance(self.to, (dt.BooleanType,
                                                   dt.DateType,
                                                   dt.TimestampType))))
        str_dst_ok = (isinstance(self.to, dt.StringType)
                      and (from_t.is_integral
                           or isinstance(from_t, (dt.BooleanType,
                                                  dt.DecimalType,
                                                  dt.DateType,
                                                  dt.TimestampType))))
        ok = (from_t == self.to or
              (from_t.is_numeric and self.to.is_numeric) or
              isinstance(from_t, dt.NullType) or
              (isinstance(from_t, dt.BooleanType) and self.to.is_numeric) or
              (from_t.is_numeric and isinstance(self.to, dt.BooleanType)) or
              (isinstance(from_t, dt.TimestampType)
               and (self.to.is_numeric
                    or isinstance(self.to, dt.DateType))) or
              (isinstance(from_t, dt.DateType)
               and isinstance(self.to, (dt.TimestampType, dt.IntegerType))) or
              (from_t.is_numeric
               and isinstance(self.to, dt.TimestampType)) or
              str_src_ok or str_dst_ok)
        if not ok:
            raise UnsupportedExpr(f"cast {from_t} -> {self.to}")
        return b

    def emit(self, ctx):
        from ..ops import cast as cast_ops
        from ..ops import cast_strings as cs
        cv = self.child.emit(ctx)
        from_t = self.child.dtype
        if isinstance(from_t, dt.StringType) and not isinstance(
                self.to, dt.StringType):
            if self.to.is_integral:
                return cs.string_to_int(cv, self.to)
            if self.to.is_floating:
                out = cs.string_to_float(cv)
                return CV(out.data.astype(self.to.np_dtype), out.validity)
            if isinstance(self.to, dt.BooleanType):
                return cs.string_to_bool(cv)
            if isinstance(self.to, dt.DateType):
                return cs.string_to_date(cv)
            if isinstance(self.to, dt.TimestampType):
                return cs.string_to_timestamp(cv)
            if isinstance(self.to, dt.DecimalType):
                return cs.string_to_decimal(cv, self.to)
        if isinstance(self.to, dt.StringType) and not isinstance(
                from_t, dt.StringType):
            if isinstance(from_t, dt.NullType):
                return CV(jnp.zeros(128, jnp.uint8),
                          jnp.zeros(cv.capacity, jnp.bool_),
                          jnp.zeros(cv.capacity + 1, jnp.int32))
            if isinstance(from_t, dt.BooleanType):
                return cs.bool_to_string(cv)
            if isinstance(from_t, dt.DecimalType):
                return cs.decimal_to_string(cv, from_t.scale)
            if isinstance(from_t, dt.DateType):
                return cs.date_to_string(cv)
            if isinstance(from_t, dt.TimestampType):
                return cs.timestamp_to_string(cv)
            if from_t.is_integral:
                return cs.int_to_string(cv)
            raise UnsupportedExpr(f"cast {from_t} -> string")
        return cast_ops.cast_cv(cv, from_t, self.to)

    def __repr__(self):
        return f"CAST({self.child} AS {self.to})"


def _select_cv(pick_a, a: CV, b: CV, out_valid) -> CV:
    """Row-wise select between two CVs; handles var-width via a gather
    over the concatenation of both buffers."""
    if a.offsets is not None or b.offsets is not None:
        from ..ops.concat import concat_cvs
        from ..ops.gather import take_strings
        combined = concat_cvs([a, b], dt.STRING)
        cap = pick_a.shape[0]
        idx = jnp.where(pick_a, jnp.arange(cap), cap + jnp.arange(cap))
        out = take_strings(combined, idx.astype(jnp.int32))
        return CV(out.data, out_valid, out.offsets)
    return CV(jnp.where(pick_a, a.data, b.data), out_valid)


class Coalesce(Expression):
    def __init__(self, *children: Expression):
        self.children = list(children)

    def bind(self, schema):
        bc = [c.bind(schema) for c in self.children]
        out = next((c.dtype for c in bc
                    if not isinstance(c.dtype, dt.NullType)), dt.NULLTYPE)
        bc = [c if c.dtype == out else Cast.bound(c, out) for c in bc]
        b = Coalesce(*bc)
        b.dtype = out
        return b

    def emit(self, ctx):
        cvs = [c.emit(ctx) for c in self.children]
        out = cvs[-1]
        for cv in reversed(cvs[:-1]):
            out = _select_cv(cv.validity, cv, out, cv.validity | out.validity)
        return out

    def __repr__(self):
        return "coalesce(" + ", ".join(map(repr, self.children)) + ")"


class If(Expression):
    def __init__(self, pred: Expression, t: Expression, f: Expression):
        self.pred, self.t, self.f = pred, t, f
        self.children = [pred, t, f]

    def bind(self, schema):
        p, t, f = (c.bind(schema) for c in self.children)
        out = t.dtype if not isinstance(t.dtype, dt.NullType) else f.dtype
        if t.dtype != out:
            t = Cast.bound(t, out)
        if f.dtype != out:
            f = Cast.bound(f, out)
        b = If(p, t, f)
        b.dtype = out
        return b

    def emit(self, ctx):
        p, t, f = (c.emit(ctx) for c in self.children)
        take_t = p.validity & p.data.astype(jnp.bool_)
        out_valid = jnp.where(take_t, t.validity, f.validity)
        return _select_cv(take_t, t, f, out_valid)

    def __repr__(self):
        return f"if({self.pred}, {self.t}, {self.f})"


class CaseWhen(Expression):
    """CASE WHEN p1 THEN v1 ... [ELSE d] END, built as nested If at bind."""

    def __init__(self, branches, default: Optional[Expression] = None):
        self.branches = branches
        self.default = default
        self.children = ([e for p, v in branches for e in (p, v)]
                         + ([default] if default else []))

    def bind(self, schema):
        expr: Expression = self.default or Literal(None)
        for p, v in reversed(self.branches):
            expr = If(p, v, expr)
        return expr.bind(schema)

    def __repr__(self):
        return "CASE WHEN ..."


class In(Expression):
    def __init__(self, child: Expression, values: List[Expression]):
        self.child = child
        self.values = values
        self.children = [child] + values

    def bind(self, schema):
        expr: Expression = None
        for v in self.values:
            e = Eq(self.child, v)
            expr = e if expr is None else Or(expr, e)
        return (expr or Literal(False)).bind(schema)

    def __repr__(self):
        return f"{self.child} IN (...)"


_MATH_FNS = {
    "sqrt": jnp.sqrt, "exp": jnp.exp, "log": jnp.log, "log10": jnp.log10,
    "log2": jnp.log2, "log1p": jnp.log1p, "sin": jnp.sin, "cos": jnp.cos,
    "tan": jnp.tan, "asin": jnp.arcsin, "acos": jnp.arccos,
    "atan": jnp.arctan, "sinh": jnp.sinh, "cosh": jnp.cosh,
    "tanh": jnp.tanh, "cbrt": jnp.cbrt, "expm1": jnp.expm1,
    "floor": jnp.floor, "ceil": jnp.ceil, "signum": jnp.sign,
    "rint": jnp.rint, "degrees": jnp.degrees, "radians": jnp.radians,
}


class MathUnary(_UnaryOp):
    """Double-valued unary math fn with Spark semantics (log(<=0) -> null)."""

    def __init__(self, fn_name: str, child: Expression):
        super().__init__(child)
        self.fn_name = fn_name
        if fn_name not in _MATH_FNS:
            raise UnsupportedExpr(f"math fn {fn_name}")

    def bind(self, schema):
        b = MathUnary(self.fn_name, self.child.bind(schema))
        if not (b.child.dtype.is_numeric or isinstance(b.child.dtype,
                                                       dt.NullType)):
            raise UnsupportedExpr(f"{self.fn_name} on {b.child.dtype}")
        if b.fn_name in ("floor", "ceil") and b.child.dtype.is_integral:
            b.dtype = dt.INT64
        else:
            b.dtype = dt.FLOAT64
        return b

    def emit(self, ctx):
        cv = self.child.emit(ctx)
        x = cv.data.astype(jnp.float64)
        if isinstance(self.child.dtype, dt.DecimalType):
            x = x / (10.0 ** self.child.dtype.scale)
        valid = cv.validity
        if self.fn_name in ("log", "log10", "log2"):
            valid = valid & (x > 0)
            x = jnp.where(x > 0, x, 1.0)
        if self.fn_name == "log1p":
            valid = valid & (x > -1)
            x = jnp.where(x > -1, x, 0.0)
        out = _MATH_FNS[self.fn_name](x)
        if self.dtype == dt.INT64:
            out = out.astype(jnp.int64)
        return CV(out, valid)

    def __repr__(self):
        return f"{self.fn_name}({self.child})"


class Round(Expression):
    """round(x, d) half-up (Spark ROUND)."""

    def __init__(self, child: Expression, digits: int = 0):
        self.child = child
        self.digits = digits
        self.children = [child]

    def bind(self, schema):
        b = Round(self.child.bind(schema), self.digits)
        ct = b.child.dtype
        _reject_d128(ct, "round")
        if isinstance(ct, dt.DecimalType):
            b.dtype = dt.DecimalType(ct.precision,
                                     min(ct.scale, max(self.digits, 0)))
        elif ct.is_integral:
            b.dtype = ct
        else:
            b.dtype = dt.FLOAT64
        return b

    def emit(self, ctx):
        cv = self.child.emit(ctx)
        ct = self.child.dtype
        if isinstance(ct, dt.DecimalType):
            # round HALF_UP at decimal position `digits` (may be negative)
            drop = ct.scale - max(self.digits, 0)
            out = cv.data
            if drop > 0:
                p = 10 ** drop
                half = p // 2
                adj = jnp.where(out >= 0, out + half, out - half)
                q = adj // p
                r = adj - q * p
                out = jnp.where((r != 0) & (adj < 0), q + 1, q)
            if self.digits < 0:
                p = 10 ** (-self.digits)
                half = p // 2
                adj = jnp.where(out >= 0, out + half, out - half)
                q = adj // p
                r = adj - q * p
                q = jnp.where((r != 0) & (adj < 0), q + 1, q)
                out = q * p
            return CV(out, cv.validity)
        if ct.is_integral and self.digits >= 0:
            return cv
        if ct.is_integral:  # negative digits on ints: round at 10^-d
            p = 10 ** (-self.digits)
            half = p // 2
            x = cv.data.astype(jnp.int64)
            adj = jnp.where(x >= 0, x + half, x - half)
            q = adj // p
            r = adj - q * p
            q = jnp.where((r != 0) & (adj < 0), q + 1, q)
            return CV((q * p).astype(ct.np_dtype), cv.validity)
        x = cv.data.astype(jnp.float64)
        p = 10.0 ** self.digits
        scaled = x * p
        out = jnp.where(scaled >= 0, jnp.floor(scaled + 0.5),
                        jnp.ceil(scaled - 0.5)) / p
        if self.dtype.is_integral:
            out = out.astype(ct.np_dtype)
        return CV(out, cv.validity)

    def __repr__(self):
        return f"round({self.child}, {self.digits})"


class _MinMaxOf(Expression):
    is_greatest = True

    def __init__(self, *children: Expression):
        self.children = list(children)

    def bind(self, schema):
        bc = [c.bind(schema) for c in self.children]
        out = bc[0].dtype
        for c in bc[1:]:
            out = dt.promote(out, c.dtype) if c.dtype != out else out
        _reject_d128(out, "greatest/least")
        bc = [c if c.dtype == out else Cast.bound(c, out) for c in bc]
        b = type(self)(*bc)
        b.dtype = out
        return b

    def emit(self, ctx):
        cvs = [c.emit(ctx) for c in self.children]
        out = cvs[0]
        for cv in cvs[1:]:
            if self.is_greatest:
                pick = (~out.validity |
                        (cv.validity & ew._nan_lt(out.data, cv.data)))
            else:
                pick = (~out.validity |
                        (cv.validity & ew._nan_lt(cv.data, out.data)))
            pick = pick & cv.validity
            out = CV(jnp.where(pick, cv.data, out.data),
                     out.validity | cv.validity)
        return out


class _Bitwise(_BinaryOp):
    op = None

    def _resolve_type(self):
        self.left, self.right, out = _coerce_pair(self.left, self.right)
        if out is None or not out.is_integral:
            raise UnsupportedExpr("bitwise op on non-integral")
        self.dtype = out

    def emit(self, ctx):
        l, r = self.left.emit(ctx), self.right.emit(ctx)
        return CV(type(self).op(l.data, r.data), ew.and_validity(l, r))


class BitwiseAnd(_Bitwise):
    symbol = "&"
    op = staticmethod(jnp.bitwise_and)


class BitwiseOr(_Bitwise):
    symbol = "|"
    op = staticmethod(jnp.bitwise_or)


class BitwiseXor(_Bitwise):
    symbol = "^"
    op = staticmethod(jnp.bitwise_xor)


class BitwiseNot(_UnaryOp):
    def _resolve_type(self):
        if not self.child.dtype.is_integral:
            raise UnsupportedExpr("~ on non-integral")
        self.dtype = self.child.dtype

    def emit(self, ctx):
        cv = self.child.emit(ctx)
        return CV(jnp.bitwise_not(cv.data), cv.validity)


class ShiftLeft(_BinaryOp):
    symbol = "<<"

    def _resolve_type(self):
        if not (self.left.dtype.is_integral
                and self.right.dtype.is_integral):
            raise UnsupportedExpr("shift on non-integral")
        # Spark promotes byte/short to int before shifting (mask by 31)
        if isinstance(self.left.dtype, (dt.ByteType, dt.ShortType)):
            self.left = Cast.bound(self.left, dt.INT32)
        self.dtype = self.left.dtype

    def emit(self, ctx):
        l, r = self.left.emit(ctx), self.right.emit(ctx)
        nbits = l.data.dtype.itemsize * 8
        sh = (r.data.astype(jnp.int32) % nbits)  # Java masks the shift
        return CV(l.data << sh.astype(l.data.dtype),
                  ew.and_validity(l, r))


class ShiftRight(ShiftLeft):
    symbol = ">>"

    def emit(self, ctx):
        l, r = self.left.emit(ctx), self.right.emit(ctx)
        nbits = l.data.dtype.itemsize * 8
        sh = (r.data.astype(jnp.int32) % nbits)
        return CV(l.data >> sh.astype(l.data.dtype),
                  ew.and_validity(l, r))


class Pow(_BinaryOp):
    symbol = "pow"

    def _resolve_type(self):
        self.left = (self.left if self.left.dtype.is_floating
                     else Cast.bound(self.left, dt.FLOAT64))
        self.right = (self.right if self.right.dtype.is_floating
                      else Cast.bound(self.right, dt.FLOAT64))
        self.dtype = dt.FLOAT64

    def emit(self, ctx):
        l, r = self.left.emit(ctx), self.right.emit(ctx)
        return CV(jnp.power(l.data.astype(jnp.float64),
                            r.data.astype(jnp.float64)),
                  ew.and_validity(l, r))


class Atan2(_BinaryOp):
    symbol = "atan2"

    def _resolve_type(self):
        for side in ("left", "right"):
            e = getattr(self, side)
            if not (e.dtype.is_numeric or isinstance(e.dtype, dt.NullType)):
                raise UnsupportedExpr(f"atan2 on {e.dtype}")
            if not e.dtype.is_floating:
                setattr(self, side, Cast.bound(e, dt.FLOAT64))
        self.dtype = dt.FLOAT64

    def emit(self, ctx):
        l, r = self.left.emit(ctx), self.right.emit(ctx)
        return CV(jnp.arctan2(l.data.astype(jnp.float64),
                              r.data.astype(jnp.float64)),
                  ew.and_validity(l, r))


class Greatest(_MinMaxOf):
    is_greatest = True


class Least(_MinMaxOf):
    is_greatest = False
