"""Collection (array/map/struct) expressions + higher-order functions.

TPU analog of the reference's collection and lambda expression rules
(reference: sql-plugin/.../collectionOperations.scala,
complexTypeCreator.scala, complexTypeExtractors.scala,
higherOrderFunctions.scala — GpuCreateArray, GpuGetArrayItem, GpuElementAt,
GpuSize, GpuArrayContains, GpuSortArray, GpuCreateNamedStruct,
GpuGetStructField, GpuArrayTransform, GpuArrayFilter, GpuArrayExists).

Design (TPU-first): a list column is offsets[int32 cap+1] + a flattened
element child CV. Per-row operations over elements become flat vectorized
kernels over the element buffer plus `segment_*` reductions keyed by the
element->row map (searchsorted over offsets) — no per-row loops, fully
MXU/VPU friendly, one XLA program per expression tree. Offsets may be
non-dense (arrow slices / null placeholder ranges); every kernel masks
elements through `_elem_rows` instead of assuming density.
"""
from __future__ import annotations

import itertools
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.column import Column
from ..ops import concat as ops_concat
from ..ops import gather as ops_gather
from ..ops.kernel_utils import CV
from .expressions import (EmitCtx, Expression, Literal, UnsupportedExpr,
                          _UnaryOp, _wrap)

__all__ = [
    "CreateArray", "GetArrayItem", "ElementAt", "Size", "ArrayContains",
    "ArrayMin", "ArrayMax", "SortArray", "CreateNamedStruct",
    "GetStructField", "MapKeys", "MapValues", "Explode", "PosExplode",
    "NamedLambdaVariable", "ArrayTransform", "ArrayFilter", "ArrayExists",
    "ArrayForAll", "ArrayAggregate",
]


# ----------------------------------------------------------------------
# element-domain helpers
# ----------------------------------------------------------------------
def arr_lens(cv: CV) -> jnp.ndarray:
    """Per-row element counts (0 for null rows / placeholder ranges)."""
    lens = (cv.offsets[1:] - cv.offsets[:-1]).astype(jnp.int32)
    return jnp.where(cv.validity, lens, 0)


def _elem_rows(cv: CV):
    """Map element buffer positions to their owning row.

    Returns (rows, live): rows int32[ecap] clipped to [0, cap-1]; live is
    False for positions in offset gaps (sliced-away prefixes, null rows'
    placeholder ranges) and beyond the last row's end.
    """
    off = cv.offsets
    cap = cv.validity.shape[0]
    ecap = cv.child.capacity
    pos = jnp.arange(ecap, dtype=jnp.int32)
    rows = jnp.searchsorted(off[1:], pos, side="right").astype(jnp.int32)
    rows = jnp.clip(rows, 0, cap - 1)
    lens = arr_lens(cv)
    live = ((pos >= off[rows]) & (pos < off[rows] + lens[rows])
            & cv.validity[rows])
    return rows, live


class _LazyElemCvs:
    """ctx.cvs adapter for lambda bodies: outer column references are
    gathered to the element domain on first use (captured variables)."""

    def __init__(self, cvs, rows, live):
        self._cvs = cvs
        self._rows = rows
        self._live = live
        self._cache = {}

    def __getitem__(self, i):
        if i not in self._cache:
            self._cache[i] = ops_gather.take(self._cvs[i], self._rows,
                                             self._live)
        return self._cache[i]

    def __len__(self):
        return len(self._cvs)


def _elem_ctx(ctx: EmitCtx, arr: CV):
    rows, live = _elem_rows(arr)
    ecap = arr.child.capacity
    ectx = EmitCtx([], ecap)
    ectx.cvs = _LazyElemCvs(ctx.cvs, rows, live)
    ectx.lambda_vals = dict(ctx.lambda_vals)
    return ectx, rows, live


def _coerce(e: Expression, target: dt.DataType, what: str) -> Expression:
    """Spark-style implicit cast of a bound expression to `target`."""
    if e.dtype == target:
        return e
    if e.dtype.is_numeric and target.is_numeric:
        from .expressions import Cast
        return Cast.bound(e, target)
    raise UnsupportedExpr(f"{what}: cannot coerce {e.dtype} to {target}")


def _require_array(e: Expression, what: str):
    if not isinstance(e.dtype, (dt.ArrayType, dt.MapType)):
        raise UnsupportedExpr(f"{what} requires an array/map, got {e.dtype}")


# ----------------------------------------------------------------------
# constructors
# ----------------------------------------------------------------------
class CreateArray(Expression):
    """array(e1, ..., ek): row i -> [e1[i], ..., ek[i]].

    Emission: concatenate the k child CVs (child j occupying rows
    [j*cap, (j+1)*cap)) then gather with src(i*k+j) = j*cap + i — one
    uniform interleave gather that works for every element type including
    strings and nested arrays (reference: complexTypeCreator.scala
    GpuCreateArray)."""

    def __init__(self, children: List[Expression]):
        if not children:
            raise UnsupportedExpr("array() needs at least one element")
        self.children = list(children)

    def bind(self, schema):
        b = CreateArray([c.bind(schema) for c in self.children])
        et = b.children[0].dtype
        for c in b.children[1:]:
            if c.dtype != et:
                raise UnsupportedExpr(
                    f"array() elements must share a type: {et} vs {c.dtype}")
        b.dtype = dt.ArrayType(et)
        return b

    def emit(self, ctx: EmitCtx) -> CV:
        k = len(self.children)
        cap = ctx.capacity
        cvs = [c.emit(ctx) for c in self.children]
        comb = ops_concat.concat_cvs(cvs, self.children[0].dtype) \
            if k > 1 else cvs[0]
        e = jnp.arange(cap * k, dtype=jnp.int32)
        src = (e % k) * cap + e // k
        child = ops_gather.take(comb, src)
        off = (jnp.arange(cap + 1, dtype=jnp.int32) * k)
        valid = jnp.ones(cap, jnp.bool_)
        return CV(jnp.zeros(0, jnp.int8), valid, off, (child,))

    def __repr__(self):
        return f"array({', '.join(map(repr, self.children))})"


class CreateNamedStruct(Expression):
    """named_struct / struct(...) (reference: GpuCreateNamedStruct)."""

    def __init__(self, names: List[str], children: List[Expression]):
        assert len(names) == len(children)
        self.names = list(names)
        self.children = list(children)

    def bind(self, schema):
        b = CreateNamedStruct(self.names,
                              [c.bind(schema) for c in self.children])
        b.dtype = dt.StructType(tuple(
            dt.StructField(n, c.dtype) for n, c in zip(b.names, b.children)))
        return b

    def emit(self, ctx: EmitCtx) -> CV:
        kids = tuple(c.emit(ctx) for c in self.children)
        valid = jnp.ones(ctx.capacity, jnp.bool_)
        return CV(jnp.zeros(0, jnp.int8), valid, None, kids)

    def __repr__(self):
        inner = ", ".join(f"{n}: {c!r}"
                          for n, c in zip(self.names, self.children))
        return f"struct({inner})"


class GetStructField(Expression):
    """col.field (reference: complexTypeExtractors.scala GpuGetStructField)."""

    def __init__(self, child: Expression, field: str):
        self.child = child
        self.field = field
        self.children = [child]

    def bind(self, schema):
        b = GetStructField(self.child.bind(schema), self.field)
        if not isinstance(b.child.dtype, dt.StructType):
            raise UnsupportedExpr(f"getField on {b.child.dtype}")
        for i, f in enumerate(b.child.dtype.fields):
            if f.name == self.field:
                b._ordinal = i
                b.dtype = f.dtype
                return b
        raise UnsupportedExpr(
            f"no field {self.field!r} in {b.child.dtype}")

    def emit(self, ctx: EmitCtx) -> CV:
        cv = self.child.emit(ctx)
        ch = cv.children[self._ordinal]
        return CV(ch.data, ch.validity & cv.validity, ch.offsets, ch.children)

    def __repr__(self):
        return f"{self.child}.{self.field}"


# ----------------------------------------------------------------------
# extractors / scalar ops
# ----------------------------------------------------------------------
class Size(_UnaryOp):
    """size(array|map) -> int32; null input -> null (Spark 3.x
    legacy.sizeOfNull=false semantics; reference: GpuSize)."""

    def _resolve_type(self):
        _require_array(self.child, "size")
        self.dtype = dt.INT32

    def emit(self, ctx: EmitCtx) -> CV:
        cv = self.child.emit(ctx)
        return CV(arr_lens(cv), cv.validity)

    def __repr__(self):
        return f"size({self.child})"


class GetArrayItem(Expression):
    """arr[i], 0-based; out-of-bounds/negative -> null
    (reference: GpuGetArrayItem)."""

    def __init__(self, child: Expression, index):
        self.child = child
        self.index = _wrap(index)
        self.children = [self.child, self.index]

    def bind(self, schema):
        b = GetArrayItem(self.child.bind(schema), self.index.bind(schema))
        if not isinstance(b.child.dtype, dt.ArrayType):
            raise UnsupportedExpr(f"getItem on {b.child.dtype}")
        if not b.index.dtype.is_integral:
            raise UnsupportedExpr(f"array index must be integral, "
                                  f"got {b.index.dtype}")
        b.dtype = b.child.dtype.element
        return b

    def emit(self, ctx: EmitCtx) -> CV:
        arr = self.child.emit(ctx)
        idx = self.index.emit(ctx)
        k = idx.data.astype(jnp.int32)
        k = jnp.broadcast_to(k, (ctx.capacity,))
        lens = arr_lens(arr)
        ok = arr.validity & idx.validity & (k >= 0) & (k < lens)
        pos = arr.offsets[:-1] + jnp.where(ok, k, 0)
        return ops_gather.take(arr.child, pos, ok)

    def __repr__(self):
        return f"{self.child}[{self.index}]"


class ElementAt(Expression):
    """element_at(array, i) 1-based (negative = from the end) or
    element_at(map, key) (reference: GpuElementAt)."""

    def __init__(self, child: Expression, key):
        self.child = child
        self.key = _wrap(key)
        self.children = [self.child, self.key]

    def bind(self, schema):
        b = ElementAt(self.child.bind(schema), self.key.bind(schema))
        cdt = b.child.dtype
        if isinstance(cdt, dt.ArrayType):
            if not b.key.dtype.is_integral:
                raise UnsupportedExpr("element_at(array, non-integer index)")
            b.dtype = cdt.element
        elif isinstance(cdt, dt.MapType):
            if cdt.key.is_nested:
                raise UnsupportedExpr("element_at over nested map keys")
            b.key = _coerce(b.key, cdt.key, "element_at")
            b.children = [b.child, b.key]
            b.dtype = cdt.value
        else:
            raise UnsupportedExpr(f"element_at on {cdt}")
        return b

    def emit(self, ctx: EmitCtx) -> CV:
        arr = self.child.emit(ctx)
        if isinstance(self.child.dtype, dt.ArrayType):
            idx = self.key.emit(ctx)
            k = jnp.broadcast_to(idx.data.astype(jnp.int32),
                                 (ctx.capacity,))
            lens = arr_lens(arr)
            k0 = jnp.where(k > 0, k - 1, lens + k)  # 1-based / from-end
            ok = (arr.validity & idx.validity & (k != 0)
                  & (k0 >= 0) & (k0 < lens))
            pos = arr.offsets[:-1] + jnp.where(ok, k0, 0)
            return ops_gather.take(arr.child, pos, ok)
        # map: per-element key equality, pick the first match per row
        key = self.key.emit(ctx)
        rows, live = _elem_rows(arr)
        kcv = arr.child.children[0]
        vcv = arr.child.children[1]
        match = _equal_rowmap(kcv, key, rows, live, ctx.capacity)
        ecap = rows.shape[0]
        cap = ctx.capacity
        epos = jnp.arange(ecap, dtype=jnp.int32)
        first = jax.ops.segment_min(jnp.where(match, epos, ecap),
                                    rows, num_segments=cap)
        found = first < ecap
        pos = jnp.where(found, first, 0)
        return ops_gather.take(vcv, pos, found & arr.validity & key.validity)

    def __repr__(self):
        return f"element_at({self.child}, {self.key})"


def _equal_rowmap(ecv: CV, vcv: CV, rows, live, cap: int) -> jnp.ndarray:
    """bool over the element domain: element e equals the per-row value
    vcv[rows[e]]. Row-mapped comparison — no replication gather, so no
    var-width output sizing is needed inside the trace."""
    if ecv.offsets is not None:
        from ..ops import strings as ops_str
        return ops_str.str_equal_rowmap(ecv, vcv, rows, live)
    vdata = jnp.broadcast_to(vcv.data, (cap,))
    vvalid = jnp.broadcast_to(vcv.validity, (cap,))
    return ((ecv.data == vdata[rows]) & ecv.validity
            & vvalid[rows] & live)


class ArrayContains(Expression):
    """array_contains(arr, value) (reference: GpuArrayContains).
    Spark null semantics: null array -> null; no match but the array has
    null entries -> null; otherwise true/false."""

    def __init__(self, child: Expression, value):
        self.child = child
        self.value = _wrap(value)
        self.children = [self.child, self.value]

    def bind(self, schema):
        b = ArrayContains(self.child.bind(schema), self.value.bind(schema))
        if not isinstance(b.child.dtype, dt.ArrayType):
            raise UnsupportedExpr(f"array_contains on {b.child.dtype}")
        if b.child.dtype.element.is_nested:
            raise UnsupportedExpr("array_contains over nested elements")
        b.value = _coerce(b.value, b.child.dtype.element, "array_contains")
        b.children = [b.child, b.value]
        b.dtype = dt.BOOL
        return b

    def emit(self, ctx: EmitCtx) -> CV:
        arr = self.child.emit(ctx)
        rows, live = _elem_rows(arr)
        cap = ctx.capacity
        val = self.value.emit(ctx)
        ecv = arr.child
        match = _equal_rowmap(ecv, val, rows, live, cap)
        # segment_max's identity for int32 is INT32_MIN — compare > 0
        # so empty segments read as False
        has = jax.ops.segment_max(match.astype(jnp.int32), rows,
                                  num_segments=cap) > 0
        has_null_elem = jax.ops.segment_max(
            (live & ~ecv.validity).astype(jnp.int32), rows,
            num_segments=cap) > 0
        valid = arr.validity & val.validity & (has | ~has_null_elem)
        return CV(has, valid)

    def __repr__(self):
        return f"array_contains({self.child}, {self.value})"


class _ArrayReduce(_UnaryOp):
    _kind = "min"

    def _resolve_type(self):
        _require_array(self.child, f"array_{self._kind}")
        et = self.child.dtype.element
        if not (et.is_numeric or et in (dt.DATE, dt.TIMESTAMP)):
            raise UnsupportedExpr(f"array_{self._kind} on array<{et}>")
        if isinstance(et, dt.DecimalType) and et.is_decimal128:
            raise UnsupportedExpr(f"array_{self._kind} on decimal128")
        self.dtype = et

    def emit(self, ctx: EmitCtx) -> CV:
        cv = self.child.emit(ctx)
        rows, live = _elem_rows(cv)
        cap = ctx.capacity
        e = cv.child
        m = live & e.validity
        if self._kind == "min":
            big = _extreme(e.data.dtype, for_min=True)
            vals = jnp.where(m, e.data, big)
            red = jax.ops.segment_min(vals, rows, num_segments=cap)
        else:
            small = _extreme(e.data.dtype, for_min=False)
            vals = jnp.where(m, e.data, small)
            red = jax.ops.segment_max(vals, rows, num_segments=cap)
        any_valid = jax.ops.segment_max(m.astype(jnp.int32), rows,
                                        num_segments=cap) > 0
        return CV(red, cv.validity & any_valid)

    def __repr__(self):
        return f"array_{self._kind}({self.child})"


def _extreme(dtype, for_min: bool):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(jnp.inf if for_min else -jnp.inf, dtype)
    info = jnp.iinfo(dtype)
    return jnp.array(info.max if for_min else info.min, dtype)


class ArrayMin(_ArrayReduce):
    _kind = "min"


class ArrayMax(_ArrayReduce):
    _kind = "max"


class SortArray(Expression):
    """sort_array(arr, asc): per-row element sort; nulls first when
    ascending, last when descending (Spark semantics; reference:
    GpuSortArray). One global stable argsort keyed by
    (row, null_flag, value) — rows stay in place, elements order within
    each row."""

    def __init__(self, child: Expression, asc: bool = True):
        self.child = child
        self.asc = asc
        self.children = [child]

    def bind(self, schema):
        b = SortArray(self.child.bind(schema), self.asc)
        if not isinstance(b.child.dtype, dt.ArrayType):
            raise UnsupportedExpr(f"sort_array on {b.child.dtype}")
        et = b.child.dtype.element
        if not (et.is_numeric or et in (dt.DATE, dt.TIMESTAMP, dt.BOOL)):
            raise UnsupportedExpr(f"sort_array on array<{et}> "
                                  "(fixed-width elements only)")
        b.dtype = b.child.dtype
        return b

    def emit(self, ctx: EmitCtx) -> CV:
        from ..ops import sortkeys as sk
        arr = self.child.emit(ctx)
        rows, live = _elem_rows(arr)
        e = arr.child
        et = self.child.dtype.element
        # radix-normalized monotone keys (descending handled by the key
        # builder — plain negation breaks on bool and collides
        # INT_MIN with -(INT_MIN+1))
        keys = sk.order_keys(CV(e.data, e.validity), et,
                             descending=not self.asc)
        # sort key tiers: dead elements last within their row never matter
        # (they stay inside gaps), null elements first (asc) / last (desc)
        nullk = jnp.where(e.validity, 1, 0 if self.asc else 2)
        order = jnp.lexsort((*reversed(keys), nullk, rows))
        child = ops_gather.take(e, order, live[order])
        # positions are permuted only within rows, so offsets are unchanged
        return CV(arr.data, arr.validity, arr.offsets, (child,))

    def __repr__(self):
        return f"sort_array({self.child}, asc={self.asc})"


class MapKeys(_UnaryOp):
    def _resolve_type(self):
        if not isinstance(self.child.dtype, dt.MapType):
            raise UnsupportedExpr(f"map_keys on {self.child.dtype}")
        self.dtype = dt.ArrayType(self.child.dtype.key, False)

    def emit(self, ctx: EmitCtx) -> CV:
        cv = self.child.emit(ctx)
        return CV(cv.data, cv.validity, cv.offsets,
                  (cv.child.children[0],))

    def __repr__(self):
        return f"map_keys({self.child})"


class MapValues(_UnaryOp):
    def _resolve_type(self):
        if not isinstance(self.child.dtype, dt.MapType):
            raise UnsupportedExpr(f"map_values on {self.child.dtype}")
        self.dtype = dt.ArrayType(self.child.dtype.value)

    def emit(self, ctx: EmitCtx) -> CV:
        cv = self.child.emit(ctx)
        return CV(cv.data, cv.validity, cv.offsets,
                  (cv.child.children[1],))

    def __repr__(self):
        return f"map_values({self.child})"


# ----------------------------------------------------------------------
# generators (consumed by GenerateExec, not emitted inline)
# ----------------------------------------------------------------------
class Explode(_UnaryOp):
    """explode(arr) — output cardinality changes, so the planner lifts
    this into a GenerateExec (reference: GpuGenerateExec + GpuExplode);
    emit() is never called on the expression itself."""

    outer = False
    with_position = False

    def bind(self, schema):
        b = type(self)(self.child.bind(schema))
        b.outer = self.outer        # instance flag survives rebinding
        b._resolve_type()
        return b

    def _resolve_type(self):
        _require_array(self.child, "explode")
        if isinstance(self.child.dtype, dt.MapType):
            self.dtype = dt.StructType(
                (dt.StructField("key", self.child.dtype.key, False),
                 dt.StructField("value", self.child.dtype.value)))
        else:
            self.dtype = self.child.dtype.element

    def emit(self, ctx):
        raise UnsupportedExpr(
            "explode() must be the top-level expression of a select "
            "(planner lifts it into GenerateExec)")

    def __repr__(self):
        return f"explode({self.child})"


class PosExplode(Explode):
    with_position = True

    def __repr__(self):
        return f"posexplode({self.child})"


# ----------------------------------------------------------------------
# higher-order functions
# ----------------------------------------------------------------------
_hof_ids = itertools.count()


class NamedLambdaVariable(Expression):
    """A lambda parameter; emits the element-domain CV registered by the
    enclosing higher-order function (reference: higherOrderFunctions.scala
    GpuNamedLambdaVariable)."""

    def __init__(self, name: str, dtype: Optional[dt.DataType] = None,
                 var_id: Optional[int] = None):
        self._name = name
        self.dtype = dtype
        self.var_id = var_id if var_id is not None else next(_hof_ids)
        self.children = []

    @property
    def name(self):
        return self._name

    def bind(self, schema):
        return self

    def emit(self, ctx: EmitCtx) -> CV:
        try:
            return ctx.lambda_vals[self.var_id]
        except KeyError:
            raise UnsupportedExpr(
                f"lambda variable {self._name} used outside its function")

    def __repr__(self):
        return self._name


def _reject_varwidth_captures(bound_body: Expression):
    """Lambda bodies run over the ELEMENT domain: captured outer columns
    are gathered with per-element replication, whose var-width output size
    cannot be measured inside the trace — reject string/nested captures at
    bind (the planner falls back to host). Lambda variables themselves
    (the element child) are fine."""
    from .expressions import BoundRef
    stack = [bound_body]
    while stack:
        e = stack.pop()
        if isinstance(e, BoundRef) and (
                e.dtype.is_variable_width or e.dtype.is_nested):
            raise UnsupportedExpr(
                f"lambda captures var-width outer column {e!r} "
                "(element-domain replication is unsized on TPU)")
        stack.extend(getattr(e, "children", []))


class _HigherOrder(Expression):
    """Base: binds the array child, then binds the lambda body with the
    lambda variables' dtypes resolved from the element type."""

    def __init__(self, child: Expression, fn: Callable, bound=None):
        self.child = child
        self.fn = fn
        self._bound = bound  # (bound_child, var, pos_var, bound_body)
        # expand the lambda once with placeholder vars so tree walks
        # (column pruning, ref collection) see captured outer columns
        import inspect
        nargs = len(inspect.signature(fn).parameters)
        tvars = [NamedLambdaVariable(f"_t{i}") for i in range(nargs)]
        self.children = [child, _wrap(fn(*tvars))]

    def _element_dtype(self, cdt) -> dt.DataType:
        return Column.element_dtype(cdt)

    def bind(self, schema):
        bchild = self.child.bind(schema)
        _require_array_t = isinstance(bchild.dtype,
                                      (dt.ArrayType, dt.MapType))
        if not _require_array_t:
            raise UnsupportedExpr(f"{type(self).__name__} on {bchild.dtype}")
        et = self._element_dtype(bchild.dtype)
        var = NamedLambdaVariable("x", et)
        import inspect
        nargs = len(inspect.signature(self.fn).parameters)
        pos_var = NamedLambdaVariable("i", dt.INT32) if nargs >= 2 else None
        body = self.fn(var, pos_var) if pos_var is not None else self.fn(var)
        bbody = _wrap(body).bind(schema)
        _reject_varwidth_captures(bbody)
        b = type(self)(bchild, self.fn, (bchild, var, pos_var, bbody))
        b._resolve_type(bchild, bbody)
        return b

    def _emit_body(self, ctx: EmitCtx):
        bchild, var, pos_var, bbody = self._bound
        arr = bchild.emit(ctx)
        ectx, rows, live = _elem_ctx(ctx, arr)
        ectx.lambda_vals[var.var_id] = arr.child
        if pos_var is not None:
            pos = jnp.arange(rows.shape[0], dtype=jnp.int32)
            idx_in_row = pos - arr.offsets[:-1][rows]
            ectx.lambda_vals[pos_var.var_id] = CV(idx_in_row, live)
        out = bbody.emit(ectx)
        return arr, rows, live, out


class ArrayTransform(_HigherOrder):
    """transform(arr, x -> f(x)) / transform(arr, (x, i) -> f(x, i))
    (reference: GpuArrayTransform). Fully parallel: the lambda body runs
    over the flat element buffer."""

    def _resolve_type(self, bchild, bbody):
        self.dtype = dt.ArrayType(bbody.dtype)

    def emit(self, ctx: EmitCtx) -> CV:
        arr, rows, live, out = self._emit_body(ctx)
        return CV(arr.data, arr.validity, arr.offsets, (out,))

    def __repr__(self):
        return f"transform({self.child}, <lambda>)"


class ArrayFilter(_HigherOrder):
    """filter(arr, x -> pred(x)) (reference: GpuArrayFilter). The kept
    elements are compacted per row with one global stable sort."""

    def _resolve_type(self, bchild, bbody):
        if bbody.dtype != dt.BOOL:
            raise UnsupportedExpr("filter lambda must return boolean")
        self.dtype = bchild.dtype

    def emit(self, ctx: EmitCtx) -> CV:
        arr, rows, live, out = self._emit_body(ctx)
        keep = live & out.validity & out.data.astype(jnp.bool_)
        cap = ctx.capacity
        new_lens = jax.ops.segment_sum(keep.astype(jnp.int32), rows,
                                       num_segments=cap)
        new_off = jnp.concatenate([
            jnp.zeros(1, jnp.int32),
            jnp.cumsum(new_lens).astype(jnp.int32)])
        # global stable compaction preserves (row, position) order
        perm = jnp.argsort(jnp.logical_not(keep), stable=True)
        total = new_off[cap]
        in_bounds = jnp.arange(perm.shape[0]) < total
        child = ops_gather.take(arr.child, perm, in_bounds)
        return CV(arr.data, arr.validity, new_off, (child,))

    def __repr__(self):
        return f"filter({self.child}, <lambda>)"


class _ArrayPredicate(_HigherOrder):
    _any = True

    def _resolve_type(self, bchild, bbody):
        if bbody.dtype != dt.BOOL:
            raise UnsupportedExpr("exists/forall lambda must return boolean")
        self.dtype = dt.BOOL

    def emit(self, ctx: EmitCtx) -> CV:
        arr, rows, live, out = self._emit_body(ctx)
        cap = ctx.capacity
        hit = live & out.validity & out.data.astype(jnp.bool_)
        if self._any:
            red = jax.ops.segment_max(hit.astype(jnp.int32), rows,
                                      num_segments=cap) > 0
        else:
            miss = live & (~out.data.astype(jnp.bool_) | ~out.validity)
            red = ~(jax.ops.segment_max(miss.astype(jnp.int32), rows,
                                        num_segments=cap) > 0)
        return CV(red, arr.validity)


class ArrayExists(_ArrayPredicate):
    _any = True

    def __repr__(self):
        return f"exists({self.child}, <lambda>)"


class ArrayForAll(_ArrayPredicate):
    _any = False

    def __repr__(self):
        return f"forall({self.child}, <lambda>)"


class ArrayAggregate(Expression):
    """aggregate(arr, zero, (acc, x) -> merge) — a sequential fold per row,
    implemented as ONE segmented lax.scan over the flat element buffer
    (carry resets at row starts). Sequential in total element count;
    correct for arbitrary lambdas like the reference's row-wise fold
    (reference: higherOrderFunctions.scala GpuArrayAggregate analog)."""

    def __init__(self, child: Expression, zero, fn: Callable, bound=None):
        self.child = child
        self.zero = _wrap(zero)
        self.fn = fn
        self._bound = bound
        tvars = [NamedLambdaVariable("_ta"), NamedLambdaVariable("_tx")]
        self.children = [self.child, self.zero, _wrap(fn(*tvars))]

    def bind(self, schema):
        bchild = self.child.bind(schema)
        if not isinstance(bchild.dtype, dt.ArrayType):
            raise UnsupportedExpr(f"aggregate on {bchild.dtype}")
        bzero = self.zero.bind(schema)
        acc_var = NamedLambdaVariable("acc", bzero.dtype)
        x_var = NamedLambdaVariable("x", bchild.dtype.element)
        bbody = _wrap(self.fn(acc_var, x_var)).bind(schema)
        if bbody.dtype != bzero.dtype:
            # widen the accumulator to the merge result type (Spark's
            # implicit cast of the zero) and rebind the lambda once
            bzero = _coerce(bzero, bbody.dtype, "aggregate zero")
            acc_var = NamedLambdaVariable("acc", bzero.dtype)
            bbody = _wrap(self.fn(acc_var, x_var)).bind(schema)
        if bbody.dtype != bzero.dtype:
            raise UnsupportedExpr(
                f"aggregate merge type {bbody.dtype} != zero {bzero.dtype}")
        if bbody.dtype.is_nested or isinstance(bbody.dtype,
                                               (dt.StringType,
                                                dt.BinaryType)):
            raise UnsupportedExpr("aggregate acc must be fixed-width")
        b = ArrayAggregate(bchild, bzero, self.fn,
                           (bchild, bzero, acc_var, x_var, bbody))
        b.dtype = bzero.dtype
        return b

    def emit(self, ctx: EmitCtx) -> CV:
        bchild, bzero, acc_var, x_var, bbody = self._bound
        arr = bchild.emit(ctx)
        rows, live = _elem_rows(arr)
        cap = ctx.capacity
        zcv = bzero.emit(ctx)
        # per-ROW zero (the zero may be a non-constant expression)
        zrow_d = jnp.broadcast_to(zcv.data, (cap,))
        zrow_v = jnp.broadcast_to(zcv.validity, (cap,))
        ecap = rows.shape[0]
        starts = arr.offsets[:-1][rows]
        pos = jnp.arange(ecap, dtype=jnp.int32)
        is_start = pos == starts
        ze_d = zrow_d[rows]        # this element's row zero
        ze_v = zrow_v[rows]

        e = arr.child
        outer_ctx = ctx

        def step(carry, xs):
            acc_d, acc_v = carry
            live_i, start_i, zd_i, zv_i, ed, ev = xs
            a_d = jnp.where(start_i, zd_i, acc_d)
            a_v = jnp.where(start_i, zv_i, acc_v)
            ectx = EmitCtx([], 1)
            ectx.lambda_vals = dict(outer_ctx.lambda_vals)
            ectx.lambda_vals[acc_var.var_id] = CV(a_d[None], a_v[None])
            ectx.lambda_vals[x_var.var_id] = CV(ed[None], ev[None])
            out = bbody.emit(ectx)
            n_d = jnp.where(live_i, out.data[0], a_d)
            n_v = jnp.where(live_i, out.validity[0], a_v)
            return (n_d, n_v), (n_d, n_v)

        (_, _), (accs, accvs) = jax.lax.scan(
            step, (zrow_d[0], zrow_v[0]),
            (live, is_start, ze_d, ze_v, e.data, e.validity))
        # per-row result = acc at that row's last live element (or zero)
        lens = arr_lens(arr)
        last = arr.offsets[:-1] + jnp.maximum(lens - 1, 0)
        last = jnp.clip(last, 0, ecap - 1)
        res_d = jnp.where(lens > 0, accs[last], zrow_d)
        res_v = jnp.where(lens > 0, accvs[last], zrow_v)
        return CV(res_d, res_v & arr.validity)

    def __repr__(self):
        return f"aggregate({self.child}, {self.zero}, <lambda>)"
