"""Regex expressions: RLike, RegexpExtract, RegexpReplace.

(reference: the regex transpiler RegexParser.scala:47 /
CudfRegexTranspiler:696 feeding cuDF RegexProgram kernels via
stringFunctions.scala rules.) Patterns compile at bind time to a
bit-parallel NFA (ops/regex_nfa.py); unsupported patterns raise
UnsupportedExpr so the planner tags/falls back instead of crashing.

Deviations documented in docs/compatibility.md (Regex): byte-domain
matching, greedy-longest alternation order, MAX_SCAN-byte scan bound.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.column import bucket_capacity
from ..ops.kernel_utils import CV
from ..ops.regex_exec import (MAX_SCAN, extract_first, nfa_match,
                              replace_all)
from ..ops.regex_nfa import (Concat, Group, RegexUnsupported, compile_nfa,
                             parse, _len_bounds)
from .expressions import Expression, UnsupportedExpr
from .string_exprs import _require_string

__all__ = ["RLike", "RegexpExtract", "RegexpReplace"]


def _compile(pattern: str):
    try:
        return compile_nfa(pattern)
    except RegexUnsupported as e:
        raise UnsupportedExpr(
            f"regex pattern {pattern!r} outside the TPU-transpilable "
            f"subset: {e}") from e


class RLike(Expression):
    """`str rlike pattern` — unanchored regex search (Java semantics on
    the supported subset)."""

    def __init__(self, child: Expression, pattern: str):
        self.child = child
        self.pattern = pattern
        self.children = [child]

    def bind(self, schema):
        c = self.child.bind(schema)
        _require_string(c, "rlike")
        b = RLike(c, self.pattern)
        b._rx = _compile(self.pattern)
        b.dtype = dt.BOOL
        return b

    def emit(self, ctx):
        cv = self.child.emit(ctx)
        # scan whole rows (an unanchored match can start anywhere), up to
        # the MAX_SCAN byte bound (documented)
        L = min(MAX_SCAN, int(cv.data.shape[0]))
        m = nfa_match(self._rx, cv, max(L, 1))
        return CV(m, cv.validity)

    def __repr__(self):
        return f"({self.child!r} RLIKE {self.pattern!r})"


class RegexpReplace(Expression):
    """regexp_replace(str, pattern, replacement-literal): replace all
    non-overlapping matches."""

    def __init__(self, child: Expression, pattern: str, replacement: str):
        self.child = child
        self.pattern = pattern
        self.replacement = replacement
        self.children = [child]

    def bind(self, schema):
        c = self.child.bind(schema)
        _require_string(c, "regexp_replace")
        if "$" in self.replacement or "\\" in self.replacement:
            # group references need capture tracking: host fallback serves
            # these (expr/host_eval.py translates $n)
            raise UnsupportedExpr(
                "regexp_replace group references in replacement")
        b = RegexpReplace(c, self.pattern, self.replacement)
        b._rx = _compile(self.pattern)
        b.dtype = dt.STRING
        return b

    def emit(self, ctx):
        cv = self.child.emit(ctx)
        rx = self._rx
        B = int(cv.data.shape[0])
        max_match = min(rx.max_len if rx.max_len is not None else MAX_SCAN,
                        MAX_SCAN, B)
        rl = len(self.replacement.encode())
        if rx.min_len <= 0:
            factor = rl + 1
        else:
            factor = max(1, -(-rl // rx.min_len))
        out_cap = bucket_capacity(B * factor)
        return replace_all(rx, cv, self.replacement.encode(),
                           max(max_match, 1), out_cap)

    def __repr__(self):
        return (f"regexp_replace({self.child!r}, {self.pattern!r}, "
                f"{self.replacement!r})")


class RegexpExtract(Expression):
    """regexp_extract(str, pattern, idx): substring matched by group idx
    of the first match; '' when no match (Spark semantics).

    idx=0 extracts the whole match. idx>0 is supported when the group is
    a top-level concat element with fixed-length prefix and suffix
    subpatterns (e.g. `foo=([0-9]+);`), else tagged unsupported."""

    def __init__(self, child: Expression, pattern: str, idx: int = 0):
        self.child = child
        self.pattern = pattern
        self.idx = idx
        self.children = [child]

    def bind(self, schema):
        c = self.child.bind(schema)
        _require_string(c, "regexp_extract")
        b = RegexpExtract(c, self.pattern, self.idx)
        b._rx = _compile(self.pattern)
        b._pre, b._post = self._group_margins()
        b.dtype = dt.STRING
        return b

    def _group_margins(self):
        if self.idx == 0:
            return 0, 0
        ast, _, aend, ngroups = parse(self.pattern)
        if aend:
            # the compiled NFA consumes an optional final line terminator
            # for '$', which would shift the fixed post-margin
            raise UnsupportedExpr(
                "regexp_extract group with a $-anchored pattern")
        if self.idx > ngroups:
            raise UnsupportedExpr(
                f"regexp_extract group {self.idx} of {ngroups}")
        parts = ast.parts if isinstance(ast, Concat) else [ast]
        gpos = None
        for i, p in enumerate(parts):
            if isinstance(p, Group) and p.index == self.idx:
                gpos = i
                break
        if gpos is None:
            raise UnsupportedExpr(
                "regexp_extract group must be a top-level concat element")
        pre_lo, pre_hi = _len_bounds(Concat(parts[:gpos]))
        post_lo, post_hi = _len_bounds(Concat(parts[gpos + 1:]))
        if pre_lo != pre_hi or post_lo != post_hi:
            raise UnsupportedExpr(
                "regexp_extract needs fixed-length text around the group")
        return pre_lo, post_lo

    def emit(self, ctx):
        from ..ops.strings import rebuild_strings
        cv = self.child.emit(ctx)
        rx = self._rx
        B = int(cv.data.shape[0])
        max_match = min(rx.max_len if rx.max_len is not None else MAX_SCAN,
                        MAX_SCAN, B)
        start, ln, found = extract_first(rx, cv, max(max_match, 1))
        gstart = start + self._pre
        glen = jnp.maximum(ln - self._pre - self._post, 0)
        # no match -> empty string (Spark), null in -> null out
        gstart = jnp.where(found, gstart, 0).astype(jnp.int32)
        glen = jnp.where(found, glen, 0).astype(jnp.int32)
        out = rebuild_strings(cv, gstart, glen)
        return CV(out.data, cv.validity, out.offsets)

    def __repr__(self):
        return (f"regexp_extract({self.child!r}, {self.pattern!r}, "
                f"{self.idx})")
