"""Aggregate functions: update/merge/finalize protocol.

Mirrors the reference's CudfAggregate split into update/merge phases
(reference: org/apache/spark/sql/rapids/aggregate/aggregateFunctions.scala)
so the exec layer can run partial-per-batch aggregation, merge partials on
device, and finalize — for both ungrouped reductions and (sort-based)
grouped aggregation via jax.ops.segment_* primitives.

States are tuples of jnp scalars (ungrouped) or [num_segments] arrays
(grouped). All null semantics follow Spark:
  sum/min/max over zero valid rows -> null; count is never null;
  avg = sum/count, null when count == 0.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..ops.kernel_utils import CV
from .expressions import (Cast, Expression, Literal, UnsupportedExpr)

__all__ = ["AggExpr", "Sum", "Count", "CountStar", "Min", "Max", "Avg",
           "First", "Last", "Stddev", "Variance"]

_MINMAX_IDENT = {
    jnp.float32: (jnp.inf, -jnp.inf),
    jnp.float64: (jnp.inf, -jnp.inf),
}


def _ident(np_dtype, for_min: bool):
    if jnp.issubdtype(np_dtype, jnp.floating):
        return jnp.inf if for_min else -jnp.inf
    if np_dtype == jnp.bool_:
        return True if for_min else False
    info = jnp.iinfo(np_dtype)
    return info.max if for_min else info.min


class AggExpr(Expression):
    """An aggregate over a child expression. Not valid in row projections."""

    def __init__(self, child: Optional[Expression]):
        self.child = child
        self.children = [child] if child is not None else []

    def bind(self, schema):
        b = type(self)(self.child.bind(schema) if self.child else None)
        b._resolve_type()
        return b

    def _resolve_type(self):
        raise NotImplementedError

    # --- protocol: ungrouped ------------------------------------------
    # update(cv, mask) -> state (tuple of scalars)
    # merge(s1, s2) -> state
    # finalize(state) -> (scalar_value, scalar_valid)
    def num_state_cols(self) -> int:
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__.lower()}({self.child})"


class Sum(AggExpr):
    """Sum. Decimal results over precision 18 accumulate EXACTLY as
    per-32-bit-limb int64 partial sums (JNI DecimalUtils sum analog);
    overflow past the result precision yields null (Spark non-ANSI)."""

    state_reducers = ("sum", "or")

    def _resolve_type(self):
        ct = self.child.dtype
        self._d128 = False
        self._in_d128 = False
        if isinstance(ct, dt.DecimalType):
            self.dtype = dt.DecimalType(min(38, ct.precision + 10),
                                        ct.scale)
            if self.dtype.is_decimal128:
                self._d128 = True
                self._in_d128 = ct.is_decimal128
                nlimbs = 4 if self._in_d128 else 2
                self.state_reducers = ("sum",) * nlimbs + ("or",)
        elif ct.is_integral or isinstance(ct, dt.BooleanType):
            self.dtype = dt.INT64
        elif ct.is_floating:
            self.dtype = dt.FLOAT64
        elif isinstance(ct, dt.NullType):
            self.dtype = dt.FLOAT64
        else:
            raise UnsupportedExpr(f"sum({ct})")
        self._acc_dtype = self.dtype.np_dtype

    def _limbs(self, cv: CV, m):
        from ..ops import decimal128 as d128
        if self._in_d128:
            raw = d128.split_d128_limbs(cv.data)
        else:
            raw = d128.split_i64_limbs(cv.data)
        return [jnp.where(m, l, 0) for l in raw]

    def update(self, cv: CV, mask):
        m = mask & cv.validity
        if self._d128:
            limbs = self._limbs(cv, m)
            return tuple(jnp.sum(l) for l in limbs) + (jnp.any(m),)
        x = jnp.where(m, cv.data, 0).astype(self._acc_dtype)
        return (jnp.sum(x), jnp.any(m))

    def merge(self, s1, s2):
        if self._d128:
            return tuple(a + b for a, b in zip(s1[:-1], s2[:-1])) \
                + (s1[-1] | s2[-1],)
        return (s1[0] + s2[0], s1[1] | s2[1])

    def finalize(self, s):
        if self._d128:
            from ..ops import decimal128 as d128
            val, ovf = d128.combine_limb_sums(list(s[:-1]),
                                              self.dtype.precision)
            return val, s[-1] & ~ovf
        return s[0], s[1]

    # --- grouped: per-segment ----
    def g_update(self, cv: CV, mask, seg_ids, num_segments):
        m = mask & cv.validity
        has = jax.ops.segment_max(m.astype(jnp.int32), seg_ids,
                                  num_segments) > 0
        if self._d128:
            limbs = self._limbs(cv, m)
            return tuple(jax.ops.segment_sum(l, seg_ids, num_segments)
                         for l in limbs) + (has,)
        x = jnp.where(m, cv.data, 0).astype(self._acc_dtype)
        return (jax.ops.segment_sum(x, seg_ids, num_segments), has)


class Count(AggExpr):
    state_reducers = ("sum",)

    def _resolve_type(self):
        self.dtype = dt.INT64

    def update(self, cv: CV, mask):
        return (jnp.sum((mask & cv.validity).astype(jnp.int64)),)

    def merge(self, s1, s2):
        return (s1[0] + s2[0],)

    def finalize(self, s):
        return s[0], jnp.bool_(True)

    def g_update(self, cv, mask, seg_ids, num_segments):
        m = (mask & cv.validity).astype(jnp.int64)
        return (jax.ops.segment_sum(m, seg_ids, num_segments),)


class CountStar(AggExpr):
    state_reducers = ("sum",)

    def __init__(self, child=None):
        super().__init__(None)

    def _resolve_type(self):
        self.dtype = dt.INT64

    def bind(self, schema):
        b = CountStar()
        b._resolve_type()
        return b

    def update(self, cv, mask):
        return (jnp.sum(mask.astype(jnp.int64)),)

    def merge(self, s1, s2):
        return (s1[0] + s2[0],)

    def finalize(self, s):
        return s[0], jnp.bool_(True)

    def g_update(self, cv, mask, seg_ids, num_segments):
        return (jax.ops.segment_sum(mask.astype(jnp.int64), seg_ids,
                                    num_segments),)

    def __repr__(self):
        return "count(*)"


def _d128_sortable(data2):
    """[cap,2] -> (hi, lo') where lexicographic (hi, lo') min/max equals
    the signed 128-bit min/max: hi signed, lo bias-flipped to signed-
    comparable unsigned order."""
    hi = data2[:, 1]
    lo = data2[:, 0] ^ jnp.int64(-(1 << 63))
    return hi, lo


def _d128_unsortable(hi, lo):
    return jnp.stack([lo ^ jnp.int64(-(1 << 63)), hi], axis=-1)


class _MinMax(AggExpr):
    for_min = True

    @property
    def state_reducers(self):
        if getattr(self, "_d128_in", False):
            return ("custom",)
        return ("min" if self.for_min else "max", "or")

    def _resolve_type(self):
        ct = self.child.dtype
        if ct.is_variable_width or ct.is_nested:
            raise UnsupportedExpr(f"min/max({ct}) round-1")
        self._d128_in = (isinstance(ct, dt.DecimalType)
                         and ct.is_decimal128)
        self.dtype = ct

    def _masked(self, cv, m):
        """Mask invalid rows to the identity; for float min, NaN (greatest
        per Spark ordering) must lose to any real value, so map it to +inf
        (documented deviation: an all-NaN min yields +inf, not NaN)."""
        ident = _ident(cv.data.dtype, self.for_min)
        x = jnp.where(m, cv.data, ident)
        if self.for_min and jnp.issubdtype(x.dtype, jnp.floating):
            x = jnp.where(jnp.isnan(x), jnp.inf, x)
        return x

    # -- decimal128: lexicographic (hi, lo') reduction -------------------
    def _d128_masked(self, cv, m):
        hi, lo = _d128_sortable(cv.data)
        ident_hi = _ident(jnp.dtype(jnp.int64), self.for_min)
        hi = jnp.where(m, hi, ident_hi)
        lo = jnp.where(m, lo, ident_hi)
        return hi, lo

    @staticmethod
    def _lex_pick(for_min, h1, l1, h2, l2):
        take1 = (h1 < h2) | ((h1 == h2) & (l1 <= l2))
        if not for_min:
            take1 = (h1 > h2) | ((h1 == h2) & (l1 >= l2))
        return (jnp.where(take1, h1, h2), jnp.where(take1, l1, l2))

    def num_state_cols(self):
        return 3 if getattr(self, "_d128_in", False) else 2

    def update(self, cv: CV, mask):
        m = mask & cv.validity
        if getattr(self, "_d128_in", False):
            hi, lo = self._d128_masked(cv, m)
            # reduce hi first, then lo among rows holding the winning hi
            red_hi = jnp.min(hi) if self.for_min else jnp.max(hi)
            cand = jnp.where(hi == red_hi, lo,
                             _ident(jnp.dtype(jnp.int64), self.for_min))
            red_lo = jnp.min(cand) if self.for_min else jnp.max(cand)
            return (red_hi, red_lo, jnp.any(m))
        x = self._masked(cv, m)
        red = jnp.min(x) if self.for_min else jnp.max(x)
        return (red, jnp.any(m))

    def merge(self, s1, s2):
        if getattr(self, "_d128_in", False):
            h, l = self._lex_pick(self.for_min, s1[0], s1[1], s2[0], s2[1])
            return (h, l, s1[2] | s2[2])
        v = jnp.minimum(s1[0], s2[0]) if self.for_min else jnp.maximum(
            s1[0], s2[0])
        # all-invalid partials carry the identity, so plain min/max is safe
        return (v, s1[1] | s2[1])

    def finalize(self, s):
        if getattr(self, "_d128_in", False):
            return _d128_unsortable(s[0], s[1]), s[2]
        return s[0], s[1]

    def g_update(self, cv, mask, seg_ids, num_segments):
        m = mask & cv.validity
        if getattr(self, "_d128_in", False):
            hi, lo = self._d128_masked(cv, m)
            seg = (jax.ops.segment_min if self.for_min
                   else jax.ops.segment_max)
            red_hi = seg(hi, seg_ids, num_segments)
            ident = _ident(jnp.dtype(jnp.int64), self.for_min)
            cand = jnp.where(hi == red_hi[seg_ids], lo, ident)
            red_lo = seg(cand, seg_ids, num_segments)
            has = jax.ops.segment_max(m.astype(jnp.int32), seg_ids,
                                      num_segments) > 0
            return (red_hi, red_lo, has)
        x = self._masked(cv, m)
        seg = (jax.ops.segment_min if self.for_min else jax.ops.segment_max)
        return (seg(x, seg_ids, num_segments),
                jax.ops.segment_max(m.astype(jnp.int32), seg_ids,
                                    num_segments) > 0)

    def g_merge_custom(self, cols_sorted, live, seg_ids, num_segments):
        hi, lo, has = cols_sorted
        eligible = live & has.astype(jnp.bool_)
        ident = _ident(jnp.dtype(jnp.int64), self.for_min)
        hi_m = jnp.where(eligible, hi, ident)
        lo_m = jnp.where(eligible, lo, ident)
        seg = (jax.ops.segment_min if self.for_min
               else jax.ops.segment_max)
        red_hi = seg(hi_m, seg_ids, num_segments)
        cand = jnp.where((hi_m == red_hi[seg_ids]) & eligible, lo_m,
                         ident)
        red_lo = seg(cand, seg_ids, num_segments)
        has_out = jax.ops.segment_max(eligible.astype(jnp.int32), seg_ids,
                                      num_segments) > 0
        return (red_hi, red_lo, has_out)


class Min(_MinMax):
    for_min = True


class Max(_MinMax):
    for_min = False


class Avg(AggExpr):
    state_reducers = ("sum", "sum")

    def _resolve_type(self):
        ct = self.child.dtype
        if isinstance(ct, dt.DecimalType):
            if ct.is_decimal128:
                raise UnsupportedExpr(
                    "avg over decimal precision > 18 (sum/count it "
                    "explicitly, or cast)")
            s = min(ct.scale + 4, 18)
            self.dtype = dt.DecimalType(18, s)
            self._sum_scale = ct.scale
        elif ct.is_integral or isinstance(ct, dt.BooleanType):
            # Spark computes avg(long) from the wrapping int64 sum
            self.dtype = dt.FLOAT64
            self._sum_scale = None
            self._int_acc = True
        elif ct.is_numeric or isinstance(ct, dt.NullType):
            self.dtype = dt.FLOAT64
            self._sum_scale = None
            self._int_acc = False
        else:
            raise UnsupportedExpr(f"avg({ct})")

    def _acc(self, cv, m):
        if self._sum_scale is not None or getattr(self, "_int_acc", False):
            return jnp.where(m, cv.data, 0).astype(jnp.int64)
        return jnp.where(m, cv.data, 0).astype(jnp.float64)

    def update(self, cv: CV, mask):
        m = mask & cv.validity
        x = self._acc(cv, m)
        return (jnp.sum(x), jnp.sum(m.astype(jnp.int64)))

    def merge(self, s1, s2):
        return (s1[0] + s2[0], s1[1] + s2[1])

    def finalize(self, s):
        total, cnt = s
        valid = cnt > 0
        safe = jnp.where(valid, cnt, 1)
        if self._sum_scale is not None:
            shift = self.dtype.scale - self._sum_scale
            num = total * (10 ** shift)
            half = safe // 2
            adj = jnp.where(num >= 0, num + half, num - half)
            q = adj // safe
            r = adj - q * safe
            q = jnp.where((r != 0) & (adj < 0), q + 1, q)
            return q, valid
        return total.astype(jnp.float64) / safe, valid

    def g_update(self, cv, mask, seg_ids, num_segments):
        m = mask & cv.validity
        x = self._acc(cv, m)
        return (jax.ops.segment_sum(x, seg_ids, num_segments),
                jax.ops.segment_sum(m.astype(jnp.int64), seg_ids,
                                    num_segments))


def _seg_extreme_pos(eligible, seg_ids, num_segments, take_first: bool):
    """Per-segment position of the first/last eligible row ->
    (safe_index, found). Shared by _FirstLast update/merge paths."""
    n = eligible.shape[0]
    idxs = jnp.arange(n)
    sentinel = n if take_first else -1
    cand = jnp.where(eligible, idxs, sentinel)
    seg = jax.ops.segment_min if take_first else jax.ops.segment_max
    pos = seg(cand, seg_ids, num_segments)
    found = (pos < n) if take_first else (pos >= 0)
    return jnp.clip(pos, 0, n - 1), found


class _FirstLast(AggExpr):
    """State (value, valid, has): `has` marks whether an eligible row was
    seen. Grouped merge picks the first/last eligible partial in concat
    order (the stable key sort preserves it) via g_merge_custom."""

    take_first = True
    state_reducers = ("custom",)

    def __init__(self, child, ignore_nulls: bool = False):
        super().__init__(child)
        self.ignore_nulls = ignore_nulls

    def bind(self, schema):
        b = type(self)(self.child.bind(schema), self.ignore_nulls)
        b._resolve_type()
        return b

    def _resolve_type(self):
        ct = self.child.dtype
        if ct.is_nested:
            raise UnsupportedExpr("first/last on nested input")
        if ct.is_variable_width:
            # strings/binary can't ride the fixed-width state wire:
            # route through the sort-collect path (raw rows exchanged on
            # the grouping keys), where a per-segment positional select
            # serves first/last in input order
            self.is_collect = True
        self.dtype = ct

    def update(self, cv: CV, mask):
        m = mask & (cv.validity if self.ignore_nulls else
                    jnp.ones_like(cv.validity))
        n = m.shape[0]
        idxs = jnp.arange(n)
        sentinel = n if self.take_first else -1
        cand = jnp.where(m, idxs, sentinel)
        pos = jnp.min(cand) if self.take_first else jnp.max(cand)
        has = (pos < n) if self.take_first else (pos >= 0)
        safe = jnp.clip(pos, 0, n - 1)
        return (cv.data[safe], cv.validity[safe] & has, has)

    def merge(self, s1, s2):
        a, b = (s1, s2) if self.take_first else (s2, s1)
        take_a = a[2]
        return (jnp.where(take_a, a[0], b[0]),
                jnp.where(take_a, a[1], b[1]), a[2] | b[2])

    def finalize(self, s):
        return s[0], s[1]

    def num_state_cols(self):
        return 3

    def g_update(self, cv, mask, seg_ids, num_segments):
        m = mask & (cv.validity if self.ignore_nulls else
                    jnp.ones_like(cv.validity))
        safe, has = _seg_extreme_pos(m, seg_ids, num_segments,
                                     self.take_first)
        return (cv.data[safe], cv.validity[safe] & has, has)

    def g_merge_custom(self, cols_sorted, live, seg_ids, num_segments):
        val, valid, has = cols_sorted
        eligible = live & has.astype(jnp.bool_)
        safe, found = _seg_extreme_pos(eligible, seg_ids, num_segments,
                                       self.take_first)
        return (val[safe], valid[safe].astype(jnp.bool_) & found, found)


class First(_FirstLast):
    take_first = True


class Last(_FirstLast):
    take_first = False


class Variance(AggExpr):
    """var_samp (Spark variance) with Welford/Chan merging — the
    E[x^2]-E[x]^2 form catastrophically cancels for large-magnitude
    inputs. State: (n, mean, M2); batch update computes the per-segment
    mean then M2 = sum((x-mean)^2); merges use Chan's formula via a
    custom grouped merge (reference: aggregateFunctions.scala M2-based
    variance)."""

    state_reducers = ("custom",)  # uses g_merge_custom
    ddof = 1

    def _resolve_type(self):
        ct = self.child.dtype
        if not (ct.is_numeric or isinstance(ct, dt.NullType)):
            raise UnsupportedExpr(f"variance({ct})")
        if isinstance(ct, dt.DecimalType) and ct.is_decimal128:
            raise UnsupportedExpr(
                "variance over decimal precision > 18 (cast first)")
        self.dtype = dt.FLOAT64
        self._scale = (10.0 ** -ct.scale
                       if isinstance(ct, dt.DecimalType) else 1.0)

    def num_state_cols(self):
        return 3

    def _xs(self, cv, m):
        return jnp.where(m, cv.data, 0).astype(jnp.float64) * self._scale

    # ---- ungrouped ----------------------------------------------------
    def update(self, cv: CV, mask):
        m = mask & cv.validity
        x = self._xs(cv, m)
        n = jnp.sum(m.astype(jnp.int64))
        nf = jnp.maximum(n, 1).astype(jnp.float64)
        mean = jnp.sum(x) / nf
        d = jnp.where(m, x - mean, 0.0)
        m2 = jnp.sum(d * d)
        return (n, mean, m2)

    def merge(self, s1, s2):
        n1, m1, q1 = s1
        n2, m2_, q2 = s2
        n = n1 + n2
        nf = jnp.maximum(n, 1).astype(jnp.float64)
        delta = m2_ - m1
        mean = m1 + delta * (n2.astype(jnp.float64) / nf)
        q = (q1 + q2 + delta * delta
             * (n1.astype(jnp.float64) * n2.astype(jnp.float64) / nf))
        return (n, mean, q)

    def finalize(self, s):
        n, _, m2 = s
        valid = n > self.ddof
        denom = jnp.where(valid, (n - self.ddof).astype(jnp.float64), 1.0)
        return self._final_value(jnp.maximum(m2, 0.0) / denom), valid

    def _final_value(self, var):
        return var

    # ---- grouped ------------------------------------------------------
    def g_update(self, cv, mask, seg_ids, num_segments):
        m = mask & cv.validity
        x = self._xs(cv, m)
        n = jax.ops.segment_sum(m.astype(jnp.int64), seg_ids, num_segments)
        nf = jnp.maximum(n, 1).astype(jnp.float64)
        mean = jax.ops.segment_sum(x, seg_ids, num_segments) / nf
        d = jnp.where(m, x - mean[seg_ids], 0.0)
        m2 = jax.ops.segment_sum(d * d, seg_ids, num_segments)
        return (n, mean, m2)

    def g_merge_custom(self, cols_sorted, live, seg_ids, num_segments):
        """Chan's parallel combine across partial states of one segment:
        Mean = sum(n_i mean_i)/N; M2 = sum(M2_i) + sum(n_i (mean_i-Mean)^2).
        Differences of means stay small, so no cancellation."""
        n_i, mean_i, m2_i = cols_sorted
        n_i = jnp.where(live, n_i, 0)
        mean_i = jnp.where(live, mean_i, 0.0)
        m2_i = jnp.where(live, m2_i, 0.0)
        N = jax.ops.segment_sum(n_i, seg_ids, num_segments)
        Nf = jnp.maximum(N, 1).astype(jnp.float64)
        Mean = jax.ops.segment_sum(
            n_i.astype(jnp.float64) * mean_i, seg_ids, num_segments) / Nf
        dev = mean_i - Mean[seg_ids]
        M2 = (jax.ops.segment_sum(m2_i, seg_ids, num_segments)
              + jax.ops.segment_sum(
                  n_i.astype(jnp.float64) * dev * dev, seg_ids,
                  num_segments))
        return (N, Mean, M2)


class Stddev(Variance):
    """stddev_samp (Spark stddev)."""

    def _final_value(self, var):
        return jnp.sqrt(var)


class _Collect(AggExpr):
    """collect_list / collect_set (reference: aggregateFunctions.scala
    GpuCollectList/GpuCollectSet over cudf collect aggregations).

    Variable-width result: runs on CollectAggExec's sort path (one stable
    sort by keys makes each group's values contiguous — the sorted value
    column IS the concatenated list child), not the flat-state machinery.
    `state_reducers = None` keeps HashAggregateExec from accepting it."""

    state_reducers = None
    is_collect = True
    is_set = False

    def _resolve_type(self):
        from ..columnar import dtypes as _dt
        if self.child.dtype.is_nested:
            raise UnsupportedExpr(
                f"{type(self).__name__.lower()} over nested input")
        self.dtype = _dt.ArrayType(self.child.dtype, contains_null=False)


class CollectList(_Collect):
    def __repr__(self):
        return f"collect_list({self.child})"


class CollectSet(_Collect):
    is_set = True

    def __repr__(self):
        return f"collect_set({self.child})"


class CountDistinct(_Collect):
    """count(DISTINCT x) via the sort path: per-group first-occurrence
    flags from a segmented value sort (reference: distinct-agg rewrite +
    cudf distinct count)."""

    is_set = True       # needs per-agg value ordering for dedup
    is_collect = True

    def _resolve_type(self):
        from ..columnar import dtypes as _dt
        if self.child.dtype.is_nested:
            raise UnsupportedExpr("count distinct over nested input")
        self.dtype = _dt.INT64

    def __repr__(self):
        return f"count(DISTINCT {self.child})"


class _HllHash(Expression):
    """Internal: murmur3(child) with the CHILD's validity (nulls skip —
    unlike the user-facing Murmur3Hash whose null folds to the seed).
    Makes the HLL agg input fixed-width int32, so strings/decimals ride
    the grouped agg paths that strip var-width agg inputs."""

    def __init__(self, child):
        self.child = child
        self.children = [child]
        self.dtype = dt.INT32

    def bind(self, schema):
        return _HllHash(self.child.bind(schema))

    def emit(self, ctx):
        from ..ops.hash import murmur3_cv
        cv = self.child.emit(ctx)
        h = murmur3_cv(cv, self.child.dtype, jnp.int32(42))
        return CV(h, cv.validity)

    def __repr__(self):
        return f"hll_hash({self.child})"


def _clz32(x):
    """Vectorized count-leading-zeros over uint32 (5-step binary
    search; no clz primitive in XLA HLO)."""
    x = x.astype(jnp.uint32)
    zero = x == 0
    c = jnp.zeros(x.shape, jnp.int32)
    for sh in (16, 8, 4, 2, 1):
        cond = x < (jnp.uint32(1) << (32 - sh))
        c = c + jnp.where(cond, sh, 0)
        x = jnp.where(cond, x << sh, x)
    return jnp.where(zero, 32, c)


class ApproxCountDistinct(AggExpr):
    """approx_count_distinct as HyperLogLog++ with O(2^p) register state
    — bounded across the exchange regardless of cardinality (reference:
    GpuHyperLogLogPlusPlus in org/apache/spark/sql/rapids/aggregate/,
    cuDF JNI HLLPP kernels).

    TPU-first layout: the 2^p byte registers of every group pack 8-per-
    int64 into W = 2^p / 8 ordinary state COLUMNS, so partial states ride
    the existing partial/final wire schema, spill framework, and mesh
    exchange like any other aggregate. update computes (register-index,
    rho) per row from the engine's 32-bit murmur3 (via the bound _HllHash
    child, so any input type arrives as int32) and runs ONE segment_max
    over combined (segment * m + register) ids — output memory is
    O(num_segments * 2^p), which on the FIRST per-batch update means
    O(batch_cap * 2^p) int32 (e.g. 4096-row batches at p=9: 8 MB; size
    batches accordingly for small rsd) and collapses to O(groups * 2^p)
    after the first merge. Merge is a per-byte max of packed words
    (custom segmented reducer). Estimation uses the HLL++ alpha with
    linear counting below 2.5m and the 32-bit large-range correction;
    the empirical bias table is omitted (documented in
    docs/compatibility.md — worst case a few percent in the 2.5m..5m
    band, still within typical rsd use).

    rsd -> p via rsd = 1.04/sqrt(2^p), clamped to [4, 12].
    """

    state_reducers = ("custom",)

    def __init__(self, child, rsd: float = 0.05):
        super().__init__(child)
        self.rsd = rsd
        import math
        p = math.ceil(2 * math.log2(1.04 / rsd))
        self.p = max(4, min(12, p))
        self.m = 1 << self.p
        self.W = self.m // 8

    def bind(self, schema):
        bc = self.child.bind(schema)
        if bc.dtype.is_nested:
            raise UnsupportedExpr("approx_count_distinct over nested")
        b = type(self)(_HllHash(bc), self.rsd)
        b._resolve_type()
        return b

    def _resolve_type(self):
        self.dtype = dt.INT64

    def num_state_cols(self):
        return self.W

    # -- hashing --------------------------------------------------------
    def _idx_rho(self, cv: CV, mask):
        # child is _HllHash: cv.data IS the 32-bit hash, validity is the
        # original child's (nulls excluded)
        hu = cv.data.astype(jnp.uint32)
        valid = mask & cv.validity
        idx = (hu >> (32 - self.p)).astype(jnp.int32)
        w = hu << self.p
        rho = _clz32(w) + 1          # 1..(32-p)+1; w==0 -> 33-p cap
        rho = jnp.minimum(rho, 32 - self.p + 1)
        rho = jnp.where(valid, rho, 0).astype(jnp.int32)
        idx = jnp.where(valid, idx, 0)
        return idx, rho

    def _pack(self, regs2d):
        """(nseg, m) int32 registers -> tuple of W packed int64 words."""
        n = regs2d.shape[0]
        r = regs2d.reshape(n, self.W, 8).astype(jnp.int64)
        shifts = (jnp.arange(8, dtype=jnp.int64) * 8)[None, None, :]
        words = jnp.sum(r << shifts, axis=2)      # (nseg, W)
        return tuple(words[:, i] for i in range(self.W))

    @staticmethod
    def _unpack(words):
        """list of W (n,) int64 -> (n, m) int32 registers."""
        return ApproxCountDistinct._unpack_stacked(
            jnp.stack(words, axis=1))

    @staticmethod
    def _unpack_stacked(stacked):
        """(n, W) packed int64 -> (n, m) int32 registers."""
        shifts = (jnp.arange(8, dtype=jnp.int64) * 8)[None, None, :]
        bytes_ = (stacked[:, :, None] >> shifts) & jnp.int64(0xFF)
        n = stacked.shape[0]
        return bytes_.reshape(n, -1).astype(jnp.int32)

    # -- grouped --------------------------------------------------------
    def g_update(self, cv: CV, mask, seg_ids, num_segments):
        idx, rho = self._idx_rho(cv, mask)
        # combined (segment, register) key -> one segment_max over
        # num_segments * m slots. Memory is O(cap + num_segments * m);
        # the with_retry split bounds cap, and num_segments collapses to
        # the actual group capacity after the first merge.
        comb = seg_ids.astype(jnp.int64) * self.m + idx.astype(jnp.int64)
        regs = jax.ops.segment_max(rho, comb, num_segments * self.m)
        # empty (segment, register) slots come back as int32-min (the
        # segment_max identity) — clamp to 0 before byte-packing
        regs = jnp.maximum(regs, 0)
        words = self._pack(regs.reshape(num_segments, self.m))
        return tuple(words)

    def g_merge_custom(self, cols_sorted, live, seg_ids, num_segments):
        regs = self._unpack(list(cols_sorted))    # (cap, m)
        regs = jnp.where(live[:, None], regs, 0)
        merged = jax.ops.segment_max(regs, seg_ids, num_segments)
        return self._pack(jnp.maximum(merged, 0))  # empty seg -> int-min

    # -- ungrouped ------------------------------------------------------
    # State is ONE (W,) vector (not W scalars: the runtime dedups
    # aliased same-buffer args, and W slices of one packed array broke
    # the compiled arg count).
    def update(self, cv: CV, mask):
        zeros = jnp.zeros(mask.shape[0], jnp.int32)
        words = self.g_update(cv, mask, zeros, 1)
        return (jnp.stack([w[0] for w in words]),)

    def merge(self, s1, s2):
        r1 = self._unpack_stacked(s1[0][None, :])
        r2 = self._unpack_stacked(s2[0][None, :])
        packed = self._pack(jnp.maximum(r1, r2))
        return (jnp.stack([w[0] for w in packed]),)

    def finalize(self, s):
        arrs = list(s)
        # ungrouped state is ONE (W,) vector; grouped is W >= 2 columns
        ungrouped = len(arrs) == 1 and arrs[0].ndim == 1
        if ungrouped:
            regs = self._unpack_stacked(arrs[0][None, :])
        else:
            regs = self._unpack(arrs)             # (n, m)
        m = float(self.m)
        alpha = 0.7213 / (1.0 + 1.079 / m)
        inv = jnp.sum(jnp.exp2(-regs.astype(jnp.float64)), axis=1)
        e_raw = alpha * m * m / inv
        zeros = jnp.sum((regs == 0).astype(jnp.float64), axis=1)
        lin = m * jnp.log(m / jnp.maximum(zeros, 1.0))
        est = jnp.where((e_raw <= 2.5 * m) & (zeros > 0), lin, e_raw)
        two32 = 4294967296.0
        est = jnp.where(
            est > two32 / 30.0,
            -two32 * jnp.log1p(-jnp.minimum(est, two32 * 0.999) / two32),
            est)
        out = jnp.round(est).astype(jnp.int64)
        if ungrouped:
            return out[0], jnp.bool_(True)
        return out, jnp.ones(out.shape[0], jnp.bool_)

    def __repr__(self):
        return f"approx_count_distinct({self.child}, rsd={self.rsd})"


class BloomFilterAggregate(AggExpr):
    """bloom_filter_agg: builds an m-bit Bloom filter over the input
    (reference: GpuBloomFilterAggregate.scala + JNI BloomFilter kernels
    — there the filter feeds InSubqueryExec runtime filtering; here the
    companion expression is BloomFilterMightContain).

    TPU-first layout: the filter lives as ONE device bool vector of
    num_bits (update is a scatter of k=hash positions per row — no
    byte-packing in the hot loop); finalize packs little-endian bytes
    (BinaryType), 'k|num_bits' prefixed, which BloomFilterMightContain
    unpacks back to a device vector. Hash scheme: two 32-bit murmur3
    passes (seed 0 / seed 0x97B3AA8C) combine as h1 + i*h2 like Spark's
    split-64 scheme. Ungrouped only, matching Spark (the agg returns
    ONE filter for the build side)."""

    state_reducers = None            # grouped path unsupported

    def __init__(self, child, estimated_items: int = 1_000_000,
                 num_bits: int = None):
        super().__init__(child)
        if num_bits is None:
            # Spark default sizing: ~8 bits/item
            num_bits = max(64, int(estimated_items) * 8)
        # cap below 2^31: positions are int32 on device, and Spark caps
        # runtime.bloomFilter.maxNumBits similarly
        num_bits = min(int(num_bits), 1 << 30)
        self.num_bits = 1 << max(6, int(num_bits - 1).bit_length())
        self.k = 5

    def bind(self, schema):
        b = type(self)(self.child.bind(schema), num_bits=self.num_bits)
        b._resolve_type()
        return b

    def _resolve_type(self):
        ct = self.child.dtype
        if ct.is_nested:
            raise UnsupportedExpr("bloom_filter_agg over nested input")
        self.dtype = dt.BINARY

    def _positions(self, cv: CV, mask):
        from ..ops.hash import bloom_positions
        masked = CV(cv.data, mask & cv.validity, cv.offsets,
                    cv.children)
        return bloom_positions(masked, self.child.dtype, self.k,
                               self.num_bits)

    def update(self, cv: CV, mask):
        # dead rows route to a SACRIFICIAL slot (num_bits) rather than
        # clipping onto bit 0 — a duplicate-index scatter .set() picks
        # arbitrarily, so a dead row's False could clobber a real True
        bits = jnp.zeros(self.num_bits + 1, jnp.bool_)
        for p in self._positions(cv, mask):
            tgt = jnp.where(p >= 0, p, self.num_bits)
            bits = bits.at[tgt].set(True)
        return (bits[:self.num_bits],)

    def merge(self, s1, s2):
        return (s1[0] | s2[0],)

    def finalize(self, s):
        # pack bool bits -> little-endian uint8 bytes on device and emit
        # as ONE BinaryType value: 'BF1|k|num_bits|' + packed
        import numpy as np
        bits = s[0].reshape(-1, 8).astype(jnp.uint8)
        shifts = jnp.arange(8, dtype=jnp.uint8)
        packed = jnp.sum(bits << shifts, axis=1).astype(jnp.uint8)
        head = np.frombuffer(
            f"BF1|{self.k}|{self.num_bits}|".encode(), np.uint8)
        data = jnp.concatenate([jnp.asarray(head), packed])
        off = jnp.array([0, data.shape[0]], jnp.int32)
        v = CV(data, jnp.ones(1, jnp.bool_), off)
        return v, jnp.bool_(True)

    def __repr__(self):
        return f"bloom_filter_agg({self.child}, bits={self.num_bits})"


def parse_bloom_filter(blob: bytes):
    """'BF1|k|num_bits|'-prefixed packed filter -> (k, num_bits,
    numpy bool bit vector)."""
    import numpy as np
    if not blob.startswith(b"BF1|"):
        raise ValueError("not a bloom filter payload")
    _, k, m, rest = blob.split(b"|", 3)
    bits = np.unpackbits(np.frombuffer(rest, np.uint8),
                         bitorder="little")
    return int(k), int(m), bits.astype(bool)


class Percentile(_Collect):
    """percentile / percentile_approx / median over the segmented value
    sort: values of each group are contiguous and ordered after the
    secondary sort, so rank selection is one gather
    (reference: GpuApproximatePercentile's t-digest — here the sort path
    yields EXACT percentiles, an accuracy superset; the accuracy argument
    is accepted and ignored)."""

    is_set = True        # percentile needs per-agg value ordering
    is_collect = True
    interpolate = True   # percentile(): linear interpolation

    def __init__(self, child, percentages, accuracy: int = 10000):
        super().__init__(child)
        self.scalar_out = not isinstance(percentages, (list, tuple))
        self.percentages = ([float(percentages)] if self.scalar_out
                            else [float(p) for p in percentages])
        for p in self.percentages:
            if not (0.0 <= p <= 1.0):
                raise ValueError(f"percentage out of [0,1]: {p}")
        self.accuracy = accuracy

    def bind(self, schema):
        b = type(self)(self.child.bind(schema), 
                       (self.percentages[0] if self.scalar_out
                        else list(self.percentages)), self.accuracy)
        b._resolve_type()
        return b

    def _resolve_type(self):
        from ..columnar import dtypes as _dt
        ct = self.child.dtype
        if not ct.is_numeric or (isinstance(ct, _dt.DecimalType)):
            raise UnsupportedExpr(f"percentile over {ct}")
        elem = _dt.FLOAT64 if self.interpolate else ct
        self.dtype = elem if self.scalar_out else _dt.ArrayType(elem)

    def __repr__(self):
        return f"percentile({self.child}, {self.percentages})"


class ApproxPercentile(Percentile):
    """percentile_approx as a t-digest sketch with O(C) centroid state —
    bounded across the exchange regardless of group size (reference:
    GpuApproximatePercentile.scala + cuDF tdigest kernels; Spark CPU's
    QuantileSummaries).

    TPU-first layout: C rank-bucketed centroids per group stored as
    2C+2 ordinary float64 state COLUMNS (means..., weights..., min,
    max), so partial digests ride the existing partial/final wire
    schema, spill framework, and mesh exchange like any other
    aggregate. update sorts the batch by (segment, validity, value) —
    three stable argsorts, no data-dependent control flow — and bins
    within-group ranks through the t-digest k1 scale function
    k(q) = (C/pi)(asin(2q-1) + pi/2), then ONE segment_sum over
    combined (segment * C + bin) ids. merge flattens buffered digests
    to rows*C candidate centroids, re-sorts by (segment, mean), and
    re-bins cumulative-weight midpoints through the same scale
    function. finalize interpolates piecewise-linearly between centroid
    midrank/mean points with min/max sharpening at the tails.

    Like the reference (which returns cuDF t-digest doubles), results
    are float64 approximations, NOT exact input elements as Spark CPU
    returns (docs/compatibility.md); worst-case rank error per bucket
    is ~pi/(2C) at the median and tighter toward the tails.
    accuracy maps to C = clamp(accuracy // 50, 16, 128)."""

    is_set = False
    is_collect = False
    state_reducers = ("custom",)
    sort_free_update = False    # g_update sorts internally: keep it off
                                # the no-sort hash-bucket first pass

    def __init__(self, child, percentages, accuracy: int = 10000):
        super().__init__(child, percentages, accuracy)
        if int(accuracy) <= 0:
            raise ValueError(
                f"accuracy must be greater than 0 (got {accuracy})")
        self.C = max(16, min(128, int(accuracy) // 50))

    def num_state_cols(self):
        return 2 * self.C + 2

    def _kbin(self, q):
        """k1 scale function -> centroid bin in [0, C-1]."""
        C = self.C
        t = ((jnp.arcsin(jnp.clip(2.0 * q - 1.0, -1.0, 1.0))
              + (jnp.pi / 2)) * (C / jnp.pi))
        return jnp.clip(t.astype(jnp.int32), 0, C - 1)

    @staticmethod
    def _sort3(minor, mid, major):
        """Stable argsort by (major, mid, minor) via composed stable
        single-key sorts (least-significant first)."""
        p = jnp.argsort(minor, stable=True)
        p = p[jnp.argsort(mid[p], stable=True)]
        return p[jnp.argsort(major[p], stable=True)]

    # -- grouped --------------------------------------------------------
    def g_update(self, cv: CV, mask, seg_ids, num_segments):
        C = self.C
        cap = mask.shape[0]
        valid = mask & cv.validity
        x = cv.data.astype(jnp.float64)
        # sort rows by (segment, invalid-last, value); NaN values sort
        # after +inf (jnp.argsort NaN-last), i.e. NaN > everything —
        # Java Double.compare ordering, like Spark CPU
        perm = self._sort3(x, jnp.logical_not(valid).astype(jnp.uint8),
                           seg_ids)
        sseg = seg_ids[perm]
        sval = x[perm]
        svalid = valid[perm]
        pos = jnp.arange(cap)
        segstart = jax.ops.segment_min(pos, sseg, num_segments)[sseg]
        rank = (pos - segstart).astype(jnp.float64)
        ng = jax.ops.segment_sum(valid.astype(jnp.float64), seg_ids,
                                 num_segments)
        q = (rank + 0.5) / jnp.maximum(ng[sseg], 1.0)
        b = self._kbin(q)
        comb = sseg.astype(jnp.int64) * C + b.astype(jnp.int64)
        w = svalid.astype(jnp.float64)
        wsum = jax.ops.segment_sum(w, comb, num_segments * C)
        xsum = jax.ops.segment_sum(jnp.where(svalid, sval, 0.0), comb,
                                   num_segments * C)
        means = jnp.where(wsum > 0, xsum / jnp.maximum(wsum, 1.0), 0.0)
        # NaN is the GREATEST value (Java Double ordering): exclude it
        # from vmin — the state identity stays +inf (all-NaN groups
        # resolve to vmax at finalize) — but let it propagate via vmax
        fin = valid & jnp.logical_not(jnp.isnan(x))
        vmax = jax.ops.segment_max(jnp.where(valid, x, -jnp.inf),
                                   seg_ids, num_segments)
        vmin = jax.ops.segment_min(jnp.where(fin, x, jnp.inf),
                                   seg_ids, num_segments)
        mm = means.reshape(num_segments, C)
        wm = wsum.reshape(num_segments, C)
        return (tuple(mm[:, i] for i in range(C))
                + tuple(wm[:, i] for i in range(C)) + (vmin, vmax))

    def g_merge_custom(self, cols_sorted, live, seg_ids, num_segments):
        C = self.C
        means = jnp.stack(cols_sorted[:C], axis=1)          # (cap, C)
        ws = jnp.stack(cols_sorted[C:2 * C], axis=1)
        vmin = cols_sorted[2 * C]
        vmax = cols_sorted[2 * C + 1]
        ws = jnp.where(live[:, None], ws, 0.0)
        fm = means.reshape(-1)
        fw = ws.reshape(-1)
        fseg = jnp.repeat(seg_ids, C)
        nm, nw = self._recompress(fm, fw, fseg, num_segments)
        nvmin = jax.ops.segment_min(
            jnp.where(live, vmin, jnp.inf), seg_ids, num_segments)
        nvmax = jax.ops.segment_max(
            jnp.where(live, vmax, -jnp.inf), seg_ids, num_segments)
        return (tuple(nm[:, i] for i in range(C))
                + tuple(nw[:, i] for i in range(C)) + (nvmin, nvmax))

    def _recompress(self, fm, fw, fseg, num_segments):
        """Merge flat candidate centroids (mean fm, weight fw, segment
        fseg) into (num_segments, C) digests: sort by (segment,
        empty-last, mean), re-bin cumulative-weight midpoints through
        the scale function, one combined segment_sum."""
        C = self.C
        n = fm.shape[0]
        key = jnp.where(fw > 0, fm, jnp.inf)     # empty slots last
        p = self._sort3(key, (fw <= 0).astype(jnp.uint8), fseg)
        sseg = fseg[p]
        sw = fw[p]
        sm = jnp.where(fw[p] > 0, fm[p], 0.0)    # no 0*inf NaNs below
        cumw = jnp.cumsum(sw)
        pre = cumw - sw                           # exclusive prefix
        pos = jnp.arange(n)
        sstart = jax.ops.segment_min(pos, sseg, num_segments)
        segbase = pre[jnp.clip(sstart, 0, n - 1)][sseg]
        totw = jax.ops.segment_sum(fw, fseg, num_segments)
        q = (pre - segbase + sw / 2) / jnp.maximum(totw[sseg], 1e-300)
        b = self._kbin(q)
        comb = sseg.astype(jnp.int64) * C + b.astype(jnp.int64)
        nw = jax.ops.segment_sum(sw, comb, num_segments * C)
        nx = jax.ops.segment_sum(sw * sm, comb, num_segments * C)
        nm = jnp.where(nw > 0, nx / jnp.maximum(nw, 1e-300), 0.0)
        return (nm.reshape(num_segments, C), nw.reshape(num_segments, C))

    # -- ungrouped ------------------------------------------------------
    # State: (means (C,), weights (C,), minmax (2,)) — three vectors.
    def update(self, cv: CV, mask):
        zeros = jnp.zeros(mask.shape[0], jnp.int32)
        cols = self.g_update(cv, mask, zeros, 1)
        C = self.C
        return (jnp.stack([c[0] for c in cols[:C]]),
                jnp.stack([c[0] for c in cols[C:2 * C]]),
                jnp.stack([cols[2 * C][0], cols[2 * C + 1][0]]))

    def merge(self, s1, s2):
        fm = jnp.concatenate([s1[0], s2[0]])
        fw = jnp.concatenate([s1[1], s2[1]])
        fseg = jnp.zeros(fm.shape[0], jnp.int32)
        nm, nw = self._recompress(fm, fw, fseg, 1)
        mm = jnp.stack([jnp.minimum(s1[2][0], s2[2][0]),
                        jnp.maximum(s1[2][1], s2[2][1])])
        return (nm[0], nw[0], mm)

    def finalize(self, s):
        arrs = list(s)
        ungrouped = len(arrs) == 3 and arrs[0].ndim == 1 \
            and arrs[0].shape[0] == self.C
        C = self.C
        if ungrouped:
            means = arrs[0][None, :]
            ws = arrs[1][None, :]
            vmin, vmax = arrs[2][0][None], arrs[2][1][None]
        else:
            means = jnp.stack(arrs[:C], axis=1)           # (n, C)
            ws = jnp.stack(arrs[C:2 * C], axis=1)
            vmin, vmax = arrs[2 * C], arrs[2 * C + 1]
        n = means.shape[0]
        # all-NaN groups kept vmin at its +inf identity: resolve to vmax
        # (= NaN); a genuine all-+inf group has vmax = +inf and stands
        vmin = jnp.where(jnp.isposinf(vmin)
                         & jnp.logical_not(jnp.isposinf(vmax)),
                         vmax, vmin)
        # compact nonzero centroids to the front (stable: preserves the
        # rank order); empty tail gets mid=+inf so it is never selected
        order = jnp.argsort((ws <= 0).astype(jnp.uint8), axis=1,
                            stable=True)
        cm = jnp.take_along_axis(means, order, axis=1)
        cw = jnp.take_along_axis(ws, order, axis=1)
        nc = jnp.sum((cw > 0).astype(jnp.int32), axis=1)  # (n,)
        totw = jnp.sum(cw, axis=1)
        cumw = jnp.cumsum(cw, axis=1)
        mid = jnp.where(cw > 0, cumw - cw / 2, jnp.inf)   # (n, C)
        outs = []
        for pq in self.percentages:
            t = pq * totw                                  # (n,)
            j = jnp.sum((mid <= t[:, None]).astype(jnp.int32), axis=1)
            jl = jnp.clip(j - 1, 0, C - 1)
            jr = jnp.clip(j, 0, C - 1)
            lm = jnp.where(j > 0,
                           jnp.take_along_axis(cm, jl[:, None],
                                               axis=1)[:, 0], vmin)
            lr = jnp.where(j > 0,
                           jnp.take_along_axis(mid, jl[:, None],
                                               axis=1)[:, 0], 0.0)
            rm = jnp.where(j < nc,
                           jnp.take_along_axis(cm, jr[:, None],
                                               axis=1)[:, 0], vmax)
            rr = jnp.where(j < nc,
                           jnp.take_along_axis(mid, jr[:, None],
                                               axis=1)[:, 0], totw)
            frac = jnp.clip((t - lr) / jnp.maximum(rr - lr, 1e-300),
                            0.0, 1.0)
            # endpoint guards keep a NaN neighbor (NaN sorts greatest,
            # Java Double ordering) from poisoning frac=0/1 answers; an
            # interior frac with a NaN right neighbor snaps to the left
            # centroid — NaN is returned only once t reaches the NaN
            # centroid's own midpoint (docs/compatibility.md)
            mid_v = lm + frac * (rm - lm)
            mid_v = jnp.where(jnp.isnan(rm) & ~jnp.isnan(lm), lm, mid_v)
            outs.append(jnp.where(frac <= 0.0, lm,
                                  jnp.where(frac >= 1.0, rm, mid_v)))
        ok = totw > 0
        if self.scalar_out:
            v = outs[0]
            if ungrouped:
                return v[0], ok[0]
            return v, ok
        P = len(self.percentages)
        flat = jnp.stack(outs, axis=1).reshape(-1)         # (n*P,)
        off = (jnp.arange(n + 1, dtype=jnp.int32) * P)
        child = CV(flat, jnp.ones(n * P, jnp.bool_))
        v = CV(jnp.zeros(0, jnp.int8), jnp.ones(n, jnp.bool_), off,
               (child,))
        if ungrouped:
            return v, ok[0]
        return v, ok

    def __repr__(self):
        return f"percentile_approx({self.child}, {self.percentages})"


class Median(Percentile):
    def __init__(self, child, percentages=0.5, accuracy: int = 10000):
        super().__init__(child, 0.5, accuracy)

    def __repr__(self):
        return f"median({self.child})"
