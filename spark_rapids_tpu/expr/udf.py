"""Columnar Python UDFs — the RapidsUDF / CPU-bridge analog.

The reference has two escape hatches: RapidsUDF.evaluateColumnar (user
supplies a columnar kernel, reference: sql-plugin-api/.../RapidsUDF.java:22)
and GpuCpuBridgeExpression (copy to host, evaluate on CPU, copy back —
reference: GpuCpuBridgeExpression.scala). Here both collapse into one
mechanism: `PyUDF` wraps a numpy-vectorized Python function and emits a
`jax.pure_callback` inside the traced pipeline — XLA suspends the device
program, runs the host function on the fetched buffers, and resumes with
the result. Null-safe by default (null in -> null out, fn sees raw
buffers).
"""
from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import dtypes as dt
from ..ops.kernel_utils import CV
from .expressions import Expression, UnsupportedExpr

__all__ = ["PyUDF", "udf"]


class PyUDF(Expression):
    def __init__(self, fn: Callable, return_type: dt.DataType,
                 children: Sequence[Expression], null_safe: bool = True):
        self.fn = fn
        self.return_type = return_type
        self.children = list(children)
        self.null_safe = null_safe
        if return_type.is_variable_width or return_type.is_nested:
            raise UnsupportedExpr("PyUDF round-1 returns fixed-width types")

    @property
    def name(self):
        return getattr(self.fn, "__name__", "udf")

    def bind(self, schema):
        b = PyUDF(self.fn, self.return_type,
                  [c.bind(schema) for c in self.children], self.null_safe)
        b.dtype = self.return_type
        return b

    def emit(self, ctx):
        cvs = [c.emit(ctx) for c in self.children]
        for c, cv in zip(self.children, cvs):
            if cv.offsets is not None:
                raise UnsupportedExpr("PyUDF over strings round-1")
        cap = ctx.capacity
        np_dt = self.return_type.np_dtype

        def host_fn(*arrays):
            # tpulint: allow[host-sync] pure_callback hands host arrays
            out = self.fn(*[np.asarray(a) for a in arrays])
            return np.ascontiguousarray(out, dtype=np_dt)

        out_shape = jax.ShapeDtypeStruct((cap,), np_dt)
        data = jax.pure_callback(host_fn, out_shape,
                                 *[cv.data for cv in cvs])
        valid = jnp.ones(cap, jnp.bool_)
        if self.null_safe:
            for cv in cvs:
                valid = valid & cv.validity
        return CV(data, valid)

    def __repr__(self):
        return f"{self.name}({', '.join(map(repr, self.children))})"


def udf(fn: Callable, return_type: dt.DataType, null_safe: bool = True,
        compile: bool = True):  # noqa: A002
    """Wrap a numpy-vectorized function as a columnar UDF factory:

        doubled = udf(lambda x: x * 2, dtypes.INT64)
        df.select(doubled(col("a")))

    When `compile` is true the udf-compiler (expr/udf_compiler.py, the
    reference's udf-compiler/ analog) first tries to translate the Python
    source into a native expression tree — the UDF then fuses into the
    XLA program instead of suspending it with a host callback. Fallback
    is silent and exact: the pure_callback bridge.
    """
    def factory(*cols):
        from ..functions import _to_expr
        exprs = [_to_expr(c) for c in cols]
        if compile:
            from .expressions import Cast
            from .udf_compiler import CompileError, compile_udf
            try:
                compiled = compile_udf(fn, exprs)
                return Cast(compiled, return_type)
            except CompileError:
                pass
        return PyUDF(fn, return_type, exprs, null_safe)
    factory.__name__ = getattr(fn, "__name__", "udf")
    return factory


def df_udf(fn: Callable):
    """Dataframe-function UDF (reference: sql-plugin-api functions.scala
    df_udf — UDFs expressed as Column->Column functions, expanded inline
    at plan time). `fn` receives expression objects and returns one:

        within = df_udf(lambda a, b: (a - b).cast("double") / b)
        df.select(within(col("x"), col("y")).alias("r"))
    """
    def factory(*cols):
        from ..functions import _to_expr
        return fn(*[_to_expr(c) for c in cols])
    factory.__name__ = getattr(fn, "__name__", "df_udf")
    return factory
