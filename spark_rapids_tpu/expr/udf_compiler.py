"""Python-UDF compiler: translate simple Python functions into engine
expression trees so they fuse into XLA programs.

(reference: udf-compiler/ — CFG recovery + symbolic execution of JVM
bytecode into Catalyst expressions, CatalystExpressionBuilder.scala. The
Python analog is far simpler: parse the function's AST and map the
supported node set onto the engine's Expression algebra; anything outside
the subset falls back to the pure_callback PyUDF bridge, exactly like the
reference falling back to a black-box UDF.)

Supported subset: arithmetic (+ - * / // % **), comparisons (incl.
chains), and/or/not, `x if c else y`, `is None` / `is not None`,
abs/min/max/round/len, math.{sqrt,floor,ceil,exp,log,sin,cos}, string
methods upper/lower/strip/startswith/endswith/contains, closures over
plain numeric/string constants.
"""
from __future__ import annotations

import ast
import inspect
import math
import textwrap
from typing import Callable, List, Optional

from .expressions import Expression, Literal

__all__ = ["compile_udf", "CompileError"]


class CompileError(Exception):
    pass


def _fn_ast(fn: Callable):
    try:
        src = inspect.getsource(fn)
    except (OSError, TypeError) as e:
        raise CompileError(f"no source: {e}")
    src = textwrap.dedent(src)
    try:
        tree = ast.parse(src)
    except SyntaxError:
        # lambda embedded in a larger expression (e.g. an argument):
        # re-parse in eval mode after slicing at the lambda keyword
        i = src.find("lambda")
        if i < 0:
            raise CompileError("cannot locate function source")
        # try progressively shorter tails until one parses
        for end in range(len(src), i, -1):
            try:
                tree = ast.parse(src[i:end], mode="eval")
                return tree.body
            except SyntaxError:
                continue
        raise CompileError("cannot parse lambda source")
    fdefs = [n for n in ast.walk(tree) if isinstance(n, ast.FunctionDef)]
    if fdefs:
        return fdefs[0]
    lams = [n for n in ast.walk(tree) if isinstance(n, ast.Lambda)]
    if len(lams) != 1:
        # two lambdas in one source statement: inspect can't tell which
        # one `fn` is, and guessing compiles the wrong body
        raise CompileError("ambiguous lambda source")
    return lams[0]


def _resolve_const(fn: Callable, name: str):
    """Closure/global lookup for plain constants."""
    if fn.__closure__ and fn.__code__.co_freevars:
        for nm, cell in zip(fn.__code__.co_freevars, fn.__closure__):
            if nm == name:
                return cell.cell_contents
    g = getattr(fn, "__globals__", {})
    if name in g:
        return g[name]
    raise CompileError(f"unresolved name {name!r}")


class _Builder:
    def __init__(self, fn: Callable, params: List[str],
                 args: List[Expression]):
        self.fn = fn
        self.env = dict(zip(params, args))

    def build(self, node) -> Expression:
        meth = getattr(self, f"_n_{type(node).__name__}", None)
        if meth is None:
            raise CompileError(f"unsupported syntax {type(node).__name__}")
        return meth(node)

    # -- leaves --------------------------------------------------------
    def _n_Name(self, n):
        if n.id in self.env:
            return self.env[n.id]
        v = _resolve_const(self.fn, n.id)
        if isinstance(v, (int, float, str, bool)) or v is None:
            return Literal(v)
        raise CompileError(f"{n.id!r} is not a plain constant")

    def _n_Constant(self, n):
        if isinstance(n.value, (int, float, str, bool)) \
                or n.value is None:
            return Literal(n.value)
        raise CompileError(f"unsupported constant {n.value!r}")

    # -- operators -----------------------------------------------------
    _BINOPS = {ast.Add: "__add__", ast.Sub: "__sub__",
               ast.Mult: "__mul__", ast.Div: "__truediv__",
               ast.FloorDiv: "__floordiv__", ast.Mod: "__mod__",
               ast.Pow: "__pow__"}

    def _n_BinOp(self, n):
        a, b = self.build(n.left), self.build(n.right)
        meth = self._BINOPS.get(type(n.op))
        if meth is None or not hasattr(a, meth):
            raise CompileError(f"unsupported operator {type(n.op).__name__}")
        out = getattr(a, meth)(b)
        if out is NotImplemented:
            raise CompileError(f"operator {meth} not supported")
        return out

    def _n_UnaryOp(self, n):
        v = self.build(n.operand)
        if isinstance(n.op, ast.USub):
            return Literal(0) - v if not hasattr(v, "__neg__") else -v
        if isinstance(n.op, ast.Not):
            return ~v
        raise CompileError(f"unsupported unary {type(n.op).__name__}")

    _CMPOPS = {ast.Eq: "__eq__", ast.NotEq: "__ne__", ast.Lt: "__lt__",
               ast.LtE: "__le__", ast.Gt: "__gt__", ast.GtE: "__ge__"}

    def _n_Compare(self, n):
        terms = []
        left = self.build(n.left)
        for op, cmp_ in zip(n.ops, n.comparators):
            if isinstance(op, (ast.Is, ast.IsNot)):
                if not (isinstance(cmp_, ast.Constant)
                        and cmp_.value is None):
                    raise CompileError("`is` only supported against None")
                from .expressions import IsNotNull, IsNull
                terms.append(IsNull(left) if isinstance(op, ast.Is)
                             else IsNotNull(left))
                continue
            meth = self._CMPOPS.get(type(op))
            if meth is None:
                raise CompileError(
                    f"unsupported comparison {type(op).__name__}")
            right = self.build(cmp_)
            terms.append(getattr(left, meth)(right))
            left = right
        out = terms[0]
        for t in terms[1:]:
            out = out & t
        return out

    def _n_BoolOp(self, n):
        vals = [self.build(v) for v in n.values]
        out = vals[0]
        for v in vals[1:]:
            out = (out & v) if isinstance(n.op, ast.And) else (out | v)
        return out

    def _n_IfExp(self, n):
        from .expressions import CaseWhen
        return CaseWhen([(self.build(n.test), self.build(n.body))],
                        self.build(n.orelse))

    # -- calls ---------------------------------------------------------
    def _n_Call(self, n):
        from .. import functions as F
        if n.keywords:
            raise CompileError("keyword arguments not supported")
        args = [self.build(a) for a in n.args]
        if isinstance(n.func, ast.Name):
            nm = n.func.id
            if nm == "abs" and len(args) == 1:
                return F.abs(args[0])
            if nm == "round" and len(args) in (1, 2):
                sc = 0
                if len(args) == 2:
                    if not isinstance(args[1], Literal):
                        raise CompileError("round scale must be constant")
                    sc = args[1].value
                return F.round(args[0], sc)
            if nm == "min" and len(args) >= 2:
                return F.least(*args)
            if nm == "max" and len(args) >= 2:
                return F.greatest(*args)
            if nm == "len" and len(args) == 1:
                return F.length(args[0])
            raise CompileError(f"unsupported function {nm}")
        if isinstance(n.func, ast.Attribute):
            base = n.func.value
            meth = n.func.attr
            if isinstance(base, ast.Name):
                try:
                    mod = _resolve_const(self.fn, base.id)
                except CompileError:
                    mod = None
                if mod is math:
                    mfn = getattr(F, meth, None)
                    if mfn is None or len(args) != 1:
                        raise CompileError(f"unsupported math.{meth}")
                    return mfn(args[0])
            # string methods on a compiled subexpression
            recv = self.build(base)
            if meth == "upper" and not args:
                return F.upper(recv)
            if meth == "lower" and not args:
                return F.lower(recv)
            if meth == "strip" and not args:
                from .string_exprs import Trim
                return Trim(recv)
            if meth == "startswith" and len(args) == 1:
                from .string_exprs import StartsWith
                return StartsWith(recv, args[0])
            if meth == "endswith" and len(args) == 1:
                from .string_exprs import EndsWith
                return EndsWith(recv, args[0])
            raise CompileError(f"unsupported method .{meth}()")
        raise CompileError("unsupported call form")


def compile_udf(fn: Callable,
                args: List[Expression]) -> Optional[Expression]:
    """Compile `fn` applied to the given argument expressions; returns
    the expression tree, or raises CompileError when fn is outside the
    supported subset (caller falls back to PyUDF)."""
    node = _fn_ast(fn)
    if isinstance(node, ast.Lambda):
        params = [a.arg for a in node.args.args]
        body = node.body
    elif isinstance(node, ast.FunctionDef):
        params = [a.arg for a in node.args.args]
        stmts = [s_ for s_ in node.body
                 if not isinstance(s_, (ast.Expr,))  # docstrings
                 or not isinstance(getattr(s_, "value", None),
                                   ast.Constant)]
        if len(stmts) != 1 or not isinstance(stmts[0], ast.Return):
            raise CompileError("only single-return functions compile")
        body = stmts[0].value
    else:
        raise CompileError("unsupported callable")
    if len(params) != len(args):
        raise CompileError(
            f"arity mismatch: {len(params)} params, {len(args)} columns")
    return _Builder(fn, params, args).build(body)
