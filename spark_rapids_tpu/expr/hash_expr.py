"""hash() expression — Spark's murmur3 row hash surfaced to users
(reference: HashFunctions.scala Murmur3Hash rule)."""
from __future__ import annotations

from typing import List

import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..ops.hash import murmur3_row_hash
from ..ops.kernel_utils import CV
from .expressions import Expression

__all__ = ["Murmur3Hash"]


class Murmur3Hash(Expression):
    def __init__(self, children: List[Expression], seed: int = 42):
        self.children = list(children)
        self.seed = seed

    def bind(self, schema):
        b = Murmur3Hash([c.bind(schema) for c in self.children], self.seed)
        b.dtype = dt.INT32
        return b

    def emit(self, ctx):
        cvs = [c.emit(ctx) for c in self.children]
        h = murmur3_row_hash(cvs, [c.dtype for c in self.children],
                             self.seed)
        return CV(h, jnp.ones(ctx.capacity, jnp.bool_))

    def __repr__(self):
        return "hash(" + ", ".join(map(repr, self.children)) + ")"
