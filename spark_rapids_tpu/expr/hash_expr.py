"""hash() expression — Spark's murmur3 row hash surfaced to users
(reference: HashFunctions.scala Murmur3Hash rule)."""
from __future__ import annotations

from typing import List

import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..ops.hash import (hive_hash_row_hash, murmur3_row_hash,
                        xxhash64_row_hash)
from ..ops.kernel_utils import CV
from .expressions import Expression

__all__ = ["Murmur3Hash", "XxHash64", "HiveHash",
           "BloomFilterMightContain"]


class BloomFilterMightContain(Expression):
    """might_contain(filter, value): membership probe against a
    bloom_filter_agg result (reference: GpuBloomFilterMightContain.scala
    — there driving InSubqueryExec runtime join filtering). The filter
    must be FOLDABLE (a binary literal, like Spark's scalar-subquery
    result): its bit vector unpacks once at bind and rides the jitted
    probe as a device constant; k positions (h1 + i*h2 murmur3 scheme,
    matching BloomFilterAggregate) must all be set."""

    def __init__(self, filter_expr: Expression, value: Expression):
        self.filter_expr = filter_expr
        self.value = value
        self.children = [filter_expr, value]

    def bind(self, schema):
        from .expressions import Literal, UnsupportedExpr
        f = self.filter_expr.bind(schema)
        v = self.value.bind(schema)
        if not isinstance(f, Literal) or not isinstance(f.value, bytes):
            raise UnsupportedExpr(
                "might_contain requires a foldable binary filter "
                "(collect bloom_filter_agg first)")
        from .aggregates import parse_bloom_filter
        b = BloomFilterMightContain(f, v)
        b._k, b._m, bits = parse_bloom_filter(f.value)
        b._bits = jnp.asarray(bits)
        b.dtype = dt.BOOL
        return b

    def emit(self, ctx):
        from ..ops.hash import bloom_positions
        cv = self.value.emit(ctx)
        hit = jnp.ones(ctx.capacity, jnp.bool_)
        for pos in bloom_positions(cv, self.value.dtype, self._k,
                                   self._m):
            hit = hit & self._bits[jnp.clip(pos, 0, self._m - 1)]
        return CV(hit, cv.validity)

    def __repr__(self):
        return f"might_contain(<filter>, {self.value})"


class Murmur3Hash(Expression):
    def __init__(self, children: List[Expression], seed: int = 42):
        self.children = list(children)
        self.seed = seed

    def bind(self, schema):
        b = Murmur3Hash([c.bind(schema) for c in self.children], self.seed)
        b.dtype = dt.INT32
        return b

    def emit(self, ctx):
        cvs = [c.emit(ctx) for c in self.children]
        h = murmur3_row_hash(cvs, [c.dtype for c in self.children],
                             self.seed)
        return CV(h, jnp.ones(ctx.capacity, jnp.bool_))

    def __repr__(self):
        return "hash(" + ", ".join(map(repr, self.children)) + ")"


class XxHash64(Expression):
    """xxhash64(cols...): Spark's 64-bit row hash (reference: the jni
    Hash kernels' xxhash64 algorithm next to murmur3). Seed 42, int64
    result, nulls pass the running hash through."""

    def __init__(self, children: List[Expression], seed: int = 42):
        self.children = list(children)
        self.seed = seed

    def bind(self, schema):
        b = XxHash64([c.bind(schema) for c in self.children], self.seed)
        b.dtype = dt.INT64
        return b

    def emit(self, ctx):
        cvs = [c.emit(ctx) for c in self.children]
        h = xxhash64_row_hash(cvs, [c.dtype for c in self.children],
                              self.seed)
        return CV(h, jnp.ones(ctx.capacity, jnp.bool_))

    def __repr__(self):
        return "xxhash64(" + ", ".join(map(repr, self.children)) + ")"


class HiveHash(Expression):
    """hive_hash(cols...): Hive's 31-polynomial row hashCode (int32,
    nulls contribute 0) — the third jni Hash kernel algorithm, used for
    Hive-bucketed table writes."""

    def __init__(self, children: List[Expression]):
        self.children = list(children)

    def bind(self, schema):
        b = HiveHash([c.bind(schema) for c in self.children])
        b.dtype = dt.INT32
        return b

    def emit(self, ctx):
        cvs = [c.emit(ctx) for c in self.children]
        h = hive_hash_row_hash(cvs, [c.dtype for c in self.children])
        return CV(h, jnp.ones(ctx.capacity, jnp.bool_))

    def __repr__(self):
        return "hive_hash(" + ", ".join(map(repr, self.children)) + ")"
