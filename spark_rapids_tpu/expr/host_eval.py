"""Host (CPU) expression interpreter — the graceful-fallback engine.

(reference: GpuCpuBridgeExpression.scala — an unsupported expression
subtree runs on the CPU instead of failing the whole query; RapidsMeta's
"will not work on GPU because ..." tagging.) Here: when an expression
cannot bind for TPU execution (e.g. a regex outside the transpilable
subset), the planner keeps the UNBOUND tree and evaluates it row-wise on
host Python values through this interpreter, then returns to the device.

Slow by design — the point is that partial TPU coverage does not mean a
failed query. Coverage is the common scalar/string/regex surface; an
expression with no host rule raises UnsupportedExpr (the query then fails
with both reasons).
"""
from __future__ import annotations

import math
import re as _re
from typing import Any, Callable, Dict, List, Optional

from ..columnar import dtypes as dt
from .expressions import Expression, UnsupportedExpr

__all__ = ["host_eval_rows", "host_output_dtype"]


import functools


@functools.lru_cache(maxsize=256)
def _java_like_to_re(pattern: str, escape: str = "\\"):
    """Full SQL LIKE semantics (incl escapes) as a compiled anchored
    regex (cached per pattern — one translation, not one per row)."""
    out = []
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if c == escape and i + 1 < len(pattern):
            out.append(_re.escape(pattern[i + 1]))
            i += 2
            continue
        if c == "%":
            out.append(".*")
        elif c == "_":
            out.append(".")
        else:
            out.append(_re.escape(c))
        i += 1
    return _re.compile("(?s)^" + "".join(out) + "$")


@functools.lru_cache(maxsize=256)
def _java_repl_to_py(repl: str) -> str:
    """Java regexp_replace replacement dialect -> Python re.sub template:
    \\X = literal X, $n = group reference, all else literal."""
    out = []
    i = 0
    while i < len(repl):
        c = repl[i]
        if c == "\\" and i + 1 < len(repl):
            nxt = repl[i + 1]
            out.append("\\\\" if nxt == "\\" else nxt)
            i += 2
        elif c == "$" and i + 1 < len(repl) and repl[i + 1].isdigit():
            out.append("\\" + repl[i + 1])
            i += 2
        else:
            out.append("\\\\" if c == "\\" else c)
            i += 1
    return "".join(out)


def _num(x):
    return x is not None


# Each rule: fn(expr, child_values: list, row_env) -> value (None = null)
_RULES: Dict[str, Callable] = {}


def _rule(*names):
    def deco(fn):
        for n in names:
            _RULES[n] = fn
        return fn
    return deco


@_rule("Literal")
def _lit(e, cv, env):
    import numpy as np
    v = e.value
    np_dt = getattr(getattr(e, "dtype", None), "np_dtype", None)
    # typed numpy scalar so arithmetic wraps at the literal's width,
    # matching the device (Java/Spark non-ANSI overflow)
    if (np_dt is not None and isinstance(v, int)
            and not isinstance(v, bool)
            and np.issubdtype(np.dtype(np_dt), np.integer)):
        return np.dtype(np_dt).type(v)
    return v


@_rule("ColumnRef")
def _colref(e, cv, env):
    return env[e.name]


@_rule("BoundRef")
def _bref(e, cv, env):
    return env[e.name]


@_rule("Alias")
def _alias(e, cv, env):
    return cv[0]


@_rule("Add")
def _add(e, cv, env):
    a, b = cv
    return None if a is None or b is None else a + b


@_rule("Subtract")
def _sub(e, cv, env):
    a, b = cv
    return None if a is None or b is None else a - b


@_rule("Multiply")
def _mul(e, cv, env):
    a, b = cv
    return None if a is None or b is None else a * b


@_rule("Divide")
def _div(e, cv, env):
    a, b = cv
    if a is None or b is None or b == 0:
        return None
    return a / b


@_rule("Negate")
def _neg(e, cv, env):
    return None if cv[0] is None else -cv[0]


@_rule("Abs")
def _abs(e, cv, env):
    return None if cv[0] is None else abs(cv[0])


def _cmp(op):
    def fn(e, cv, env):
        a, b = cv
        if a is None or b is None:
            return None
        # native bool: numpy comparison results (np.bool_) would break
        # the And/Or rules' `is False` Kleene short-circuits
        return bool(op(a, b))
    return fn


_RULES["Eq"] = _cmp(lambda a, b: a == b)
_RULES["Ne"] = _cmp(lambda a, b: a != b)
_RULES["Lt"] = _cmp(lambda a, b: a < b)
_RULES["Le"] = _cmp(lambda a, b: a <= b)
_RULES["Gt"] = _cmp(lambda a, b: a > b)
_RULES["Ge"] = _cmp(lambda a, b: a >= b)


@_rule("EqNullSafe")
def _eqns(e, cv, env):
    a, b = cv
    if a is None and b is None:
        return True
    if a is None or b is None:
        return False
    return a == b


@_rule("And")
def _and(e, cv, env):
    a, b = cv
    if a is False or b is False:
        return False
    if a is None or b is None:
        return None
    return bool(a) and bool(b)


@_rule("Or")
def _or(e, cv, env):
    a, b = cv
    if a is True or b is True:
        return True
    if a is None or b is None:
        return None
    return bool(a) or bool(b)


@_rule("Not")
def _not(e, cv, env):
    return None if cv[0] is None else not cv[0]


@_rule("IsNull")
def _isnull(e, cv, env):
    return cv[0] is None


@_rule("IsNotNull")
def _isnotnull(e, cv, env):
    return cv[0] is not None


@_rule("Coalesce")
def _coalesce(e, cv, env):
    for v in cv:
        if v is not None:
            return v
    return None


@_rule("If")
def _if(e, cv, env):
    return cv[1] if cv[0] else cv[2]


@_rule("In")
def _in(e, cv, env):
    v = cv[0]
    if v is None:
        return None
    vals = cv[1:]
    if v in [x for x in vals if x is not None]:
        return True
    return None if any(x is None for x in vals) else False


# ---- strings ---------------------------------------------------------
@_rule("Length")
def _length(e, cv, env):
    return None if cv[0] is None else len(cv[0])


@_rule("Upper")
def _upper(e, cv, env):
    return None if cv[0] is None else cv[0].upper()


@_rule("Lower")
def _lower(e, cv, env):
    return None if cv[0] is None else cv[0].lower()


@_rule("Contains")
def _contains(e, cv, env):
    a, b = cv
    return None if a is None or b is None else (b in a)


@_rule("StartsWith")
def _startswith(e, cv, env):
    a, b = cv
    return None if a is None or b is None else a.startswith(b)


@_rule("EndsWith")
def _endswith(e, cv, env):
    a, b = cv
    return None if a is None or b is None else a.endswith(b)


@_rule("ConcatStr")
def _concatstr(e, cv, env):
    if any(v is None for v in cv):
        return None
    return "".join(cv)


@_rule("Like")
def _like(e, cv_or_child, env):
    s = cv_or_child[0]
    if s is None:
        return None
    return _java_like_to_re(e.pattern).match(s) is not None


@_rule("RLike")
def _rlike(e, cv, env):
    s = cv[0]
    if s is None:
        return None
    return _re.search(e.pattern, s) is not None


@_rule("RegexpExtract")
def _regexp_extract(e, cv, env):
    s = cv[0]
    if s is None:
        return None
    m = _re.search(e.pattern, s)
    if not m or e.idx > (m.re.groups):
        return ""
    g = m.group(e.idx)
    return g if g is not None else ""


@_rule("RegexpReplace")
def _regexp_replace(e, cv, env):
    s = cv[0]
    if s is None:
        return None
    return _re.sub(e.pattern, _java_repl_to_py(e.replacement), s)


# ---------------------------------------------------------------------
def _eval_one(e: Expression, env) -> Any:
    name = type(e).__name__
    fn = _RULES.get(name)
    if fn is None:
        raise UnsupportedExpr(
            f"no host (CPU fallback) implementation for {name}")
    child_vals = [_eval_one(c, env) for c in e.children if c is not None]
    return fn(e, child_vals, env)


def host_eval_rows(expr: Expression, rows: List[dict]) -> List[Any]:
    """Evaluate an UNBOUND expression tree over row dicts (name->value).
    Integer inputs should be numpy width-typed scalars (see
    host_fallback._batch_rows) so arithmetic wraps like the device;
    overflow warnings from that wrapping are expected and silenced."""
    import numpy as np
    import warnings
    with np.errstate(over="ignore"), warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        return [_eval_one(expr, row) for row in rows]


# output dtype WITHOUT capability checks, for planning around fallbacks
_DTYPE_HINTS = {
    "RLike": dt.BOOL, "Like": dt.BOOL, "Contains": dt.BOOL,
    "StartsWith": dt.BOOL, "EndsWith": dt.BOOL, "And": dt.BOOL,
    "Or": dt.BOOL, "Not": dt.BOOL, "Eq": dt.BOOL, "Ne": dt.BOOL,
    "Lt": dt.BOOL, "Le": dt.BOOL, "Gt": dt.BOOL, "Ge": dt.BOOL,
    "EqNullSafe": dt.BOOL, "IsNull": dt.BOOL, "IsNotNull": dt.BOOL,
    "In": dt.BOOL,
    "RegexpExtract": dt.STRING, "RegexpReplace": dt.STRING,
    "Upper": dt.STRING, "Lower": dt.STRING, "ConcatStr": dt.STRING,
    "Length": dt.INT32,
}


def host_output_dtype(expr: Expression) -> Optional[dt.DataType]:
    name = type(expr).__name__
    if name == "Alias":
        return host_output_dtype(expr.children[0])
    hd = getattr(expr, "host_dtype", None)
    if hd is not None:
        return hd
    if name == "Cast":
        return expr.to
    return _DTYPE_HINTS.get(name)


# -- JSON / URL expressions (expr/json_exprs.py) -----------------------
import json as _json


@_rule("GetJsonObject")
def _get_json_object(e, cv, env):
    s = cv[0]
    if s is None:
        return None
    from .json_exprs import render_json_value, walk_json_path
    try:
        obj = _json.loads(s)
    except (ValueError, TypeError):
        return None
    matches = walk_json_path(obj, e.steps)
    if not matches:
        return None
    if len(matches) == 1:
        return render_json_value(matches[0])
    return _json.dumps(matches, separators=(",", ":"))


def _coerce_json(v, dtype):
    if v is None:
        return None
    if isinstance(dtype, dt.StructType):
        if not isinstance(v, dict):
            return None
        return {f.name: _coerce_json(v.get(f.name), f.dtype)
                for f in dtype.fields}
    if isinstance(dtype, dt.ArrayType):
        if not isinstance(v, list):
            return None
        return [_coerce_json(x, dtype.element) for x in v]
    if isinstance(dtype, dt.MapType):
        if not isinstance(v, dict):
            return None
        return {str(k): _coerce_json(x, dtype.value)
                for k, x in v.items()}
    try:
        if isinstance(dtype, dt.StringType):
            return v if isinstance(v, str) else _json.dumps(v)
        if isinstance(dtype, dt.BooleanType):
            return v if isinstance(v, bool) else None
        if isinstance(dtype, (dt.ByteType, dt.ShortType, dt.IntegerType,
                              dt.LongType)):
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                return None
            return int(v)
        if isinstance(dtype, (dt.FloatType, dt.DoubleType)):
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                return None
            return float(v)
    except (ValueError, TypeError, OverflowError):
        return None
    return None


@_rule("FromJson")
def _from_json(e, cv, env):
    s = cv[0]
    if s is None:
        return None
    try:
        obj = _json.loads(s)
    except (ValueError, TypeError):
        return None
    return _coerce_json(obj, e.host_dtype)


def _jsonable(v):
    import datetime
    import decimal
    if isinstance(v, dict):
        return {k: _jsonable(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_jsonable(x) for x in v]
    if isinstance(v, decimal.Decimal):
        return float(v)
    if isinstance(v, (datetime.date, datetime.datetime)):
        return v.isoformat()
    return v


@_rule("ToJson")
def _to_json(e, cv, env):
    v = cv[0]
    if v is None:
        return None
    return _json.dumps(_jsonable(v), separators=(",", ":"))


@_rule("ParseUrl")
def _parse_url(e, cv, env):
    s = cv[0]
    if s is None:
        return None
    from urllib.parse import urlparse
    try:
        u = urlparse(s)
    except ValueError:
        return None
    part = e.part
    if part == "QUERY" and e.key is not None:
        # Spark extracts the RAW value with (&|^)key=([^&]*) — no URL
        # decoding, empty string preserved
        mt = _re.search(r"(?:^|&)" + _re.escape(e.key) + r"=([^&]*)",
                        u.query)
        return mt.group(1) if mt else None
    if part == "HOST":
        return u.hostname
    if part == "PATH":
        return u.path or ""
    if part == "QUERY":
        return u.query or None
    if part == "REF":
        return u.fragment or None
    if part == "PROTOCOL":
        return u.scheme or None
    if part == "FILE":
        return (u.path or "") + (f"?{u.query}" if u.query else "")
    if part == "AUTHORITY":
        return u.netloc or None
    if part == "USERINFO":
        if u.username is None and u.password is None:
            return None
        return (u.username or "") + (f":{u.password}"
                                     if u.password is not None else "")
    return None


@_rule("Cast")
def _cast(e, cv, env):
    """Host-side Spark CAST over Python values (the common scalar
    matrix; string->number trims, failures -> null)."""
    v = cv[0]
    if v is None:
        return None
    to = e.to
    try:
        if isinstance(to, dt.StringType):
            if isinstance(v, bool):
                return "true" if v else "false"
            return str(v)
        if isinstance(to, dt.BooleanType):
            if isinstance(v, str):
                t = v.strip().lower()
                if t in ("t", "true", "y", "yes", "1"):
                    return True
                if t in ("f", "false", "n", "no", "0"):
                    return False
                return None
            return bool(v)
        if isinstance(to, (dt.ByteType, dt.ShortType, dt.IntegerType,
                           dt.LongType)):
            if isinstance(v, str):
                t = v.strip()
                try:
                    return int(t)    # exact for integral strings
                except ValueError:
                    return int(float(t))
            return int(v)
        if isinstance(to, (dt.FloatType, dt.DoubleType)):
            return float(v.strip() if isinstance(v, str) else v)
    except (ValueError, TypeError, OverflowError):
        return None
    raise UnsupportedExpr(f"host cast to {to} not implemented")
