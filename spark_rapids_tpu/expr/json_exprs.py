"""JSON + URL expressions — host-bridge evaluated.

(reference: GpuGetJsonObject.scala / GpuJsonToStructs.scala /
GpuStructsToJson.scala via JNI JSONUtils; GpuParseUrl.scala via JNI
ParseURI.) Byte-level JSON/URI parsing is the reference's hand-written
CUDA kernel territory; here these expressions deliberately route through
the CPU bridge (exec/host_fallback.py) — bind() raises UnsupportedExpr,
the planner keeps the unbound tree, and rows evaluate on host between
device stages. Correctness-first; a Pallas byte-parser can replace the
host path later without API changes.
"""
from __future__ import annotations

import json
from typing import List, Optional, Tuple

from ..columnar import dtypes as dt
from .expressions import Expression, UnsupportedExpr, _wrap

__all__ = ["GetJsonObject", "FromJson", "ToJson", "ParseUrl",
           "parse_json_path"]


def parse_json_path(path: str) -> List[Tuple[str, object]]:
    """Parse a Spark get_json_object path ($.a.b[0]['c'][*]) into steps:
    ("field", name) | ("index", i) | ("wild", None)."""
    if not path or path[0] != "$":
        raise ValueError(f"JSON path must start with $: {path!r}")
    steps: List[Tuple[str, object]] = []
    i = 1
    n = len(path)
    while i < n:
        c = path[i]
        if c == ".":
            j = i + 1
            while j < n and path[j] not in ".[":
                j += 1
            name = path[i + 1:j]
            if name == "*":
                steps.append(("wild", None))
            elif name:
                steps.append(("field", name))
            else:
                raise ValueError(f"empty field in path {path!r}")
            i = j
        elif c == "[":
            j = path.index("]", i)
            tok = path[i + 1:j].strip()
            if tok == "*":
                steps.append(("wild", None))
            elif tok.startswith(("'", '"')) and tok.endswith(tok[0]):
                steps.append(("field", tok[1:-1]))
            else:
                steps.append(("index", int(tok)))
            i = j + 1
        else:
            raise ValueError(f"bad JSON path at {i}: {path!r}")
    return steps


def walk_json_path(obj, steps):
    """Apply parsed steps; returns a list of matches (wildcards fan
    out)."""
    cur = [obj]
    for kind, arg in steps:
        nxt = []
        for o in cur:
            if kind == "field":
                if isinstance(o, dict) and arg in o:
                    nxt.append(o[arg])
                elif isinstance(o, list):
                    # Spark: a field step over an array maps over elems
                    for e in o:
                        if isinstance(e, dict) and arg in e:
                            nxt.append(e[arg])
            elif kind == "index":
                if isinstance(o, list) and -len(o) <= arg < len(o):
                    nxt.append(o[arg])
            else:  # wild
                if isinstance(o, list):
                    nxt.extend(o)
                elif isinstance(o, dict):
                    nxt.extend(o.values())
        cur = nxt
        if not cur:
            return []
    return cur


def render_json_value(v) -> str:
    """Jackson-style rendering: bare scalars unquoted, containers as
    compact JSON."""
    if isinstance(v, str):
        return v
    if isinstance(v, bool):
        return "true" if v else "false"
    if v is None:
        return None
    if isinstance(v, (int, float)):
        return json.dumps(v)
    return json.dumps(v, separators=(",", ":"))


class _HostOnlyExpr(Expression):
    """Expression that always routes to the CPU bridge."""

    _reason = "host-bridge expression"

    def bind(self, schema):
        raise UnsupportedExpr(self._reason)


class GetJsonObject(Expression):
    """SCALAR paths (field/index steps) evaluate ON DEVICE via the byte-
    tape tokenizer (ops/json_tape.py — the analog of the reference's JNI
    JSONUtils.getJsonObject kernel); wildcard paths route to the CPU
    bridge like before. SRTPU_JSON_HOST=1 forces the host path (used by
    tests to cross-check both)."""

    host_dtype = dt.STRING

    def __init__(self, child: Expression, path: str):
        self.children = [_wrap(child)]
        self.path = path
        self.steps = parse_json_path(path)

    def bind(self, schema):
        import os

        from ..ops.json_tape import device_path_supported
        if os.environ.get("SRTPU_JSON_HOST") == "1" \
                or not device_path_supported(self.steps):
            raise UnsupportedExpr(
                "get_json_object wildcard path runs on the CPU bridge")
        b = GetJsonObject(self.children[0].bind(schema), self.path)
        if not isinstance(b.children[0].dtype, dt.StringType):
            raise UnsupportedExpr("get_json_object over non-string")
        b.dtype = dt.STRING
        return b

    def emit(self, ctx):
        from ..ops.json_tape import get_json_object_tape
        cv = self.children[0].emit(ctx)
        # result is a slice of the input: input byte capacity bounds it
        return get_json_object_tape(cv, self.steps,
                                    out_data_capacity=cv.data.shape[0])

    @property
    def name(self):
        return f"get_json_object({self.children[0].name}, {self.path})"

    def __repr__(self):
        return f"get_json_object({self.children[0]!r}, {self.path!r})"


class FromJson(_HostOnlyExpr):
    _reason = "from_json runs on the CPU bridge"

    def __init__(self, child: Expression, schema: dt.DataType):
        if not isinstance(schema, (dt.StructType, dt.ArrayType,
                                   dt.MapType)):
            raise ValueError("from_json needs a struct/array/map dtype")
        self.children = [_wrap(child)]
        self.host_dtype = schema

    @property
    def name(self):
        return f"from_json({self.children[0].name})"

    def __repr__(self):
        return f"from_json({self.children[0]!r}, {self.host_dtype})"


class ToJson(_HostOnlyExpr):
    _reason = "to_json runs on the CPU bridge"
    host_dtype = dt.STRING

    def __init__(self, child: Expression):
        self.children = [_wrap(child)]

    @property
    def name(self):
        return f"to_json({self.children[0].name})"

    def __repr__(self):
        return f"to_json({self.children[0]!r})"


_URL_PARTS = ("HOST", "PATH", "QUERY", "REF", "PROTOCOL", "FILE",
              "AUTHORITY", "USERINFO")


class ParseUrl(_HostOnlyExpr):
    _reason = "parse_url runs on the CPU bridge"
    host_dtype = dt.STRING

    def __init__(self, child: Expression, part: str,
                 key: Optional[str] = None):
        if part not in _URL_PARTS:
            raise ValueError(f"parse_url part must be one of "
                             f"{_URL_PARTS}, got {part!r}")
        self.children = [_wrap(child)]
        self.part = part
        self.key = key

    @property
    def name(self):
        return f"parse_url({self.children[0].name}, {self.part})"

    def __repr__(self):
        return f"parse_url({self.children[0]!r}, {self.part!r})"
