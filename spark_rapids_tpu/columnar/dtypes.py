"""Data type system for TPU columnar execution.

Mirrors the type surface that the reference plugin supports on GPU
(reference: sql-plugin/src/main/scala/com/nvidia/spark/rapids/TypeChecks.scala:125,
GpuColumnVector.java type mapping) but is designed TPU-first: every type maps
to a fixed-width device representation (jax.numpy dtype) plus, for variable
width types, Arrow-style offset/child buffers.

Device representations:
  - BooleanType      -> bool_
  - ByteType         -> int8
  - ShortType        -> int16
  - IntegerType      -> int32
  - LongType         -> int64
  - FloatType        -> float32
  - DoubleType       -> float64
  - DateType         -> int32   (days since epoch; Spark semantics)
  - TimestampType    -> int64   (microseconds since epoch, UTC)
  - StringType       -> offsets int32[n+1] + data uint8[nbytes]
  - BinaryType       -> same as string
  - DecimalType(p,s) -> int64 scaled integer for p <= 18 (DECIMAL64);
                        p in (18, 38] represented as (hi int64, lo uint64)
                        pair -- round-1 supports arithmetic only on p<=18.
  - NullType         -> int8 all-null
  - ArrayType        -> offsets + child column
  - StructType       -> child columns
  - MapType          -> array of struct<key,value>
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

__all__ = [
    "DataType", "BooleanType", "ByteType", "ShortType", "IntegerType",
    "LongType", "FloatType", "DoubleType", "StringType", "BinaryType",
    "DateType", "TimestampType", "DecimalType", "NullType", "ArrayType",
    "StructType", "StructField", "MapType",
    "BOOL", "INT8", "INT16", "INT32", "INT64", "FLOAT32", "FLOAT64",
    "STRING", "BINARY", "DATE", "TIMESTAMP", "NULLTYPE",
]


class DataType:
    """Base class for all SQL data types."""

    #: numpy dtype of the primary device buffer, or None for nested
    np_dtype: Optional[np.dtype] = None

    @property
    def is_numeric(self) -> bool:
        return isinstance(self, (ByteType, ShortType, IntegerType, LongType,
                                 FloatType, DoubleType, DecimalType))

    @property
    def is_integral(self) -> bool:
        return isinstance(self, (ByteType, ShortType, IntegerType, LongType))

    @property
    def is_floating(self) -> bool:
        return isinstance(self, (FloatType, DoubleType))

    @property
    def is_variable_width(self) -> bool:
        return isinstance(self, (StringType, BinaryType, ArrayType, MapType))

    @property
    def is_nested(self) -> bool:
        return isinstance(self, (ArrayType, StructType, MapType))

    def simple_name(self) -> str:
        return type(self).__name__.replace("Type", "").lower()

    def __repr__(self) -> str:
        return self.simple_name()

    def __eq__(self, other):
        return type(self) is type(other)

    def __hash__(self):
        return hash(type(self))


class BooleanType(DataType):
    np_dtype = np.dtype(np.bool_)


class ByteType(DataType):
    np_dtype = np.dtype(np.int8)


class ShortType(DataType):
    np_dtype = np.dtype(np.int16)


class IntegerType(DataType):
    np_dtype = np.dtype(np.int32)


class LongType(DataType):
    np_dtype = np.dtype(np.int64)


class FloatType(DataType):
    np_dtype = np.dtype(np.float32)


class DoubleType(DataType):
    np_dtype = np.dtype(np.float64)


class StringType(DataType):
    np_dtype = np.dtype(np.uint8)  # data buffer


class BinaryType(DataType):
    np_dtype = np.dtype(np.uint8)


class DateType(DataType):
    np_dtype = np.dtype(np.int32)


class TimestampType(DataType):
    np_dtype = np.dtype(np.int64)


class NullType(DataType):
    np_dtype = np.dtype(np.int8)


class DecimalType(DataType):
    """Fixed-point decimal. p<=18 backed by a scaled int64 (DECIMAL64)."""

    MAX_INT64_PRECISION = 18
    MAX_PRECISION = 38

    def __init__(self, precision: int = 10, scale: int = 0):
        if not (1 <= precision <= self.MAX_PRECISION):
            raise ValueError(f"precision out of range: {precision}")
        if not (0 <= scale <= precision):
            raise ValueError(f"scale out of range: {scale} (precision {precision})")
        self.precision = precision
        self.scale = scale

    @property
    def np_dtype(self):  # type: ignore[override]
        return np.dtype(np.int64)

    @property
    def is_decimal128(self) -> bool:
        """precision > 18: data travels as a [cap, 2] int64 limb buffer
        (ops/decimal128.py two's-complement little-endian)."""
        return self.precision > self.MAX_INT64_PRECISION

    def simple_name(self) -> str:
        return f"decimal({self.precision},{self.scale})"

    def __eq__(self, other):
        return (isinstance(other, DecimalType)
                and other.precision == self.precision
                and other.scale == self.scale)

    def __hash__(self):
        return hash((DecimalType, self.precision, self.scale))


@dataclasses.dataclass(frozen=True)
class StructField:
    name: str
    dtype: "DataType"
    nullable: bool = True


class StructType(DataType):
    def __init__(self, fields: Tuple[StructField, ...]):
        self.fields = tuple(fields)

    def simple_name(self) -> str:
        inner = ",".join(f"{f.name}:{f.dtype.simple_name()}" for f in self.fields)
        return f"struct<{inner}>"

    def __eq__(self, other):
        return isinstance(other, StructType) and other.fields == self.fields

    def __hash__(self):
        return hash((StructType, self.fields))


class ArrayType(DataType):
    def __init__(self, element: DataType, contains_null: bool = True):
        self.element = element
        self.contains_null = contains_null

    def simple_name(self) -> str:
        return f"array<{self.element.simple_name()}>"

    def __eq__(self, other):
        return isinstance(other, ArrayType) and other.element == self.element

    def __hash__(self):
        return hash((ArrayType, self.element))


class MapType(DataType):
    def __init__(self, key: DataType, value: DataType,
                 value_contains_null: bool = True):
        self.key = key
        self.value = value
        self.value_contains_null = value_contains_null

    def simple_name(self) -> str:
        return f"map<{self.key.simple_name()},{self.value.simple_name()}>"

    def __eq__(self, other):
        return (isinstance(other, MapType) and other.key == self.key
                and other.value == self.value)

    def __hash__(self):
        return hash((MapType, self.key, self.value))


# Singletons for the common fixed types.
BOOL = BooleanType()
INT8 = ByteType()
INT16 = ShortType()
INT32 = IntegerType()
INT64 = LongType()
FLOAT32 = FloatType()
FLOAT64 = DoubleType()
STRING = StringType()
BINARY = BinaryType()
DATE = DateType()
TIMESTAMP = TimestampType()
NULLTYPE = NullType()

_NUMERIC_ORDER = [ByteType, ShortType, IntegerType, LongType, FloatType,
                  DoubleType]


def promote(a: DataType, b: DataType) -> DataType:
    """Numeric type promotion following Spark's binary-arithmetic widening."""
    if a == b:
        return a
    if isinstance(a, DecimalType) or isinstance(b, DecimalType):
        raise TypeError("decimal promotion handled by expression layer")
    if not (a.is_numeric and b.is_numeric):
        raise TypeError(f"cannot promote {a} and {b}")
    ia = _NUMERIC_ORDER.index(type(a))
    ib = _NUMERIC_ORDER.index(type(b))
    # int64 + float32 -> float64 under Spark
    pair = {type(a), type(b)}
    if pair == {LongType, FloatType}:
        return FLOAT64
    return (a if ia >= ib else b)


def from_arrow(at) -> DataType:
    """Map a pyarrow type to our DataType."""
    import pyarrow as pa
    if pa.types.is_boolean(at):
        return BOOL
    if pa.types.is_int8(at):
        return INT8
    if pa.types.is_int16(at):
        return INT16
    if pa.types.is_int32(at):
        return INT32
    if pa.types.is_int64(at):
        return INT64
    if pa.types.is_float32(at):
        return FLOAT32
    if pa.types.is_float64(at):
        return FLOAT64
    if pa.types.is_string(at) or pa.types.is_large_string(at):
        return STRING
    if pa.types.is_binary(at) or pa.types.is_large_binary(at):
        return BINARY
    if pa.types.is_date32(at):
        return DATE
    if pa.types.is_timestamp(at):
        return TIMESTAMP
    if pa.types.is_decimal(at):
        return DecimalType(at.precision, at.scale)
    if pa.types.is_null(at):
        return NULLTYPE
    if pa.types.is_list(at) or pa.types.is_large_list(at):
        return ArrayType(from_arrow(at.value_type))
    if pa.types.is_struct(at):
        return StructType(tuple(StructField(f.name, from_arrow(f.type))
                                for f in at))
    if pa.types.is_map(at):
        return MapType(from_arrow(at.key_type), from_arrow(at.item_type))
    raise TypeError(f"unsupported arrow type: {at}")


def to_arrow(dt: DataType):
    import pyarrow as pa
    if isinstance(dt, BooleanType):
        return pa.bool_()
    if isinstance(dt, ByteType):
        return pa.int8()
    if isinstance(dt, ShortType):
        return pa.int16()
    if isinstance(dt, IntegerType):
        return pa.int32()
    if isinstance(dt, LongType):
        return pa.int64()
    if isinstance(dt, FloatType):
        return pa.float32()
    if isinstance(dt, DoubleType):
        return pa.float64()
    if isinstance(dt, StringType):
        return pa.string()
    if isinstance(dt, BinaryType):
        return pa.binary()
    if isinstance(dt, DateType):
        return pa.date32()
    if isinstance(dt, TimestampType):
        return pa.timestamp("us", tz="UTC")
    if isinstance(dt, DecimalType):
        return pa.decimal128(dt.precision, dt.scale)
    if isinstance(dt, NullType):
        return pa.null()
    if isinstance(dt, ArrayType):
        return pa.list_(to_arrow(dt.element))
    if isinstance(dt, StructType):
        return pa.struct([(f.name, to_arrow(f.dtype)) for f in dt.fields])
    if isinstance(dt, MapType):
        return pa.map_(to_arrow(dt.key), to_arrow(dt.value))
    raise TypeError(f"unsupported dtype: {dt}")


_NAME_TO_DTYPE = {
    "boolean": BOOL, "bool": BOOL,
    "byte": INT8, "tinyint": INT8,
    "short": INT16, "smallint": INT16,
    "int": INT32, "integer": INT32,
    "long": INT64, "bigint": INT64,
    "float": FLOAT32, "real": FLOAT32,
    "double": FLOAT64,
    "string": STRING, "binary": BINARY,
    "date": DATE, "timestamp": TIMESTAMP,
}


def from_name(name: str) -> DataType:
    """Resolve a Spark SQL type name ('int', 'bigint', 'decimal(10,2)',
    ...) to a DataType."""
    t = name.strip().lower()
    if t in _NAME_TO_DTYPE:
        return _NAME_TO_DTYPE[t]
    if t.startswith("decimal"):
        inner = t[len("decimal"):].strip()
        if not inner:
            return DecimalType(10, 0)
        if inner.startswith("(") and inner.endswith(")"):
            p, _, s = inner[1:-1].partition(",")
            return DecimalType(int(p), int(s or 0))
    raise ValueError(f"unknown type name {name!r}")
